"""Numerical guardrails (ISSUE 3): taxonomy, guard modes, in-graph
sentinels, precision-escalation recovery, and entry validation.

The contract under test, per mode:

- ``off``      — bit-identical outputs, NaN propagates silently (seed
                 behavior preserved exactly);
- ``check``    — seeded NaN/Inf raises a typed error at the boundary
                 that observed it, attributing input vs output;
- ``recover``  — a rescuable breakdown re-runs one precision-ladder
                 tier up and matches the f64 reference within tol; a
                 genuine failure still raises.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core.guards import (ArtifactCorruptError, ConvergenceError,
                                  ConvergenceReport, IllConditionedError,
                                  NonFiniteError, NumericalError,
                                  finite_sentinel, guard_mode, guard_scope,
                                  resolve_guard_mode, set_guard_mode)

# f32-representable pair with fl32(a0*a0) == a1 exactly (f32 pivot 0,
# rejected) while a1 - a0*a0 = +0.99 in f64 (PSD, rescuable): the
# cancellation-breakdown fixture for the recover tests below.
_A0, _A1 = 4097.0048828125, 16785450.0

# the suite must pass under ci/smoke.sh's RAFT_TPU_GUARD_MODE=check gate
# too: the baseline mode is whatever the environment armed, and seed-
# behavior assertions pin guard_scope("off") explicitly.
_ENV_MODE = os.environ.get("RAFT_TPU_GUARD_MODE", "off").lower()
if _ENV_MODE not in ("off", "check", "recover"):
    _ENV_MODE = "off"


@pytest.fixture(autouse=True)
def _reset_guard_mode():
    yield
    set_guard_mode(_ENV_MODE)


class TestTaxonomy:
    def test_hierarchy_keeps_runtimeerror_base(self):
        # pre-taxonomy `except RuntimeError` call sites must keep working
        for exc in (NumericalError, NonFiniteError, IllConditionedError,
                    ConvergenceError):
            assert issubclass(exc, RuntimeError)
            assert issubclass(exc, NumericalError)
        assert issubclass(ArtifactCorruptError, RuntimeError)

    def test_error_payloads(self):
        e = NonFiniteError("boom", op="linalg.gemm", stage="input")
        assert e.op == "linalg.gemm" and e.stage == "input"
        rep = ConvergenceReport(converged=False, n_iter=7, residual=1e-3,
                                tol=1e-6)
        ce = ConvergenceError("no", report=rep, op="solver.x")
        assert ce.report is rep and not ce.report.converged
        ae = ArtifactCorruptError("bad", path="/tmp/a.bin")
        assert ae.path == "/tmp/a.bin"

    def test_report_defaults(self):
        rep = ConvergenceReport(converged=True, n_iter=3, residual=0.0,
                                tol=1e-6)
        assert not rep.escalated and rep.breakdowns == 0 and rep.detail == ""


class TestGuardModeKnob:
    def test_default_matches_environment(self):
        # 'off' in a plain run; the CI guard-mode gate arms 'check'
        assert guard_mode() == _ENV_MODE

    def test_set_and_scope_nesting(self):
        set_guard_mode("check")
        assert guard_mode() == "check"
        with guard_scope("recover"):
            assert guard_mode() == "recover"
            with guard_scope("off"):
                assert guard_mode() == "off"
            assert guard_mode() == "recover"
        assert guard_mode() == "check"

    def test_per_call_override_wins(self):
        with guard_scope("check"):
            assert resolve_guard_mode("off") == "off"
            assert resolve_guard_mode(None) == "check"

    def test_invalid_modes_rejected(self):
        with pytest.raises(ValueError):
            set_guard_mode("paranoid")
        with pytest.raises(ValueError):
            resolve_guard_mode("paranoid")
        with pytest.raises(ValueError):
            with guard_scope("paranoid"):
                pass


class TestFiniteSentinel:
    def test_finite_true_nan_false(self):
        assert bool(finite_sentinel(jnp.ones((4, 4))))
        x = jnp.ones((4, 4)).at[2, 1].set(jnp.nan)
        assert not bool(finite_sentinel(x))
        assert not bool(finite_sentinel(jnp.ones(3), x))

    def test_integer_arrays_are_finite_by_construction(self):
        assert bool(finite_sentinel(jnp.arange(5), jnp.ones(2, bool)))


class TestSentinelsFire:
    """Satellite (d): seeded NaN/Inf raises across pairwise /
    contractions (gemm) / spmv under check; off propagates silently."""

    def test_pairwise_nan_input(self):
        from raft_tpu.distance import DistanceType, pairwise_distance

        x = np.ones((8, 4), np.float32)
        x[3, 2] = np.nan
        with guard_scope("check"):
            with pytest.raises(NonFiniteError) as ei:
                pairwise_distance(None, x, metric=DistanceType.L2Expanded)
        assert ei.value.stage == "input"
        # off: the seed behavior — NaN rows, no raise
        with guard_scope("off"):
            d = pairwise_distance(None, x)
        assert np.isnan(np.asarray(d)).any()

    def test_pairwise_output_overflow_attributed_to_output(self):
        from raft_tpu.distance import DistanceType, pairwise_distance

        # finite f32 inputs whose squared distances overflow f32: the
        # sentinel must blame the OUTPUT boundary (cancellation/overflow)
        x = np.full((4, 8), 1e38 / 4, np.float32)
        y = -x
        with guard_scope("check"):
            with pytest.raises(NonFiniteError) as ei:
                pairwise_distance(None, x, y,
                                  metric=DistanceType.L2Expanded)
        assert ei.value.stage == "output"

    def test_gemm_nan_input(self):
        from raft_tpu.linalg.blas import gemm

        a = np.ones((4, 4), np.float32)
        b = np.ones((4, 4), np.float32)
        b[0, 0] = np.inf
        with guard_scope("check"):
            with pytest.raises(NonFiniteError):
                gemm(None, a, b)
        with guard_scope("off"):               # off: silent propagation
            out = gemm(None, a, b)
        assert not np.isfinite(np.asarray(out)).all()

    def test_spmv_nan_data(self):
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.linalg import spmv

        s = sp.random(32, 32, density=0.2, format="csr",
                      random_state=0).astype(np.float32)
        s.data[1] = np.nan
        a = CSRMatrix(jnp.asarray(s.indptr), jnp.asarray(s.indices),
                      jnp.asarray(s.data), shape=s.shape)
        x = jnp.ones((32,), jnp.float32)
        with guard_scope("check"):
            with pytest.raises(NonFiniteError):
                spmv(a, x)
        with guard_scope("off"):               # off: silent
            assert np.isnan(np.asarray(spmv(a, x))).any()

    def test_eigsh_entry_validation(self):
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.solver.lanczos import eigsh

        s = sp.diags([np.full(64, 2.0), np.full(63, -1.0)],
                     [0, 1]).tocsr()
        s = (s + s.T).astype(np.float32)
        s.data[0] = np.nan
        a = CSRMatrix(jnp.asarray(s.indptr), jnp.asarray(s.indices),
                      jnp.asarray(s.data), shape=s.shape)
        with guard_scope("check"):
            with pytest.raises(NonFiniteError):
                eigsh(a, k=2)


class TestOffBitIdentical:
    """Acceptance: guard_mode='off' outputs are bit-identical, and a
    passing 'check' run does not perturb values either (read-only
    sentinel)."""

    def test_pairwise_bitwise_stable_across_modes(self):
        from raft_tpu.distance import pairwise_distance

        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 16)).astype(np.float32)
        with guard_scope("off"):
            d_off = np.asarray(pairwise_distance(None, x))
        with guard_scope("check"):
            d_chk = np.asarray(pairwise_distance(None, x))
        with guard_scope("recover"):
            d_rec = np.asarray(pairwise_distance(None, x))
        np.testing.assert_array_equal(d_off, d_chk)
        np.testing.assert_array_equal(d_off, d_rec)

    def test_gemm_bitwise_stable_across_modes(self):
        from raft_tpu.linalg.blas import gemm

        rng = np.random.default_rng(1)
        a = rng.normal(size=(16, 8)).astype(np.float32)
        b = rng.normal(size=(8, 16)).astype(np.float32)
        with guard_scope("off"):
            ref = np.asarray(gemm(None, a, b))
        with guard_scope("check"):
            np.testing.assert_array_equal(
                ref, np.asarray(gemm(None, a, b)))


class TestCholeskyGuards:
    """Satellite (a): the silent-NaN cholesky_r1_update path."""

    def _operands(self, a1):
        L = jnp.zeros((2, 2), jnp.float32).at[0, 0].set(1.0)
        return L, jnp.asarray([_A0, a1], jnp.float32)

    def test_non_psd_update_nan_under_off_typed_under_check(self):
        from raft_tpu.linalg.cholesky import cholesky_r1_update

        L, col = self._operands(_A1 - 100.0)   # negative pivot in f32+f64
        with guard_scope("off"):
            out = cholesky_r1_update(None, L, col, 2)
        assert np.isnan(np.asarray(out)[1, 1])           # seed behavior
        with guard_scope("check"):
            with pytest.raises(IllConditionedError) as ei:
                cholesky_r1_update(None, L, col, 2)
        assert ei.value.op == "linalg.cholesky_r1_update"

    def test_recover_rescues_f32_cancellation_to_f64_reference(self):
        from raft_tpu.linalg.cholesky import cholesky_r1_update

        L, col = self._operands(_A1)           # pivot 0 in f32, +0.99 f64
        with guard_scope("recover"):
            out = np.asarray(cholesky_r1_update(None, L, col, 2))
        ref = np.linalg.cholesky(
            np.array([[1.0, _A0], [_A0, _A1]], np.float64))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_recover_still_raises_when_f64_confirms_non_psd(self):
        from raft_tpu.linalg.cholesky import cholesky_r1_update

        L, col = self._operands(_A1 - 100.0)
        with guard_scope("recover"):
            with pytest.raises(IllConditionedError):
                cholesky_r1_update(None, L, col, 2)

    def test_per_call_override(self):
        from raft_tpu.linalg.cholesky import cholesky_r1_update

        L, col = self._operands(_A1 - 100.0)
        with pytest.raises(IllConditionedError):
            cholesky_r1_update(None, L, col, 2, guard_mode="check")


class TestConvergenceReports:
    def test_eig_jacobi_report_and_strict(self):
        from raft_tpu.linalg.eig import eig_jacobi

        rng = np.random.default_rng(2)
        s = rng.normal(size=(12, 12)).astype(np.float32)
        s = s + s.T
        w, v, rep = eig_jacobi(None, s, return_report=True)
        assert rep.converged and rep.n_iter >= 1
        # one sweep at an unreachable tol: unconverged, typed under strict
        w, v, rep = eig_jacobi(None, s, tol=1e-30, sweeps=1,
                               return_report=True)
        assert not rep.converged
        with pytest.raises(ConvergenceError) as ei:
            eig_jacobi(None, s, tol=1e-30, sweeps=1, strict=True)
        assert ei.value.report is not None

    def test_eig_jacobi_recover_escalates_to_f64(self):
        from raft_tpu.linalg.eig import eig_jacobi

        rng = np.random.default_rng(3)
        s = rng.normal(size=(12, 12)).astype(np.float32)
        s = s + s.T
        with guard_scope("recover"):
            w, v, rep = eig_jacobi(None, s, tol=1e-30, sweeps=1,
                                   return_report=True)
        assert rep.escalated and rep.converged
        ref = np.linalg.eigh(np.asarray(s, np.float64))[0]
        np.testing.assert_allclose(np.asarray(w), ref, rtol=1e-5,
                                   atol=1e-5)

    def test_lanczos_report(self):
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.solver.lanczos import eigsh

        s = sp.diags([np.full(100, 3.0), np.full(99, -1.0)], [0, 1])
        s = (s + s.T).tocsr().astype(np.float32)
        a = CSRMatrix(jnp.asarray(s.indptr), jnp.asarray(s.indices),
                      jnp.asarray(s.data), shape=s.shape)
        w, v, rep = eigsh(a, k=3, seed=0, return_report=True)
        assert rep.converged
        assert rep.n_iter >= 1

    def test_kmeans_report_and_strict(self):
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        rng = np.random.default_rng(4)
        x = np.concatenate([rng.normal(size=(50, 3)),
                            rng.normal(size=(50, 3)) + 20]).astype(
                                np.float32)
        params = KMeansParams(n_clusters=2, max_iter=50, seed=0)
        c, inertia, labels, n_iter, rep = kmeans_fit(
            None, params, x, return_report=True)
        assert rep.converged and rep.n_iter == n_iter
        # max_iter=1 cannot even poll twice: provably unconverged
        hard = KMeansParams(n_clusters=2, max_iter=1, seed=0)
        with pytest.raises(ConvergenceError):
            kmeans_fit(None, hard, x, strict=True)

    def test_lap_typed_error_keeps_runtimeerror_compat(self):
        from raft_tpu.solver.linear_assignment import \
            solve_linear_assignment

        cost = np.ones((4, 4), np.float32)
        cost[0, 0] = np.nan                    # bad lane → unassigned
        with pytest.raises(RuntimeError) as ei:   # pre-taxonomy spelling
            solve_linear_assignment(None, cost)
        assert isinstance(ei.value, ConvergenceError)
        assert not ei.value.report.converged
        # strict=False downgrades to warn + -1 lanes + report
        rows, total, rep = solve_linear_assignment(
            None, cost, strict=False, return_report=True)
        assert not rep.converged and bool((np.asarray(rows) < 0).any())

    def test_lap_converged_report(self):
        from raft_tpu.solver.linear_assignment import \
            solve_linear_assignment

        cost = np.array([[4., 1., 3.], [2., 0., 5.], [3., 2., 2.]],
                        np.float32)
        rows, total, rep = solve_linear_assignment(None, cost,
                                                   return_report=True)
        assert rep.converged and float(total) == 5.0


class TestValidators:
    def test_expect_square(self):
        from raft_tpu.util import expect_square

        expect_square(np.ones((3, 3)))
        with pytest.raises(ValueError):
            expect_square(np.ones((3, 4)), name="m")

    def test_expect_dtype(self):
        from raft_tpu.util import expect_dtype

        expect_dtype(np.ones(3, np.float32), np.float32)
        with pytest.raises(TypeError):
            expect_dtype(np.ones(3, np.int16), (np.float32, np.float64))

    def test_expect_positive(self):
        from raft_tpu.util import expect_positive

        expect_positive(3)
        expect_positive(0, strict=False)
        with pytest.raises(ValueError):
            expect_positive(0)
        with pytest.raises(ValueError):
            expect_positive(float("nan"))

    def test_expect_finite_gated_on_mode(self):
        from raft_tpu.util import expect_finite

        bad = np.array([1.0, np.nan], np.float32)
        with guard_scope("off"):
            expect_finite(bad, name="x")       # off: free, no raise
        with guard_scope("check"):
            with pytest.raises(NonFiniteError) as ei:
                expect_finite(bad, name="x")
        assert ei.value.stage == "input"

    def test_lstsq_entry_validation(self):
        from raft_tpu.linalg.lstsq import lstsq_qr

        a = np.ones((6, 3), np.float32)
        b = np.ones((6,), np.float32)
        with pytest.raises(ValueError):
            lstsq_qr(None, a, np.ones((5,), np.float32))
        bad = a.copy()
        bad[0, 0] = np.inf
        with guard_scope("check"):
            with pytest.raises(NonFiniteError):
                lstsq_qr(None, bad, b)

    def test_pca_entry_validation(self):
        from raft_tpu.linalg.pca import pca_fit

        with pytest.raises(ValueError):
            pca_fit(None, np.ones((4, 3), np.float32), n_components=0)
        bad = np.ones((8, 4), np.float32)
        bad[1, 1] = np.nan
        with guard_scope("check"):
            with pytest.raises(NonFiniteError):
                pca_fit(None, bad, n_components=2)


class TestRecoverEscalation:
    def test_escalation_emits_trace_event(self):
        from raft_tpu.core import trace
        from raft_tpu.linalg.cholesky import cholesky_r1_update

        L = jnp.zeros((2, 2), jnp.float32).at[0, 0].set(1.0)
        col = jnp.asarray([_A0, _A1], jnp.float32)
        trace.clear_events()
        with guard_scope("recover"):
            cholesky_r1_update(None, L, col, 2)
        evs = trace.events("guards.escalate")
        assert evs and evs[-1]["op"] == "linalg.cholesky_r1_update"

    def test_matmul_ladder_walks_one_rung(self):
        from raft_tpu.util import numerics

        assert numerics.next_tier("default") == "high"
        assert numerics.next_tier("high") == "highest"
        assert numerics.next_tier("highest") == "f64"
        assert numerics.next_tier("f64") is None

    def test_f64_host_round_trip(self):
        from raft_tpu.util.numerics import f64_host

        a = f64_host(np.ones(3, np.float32))
        assert a.dtype == np.float64
        a, b = f64_host(np.ones(2, np.float32), np.zeros(2, np.float32))
        assert a.dtype == b.dtype == np.float64
