"""Dense linalg tests vs NumPy/SciPy references
(ref test models: cpp/tests/linalg/*)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg
from raft_tpu.core import operators as ops
from raft_tpu.random import RngState


@pytest.fixture
def rng():
    return np.random.default_rng(42)


class TestBlas:
    def test_gemm(self, rng):
        A = rng.normal(size=(32, 16)).astype(np.float32)
        B = rng.normal(size=(16, 24)).astype(np.float32)
        out = np.asarray(linalg.gemm(None, A, B))
        np.testing.assert_allclose(out, A @ B, rtol=1e-4)

    def test_gemm_trans_alpha_beta(self, rng):
        A = rng.normal(size=(16, 32)).astype(np.float32)
        B = rng.normal(size=(24, 16)).astype(np.float32)
        C = rng.normal(size=(32, 24)).astype(np.float32)
        out = np.asarray(linalg.gemm(None, A, B, alpha=2.0, beta=0.5, C=C,
                                     trans_a=True, trans_b=True))
        np.testing.assert_allclose(out, 2.0 * (A.T @ B.T) + 0.5 * C,
                                   rtol=1e-4)

    def test_gemv_axpy_dot(self, rng):
        A = rng.normal(size=(10, 5)).astype(np.float32)
        x = rng.normal(size=5).astype(np.float32)
        y = rng.normal(size=10).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.gemv(None, A, x)),
                                   A @ x, rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(linalg.axpy(None, 2.0, y, y)), 3.0 * y, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(linalg.dot(None, x, x)),
                                   x @ x, rtol=1e-4)

    def test_transpose_mse(self, rng):
        A = rng.normal(size=(4, 7)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(linalg.transpose(None, A)),
                                      A.T)
        B = A + 1.0
        np.testing.assert_allclose(
            np.asarray(linalg.mean_squared_error(None, A, B)), 1.0,
            rtol=1e-5)


class TestReductions:
    def test_reduce_rows_and_cols(self, rng):
        X = rng.normal(size=(8, 6)).astype(np.float32)
        r = np.asarray(linalg.reduce(None, X, apply=linalg.ALONG_ROWS))
        np.testing.assert_allclose(r, X.sum(axis=1), rtol=1e-4)
        c = np.asarray(linalg.reduce(None, X, apply=linalg.ALONG_COLUMNS))
        np.testing.assert_allclose(c, X.sum(axis=0), rtol=1e-4)

    def test_reduce_with_ops(self, rng):
        X = rng.normal(size=(8, 6)).astype(np.float32)
        # sum of squares with final sqrt = L2 norms
        r = np.asarray(linalg.reduce(None, X, main_op=ops.sq_op,
                                     final_op=ops.sqrt_op))
        np.testing.assert_allclose(r, np.linalg.norm(X, axis=1), rtol=1e-4)
        m = np.asarray(linalg.reduce(None, X, reduce_op=ops.max_op,
                                     init=-np.inf))
        np.testing.assert_allclose(m, X.max(axis=1))

    def test_reduce_rows_by_key(self, rng):
        X = rng.normal(size=(10, 4)).astype(np.float32)
        keys = np.array([0, 1, 0, 2, 1, 0, 2, 2, 1, 0], dtype=np.int32)
        out = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 3))
        for k in range(3):
            np.testing.assert_allclose(out[k], X[keys == k].sum(axis=0),
                                       rtol=1e-4)

    def test_reduce_rows_by_key_weighted(self, rng):
        X = rng.normal(size=(6, 3)).astype(np.float32)
        keys = np.array([0, 0, 1, 1, 1, 0], dtype=np.int32)
        w = rng.uniform(size=6).astype(np.float32)
        out = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 2,
                                                   weights=w))
        for k in range(2):
            np.testing.assert_allclose(
                out[k], (X[keys == k] * w[keys == k, None]).sum(axis=0),
                rtol=1e-4)

    def test_reduce_cols_by_key(self, rng):
        X = rng.normal(size=(5, 8)).astype(np.float32)
        keys = np.array([0, 1, 2, 0, 1, 2, 0, 1], dtype=np.int32)
        out = np.asarray(linalg.reduce_cols_by_key(None, X, keys, 3))
        for k in range(3):
            np.testing.assert_allclose(out[:, k], X[:, keys == k].sum(axis=1),
                                       rtol=1e-4)


class TestMapNormMvo:
    def test_map_and_map_offset(self, rng):
        x = rng.normal(size=10).astype(np.float32)
        y = rng.normal(size=10).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.map(None, ops.add_op, x, y)), x + y, rtol=1e-5)
        out = np.asarray(linalg.map_offset(None, lambda i, v: i + v,
                                           10, jnp.zeros(10)))
        np.testing.assert_allclose(out, np.arange(10))

    def test_map_then_reduce(self, rng):
        x = rng.normal(size=100).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.map_then_reduce(None, ops.sq_op, x)),
            (x * x).sum(), rtol=1e-3)

    def test_matrix_vector_op(self, rng):
        X = rng.normal(size=(6, 4)).astype(np.float32)
        v = rng.normal(size=4).astype(np.float32)
        out = np.asarray(linalg.matrix_vector_op(None, X, v, ops.add_op,
                                                 apply=linalg.ALONG_ROWS))
        np.testing.assert_allclose(out, X + v[None, :], rtol=1e-5)
        w = rng.normal(size=6).astype(np.float32)
        out = np.asarray(linalg.matrix_vector_op(None, X, w, ops.mul_op,
                                                 apply=linalg.ALONG_COLUMNS))
        np.testing.assert_allclose(out, X * w[:, None], rtol=1e-5)

    def test_norms(self, rng):
        X = rng.normal(size=(6, 4)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm(None, X, linalg.L2Norm, sqrt=True)),
            np.linalg.norm(X, axis=1), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(linalg.col_norm(None, X, linalg.L1Norm)),
            np.abs(X).sum(axis=0), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(linalg.norm(None, X, linalg.LinfNorm)),
            np.abs(X).max(axis=1), rtol=1e-5)

    def test_normalize(self, rng):
        X = rng.normal(size=(6, 4)).astype(np.float32)
        out = np.asarray(linalg.normalize(None, X))
        np.testing.assert_allclose(np.linalg.norm(out, axis=1),
                                   np.ones(6), rtol=1e-4)


class TestDecompositions:
    def test_eig_dc(self, rng):
        A = rng.normal(size=(12, 12))
        S = (A + A.T).astype(np.float64)
        w, v = linalg.eig_dc(None, S)
        w, v = np.asarray(w), np.asarray(v)
        wref = np.linalg.eigvalsh(S)
        np.testing.assert_allclose(w, wref, rtol=1e-8)
        np.testing.assert_allclose(S @ v, v * w[None, :], atol=1e-8)

    def test_eig_sel(self, rng):
        A = rng.normal(size=(10, 10))
        S = (A + A.T).astype(np.float64)
        w, v = linalg.eig_sel(None, S, 3, largest=True)
        wref = np.linalg.eigvalsh(S)
        np.testing.assert_allclose(np.asarray(w), wref[-3:], rtol=1e-8)

    @pytest.mark.parametrize("largest", [True, False])
    def test_eig_sel_iterative_subset_path(self, rng, largest):
        # above _EIG_SEL_ITERATIVE_MIN_N the subset solver must run the
        # dense-operator Lanczos (never the full spectrum) and still match
        # the scipy subset to f32 accuracy
        from raft_tpu.linalg.eig import _EIG_SEL_ITERATIVE_MIN_N as n

        k = 4
        q, _ = np.linalg.qr(rng.normal(size=(n, n)))
        lam = np.sort(rng.normal(size=n) * 3.0)
        S = ((q * lam) @ q.T).astype(np.float32)
        w, v = linalg.eig_sel(None, jnp.asarray(S), k, largest=largest)
        w, v = np.asarray(w), np.asarray(v)
        ref = lam[-k:] if largest else lam[:k]
        np.testing.assert_allclose(w, ref, rtol=5e-4, atol=5e-4)
        assert np.all(np.diff(w) >= 0)          # ascending within selection
        res = np.abs(S.astype(np.float64) @ v - v * w).max()
        assert res < 5e-3 * np.abs(lam).max()

    def test_eig_sel_degenerate_multiplicity(self, rng):
        # ADVICE r4 medium: a degenerate extremal eigenvalue must come
        # back with its full multiplicity — via locking-deflated Lanczos
        # or the verified fallback to the exact slice, the CONTRACT is
        # the syevdx subset
        from raft_tpu.linalg.eig import _EIG_SEL_ITERATIVE_MIN_N as n

        k = 4
        q, _ = np.linalg.qr(rng.normal(size=(n, n)))
        lam = np.sort(rng.normal(size=n))
        lam[-3:] = 7.5                          # multiplicity-3 top value
        S = ((q * lam) @ q.T).astype(np.float32)
        w, v = linalg.eig_sel(None, jnp.asarray(S), k, largest=True)
        w, v = np.asarray(w, np.float64), np.asarray(v, np.float64)
        np.testing.assert_allclose(w, lam[-k:], rtol=2e-3, atol=2e-3)
        # the three copies must span a genuinely 3-dim eigenspace
        res = np.abs(S.astype(np.float64) @ v - v * w[None, :]).max()
        assert res < 5e-3 * np.abs(lam).max()
        g = v[:, -3:].T @ v[:, -3:]
        np.testing.assert_allclose(g, np.eye(3), atol=5e-3)

    def test_eig_sel_exact_kwarg(self, rng):
        # exact=True always takes the eig_dc slice, any dtype/size
        from raft_tpu.linalg.eig import _EIG_SEL_ITERATIVE_MIN_N as n

        q, _ = np.linalg.qr(rng.normal(size=(n, n)))
        lam = np.sort(rng.normal(size=n) * 2.0)
        S = ((q * lam) @ q.T).astype(np.float32)
        w, _ = linalg.eig_sel(None, jnp.asarray(S), 3, largest=False,
                              exact=True)
        np.testing.assert_allclose(np.asarray(w), lam[:3],
                                   rtol=5e-4, atol=5e-4)

    def test_eig_sel_wide_k_envelope(self, rng):
        # VERDICT r4 #8: k up to n/2 supported on the iterative path
        # (exact=False forces it past the n/3 auto crossover); parity vs
        # the numpy spectrum across the widened envelope
        n = 512
        k = n // 2
        q, _ = np.linalg.qr(rng.normal(size=(n, n)))
        lam = np.sort(rng.normal(size=n) * 3.0)
        S = ((q * lam) @ q.T).astype(np.float32)
        w, v = linalg.eig_sel(None, jnp.asarray(S), k, largest=True,
                              exact=False)
        w, v = np.asarray(w, np.float64), np.asarray(v, np.float64)
        np.testing.assert_allclose(w, lam[-k:], rtol=2e-3, atol=2e-3)
        res = np.abs(S.astype(np.float64) @ v - v * w[None, :]).max()
        assert res < 1e-2 * np.abs(lam).max()

    @pytest.mark.parametrize("n", [2, 5, 16, 33])
    def test_eig_jacobi(self, rng, n):
        """Real cyclic Jacobi (syevj analogue): eigenpairs, orthogonality,
        and both odd/even n (odd exercises the decoupled padding slot)."""
        A = rng.normal(size=(n, n))
        S = ((A + A.T) / 2).astype(np.float32)
        w, v = linalg.eig_jacobi(None, S, tol=1e-7, sweeps=20)
        w, v = np.asarray(w), np.asarray(v)
        wref = np.linalg.eigvalsh(S.astype(np.float64))
        np.testing.assert_allclose(w, wref, atol=5e-4)
        np.testing.assert_allclose(S @ v, v * w[None, :], atol=5e-3)
        np.testing.assert_allclose(v.T @ v, np.eye(n), atol=1e-4)

    def test_eig_jacobi_equal_diagonal(self):
        """tau == 0 (equal diagonal entries) needs the sign(0)=+1
        convention — a 45° rotation, not the identity."""
        S = np.array([[1.0, 0.5], [0.5, 1.0]], np.float32)
        w, v = linalg.eig_jacobi(None, S, tol=1e-7, sweeps=10)
        np.testing.assert_allclose(np.asarray(w), [0.5, 1.5], atol=1e-5)
        np.testing.assert_allclose(
            S @ np.asarray(v), np.asarray(v) * np.asarray(w)[None, :],
            atol=1e-5)

    def test_eig_jacobi_complex_routes_to_dc(self):
        A = np.array([[2.0, 1j], [-1j, 2.0]], np.complex64)
        w, v = linalg.eig_jacobi(None, A)
        np.testing.assert_allclose(np.sort(np.asarray(w)), [1.0, 3.0],
                                   atol=1e-5)

    def test_eig_jacobi_sweeps_knob(self, rng):
        """The sweeps cap must actually bound work (round 1 aliased
        eig_jacobi to eig_dc and ignored it)."""
        A = rng.normal(size=(48, 48))
        S = ((A + A.T) / 2).astype(np.float32)
        wref = np.linalg.eigvalsh(S.astype(np.float64))
        e1 = np.abs(np.sort(np.asarray(
            linalg.eig_jacobi(None, S, tol=1e-12, sweeps=1)[0])) - wref).max()
        e12 = np.abs(np.sort(np.asarray(
            linalg.eig_jacobi(None, S, tol=1e-12, sweeps=12)[0])) - wref).max()
        assert e12 < e1 * 1e-2

    def test_qr(self, rng):
        A = rng.normal(size=(10, 4)).astype(np.float64)
        q, r = linalg.qr_get_qr(None, A)
        q, r = np.asarray(q), np.asarray(r)
        np.testing.assert_allclose(q @ r, A, atol=1e-10)
        np.testing.assert_allclose(q.T @ q, np.eye(4), atol=1e-10)

    def test_svd_qr_and_eig(self, rng):
        A = rng.normal(size=(20, 6)).astype(np.float64)
        for fn in (linalg.svd_qr, linalg.svd_eig):
            u, s, v = fn(None, A)
            u, s, v = np.asarray(u), np.asarray(s), np.asarray(v)
            np.testing.assert_allclose((u * s[None, :]) @ v.T, A, atol=1e-6)
            np.testing.assert_allclose(
                s, np.linalg.svd(A, compute_uv=False), rtol=1e-6)
        assert linalg.evaluate_svd_by_reconstruction(
            None, A, *linalg.svd_qr(None, A))

    def test_rsvd(self, rng):
        # Low-rank matrix: rsvd should recover the spectrum.
        U = rng.normal(size=(60, 5))
        V = rng.normal(size=(5, 40))
        A = (U @ V).astype(np.float64)
        u, s, v = linalg.rsvd_fixed_rank(None, A, 5, state=RngState(0))
        sref = np.linalg.svd(A, compute_uv=False)[:5]
        np.testing.assert_allclose(np.asarray(s), sref, rtol=1e-6)
        np.testing.assert_allclose(
            (np.asarray(u) * np.asarray(s)) @ np.asarray(v).T, A, atol=1e-6)

    def test_lstsq_all_variants(self, rng):
        A = rng.normal(size=(30, 5)).astype(np.float64)
        x_true = rng.normal(size=5)
        b = A @ x_true
        for fn in (linalg.lstsq_svd_qr, linalg.lstsq_eig, linalg.lstsq_qr):
            x = np.asarray(fn(None, A, b))
            np.testing.assert_allclose(x, x_true, rtol=1e-6,
                                       err_msg=str(fn))

    def test_cholesky_r1_update(self, rng):
        # Grow a Cholesky factor one rank at a time; compare to direct chol.
        n = 6
        B = rng.normal(size=(n, n))
        A = B @ B.T + n * np.eye(n)
        L = jnp.zeros((n, n), dtype=jnp.float64)
        for k in range(1, n + 1):
            L = linalg.cholesky_r1_update(None, L, A[:k, k - 1], k)
        np.testing.assert_allclose(np.asarray(L), np.linalg.cholesky(A),
                                   atol=1e-8)


class TestPCA:
    def test_pca_matches_svd(self, rng):
        X = rng.normal(size=(200, 10)).astype(np.float64)
        result = linalg.pca_fit(None, X, 4)
        Xc = X - X.mean(axis=0)
        _, sref, vt = np.linalg.svd(Xc, full_matrices=False)
        var_ref = (sref ** 2) / (X.shape[0] - 1)
        np.testing.assert_allclose(np.asarray(result.explained_variance),
                                   var_ref[:4], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(result.singular_values),
                                   sref[:4], rtol=1e-6)
        # components span the same subspace (rows, up to sign)
        for i in range(4):
            c = np.asarray(result.components)[i]
            r = vt[i]
            assert min(np.linalg.norm(c - r), np.linalg.norm(c + r)) < 1e-6

    def test_pca_transform_roundtrip(self, rng):
        X = rng.normal(size=(100, 8)).astype(np.float64)
        T, result = linalg.pca_fit_transform(None, X, 8)
        Xr = np.asarray(linalg.pca_inverse_transform(None, T, result))
        np.testing.assert_allclose(Xr, X, atol=1e-8)

    def test_pca_whiten_roundtrip(self, rng):
        X = rng.normal(size=(100, 6)).astype(np.float64)
        result = linalg.pca_fit(None, X, 6)
        T = linalg.pca_transform(None, X, result, whiten=True)
        np.testing.assert_allclose(np.asarray(T).std(axis=0, ddof=1),
                                   np.ones(6), rtol=1e-6)
        Xr = np.asarray(linalg.pca_inverse_transform(None, T, result,
                                                     whiten=True))
        np.testing.assert_allclose(Xr, X, atol=1e-8)

    def test_pca_randomized_solver(self, rng):
        X = rng.normal(size=(300, 12)).astype(np.float64)
        exact = linalg.pca_fit(None, X, 3)
        rnd = linalg.pca_fit(None, X, 3, solver=linalg.Solver.RANDOMIZED,
                             state=RngState(1))
        np.testing.assert_allclose(np.asarray(rnd.explained_variance),
                                   np.asarray(exact.explained_variance),
                                   rtol=1e-2)

    def test_tsvd(self, rng):
        X = rng.normal(size=(150, 10)).astype(np.float64)
        result = linalg.tsvd_fit(None, X, 4)
        sref = np.linalg.svd(X, compute_uv=False)
        np.testing.assert_allclose(np.asarray(result.singular_values),
                                   sref[:4], rtol=1e-6)
        T, _ = linalg.tsvd_fit_transform(None, X, 10)
        Xr = np.asarray(linalg.tsvd_inverse_transform(None, T,
                        linalg.tsvd_fit(None, X, 10)))
        np.testing.assert_allclose(Xr, X, atol=1e-6)


class TestIncrementalPCA:
    """pca_partial_fit through the compiled-driver chunk runner
    (ROADMAP item 3's open line): chunked sufficient statistics must
    finalize to the monolithic pca_fit, stream across batches, and
    resume from a mid-batch checkpoint."""

    @pytest.fixture
    def X(self, rng):
        # correlated columns so the spectrum is non-trivial
        return (rng.normal(size=(2000, 24))
                @ rng.normal(size=(24, 24))).astype(np.float32)

    def test_chunked_matches_monolithic(self, X):
        full = linalg.pca_fit(None, X, 5)
        st = linalg.pca_partial_fit(None, X, chunk_rows=256)
        inc = linalg.pca_finalize(None, st, 5)
        np.testing.assert_allclose(np.asarray(inc.mean),
                                   np.asarray(full.mean), atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(inc.explained_variance),
            np.asarray(full.explained_variance), rtol=1e-4)
        np.testing.assert_allclose(
            np.asarray(inc.explained_variance_ratio),
            np.asarray(full.explained_variance_ratio), rtol=1e-4)
        np.testing.assert_allclose(np.abs(np.asarray(inc.components)),
                                   np.abs(np.asarray(full.components)),
                                   atol=1e-3)
        np.testing.assert_allclose(np.asarray(inc.noise_variance),
                                   np.asarray(full.noise_variance),
                                   rtol=1e-3)

    def test_two_batch_streaming_and_pad_tail(self, X):
        one = linalg.pca_partial_fit(None, X, chunk_rows=256)
        s1 = linalg.pca_partial_fit(None, X[:777], chunk_rows=128)
        # 777 rows / 128-row chunks: the pad rows must not perturb
        np.testing.assert_allclose(np.asarray(s1.mean),
                                   X[:777].mean(0), atol=1e-4)
        assert float(s1.count) == 777.0
        s2 = linalg.pca_partial_fit(None, X[777:], state=s1,
                                    chunk_rows=128)
        assert float(s2.count) == 2000.0
        np.testing.assert_allclose(np.asarray(s2.mean),
                                   np.asarray(one.mean), atol=1e-4)
        np.testing.assert_allclose(np.asarray(s2.scatter),
                                   np.asarray(one.scatter), rtol=1e-3)

    def test_checkpoint_resume_mid_batch(self, X, tmp_path):
        import os

        full = linalg.pca_partial_fit(None, X, chunk_rows=256,
                                      checkpoint_dir=str(tmp_path),
                                      checkpoint_every=1, sync_every=2)
        files = sorted(os.listdir(tmp_path))
        assert files and all(f.startswith("pca_pf") for f in files)
        resumed = linalg.pca_partial_fit(
            None, X, chunk_rows=256,
            resume_from=str(tmp_path / files[0]))
        np.testing.assert_allclose(np.asarray(resumed.mean),
                                   np.asarray(full.mean), atol=1e-5)
        np.testing.assert_allclose(np.asarray(resumed.scatter),
                                   np.asarray(full.scatter), rtol=1e-5)
        assert float(resumed.count) == float(full.count)

    def test_trace_and_validation(self, X):
        from raft_tpu.core import trace

        trace.clear_events()
        linalg.pca_partial_fit(None, X[:300], chunk_rows=100)
        ev = trace.events("pca.partial_fit")
        assert ev and ev[0]["rows"] == 300 and ev[0]["chunks"] == 3
        with pytest.raises(ValueError, match="columns"):
            st = linalg.pca_partial_fit(None, X[:100], chunk_rows=64)
            linalg.pca_partial_fit(None, X[:100, :8], state=st)
        with pytest.raises(ValueError, match="rows"):
            linalg.pca_finalize(
                None, linalg.IncrementalPCAState(
                    jnp.zeros(4), jnp.zeros((4, 4)),
                    jnp.zeros(())), 2)


class TestContractions:
    def test_pairwise_l2_vs_numpy(self, rng):
        x = rng.normal(size=(100, 37)).astype(np.float32)
        y = rng.normal(size=(53, 37)).astype(np.float32)
        ref = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        d = np.asarray(linalg.pairwise_l2_pallas(x, y))
        np.testing.assert_allclose(d, ref, atol=1e-3)
        d2 = np.asarray(linalg.pairwise_l2_pallas(x, y, sqrt=True))
        np.testing.assert_allclose(d2, np.sqrt(ref), atol=1e-3)

    def test_fused_l2_argmin(self, rng):
        x = rng.normal(size=(129, 17)).astype(np.float32)
        y = rng.normal(size=(77, 17)).astype(np.float32)
        ref = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        val, idx = linalg.fused_l2_argmin_pallas(x, y)
        np.testing.assert_array_equal(np.asarray(idx), ref.argmin(axis=1))
        np.testing.assert_allclose(np.asarray(val), ref.min(axis=1),
                                   atol=1e-3)

    def test_fused_l2_argmin_multi_tile(self, rng):
        # More centroids than one tile → exercises the running-min loop.
        x = rng.normal(size=(64, 8)).astype(np.float32)
        y = rng.normal(size=(300, 8)).astype(np.float32)
        ref = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        val, idx = linalg.fused_l2_argmin_pallas(x, y, tm=64, tn=128)
        np.testing.assert_array_equal(np.asarray(idx), ref.argmin(axis=1))

    def test_fused_l2_argmin_tiled_path(self, rng):
        # Y too large for VMEM residency → the 2-axis running-min kernel.
        from raft_tpu.linalg.contractions import _pick_tm
        x = rng.normal(size=(40, 9)).astype(np.float32)
        y = rng.normal(size=(20000, 9)).astype(np.float32)
        assert _pick_tm(128, 20096, mn_bufs=2,
                        const_bytes=20096 * 128 * 4) is None
        ref = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        val, idx = linalg.fused_l2_argmin_pallas(x, y)
        np.testing.assert_array_equal(np.asarray(idx), ref.argmin(axis=1))
        np.testing.assert_allclose(np.asarray(val), ref.min(axis=1),
                                   atol=1e-3)

    @pytest.mark.parametrize("metric", ["cosine", "inner"])
    def test_pairwise_metric_epilogues(self, rng, metric):
        from raft_tpu.linalg.contractions import pairwise_pallas

        x = rng.normal(size=(90, 23)).astype(np.float32)
        y = rng.normal(size=(41, 23)).astype(np.float32)
        d = np.asarray(pairwise_pallas(x, y, metric=metric))
        if metric == "cosine":
            xn = np.linalg.norm(x, axis=1, keepdims=True)
            yn = np.linalg.norm(y, axis=1, keepdims=True)
            ref = 1.0 - (x @ y.T) / (xn * yn.T)
        else:
            ref = -(x @ y.T)
        np.testing.assert_allclose(d, ref, rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("metric", ["cosine", "inner"])
    def test_fused_argmin_metric(self, rng, metric):
        from raft_tpu.linalg.contractions import fused_argmin_pallas

        x = rng.normal(size=(129, 17)).astype(np.float32)
        y = rng.normal(size=(300, 17)).astype(np.float32)
        if metric == "cosine":
            xn = np.linalg.norm(x, axis=1, keepdims=True)
            yn = np.linalg.norm(y, axis=1, keepdims=True)
            ref = 1.0 - (x @ y.T) / (xn * yn.T)
        else:
            ref = -(x @ y.T)
        val, idx = fused_argmin_pallas(x, y, metric=metric)
        np.testing.assert_array_equal(np.asarray(idx), ref.argmin(1))
        np.testing.assert_allclose(np.asarray(val), ref.min(1),
                                   rtol=1e-4, atol=1e-4)

    def _lloyd_oracle(self, x, y):
        ref = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        lab = ref.argmin(axis=1)
        sums = np.zeros_like(y)
        np.add.at(sums, lab, x)
        counts = np.bincount(lab, minlength=y.shape[0]).astype(np.float32)
        return ref, lab, sums, counts

    def test_fused_lloyd(self, rng):
        from raft_tpu.linalg.contractions import fused_lloyd_pallas
        x = rng.normal(size=(257, 19)).astype(np.float32)
        y = rng.normal(size=(31, 19)).astype(np.float32)
        ref, lab, sums_ref, counts_ref = self._lloyd_oracle(x, y)
        sums, counts, val, idx = fused_lloyd_pallas(x, y)
        np.testing.assert_array_equal(np.asarray(idx), lab)
        np.testing.assert_allclose(np.asarray(val), ref.min(1), atol=1e-3)
        np.testing.assert_allclose(np.asarray(sums), sums_ref,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(counts), counts_ref)

    def test_fused_lloyd_large_n_fallback(self, rng):
        # n too large for VMEM residency → argmin kernel + chunked one-hot.
        from raft_tpu.linalg.contractions import fused_lloyd_pallas
        x = rng.normal(size=(37, 5)).astype(np.float32)
        y = rng.normal(size=(20000, 5)).astype(np.float32)
        ref, lab, sums_ref, counts_ref = self._lloyd_oracle(x, y)
        sums, counts, val, idx = fused_lloyd_pallas(x, y)
        np.testing.assert_array_equal(np.asarray(idx), lab)
        np.testing.assert_allclose(np.asarray(sums), sums_ref,
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(counts), counts_ref)


def test_lstsq_multi_rhs(res):
    """Regression: 2-D (multi-RHS) b must row-scale by 1/s, not broadcast
    along the RHS axis."""
    import numpy as np
    from raft_tpu.linalg import lstsq_svd_qr, lstsq_eig, lstsq_qr

    rng = np.random.default_rng(3)
    A = rng.normal(size=(100, 4)).astype(np.float64)
    b = rng.normal(size=(100, 3)).astype(np.float64)
    want = np.linalg.lstsq(A, b, rcond=None)[0]
    for fn in (lstsq_svd_qr, lstsq_eig, lstsq_qr):
        got = np.asarray(fn(res, A, b))
        assert got.shape == (4, 3)
        assert np.allclose(got, want, atol=1e-8), fn.__name__


def test_reduce_minmax_default_init(res):
    """Regression: defaulted init must not clamp min/max reductions at 0."""
    import numpy as np
    from raft_tpu.linalg import coalesced_reduction
    from raft_tpu.core import operators as ops

    x = -1.0 - np.arange(6, dtype=np.float32).reshape(2, 3)
    got = np.asarray(coalesced_reduction(res, x, reduce_op=ops.max_op))
    assert np.allclose(got, x.max(axis=1))
    got = np.asarray(coalesced_reduction(res, -x, reduce_op=ops.min_op))
    assert np.allclose(got, (-x).min(axis=1))


class TestShapeDtypeGrid:
    """Multi-shape / multi-dtype grid over the hot dense primitives
    (round-2 verdict weak #9: single-shape coverage; the reference's
    typed test instantiations — e.g. cpp/tests/linalg/reduce.cu's
    float/double/half grids — are the model)."""

    SHAPES = [(1, 1), (3, 7), (128, 128), (129, 257), (1000, 3)]

    @pytest.mark.parametrize("shape", SHAPES)
    @pytest.mark.parametrize("dtype", [np.float32, np.float16, np.float64])
    def test_reduce_grid(self, shape, dtype):
        from raft_tpu.linalg import reduce as reduce_fn

        x = np.random.default_rng(hash(shape) % 2**31).normal(
            size=shape).astype(dtype)
        # raft vocabulary: ALONG_ROWS = one value per row (axis=1)
        for apply, axis in (("along_rows", 1), ("along_columns", 0)):
            out = np.asarray(reduce_fn(None, jnp.asarray(x), apply=apply))
            ref = x.astype(np.float64).sum(axis=axis)
            # f16 atol scales with reduction length: near-zero sums of N
            # cancel-prone values carry O(sqrt(N)·eps_f16) absolute error
            atol = (1e-2 * np.sqrt(x.shape[axis])
                    if dtype == np.float16 else 1e-2)
            np.testing.assert_allclose(out.astype(np.float64), ref,
                                       rtol=2e-2 if dtype == np.float16
                                       else 1e-5, atol=atol)

    @pytest.mark.parametrize("shape", SHAPES)
    def test_norm_normalize_grid(self, shape):
        from raft_tpu.linalg import normalize, row_norm

        x = np.random.default_rng(7).normal(size=shape).astype(np.float32)
        x[0, 0] = 0.0
        # sqrt=True: the default returns the squared norm, as the
        # reference's NormType::L2Norm does
        n = np.asarray(row_norm(None, jnp.asarray(x), norm_type="l2",
                                sqrt=True))
        ref = np.sqrt((x.astype(np.float64) ** 2).sum(1))
        np.testing.assert_allclose(n, ref, rtol=1e-5, atol=1e-6)
        z = np.asarray(normalize(None, jnp.asarray(x)))
        norms = np.linalg.norm(z, axis=1)
        nonzero = ref > 1e-8     # same eps gate normalize() itself uses
        np.testing.assert_allclose(norms[nonzero], 1.0, rtol=1e-5)

    @pytest.mark.parametrize("shape", SHAPES)
    @pytest.mark.parametrize("dtype", [np.float32, np.float16])
    def test_transpose_mvo_grid(self, shape, dtype):
        from raft_tpu.linalg import matrix_vector_op, transpose

        x = np.random.default_rng(9).normal(size=shape).astype(dtype)
        v = np.random.default_rng(10).normal(size=shape[1]).astype(dtype)
        t = np.asarray(transpose(None, jnp.asarray(x)))
        np.testing.assert_array_equal(t, x.T)
        out = np.asarray(matrix_vector_op(None, jnp.asarray(x),
                                          jnp.asarray(v),
                                          op=lambda a, b: a + b))
        np.testing.assert_allclose(out.astype(np.float64),
                                   (x.astype(np.float64)
                                    + v.astype(np.float64)[None, :]),
                                   rtol=2e-2 if dtype == np.float16
                                   else 1e-5, atol=1e-2)

    @pytest.mark.parametrize("m,n,k", [(1, 1, 1), (17, 33, 65),
                                       (128, 256, 64), (3, 500, 2)])
    def test_gemm_shape_grid(self, m, n, k):
        from raft_tpu.linalg import gemm

        rng = np.random.default_rng(m * 1000 + n)
        a = rng.normal(size=(m, k)).astype(np.float32)
        b = rng.normal(size=(k, n)).astype(np.float32)
        out = np.asarray(gemm(None, jnp.asarray(a), jnp.asarray(b)))
        ref = a.astype(np.float64) @ b.astype(np.float64)
        np.testing.assert_allclose(out.astype(np.float64), ref,
                                   rtol=1e-4, atol=1e-4)


class TestKeyedRowsumMatmul:
    """The one-hot contraction path of reduce_rows_by_key (small key
    counts) vs the segment-sum oracle, incl. chunk-boundary row counts,
    out-of-range key drops, and the int-dtype carve-out."""

    def test_matches_segment_sum_multi_chunk(self):
        import jax

        from raft_tpu import linalg

        rng = np.random.default_rng(7)
        # chunk = (32<<20)//(2*512) = 32768 -> 70000 rows span 3 chunks
        X = rng.normal(size=(70000, 8)).astype(np.float32)
        keys = rng.integers(-2, 514, size=70000).astype(np.int32)
        got = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 512))
        ref = np.asarray(jax.ops.segment_sum(
            jnp.asarray(X), jnp.asarray(keys), num_segments=512))
        np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-4)

    def test_int_data_stays_exact_segment_path(self):
        from raft_tpu import linalg

        X = np.arange(40, dtype=np.int32).reshape(10, 4)
        keys = np.array([0, 1] * 5, np.int32)
        got = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 2))
        assert got.dtype == np.int32
        np.testing.assert_array_equal(got[0], X[::2].sum(0))

    def test_large_key_count_uses_segment_path(self, monkeypatch):
        import importlib

        from raft_tpu import linalg
        red = importlib.import_module("raft_tpu.linalg.reduce")

        def boom(*a, **k):
            raise AssertionError("matmul path must not run at 5000 keys")

        monkeypatch.setattr(red, "_keyed_rowsum_matmul", boom)
        rng = np.random.default_rng(8)
        X = rng.normal(size=(100, 4)).astype(np.float32)
        keys = rng.integers(0, 5000, size=100).astype(np.int32)
        got = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 5000))
        assert got.shape == (5000, 4)
        np.testing.assert_allclose(got.sum(0), X.sum(0), rtol=1e-5)

    def test_default_tier_keeps_high_floor(self):
        """The keyed rowsum replaces an exact segment sum, so it must
        NOT follow the session tier down to one bf16 pass (~1e-3 rel) —
        the data side keeps its hi/lo split even at 'default'."""
        import raft_tpu
        from raft_tpu import linalg

        rng = np.random.default_rng(12)
        X = rng.normal(size=(40000, 6)).astype(np.float32)
        keys = rng.integers(0, 32, size=40000).astype(np.int32)
        ref = np.zeros((32, 6), np.float64)
        np.add.at(ref, keys, X.astype(np.float64))
        old = raft_tpu.get_matmul_precision()
        try:
            raft_tpu.set_matmul_precision("default")
            got = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 32))
        finally:
            raft_tpu.set_matmul_precision(old)
        np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-3)

    def test_narrow_key_dtype(self):
        from raft_tpu import linalg

        rng = np.random.default_rng(9)
        X = rng.normal(size=(1000, 4)).astype(np.float32)
        keys = rng.integers(0, 250, size=1000).astype(np.uint8)
        got = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 300))
        ref = np.zeros((300, 4), np.float64)
        np.add.at(ref, keys, X.astype(np.float64))
        np.testing.assert_allclose(got, ref, rtol=3e-5, atol=3e-4)

    def test_f64_keeps_exact_segment_path(self, monkeypatch):
        import jax

        if not jax.config.jax_enable_x64:
            pytest.skip("requires jax_enable_x64")
        import importlib

        from raft_tpu import linalg
        red = importlib.import_module("raft_tpu.linalg.reduce")

        def boom(*a, **k):
            raise AssertionError("f64 must stay on segment_sum")

        monkeypatch.setattr(red, "_keyed_rowsum_matmul", boom)
        X = np.random.default_rng(10).normal(size=(50, 3))
        keys = np.zeros(50, np.int32)
        got = np.asarray(linalg.reduce_rows_by_key(None, X, keys, 4))
        np.testing.assert_allclose(got[0], X.sum(0), rtol=1e-12)
