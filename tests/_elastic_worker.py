"""Worker for the multiprocess elastic k-means chaos test (ISSUE 2
acceptance: a 4-rank fit survives one SIGKILL'd rank and finishes on the
3 survivors from the last checkpoint).

Each worker builds a TcpMailbox clique (fast heartbeats: the detection →
abort → consensus → shrink round-trip must fit a test budget) over a
local CPU-device mesh — deliberately NOT `jax.distributed`: the global
XLA runtime cannot outlive a killed participant, which is exactly why
`kmeans_fit_elastic` keeps its reduction on the host mailbox.

Usage: python _elastic_worker.py <rank> <ckpt_dir> <mode> <addr0> ...

mode "faulted": checkpoint every iteration; rank 2 SIGKILLs itself at
iteration 4 (after the update, before the rank-0 checkpoint probe).
mode "clean:<path>": no failures, no checkpointing; resume from the
named checkpoint file on a (smaller) clique.
"""

import os
import signal
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=8 "
                           + os.environ.get("XLA_FLAGS", ""))

KILL_AT = 4


def dataset():
    import numpy as np

    rng = np.random.default_rng(7)
    return np.concatenate(
        [rng.normal(c, 0.35, (200, 6)) for c in range(5)])


def main():
    rank = int(sys.argv[1])
    ckpt_dir = sys.argv[2]
    mode = sys.argv[3]
    addrs = sys.argv[4:]
    nranks = len(addrs)

    import numpy as np
    import jax
    from jax.sharding import Mesh

    from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_elastic
    from raft_tpu.comms.comms import MeshComms
    from raft_tpu.comms.tcp_mailbox import TcpMailbox

    box = TcpMailbox(rank, addrs, heartbeat_interval=0.3,
                     heartbeat_timeout=1.5, default_recv_timeout=60.0)
    mesh = Mesh(np.asarray(jax.devices()[:nranks]), axis_names=("data",))
    comms = MeshComms(mesh, "data", rank, _mailbox=box)

    x = dataset()
    params = KMeansParams(n_clusters=5, max_iter=12, tol=1e-12, seed=11)

    def chaos(it, c):
        if rank == 2 and it == KILL_AT:
            print("ELASTIC_WORKER_SUICIDE", flush=True)
            sys.stdout.flush()
            os.kill(os.getpid(), signal.SIGKILL)

    if mode.startswith("clean:"):
        c, inertia, n_iter, comms = kmeans_fit_elastic(
            comms, params, x, resume_from=mode.split(":", 1)[1])
    else:
        c, inertia, n_iter, comms = kmeans_fit_elastic(
            comms, params, x, checkpoint_every=1, checkpoint_dir=ckpt_dir,
            checkpoint_keep=100, on_iteration=chaos)

    import zlib

    crc = zlib.crc32(np.ascontiguousarray(c).tobytes())
    print(f"ELASTIC_WORKER_OK rank={rank} size={comms.get_size()} "
          f"n_iter={n_iter} inertia={inertia:.17g} crc={crc}", flush=True)
    box.close()


if __name__ == "__main__":
    main()
