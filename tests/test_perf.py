"""Performance-attribution tests (ISSUE 13): the hardware peak table,
per-executable static-cost profiles (XLA cost analysis + model
fallback), roofline-fraction launch attribution and its
compute/bandwidth/overhead classification, the perf-off single-bool
no-op contract (serve-path bit identity), span/event ring loss counters
in ``obs.snapshot()``, profile_session span alignment, the bench
regression sentry, and fail-loud env-knob parsing."""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time

import numpy as np
import pytest

from raft_tpu import obs, serve
from raft_tpu.core import hw
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import perf
# NOT `from raft_tpu.obs import spans` — the facade re-exports the
# spans() *function* under that name, shadowing the submodule
from raft_tpu.obs.spans import set_retention as set_span_retention

_REPO = str(pathlib.Path(__file__).resolve().parent.parent)
_SENTRY = os.path.join(_REPO, "ci", "perf_sentry.py")

DIM = 16


@pytest.fixture
def perf_on():
    """Perf attribution on with a clean profile registry; restored to
    the ambient (off) state afterwards."""
    prev = perf.set_perf_enabled(True)
    perf.clear_perf_profiles()
    perf.reset_peaks()
    try:
        yield
    finally:
        perf.set_perf_enabled(prev)
        perf.clear_perf_profiles()
        perf.reset_peaks()


@pytest.fixture
def live_obs():
    """Metrics on with a fresh private registry and clean rings."""
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    old_sink = obs.set_sink(None)
    obs.set_enabled(True)
    obs.clear_spans()
    obs.clear_events()
    obs.set_sample_rate(1.0)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)
        obs.set_sink(old_sink)
        obs.clear_spans()
        obs.clear_events()
        obs.set_sample_rate(1.0)
        set_span_retention(2048)


def _gauge_value(reg, name, **labels):
    fam = reg.snapshot().get(name)
    if not fam:
        return None
    for s in fam["series"]:
        if all(s["labels"].get(k) == v for k, v in labels.items()):
            return s["value"]
    return None


# ---------------------------------------------------------------------------
# hardware peak table
# ---------------------------------------------------------------------------

class _FakeDevice:
    def __init__(self, platform, kind):
        self.platform = platform
        self.device_kind = kind


class TestHwPeaks:
    def test_cpu_backend(self):
        pk = hw.peaks(backend="cpu")
        assert pk.name == "cpu"
        assert pk.flops_per_s == 5e10 and pk.bytes_per_s == 2e10
        assert pk.source == "table"

    @pytest.mark.parametrize("kind,name,flops", [
        ("TPU v5 lite", "tpu-v5e", 197e12),
        ("TPU v5e", "tpu-v5e", 197e12),
        ("TPU v5p", "tpu-v5p", 459e12),
        ("TPU v4", "tpu-v4", 275e12),
        ("TPU v6 lite", "tpu-v6e", 918e12),
    ])
    def test_tpu_generation_match(self, kind, name, flops):
        pk = hw.peaks(_FakeDevice("tpu", kind))
        assert (pk.name, pk.flops_per_s) == (name, flops)
        assert pk.source == "table"

    def test_unknown_tpu_kind_falls_back(self):
        pk = hw.peaks(_FakeDevice("tpu", "TPU v99 hyper"))
        assert pk.source == "fallback"
        assert pk.flops_per_s > 0 and pk.bytes_per_s > 0

    def test_v5e_matches_harness_ceilings(self):
        """The bench harness's mxu/hbm roofline columns and the live
        perf gauges must divide by the same v5e ceilings."""
        from benches.harness import BenchResult
        pk = hw.peaks(_FakeDevice("tpu", "TPU v5 lite"))
        assert pk.flops_per_s == BenchResult.MXU_GFLOPS * 1e9
        assert pk.bytes_per_s == BenchResult.HBM_GB_S * 1e9

    def test_env_override_partial(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PERF_PEAKS", "flops=1e12")
        pk = hw.peaks(backend="cpu")
        assert pk.flops_per_s == 1e12
        assert pk.bytes_per_s == 2e10      # untouched axis keeps table
        assert pk.source == "env"

    def test_env_override_both(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PERF_PEAKS",
                           "flops=2e12,bytes=3e11")
        pk = hw.peaks(backend="cpu")
        assert (pk.flops_per_s, pk.bytes_per_s) == (2e12, 3e11)

    @pytest.mark.parametrize("bad", ["banana", "flops=", "flops=-1",
                                     "watts=3", "flops=1e12;bytes=2"])
    def test_env_override_malformed_raises(self, monkeypatch, bad):
        monkeypatch.setenv("RAFT_TPU_PERF_PEAKS", bad)
        with pytest.raises(ValueError, match="RAFT_TPU_PERF_PEAKS"):
            hw.peaks(backend="cpu")

    def test_limits_reexports_sustained_tables(self):
        from raft_tpu.runtime import limits
        assert limits._PEAK_FLOP_S is hw.SUSTAINED_FLOP_S
        assert limits._PEAK_BYTES_S is hw.SUSTAINED_BYTES_S


# ---------------------------------------------------------------------------
# static-cost profiles
# ---------------------------------------------------------------------------

class TestProfileExecutable:
    def test_off_is_noop(self):
        assert not perf.perf_enabled()
        assert perf.profile_executable("op", 8, model_flops=1.0) is None
        assert perf.record_launch("op", 8, 0.1) is None
        assert perf.record_hbm_watermark() is None
        assert perf.perf_profiles() == {}

    def test_model_source_without_fn(self, perf_on):
        prof = perf.profile_executable("op", 8, model_flops=100.0,
                                       model_bytes=200.0)
        assert prof.source == "model"
        assert (prof.flops, prof.bytes) == (100.0, 200.0)
        assert perf.perf_profiles()[("op", 8)] is prof

    def test_xla_source_with_real_fn(self, perf_on):
        import jax.numpy as jnp
        a = np.zeros((64, 32), np.float32)
        b = np.zeros((32, 16), np.float32)
        prof = perf.profile_executable(
            "dot", 64, fn=lambda x, y: jnp.dot(x, y), example=(a, b))
        assert prof.source == "xla"
        assert prof.flops > 0 and prof.bytes > 0

    def test_compiler_refusal_falls_back_to_model(self, perf_on):
        def bad(x):
            raise RuntimeError("untraceable")

        prof = perf.profile_executable(
            "bad", 4, fn=bad, example=(np.zeros(3, np.float32),),
            model_flops=7.0, model_bytes=9.0)
        assert prof.source == "model"
        assert (prof.flops, prof.bytes) == (7.0, 9.0)

    def test_reprofile_updates_in_place(self, perf_on):
        p1 = perf.profile_executable("op", 8, model_flops=1.0)
        perf.record_launch("op", 8, 0.5)
        p2 = perf.profile_executable("op", 8, model_flops=2.0)
        assert p2 is p1                   # launch history survives
        assert p1.flops == 2.0 and p1.launches == 1


class TestRecordLaunch:
    def test_roofline_math_compute_bound(self, perf_on):
        # CPU peaks: 5e10 flop/s, 2e10 B/s. flops dominate here.
        perf.profile_executable("op", 8, model_flops=2.5e10,
                                model_bytes=1e9)
        prof = perf.record_launch("op", 8, 1.0)
        assert prof.achieved_flops_per_s == pytest.approx(2.5e10)
        assert prof.roofline_frac == pytest.approx(0.5)
        assert prof.bound == "compute"

    def test_roofline_math_bandwidth_bound(self, perf_on):
        prof_bytes = 1.5e10               # t_b = 0.75 > t_f = 0.02
        perf.profile_executable("op", 8, model_flops=1e9,
                                model_bytes=prof_bytes)
        prof = perf.record_launch("op", 8, 1.0)
        assert prof.roofline_frac == pytest.approx(0.75)
        assert prof.bound == "bandwidth"

    def test_tiny_device_time_is_overhead_bound(self, perf_on):
        perf.profile_executable("op", 8, model_flops=1e6,
                                model_bytes=1e6)
        prof = perf.record_launch("op", 8, 1.0)
        assert prof.bound == "overhead"
        assert prof.roofline_frac < perf.OVERHEAD_FRAC

    def test_steps_scale_static_costs(self, perf_on):
        perf.profile_executable("op", "chunk", model_flops=1e9)
        prof = perf.record_launch("op", "chunk", 1.0, steps=10.0)
        assert prof.achieved_flops_per_s == pytest.approx(1e10)
        assert prof.steps == 10.0

    def test_unregistered_or_nonpositive_wall_ignored(self, perf_on):
        assert perf.record_launch("ghost", 8, 0.5) is None
        perf.profile_executable("op", 8, model_flops=1.0)
        assert perf.record_launch("op", 8, 0.0) is None
        assert perf.perf_profiles()[("op", 8)].launches == 0

    def test_gauges_published_when_metrics_on(self, perf_on, live_obs):
        perf.profile_executable("op", 8, model_flops=2.5e10,
                                model_bytes=1e9)
        perf.record_launch("op", 8, 1.0)
        assert _gauge_value(live_obs, "perf_roofline_frac", op="op",
                            bucket="8", bound="compute") \
            == pytest.approx(0.5)
        assert _gauge_value(live_obs, "perf_achieved_flops_per_s",
                            op="op", bucket="8") \
            == pytest.approx(2.5e10)
        assert _gauge_value(live_obs, "perf_achieved_bytes_per_s",
                            op="op", bucket="8") == pytest.approx(1e9)

    def test_hbm_watermark_polls_into_snapshot(self, perf_on):
        perf.record_hbm_watermark()       # CPU may report zeros; the
        snap = perf.perf_snapshot()       # poll itself must register
        assert snap["hbm"]["polls"] == 1


# ---------------------------------------------------------------------------
# obs.snapshot() integration: perf section + ring loss counters
# ---------------------------------------------------------------------------

class TestSnapshotIntegration:
    def test_snapshot_off_shape(self):
        snap = obs.snapshot()
        assert snap["perf"] == {"enabled": False, "profiles": {},
                                "hbm": snap["perf"]["hbm"]}
        for key in ("spans_dropped", "spans_sampled_out",
                    "events_overwritten"):
            assert key in snap

    def test_snapshot_perf_section(self, perf_on):
        perf.profile_executable("op", 8, model_flops=2.5e10,
                                model_bytes=1e9)
        perf.record_launch("op", 8, 1.0)
        sect = obs.snapshot()["perf"]
        assert sect["enabled"] is True
        assert sect["peaks"]["flops_per_s"] > 0
        prof = sect["profiles"]["op[8]"]
        assert prof["launches"] == 1
        assert prof["roofline_frac"] == pytest.approx(0.5)
        json.dumps(sect)                  # JSON-able end to end

    def test_span_ring_drop_counter(self, live_obs):
        set_span_retention(4)
        for i in range(7):
            obs.record_span(f"s{i}", t_start=0.0, duration=0.001)
        snap = obs.snapshot()
        assert snap["spans_dropped"] == 3
        assert obs.ring_stats()["retained"] == 4

    def test_span_sampling_counter(self, live_obs):
        obs.set_sample_rate(0.5)          # keep every 2nd per name
        for _ in range(6):
            with obs.span("sampled.op"):
                pass
        assert obs.snapshot()["spans_sampled_out"] == 3

    def test_event_ring_overwrite_counter(self, live_obs):
        for i in range(1024 + 5):
            obs.emit_event("evt", i=i)
        assert obs.snapshot()["events_overwritten"] == 5
        obs.clear_events()
        assert obs.snapshot()["events_overwritten"] == 0


# ---------------------------------------------------------------------------
# profile_session
# ---------------------------------------------------------------------------

class TestProfileSession:
    def test_off_yields_none_and_no_span(self, live_obs):
        assert not perf.perf_enabled()
        with obs.profile_session() as d:
            assert d is None
        assert obs.spans("perf.profile_session") == []

    def test_span_alignment(self, perf_on, live_obs, tmp_path):
        import jax.numpy as jnp
        t_before = time.monotonic()
        with obs.profile_session(str(tmp_path)) as d:
            jnp.dot(jnp.ones((8, 8)), jnp.ones((8, 8))).block_until_ready()
        t_after = time.monotonic()
        recs = obs.spans("perf.profile_session")
        assert len(recs) == 1
        rec = recs[0]
        # the span sits on the ring's monotonic clock, inside the
        # bracketing window, so Perfetto can line it up with host spans
        assert t_before <= rec["t"] <= t_after
        assert rec["t"] + rec["duration"] <= t_after + 0.01
        assert rec["attrs"]["log_dir"] == str(tmp_path)
        if rec["attrs"]["captured"]:      # CPU profiler availability
            assert d == str(tmp_path)


# ---------------------------------------------------------------------------
# serve-path integration: bit identity off, full attribution on
# ---------------------------------------------------------------------------

class TestServeIntegration:
    def _serve_outputs(self, data, rows_list):
        rng = np.random.default_rng(11)
        queries = [rng.standard_normal((r, DIM)).astype(np.float32)
                   for r in rows_list]
        services = [serve.KnnService(data["db"], k=4),
                    serve.PairwiseService(data["db"]),
                    serve.KMeansPredictService(data["centroids"])]
        ops = ["knn_k4_l2", "pairwise_l2_expanded", "kmeans_predict_k6"]
        ex = serve.Executor(
            services,
            policy=serve.BatchPolicy(max_batch=64, max_wait_ms=5.0))
        ex.warm([8, 16])
        outs = []
        with ex:
            futs = [(ops[i % 3], ex.submit(ops[i % 3], q))
                    for i, q in enumerate(queries)]
            for op, f in futs:
                got = f.result(timeout=60)
                got = got if isinstance(got, tuple) else (got,)
                outs.append((op, [np.asarray(x) for x in got]))
        return outs

    @pytest.fixture(scope="class")
    def data(self):
        rng = np.random.default_rng(7)
        return {
            "db": rng.standard_normal((96, DIM)).astype(np.float32),
            "centroids": rng.standard_normal((6, DIM)).astype(np.float32),
        }

    def test_perf_off_bit_identical_serve(self, data):
        """Flipping RAFT_TPU_PERF must not change a single served bit
        across knn / pairwise / kmeans-predict."""
        rows = [1, 3, 8, 2, 6, 5]
        base = self._serve_outputs(data, rows)
        prev = perf.set_perf_enabled(True)
        perf.clear_perf_profiles()
        try:
            on = self._serve_outputs(data, rows)
        finally:
            perf.set_perf_enabled(prev)
            perf.clear_perf_profiles()
        assert [op for op, _ in base] == [op for op, _ in on]
        for (_, b), (_, o) in zip(base, on):
            assert len(b) == len(o)
            for x, y in zip(b, o):
                np.testing.assert_array_equal(x, y)

    def test_every_warmed_executable_profiled(self, data, perf_on):
        """The acceptance bar: with perf on, every warmed (service,
        bucket) executable reports static costs plus a measured
        roofline fraction in obs.snapshot()."""
        self._serve_outputs(data, [1, 3, 8, 2, 6, 5])
        profs = perf.perf_profiles()
        for op in ("knn_k4_l2", "pairwise_l2_expanded",
                   "kmeans_predict_k6"):
            for bucket in (8, 16):
                prof = profs[(op, bucket)]
                assert prof.flops > 0 or prof.bytes > 0
                assert prof.launches >= 1      # warm() timed invocation
                assert prof.roofline_frac > 0
                assert prof.bound in ("compute", "bandwidth",
                                      "overhead")
        sect = obs.snapshot()["perf"]
        assert f"knn_k4_l2[8]" in sect["profiles"]


# ---------------------------------------------------------------------------
# compiled-driver integration
# ---------------------------------------------------------------------------

class TestCompiledDriverIntegration:
    def test_chunk_profile_and_hbm_polls(self, perf_on):
        from raft_tpu.cluster import KMeansParams, kmeans_fit
        rng = np.random.default_rng(0)
        x = rng.standard_normal((120, 8)).astype(np.float32)
        kmeans_fit(None, KMeansParams(n_clusters=4, max_iter=6), x,
                   sync_every=2)
        profs = perf.perf_profiles()
        prof = profs[("cluster.kmeans_fit", "chunk")]
        assert prof.source == "model"
        assert prof.flops > 0 and prof.bytes > 0
        assert prof.launches >= 1
        assert prof.steps >= prof.launches   # chunks run >= 1 step
        assert perf.perf_snapshot()["hbm"]["polls"] >= 1


# ---------------------------------------------------------------------------
# perf_sentry
# ---------------------------------------------------------------------------

def _run_sentry(*argv, env=None):
    return subprocess.run(
        [sys.executable, _SENTRY, *argv],
        capture_output=True, text=True, cwd=_REPO,
        env={**os.environ, **(env or {})})


class TestPerfSentry:
    @pytest.fixture
    def hist(self, tmp_path):
        h = tmp_path / "hist"
        h.mkdir()
        rows = [
            {"bench": "fam/a", "median_ms": 10.0, "era": 2},
            {"bench": "fam/a", "median_ms": 8.0, "era": 2},
            {"bench": "fam/a", "median_ms": 5.0, "era": 1,
             "superseded_by": "r2"},      # retired: NOT the baseline
            {"metric": "fam/tput", "value": 100.0, "backend": "tpu",
             "era": 2},
        ]
        (h / "bench_small_cpu_r1.jsonl").write_text(
            "\n".join(json.dumps(r) for r in rows) + "\n")
        return h

    def _fresh(self, tmp_path, rows, name="fresh.jsonl"):
        p = tmp_path / name
        p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        return str(p)

    def test_audit_shipped_history_passes(self):
        proc = _run_sentry()
        assert proc.returncode == 0, proc.stderr
        assert "PASS (audit)" in proc.stdout

    def test_no_regression_passes(self, hist, tmp_path):
        fresh = self._fresh(tmp_path, [
            {"bench": "fam/a", "median_ms": 9.0, "era": 2},
            {"metric": "fam/tput", "value": 95.0, "backend": "tpu",
             "era": 2},
        ])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_regression_fails(self, hist, tmp_path):
        # baseline is the best CURRENT row (8.0), not the superseded
        # 5.0 — 2x the baseline trips the default 1.5x tolerance
        fresh = self._fresh(tmp_path,
                            [{"bench": "fam/a", "median_ms": 16.0,
                              "era": 2}])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh)
        assert proc.returncode == 1
        assert "fam/a" in proc.stdout

    def test_throughput_regression_fails(self, hist, tmp_path):
        fresh = self._fresh(tmp_path,
                            [{"metric": "fam/tput", "value": 40.0,
                              "backend": "tpu", "era": 2}])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh)
        assert proc.returncode == 1
        assert "higher is better" in proc.stdout

    def test_stale_era_fails_loud(self, hist, tmp_path):
        fresh = self._fresh(tmp_path,
                            [{"bench": "fam/a", "median_ms": 1.0,
                              "era": 1}])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh)
        assert proc.returncode == 1
        assert "stale-era" in proc.stdout

    def test_family_tol_overrides_default(self, hist, tmp_path):
        fresh = self._fresh(tmp_path,
                            [{"bench": "fam/a", "median_ms": 16.0,
                              "era": 2}])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh,
                           "--family-tol", "fam/a=2.5")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_env_tolerance_knob(self, hist, tmp_path):
        fresh = self._fresh(tmp_path,
                            [{"bench": "fam/a", "median_ms": 16.0,
                              "era": 2}])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh,
                           env={"RAFT_TPU_SENTRY_TOL": "2.5"})
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_malformed_tolerance_exits_2(self, hist, tmp_path):
        fresh = self._fresh(tmp_path,
                            [{"bench": "fam/a", "median_ms": 9.0,
                              "era": 2}])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh,
                           env={"RAFT_TPU_SENTRY_TOL": "banana"})
        assert proc.returncode == 2
        assert "RAFT_TPU_SENTRY_TOL" in proc.stderr

    def test_corrupt_history_exits_2(self, tmp_path):
        h = tmp_path / "hist"
        h.mkdir()
        (h / "bench_small_cpu_r1.jsonl").write_text("{not json\n")
        proc = _run_sentry("--history", str(h))
        assert proc.returncode == 2

    def test_superseded_fresh_row_skipped(self, hist, tmp_path):
        fresh = self._fresh(tmp_path,
                            [{"bench": "fam/a", "median_ms": 99.0,
                              "era": 2, "superseded_by": "r3"}])
        proc = _run_sentry("--history", str(hist), "--fresh", fresh)
        assert proc.returncode == 0
        assert "skipped" in proc.stdout


# ---------------------------------------------------------------------------
# env knobs: fail-loud subprocess contracts
# ---------------------------------------------------------------------------

class TestEnvKnobs:
    def _run(self, code, env):
        return subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, **env}, capture_output=True, text=True,
            cwd=_REPO)

    def test_malformed_peaks_raises_at_read(self):
        proc = self._run(
            "from raft_tpu.core import hw; hw.peaks(backend='cpu')",
            {"RAFT_TPU_PERF_PEAKS": "banana"})
        assert proc.returncode != 0
        assert "RAFT_TPU_PERF_PEAKS" in proc.stderr

    def test_malformed_sentry_tol_raises_at_read(self):
        proc = self._run(
            "from raft_tpu.core import env; "
            "env.read('RAFT_TPU_SENTRY_TOL')",
            {"RAFT_TPU_SENTRY_TOL": "0.5"})
        assert proc.returncode != 0
        assert "RAFT_TPU_SENTRY_TOL" in proc.stderr

    def test_malformed_perf_warns_and_stays_off(self):
        # observability toggles degrade to off with a warning (the
        # RAFT_TPU_METRICS policy), they do not crash the import
        proc = self._run(
            "import warnings; warnings.simplefilter('error');\n"
            "try:\n"
            "    from raft_tpu.obs import perf\n"
            "    raise SystemExit('expected a warning')\n"
            "except Warning as w:\n"
            "    assert 'RAFT_TPU_PERF' in str(w)\n",
            {"RAFT_TPU_PERF": "banana"})
        assert proc.returncode == 0, proc.stderr

    def test_perf_on_via_env(self):
        proc = self._run(
            "from raft_tpu.obs import perf; "
            "assert perf.perf_enabled()",
            {"RAFT_TPU_PERF": "on"})
        assert proc.returncode == 0, proc.stderr
