"""Worker + orchestrator for the durable-streaming-fleet chaos witness
(ISSUE 18 acceptance: a follower replica SIGKILL'd mid-stream restarts
from its mirrored journal, catches up over the wire under query load,
and converges to the leader's ``content_crc`` bit-for-bit — equal to a
clean never-killed twin).

Roles (``python tests/_durability_worker.py <role> ...``):

``leader --dir D --addrs A0 A1``
    Rank 0: builds the journaled index, attaches a
    :class:`~raft_tpu.neighbors.wal_ship.WalShipper` (live shipping +
    catch-up service), waits for the follower's READY, streams the
    deterministic mutation sequence (one forced refit mid-stream so a
    KIND_CENTROIDS record crosses the wire), then keeps serving
    catch-up until the follower's DONE. Prints
    ``LEADER_OK crc=<c> seq=<s> ship_errors=<n>``.

``follower --dir D --addrs A0 A1 --kill-at-seq N``
    Rank 1, phase 1: bootstraps a blank follower (snapshot resync),
    drains live records until its applied cursor reaches N, then
    SIGKILLs itself — no atexit, no finally; the mirrored journal on
    disk is whatever the OS kept.

``follower --dir D --addrs A0 A1 --resume``
    Rank 1, phase 2: recovers the SAME index from the mirrored journal
    (``StreamingIndex.recover``), prints the resume cursor, then
    catches up to TARGET_SEQ **under query load**
    (:func:`~raft_tpu.serve.loadgen.catchup_under_load` — the
    recall-floor-during-catch-up witness), sends DONE, prints
    ``FOLLOWER_OK crc=<c> applied=<s> resumed=<r> min_recall=<f>
    queries=<q> resyncs=<n>``.

``clean --dir D``
    The never-killed twin: runs the identical mutation sequence
    in-process (no comms) and prints ``CLEAN_OK crc=<c> seq=<s>``.

``orchestrate``
    Runs the whole dance (clean twin, leader, follower kill at
    KILL_AT_SEQ with rc −9 asserted, follower resume) in subprocesses
    and asserts all three CRCs equal and the catch-up recall floor
    held. Prints ``DURABILITY_CHAOS_OK ...`` — ci/smoke.sh gates on it.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_DB, DIM, N_LISTS = 160, 8, 8
N_BATCHES = 8           # each batch = 1 insert + 1 delete WAL record
B_ROWS = 12
REFIT_AT = 4            # forced refit after this batch (+1 record)
TARGET_SEQ = 2 * N_BATCHES          # 16 records, seqs 0..16 inclusive
KILL_AT_SEQ = 6                      # mid-stream, before the refit
K, NPROBE = 5, N_LISTS               # exact probe: recall floor is 1.0
TAG_READY, TAG_DONE = 7400, 7401


def _mutate(idx, rng):
    """The deterministic mutation stream both twins run. Yields after
    every batch so the leader can pace live shipping."""
    for i in range(N_BATCHES):
        ids = idx.insert(rng.normal(size=(B_ROWS, DIM)).astype("float32"))
        idx.delete(ids[::3])
        if i == REFIT_AT:
            idx.maybe_refit(force=True)
        yield i


def _build(directory):
    import numpy as np

    from raft_tpu.neighbors import streaming

    rng = np.random.default_rng(7)
    db = rng.normal(size=(N_DB, DIM)).astype(np.float32)
    idx = streaming.stream_build(None, db, N_LISTS, seed=0, max_iter=4,
                                 directory=directory)
    return idx, rng


def run_clean(directory):
    idx, rng = _build(directory)
    for _ in _mutate(idx, rng):
        pass
    print(f"CLEAN_OK crc={idx.content_crc()} seq={idx._applied_seq}",
          flush=True)


def run_leader(directory, addrs):
    import numpy as np

    from raft_tpu.comms.errors import (CommsTimeoutError,
                                       PeerFailedError)
    from raft_tpu.comms.tcp_mailbox import TcpMailbox
    from raft_tpu.neighbors.wal_ship import WalShipper

    box = TcpMailbox(0, addrs, heartbeat_interval=0.3,
                     heartbeat_timeout=2.0)
    idx, rng = _build(directory)
    shipper = WalShipper(idx, box, 0, [1], poll_interval=0.01)
    shipper.attach()
    shipper.start()
    np.asarray(box.get(1, 0, TAG_READY, timeout=120.0))
    for _ in _mutate(idx, rng):
        time.sleep(0.03)        # pace: the kill lands mid-stream
    print(f"LEADER_STREAMED seq={idx._applied_seq}", flush=True)
    # wait for the restarted follower's DONE; the phase-1 death marks
    # the peer failed (pending gets fail fast), so revive + retry until
    # phase 2 reconnects
    deadline = time.monotonic() + 120.0
    while True:
        try:
            np.asarray(box.get(1, 0, TAG_DONE, timeout=5.0))
            break
        except (PeerFailedError, CommsTimeoutError):
            if time.monotonic() > deadline:
                raise
            box.revive_peer(1)
    print(f"LEADER_OK crc={idx.content_crc()} seq={idx._applied_seq} "
          f"ship_errors={shipper.ship_errors}", flush=True)
    shipper.stop()
    shipper.detach()
    box.close()


def run_follower_phase1(directory, addrs, kill_at_seq):
    import numpy as np

    from raft_tpu.comms.tcp_mailbox import TcpMailbox
    from raft_tpu.neighbors.wal_ship import (WalFollower,
                                             bootstrap_follower)

    box = TcpMailbox(1, addrs, heartbeat_interval=0.3,
                     heartbeat_timeout=2.0)
    idx = bootstrap_follower(None, dim=DIM, n_lists=N_LISTS,
                             directory=directory)
    wf = WalFollower(idx, box, 1, 0)
    box.put(1, 0, TAG_READY, np.asarray([1], np.int64))
    wf.catch_up(timeout=60.0)       # cursor −1 → snapshot resync
    while wf.applied_seq < kill_at_seq:
        if wf.drain() == 0:
            time.sleep(0.005)
    print(f"FOLLOWER_SUICIDE seq={wf.applied_seq}", flush=True)
    sys.stdout.flush()
    os.kill(os.getpid(), signal.SIGKILL)


def run_follower_resume(directory, addrs):
    import numpy as np

    from raft_tpu.comms.tcp_mailbox import TcpMailbox
    from raft_tpu.neighbors.streaming import StreamingIndex
    from raft_tpu.neighbors.wal_ship import WalFollower
    from raft_tpu.serve.loadgen import catchup_under_load

    box = TcpMailbox(1, addrs, heartbeat_interval=0.3,
                     heartbeat_timeout=2.0)
    # the SIGKILL'd replica's restart: epoch snapshot + mirrored WAL
    # suffix reproduce the pre-kill state and cursor exactly
    idx = StreamingIndex.recover(None, directory)
    resumed = idx._applied_seq
    print(f"FOLLOWER_RESUMED seq={resumed}", flush=True)
    wf = WalFollower(idx, box, 1, 0)
    rep = catchup_under_load(wf, k=K, nprobe=NPROBE,
                             target_seq=TARGET_SEQ, rows=4, seed=3,
                             wait_s=60.0)
    box.put(1, 0, TAG_DONE, np.asarray([1], np.int64))
    print(f"FOLLOWER_OK crc={idx.content_crc()} "
          f"applied={wf.applied_seq} resumed={resumed} "
          f"min_recall={rep.min_recall:.4f} queries={rep.queries} "
          f"resyncs={rep.resyncs}", flush=True)
    time.sleep(0.2)                 # let the DONE frame flush
    box.close()


# -- orchestrator ------------------------------------------------------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _field(out, marker, key):
    import re

    m = re.search(rf"{marker}\b.*\b{key}=([\d.+-]+)", out)
    assert m, f"missing {marker} {key}= in:\n{out}"
    return m.group(1)


def orchestrate():
    import tempfile

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    me = os.path.abspath(__file__)

    def launch(args):
        return subprocess.Popen([sys.executable, me] + args, cwd=_REPO,
                                env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    with tempfile.TemporaryDirectory() as tmp:
        d_clean = os.path.join(tmp, "clean")
        d_lead = os.path.join(tmp, "leader")
        d_foll = os.path.join(tmp, "follower")
        clean = launch(["clean", "--dir", d_clean])
        addrs = [f"127.0.0.1:{p}" for p in _free_ports(2)]
        leader = launch(["leader", "--dir", d_lead, "--addrs"] + addrs)
        f1 = launch(["follower", "--dir", d_foll, "--addrs"] + addrs
                    + ["--kill-at-seq", str(KILL_AT_SEQ)])
        out1 = f1.communicate(timeout=180)[0]
        assert f1.returncode == -9, \
            f"phase-1 follower was not SIGKILLed (rc={f1.returncode}):" \
            f"\n{out1}"
        assert "FOLLOWER_SUICIDE" in out1, out1
        f2 = launch(["follower", "--dir", d_foll, "--addrs"] + addrs
                    + ["--resume"])
        out2 = f2.communicate(timeout=180)[0]
        assert f2.returncode == 0, f"resume follower failed:\n{out2}"
        out_l = leader.communicate(timeout=180)[0]
        assert leader.returncode == 0, f"leader failed:\n{out_l}"
        out_c = clean.communicate(timeout=180)[0]
        assert clean.returncode == 0, f"clean twin failed:\n{out_c}"

    crc_clean = _field(out_c, "CLEAN_OK", "crc")
    crc_lead = _field(out_l, "LEADER_OK", "crc")
    crc_foll = _field(out2, "FOLLOWER_OK", "crc")
    assert crc_lead == crc_clean, \
        f"leader diverged from clean twin: {crc_lead} != {crc_clean}"
    assert crc_foll == crc_lead, \
        f"restarted follower diverged: {crc_foll} != {crc_lead}"
    # the journal cursor survived the SIGKILL: the restart resumed at
    # least at the kill threshold (drain may overshoot by one queued
    # batch) and well short of the leader's final horizon
    resumed = int(_field(out2, "FOLLOWER_OK", "resumed"))
    assert KILL_AT_SEQ <= resumed < TARGET_SEQ, out2
    applied = int(_field(out2, "FOLLOWER_OK", "applied"))
    assert applied >= TARGET_SEQ, out2
    min_recall = float(_field(out2, "FOLLOWER_OK", "min_recall"))
    queries = int(_field(out2, "FOLLOWER_OK", "queries"))
    assert queries >= 1, out2
    assert min_recall >= 0.99, \
        f"recall floor broken during catch-up: {min_recall}\n{out2}"
    print(f"DURABILITY_CHAOS_OK crc={crc_foll} resumed={resumed} "
          f"applied={applied} min_recall={min_recall:.4f} "
          f"queries={queries}", flush=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("role", choices=["leader", "follower", "clean",
                                    "orchestrate"])
    p.add_argument("--dir")
    p.add_argument("--addrs", nargs="*", default=[])
    p.add_argument("--kill-at-seq", type=int, default=None)
    p.add_argument("--resume", action="store_true")
    a = p.parse_args(argv)
    if a.role == "orchestrate":
        orchestrate()
    elif a.role == "clean":
        run_clean(a.dir)
    elif a.role == "leader":
        run_leader(a.dir, a.addrs)
    elif a.resume:
        run_follower_resume(a.dir, a.addrs)
    else:
        assert a.kill_at_seq is not None
        run_follower_phase1(a.dir, a.addrs, a.kill_at_seq)
    return 0


if __name__ == "__main__":
    sys.exit(main())
