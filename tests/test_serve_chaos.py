"""Multiprocess sharded-serving chaos test (ISSUE 11 acceptance): a
3-process serving clique streaming queries has its highest rank
SIGKILL'd mid-stream; the 2 survivors detect → abort → agree → shrink →
repack and keep answering, and BOTH their repacked index and their full
result stream are bit-for-bit equal to a clean 2-process run.
"""

import os
import re
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_OK_RE = (r"SERVE_CHAOS_OK rank=\d+ size=(\d+) n_iter=(\d+) "
          r"idx_crc=(\d+) res_crc=(\d+) recovery_s=([\d.]+)")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class TestServeChaosSigkill:
    # slow: boots 5 fresh interpreters (two cliques) at ~22s wall — off
    # the tier-1 budget like the PR-9 heavyweights; ci/smoke.sh carries
    # the in-process kill/heal/repack gate on every run.
    @pytest.mark.slow
    def test_killed_rank_survivors_answer_bit_for_bit(self):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        worker = os.path.join(_REPO, "tests", "_serve_chaos_worker.py")

        def launch(nproc, mode):
            addrs = [f"127.0.0.1:{p}" for p in _free_ports(nproc)]
            procs = [subprocess.Popen(
                [sys.executable, worker, str(r), mode] + addrs,
                cwd=_REPO, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
                for r in range(nproc)]
            outs = []
            try:
                for p in procs:
                    outs.append(p.communicate(timeout=180)[0])
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
            return procs, outs

        procs, outs = launch(3, "faulted")
        assert procs[2].returncode == -9, outs[2]   # actually SIGKILLed
        assert "SERVE_CHAOS_SUICIDE" in outs[2]
        results = set()
        recoveries = []
        for r in (0, 1):
            assert procs[r].returncode == 0, \
                f"survivor {r} failed:\n{outs[r]}"
            m = re.search(_OK_RE, outs[r])
            assert m, outs[r]
            assert m.group(1) == "2"                # finished on 2 ranks
            results.add(m.groups()[:4])
            recoveries.append(float(m.group(5)))
        assert len(results) == 1                    # survivors agree
        # detect -> consensus -> shrink -> repack -> redone iteration,
        # well inside the serving recovery budget
        assert all(0.0 < s < 60.0 for s in recoveries)

        procs, outs = launch(2, "clean")
        clean = set()
        for r in range(2):
            assert procs[r].returncode == 0, outs[r]
            m = re.search(_OK_RE, outs[r])
            assert m, outs[r]
            clean.add(m.groups()[:4])
        # post-shrink index AND the merged result stream are bit-equal
        # to the clean 2-rank run (idx_crc + res_crc both in the tuple)
        assert clean == results
