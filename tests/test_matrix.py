"""Matrix primitive tests (ref test models: cpp/tests/matrix/*)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import matrix
from raft_tpu.matrix import SelectAlgo
from raft_tpu.random import RngState


@pytest.fixture
def rng():
    return np.random.default_rng(7)


class TestSelectK:
    @pytest.mark.parametrize("n_rows,n_cols,k", [
        (1, 100, 5), (8, 1000, 32), (3, 257, 257), (4, 64, 1),
    ])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_against_numpy(self, rng, n_rows, n_cols, k, select_min):
        v = rng.normal(size=(n_rows, n_cols)).astype(np.float32)
        out_val, out_idx = matrix.select_k(None, v, k, select_min=select_min)
        out_val, out_idx = np.asarray(out_val), np.asarray(out_idx)
        order = np.sort(v, axis=1)
        expect = order[:, :k] if select_min else order[:, ::-1][:, :k]
        np.testing.assert_allclose(out_val, expect, rtol=1e-6)
        # indices recover the values
        np.testing.assert_allclose(
            np.take_along_axis(v, out_idx, axis=1), out_val, rtol=1e-6)

    def test_tiled_path_matches_direct(self, rng):
        v = rng.normal(size=(2, 70000)).astype(np.float32)
        direct_v, direct_i = matrix.select_k(
            None, v, 50, algo=SelectAlgo.WARPSORT_IMMEDIATE)
        tiled_v, tiled_i = matrix.select_k(
            None, v, 50, algo=SelectAlgo.RADIX_11BITS)
        np.testing.assert_allclose(np.asarray(tiled_v), np.asarray(direct_v),
                                   rtol=1e-6)

    def test_in_idx_passthrough(self, rng):
        v = rng.normal(size=(2, 100)).astype(np.float32)
        payload = rng.integers(0, 10**6, size=(2, 100)).astype(np.int32)
        out_val, out_idx = matrix.select_k(None, v, 5, in_idx=payload)
        pos = np.argsort(np.asarray(v), axis=1)[:, :5]
        np.testing.assert_array_equal(np.asarray(out_idx),
                                      np.take_along_axis(payload, pos, 1))

    def test_int_dtype_preserved(self):
        v = jnp.asarray([[16777216, 16777217, 3]], dtype=jnp.int32)
        out_val, out_idx = matrix.select_k(None, v, 1, select_min=False)
        assert out_val.dtype == jnp.int32
        assert int(out_val[0, 0]) == 16777217
        assert int(out_idx[0, 0]) == 1

    def test_k_too_large_raises(self, rng):
        with pytest.raises(ValueError):
            matrix.select_k(None, jnp.ones((2, 10)), 11)

    @pytest.mark.parametrize("n_cols", [4096, 10_000, 40_000])
    @pytest.mark.parametrize("k", [1, 100, 1000, 10_000])
    @pytest.mark.parametrize("algo", [SelectAlgo.AUTO,
                                      SelectAlgo.RADIX_11BITS])
    def test_property_k_len_grid(self, rng, n_cols, k, algo):
        """Any (k, len) combination must be exact, every algo — the round-1
        k>8192 tiled bug regression net (VERDICT #4; ref handles any k ≤ len,
        select_radix.cuh:877)."""
        if k > n_cols:
            pytest.skip("k > len")
        v = rng.normal(size=(2, n_cols)).astype(np.float32)
        out_val, out_idx = matrix.select_k(None, v, k, algo=algo)
        expect = np.sort(v, axis=1)[:, :k]
        np.testing.assert_allclose(np.asarray(out_val), expect, rtol=1e-6)
        np.testing.assert_allclose(
            np.take_along_axis(v, np.asarray(out_idx), axis=1), out_val,
            rtol=1e-6)

    def test_tiled_duplicates_k_exceeds_tile(self, rng):
        """All duplicates concentrated in one tile with k > one tile's
        worth: the candidate pool must still carry k entries per tile."""
        v = np.full((1, 40_000), 100.0, np.float32)
        v[0, :9000] = 0.0        # the 9000 smallest all live in tile 0
        out_val, _ = matrix.select_k(None, v, 9000,
                                     algo=SelectAlgo.RADIX_11BITS)
        np.testing.assert_array_equal(np.asarray(out_val),
                                      np.zeros((1, 9000), np.float32))


class TestArgMinMax:
    def test_argmin_argmax(self, rng):
        m = rng.normal(size=(20, 30)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.argmin(None, m)),
                                      m.argmin(axis=1))
        np.testing.assert_array_equal(np.asarray(matrix.argmax(None, m)),
                                      m.argmax(axis=1))


class TestGatherScatter:
    def test_gather(self, rng):
        m = rng.normal(size=(10, 4)).astype(np.float32)
        idx = np.array([3, 1, 7], dtype=np.int32)
        np.testing.assert_array_equal(np.asarray(matrix.gather(None, m, idx)),
                                      m[idx])

    def test_gather_if(self, rng):
        m = rng.normal(size=(10, 4)).astype(np.float32)
        idx = np.array([0, 1, 2, 3], dtype=np.int32)
        stencil = np.array([1.0, -1.0, 1.0, -1.0], dtype=np.float32)
        out = np.asarray(matrix.gather_if(None, m, idx, stencil,
                                          lambda s: s > 0))
        np.testing.assert_array_equal(out[0], m[0])
        np.testing.assert_array_equal(out[1], np.zeros(4))

    def test_scatter_permutation(self, rng):
        m = rng.normal(size=(5, 3)).astype(np.float32)
        perm = np.array([4, 2, 0, 1, 3], dtype=np.int32)
        out = np.asarray(matrix.scatter(None, m, perm))
        np.testing.assert_array_equal(out[perm], m)

    def test_take_rows_variable_blocks(self, rng):
        m = rng.normal(size=(20, 4)).astype(np.float32)
        starts = np.array([2, 10, 17], dtype=np.int32)
        counts = np.array([3, 0, 5], dtype=np.int32)
        blocks, valid = matrix.take_rows(None, m, starts, counts,
                                         max_count=5)
        assert blocks.shape == (3, 5, 4) and valid.shape == (3, 5)
        np.testing.assert_array_equal(np.asarray(blocks[0, :3]), m[2:5])
        np.testing.assert_array_equal(np.asarray(blocks[0, 3:]),
                                      np.zeros((2, 4)))
        assert not np.asarray(valid[1]).any()       # zero-count block
        # block 3 runs past the matrix end: clipped + masked invalid
        np.testing.assert_array_equal(np.asarray(valid[2]),
                                      [True, True, True, False, False])
        np.testing.assert_array_equal(np.asarray(blocks[2, :3]),
                                      m[17:20])

    def test_take_rows_batched_and_1d(self, rng):
        m = rng.normal(size=(16, 3)).astype(np.float32)
        starts = np.array([[0, 4], [8, 12]], dtype=np.int32)
        counts = np.array([[2, 2], [2, 2]], dtype=np.int32)
        blocks, valid = matrix.take_rows(None, m, starts, counts,
                                         max_count=2)
        assert blocks.shape == (2, 2, 2, 3)
        np.testing.assert_array_equal(np.asarray(blocks[1, 0]), m[8:10])
        v = np.arange(9, dtype=np.int32)
        blocks1, valid1 = matrix.take_rows(
            None, v, np.array([4]), np.array([3]), max_count=4,
            fill_value=-1)
        np.testing.assert_array_equal(np.asarray(blocks1[0]),
                                      [4, 5, 6, -1])

    def test_take_rows_preserves_integer_dtypes(self, rng):
        # PQ code matrices ride take_rows as uint8/int8 — neither the
        # gather nor the fill may promote (codes stay 1 byte/entry)
        starts = np.array([2, 17], dtype=np.int32)
        counts = np.array([3, 5], dtype=np.int32)
        for dt, fill in ((np.uint8, 0), (np.int8, -1), (np.int32, -1)):
            m = rng.integers(0, 100, size=(20, 3)).astype(dt)
            blocks, valid = matrix.take_rows(None, m, starts, counts,
                                             max_count=5,
                                             fill_value=fill)
            assert blocks.dtype == dt, (dt, blocks.dtype)
            np.testing.assert_array_equal(np.asarray(blocks[0, :3]),
                                          m[2:5])
            np.testing.assert_array_equal(
                np.asarray(blocks[0, 3:]),
                np.full((2, 3), fill, dtype=dt))
            # clipped tail block: data rows exact, pad filled
            np.testing.assert_array_equal(np.asarray(blocks[1, :3]),
                                          m[17:20])
            np.testing.assert_array_equal(np.asarray(valid[1]),
                                          [True, True, True,
                                           False, False])
        # 1-D code vectors too
        v = np.arange(9, dtype=np.uint8)
        b1, _ = matrix.take_rows(None, v, np.array([6]), np.array([3]),
                                 max_count=4, fill_value=0)
        assert b1.dtype == np.uint8
        np.testing.assert_array_equal(np.asarray(b1[0]), [6, 7, 8, 0])


class TestMiscOps:
    def test_diagonal(self, rng):
        m = rng.normal(size=(5, 5)).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(matrix.get_diagonal(None, m)), np.diag(m))
        out = np.asarray(matrix.set_diagonal(None, m, jnp.zeros(5)))
        assert np.abs(np.diag(out)).max() == 0

    def test_linewise_and_reverse(self, rng):
        m = rng.normal(size=(4, 6)).astype(np.float32)
        v = rng.normal(size=6).astype(np.float32)
        out = np.asarray(matrix.linewise_op(None, m, lambda a, b: a * b,
                                            True, v))
        np.testing.assert_allclose(out, m * v[None, :], rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(matrix.col_reverse(None, m)),
                                      m[:, ::-1])
        np.testing.assert_array_equal(np.asarray(matrix.row_reverse(None, m)),
                                      m[::-1])

    def test_sign_flip(self, rng):
        m = rng.normal(size=(6, 3)).astype(np.float32)
        out = np.asarray(matrix.sign_flip(None, m))
        for j in range(3):
            assert out[np.abs(out[:, j]).argmax(), j] > 0

    def test_shift(self):
        m = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
        out = np.asarray(matrix.col_shift(None, m, k=1, fill_value=-1))
        np.testing.assert_array_equal(out[:, 0], [-1, -1, -1])
        np.testing.assert_array_equal(out[:, 1:], np.asarray(m)[:, :3])
        out = np.asarray(matrix.row_shift(
            None, m, k=1, direction=matrix.SHIFT_TOWARDS_BEGINNING,
            fill_value=0))
        np.testing.assert_array_equal(out[:2], np.asarray(m)[1:])
        np.testing.assert_array_equal(out[2], np.zeros(4))

    def test_sort_cols_per_row(self, rng):
        m = rng.normal(size=(5, 9)).astype(np.float32)
        out, idx = matrix.sort_cols_per_row(None, m, return_indices=True)
        np.testing.assert_allclose(np.asarray(out), np.sort(m, axis=1),
                                   rtol=1e-6)
        np.testing.assert_array_equal(
            np.take_along_axis(m, np.asarray(idx), axis=1), np.asarray(out))

    def test_sample_rows(self, rng):
        m = rng.normal(size=(100, 3)).astype(np.float32)
        out = np.asarray(matrix.sample_rows(None, RngState(3), m, 10))
        assert out.shape == (10, 3)
        # every sampled row exists in the source
        for row in out:
            assert (np.abs(m - row).sum(axis=1) < 1e-6).any()

    def test_triangular_threshold_reciprocal(self, rng):
        m = rng.normal(size=(4, 4)).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(matrix.upper_triangular(None, m)), np.triu(m))
        z = np.asarray(matrix.zero_small_values(None, m, thres=10.0))
        assert np.abs(z).max() == 0
        r = np.asarray(matrix.reciprocal(None, m + 10.0))
        np.testing.assert_allclose(r, 1.0 / (m + 10.0), rtol=1e-5)


class TestInsertSelect:
    """matrix/topk_insert.insert_select — the bound-gated insertion
    contender for k <= 256 (the reference's warpsort-filtered slot,
    select_warpsort.cuh:129), sharing the drain with the fused kNN
    kernel."""

    @pytest.mark.parametrize("select_min", [True, False])
    def test_exact_vs_stable_argsort(self, rng, select_min):
        from raft_tpu.matrix.topk_insert import insert_select

        x = rng.normal(size=(70, 900)).astype(np.float32)
        v, i = insert_select(jnp.asarray(x), 17, select_min=select_min,
                             tn=256)
        order = np.argsort(x if select_min else -x, axis=1,
                           kind="stable")[:, :17]
        np.testing.assert_array_equal(np.asarray(i), order)
        np.testing.assert_array_equal(np.asarray(v),
                                      np.take_along_axis(x, order, 1))

    def test_ties_smallest_index_and_strips(self, rng):
        from raft_tpu.matrix.topk_insert import insert_select

        x = np.tile(rng.normal(size=(4, 100)).astype(np.float32), (1, 6))
        order = np.argsort(x, axis=1, kind="stable")[:, :9]
        for sw in (0, 128):
            v, i = insert_select(jnp.asarray(x), 9, tn=128, sw=sw)
            np.testing.assert_array_equal(np.asarray(i), order)

    def test_nan_sorts_last_and_terminates(self, rng):
        """NaNs map to +inf inside the drain — without that a NaN pool
        minimum consumes no lane and the device while-loop would hang
        whenever a finite candidate stays below the bound."""
        from raft_tpu.matrix.topk_insert import insert_select

        x = rng.normal(size=(20, 600)).astype(np.float32)
        x[:, ::7] = np.nan
        v, i = insert_select(jnp.asarray(x), 5, tn=256)
        assert not np.isnan(np.asarray(v)).any()
        order = np.argsort(np.where(np.isnan(x), np.inf, x), 1,
                           kind="stable")[:, :5]
        np.testing.assert_array_equal(np.asarray(i), order)

    def test_two_vreg_k200_and_dtype_roundtrip(self, rng):
        from raft_tpu.matrix.topk_insert import insert_select

        x = rng.normal(size=(16, 700)).astype(np.float32)
        v, i = insert_select(jnp.asarray(x), 200, tn=256, sw=128)
        order = np.argsort(x, 1, kind="stable")[:, :200]
        np.testing.assert_array_equal(np.asarray(i), order)
        vb, ib = insert_select(jnp.asarray(x, jnp.bfloat16), 7, tn=256)
        assert vb.dtype == jnp.bfloat16

    def test_unsupported_raises(self):
        from raft_tpu.matrix.topk_insert import insert_select, supports

        assert not supports(jnp.int32, 5) and not supports(jnp.float32,
                                                           257)
        with pytest.raises(ValueError):
            insert_select(jnp.ones((2, 500), jnp.int32), 5)
        # sw that never divided the requested tn is a caller error...
        with pytest.raises(ValueError):
            insert_select(jnp.ones((2, 5000), jnp.float32), 5, tn=1024,
                          sw=384)
        # ...but clamp-induced indivisibility degrades to whole-tile
        v, i = insert_select(jnp.ones((2, 300), jnp.float32), 3,
                             tn=1024, sw=256)
        assert i.shape == (2, 3)

    def test_inf_saturated_rows_get_direct_semantics(self, rng):
        """Rows whose k-th best is +/-inf would leave drain slots
        unfilled; the lax.cond fallback re-answers the whole call via
        the direct path, so indices stay REAL positions (parity with
        the old WARPSORT_FILTERED routing)."""
        from raft_tpu.matrix.topk_insert import insert_select

        x = np.full((3, 500), np.inf, np.float32)
        x[:, 7] = 1.0                      # one finite candidate
        v, i = insert_select(jnp.asarray(x), 3, tn=256)
        assert np.asarray(i)[0, 0] == 7
        # remaining slots: real inf positions, not filler zeros
        assert set(np.asarray(i)[0, 1:]) <= {0, 1}
        dv, di = matrix.select_k(None, x, 3,
                                 algo=SelectAlgo.WARPSORT_IMMEDIATE)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(di))
        # select_max mirror: -inf saturation
        xm = -x
        v, i = insert_select(jnp.asarray(xm), 3, select_min=False,
                             tn=256)
        dv, di = matrix.select_k(None, xm, 3, select_min=False,
                                 algo=SelectAlgo.WARPSORT_IMMEDIATE)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(di))

    def test_select_k_warpsort_filtered_routes_here(self, rng):
        x = rng.normal(size=(8, 600)).astype(np.float32)
        v, i = matrix.select_k(None, x, 17,
                               algo=SelectAlgo.WARPSORT_FILTERED)
        order = np.argsort(x, 1, kind="stable")[:, :17]
        np.testing.assert_array_equal(np.asarray(i), order)


def test_select_k_int_min_extremes(res):
    """Regression: integer select_min must not wrap at INT32_MIN
    (order-flip uses bitwise NOT, not negation)."""
    import numpy as np
    from raft_tpu.matrix import select_k

    lo = np.iinfo(np.int32).min
    vals = np.array([[lo, 5, 7]], np.int32)
    v, i = select_k(res, vals, k=1, select_min=True)
    assert int(v[0, 0]) == lo and int(i[0, 0]) == 0
    u = np.array([[0, 3, 2**32 - 1]], np.uint32)
    v, i = select_k(res, u, k=2, select_min=True)
    assert list(np.asarray(v[0])) == [0, 3]


class TestSelectKLarge:
    """MATRIX_SELECT_LARGE_TEST analogue (cpp/tests/CMakeLists.txt:216-219):
    randomized wide rows across algos vs a numpy partition oracle."""

    def test_wide_rows_all_algos(self):
        import numpy as np
        from raft_tpu.matrix import SelectAlgo, select_k

        rng = np.random.default_rng(7)
        vals = rng.normal(size=(4, 70_000)).astype(np.float32)
        expect = np.sort(vals, axis=1)[:, :37]
        for algo in (SelectAlgo.AUTO, SelectAlgo.RADIX_11BITS,
                     SelectAlgo.WARPSORT_IMMEDIATE):
            v, i = select_k(None, vals, k=37, select_min=True, algo=algo)
            np.testing.assert_allclose(np.asarray(v), expect, rtol=1e-6)
            np.testing.assert_allclose(
                np.take_along_axis(vals, np.asarray(i), axis=1), expect,
                rtol=1e-6)

    def test_k_equals_len_and_duplicates(self):
        import numpy as np
        from raft_tpu.matrix import select_k

        vals = np.array([[2., 2., 1., 1.]], np.float32)
        v, i = select_k(None, vals, k=4, select_min=True)
        np.testing.assert_array_equal(np.asarray(v), [[1, 1, 2, 2]])
        assert sorted(np.asarray(i)[0].tolist()) == [0, 1, 2, 3]
