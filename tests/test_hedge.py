"""Hedged-dispatch tests (ISSUE 16 tentpole, tail half): the adaptive
per-bucket delay estimate, first-success-wins with typed loser
cancellation, bit-identical winners under a stalled replica, the
per-tenant hedge budget, and the env kill switch.
"""

import time

import numpy as np
import pytest

from raft_tpu import obs, serve
from raft_tpu.comms.faults import FaultInjector
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.runtime import limits
from raft_tpu.serve import (BatchPolicy, Executor, HedgePolicy,
                            KnnService, ReplicaGroup)
from raft_tpu.serve.queue import bucket_rows

DIM = 16
OP = "knn_k4_l2"


@pytest.fixture
def live_obs():
    was_enabled = obs.enabled()
    old_reg = obs_metrics.set_registry(obs.MetricsRegistry())
    old_sink = obs.set_sink(None)
    obs.set_enabled(True)
    try:
        yield obs_metrics.get_registry()
    finally:
        obs.set_enabled(was_enabled)
        obs_metrics.set_registry(old_reg)
        obs.set_sink(old_sink)


@pytest.fixture(scope="module")
def db():
    rng = np.random.default_rng(21)
    return rng.standard_normal((128, DIM)).astype(np.float32)


def _make_ex(db, inj):
    ex = Executor([KnnService(db, k=4)],
                  policy=BatchPolicy(max_batch=32, max_wait_ms=0.5),
                  faults=inj)
    ex.warm([8])
    return ex


def _group(db, policy, n=2):
    injs = [FaultInjector(seed=i) for i in range(n)]
    group = ReplicaGroup([_make_ex(db, inj) for inj in injs],
                         hedge=policy)
    return group, injs


def _counter_value(reg, name, **labels):
    fam = reg.snapshot().get(name)
    if not fam:
        return 0.0
    return sum(s["value"] for s in fam["series"]
               if all(s["labels"].get(k) == v for k, v in labels.items()))


class TestHedgePolicy:
    def test_validation(self):
        HedgePolicy()                  # defaults valid
        with pytest.raises(ValueError):
            HedgePolicy(quantile=1.0)
        with pytest.raises(ValueError):
            HedgePolicy(budget_fraction=0.0)
        with pytest.raises(ValueError):
            HedgePolicy(min_samples=0)
        with pytest.raises(ValueError):
            HedgePolicy(delay_floor_s=-0.1)

    def test_no_delay_estimate_below_min_samples(self, db):
        group, _ = _group(db, HedgePolicy(min_samples=100))
        q = np.random.default_rng(0).standard_normal(
            (4, DIM)).astype(np.float32)
        with group:
            for _ in range(5):
                group.submit(OP, q).result(timeout=30.0)
        assert group._hedger.hedge_delay(bucket_rows(4)) is None
        assert group.stats.hedges_issued == 0, \
            "an unwarmed fleet must not hedge blind"

    def test_delay_is_quantile_floored(self, db):
        group, _ = _group(db, HedgePolicy(min_samples=4, quantile=0.5,
                                          delay_floor_s=10.0))
        h = group._hedger
        for v in (0.001, 0.002, 0.003, 0.004):
            h._record_sample(8, v)
        # p50 of tiny samples floors at delay_floor_s
        assert h.hedge_delay(8) == 10.0


class TestHedgedDispatch:
    STALL = 0.5

    def test_stalled_replica_hedges_bit_identical(self, db, live_obs):
        """The acceptance core: with one replica stalled, hedged
        submits complete well under the stall via the healthy replica,
        the winner's payload is bit-identical to the eager answer, and
        the loser is cancelled (typed) instead of burning a launch."""
        policy = HedgePolicy(min_samples=4, quantile=0.5,
                             delay_floor_s=0.002, budget_fraction=1.0,
                             budget_window_s=60.0)
        group, injs = _group(db, policy)
        rng = np.random.default_rng(1)
        q = rng.standard_normal((4, DIM)).astype(np.float32)
        svc = group.replicas[0].executor.services[OP]
        want = svc.eager(q)
        with group:
            for _ in range(8):          # prime the delay estimate
                group.submit(OP, q).result(timeout=30.0)
            assert group._hedger.hedge_delay(bucket_rows(4)) is not None
            injs[0].stall(self.STALL)
            try:
                lat = []
                for _ in range(4):
                    t0 = time.monotonic()
                    out = group.submit(OP, q).result(timeout=30.0)
                    lat.append(time.monotonic() - t0)
                    for g, w in zip(out, want):
                        np.testing.assert_array_equal(
                            np.asarray(g), np.asarray(w))
            finally:
                injs[0].stall(0.0)
            # cancelled losers surface at the stalled replica's drain
            deadline = time.monotonic() + 10.0
            while (group.replicas[0].executor.stats.cancelled == 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        s = group.stats
        assert s.hedges_issued >= 1
        assert s.hedges_won >= 1
        # at least one hedged request beat the stall decisively
        assert min(lat) < self.STALL * 0.8, lat
        assert group.replicas[0].executor.stats.cancelled >= 1, \
            "hedge loser must be cancelled, not executed"
        issued = _counter_value(live_obs, "serve_hedges_total",
                                outcome="issued")
        won = _counter_value(live_obs, "serve_hedges_total",
                             outcome="won")
        assert issued == s.hedges_issued and won == s.hedges_won

    def test_budget_suppresses_hedges(self, db, live_obs):
        """A tiny fractional budget suppresses second legs instead of
        amplifying: Dean & Barroso's <=5% cap as a hard gate."""
        policy = HedgePolicy(min_samples=4, quantile=0.5,
                             delay_floor_s=0.002,
                             budget_fraction=0.01,
                             budget_window_s=60.0)
        group, injs = _group(db, policy)
        rng = np.random.default_rng(2)
        q = rng.standard_normal((4, DIM)).astype(np.float32)
        with group:
            for _ in range(8):
                group.submit(OP, q).result(timeout=30.0)
            injs[0].stall(0.15)
            injs[1].stall(0.15)         # both slow: every watch fires
            try:
                futs = [group.submit(OP, q) for _ in range(3)]
                for f in futs:
                    f.result(timeout=30.0)
            finally:
                injs[0].stall(0.0)
                injs[1].stall(0.0)
        # int(11 * 0.01) == 0 allowed hedges in the window
        assert group.stats.hedges_issued == 0
        assert group.stats.hedges_suppressed >= 1
        assert _counter_value(live_obs, "serve_hedges_total",
                              outcome="suppressed") >= 1.0
        assert group.stats.hedge_rate() == 0.0

    def test_unhedged_group_unchanged(self, db):
        group = ReplicaGroup([_make_ex(db, None), _make_ex(db, None)])
        assert group._hedger is None
        q = np.random.default_rng(3).standard_normal(
            (4, DIM)).astype(np.float32)
        with group:
            fut = group.submit(OP, q)
            fut.result(timeout=30.0)
        assert group.stats.hedges_issued == 0

    def test_env_kill_switch(self, db, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_HEDGE", "off")
        group, _ = _group(db, HedgePolicy())
        assert group._hedger is None and group.hedge is None


class TestResultFutureSemantics:
    """The first-fulfillment-wins contract the hedge state machine
    leans on."""

    def test_second_result_is_noop(self):
        fut = serve.ResultFuture()
        fut.set_result("first")
        fut.set_result("second")
        fut.set_exception(RuntimeError("late"))
        assert fut.result(timeout=0) == "first"
        assert fut.exception(timeout=0) is None

    def test_done_callback_fires_once_outside_lock(self):
        fut = serve.ResultFuture()
        fired = []
        fut.add_done_callback(lambda f: fired.append(f.result(timeout=0)))
        fut.set_result(7)
        fut.set_result(8)
        assert fired == [7]
        # late registration fires immediately with the settled value
        fut.add_done_callback(lambda f: fired.append(f.result(timeout=0)))
        assert fired == [7, 7]

    def test_cancel_resolves_typed(self, db):
        ex = _make_ex(db, None)
        q = np.random.default_rng(4).standard_normal(
            (2, DIM)).astype(np.float32)
        req = ex.submit_request(OP, q)
        req.cancel("hedge_lost")
        with pytest.raises(limits.RejectedError) as ei:
            req.future.result(timeout=1.0)
        assert ei.value.reason == "cancelled"
        assert req.cancelled == "hedge_lost"
