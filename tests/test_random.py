"""Random generation tests (ref: cpp/tests/random/, pylibraft test_random.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import random as rrandom
from raft_tpu.random import Decomposer, RngState


class TestRngState:
    def test_determinism_and_advance(self):
        s1 = RngState(seed=7)
        s2 = RngState(seed=7)
        a = rrandom.uniform(None, s1, 100)
        b = rrandom.uniform(None, s2, 100)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # advanced state → different stream
        c = rrandom.uniform(None, s1, 100)
        assert not np.allclose(np.asarray(a), np.asarray(c))

    def test_explicit_advance_matches(self):
        s1 = RngState(seed=7)
        rrandom.uniform(None, s1, 10)
        s2 = RngState(seed=7)
        s2.advance()
        a = rrandom.uniform(None, s1, 10)
        b = rrandom.uniform(None, s2, 10)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestDistributions:
    def test_uniform_range(self, rng_state):
        x = np.asarray(rrandom.uniform(None, rng_state, 10000, -2.0, 3.0))
        assert x.min() >= -2.0 and x.max() < 3.0
        assert abs(x.mean() - 0.5) < 0.1

    def test_uniform_int(self, rng_state):
        x = np.asarray(rrandom.uniform_int(None, rng_state, 10000, 0, 10))
        assert x.min() == 0 and x.max() == 9

    def test_normal_moments(self, rng_state):
        x = np.asarray(rrandom.normal(None, rng_state, 50000, 3.0, 2.0))
        assert abs(x.mean() - 3.0) < 0.1
        assert abs(x.std() - 2.0) < 0.1

    def test_normal_table(self, rng_state):
        mu = jnp.asarray([0.0, 10.0, -5.0])
        sigma = jnp.asarray([1.0, 0.5, 2.0])
        x = np.asarray(rrandom.normal_table(None, rng_state, 20000, mu, sigma))
        np.testing.assert_allclose(x.mean(axis=0), [0.0, 10.0, -5.0],
                                   atol=0.15)
        np.testing.assert_allclose(x.std(axis=0), [1.0, 0.5, 2.0], atol=0.15)

    def test_bernoulli(self, rng_state):
        x = np.asarray(rrandom.bernoulli(None, rng_state, 20000, 0.3))
        assert abs(x.mean() - 0.3) < 0.02

    @pytest.mark.parametrize("dist,params,mean_fn", [
        ("exponential", {"lam": 2.0}, lambda: 0.5),
        ("rayleigh", {"sigma": 1.0}, lambda: np.sqrt(np.pi / 2)),
        ("lognormal", {"mu": 0.0, "sigma": 0.25},
         lambda: float(np.exp(0.25 ** 2 / 2))),
        ("laplace", {"mu": 1.0, "scale": 1.0}, lambda: 1.0),
        ("logistic", {"mu": -1.0, "scale": 0.5}, lambda: -1.0),
        ("gumbel", {"mu": 0.0, "beta": 1.0}, lambda: float(np.euler_gamma)),
    ])
    def test_distribution_means(self, rng_state, dist, params, mean_fn):
        fn = getattr(rrandom, dist)
        x = np.asarray(fn(None, rng_state, 100000, **params))
        assert abs(x.mean() - mean_fn()) < 0.05

    def test_scaled_bernoulli(self, rng_state):
        x = np.asarray(rrandom.scaled_bernoulli(None, rng_state, 10000,
                                                0.25, 2.0))
        assert set(np.unique(x)) == {-2.0, 2.0}
        assert abs((x == -2.0).mean() - 0.25) < 0.02


class TestSampling:
    def test_weighted_sample(self, rng_state):
        w = jnp.asarray([0.0, 1.0, 3.0, 0.0])
        idx = np.asarray(rrandom.sample(None, rng_state, 20000, w))
        assert set(np.unique(idx)) <= {1, 2}
        assert abs((idx == 2).mean() - 0.75) < 0.02

    def test_sample_without_replacement_unique(self, rng_state):
        idx = np.asarray(rrandom.sample_without_replacement(
            None, rng_state, 50, pool_size=64))
        assert len(np.unique(idx)) == 50

    def test_weighted_without_replacement_respects_zero(self, rng_state):
        w = np.ones(100)
        w[10] = 0.0
        idx = np.asarray(rrandom.sample_without_replacement(
            None, rng_state, 99, weights=jnp.asarray(w)))
        assert 10 not in idx
        assert len(np.unique(idx)) == 99

    def test_excess_subsample(self, rng_state):
        idx = np.asarray(rrandom.excess_subsample(None, rng_state, 10, 1000))
        assert len(np.unique(idx)) == 10
        assert idx.max() < 1000

    def test_permute(self, rng_state):
        p = np.asarray(rrandom.permute(None, rng_state, 100))
        np.testing.assert_array_equal(np.sort(p), np.arange(100))


class TestGenerators:
    def test_make_blobs_labels_and_spread(self, rng_state):
        X, labels, centers = rrandom.make_blobs(
            None, rng_state, 1000, 8, n_clusters=4, cluster_std=0.1)
        assert X.shape == (1000, 8)
        assert centers.shape == (4, 8)
        labels = np.asarray(labels)
        assert set(np.unique(labels)) == {0, 1, 2, 3}
        # points cluster tightly around their centers
        d = np.linalg.norm(np.asarray(X) - np.asarray(centers)[labels],
                           axis=1)
        assert d.max() < 1.5

    def test_make_blobs_given_centers(self, rng_state):
        centers = jnp.asarray([[0.0, 0.0], [100.0, 100.0]])
        X, labels, _ = rrandom.make_blobs(None, rng_state, 200, 2,
                                          centers=centers, cluster_std=0.5)
        X, labels = np.asarray(X), np.asarray(labels)
        assert np.all(X[labels == 1].mean(axis=0) > 90)

    def test_make_regression_recoverable(self, rng_state):
        X, y, w = rrandom.make_regression(None, rng_state, 500, 10,
                                          n_informative=5, noise=0.0,
                                          shuffle=False)
        X, y, w = np.asarray(X), np.asarray(y), np.asarray(w)
        np.testing.assert_allclose(X @ w, y, rtol=1e-3, atol=1e-2)
        assert np.abs(w[5:]).max() == 0.0

    def test_mvg_cholesky_vs_eig(self, rng_state):
        cov = np.asarray([[2.0, 0.8], [0.8, 1.0]])
        mean = np.asarray([1.0, -1.0])
        for method in (Decomposer.CHOLESKY, Decomposer.JACOBI, Decomposer.QR):
            x = np.asarray(rrandom.multi_variable_gaussian(
                None, rng_state, mean, cov, 50000, method=method))
            np.testing.assert_allclose(x.mean(axis=0), mean, atol=0.05)
            np.testing.assert_allclose(np.cov(x.T), cov, atol=0.1)

    def test_rmat_shapes_and_bounds(self, rng_state):
        src, dst = rrandom.rmat_rectangular_gen(None, rng_state, 10, 8,
                                                5000)
        src, dst = np.asarray(src), np.asarray(dst)
        assert src.shape == dst.shape == (5000,)
        assert src.min() >= 0 and src.max() < 2 ** 10
        assert dst.min() >= 0 and dst.max() < 2 ** 8

    def test_rmat_skew(self, rng_state):
        # a=0.9 concentrates edges near vertex 0
        src, dst = rrandom.rmat_rectangular_gen(None, rng_state, 12, 12,
                                                20000, a=0.9, b=0.04, c=0.04)
        src = np.asarray(src)
        assert (src < 2 ** 11).mean() > 0.8  # heavy top-half skew


def test_make_regression_wide_low_rank(res, rng_state):
    """Regression: effective_rank path with n_rows < n_cols."""
    import numpy as np
    from raft_tpu.random import make_regression

    X, y, w = make_regression(res, rng_state, n_rows=10, n_cols=20,
                              effective_rank=5)
    assert X.shape == (10, 20) and y.shape == (10, 1) and w.shape == (20, 1)
    assert np.isfinite(np.asarray(X)).all()


class TestDistributionKS:
    """Kolmogorov–Smirnov goodness-of-fit against scipy's reference CDFs —
    distribution SHAPE validation beyond the existing moment checks (the
    reference's rng tests use mean/std tolerance matchers; KS is strictly
    stronger and free here)."""

    N = 20_000

    def _ks(self, samples, cdf):
        from scipy.stats import kstest

        return kstest(np.asarray(samples, np.float64), cdf).pvalue

    def test_ks_uniform_normal_exponential(self):
        from scipy import stats as ss

        from raft_tpu.random import RngState, exponential, normal, uniform

        s = RngState(1234)
        assert self._ks(uniform(None, s, (self.N,), 2.0, 5.0),
                        ss.uniform(loc=2.0, scale=3.0).cdf) > 1e-3
        assert self._ks(normal(None, s, (self.N,), 1.0, 2.0),
                        ss.norm(loc=1.0, scale=2.0).cdf) > 1e-3
        assert self._ks(exponential(None, s, (self.N,), lam=0.5),
                        ss.expon(scale=2.0).cdf) > 1e-3

    def test_ks_gumbel_laplace_lognormal(self):
        from scipy import stats as ss

        from raft_tpu.random import RngState, gumbel, laplace, lognormal

        s = RngState(77)
        assert self._ks(gumbel(None, s, (self.N,), 0.5, 1.5),
                        ss.gumbel_r(loc=0.5, scale=1.5).cdf) > 1e-3
        assert self._ks(laplace(None, s, (self.N,), -1.0, 0.7),
                        ss.laplace(loc=-1.0, scale=0.7).cdf) > 1e-3
        assert self._ks(lognormal(None, s, (self.N,), 0.2, 0.6),
                        ss.lognorm(s=0.6, scale=np.exp(0.2)).cdf) > 1e-3


class TestRbgGenerator:
    """GeneratorType.RBG drives jax's rbg implementation (hardware RNG
    instructions on TPU); counter-based key semantics must hold."""

    def test_deterministic_and_distinct_from_threefry(self):
        from raft_tpu.random import GeneratorType, RngState, uniform

        a = np.asarray(uniform(None, RngState(7, type=GeneratorType.RBG),
                               (5000,)))
        b = np.asarray(uniform(None, RngState(7, type=GeneratorType.RBG),
                               (5000,)))
        c = np.asarray(uniform(None, RngState(7), (5000,)))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)
        assert (0 <= a).all() and (a < 1).all()
        assert abs(a.mean() - 0.5) < 0.03

    def test_subsequences_independent(self):
        from raft_tpu.random import GeneratorType, RngState, normal

        st = RngState(3, type=GeneratorType.RBG)
        x = np.asarray(normal(None, st, (4000,)))
        y = np.asarray(normal(None, st, (4000,)))   # advanced subsequence
        assert not np.array_equal(x, y)
        assert abs(np.corrcoef(x, y)[0, 1]) < 0.05
