"""Workload robustness layer tests (ISSUE 5): deadlines, budget-aware
admission, degraded execution, and the circuit breaker.

Acceptance criteria exercised here:

* the deadline contract under chaos — every rank of a stalled clique
  raises the typed ``DeadlineExceededError`` within the budget plus one
  poll interval, never a hang or a bare timeout;
* the admission contract — over-budget ``pairwise_distance`` / ``knn``
  degrade to tiled paths that are **bit-for-bit** equal to the
  monolithic ones; an unfittable launch raises ``RejectedError``
  carrying the estimate; with no limits configured every instrumented
  op is bit-identical to the unlimited library;
* satellite 4 — ``CancelToken.cancel()`` racing ``check()`` / waker
  registration from 8 threads stays corruption-free, and a deadline
  expiring mid-``eigsh_mnmg`` leaves a usable checkpoint behind
  (resume completes and matches scipy).
"""

import os
import threading
import time

import numpy as np
import pytest

from raft_tpu.runtime import limits

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_breakers():
    """Deadline/rejection tests record breaker failures; never let one
    test's failure streak open the breaker on a later test's op key."""
    limits.reset_breakers()
    yield
    limits.reset_breakers()


def _submesh(n):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]), axis_names=("data",))


# -- deadline scopes --------------------------------------------------------


class TestDeadline:
    def test_no_scope_is_inert(self):
        assert limits.current_deadline() is None
        assert limits.remaining() is None
        assert limits.remaining(default=7.0) == 7.0
        limits.check_deadline("test.noop")  # must not raise

    def test_scope_counts_down(self):
        with limits.deadline_scope(5.0):
            d = limits.current_deadline()
            assert d is not None
            r = d.remaining()
            assert 0.0 < r <= 5.0
            assert limits.remaining() == pytest.approx(r, abs=0.5)
        assert limits.current_deadline() is None

    def test_nesting_innermost_expiring_wins(self):
        with limits.deadline_scope(60.0):
            with limits.deadline_scope(1.0):
                assert limits.remaining() <= 1.0
            assert limits.remaining() > 30.0

    def test_expiry_raises_typed_with_attribution(self):
        with limits.deadline_scope(0.0):
            with pytest.raises(limits.DeadlineExceededError) as ei:
                limits.check_deadline("test.op")
        assert ei.value.op == "test.op"
        assert ei.value.budget_s == 0.0
        assert isinstance(ei.value, RuntimeError)

    def test_sleep_within_deadline_raises_before_oversleeping(self):
        t0 = time.monotonic()
        with limits.deadline_scope(0.2):
            with pytest.raises(limits.DeadlineExceededError):
                limits.sleep_within_deadline(10.0, op="test.sleep")
        assert time.monotonic() - t0 < 2.0

    def test_sleep_without_scope_is_plain_sleep(self):
        t0 = time.monotonic()
        limits.sleep_within_deadline(0.05)
        assert 0.04 <= time.monotonic() - t0 < 1.0

    def test_retry_policy_backoff_respects_deadline(self):
        from raft_tpu.comms.resilience import RetryPolicy

        policy = RetryPolicy(max_attempts=50, base_delay=0.5,
                             max_delay=0.5, deadline=30.0)
        calls = []

        def always_fails():
            calls.append(1)
            raise OSError("transient")

        t0 = time.monotonic()
        with limits.deadline_scope(0.3):
            with pytest.raises(limits.DeadlineExceededError):
                policy.call(always_fails, seed=0)
        assert time.monotonic() - t0 < 3.0
        assert calls  # at least one attempt ran before the budget cut in

    def test_tagstore_get_raises_deadline_not_timeout(self):
        from raft_tpu.comms.resilience import TagStore

        store = TagStore()
        t0 = time.monotonic()
        with limits.deadline_scope(0.2):
            with pytest.raises(limits.DeadlineExceededError):
                store.get(0, 1, 42, timeout=30.0)
        assert time.monotonic() - t0 < 2.0

    def test_tagstore_queued_message_beats_expired_deadline(self):
        from raft_tpu.comms.resilience import TagStore

        store = TagStore()
        store.deliver(0, 1, 42, "payload")
        with limits.deadline_scope(0.0):
            assert store.get(0, 1, 42, timeout=1.0) == "payload"


# -- budgets and estimates --------------------------------------------------


class TestBudget:
    def test_parse_bytes_suffixes(self):
        assert limits.parse_bytes("1024", name="t") == 1024
        assert limits.parse_bytes("4k", name="t") == 4 << 10
        assert limits.parse_bytes("2M", name="t") == 2 << 20
        assert limits.parse_bytes("3g", name="t") == 3 << 30
        assert limits.parse_bytes("1t", name="t") == 1 << 40

    @pytest.mark.parametrize("bad", ["banana", "", "-5", "0", "12q", "k"])
    def test_parse_bytes_fails_loud(self, bad):
        with pytest.raises(ValueError, match="t"):
            limits.parse_bytes(bad, name="t")

    def test_malformed_env_budget_fails_at_import(self):
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-c", "import raft_tpu.runtime.limits"],
            env={**os.environ, "RAFT_TPU_HBM_BUDGET": "banana"},
            capture_output=True, text=True, cwd=_REPO)
        assert proc.returncode != 0
        assert "RAFT_TPU_HBM_BUDGET" in proc.stderr

    def test_estimate_bytes_pairwise(self):
        est = limits.estimate_bytes("distance.pairwise_distance",
                                    m=10, n=20, k=4, itemsize=4)
        assert est == (10 * 4 + 20 * 4 + 10 * 20) * 4

    def test_estimate_bytes_unknown_op(self):
        with pytest.raises(ValueError, match="no footprint estimator"):
            limits.estimate_bytes("not.an.op", m=1)

    def test_active_budget_scoped_min_wins(self):
        prev = limits.set_default_budget(None)
        try:
            assert limits.active_budget() is None
            with limits.budget_scope(1 << 30):
                with limits.budget_scope(1 << 20):
                    assert limits.active_budget().limit_bytes == 1 << 20
                assert limits.active_budget().limit_bytes == 1 << 30
            assert limits.active_budget() is None
        finally:
            limits.set_default_budget(prev)

    def test_admit_without_budget_is_unconditional(self):
        assert limits.admit("test.op", 1 << 60) is True


# -- admission: degrade bit-for-bit or reject -------------------------------


class TestAdmission:
    def _xy(self, m=300, n=257, d=16):
        rng = np.random.default_rng(0)
        return (rng.normal(size=(m, d)).astype(np.float32),
                rng.normal(size=(n, d)).astype(np.float32))

    def test_pairwise_degraded_bit_identical(self):
        from raft_tpu.distance import pairwise_distance

        x, y = self._xy()
        base = np.asarray(pairwise_distance(None, x, y))
        est = limits.estimate_bytes("distance.pairwise_distance",
                                    m=300, n=257, k=16, itemsize=4)
        with limits.budget_scope(est // 2):
            tiled = np.asarray(pairwise_distance(None, x, y))
        assert np.array_equal(base, tiled)

    def test_pairwise_self_distance_degraded_bit_identical(self):
        from raft_tpu.distance import pairwise_distance

        x, _ = self._xy()
        base = np.asarray(pairwise_distance(None, x))
        est = limits.estimate_bytes("distance.pairwise_distance",
                                    m=300, n=300, k=16, itemsize=4)
        with limits.budget_scope(est // 2):
            tiled = np.asarray(pairwise_distance(None, x))
        assert np.array_equal(base, tiled)

    def test_pairwise_unfittable_rejected_with_estimate(self):
        from raft_tpu.distance import pairwise_distance

        x, y = self._xy()
        est = limits.estimate_bytes("distance.pairwise_distance",
                                    m=300, n=257, k=16, itemsize=4)
        with limits.budget_scope(1024):
            with pytest.raises(limits.RejectedError) as ei:
                pairwise_distance(None, x, y)
        assert ei.value.estimate == est
        assert ei.value.budget == 1024
        assert ei.value.reason == "over_budget"
        assert isinstance(ei.value, RuntimeError)

    def test_knn_degraded_bit_identical(self):
        from raft_tpu.neighbors import knn

        rng = np.random.default_rng(1)
        db = rng.normal(size=(2048, 8)).astype(np.float32)
        q = rng.normal(size=(64, 8)).astype(np.float32)
        bd, bi = knn(None, db, q, k=8)
        est = limits.estimate_bytes("neighbors.brute_force_knn",
                                    n_queries=64, n_db=2048, n_dims=8,
                                    k=8, itemsize=4)
        with limits.budget_scope(est // 3):
            dd, di = knn(None, db, q, k=8)
        assert np.array_equal(np.asarray(bd), np.asarray(dd))
        assert np.array_equal(np.asarray(bi), np.asarray(di))

    def test_knn_unfittable_rejected(self):
        from raft_tpu.neighbors import knn

        rng = np.random.default_rng(1)
        db = rng.normal(size=(2048, 8)).astype(np.float32)
        q = rng.normal(size=(64, 8)).astype(np.float32)
        with limits.budget_scope(256):
            with pytest.raises(limits.RejectedError) as ei:
                knn(None, db, q, k=8)
        assert ei.value.estimate is not None and ei.value.estimate > 256

    def test_gemm_over_budget_rejected(self):
        from raft_tpu.linalg.blas import gemm

        A = np.ones((64, 64), np.float32)
        with limits.budget_scope(1024):
            with pytest.raises(limits.RejectedError):
                gemm(None, A, A)

    def test_spmv_over_budget_rejected(self):
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.linalg import spmv

        rng = np.random.default_rng(2)
        dense = rng.normal(size=(100, 100)).astype(np.float32)
        dense[rng.uniform(size=dense.shape) > 0.1] = 0.0
        csr = CSRMatrix.from_scipy(sp.csr_matrix(dense))
        v = rng.normal(size=100).astype(np.float32)
        with limits.budget_scope(64):
            with pytest.raises(limits.RejectedError):
                spmv(csr, v)

    def test_within_budget_runs_monolithic(self):
        from raft_tpu.distance import pairwise_distance

        x, y = self._xy()
        base = np.asarray(pairwise_distance(None, x, y))
        with limits.budget_scope(1 << 40):
            out = np.asarray(pairwise_distance(None, x, y))
        assert np.array_equal(base, out)


# -- circuit breaker --------------------------------------------------------


class TestCircuitBreaker:
    def test_opens_after_threshold_and_recovers(self):
        br = limits.CircuitBreaker("test.op", threshold=3, cooldown_s=0.1)
        for _ in range(2):
            br.record_failure()
        assert br.allow() and not br.open
        br.record_failure()
        assert br.open and not br.allow()
        time.sleep(0.15)
        assert br.allow()          # half-open: one probe admitted
        br.record_success()
        assert not br.open and br.allow()

    def test_half_open_failure_reopens(self):
        br = limits.CircuitBreaker("test.op", threshold=2, cooldown_s=0.05)
        br.record_failure()
        br.record_failure()
        assert not br.allow()
        time.sleep(0.1)
        assert br.allow()
        br.record_failure()        # the probe fails → snap back open
        assert not br.allow()

    def test_check_deadline_fast_fails_when_open(self):
        br = limits.get_breaker("test.breaker_op")
        for _ in range(br.threshold):
            br.record_failure()
        with limits.deadline_scope(60.0):
            with pytest.raises(limits.RejectedError) as ei:
                limits.check_deadline("test.breaker_op")
        assert ei.value.reason == "breaker_open"

    def test_deadline_expiries_feed_the_breaker(self):
        # pytest.raises sits OUTSIDE the scope: catching the expiry
        # inside would make the scope exit clean, which counts as a
        # breaker success and resets the streak
        for _ in range(limits.BREAKER_THRESHOLD):
            with pytest.raises(limits.DeadlineExceededError):
                with limits.deadline_scope(0.0):
                    limits.check_deadline("test.flaky_op")
        assert limits.get_breaker("test.flaky_op").open

    def test_clean_scope_exit_closes_the_streak(self):
        with pytest.raises(limits.DeadlineExceededError):
            with limits.deadline_scope(0.0):
                limits.check_deadline("test.healing_op")
        with limits.deadline_scope(60.0):
            limits.check_deadline("test.healing_op")
        assert limits.get_breaker("test.healing_op")._failures == 0


# -- deadline chaos: the stalled clique ------------------------------------


class TestDeadlineChaos:
    def test_stalled_clique_every_rank_raises_typed_within_budget(self):
        """A 10 s stall against a 1 s deadline: all 4 ranks must raise
        ``DeadlineExceededError`` (senders via the sliced fault sleep,
        receivers via the TagStore deadline exit) well before the stall
        clears — the no-hang contract."""
        from raft_tpu.comms.comms import MeshComms, _Mailbox
        from raft_tpu.comms.faults import FaultInjector

        inj = FaultInjector(seed=0)
        inj.stall(10.0)
        comms = MeshComms(_submesh(4), "data", 0,
                          _mailbox=_Mailbox(faults=inj))
        n = comms.get_size()
        errs = [None] * n

        def body(r):
            try:
                with limits.deadline_scope(1.0):
                    comms.rank_view(r).host_allreduce(
                        np.full(3, float(r), np.float32), tag=910)
            except limits.DeadlineExceededError as exc:
                errs[r] = exc

        t0 = time.monotonic()
        threads = [threading.Thread(target=body, args=(r,))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=6.0)
        elapsed = time.monotonic() - t0
        assert all(isinstance(e, limits.DeadlineExceededError)
                   for e in errs), errs
        assert elapsed < 5.0, elapsed
        assert inj.counts["stall"] >= 1


# -- satellite 4: cancellation race ----------------------------------------


class TestCancelTokenRace:
    def test_cancel_races_check_and_wakers_from_8_threads(self):
        """8 threads hammer ``check()`` + waker add/remove while the main
        thread fires ``cancel()`` repeatedly: no deadlock, no waker-list
        corruption, every raise is the typed ``InterruptedException``."""
        from raft_tpu.core.interruptible import (CancelToken,
                                                 InterruptedException)

        token = CancelToken()
        stop = threading.Event()
        interrupts = [0] * 8
        foreign = []
        woken = threading.Event()

        def body(i):
            def waker():
                woken.set()

            while not stop.is_set():
                token.add_waker(waker)
                try:
                    token.check()
                except InterruptedException:
                    interrupts[i] += 1
                except Exception as exc:  # noqa: BLE001 — the assertion
                    foreign.append(exc)
                    return
                finally:
                    token.remove_waker(waker)

        threads = [threading.Thread(target=body, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            token.cancel()
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        assert not any(t.is_alive() for t in threads), "worker deadlocked"
        assert not foreign, foreign
        assert sum(interrupts) > 0, "no thread ever observed the cancel"
        assert woken.is_set(), "no waker ever fired"
        assert token._wakers == [], "waker list leaked entries"


# -- satellite 4: deadline expiry leaves a usable checkpoint ---------------


class TestDeadlineLeavesCheckpointUsable:
    def test_eigsh_deadline_expiry_then_resume_completes(self, tmp_path):
        """A zero deadline expires on the very first restart — but the
        solver polls AFTER the checkpoint hook, so the it=0 state is on
        disk; resuming with a fresh (absent) budget completes and matches
        scipy. This is the ISSUE 5 + ISSUE 2 composition contract."""
        import scipy.sparse as sp

        from raft_tpu.core.sparse_types import CSRMatrix
        from raft_tpu.sparse.solver import eigsh_mnmg

        n = 96
        A = sp.random(n, n, density=0.08, random_state=2, format="csr",
                      dtype=np.float64)
        A = ((A + A.T) * 0.5).astype(np.float32)
        csr = CSRMatrix.from_scipy(A)
        d = str(tmp_path)

        with limits.deadline_scope(0.0):
            with pytest.raises(limits.DeadlineExceededError) as ei:
                eigsh_mnmg(csr, k=4, mesh=_submesh(2), which="SA",
                           maxiter=50, tol=1e-6, checkpoint_every=1,
                           checkpoint_dir=d, checkpoint_keep=50)
        assert ei.value.op == "sparse.solver.lanczos"
        ckpts = sorted(f for f in os.listdir(d) if f.endswith(".ckpt"))
        assert ckpts, "expiry must leave the it=0 checkpoint behind"

        limits.reset_breakers()
        w, _ = eigsh_mnmg(csr, k=4, mesh=_submesh(2), which="SA",
                          maxiter=50, tol=1e-6,
                          resume_from=os.path.join(d, ckpts[0]))

        from scipy.sparse.linalg import eigsh as scipy_eigsh

        ws = scipy_eigsh(A.astype(np.float64), k=4, which="SA")[0]
        np.testing.assert_allclose(np.sort(np.asarray(w)), np.sort(ws),
                                   atol=1e-4)

    def test_kmeans_deadline_expiry_is_typed(self):
        import raft_tpu
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        rng = np.random.default_rng(0)
        x = np.concatenate(
            [rng.normal(c, 0.3, (100, 5)) for c in range(3)]
        ).astype(np.float32)
        res = raft_tpu.device_resources(seed=0)
        with limits.deadline_scope(0.0):
            with pytest.raises(limits.DeadlineExceededError):
                kmeans_fit(res, KMeansParams(n_clusters=3, max_iter=20,
                                             seed=0), x)


# -- ISSUE 16: RateBudget (the retry/hedge spend cap) ----------------------


class TestRateBudget:
    def test_absolute_cap(self):
        b = limits.RateBudget(max_events=2, window_s=60.0)
        assert b.try_spend()
        assert b.try_spend()
        assert not b.try_spend()
        assert b.spent() == 2

    def test_fractional_cap_tracks_primaries(self):
        b = limits.RateBudget(max_fraction=0.5, window_s=60.0)
        assert not b.try_spend(), "no primaries -> nothing to hedge"
        b.note(4)
        assert b.try_spend()
        assert b.try_spend()
        assert not b.try_spend()        # int(4 * 0.5) == 2
        b.note(2)                       # more traffic raises allowance
        assert b.try_spend()

    def test_tighter_mode_wins(self):
        b = limits.RateBudget(max_events=1, max_fraction=0.5,
                              window_s=60.0)
        b.note(10)
        assert b.try_spend()
        assert not b.try_spend()        # absolute cap bites first

    def test_window_expiry_refills(self):
        b = limits.RateBudget(max_events=1, window_s=0.05)
        assert b.try_spend()
        assert not b.try_spend()
        time.sleep(0.08)
        assert b.try_spend()

    def test_multi_spend_is_atomic(self):
        b = limits.RateBudget(max_events=3, window_s=60.0)
        assert b.try_spend(2)
        assert not b.try_spend(2)       # would overshoot: all-or-nothing
        assert b.try_spend(1)

    def test_validation(self):
        with pytest.raises(ValueError):
            limits.RateBudget()
        with pytest.raises(ValueError):
            limits.RateBudget(max_events=-1)
        with pytest.raises(ValueError):
            limits.RateBudget(max_fraction=1.5)
        with pytest.raises(ValueError):
            limits.RateBudget(max_events=1, window_s=0.0)
