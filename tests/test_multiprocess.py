"""Multi-process comms tier (VERDICT #7): real separate processes wired by
`jax.distributed`, exercising (a) a device-side collective through the
global mesh and (b) cross-process host p2p through TcpMailbox — the
analogue of raft-dask's LocalCUDACluster-based test_comms.py:254-293,
where each dask worker process NCCL-rendezvouses and runs device-verified
collective self-tests.
"""

import os
import socket
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


@pytest.mark.parametrize("nproc", [2, 4])
def test_multiprocess_comms(nproc):
    """nproc=2: quick wiring check; nproc=4: the full 13-op self-test
    battery + comm_split at 2 colors over an 8-device, 4-process clique
    (ref: raft-dask test_comms.py:254-293,429 — the N-worker cluster
    battery the round-2 verdict asked to match)."""
    coord, *p2p = _free_ports(1 + nproc)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # no TPU plugin in the workers
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    worker = os.path.join(_REPO, "tests", "_mp_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(coord)]
            + [str(p) for p in p2p],
            cwd=_REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert f"MP_WORKER_OK {pid}" in out, out
