"""Decompose the fused-Lloyd kernel's time at the north-star shape.

Times, at each precision tier: the bare distance matmul, the pairwise
kernel, the fused argmin kernel, and the full Lloyd kernel — the
increments localize where the milliseconds go (MXU passes vs VPU epilogue
vs one-hot update), which is what decides the next tuning step.

Run on the real chip: python ci/lloyd_decomp.py [m] [k] [K]
"""

import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

import raft_tpu
from raft_tpu.linalg.contractions import (fused_l2_argmin_pallas,
                                          fused_lloyd_pallas,
                                          pairwise_l2_pallas)
from raft_tpu.cluster.kmeans import lloyd_step


def timeit(fn, *args, reps=10):
    out = fn(*args)
    jax.tree_util.tree_map(
        lambda a: float(jnp.ravel(a)[0]), out)          # sync via fetch
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.tree_util.tree_map(lambda a: float(jnp.ravel(a)[0]), out)
    return (time.perf_counter() - t0) / reps * 1e3


def main():
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    K = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
    c = jnp.asarray(rng.normal(size=(K, k)), jnp.float32)

    mm = jax.jit(lambda a, b: a @ b.T)
    cases = [
        ("matmul x@cT", lambda: mm(x, c)),
        ("pairwise_l2", lambda: pairwise_l2_pallas(x, c)),
        ("fused_argmin", lambda: fused_l2_argmin_pallas(x, c)),
        ("fused_lloyd", lambda: fused_lloyd_pallas(x, c)),
        ("lloyd_step", lambda: lloyd_step(x, c, K)),
    ]
    for tier in ("default", "high", "highest"):
        raft_tpu.set_matmul_precision(tier)
        for name, fn in cases:
            try:
                ms = timeit(fn)
                print(f"{tier:8s} {name:14s} {ms:8.2f} ms")
            except Exception as e:  # noqa: BLE001 — report and continue
                print(f"{tier:8s} {name:14s} FAILED {type(e).__name__}: "
                      f"{str(e)[:120]}")


if __name__ == "__main__":
    main()
