#!/usr/bin/env bash
# Wait for the TPU tunnel, then run the full hardware battery in priority
# order: north-star bench FIRST (it is the driver-readable artifact —
# refresh it before anything else in EVERY tunnel window), then the smoke
# tier, then the full per-family sweep. Results land in tpu_battery_out/.
#
# The sweep runs ONE PYTHON PROCESS PER FAMILY with an individual timeout:
# the axon tunnel can wedge a long-lived client process indefinitely (seen
# twice in round 2 — a wedged process goes ~idle while fresh processes
# talk to the chip fine), so isolation + per-family budgets turn a wedge
# into one rc=124 line instead of a lost sweep. A family is skipped on
# resume ONLY if its family_done marker is present — a timed-out family
# (partial rows, no marker) reruns on the next pass.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p tpu_battery_out
OUT=tpu_battery_out/bench_full.jsonl
ERR=tpu_battery_out/bench_full.err
touch "$OUT"

. ci/tpu_common.sh   # probe / wait_for_tpu (we cd'd to repo root above)

# Refresh the driver-readable north-star artifact. Atomic: write to a temp
# file, accept only if the output parses as a backend=tpu JSON line with no
# error field (python does the validation), then move into place. stderr
# goes to its own log — round 2 mixed it into the artifact.
refresh_northstar() {
    echo "[battery] refreshing north-star artifact $(date +%H:%M:%S)"
    timeout -k 30 900 python bench.py \
        > tpu_battery_out/bench_northstar.tmp \
        2>> tpu_battery_out/bench_northstar.err
    rc=$?
    if [ "$rc" = 0 ] && python - <<'EOF'
import json, sys
from bench import is_valid_northstar_line   # shared predicate
ok = False
with open("tpu_battery_out/bench_northstar.tmp") as f:
    for raw in f:
        raw = raw.strip()
        if raw.startswith("{"):
            try:
                ok = is_valid_northstar_line(json.loads(raw))
            except ValueError:
                ok = False
sys.exit(0 if ok else 1)
EOF
    then
        mv tpu_battery_out/bench_northstar.tmp \
           tpu_battery_out/bench_northstar.json
        echo "[battery] north-star artifact updated:"
        cat tpu_battery_out/bench_northstar.json
        return 0
    fi
    echo "[battery] north-star refresh rejected (rc=$rc, tail below)"
    tail -2 tpu_battery_out/bench_northstar.tmp 2>/dev/null
    return 1
}

wait_for_tpu || exit 1
refresh_northstar

# smoke-green marker is keyed on HEAD + a working-tree diff hash: a pass
# only counts for the exact code state it ran against — committed OR
# uncommitted kernel changes invalidate it
HEAD_SHA="$(git rev-parse HEAD 2>/dev/null || echo unknown)-$(
    git diff HEAD -- . ':!tpu_battery_out' 2>/dev/null \
    | sha1sum | cut -c1-12)"   # battery's own output mutations excluded
if [ "$(cat tpu_battery_out/smoke_green 2>/dev/null)" != "$HEAD_SHA" ]; then
    echo "[battery] running tpu_tests smoke tier (HEAD $HEAD_SHA)"
    # ONE PROCESS PER TEST, output appended incrementally: pytest only
    # prints its FAILURES section at session end, so the 01:06 wedge mid-
    # session lost every traceback — per-test isolation turns a wedge
    # into one truncated case instead of a lost tier (same lesson as the
    # per-family sweep below)
    : > tpu_battery_out/tpu_smoke.txt
    SMOKE_RC=0
    SMOKE_IDS=$(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
                python -m pytest tpu_tests -q --collect-only -p no:cacheprovider 2>/dev/null \
                | grep '::')
    if [ -z "$SMOKE_IDS" ]; then
        # collection failed (import error etc.) — that is a red tier, not
        # a vacuous green one
        echo "[battery] smoke COLLECTION FAILED" \
            | tee -a tpu_battery_out/tpu_smoke.txt
        SMOKE_RC=1
    fi
    while IFS= read -r t; do
        [ -n "$t" ] || continue
        if ! probe; then
            echo "[battery] tunnel gone mid-smoke; waiting" \
                | tee -a tpu_battery_out/tpu_smoke.txt
            wait_for_tpu || { SMOKE_RC=1; break; }
        fi
        echo "=== $t ===" >> tpu_battery_out/tpu_smoke.txt
        TLOG=tpu_battery_out/.smoke_one.tmp
        timeout -k 30 420 python -m pytest "$t" -q --tb=short \
            -p no:cacheprovider > "$TLOG" 2>&1
        rc=$?
        cat "$TLOG" >> tpu_battery_out/tpu_smoke.txt
        # tpu_tests/conftest.py SKIPS (exit 0) when the backend isn't tpu
        # — e.g. the tunnel dropped between probe and jax init. A skip is
        # NOT a pass for the hardware tier: without this check the loop
        # could write smoke_green for a tier that never touched the chip.
        if [ "$rc" != 0 ] || ! grep -q "1 passed" "$TLOG" \
           || grep -q "skipped" "$TLOG"; then
            SMOKE_RC=1
        fi
        rm -f "$TLOG"
        echo "[battery] smoke rc=$rc $t"
    done <<< "$SMOKE_IDS"
    echo "[battery] smoke tier overall rc=$SMOKE_RC"
    if [ "$SMOKE_RC" = 0 ]; then echo "$HEAD_SHA" > tpu_battery_out/smoke_green; fi
else
    echo "[battery] smoke already green at $HEAD_SHA; skipping"
fi

# north-star tuning sweep (tm × tier × scan-vs-loop × dispatch overhead):
# the decision data for contraction defaults — once per code state
if [ "$(cat tpu_battery_out/tune_done 2>/dev/null)" != "$HEAD_SHA" ]; then
    echo "[battery] running north-star tuning sweep"
    timeout -k 30 1500 python benches/tune_northstar.py \
        > tpu_battery_out/northstar_tune.jsonl \
        2>> tpu_battery_out/northstar_tune.err
    rc=$?
    echo "[battery] tune rc=$rc"
    tail -9 tpu_battery_out/northstar_tune.jsonl
    [ "$rc" = 0 ] && echo "$HEAD_SHA" > tpu_battery_out/tune_done
else
    echo "[battery] tune already recorded at $HEAD_SHA; skipping"
fi

# fused-kNN tuning sweep (tile grid × minonly floor × tier × strip width):
# the decision data for fused_topk defaults — once per code state
if [ "$(cat tpu_battery_out/knn_tune_done 2>/dev/null)" != "$HEAD_SHA" ]; then
    echo "[battery] running fused-kNN tuning sweep"
    timeout -k 30 2400 python benches/tune_knn.py \
        > tpu_battery_out/knn_tune.jsonl \
        2>> tpu_battery_out/knn_tune.err
    rc=$?
    echo "[battery] knn tune rc=$rc"
    tail -6 tpu_battery_out/knn_tune.jsonl
    [ "$rc" = 0 ] && echo "$HEAD_SHA" > tpu_battery_out/knn_tune_done
else
    echo "[battery] knn tune already recorded at $HEAD_SHA; skipping"
fi

echo "[battery] running full bench sweep (per-family processes)"
# decision-bearing families first (they gate standing design choices:
# select_k thresholds, ELL auto-select, segment-spmv, north-star shape),
# then everything else in registry order
PRIORITY="cluster/kmeans_iter sparse/prim_probe sparse/spmv_large
sparse/lanczos matrix/select_k matrix/select_k_large
neighbors/brute_force sparse/mst
stats/moments stats/metrics random/rng random/make_blobs random/permute
random/subsample"
PRIORITY=$(echo $PRIORITY)   # flatten newlines -> single spaces
ALL=$(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python benches/run_benches.py --list)
REST=$(for f in $ALL; do
    case " $PRIORITY " in *" $f "*) ;; *) echo "$f";; esac
done)
for fam in $PRIORITY $REST; do
    # skip ONLY on the family_done marker: a family with partial rows but
    # no marker (rc=124 mid-run) must rerun (advisor finding, round 2)
    if grep -q "\"family_done\": \"$fam\"" "$OUT"; then
        echo "[battery] skip $fam (family_done recorded)"
        continue
    fi
    # re-probe between families: don't burn every budget on a dead tunnel
    if ! probe; then
        echo "[battery] tunnel gone before $fam; waiting"
        wait_for_tpu || break
        # new tunnel window: the driver artifact is the priority measurement
        refresh_northstar
    fi
    # heavy families (graph generation + many compiles) get a bigger
    # budget — in round 2 these were exactly the ones rc=124'd
    case "$fam" in
        matrix/select_k)
            BUDGET=1500 ;;  # four-way grid: 900 s was all compiles
                            # (17:38 pass, zero completed rows)
        sparse/lanczos|sparse/mst|sparse/spmv_large|sparse/spmv|\
        matrix/select_k_large|neighbors/brute_force|\
        cluster/kmeans_iter)
            BUDGET=900 ;;   # kmeans_iter rc=124'd at 420 in round 5;
                            # sparse/spmv rc=124'd at 420 (18:43, grid
                            # plan pack + compiles)
        *)  BUDGET=420 ;;
    esac
    echo "[battery] run $fam (budget ${BUDGET}s) $(date +%H:%M:%S)"
    # per-family tmp file: completed families append clean; a timed-out
    # family's completed cases still land, annotated "partial": true, so
    # a later rerun's full rows are distinguishable from the stale window
    FTMP="tpu_battery_out/.fam.$(echo "$fam" | tr / _).tmp"
    timeout -k 30 "$BUDGET" python benches/run_benches.py --size full \
        --family "$fam" 2>>"$ERR" | grep -v '^#' > "$FTMP"
    rc=${PIPESTATUS[0]}   # the runner's status, not grep's (a family that
                          # legitimately emits zero rows must still get
                          # its family_done marker under pipefail)
    echo "[battery] rc=$rc $fam"
    if [ "$rc" = 0 ]; then
        cat "$FTMP" >> "$OUT"
        echo "{\"family_done\": \"$fam\"}" >> "$OUT"
    else
        python - "$FTMP" <<'EOF' >> "$OUT"
import json, sys
for raw in open(sys.argv[1]):
    raw = raw.strip()
    if raw.startswith("{"):
        try:
            d = json.loads(raw)
        except ValueError:      # stray non-JSON line: keep the rest
            continue
        d["partial"] = True
        print(json.dumps(d))
EOF
    fi
    rm -f "$FTMP"
done

# Adjudications from the fresh rows (decision data for dispatch defaults;
# consumed by the next code change, never auto-applied): the four-way
# select_k tournament and the SpMV formulation comparison.
if grep -q '"bench": "matrix/select_k' "$OUT"; then
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python ci/derive_select_k.py "$OUT" \
        > tpu_battery_out/select_k_derive.txt 2>&1 \
        && echo "[battery] select_k adjudication written"
fi
grep -E '"bench": "sparse/(spmv|probe)' "$OUT" \
    > tpu_battery_out/spmv_verdict_rows.txt 2>/dev/null

echo "[battery] DONE $(date +%H:%M:%S)"
