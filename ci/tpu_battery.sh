#!/usr/bin/env bash
# Wait for the TPU tunnel, then run the full hardware battery:
# smoke tier -> north-star bench -> full bench sweep. Results land in
# tpu_battery_out/.
#
# The sweep runs ONE PYTHON PROCESS PER FAMILY with an individual timeout:
# the axon tunnel can wedge a long-lived client process indefinitely (seen
# twice in round 2 — a wedged process goes ~idle while fresh processes
# talk to the chip fine), so isolation + per-family budgets turn a wedge
# into one rc=124 line instead of a lost sweep. Families already recorded
# in bench_full.jsonl are skipped, so the script is resumable.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p tpu_battery_out
OUT=tpu_battery_out/bench_full.jsonl
ERR=tpu_battery_out/bench_full.err
touch "$OUT"

probe() {
    timeout 240 python -c "import jax; assert jax.default_backend()=='tpu'" \
        >/dev/null 2>&1
}

wait_for_tpu() {
    for i in $(seq 1 2000); do
        if probe; then
            echo "[battery] TPU reachable (attempt $i)"
            return 0
        fi
        sleep 120
    done
    echo "[battery] TPU never came back; giving up"
    return 1
}

wait_for_tpu || exit 1

echo "[battery] running tpu_tests smoke tier"
timeout 1800 python -m pytest tpu_tests -q \
    > tpu_battery_out/tpu_smoke.txt 2>&1
echo "[battery] smoke rc=$? (tail below)"
tail -3 tpu_battery_out/tpu_smoke.txt

echo "[battery] running north-star bench"
timeout 900 python bench.py > tpu_battery_out/bench_northstar.json 2>&1
echo "[battery] bench rc=$?"
cat tpu_battery_out/bench_northstar.json

echo "[battery] running full bench sweep (per-family processes)"
for fam in $(env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
             python benches/run_benches.py --list); do
    # family-done markers handle families whose case names differ from
    # the family name (e.g. cluster/kmeans_iter -> cluster/lloyd_iter)
    if grep -q "\"family_done\": \"$fam\"" "$OUT" \
            || grep -q "\"bench\": \"$fam" "$OUT"; then
        echo "[battery] skip $fam (already recorded)"
        continue
    fi
    # re-probe between families: don't burn every budget on a dead tunnel
    if ! probe; then
        echo "[battery] tunnel gone before $fam; waiting"
        wait_for_tpu || break
    fi
    echo "[battery] run $fam $(date +%H:%M:%S)"
    timeout 420 python benches/run_benches.py --size full --filter "$fam" \
        2>>"$ERR" | grep -v '^#' >> "$OUT"
    rc=$?
    echo "[battery] rc=$rc $fam"
    [ "$rc" = 0 ] && echo "{\"family_done\": \"$fam\"}" >> "$OUT"
done

echo "[battery] DONE"
