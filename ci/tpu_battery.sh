#!/usr/bin/env bash
# Wait for the TPU tunnel, then run the full hardware battery:
# smoke tier -> full bench sweep -> north-star bench. Results land in
# tpu_battery_out/.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p tpu_battery_out

probe() {
    timeout 240 python -c "import jax; assert jax.default_backend()=='tpu'" \
        >/dev/null 2>&1
}

echo "[battery] waiting for TPU tunnel..."
for i in $(seq 1 100); do
    if probe; then
        echo "[battery] TPU reachable (attempt $i)"
        break
    fi
    if [ "$i" = 100 ]; then
        echo "[battery] TPU never came back; giving up"
        exit 1
    fi
    sleep 120
done

echo "[battery] running tpu_tests smoke tier"
timeout 1800 python -m pytest tpu_tests -q \
    > tpu_battery_out/tpu_smoke.txt 2>&1
echo "[battery] smoke rc=$? (tail below)"
tail -3 tpu_battery_out/tpu_smoke.txt

echo "[battery] running full bench sweep"
timeout 5400 python benches/run_benches.py --size full \
    > tpu_battery_out/bench_full.jsonl 2> tpu_battery_out/bench_full.err
echo "[battery] sweep rc=$?"

echo "[battery] running north-star bench"
timeout 900 python bench.py > tpu_battery_out/bench_northstar.json 2>&1
echo "[battery] bench rc=$?"
cat tpu_battery_out/bench_northstar.json
echo "[battery] DONE"
