"""Compile-only Mosaic capability probes against the local v5e AOT
toolchain (ci/aot_compile.py — chipless, tunnel-free).

Answers:
  1. dynamic_gather legality envelope: lane widths, sublane-dim gather,
     in-vreg 2-D gather, the select-tree fallback.
  2. which radix_select_k shapes crash VectorLayoutInferer (the
     matrix/select_k battery family SIGABRT at len 8192).
  3. grid_spmv kernel legality at several shard widths.

Each probe compiles in a SUBPROCESS so a compiler SIGABRT is one line of
output, not the end of the probe run.

Run:  python ci/probe_mosaic.py [probe ...]
(handles its own env scrubbing for the subprocesses)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HEADER = """
import jax, jax.numpy as jnp
import sys
sys.path.insert(0, %r)
from ci.aot_compile import tpu_aot_compile, tpu_struct
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

GATHER_KERN = """
def kern(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=%d)
def run(x, i):
    return pl.pallas_call(kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((%d, %d), jnp.float32))(x, i)
tpu_aot_compile(run, ((%d, %d), jnp.float32), ((%d, %d), jnp.int32))
print("PROBE_OK")
"""


def gather_probe(rows, cols, axis):
    return HEADER + GATHER_KERN % (axis, rows, cols, rows, cols, rows,
                                   cols)


PROBES = {
    "dg_lane_8x128": gather_probe(8, 128, 1),
    "dg_lane_8x256": gather_probe(8, 256, 1),
    "dg_lane_8x512": gather_probe(8, 512, 1),
    "dg_lane_32x128": gather_probe(32, 128, 1),
    "dg_sublane_8x128": gather_probe(8, 128, 0),
    "dg_sublane_32x128": gather_probe(32, 128, 0),
    "tree_gather_1024": HEADER + """
def kern(x_ref, i_ref, o_ref):
    idx = i_ref[:]
    hi = idx >> 7
    lo = idx & 127
    acc = jnp.zeros((8, 128), jnp.float32)
    for v in range(8):
        row = x_ref[v, :].reshape(1, 128)
        src = jnp.broadcast_to(row, (8, 128))
        g = jnp.take_along_axis(src, lo, axis=1)
        acc = acc + jnp.where(hi == v, g, 0.0)
    o_ref[:] = acc
def run(x, i):
    return pl.pallas_call(kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))(x, i)
tpu_aot_compile(run, ((8, 128), jnp.float32), ((8, 128), jnp.int32))
print("PROBE_OK")
""",
    # two-step sublane-then-lane composition (separable 2-D gather)
    "dg_compose_8x128": HEADER + """
def kern(x_ref, si_ref, li_ref, o_ref):
    g = jnp.take_along_axis(x_ref[:], si_ref[:], axis=0)
    o_ref[:] = jnp.take_along_axis(g, li_ref[:], axis=1)
def run(x, si, li):
    return pl.pallas_call(kern,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))(x, si, li)
tpu_aot_compile(run, ((8, 128), jnp.float32), ((8, 128), jnp.int32),
                ((8, 128), jnp.int32))
print("PROBE_OK")
""",
    "radix_8192_k16": HEADER + """
import functools
from raft_tpu.matrix import radix_select
f = functools.partial(radix_select.radix_select_k, k=16, select_min=True)
tpu_aot_compile(f, ((8192, 8192), jnp.float32))
print("PROBE_OK")
""",
    "radix_65536_k256": HEADER + """
import functools
from raft_tpu.matrix import radix_select
f = functools.partial(radix_select.radix_select_k, k=256, select_min=True)
tpu_aot_compile(f, ((64, 65536), jnp.float32))
print("PROBE_OK")
""",
    "radix_1M_k16": HEADER + """
import functools
from raft_tpu.matrix import radix_select
f = functools.partial(radix_select.radix_select_k, k=16, select_min=True)
tpu_aot_compile(f, ((64, 1048576), jnp.float32))
print("PROBE_OK")
""",
}


def run_probe(name):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_SKIP_MDS_QUERY"] = "1"
    env["TPU_ACCELERATOR_TYPE"] = "v5litepod-1"
    env["RAFT_TPU_PALLAS_INTERPRET"] = "0"
    r = subprocess.run([sys.executable, "-c", PROBES[name]],
                       capture_output=True, text=True, timeout=600,
                       env=env)
    ok = r.returncode == 0 and "PROBE_OK" in (r.stdout or "")
    if ok:
        print(json.dumps({"probe": name, "ok": True}), flush=True)
        return True
    key = ""
    for line in (r.stderr or "").splitlines():
        if ("Not implemented" in line or "Check failed" in line
                or "NotImplementedError" in line
                or "INTERNAL" in line or "RET_CHECK" in line):
            key = line.strip()[:300]
            break
    print(json.dumps({"probe": name, "ok": False, "rc": r.returncode,
                      "key": key,
                      "tail": "" if key else (r.stderr or "")[-1200:]}),
          flush=True)
    return False


if __name__ == "__main__":
    for nm in (sys.argv[1:] or list(PROBES)):
        run_probe(nm)
