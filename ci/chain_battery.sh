#!/usr/bin/env bash
# Chain a fresh battery pass at the current HEAD after the running one
# exits: wait for the old watcher pid to disappear, then run the full
# battery (north-star refresh + smoke at the new sha + the re-opened
# select_k four-way grid incl. the radix kernel).
set -uo pipefail
cd "$(dirname "$0")/.."
OLD_PID="${1:?usage: chain_battery.sh <old-watcher-pid>}"
# PID liveness alone misreads reuse (waits forever) and EPERM (double
# battery on one chip) — require the cmdline to still be one of the
# battery-family scripts (tpu_battery / diag_then_battery /
# chain_battery all match "battery").
while grep -qa "battery" "/proc/$OLD_PID/cmdline" 2>/dev/null; do
    sleep 60
done
echo "[chain] previous battery (pid $OLD_PID) exited; starting fresh pass"
exec bash ci/tpu_battery.sh
