#!/usr/bin/env python
"""Render a bench sweep (JSON lines from benches/run_benches.py) as a
markdown table for BASELINE.md / round notes.

Usage: python ci/render_bench.py tpu_battery_out/bench_full.jsonl
"""

import json
import sys


def current_rows(rows):
    """Provenance filter (mirrors benches.harness.is_current_row —
    inlined because ci/ scripts run outside the package path): drop
    rows a later measurement retired (``superseded_by``) and, per bench
    name, rows older than the newest era present in the file (rows
    predating era stamping count as era 0)."""
    rows = [r for r in rows if not r.get("superseded_by")]
    newest = {}
    for r in rows:
        e = int(r.get("era", 0) or 0)
        newest[r["bench"]] = max(newest.get(r["bench"], 0), e)
    return [r for r in rows
            if int(r.get("era", 0) or 0) >= newest[r["bench"]]]


def main(path: str) -> None:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "bench" in row:          # skip family_done marker lines
                rows.append(row)
    rows = current_rows(rows)
    if not rows:
        print("(no results)")
        return
    print("| bench | median ms | throughput | roofline | bar | recall@k "
          "| compr | qps @ ranks | dev/host ms per iter | params |")
    print("|---|---|---|---|---|---|---|---|---|---|")
    # device_ms_per_iter / host_overhead_ms_per_iter: the era-8
    # compiled-inner-loop split on MULTICHIP solver rows. Rendered as
    # its own column so a collective-overhead claim has to show the
    # split, not a bundled per-iteration number. recall_at_k: the era-9
    # ANN column — an approximate-search row's throughput is
    # meaningless without the recall it was bought at, so the pair
    # renders side by side (blank for exact rows). serve_qps @ n_ranks:
    # the era-11 sharded-serving column — a scaling claim has to show
    # served qps next to the rank count that bought it.
    # mxu_frac / hbm_frac: harness ceiling fractions (TPU rows);
    # roofline_frac: the era-13 obs.perf measured fraction. Rendered as
    # one column — the larger ceiling fraction names the bound a perf
    # claim is pushing against. bar_*: the era-14 armed lever bars
    # (matrix/epilogue_levers and the select_k bar rows) — an armed row
    # renders its acceptance bar beside the measurement, with the
    # cost-model cut in parentheses on partial (off-TPU proxy) rows.
    skip = {"bench", "median_ms", "best_ms", "repeats", "era",
            "device_ms_per_iter", "host_overhead_ms_per_iter",
            "recall_at_k", "serve_qps", "mxu_frac", "hbm_frac",
            "roofline_frac", "bar_ms", "bar_gb_s", "bar_iters_per_s",
            "bar_mxu_frac", "model_cut", "compression_ratio"}
    for r in sorted(rows, key=lambda r: r["bench"]):
        thr = ""
        for k, unit in (("GFLOP_per_s", "GFLOP/s"), ("GB_per_s", "GB/s"),
                        ("items_per_s", "items/s")):
            if r.get(k) is not None:
                thr = f"{r[k]} {unit}"
                break
        split = ""
        if r.get("device_ms_per_iter") is not None:
            split = (f"{r['device_ms_per_iter']} / "
                     f"{r.get('host_overhead_ms_per_iter', 0.0)}")
        roof = ""
        if r.get("roofline_frac") is not None:
            roof = f"{float(r['roofline_frac']):.2f}"
        else:
            mxu = r.get("mxu_frac")
            hbm = r.get("hbm_frac")
            if mxu is not None or hbm is not None:
                mxu = float(mxu or 0.0)
                hbm = float(hbm or 0.0)
                roof = (f"{mxu:.2f} mxu" if mxu >= hbm
                        else f"{hbm:.2f} hbm")
        bars = []
        for key, fmt in (("bar_ms", "<= {} ms"),
                         ("bar_gb_s", ">= {} GB/s"),
                         ("bar_iters_per_s", ">= {} it/s"),
                         ("bar_mxu_frac", ">= {} mxu")):
            if r.get(key) is not None:
                bars.append(fmt.format(r[key]))
        bar = "; ".join(bars)
        if bar and r.get("model_cut") is not None:
            bar += f" (model {r['model_cut']}x)"
        recall = ""
        if r.get("recall_at_k") is not None:
            recall = f"{r['recall_at_k']}"
        # compression_ratio: the era-19 PQ column — an ANN row that
        # quantizes the database has to show the recall next to the
        # HBM bytes it saved (flat index bytes / PQ index bytes)
        compr = ""
        if r.get("compression_ratio") is not None:
            compr = f"{float(r['compression_ratio']):.1f}x"
        qps_ranks = ""
        if r.get("serve_qps") is not None:
            qps_ranks = (f"{r['serve_qps']} @ "
                         f"{r.get('n_ranks', 1)}r")
        params = ", ".join(f"{k}={v}" for k, v in r.items()
                           if k not in skip and f"{k} {v}" not in thr
                           and k not in ("GFLOP_per_s", "GB_per_s",
                                         "items_per_s"))
        print(f"| {r['bench']} | {r['median_ms']} | {thr} | {roof} "
              f"| {bar} | {recall} | {compr} | {qps_ranks} | {split} "
              f"| {params} |")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else
         "tpu_battery_out/bench_full.jsonl")
