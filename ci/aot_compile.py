"""Deviceless TPU AOT compile harness (round 5).

The axon terminal's compile helper is chipless — and so is libtpu's own
AOT path, reachable locally via a v5e TopologyDescription. That gives a
Mosaic-compile repro loop that NEVER touches the tunnel (safe to run
while the chip is busy) and catches the class of failure jax.export
lowering cannot: VectorLayoutInferer crashes, 'Not implemented' Mosaic
rejections, VMEM overflows.

Usage:
    from ci.aot_compile import tpu_aot_compile
    tpu_aot_compile(fn, arg_struct_or_array, ...)   # raises on failure

Run under:  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu
(plus TPU_SKIP_MDS_QUERY=1 TPU_ACCELERATOR_TYPE=v5litepod-1 to quiet
libtpu's metadata probing; set automatically when imported as a main
harness via ci/probe_mosaic.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.lru_cache(None)
def _topology():
    from jax.experimental import topologies

    return topologies.get_topology_desc(
        "v5e:1x1x1", "tpu",
        chips_per_host_bounds=[1, 1, 1], wrap=[False, False, False])


@functools.lru_cache(None)
def _sharding():
    return jax.sharding.SingleDeviceSharding(_topology().devices[0])


def tpu_struct(shape, dtype=jnp.float32):
    """ShapeDtypeStruct pinned to the abstract v5e device."""
    return jax.ShapeDtypeStruct(shape, dtype, sharding=_sharding())


def tpu_aot_compile(fn, *args):
    """Compile fn for v5e (deviceless). args: arrays or (shape, dtype)
    tuples. Returns the Compiled object; raises on Mosaic failure."""
    structs = []
    for a in args:
        if isinstance(a, tuple):
            structs.append(tpu_struct(*a))
        else:
            structs.append(tpu_struct(jnp.shape(a), a.dtype))
    return jax.jit(fn).lower(*structs).compile()
