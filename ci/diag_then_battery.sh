#!/usr/bin/env bash
# Wait for the TPU tunnel, capture the precision diagnosis FIRST (short,
# bounded — the decision data for the smoke-tier accuracy failures), then
# hand off to the full battery.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p tpu_battery_out

. ci/tpu_common.sh   # probe / wait_for_tpu (we cd'd to repo root above)

if wait_for_tpu; then
    echo "[diag] running precision diagnosis $(date +%H:%M:%S)"
    timeout -k 30 900 python ci/diag_precision.py \
        > tpu_battery_out/diag_precision.jsonl \
        2> tpu_battery_out/diag_precision.err
    echo "[diag] rc=$? — results:"
    cat tpu_battery_out/diag_precision.jsonl
else
    echo "[diag] TPU never came back; skipping diagnosis"
fi

# hand off either way — the battery has its own wait/give-up logic
exec bash ci/tpu_battery.sh
