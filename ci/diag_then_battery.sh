#!/usr/bin/env bash
# Wait for the TPU tunnel, capture the precision diagnosis FIRST (short,
# bounded — the decision data for the smoke-tier accuracy failures), then
# hand off to the full battery.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p tpu_battery_out

probe() {
    timeout -k 15 240 python -c "import jax; assert jax.default_backend()=='tpu'" \
        >/dev/null 2>&1
}

reached=""
for i in $(seq 1 2000); do
    if probe; then
        echo "[diag] TPU reachable (attempt $i) $(date +%H:%M:%S)"
        reached=1
        break
    fi
    sleep 120
done

if [ -n "$reached" ]; then
    echo "[diag] running precision diagnosis $(date +%H:%M:%S)"
    timeout -k 30 900 python ci/diag_precision.py \
        > tpu_battery_out/diag_precision.jsonl \
        2> tpu_battery_out/diag_precision.err
    echo "[diag] rc=$? — results:"
    cat tpu_battery_out/diag_precision.jsonl
else
    echo "[diag] TPU never came back; skipping diagnosis"
fi

# hand off either way — the battery has its own wait/give-up logic
exec bash ci/tpu_battery.sh
