#!/usr/bin/env bash
# Install-and-test smoke (the analogue of the reference's ci/ scripts:
# run_pylibraft_pytests.sh etc.). Creates a fresh venv, installs the wheel
# path end-to-end, and runs the CPU test suite.
#
# Offline-friendly: --no-build-isolation --no-deps reuse the ambient
# jax/numpy/pytest (this environment has no network egress; a networked CI
# would drop those flags).
set -euo pipefail
cd "$(dirname "$0")/.."

OUTER_SITE=$(python -c 'import site; print(site.getsitepackages()[0])')
VENV=$(mktemp -d)/venv
python -m venv --system-site-packages "$VENV"
# The ambient interpreter may itself be a venv (as on this machine, where
# python lives in /opt/venv): --system-site-packages then links the BASE
# interpreter's site-packages, not the ambient one holding jax/setuptools.
# A .pth file bridges the ambient site-packages into the fresh venv.
VENV_SITE=$("$VENV/bin/python" -c 'import site; print(site.getsitepackages()[0])')
echo "$OUTER_SITE" > "$VENV_SITE/_ambient.pth"
. "$VENV/bin/activate"

pip install --no-build-isolation --no-deps -e . 2>&1 | tail -2
python -c "
import raft_tpu
from raft_tpu.core.native_runtime import native_available
print('import OK; native runtime available:', native_available())
import raft_tpu.cluster.kmeans, raft_tpu.sparse.solver, raft_tpu.comms
print('subsystem imports OK')
"
python -m pytest tests/ -x -q
echo "smoke: PASS"
