#!/usr/bin/env bash
# Install-and-test smoke (the analogue of the reference's ci/ scripts:
# run_pylibraft_pytests.sh etc.). Creates a fresh venv, installs the wheel
# path end-to-end, and runs the CPU test suite.
#
# Offline-friendly: --no-build-isolation --no-deps reuse the ambient
# jax/numpy/pytest (this environment has no network egress; a networked CI
# would drop those flags).
set -euo pipefail
cd "$(dirname "$0")/.."

OUTER_SITE=$(python -c 'import site; print(site.getsitepackages()[0])')
VENV=$(mktemp -d)/venv
python -m venv --system-site-packages "$VENV"
# The ambient interpreter may itself be a venv (as on this machine, where
# python lives in /opt/venv): --system-site-packages then links the BASE
# interpreter's site-packages, not the ambient one holding jax/setuptools.
# A .pth file bridges the ambient site-packages into the fresh venv.
VENV_SITE=$("$VENV/bin/python" -c 'import site; print(site.getsitepackages()[0])')
echo "$OUTER_SITE" > "$VENV_SITE/_ambient.pth"
. "$VENV/bin/activate"

pip install --no-build-isolation --no-deps -e . 2>&1 | tail -2
python -c "
import raft_tpu
from raft_tpu.core.native_runtime import native_available
print('import OK; native runtime available:', native_available())
import raft_tpu.cluster.kmeans, raft_tpu.sparse.solver, raft_tpu.comms
print('subsystem imports OK')
"
# Static invariants (ISSUE 12): raftlint subsumes the old grep lints —
# R4 carries the comms/numeric error hygiene, R8 the annotated
# breakdown sites, R6 the obs API boundary — and adds jit purity (R1),
# recompile hazards (R2), lock discipline (R3), off-path purity (R5)
# and the env-knob registry (R7). The shipped tree must be clean
# against the checked-in baseline; stale waivers fail too. ISSUE 15's
# dataflow engine adds the semantic rules: donation safety (R10),
# collective discipline (R11), layout/promotion hazards (R12),
# cost-model coverage (R13), and import resolution (R14).
python -m tools.raftlint raft_tpu

# Debt inventory (non-fatal): the same scan with the baseline ignored,
# so the waived backlog stays visible in every CI log.
python -m tools.raftlint --no-baseline raft_tpu | tail -1 || true

# Gate self-test: a seeded violation per rule, linted from a tempdir
# copy, must FAIL with that rule id — proves the gate can actually
# fire, not merely that the tree is clean today.
seed_violation() {
    local rule="$1" rel="$2" dir
    dir=$(mktemp -d)
    mkdir -p "$dir/raft_tpu/$(dirname "$rel")"
    cat > "$dir/raft_tpu/$rel"
    (cd "$dir" && find raft_tpu -type d -exec touch {}/__init__.py \;)
    if python -m tools.raftlint --root "$dir" --no-baseline \
            --no-cache --rules "$rule" raft_tpu \
            > "$dir/out.txt" 2>&1; then
        echo "raftlint gate: seeded $rule violation went undetected"
        cat "$dir/out.txt"; exit 1
    fi
    grep -q ": $rule " "$dir/out.txt" || {
        echo "raftlint gate: seeded $rule violation misreported"
        cat "$dir/out.txt"; exit 1; }
    rm -rf "$dir"
}
seed_violation R1 a.py <<'EOF'
import jax
import numpy as np

@jax.jit
def f(x):
    return np.sin(x)
EOF
seed_violation R2 a.py <<'EOF'
import jax

def call(x):
    def inner(y):
        return y * 2
    return jax.jit(inner)(x)
EOF
seed_violation R3 a.py <<'EOF'
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        self.count += 1
EOF
seed_violation R4 a.py <<'EOF'
def f():
    raise RuntimeError("boom")
EOF
seed_violation R5 obs/metrics.py <<'EOF'
_enabled = False

def inc(name, value=1, **labels):
    key = (name, tuple(sorted(labels.items())))
    if not _enabled:
        return
EOF
seed_violation R6 a.py <<'EOF'
from raft_tpu.obs.metrics import inc

def f():
    inc("x")
EOF
seed_violation R7 a.py <<'EOF'
import os

FLAG = os.getenv("RAFT_TPU_FLAG", "0")
EOF
seed_violation R8 linalg/a.py <<'EOF'
import jax.numpy as jnp

def f(x):
    return jnp.sqrt(x)
EOF
seed_violation R9 a.py <<'EOF'
import jax

def f(labels):
    return jax.nn.one_hot(labels, 16)
EOF
seed_violation R10 a.py <<'EOF'
import functools
import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def consume(buf, delta):
    return buf + delta

def step(buf, delta):
    out = consume(buf, delta)
    return out + buf.sum()
EOF
seed_violation R11 a.py <<'EOF'
import jax

def body(x):
    return jax.lax.psum(x, "rows")

def run(x):
    mesh = jax.sharding.Mesh(jax.devices(), axis_names=("data",))
    mapped = jax.shard_map(body, mesh=mesh, in_specs=None,
                           out_specs=None)
    return mapped(x)
EOF
seed_violation R12 a.py <<'EOF'
from raft_tpu.matrix.epilogue import insert_drain

def drain(dist, val_ref, idx_ref, j):
    return insert_drain(dist, val_ref, idx_ref, j, tn=100, k=64,
                        n_valid=10)
EOF
seed_violation R13 runtime/limits.py <<'EOF'
def _est_toy(*, m, n, itemsize):
    return m * n * itemsize

_ESTIMATORS = {
    "toy.op": _est_toy,
}

_SECONDS_ESTIMATORS = {}
EOF
seed_violation R14 a.py <<'EOF'
from raft_tpu.gone_module import something
EOF
echo "raftlint gate: tree clean; all 14 seeded violations fail loud"

# Cache correctness + runtime budget: a warm .raftlint_cache/ run must
# reproduce the cold run's findings byte-for-byte and finish inside the
# single-digit-seconds CI budget (the memoized-findings fast path).
lintdir=$(mktemp -d)
rm -rf .raftlint_cache
python -m tools.raftlint --no-baseline raft_tpu \
    > "$lintdir/cold.txt" || true
python -m tools.raftlint --no-baseline raft_tpu \
    > "$lintdir/warm.txt" || true
diff "$lintdir/cold.txt" "$lintdir/warm.txt" || {
    echo "raftlint cache: warm-run findings differ from cold run"
    exit 1; }
python - <<'EOF'
import subprocess, sys, time
t0 = time.monotonic()
rc = subprocess.run(
    [sys.executable, "-m", "tools.raftlint", "raft_tpu"]).returncode
dt = time.monotonic() - t0
print(f"raftlint warm gate: {dt:.2f}s (budget 5s)")
if rc != 0:
    sys.exit(rc)
if dt > 5.0:
    print("raftlint warm gate: exceeded the 5s lint-runtime budget")
    sys.exit(1)
EOF
rm -rf "$lintdir"
echo "raftlint cache gate: cold==warm findings, warm run in budget"

# Epilogue bit-identity gate (ISSUE 14): the unified epilogue layer's
# primitive oracles + consumer witnesses (kmeans single/mnmg, fused +
# chunked-radix kNN, IVF full probe, dense + CSR select_k, strip-width
# invariance) run first and alone — a refactor of the shared argmin /
# one-hot / drain machinery must fail HERE, with the primitive named,
# before the full suite runs.
JAX_PLATFORMS=cpu python -m pytest tests/test_epilogue.py -q

python -m pytest tests/ -x -q

# Guard-mode gate (ISSUE 3): the solver tests must also pass with the
# numerical sentinels ARMED — 'check' raising on any non-finite value a
# solver manufactures internally is exactly the regression this catches.
RAFT_TPU_GUARD_MODE=check JAX_PLATFORMS=cpu python -m pytest \
    tests/test_guards.py tests/test_linalg.py \
    tests/test_solvers_label_spectral.py -q

# Chaos smoke: the comms fault-injection suite on the CPU backend —
# deterministic fault schedules, typed errors, fast dead-peer detection.
JAX_PLATFORMS=cpu python -m pytest tests/test_comms_faults.py -q

# Checkpoint-format gate: the committed v1 fixture must keep loading —
# a failure here means the format changed without a VERSION bump.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
from raft_tpu.core.checkpoint import restore_checkpoint
out = restore_checkpoint("tests/data/checkpoint_v1.ckpt")
assert out["n_iter"] == 17 and out["prev_inertia"] == 123.4375
assert out["centroids"].shape == (3, 4)
np.testing.assert_array_equal(
    out["centroids"],
    np.arange(12, dtype=np.float32).reshape(3, 4) / 7.0)
print("checkpoint v1 fixture: loads OK")
PYEOF

# Kill-a-rank chaos smoke: 4 real processes, one SIGKILL'd mid-iteration,
# survivors shrink + resume from checkpoint bit-for-bit (the elastic
# acceptance run).
JAX_PLATFORMS=cpu python -m pytest \
    tests/test_elastic.py::TestMultiprocessSigkill -q

# Observability gate (ISSUE 4 acceptance): a real MNMG kmeans + eigsh
# run with RAFT_TPU_METRICS=on must export (a) a schema-valid JSONL
# stream and (b) a snapshot/Prometheus exposition carrying comms byte
# counters, solver iteration counters, compile-cache stats, and a
# populated per-collective latency histogram.
OBS_JSONL=$(mktemp -d)/obs.jsonl
RAFT_TPU_METRICS=on RAFT_TPU_METRICS_JSONL="$OBS_JSONL" \
    JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PYEOF'
import os
import threading

import numpy as np
import jax
from jax.sharding import Mesh

from raft_tpu import obs
from raft_tpu.obs.schema import validate_jsonl

assert obs.enabled(), "RAFT_TPU_METRICS=on must arm the subsystem"
assert obs.get_sink() is not None, \
    "RAFT_TPU_METRICS_JSONL sink must auto-attach at import"

mesh = Mesh(np.asarray(jax.devices()[:8]), axis_names=("data",))

# -- MNMG kmeans with a live comms clique (inproc transport) ------------
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit_mnmg
from raft_tpu.comms.comms import MeshComms, _Mailbox
from raft_tpu.core import resources as core_res

rng = np.random.default_rng(0)
x = np.concatenate([rng.normal(c, 0.3, (200, 5)) for c in range(4)]
                   ).astype(np.float32)
res = core_res.Resources()
core_res.set_mesh(res, mesh)
comms = MeshComms(mesh, "data", 0, _mailbox=_Mailbox())
core_res.set_comms(res, comms)
comms.barrier()
comms.allreduce(np.ones((8, 4), np.float32))
comms.allreduce(np.ones((8, 4), np.float32))   # second call: a cache hit

# host mailbox traffic (inproc byte counters + the host_allreduce span):
# all 8 rank views over one shared mailbox, one thread per rank
n = comms.get_size()
results = [None] * n


def _rank_body(r):
    results[r] = comms.rank_view(r).host_allreduce(
        np.full(3, float(r), np.float32), tag=900)


threads = [threading.Thread(target=_rank_body, args=(r,))
           for r in range(n)]
for t in threads:
    t.start()
for t in threads:
    t.join()
assert all(np.allclose(out, sum(range(n))) for out in results)

kmeans_fit_mnmg(res, KMeansParams(n_clusters=4, max_iter=10, seed=0),
                x, mesh=mesh)

# -- single-device eigsh (solver convergence metrics) -------------------
import scipy.sparse as sp

from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.sparse.solver import eigsh

dense = rng.normal(size=(120, 120)).astype(np.float32)
dense[rng.uniform(size=dense.shape) > 0.08] = 0.0
A = sp.csr_matrix(dense + dense.T)
eigsh(CSRMatrix.from_scipy(A), k=2, which="SA", maxiter=40)

snap = obs.snapshot()
fams = snap["metrics"]


def _total(name):
    f = fams.get(name)
    if f is None:
        return 0.0
    return sum(s.get("value", s.get("count", 0)) for s in f["series"])


required = ["comms_bytes_sent_total", "comms_messages_sent_total",
            "solver_iterations_total", "solver_runs_total",
            "runtime_compile_cache_total"]
missing = [name for name in required if _total(name) <= 0]
assert not missing, \
    f"metric families absent/empty after MNMG run: {missing}"

hits = [s for s in fams["runtime_compile_cache_total"]["series"]
        if s["labels"].get("outcome") == "hit"]
assert hits and hits[0]["value"] > 0, \
    "expected at least one eager-cache hit"

hist = fams.get("comms_collective_seconds")
assert hist and hist["type"] == "histogram" \
    and sum(s["count"] for s in hist["series"]) > 0, \
    "collective latency histogram must have samples"

text = obs.render_prometheus()
for name in required + ["comms_collective_seconds_bucket"]:
    assert name in text, f"{name} missing from Prometheus exposition"

sink = obs.set_sink(None)
sink.close()
n_ok, problems = validate_jsonl(os.environ["RAFT_TPU_METRICS_JSONL"])
assert not problems, \
    "JSONL schema violations:\n" + "\n".join(problems[:10])
assert n_ok > 0, "empty JSONL export"
print(f"obs gate: {len(fams)} metric families, "
      f"{n_ok} schema-valid JSONL records")
PYEOF

# Deadline-chaos gate (ISSUE 5 acceptance): a 10 s FaultInjector.stall
# against a 2 s deadline_scope must raise the typed DeadlineExceededError
# on EVERY rank well before the stall clears — no hang, no bare timeout —
# and the expiry counter must tick.
RAFT_TPU_METRICS=on JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PYEOF'
import threading
import time

import numpy as np
import jax
from jax.sharding import Mesh

from raft_tpu import obs
from raft_tpu.comms.comms import MeshComms, _Mailbox
from raft_tpu.comms.faults import FaultInjector
from raft_tpu.runtime import limits

mesh = Mesh(np.asarray(jax.devices()[:4]), axis_names=("data",))
inj = FaultInjector(seed=0)
inj.stall(10.0)                       # every send now sleeps 10 s
comms = MeshComms(mesh, "data", 0, _mailbox=_Mailbox(faults=inj))
n = comms.get_size()
errs = [None] * n


def _rank_body(r):
    try:
        with limits.deadline_scope(2.0):
            comms.rank_view(r).host_allreduce(
                np.full(3, float(r), np.float32), tag=900)
    except Exception as exc:          # noqa: BLE001 — gate records verbatim
        errs[r] = exc


t0 = time.monotonic()
threads = [threading.Thread(target=_rank_body, args=(r,))
           for r in range(n)]
for t in threads:
    t.start()
for t in threads:
    t.join(timeout=8.0)
elapsed = time.monotonic() - t0

bad = [(r, type(e).__name__) for r, e in enumerate(errs)
       if not isinstance(e, limits.DeadlineExceededError)]
assert not bad, f"ranks without typed deadline error: {bad}"
assert elapsed < 6.0, \
    f"deadline contract violated: {elapsed:.1f}s to unwind a 2s budget"
fam = obs.snapshot()["metrics"].get("limits_deadline_exceeded_total")
assert fam and sum(s["value"] for s in fam["series"]) > 0, \
    "limits_deadline_exceeded_total must tick under chaos"
limits.reset_breakers()
print(f"deadline-chaos gate: {n} ranks raised typed errors "
      f"in {elapsed:.1f}s against a 10s stall")
PYEOF

# Admission gate (ISSUE 5 acceptance): a tiny HBM budget must degrade
# pairwise/kNN to tiled paths that are bit-for-bit equal to the
# monolithic ones; an unfittable launch must raise RejectedError with
# the estimate attached; a malformed RAFT_TPU_HBM_BUDGET must fail at
# import, not at first launch.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import os
import subprocess
import sys

import numpy as np

from raft_tpu.distance import pairwise_distance
from raft_tpu.neighbors import knn
from raft_tpu.runtime import limits

rng = np.random.default_rng(0)
x = rng.normal(size=(300, 16)).astype(np.float32)
y = rng.normal(size=(257, 16)).astype(np.float32)
base = np.asarray(pairwise_distance(None, x, y))
est = limits.estimate_bytes("distance.pairwise_distance",
                            m=300, n=257, k=16, itemsize=4)
with limits.budget_scope(est // 2):
    tiled = np.asarray(pairwise_distance(None, x, y))
assert np.array_equal(base, tiled), \
    "degraded pairwise must be bit-identical to monolithic"

db = rng.normal(size=(2048, 8)).astype(np.float32)
q = rng.normal(size=(64, 8)).astype(np.float32)
bd, bi = knn(None, db, q, k=8)
kest = limits.estimate_bytes("neighbors.brute_force_knn", n_queries=64,
                             n_db=2048, n_dims=8, k=8, itemsize=4)
with limits.budget_scope(kest // 3):
    dd, di = knn(None, db, q, k=8)
assert np.array_equal(np.asarray(bd), np.asarray(dd)) \
    and np.array_equal(np.asarray(bi), np.asarray(di)), \
    "degraded kNN must be bit-identical to monolithic"

try:
    with limits.budget_scope(1024):
        pairwise_distance(None, x, y)
    raise AssertionError("unfittable launch must be rejected")
except limits.RejectedError as exc:
    assert exc.estimate == est and exc.budget == 1024, \
        "RejectedError must carry the estimate and the budget"

limits.reset_breakers()

rc = subprocess.run(
    [sys.executable, "-c", "import raft_tpu.runtime.limits"],
    env={**os.environ, "RAFT_TPU_HBM_BUDGET": "banana"},
    capture_output=True, text=True).returncode
assert rc != 0, "malformed RAFT_TPU_HBM_BUDGET must fail at import"
print("admission gate: tiled == monolithic bit-for-bit; "
      "rejection carries estimate; malformed budget fails loud")
PYEOF

# Serving gate (ISSUE 6 acceptance): a few seconds of load generation on
# CPU must show real coalescing (factor > 1) with a reported p99, zero
# recompiles after AOT warmup, at least one typed RejectedError under a
# forced tiny queue, and a JSONL obs stream that validates against the
# schema.
SERVE_JSONL=$(mktemp /tmp/serve_obs.XXXXXX.jsonl)
RAFT_TPU_METRICS=on RAFT_TPU_METRICS_JSONL="$SERVE_JSONL" \
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np

from raft_tpu import obs, serve
from raft_tpu.runtime import limits

rng = np.random.default_rng(0)
db = rng.standard_normal((1024, 32)).astype(np.float32)

ex = serve.Executor(
    [serve.KnnService(db, k=8)],
    policy=serve.BatchPolicy(max_batch=64, max_wait_ms=2.0))
ex.warm()
traces_at_warm = ex.stats.traces
with ex:
    rep = serve.closed_loop(ex, "knn_k8_l2", clients=6, rows=4,
                            duration_s=1.5)

assert rep.completed > 0, "loadgen completed no requests"
assert rep.coalescing_factor > 1.0, \
    f"no coalescing happened (factor={rep.coalescing_factor:.2f})"
assert np.isfinite(rep.p99_ms) and rep.p99_ms > 0, "p99 must be reported"
assert ex.stats.traces == traces_at_warm, (
    f"{ex.stats.traces - traces_at_warm} recompiles after AOT warmup")

# backpressure: a 2-deep queue with no executor draining it must refuse
# the third submit with the typed, metered rejection
tiny = serve.Executor(
    [serve.KnnService(db, k=8)],
    policy=serve.BatchPolicy(max_batch=64, max_wait_ms=1000.0,
                             max_queue=2))
tiny.submit("knn_k8_l2", rng.standard_normal((1, 32)))
tiny.submit("knn_k8_l2", rng.standard_normal((1, 32)))
rejections = 0
try:
    tiny.submit("knn_k8_l2", rng.standard_normal((1, 32)))
except limits.RejectedError as exc:
    assert exc.reason == "queue_full", exc.reason
    rejections += 1
assert rejections >= 1, "tiny queue must raise typed RejectedError"
fam = obs.snapshot()["metrics"].get("limits_rejected_total")
assert fam and sum(
    s["value"] for s in fam["series"]
    if s["labels"].get("reason") == "queue_full") >= 1, \
    "queue_full rejection must be metered through limits_rejected_total"

# flush the env-attached JSONL sink (atexit would too; be explicit)
sink = obs.get_sink()
if sink is not None:
    sink.close()
print(f"serving gate: {rep.completed} reqs at {rep.qps:.0f} q/s, "
      f"coalescing {rep.coalescing_factor:.1f}, p99 {rep.p99_ms:.2f} ms, "
      f"0 recompiles, {rejections} typed rejection(s)")
PYEOF

JAX_PLATFORMS=cpu python - "$SERVE_JSONL" <<'PYEOF'
import sys

from raft_tpu.obs.schema import validate_jsonl

path = sys.argv[1]
n, errors = validate_jsonl(path)
assert n > 0, f"serving run wrote no JSONL records to {path}"
assert not errors, f"obs JSONL schema violations: {errors[:5]}"
print(f"serving obs stream: {n} JSONL records validate against schema")
PYEOF
rm -f "$SERVE_JSONL"

# Radix-parity gate (ISSUE 7 acceptance): the digit-histogram threshold
# must pick bit-identical winners vs lax.top_k on adversarial tie-heavy
# inputs, order NaN/inf by the sign-magnitude total order, and the cost
# model must show the >= 4x byte-traffic cut over the retired binary
# search.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
import jax
import jax.numpy as jnp

from benches import select_model
from raft_tpu.matrix.radix_select import radix_select_k

rng = np.random.default_rng(7)

# adversarial: rows drawn from 4 distinct values -> the threshold digit
# carries a deep tie run in every row
v = rng.choice(np.asarray([-1.0, 0.0, 0.5, 2.0], np.float32),
               size=(16, 4096))
for k in (1, 37, 256, 1000):
    gv, gi = radix_select_k(jnp.asarray(v), k, select_min=False)
    tv, ti = jax.lax.top_k(jnp.asarray(v), k)
    np.testing.assert_array_equal(np.asarray(gv), np.asarray(tv)), \
        f"k={k}: selected values diverge from lax.top_k"
    # winners are bit-identical as a set: every selected index holds the
    # selected value (tie ORDER is radix's documented first-come rule;
    # top_k leaves its own unspecified)
    np.testing.assert_array_equal(
        np.take_along_axis(v, np.asarray(gi), 1), np.asarray(gv))

# NaN/inf: IEEE total order via the sign-magnitude fold -> -NaN sorts
# below -inf, +NaN above +inf (lax.top_k has no defined NaN rule, so
# the oracle is the fold itself)
w = np.array([[np.nan, -np.nan, np.inf, -np.inf, 0.0, -0.0, 1.0, -1.0]],
             np.float32)
b = w.view(np.int32)
key = b ^ ((b >> 31) & 0x7FFFFFFF)
oi = np.argsort(key, axis=1, kind="stable")
gv, gi = radix_select_k(jnp.asarray(w), 8)
np.testing.assert_array_equal(np.asarray(gi), oi), \
    "NaN/inf ordering diverges from the sign-magnitude total order"

ratio = select_model.traffic_ratio()
assert ratio >= 4.0, \
    f"cost model: digit-histogram must move >=4x fewer bytes ({ratio:.1f}x)"
print(f"radix-parity gate: tie/NaN winners bit-identical; "
      f"{ratio:.1f}x selection-traffic cut over binary search")
PYEOF

# Five-way adjudication gate (ISSUE 7): the CPU smoke grid must populate
# ALL armed tournament columns (incl. the round-5 empty insert column)
# and derive_select_k must adjudicate; stripping a column must turn into
# the loud exit-2 failure, never a silent drop.
SELECT_ROWS=$(mktemp /tmp/select_rows.XXXXXX.jsonl)
JAX_PLATFORMS=cpu python benches/run_benches.py \
    --family matrix/select_k_smoke > "$SELECT_ROWS"
JAX_PLATFORMS=cpu python ci/derive_select_k.py "$SELECT_ROWS" \
    > "$SELECT_ROWS.out"
grep -q "insert" "$SELECT_ROWS.out" || {
    echo "adjudication gate: insert column absent from derive output"
    exit 1
}
grep -v '"algo": "insert"' "$SELECT_ROWS" > "$SELECT_ROWS.stripped"
if JAX_PLATFORMS=cpu python ci/derive_select_k.py \
        "$SELECT_ROWS.stripped" >/dev/null 2>&1; then
    echo "adjudication gate: derive must exit 2 on an armed-but-"\
         "unmeasured contender (stripped insert column went unnoticed)"
    exit 1
fi
rm -f "$SELECT_ROWS" "$SELECT_ROWS.out" "$SELECT_ROWS.stripped"
echo "adjudication gate: five columns populated; stripped column fails loud"

# Serve-path gate (ISSUE 7 acceptance): a k=512 KnnService dispatches
# through the radix epilogue (trace-event assertion at warm) and the
# batched serve answer is bit-identical to the unbatched knn call.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
import jax.numpy as jnp

from raft_tpu import serve
from raft_tpu.core import trace
from raft_tpu.neighbors import knn

rng = np.random.default_rng(0)
db = rng.standard_normal((16384, 16)).astype(np.float32)
svc = serve.KnnService(jnp.asarray(db), k=512)
ex = serve.Executor([svc],
                    policy=serve.BatchPolicy(max_batch=8, max_wait_ms=1.0))
trace.clear_events()
ex.warm(buckets=(8,))
disp = [e for e in trace.events("knn.dispatch") if e["k"] == 512]
assert disp and all(e["path"] == "radix" for e in disp), \
    f"k=512 service must warm onto the radix epilogue: {disp}"
warmed = trace.events("serve.warmed")
assert warmed and warmed[-1].get("epilogue") == "radix"

q = rng.standard_normal((4, 16)).astype(np.float32)
with ex:
    got = ex.submit("knn_k512_l2", q).result(timeout=120)
want = knn(None, jnp.asarray(db), jnp.asarray(q), k=512)
for g, w in zip(got, want):
    np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
print("serve-path gate: k=512 warmed onto radix epilogue; "
      "batched answer bit-identical to unbatched knn")
PYEOF

# Compiled-driver gate (single-program multichip): a 32-iteration kmeans
# fit at sync_every=8 must touch the host exactly ceil(32/8)=4 times
# (trace events AND the solver_host_syncs_total counter agree), and
# sync_every=1 must stay bit-for-bit the host-driven loop.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
import jax

from raft_tpu import obs
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.core import trace
from raft_tpu.obs import metrics as obs_metrics

rng = np.random.default_rng(0)
x = rng.standard_normal((512, 16)).astype(np.float32)

obs_metrics.set_registry(obs.MetricsRegistry())
obs.set_enabled(True)
trace.clear_events()
p = KMeansParams(n_clusters=8, seed=0, max_iter=32, tol=-1.0)
kmeans_fit(None, p, x, sync_every=8)
chunks = trace.events("compiled_driver.chunk")
assert len(chunks) == 4, \
    f"32 iters at sync_every=8 must be 4 chunks, saw {len(chunks)}"
assert sum(e["steps"] for e in chunks) == 32
snap = obs_metrics.get_registry().snapshot()
series = snap["solver_host_syncs_total"]["series"]
got = {tuple(s["labels"].items()): s["value"] for s in series}
assert got.get((("op", "cluster.kmeans_fit"),)) == 4, \
    f"solver_host_syncs_total must read 4, saw {got}"
obs.set_enabled(False)

p2 = KMeansParams(n_clusters=8, seed=0, max_iter=20)
c1, i1, l1, n1 = kmeans_fit(None, p2, x, sync_every=1)
trace.clear_events()
c0, i0, l0, n0 = kmeans_fit(None, p2, x)  # default: host-driven on cpu
assert not trace.events("compiled_driver.chunk"), \
    "cpu default must stay the host-driven loop"
np.testing.assert_array_equal(np.asarray(c1), np.asarray(c0))
np.testing.assert_array_equal(np.asarray(l1), np.asarray(l0))
assert (i1, n1) == (i0, n0)
print("compiled-driver gate: 4 host syncs for 32 iters at sync_every=8 "
      "(trace+counter agree); sync_every=1 bit-identical to host loop")
PYEOF

# IVF gate (ISSUE 9 acceptance): CPU build+search clears the recall
# floor at a partial probe, nprobe=n_lists is BIT-identical to
# brute_force.knn on the same db, and the serving IvfKnnService warms to
# zero post-warm recompiles with batched answers bit-identical to the
# eager search.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
import jax.numpy as jnp

import raft_tpu
from raft_tpu import serve
from raft_tpu.neighbors import ivf_flat, knn
from raft_tpu.random import RngState, make_blobs

res = raft_tpu.device_resources(seed=0)
X, _, _ = make_blobs(res, RngState(5), 8192, 32, n_clusters=64)
idx = ivf_flat.build(res, X, 64, seed=0)
q = np.asarray(X[:128])

# exactness boundary: full probe == brute force, bit for bit
bd, bi = knn(res, X, q, k=10)
ad, ai = ivf_flat.search(res, idx, q, k=10, nprobe=idx.n_lists)
np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))
np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))

# recall floor at a partial probe (16/64 lists scanned)
_, pi = ivf_flat.search(res, idx, q, k=10, nprobe=16)
gi, pi = np.asarray(bi), np.asarray(pi)
recall = float(np.mean([len(set(a) & set(b)) / 10
                        for a, b in zip(gi, pi)]))
assert recall >= 0.95, f"recall@10 at nprobe=16 fell to {recall}"

# serve path: warmed IvfKnnService, zero post-warm recompiles,
# batched bits == eager bits
svc = serve.IvfKnnService(idx, k=10, nprobe=16)
assert svc.epilogue() == "ivf"
ex = serve.Executor([svc],
                    policy=serve.BatchPolicy(max_batch=32,
                                             max_wait_ms=1.0))
ex.warm()
t0 = ex.stats.traces
with ex:
    got = ex.submit(svc.name, q[:24]).result(timeout=120)
assert ex.stats.traces == t0, \
    f"steady-state serve must not recompile: {ex.stats.traces} != {t0}"
want = ivf_flat.search(res, idx, q[:24], k=10, nprobe=16)
for g, w in zip(got, want):
    np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
print(f"ivf gate: nprobe=n_lists bit-identical to brute force; "
      f"recall@10={recall:.3f} at nprobe=16; IvfKnnService warmed with "
      f"zero post-warm recompiles, batched bits == eager bits")
PYEOF

# IVF-PQ gate (ISSUE 19 acceptance): the product-quantized index
# clears the recall floor at nprobe=16 WITH the refine stage armed,
# the full-probe+full-refine path is BIT-identical to brute_force.knn,
# the packed index costs <= 1/8 of the flat layout's resident bytes at
# the acceptance shape, and the serving IvfPqKnnService warms to zero
# post-warm recompiles with batched answers bit-identical to eager.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np

import raft_tpu
from raft_tpu import serve
from raft_tpu.neighbors import ivf_flat, ivf_pq, knn
from raft_tpu.random import RngState, make_blobs

res = raft_tpu.device_resources(seed=0)
X, _, _ = make_blobs(res, RngState(5), 8192, 32, n_clusters=64)
idx = ivf_pq.build(res, X, 64, m=8, nbits=8, seed=0)
q = np.asarray(X[:128])

# exactness boundary: full probe + full refine == brute force, bit
# for bit (nprobe >= n_lists delegates to the exact scan over the
# host-resident raw rows, so refine cannot perturb it either)
bd, bi = knn(res, X, q, k=10)
ad, ai = ivf_pq.search(res, idx, q, k=10, nprobe=idx.n_lists,
                       refine=40)
np.testing.assert_array_equal(np.asarray(bd), np.asarray(ad))
np.testing.assert_array_equal(np.asarray(bi), np.asarray(ai))

# recall floor at a partial probe with refine re-scoring the ADC
# candidates against the raw vectors
_, pi = ivf_pq.search(res, idx, q, k=10, nprobe=16, refine=40)
gi, pi = np.asarray(bi), np.asarray(pi)
recall = float(np.mean([len(set(a) & set(b)) / 10
                        for a, b in zip(gi, pi)]))
assert recall >= 0.9, f"refined recall@10 at nprobe=16 fell to {recall}"

# memory contract at the acceptance shape (d=128, m=16, nbits=8):
# PQ resident bytes <= 1/8 of the flat inverted-list layout, read off
# the packed arrays actually built — not estimated
rng = np.random.default_rng(29)
M = rng.normal(size=(8192, 128)).astype(np.float32)
flat = ivf_flat.build(res, M, 32, seed=0, max_iter=2)
pq = ivf_pq.build(res, M, 32, m=16, nbits=8, seed=0, max_iter=2,
                  pq_max_iter=2)
flat_bytes = int(flat.packed_db.nbytes + flat.packed_ids.nbytes
                 + flat.centroids.nbytes + flat.starts.nbytes
                 + flat.sizes.nbytes)
pq_bytes = int(pq.device_bytes())
assert pq_bytes * 8 <= flat_bytes, (pq_bytes, flat_bytes)

# serve path: warmed IvfPqKnnService, zero post-warm recompiles,
# batched bits == eager bits
svc = serve.IvfPqKnnService(idx, k=10, nprobe=16)
assert svc.epilogue() == "ivf_pq"
ex = serve.Executor([svc],
                    policy=serve.BatchPolicy(max_batch=32,
                                             max_wait_ms=1.0))
ex.warm()
t0 = ex.stats.traces
with ex:
    got = ex.submit(svc.name, q[:24]).result(timeout=120)
assert ex.stats.traces == t0, \
    f"steady-state serve must not recompile: {ex.stats.traces} != {t0}"
want = ivf_pq.search(res, idx, q[:24], k=10, nprobe=16)
for g, w in zip(got, want):
    np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
print(f"ivf_pq gate: full probe + refine bit-identical to brute "
      f"force; refined recall@10={recall:.3f} at nprobe=16; index "
      f"{flat_bytes / pq_bytes:.1f}x smaller than flat at d=128 m=16; "
      f"IvfPqKnnService warmed with zero post-warm recompiles")
PYEOF

# Tracing gate (ISSUE 10 acceptance): a metrics+tracing-on loadgen run
# must give EVERY completed request a full trace — a serve.request span
# whose queue_wait/execute children share its trace_id, request_id, and
# synthetic tid — with every traced request linked by exactly one
# serve.batch span, and the whole ring must render as a valid Perfetto
# document. Tenant SLO accounting must cover the run.
RAFT_TPU_METRICS=on RAFT_TPU_TRACING=on JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import numpy as np

from raft_tpu import obs, serve
from raft_tpu.obs.schema import validate_chrome_trace

assert obs.tracing_enabled(), "RAFT_TPU_TRACING=on must arm tracing"
rng = np.random.default_rng(0)
db = rng.standard_normal((1024, 32)).astype(np.float32)

qos = serve.QosPolicy(default=serve.TenantPolicy(slo_latency_s=30.0))
ex = serve.Executor(
    [serve.KnnService(db, k=8)],
    policy=serve.BatchPolicy(max_batch=64, max_wait_ms=2.0), qos=qos)
ex.warm()
# a 1 s CPU loadgen run mints a few thousand spans (3+ per request) —
# size the ring so the whole run is auditable, not just its tail
obs.set_retention(65536)
obs.clear_spans()
with ex:
    rep = serve.closed_loop(ex, "knn_k8_l2", clients=4, rows=4,
                            duration_s=1.0,
                            tenants=["gold", "bronze"])
assert rep.completed > 0, "loadgen completed no requests"

req_spans = {s["request_id"]: s for s in obs.spans("serve.request")}
assert len(req_spans) >= rep.completed, (
    f"{rep.completed} completions but only {len(req_spans)} "
    "serve.request spans")
for fam in ("serve.queue_wait", "serve.execute"):
    children = obs.spans(fam)
    by_rid = {}
    for s in children:
        assert s["parent"] == "serve.request", \
            f"{fam} span parent broke: {s['parent']!r}"
        parent = req_spans.get(s["request_id"])
        assert parent is not None, f"orphan {fam} span"
        assert s["trace_id"] == parent["trace_id"], "trace_id split"
        assert s["thread"] == parent["thread"], "tid split"
        by_rid[s["request_id"]] = s
    assert set(by_rid) == set(req_spans), \
        f"{fam}: {len(by_rid)} spans for {len(req_spans)} requests"

linked = [rid for b in obs.spans("serve.batch")
          for rid in b["attrs"]["request_ids"]]
assert set(req_spans) <= set(linked), \
    "every traced request must appear in a serve.batch span"
assert len(linked) == len(set(linked)), \
    "a request_id appeared in two batches"

doc = obs.render_chrome_trace()
problems = validate_chrome_trace(doc)
assert not problems, "chrome trace invalid:\n" + "\n".join(problems[:5])

slo = qos.slo_snapshot()
total = sum(t["window_requests"] for t in slo.values())
assert set(slo) == {"gold", "bronze"} and total >= rep.completed, \
    f"SLO window missed requests: {slo}"
print(f"tracing gate: {len(req_spans)} traced requests across "
      f"{len(obs.spans('serve.batch'))} batches; "
      f"{len(doc['traceEvents'])} chrome events validate; "
      f"SLO window covers {total} outcomes")
PYEOF

# Flight-recorder gate (ISSUE 10 acceptance): a request stalled in queue
# past its deadline must dump a bundle that schema-validates, whose
# header names the trace the failure killed, and whose span snapshot
# still holds the pre-failure serving spans.
FLIGHT_DIR=$(mktemp -d)
RAFT_TPU_METRICS=on RAFT_TPU_TRACING=on \
    RAFT_TPU_FLIGHT_DIR="$FLIGHT_DIR" JAX_PLATFORMS=cpu \
    python - <<'PYEOF'
import glob
import os

import numpy as np

from raft_tpu import obs, serve
from raft_tpu.obs.schema import validate_flight_bundle
from raft_tpu.runtime import limits

rng = np.random.default_rng(0)
db = rng.standard_normal((1024, 32)).astype(np.float32)
ex = serve.Executor(
    [serve.KnnService(db, k=8)],
    policy=serve.BatchPolicy(max_batch=64, max_wait_ms=50.0))
ex.warm()
with ex:
    ex.submit("knn_k8_l2", rng.standard_normal((4, 32))
              ).result(timeout=60)          # healthy request first
    # injected fault: a 0.5 ms deadline stalls in the 50 ms coalescing
    # window — expiry is detected at dispatch, before any launch
    fut = ex.submit("knn_k8_l2", rng.standard_normal((4, 32)),
                    deadline_s=5e-4)
    try:
        fut.result(timeout=60)
        raise AssertionError("stalled request must expire")
    except limits.DeadlineExceededError:
        pass

bundles = obs.flight_bundles("DeadlineExceededError")
assert bundles, "expiry must flight-record"
header = bundles[-1]["header"]
assert header.get("trace_id", "").startswith("t-"), \
    f"bundle must name the dead trace: {header}"
assert header["op"] == "serve.knn_k8_l2", header["op"]
assert any(s["name"] == "serve.batch" for s in bundles[-1]["spans"]), \
    "pre-failure serving spans must be inside the snapshot"

path = header.get("path")
assert path and os.path.dirname(path) == os.environ["RAFT_TPU_FLIGHT_DIR"]
n_ok, problems = validate_flight_bundle(path)
assert not problems, \
    "flight bundle schema violations:\n" + "\n".join(problems[:10])
assert n_ok == 2 + header["n_spans"] + header["n_events"]
assert len(glob.glob(os.path.join(os.path.dirname(path),
                                  "flight-*.jsonl"))) >= 1
print(f"flight gate: bundle {os.path.basename(path)} validates "
      f"({n_ok} records) and names trace {header['trace_id']}")
PYEOF
rm -rf "$FLIGHT_DIR"

# Fail-loud span knobs (ISSUE 10 satellite, the RAFT_TPU_HBM_BUDGET
# pattern): malformed retention/sampling values must fail at import.
for spec in "RAFT_TPU_SPAN_RETAIN=lots" "RAFT_TPU_SPAN_RETAIN=0" \
            "RAFT_TPU_SPAN_SAMPLE=often" "RAFT_TPU_SPAN_SAMPLE=1.5"; do
    if env "$spec" JAX_PLATFORMS=cpu \
            python -c "import raft_tpu.obs" >/dev/null 2>&1; then
        echo "span-knob gate: $spec must fail at import"
        exit 1
    fi
done
echo "span-knob gate: malformed RETAIN/SAMPLE values fail at import"

# Obs-overhead row (ISSUE 10 acceptance, BENCH_ERA=10): the north-star
# kmeans fit with metrics+tracing ON must stay within 2% of the
# everything-off wall time — the single-bool no-op discipline, measured.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import json
import logging
import time

import numpy as np

from benches.harness import BENCH_ERA
from raft_tpu import obs
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

# tol=-1 pins the iteration count; the not-converged warning is expected
logging.getLogger("raft_tpu").setLevel(logging.ERROR)
rng = np.random.default_rng(0)
x = rng.standard_normal((8192, 32)).astype(np.float32)
p = KMeansParams(n_clusters=16, seed=0, max_iter=25, tol=-1.0)


def one(armed):
    obs.set_enabled(armed)
    obs.set_tracing(armed)
    t0 = time.monotonic()
    kmeans_fit(None, p, x)
    return time.monotonic() - t0


assert not obs.enabled() and not obs.tracing_enabled()
one(False), one(True)                     # warm both modes' jit caches
# interleaved off/on pairs: adjacent runs see the same machine state,
# so the per-pair ratio cancels CPU-frequency / container drift that a
# sequential A-then-B timing misreads as obs overhead
pairs = [(one(False), one(True)) for _ in range(9)]
obs.set_enabled(False)
obs.set_tracing(False)

off_s = float(np.median([o for o, _ in pairs]))
on_s = float(np.median([n for _, n in pairs]))
delta = float(np.median([(n - o) / o for o, n in pairs]))
row = {"metric": "obs_overhead_kmeans_8192x32_k16", "era": BENCH_ERA,
       "value": round(delta * 100.0, 3), "unit": "percent",
       "off_ms": round(off_s * 1e3, 3), "on_ms": round(on_s * 1e3, 3),
       "backend": "cpu"}
print(json.dumps(row))
assert delta < 0.02, (
    f"metrics+tracing overhead {delta * 100:.2f}% exceeds the 2% "
    f"budget (off {off_s * 1e3:.1f} ms, on {on_s * 1e3:.1f} ms)")
print(f"obs-overhead gate: {delta * 100:+.2f}% "
      f"(off {off_s * 1e3:.1f} ms, on {on_s * 1e3:.1f} ms)")
PYEOF

# Sharded-serving gate (ISSUE 11 acceptance): a 2-rank CPU build must
# answer the full probe bit-identically to the single-rank search (one
# shard_map program, merge in-graph); every replica executor warms to
# zero post-warm recompiles; and a kill-a-rank chaos pass through
# ReplicaGroup.heal() returns the TYPED RecoveryReport — dead ranks,
# recovery seconds, post-recovery SLO state — with the survivor repack
# bit-equal to a fresh build and the loadgen's recovery_time_to_slo_s
# finite after a mid-run kill.
JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - <<'PYEOF'
import numpy as np

import raft_tpu
from raft_tpu import serve
from raft_tpu.neighbors import ivf_flat
from raft_tpu.neighbors.ivf_mnmg import (build_mnmg, search_mnmg,
                                         shrink_mnmg)
from raft_tpu.random import RngState, make_blobs
from raft_tpu.serve import (BatchPolicy, Executor, IvfMnmgKnnService,
                            QosPolicy, RecoveryReport, ReplicaGroup,
                            TenantPolicy, fleet_closed_loop)

from raft_tpu import obs

obs.set_enabled(True)       # SLO burn-rate metering rides the metrics
res = raft_tpu.device_resources(seed=0)
X, _, _ = make_blobs(res, RngState(5), 4096, 24, n_clusters=32)
X = np.asarray(X)
q = X[:64] + 0.01
flat = ivf_flat.build(res, X, 32, seed=0)

# full-probe bit-identity: 2-rank sharded == single-rank, ids AND bits
sd, si = ivf_flat.search(res, flat, q, k=10, nprobe=flat.n_lists)
idx = build_mnmg(res, X, 32, 2, flat=flat)
md, mi = search_mnmg(res, idx, q, k=10, nprobe=idx.n_lists)
np.testing.assert_array_equal(np.asarray(md), np.asarray(sd))
np.testing.assert_array_equal(np.asarray(mi), np.asarray(si))
# and the partial probe agrees across rank counts too
pd1, pi1 = ivf_flat.search(res, flat, q, k=10, nprobe=8)
pd2, pi2 = search_mnmg(res, idx, q, k=10, nprobe=8)
np.testing.assert_array_equal(np.asarray(pd2), np.asarray(pd1))
np.testing.assert_array_equal(np.asarray(pi2), np.asarray(pi1))


def make_executor(index):
    ex = Executor([IvfMnmgKnnService(index, k=10, nprobe=8)],
                  policy=BatchPolicy(max_batch=32, max_wait_ms=1.0),
                  qos=QosPolicy({"default": TenantPolicy(
                      slo_latency_s=5.0)}))
    ex.warm([8, 32])
    return ex


# three replicas over a 3-rank clique; rank 2 fault-disconnects
import jax
from jax.sharding import Mesh

from raft_tpu.comms.comms import MeshComms, _Mailbox
from raft_tpu.comms.faults import FaultInjector

idx3 = build_mnmg(res, X, 32, 3, flat=flat)
mesh = Mesh(np.asarray(jax.devices()[:3]), ("data",))
inj = FaultInjector(seed=0, disconnect=1.0, source_ranks={2})
comms = MeshComms(mesh, "data", 0, _mailbox=_Mailbox(faults=inj))

repack = {}


def on_shrink(new_comms, survivors):
    repack["idx"] = shrink_mnmg(idx3, survivors)
    return [make_executor(repack["idx"]) for _ in survivors]


replicas = [make_executor(idx3) for _ in range(3)]
trace_counts = [r.stats.traces for r in replicas]
group = ReplicaGroup(replicas, comms=comms, on_shrink=on_shrink)
group.start()
op3 = f"ivf_mnmg_k10_np8_r3_{idx3.metric}"
for _ in range(6):
    group.submit(op3, q[:8]).result(timeout=120)
# zero post-warm recompiles per replica under routed load
for r, t0 in zip(replicas, trace_counts):
    assert r.stats.traces == t0, \
        f"replica retraced post-warm: {r.stats.traces} != {t0}"

report = group.heal(timeout=5.0)
assert isinstance(report, RecoveryReport), report
assert report.dead == (2,) and report.survivors == (0, 1)
assert report.repacked and report.recovery_s > 0
assert isinstance(report.slo, dict)     # SLO state rides the report
fresh = build_mnmg(res, X, 32, 2, flat=flat)
for a, b in ((repack["idx"].packed_db_sh, fresh.packed_db_sh),
             (repack["idx"].packed_ids_sh, fresh.packed_ids_sh),
             (repack["idx"].starts_sh, fresh.starts_sh),
             (repack["idx"].sizes_sh, fresh.sizes_sh)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
# survivors answer on the repacked op, bits equal to eager
op2 = f"ivf_mnmg_k10_np8_r2_{idx3.metric}"
got = group.submit(op2, q[:8]).result(timeout=120)
want = search_mnmg(res, repack["idx"], q[:8], k=10, nprobe=8)
for g, w in zip(got, want):
    np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
# post-recovery SLO state: survivors answered inside the latency budget
slo = group.slo_snapshot()
assert slo["default"]["window_requests"] >= 1
assert slo["default"]["burn_rate"] == 0.0, slo
group.stop()

# loadgen recovery clock: kill one of two replicas mid-run, the fleet
# report's recovery_time_to_slo_s must come back finite
group2 = ReplicaGroup([make_executor(idx) for _ in range(2)])
with group2:
    rep = fleet_closed_loop(group2, f"ivf_mnmg_k10_np8_r2_{idx.metric}",
                            clients=3, rows=4, duration_s=1.2,
                            kill_after_s=0.4)
assert rep.killed is not None
assert rep.recovery_time_to_slo_s is not None
assert rep.recovery_time_to_slo_s != float("inf"), \
    "no post-kill completion met the SLO"
print(f"sharded-serving gate: 2-rank full probe bit-identical; zero "
      f"post-warm recompiles across 3 replicas; heal() shrank "
      f"{report.dead} -> survivors {report.survivors} in "
      f"{report.recovery_s:.2f}s with repack == fresh build; loadgen "
      f"recovery_time_to_slo_s={rep.recovery_time_to_slo_s:.3f}s")
PYEOF

# Fail-loud perf knobs (ISSUE 13 satellite): a malformed peak override
# or sentry tolerance must raise at the read site, never silently skew
# every roofline fraction / gate decision.
for spec in "RAFT_TPU_PERF_PEAKS=banana" "RAFT_TPU_PERF_PEAKS=watts=3" \
            "RAFT_TPU_SENTRY_TOL=banana" "RAFT_TPU_SENTRY_TOL=0.5"; do
    if env "$spec" JAX_PLATFORMS=cpu python -c \
            "from raft_tpu.core import hw, env
hw.peaks(backend='cpu'); env.read('RAFT_TPU_SENTRY_TOL')" \
            >/dev/null 2>&1; then
        echo "perf-knob gate: $spec must fail at the read site"
        exit 1
    fi
done
echo "perf-knob gate: malformed PEAKS/TOL values fail loud"

# Perf-attribution gate (ISSUE 13 acceptance): the served bits must be
# identical with RAFT_TPU_PERF off and on; with it on, every warmed
# (service, bucket) executable must report nonzero static costs plus a
# measured roofline fraction, with the gauges live in the registry.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np

from raft_tpu import obs, serve
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.obs import perf

DIM = 16
rng = np.random.default_rng(7)
db = rng.standard_normal((128, DIM)).astype(np.float32)
cen = rng.standard_normal((6, DIM)).astype(np.float32)
queries = [rng.standard_normal((r, DIM)).astype(np.float32)
           for r in (1, 3, 8, 2, 6, 5)]
ops = ["knn_k4_l2", "pairwise_l2_expanded", "kmeans_predict_k6"]


def run_serve():
    ex = serve.Executor(
        [serve.KnnService(db, k=4), serve.PairwiseService(db),
         serve.KMeansPredictService(cen)],
        policy=serve.BatchPolicy(max_batch=64, max_wait_ms=5.0))
    ex.warm([8, 16])
    outs = []
    with ex:
        futs = [(ops[i % 3], ex.submit(ops[i % 3], q))
                for i, q in enumerate(queries)]
        for op, f in futs:
            got = f.result(timeout=60)
            got = got if isinstance(got, tuple) else (got,)
            outs.append([np.asarray(x) for x in got])
    return outs


assert not perf.perf_enabled(), "RAFT_TPU_PERF must default off"
base = run_serve()

obs_metrics.set_registry(obs.MetricsRegistry())
obs.set_enabled(True)
perf.set_perf_enabled(True)
perf.clear_perf_profiles()
armed = run_serve()

for b, a in zip(base, armed):
    for x, y in zip(b, a):
        np.testing.assert_array_equal(x, y)

profs = perf.perf_profiles()
for op in ops:
    for bucket in (8, 16):
        p = profs[(op, bucket)]
        assert p.flops > 0 or p.bytes > 0, \
            f"{op}[{bucket}]: no static costs ({p.source})"
        assert p.launches >= 1 and p.roofline_frac > 0, \
            f"{op}[{bucket}]: no measured roofline ({p.as_dict()})"
snap = obs_metrics.get_registry().snapshot()
for g in ("perf_roofline_frac", "perf_achieved_bytes_per_s",
          "perf_achieved_flops_per_s"):
    assert snap.get(g, {}).get("series"), f"{g} gauge missing"
sect = obs.snapshot()["perf"]
assert sect["enabled"] and sect["peaks"]["flops_per_s"] > 0
n_xla = sum(1 for p in profs.values() if p.source == "xla")
perf.set_perf_enabled(False)
obs.set_enabled(False)
print(f"perf gate: serve bits identical off/on; {len(profs)} warmed "
      f"executables profiled ({n_xla} via XLA cost analysis), roofline "
      f"gauges live against {sect['peaks']['name']} peaks")
PYEOF

# Bench sentry (ISSUE 13): the shipped history must audit clean, a
# fresh copy of the best row must pass, and a seeded 2x regression of
# the same row must trip the gate.
JAX_PLATFORMS=cpu python ci/perf_sentry.py >/dev/null
SENTRY_TMP=$(mktemp -d)
python - "$SENTRY_TMP" <<'PYEOF'
import json
import sys

sys.path.insert(0, ".")
from ci.perf_sentry import collect_history

# seed from the sentry's own baseline (shipped rounds drift several x
# between container sessions, so a literal copy of one round's row is
# not a guaranteed pass — the best current-era value is, by definition)
best, newest = collect_history(".")
val, higher = best["linalg/add"]
assert not higher
row = {"bench": "linalg/add", "median_ms": val,
       "era": newest["linalg/add"]}
with open(sys.argv[1] + "/fresh_ok.jsonl", "w") as fh:
    fh.write(json.dumps(row) + "\n")
with open(sys.argv[1] + "/fresh_bad.jsonl", "w") as fh:
    fh.write(json.dumps(dict(row, median_ms=val * 2.0)) + "\n")
PYEOF
JAX_PLATFORMS=cpu python ci/perf_sentry.py \
    --fresh "$SENTRY_TMP/fresh_ok.jsonl" >/dev/null
if JAX_PLATFORMS=cpu python ci/perf_sentry.py \
        --fresh "$SENTRY_TMP/fresh_bad.jsonl" >/dev/null 2>&1; then
    echo "sentry gate: seeded regression must exit nonzero"
    exit 1
fi
rm -rf "$SENTRY_TMP"
echo "sentry gate: shipped history audits clean; seeded regression trips"

# Epilogue-lever bench gate (ISSUE 14): the armed lever family must
# run on the CPU tier with every row stamped the CURRENT era +
# ``partial`` and the armed rows carrying their bars plus the >= 1.5x
# cost-model cut; the strip-mined drain must not LOSE to the whole-tile
# drain (the lever's direction holds even in interpret mode); and the
# fresh rows must clear the sentry against the shipped era-14 lever
# baseline (newer eras gate against the best older-era row until a
# newer artifact ships; per-family tolerance 3.0: interpret-mode rows
# drift between container sessions).
LEVER_ROWS=$(mktemp /tmp/lever_rows.XXXXXX.jsonl)
JAX_PLATFORMS=cpu python benches/run_benches.py \
    --family matrix/epilogue_levers > "$LEVER_ROWS"
python - "$LEVER_ROWS" <<'PYEOF'
import json
import sys

from benches.harness import BENCH_ERA

rows = {}
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if "bench" in row and row.get("median_ms") is not None:
            rows[row["bench"]] = row

expected = {"epilogue/northstar_sharediota",
            "epilogue/knn_drain_k64_strip",
            "epilogue/knn_drain_k64_wholetile",
            "epilogue/select_k_insert_strip",
            "epilogue/select_k_insert_wholetile"}
missing = expected - set(rows)
assert not missing, f"lever family dropped rows: {missing}"
for name, row in rows.items():
    assert row["era"] == BENCH_ERA, (name, row.get("era"))
    assert row.get("partial") is True, \
        f"{name}: CPU proxy row must stamp partial"
ns = rows["epilogue/northstar_sharediota"]
assert ns["bar_iters_per_s"] == 125.0 and ns.get("iters_per_s", 0) > 0
armed = rows["epilogue/knn_drain_k64_strip"]
assert armed["bar_ms"] == 50.0 and armed["bar_mxu_frac"] == 0.15
assert armed.get("model_cut", 0) >= 1.5, \
    "armed drain row must record a >= 1.5x cost-model cut"
assert rows["epilogue/select_k_insert_strip"].get("model_cut", 0) >= 1.5
for fam in ("knn_drain_k64", "select_k_insert"):
    s = rows[f"epilogue/{fam}_strip"]["median_ms"]
    w = rows[f"epilogue/{fam}_wholetile"]["median_ms"]
    assert s <= w * 1.10, \
        f"{fam}: strip drain ({s} ms) lost to whole tile ({w} ms)"
print(f"lever gate: 5 era-{BENCH_ERA} rows, armed bars carried, strip <= whole "
      f"tile on both drain consumers (model cut {armed['model_cut']}x)")
PYEOF
JAX_PLATFORMS=cpu python ci/perf_sentry.py --fresh "$LEVER_ROWS" \
    --family-tol epilogue/northstar_sharediota=3.0 \
    --family-tol epilogue/knn_drain_k64_strip=3.0 \
    --family-tol epilogue/knn_drain_k64_wholetile=3.0 \
    --family-tol epilogue/select_k_insert_strip=3.0 \
    --family-tol epilogue/select_k_insert_wholetile=3.0 >/dev/null
rm -f "$LEVER_ROWS"
echo "lever sentry: fresh current-era rows clear the shipped baseline"

# Serve-level lever witness (ISSUE 14 satellite): the spent epilogue
# levers observed from the SERVING side — a loadgen p99 row and a
# north-star iters/s row, both captured through obs.snapshot()["perf"]
# so each carries the roofline bound class attributing what the lever
# moved (overhead-bound on the CPU proxy; a TPU window's rows show the
# north star's bound flip the fusion buys).
WITNESS_ROWS=$(mktemp /tmp/witness_rows.XXXXXX.jsonl)
RAFT_TPU_METRICS=on JAX_PLATFORMS=cpu python - "$WITNESS_ROWS" <<'PYEOF'
import functools
import json
import sys
import time

import numpy as np
import jax

from benches.harness import BENCH_ERA
from raft_tpu import obs, serve
from raft_tpu.cluster.kmeans import lloyd_step
from raft_tpu.obs import perf

perf.set_perf_enabled(True)
perf.clear_perf_profiles()

rng = np.random.default_rng(14)
db = rng.standard_normal((2048, 32)).astype(np.float32)
ex = serve.Executor([serve.KnnService(db, k=64)],
                    policy=serve.BatchPolicy(max_batch=32,
                                             max_wait_ms=2.0))
ex.warm([4, 8])
with ex:
    rep = serve.closed_loop(ex, "knn_k64_l2", clients=4, rows=4,
                            duration_s=1.0)
assert rep.completed > 0 and np.isfinite(rep.p99_ms) and rep.p99_ms > 0

# north-star proxy iteration through the shared-iota epilogue,
# attributed against the roofline by obs.perf
x = jax.numpy.asarray(rng.standard_normal((4096, 32)).astype(np.float32))
c = jax.numpy.asarray(rng.standard_normal((64, 32)).astype(np.float32))
f = jax.jit(functools.partial(lloyd_step, n_clusters=64))
perf.profile_executable("cluster.lloyd_step", 4096, fn=f,
                        example=(x, c),
                        model_flops=2.0 * 4096 * 64 * 32,
                        model_bytes=4.0 * (4096 * 32 + 64 * 32))
jax.block_until_ready(f(x, c))               # compile outside the clock
iters = 10
t0 = time.perf_counter()
for _ in range(iters):
    out = f(x, c)
jax.block_until_ready(out)
wall = time.perf_counter() - t0
perf.record_launch("cluster.lloyd_step", 4096, wall, steps=iters)
iters_per_s = iters / wall

snap = obs.snapshot()["perf"]
assert snap["enabled"] and snap["profiles"], \
    "obs.snapshot()['perf'] must carry the witness profiles"
prof = snap["profiles"]["cluster.lloyd_step[4096]"]
assert prof["bound"] in ("compute", "bandwidth", "overhead"), prof
assert prof["roofline_frac"] > 0, prof
knn_profs = {name: p for name, p in snap["profiles"].items()
             if name.startswith("knn_k64_l2")}
assert knn_profs, "warmed KnnService must register perf profiles"
knn_bound = next(iter(knn_profs.values()))["bound"]
assert knn_bound in ("compute", "bandwidth", "overhead")

rows = [
    {"bench": "serve/loadgen_p99_knn_k64", "era": BENCH_ERA,
     "median_ms": round(rep.p99_ms, 3), "backend": "cpu",
     "partial": True, "bound": knn_bound, "qps": round(rep.qps, 1),
     "completed": rep.completed},
    {"metric": "epilogue/northstar_iters_per_s", "era": BENCH_ERA,
     "value": round(iters_per_s, 2), "backend": "cpu", "partial": True,
     "bound": prof["bound"],
     "roofline_frac": round(prof["roofline_frac"], 4),
     "bar_iters_per_s": 125.0},
]
with open(sys.argv[1], "w") as fh:
    for row in rows:
        fh.write(json.dumps(row) + "\n")
perf.set_perf_enabled(False)
print(f"serve witness: p99 {rep.p99_ms:.2f} ms ({knn_bound}-bound), "
      f"north-star proxy {iters_per_s:.1f} iters/s "
      f"({prof['bound']}-bound, roofline_frac "
      f"{prof['roofline_frac']:.3f})")
PYEOF
JAX_PLATFORMS=cpu python ci/perf_sentry.py --fresh "$WITNESS_ROWS" \
    --family-tol serve/loadgen_p99_knn_k64@cpu=3.0 \
    --family-tol epilogue/northstar_iters_per_s@cpu=3.0 >/dev/null
rm -f "$WITNESS_ROWS"
echo "witness sentry: serve-side lever rows clear the shipped baseline"


# Brownout gate (ISSUE 16): a 4x open-loop traffic step against a
# brownout-armed Executor (capacity throttled by a constant fault
# stall so the step genuinely overloads). Witnesses: the degradation
# ladder engages (level > 0 responses served), every transition rides
# a pre-warmed executable (zero retraces during the chaos run), the
# min_quality=0 gold tenant is never degraded (no controller step, no
# floor-violation flight bundle), and after the step the level returns
# to 0 with p99 back near the base phase.
RAFT_TPU_METRICS=on JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np

from raft_tpu import obs, serve
from raft_tpu.comms.faults import FaultInjector
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.serve import loadgen

obs.set_enabled(True)
reg = obs.MetricsRegistry()
obs_metrics.set_registry(reg)

rng = np.random.default_rng(16)
db = rng.standard_normal((2048, 32)).astype(np.float32)
ladder = serve.knn_ladder(db, [32, 16, 8])
qos = serve.QosPolicy({
    "default": serve.TenantPolicy(slo_latency_s=0.25),
    "gold": serve.TenantPolicy(slo_latency_s=0.25, min_quality=0),
})
qos.SLO_WINDOW_S = 1.5       # gate-speed burn window (default 60 s)
ctl = serve.BrownoutController(
    [ladder], qos=qos, queue_high=0.5, step_interval_s=0.1,
    window_s=0.2, clean_windows=2)
inj = FaultInjector(seed=0)
ex = serve.Executor(
    [], policy=serve.BatchPolicy(max_batch=8, max_wait_ms=2.0,
                                 max_queue=64),
    qos=qos, brownout=ctl, faults=inj)
ex.warm([4, 8])
inj.stall(0.02)              # throttle capacity so the 4x step overloads
with ex:
    rep = loadgen.chaos_traffic_step(
        ex, "knn_k32_l2", base_qps=40.0, step_factor=4.0, rows=4,
        phase_s=1.2, recovery_s=3.0, tenants=["default", "gold"],
        seed=16)

step = rep.phases["step"]
assert rep.brownout_max_level > 0, \
    f"4x step never engaged the ladder: {step}"
assert any(int(lv) > 0
           for lv in step.get("brownout_levels", {})), \
    f"no degraded responses served DURING the step: {step}"
assert rep.retraces_during == 0, \
    f"brownout stepping recompiled ({rep.retraces_during} retraces) " \
    f"— every ladder level must be pre-warmed"
assert rep.brownout_recovered, \
    f"level did not return to 0 after the step: {rep.notes}"
base_p99 = rep.phases["base"]["p99_ms"]
rec_p99 = rep.phases["recovery"]["p99_ms"]
assert rec_p99 <= 3.0 * base_p99, \
    f"p99 did not recover: base {base_p99} ms -> recovery {rec_p99} ms"
snap = reg.snapshot()
floor = snap.get("serve_brownout_floor_violations_total")
assert floor is None or not floor["series"], \
    f"min_quality floor violated: {floor}"
gauge = snap.get("serve_brownout_level")
gold = [sr for sr in (gauge["series"] if gauge else [])
        if sr["labels"].get("tenant") == "gold"]
assert not gold, \
    f"gold tenant (min_quality=0) was stepped by the controller: {gold}"
print(f"brownout gate: 4x step engaged level {rep.brownout_max_level} "
      f"(0 retraces), gold pinned at full quality, recovered to "
      f"level 0 (p99 {base_p99:.1f} -> {step['p99_ms']:.1f} -> "
      f"{rec_p99:.1f} ms)")
PYEOF

# Slow-replica hedge gate (ISSUE 16): one replica of a hedged
# 4-replica fleet straggles on a duty cycle (the GC-pause profile
# hedging is built for — a CONSTANT straggler on a small fleet is more
# demand than a 5% hedge budget can cover by design, loadgen.py
# chaos_slow_replica docstring). Witnesses: fleet p99 under the
# straggler holds within 2x the healthy baseline, the hedge spend
# stays within the 5% budget, hedges actually issue AND win, and the
# hedge legs ride pre-warmed executables (zero retraces).
RAFT_TPU_METRICS=on JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np

from raft_tpu import obs, serve
from raft_tpu.comms.faults import FaultInjector
from raft_tpu.obs import metrics as obs_metrics
from raft_tpu.serve import loadgen

obs.set_enabled(True)
reg = obs.MetricsRegistry()
obs_metrics.set_registry(reg)

rng = np.random.default_rng(17)
db = rng.standard_normal((2048, 32)).astype(np.float32)
injs = [FaultInjector(seed=i) for i in range(4)]
execs = []
for i in range(4):
    ex = serve.Executor(
        [serve.KnnService(db, k=8)],
        policy=serve.BatchPolicy(max_batch=16, max_wait_ms=2.0,
                                 max_queue=32),
        faults=injs[i])
    ex.warm()
    execs.append(ex)
# 0.045: the fractional budget's base window also counts the priming
# phase's submits, so an exact 0.05 can land a hair over the asserted
# 5% hedge-rate ceiling
group = serve.ReplicaGroup(
    execs, hedge=serve.HedgePolicy(delay_floor_s=0.005,
                                   min_samples=16,
                                   budget_fraction=0.045))
with group:
    # prime the hedger's delay estimate (and the fractional budget's
    # base window) at steady state before measuring
    loadgen._group_closed_loop(group, "knn_k8_l2", clients=8, rows=4,
                               duration_s=2.0, seed=3)
    traces0 = sum(ex.stats.traces for ex in execs)
    rep = loadgen.chaos_slow_replica(
        group, "knn_k8_l2", stall_s=0.08, victim=0, clients=8,
        rows=4, phase_s=3.0, stall_duty=0.07, stall_period_s=0.5,
        seed=17)
    retraces = sum(ex.stats.traces for ex in execs) - traces0

h = rep.phases["healthy"]["p99_ms"]
st = rep.phases["stalled"]["p99_ms"]
hd = rep.phases["healed"]["p99_ms"]
assert st <= 2.0 * h, \
    f"straggler broke the fleet p99: healthy {h:.1f} ms -> " \
    f"stalled {st:.1f} ms (> 2x)"
assert rep.hedge_rate <= 0.05, \
    f"hedge spend {rep.hedge_rate:.4f} exceeds the 5% budget"
assert rep.hedges_issued > 0 and rep.hedges_won > 0, \
    f"hedging never engaged: issued {rep.hedges_issued}, " \
    f"won {rep.hedges_won}"
assert retraces == 0, \
    f"hedge legs recompiled ({retraces} retraces) — hedges must ride " \
    f"the same pre-warmed executables"
assert hd <= 2.0 * h, \
    f"fleet did not heal: healthy {h:.1f} ms -> healed {hd:.1f} ms"
print(f"hedge gate: duty-cycled straggler held p99 {h:.1f} -> "
      f"{st:.1f} ms (<= 2x), hedge rate "
      f"{rep.hedge_rate:.3f} <= 0.05 "
      f"({rep.hedges_issued} issued / {rep.hedges_won} won, "
      f"0 retraces)")
PYEOF

# Overload bench sentry (ISSUE 16): the serve/overload family must run
# on the CPU tier with every row stamped the current era + partial and
# carrying its resilience witnesses, and the fresh rows must clear the
# sentry against the shipped era-16 baseline
# (per-family tolerance 3.0: chaos-phase p99 rows drift between
# container sessions).
OVERLOAD_ROWS=$(mktemp /tmp/overload_rows.XXXXXX.jsonl)
JAX_PLATFORMS=cpu python benches/run_benches.py \
    --family serve/overload > "$OVERLOAD_ROWS"
python - "$OVERLOAD_ROWS" <<'PYEOF'
import json
import sys

from benches.harness import BENCH_ERA

rows = {}
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if "bench" in row and row.get("median_ms") is not None:
            rows[row["bench"]] = row

expected = {"serve/overload_step_p99", "serve/overload_slowreplica_p99"}
missing = expected - set(rows)
assert not missing, f"overload family dropped rows: {missing}"
for name, row in rows.items():
    assert row["era"] == BENCH_ERA, (name, row.get("era"))
    assert row.get("partial") is True, \
        f"{name}: CPU proxy row must stamp partial"
step = rows["serve/overload_step_p99"]
assert step["brownout_max_level"] > 0, step
assert step["retraces"] == 0, step
slow = rows["serve/overload_slowreplica_p99"]
assert slow["hedge_rate"] <= 0.05, slow
assert slow["hedges_issued"] > 0, slow
print(f"overload bench: 2 era-{BENCH_ERA} rows (step engaged level "
      f"{step['brownout_max_level']}, slow-replica hedge rate "
      f"{slow['hedge_rate']})")
PYEOF
JAX_PLATFORMS=cpu python ci/perf_sentry.py --fresh "$OVERLOAD_ROWS" \
    --family-tol serve/overload_step_p99=3.0 \
    --family-tol serve/overload_slowreplica_p99=3.0 >/dev/null
rm -f "$OVERLOAD_ROWS"
echo "overload sentry: fresh current-era rows clear the shipped baseline"

# Streaming lifecycle gate (ISSUE 17): sustained ingest + deletes
# racing concurrent queries through at least one shape-changing
# snapshot swap, recall scored per query against an exact reference
# over the snapshot window it was served from. Floors: no failed
# queries, >= 1 swap crossed, min recall 0.5, mean recall 0.85.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np

from raft_tpu import serve
from raft_tpu.neighbors.streaming import stream_build

rng = np.random.default_rng(3)
db = rng.normal(size=(256, 8)).astype(np.float32)
idx = stream_build(None, db, 8, seed=0, max_iter=4, repack_slack=48)
idx.compact(reason="provision")
svc = serve.StreamingKnnService(idx, k=5, nprobe=7)
ctl = serve.IngestController(
    idx, [svc],
    policy=serve.BatchPolicy(max_batch=8, max_wait_ms=2.0),
    compact_interval=0.05, refit=False, warm_buckets=[8])
with ctl:
    rep = serve.streaming_loop(
        ctl, svc.name, clients=3, rows=4, duration_s=2.5,
        ingest_rows=48, ingest_interval_s=0.02, delete_frac=0.3,
        seed=1)
assert rep.failed == 0, rep.as_dict()
assert rep.queries > 0 and rep.ingest_batches >= 2, rep.as_dict()
assert rep.swaps >= 1, "the run must cross a shape-changing swap"
assert rep.min_recall >= 0.5, rep.as_dict()
assert rep.mean_recall >= 0.85, rep.as_dict()
assert rep.n_live_final == idx.n_live, rep.as_dict()
print(f"streaming gate: {rep.queries} queries over "
      f"{rep.ingest_batches} ingest batches, {rep.swaps} swaps, "
      f"recall min {rep.min_recall:.3f} / mean {rep.mean_recall:.3f}, "
      f"0 failed")
PYEOF

# Streaming crash-consistency smoke (ISSUE 17): SIGKILL the mutation
# worker mid-epoch-write and require recovery to land bit-equal on the
# last journaled state — never a torn index. The reference CRCs and
# the recovery CRCs are printed by subprocesses from the same
# environment so jax config can never skew reference vs witness.
CHAOS_DIR=$(mktemp -d /tmp/stream_chaos.XXXXXX)
CLEAN_DIR=$(mktemp -d /tmp/stream_clean.XXXXXX)
CLEAN_CRCS=$(JAX_PLATFORMS=cpu python tests/_streaming_chaos_worker.py \
    --dir "$CLEAN_DIR")
read -r CRC_DEL CRC_INS2 CRC_FINAL <<<"$CLEAN_CRCS"
rc=0
JAX_PLATFORMS=cpu python tests/_streaming_chaos_worker.py \
    --dir "$CHAOS_DIR" --crash compact.mid_write --mode kill || rc=$?
if [ "$rc" -ne 137 ]; then
    echo "chaos worker expected SIGKILL (rc 137), got rc=$rc" >&2
    exit 1
fi
REC_CRCS=$(JAX_PLATFORMS=cpu python tests/_streaming_chaos_worker.py \
    --dir "$CHAOS_DIR" --recover)
read -r REC_FIRST REC_SECOND <<<"$REC_CRCS"
if [ "$REC_FIRST" != "$REC_SECOND" ]; then
    echo "recovery is not deterministic: $REC_FIRST vs $REC_SECOND" >&2
    exit 1
fi
if [ "$REC_FIRST" != "$CRC_INS2" ]; then
    echo "torn recovery: got $REC_FIRST, want $CRC_INS2" \
         "(pre-crash journaled state)" >&2
    exit 1
fi
rm -rf "$CHAOS_DIR" "$CLEAN_DIR"
echo "streaming chaos: SIGKILL at compact.mid_write recovered" \
     "bit-equal to the journaled epoch (crc $REC_FIRST, deterministic)"

# Streaming bench sentry (ISSUE 17): the neighbors/streaming_ingest
# family must run on the CPU tier with every row stamped the current
# era + partial and carrying its lifecycle witnesses (swaps crossed,
# recall floor held, recovery CRC bit-equal), and the fresh rows must
# clear the sentry against the shipped baseline (per-family tolerance
# 3.0: live-loop tail rows drift between container sessions).
STREAM_ROWS=$(mktemp /tmp/stream_rows.XXXXXX.jsonl)
JAX_PLATFORMS=cpu python benches/run_benches.py \
    --family neighbors/streaming_ingest > "$STREAM_ROWS"
python - "$STREAM_ROWS" <<'PYEOF'
import json
import sys

from benches.harness import BENCH_ERA

rows = {}
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if not line:
            continue
        row = json.loads(line)
        if "bench" in row and row.get("median_ms") is not None:
            rows[row["bench"]] = row

expected = {"neighbors/streaming_ingest_p99",
            "neighbors/streaming_recovery"}
missing = expected - set(rows)
assert not missing, f"streaming family dropped rows: {missing}"
for name, row in rows.items():
    assert row["era"] == BENCH_ERA, (name, row.get("era"))
    assert row.get("partial") is True, \
        f"{name}: CPU proxy row must stamp partial"
ing = rows["neighbors/streaming_ingest_p99"]
assert ing["failed"] == 0, ing
assert ing["swaps"] >= 1, ing
assert ing["min_recall"] >= 0.5, ing
rec = rows["neighbors/streaming_recovery"]
assert rec["crc_match"] is True, rec
print(f"streaming bench: 2 era-{BENCH_ERA} rows (ingest "
      f"{ing['ingest_rate']:.0f} rows/s across {ing['swaps']} swaps, "
      f"recall min {ing['min_recall']}, recovery crc bit-equal)")
PYEOF
JAX_PLATFORMS=cpu python ci/perf_sentry.py --fresh "$STREAM_ROWS" \
    --family-tol neighbors/streaming_ingest_p99=3.0 \
    --family-tol neighbors/streaming_recovery=3.0 >/dev/null
rm -f "$STREAM_ROWS"
echo "streaming sentry: fresh current-era rows clear the shipped baseline"

# Durable-fleet restart chaos gate (ISSUE 18 acceptance): a three-role
# witness — a clean never-killed run, a leader streaming mutations over
# real TCP WAL shipping, and a follower SIGKILL'd mid-stream that
# restarts from its mirrored journal and catches up UNDER QUERY LOAD.
# The orchestrator asserts the follower resumed from a mid-stream
# cursor, converged past the target sequence, held the recall floor
# while catching up, and landed content-CRC bit-equal to both the
# leader and the clean twin.
DUR_OUT=$(JAX_PLATFORMS=cpu python tests/_durability_worker.py orchestrate) \
    || { echo "durability orchestrator exited rc=$?" >&2; exit 1; }
echo "$DUR_OUT" | grep -q "DURABILITY_CHAOS_OK" || {
    echo "durability chaos gate failed:" >&2
    echo "$DUR_OUT" >&2
    exit 1
}
echo "durability chaos: $(echo "$DUR_OUT" | grep DURABILITY_CHAOS_OK)"

# Scrub + read-repair gate (ISSUE 18): a seeded bit-flip in the newest
# epoch snapshot must be DETECTED (container CRC), QUARANTINED (renamed
# out of every recovery walk), and REPAIRED (fresh epoch rewritten from
# the healthy live index) — and with no healthy source the damage must
# surface as the typed ShardCorruptError, never a silent serve.
JAX_PLATFORMS=cpu python - <<'PYEOF2'
import os
import tempfile

import numpy as np

from raft_tpu.comms.faults import FaultInjector
from raft_tpu.neighbors.scrub import Scrubber
from raft_tpu.neighbors.streaming import (MutationLog, ShardCorruptError,
                                          StreamingIndex, _epoch_entries,
                                          stream_build)

rng = np.random.default_rng(5)
db = rng.normal(size=(256, 8)).astype(np.float32)
with tempfile.TemporaryDirectory() as d:
    idx = stream_build(None, db, 8, seed=0, max_iter=4, directory=d)
    ids = idx.insert(rng.normal(size=(32, 8)).astype(np.float32))
    idx.delete(ids[::4])
    crc = idx.content_crc()
    newest = idx.log.epoch_path(max(idx.log.epoch_steps()))
    FaultInjector().corrupt_bytes(newest)
    sc = Scrubber(idx, interval=60.0)
    rep = sc.run_once()
    assert rep.corrupt and rep.quarantined and rep.repaired, vars(rep)
    assert os.path.exists(newest + ".quarantined"), "not quarantined"
    assert not sc.run_once().corrupt, "repair did not restore redundancy"
    rec = StreamingIndex.recover(None, d)
    assert rec.content_crc() == crc, "repaired journal not bit-equal"
    # unrepairable: a lone corrupt epoch with no healthy source
    cold = os.path.join(d, "cold")
    log = MutationLog(cold)
    log.write_epoch(0, _epoch_entries(idx))
    FaultInjector().corrupt_bytes(log.epoch_path(0))
    try:
        Scrubber(log=log, interval=60.0).run_once()
    except ShardCorruptError as e:
        print(f"scrub gate: bit-flip quarantined + repaired bit-equal "
              f"(crc {crc}); unrepairable raised typed {type(e).__name__}")
    else:
        raise SystemExit("unrepairable damage did not raise")
PYEOF2

# Durability bench sentry (ISSUE 18): the serve/durability family must
# run on the CPU tier with every row stamped the current era + partial
# and carrying its witnesses (catch-up CRC bit-equal over the records
# path, scrub detect/repair, drift recall floors), and the fresh rows
# must clear the sentry against the shipped baseline (per-family
# tolerance 3.0: live-loop rows drift between container sessions).
DUR_ROWS=$(mktemp /tmp/dur_rows.XXXXXX.jsonl)
JAX_PLATFORMS=cpu python benches/run_benches.py \
    --family serve/durability > "$DUR_ROWS"
python - "$DUR_ROWS" <<'PYEOF2'
import json
import sys

from benches.harness import BENCH_ERA

rows = {}
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if line:
            row = json.loads(line)
            if "bench" in row and row.get("median_ms") is not None:
                rows[row["bench"]] = row

expected = {"serve/durability_catchup_d64",
            "serve/durability_catchup_d256",
            "serve/durability_scrub",
            "serve/durability_drift_stream",
            "serve/durability_drift_rebuild"}
missing = expected - set(rows)
assert not missing, f"durability family dropped rows: {missing}"
for name, row in rows.items():
    assert row["era"] == BENCH_ERA, (name, row.get("era"))
    assert row.get("partial") is True, \
        f"{name}: CPU proxy row must stamp partial"
for d in (64, 256):
    cu = rows[f"serve/durability_catchup_d{d}"]
    assert cu["crc_match"] is True, cu
    assert cu["snapshot"] is False and cu["records"] == d, cu
sc = rows["serve/durability_scrub"]
assert sc["detect_repair_ok"] is True, sc
st = rows["serve/durability_drift_stream"]
assert st["recall_final"] >= 0.9, st
print(f"durability bench: {len(rows)} era-{BENCH_ERA} rows (catch-up "
      f"{rows['serve/durability_catchup_d256']['median_ms']:.0f} ms @ "
      f"depth 256 crc bit-equal, scrub detect/repair ok, drift recall "
      f"{st['recall_mid']}/{st['recall_final']})")
PYEOF2
JAX_PLATFORMS=cpu python ci/perf_sentry.py --fresh "$DUR_ROWS" \
    --family-tol serve/durability_catchup_d64=3.0 \
    --family-tol serve/durability_catchup_d256=3.0 \
    --family-tol serve/durability_scrub=3.0 \
    --family-tol serve/durability_drift_stream=3.0 \
    --family-tol serve/durability_drift_rebuild=3.0 >/dev/null
rm -f "$DUR_ROWS"
echo "durability sentry: fresh current-era rows clear the shipped baseline"

# IVF-PQ bench sentry (ISSUE 19): the neighbors/ivf_pq_recall family
# must run on the CPU tier with every row stamped the current era, the
# sweep rows carrying BOTH witnesses (recall_at_k next to the measured
# compression_ratio), at least one swept (nprobe, refine) point
# clearing recall@10 >= 0.9 at compression >= 8x, and the fresh rows
# must clear the sentry against the shipped baseline (per-family
# tolerance 3.0: CPU-proxy rows drift between container sessions).
PQ_ROWS=$(mktemp /tmp/pq_rows.XXXXXX.jsonl)
JAX_PLATFORMS=cpu python benches/run_benches.py \
    --family neighbors/ivf_pq_recall > "$PQ_ROWS"
python - "$PQ_ROWS" <<'PYEOF2'
import json
import sys

from benches.harness import BENCH_ERA

rows = {}
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if line:
            row = json.loads(line)
            if "bench" in row and row.get("median_ms") is not None:
                rows[row["bench"]] = row

expected = {"neighbors/ivf_pq_brute_baseline",
            "neighbors/ivf_pq_search_np1_rf0",
            "neighbors/ivf_pq_search_np4_rf0",
            "neighbors/ivf_pq_search_np16_rf0",
            "neighbors/ivf_pq_search_np16_rf40",
            "neighbors/ivf_pq_search_np64_rf40"}
missing = expected - set(rows)
assert not missing, f"ivf_pq_recall family dropped rows: {missing}"
best = 0.0
compr = None
for name, row in rows.items():
    assert row["era"] == BENCH_ERA, (name, row.get("era"))
    if name == "neighbors/ivf_pq_brute_baseline":
        continue
    assert row.get("recall_at_k") is not None, name
    assert row.get("compression_ratio") is not None, name
    compr = float(row["compression_ratio"])
    assert compr >= 8.0, (name, compr)
    if float(row["scanned_frac"]) < 1.0:
        best = max(best, float(row["recall_at_k"]))
assert best >= 0.9, f"no partial-probe sweep point reached 0.9: {best}"
print(f"ivf_pq bench: {len(rows)} era-{BENCH_ERA} rows (best "
      f"partial-probe recall@10 {best} at {compr}x compression)")
PYEOF2
JAX_PLATFORMS=cpu python ci/perf_sentry.py --fresh "$PQ_ROWS" \
    --family-tol neighbors/ivf_pq_brute_baseline=3.0 \
    --family-tol neighbors/ivf_pq_search_np1_rf0=3.0 \
    --family-tol neighbors/ivf_pq_search_np4_rf0=3.0 \
    --family-tol neighbors/ivf_pq_search_np16_rf0=3.0 \
    --family-tol neighbors/ivf_pq_search_np16_rf40=3.0 \
    --family-tol neighbors/ivf_pq_search_np64_rf40=3.0 >/dev/null
rm -f "$PQ_ROWS"
echo "ivf_pq sentry: fresh current-era rows clear the shipped baseline"

# Kill-the-leader chaos gate (ISSUE 20 acceptance): a three-node
# real-TCP fleet — every node an ElectionNode over its own journal,
# WAL records streaming leader→followers — with the LEADER SIGKILL'd
# mid-stream. The survivors detect heartbeat silence, elect the
# most-caught-up follower by (term, applied_seq), and the new leader
# resumes term-stamped writes. The orchestrator asserts quorum-acked
# writes survived the kill (zero acked-write loss), the new term
# fences the old one, and the promoted journal lands content-CRC
# bit-equal to a clean never-killed twin.
FO_OUT=$(JAX_PLATFORMS=cpu python tests/_failover_worker.py orchestrate) \
    || { echo "failover orchestrator exited rc=$?" >&2; exit 1; }
echo "$FO_OUT" | grep -q "FAILOVER_CHAOS_OK" || {
    echo "failover chaos gate failed:" >&2
    echo "$FO_OUT" >&2
    exit 1
}
echo "failover chaos: $(echo "$FO_OUT" | grep FAILOVER_CHAOS_OK)"

# Failover bench sentry (ISSUE 20): the serve/failover family must run
# on the CPU tier with every row stamped the current era + partial and
# carrying its witnesses (most-caught-up winner, post-heal CRC match,
# acked writes resumed on the successor), the quorum row must stamp
# its overhead-vs-async ratios, and the fresh rows must clear the
# sentry against the shipped baseline (per-family tolerance 3.0:
# live-fleet rows drift between container sessions). The gate asserts
# witness PRESENCE and the boolean witnesses, not latency magnitudes —
# single-sample tails on a busy CPU container are noise-dominated.
FO_ROWS=$(mktemp /tmp/fo_rows.XXXXXX.jsonl)
JAX_PLATFORMS=cpu python benches/run_benches.py \
    --family serve/failover > "$FO_ROWS"
python - "$FO_ROWS" <<'PYEOF2'
import json
import sys

from benches.harness import BENCH_ERA

rows = {}
with open(sys.argv[1]) as fh:
    for line in fh:
        line = line.strip()
        if line:
            row = json.loads(line)
            if "bench" in row and row.get("median_ms") is not None:
                rows[row["bench"]] = row

expected = {"serve/failover_election_n3",
            "serve/failover_ingest_gap",
            "serve/failover_ack_async",
            "serve/failover_ack_majority"}
missing = expected - set(rows)
assert not missing, f"failover family dropped rows: {missing}"
for name, row in rows.items():
    assert row["era"] == BENCH_ERA, (name, row.get("era"))
    assert row.get("partial") is True, \
        f"{name}: CPU proxy row must stamp partial"
el = rows["serve/failover_election_n3"]
assert el["winner_most_caught_up"] is True, el
assert el["crc_match"] is True, el
assert el["term"] >= 1, el
gap = rows["serve/failover_ingest_gap"]
assert gap["writes_resumed"] is True, gap
for mode in ("async", "majority"):
    assert rows[f"serve/failover_ack_{mode}"].get("p99_ms") is not None
mj = rows["serve/failover_ack_majority"]
assert mj.get("p99_overhead_vs_async") is not None, mj
assert mj.get("p50_overhead_vs_async") is not None, mj
assert mj.get("quorum_waits", 0) > 0, mj
print(f"failover bench: {len(rows)} era-{BENCH_ERA} rows (election "
      f"{el['median_ms']:.1f} ms, ingest gap {gap['median_ms']:.1f} ms, "
      f"quorum p50 overhead {mj['p50_overhead_vs_async']}x, "
      f"{mj['quorum_waits']} quorum waits)")
PYEOF2
JAX_PLATFORMS=cpu python ci/perf_sentry.py --fresh "$FO_ROWS" \
    --family-tol serve/failover_election_n3=3.0 \
    --family-tol serve/failover_ingest_gap=3.0 \
    --family-tol serve/failover_ack_async=3.0 \
    --family-tol serve/failover_ack_majority=3.0 >/dev/null
rm -f "$FO_ROWS"
echo "failover sentry: fresh current-era rows clear the shipped baseline"

echo "smoke: PASS"
