#!/usr/bin/env bash
# Install-and-test smoke (the analogue of the reference's ci/ scripts:
# run_pylibraft_pytests.sh etc.). Creates a fresh venv, installs the wheel
# path end-to-end, and runs the CPU test suite.
#
# Offline-friendly: --no-build-isolation --no-deps reuse the ambient
# jax/numpy/pytest (this environment has no network egress; a networked CI
# would drop those flags).
set -euo pipefail
cd "$(dirname "$0")/.."

OUTER_SITE=$(python -c 'import site; print(site.getsitepackages()[0])')
VENV=$(mktemp -d)/venv
python -m venv --system-site-packages "$VENV"
# The ambient interpreter may itself be a venv (as on this machine, where
# python lives in /opt/venv): --system-site-packages then links the BASE
# interpreter's site-packages, not the ambient one holding jax/setuptools.
# A .pth file bridges the ambient site-packages into the fresh venv.
VENV_SITE=$("$VENV/bin/python" -c 'import site; print(site.getsitepackages()[0])')
echo "$OUTER_SITE" > "$VENV_SITE/_ambient.pth"
. "$VENV/bin/activate"

pip install --no-build-isolation --no-deps -e . 2>&1 | tail -2
python -c "
import raft_tpu
from raft_tpu.core.native_runtime import native_available
print('import OK; native runtime available:', native_available())
import raft_tpu.cluster.kmeans, raft_tpu.sparse.solver, raft_tpu.comms
print('subsystem imports OK')
"
# Error-hygiene lint for the comms stack: the resilience layer exists so
# failures surface as typed CommsError subclasses — reject reintroduced
# blanket handlers (`except Exception`) and silently swallowed socket
# errors (`except OSError: pass`; use contextlib.suppress(OSError) at
# well-understood shutdown sites instead).
python - <<'PYEOF'
import pathlib, re, sys
bad = []
for p in sorted(pathlib.Path("raft_tpu/comms").glob("*.py")):
    text = p.read_text()
    for m in re.finditer(r"except\s+Exception\b", text):
        bad.append(f"{p}:{text.count(chr(10), 0, m.start()) + 1}: "
                   "bare 'except Exception' (catch typed CommsError kinds)")
    for m in re.finditer(r"except\s+OSError\s*:\s*\n\s*pass\b", text):
        bad.append(f"{p}:{text.count(chr(10), 0, m.start()) + 1}: "
                   "silent 'except OSError: pass' (use "
                   "contextlib.suppress or surface a typed error)")
print("\n".join(bad) if bad else "comms error-hygiene lint: clean")
sys.exit(1 if bad else 0)
PYEOF

# Numeric error-hygiene lint (ISSUE 3, the solver-layer mirror of the
# comms lint above): in linalg/ and sparse/solver/, reject blanket
# handlers and UNANNOTATED breakdown sites — a sqrt or norm-divide whose
# operand sign/zero is not visibly handled (maximum/abs/clip/eps floor)
# must either grow a guard or carry a `# guarded:` comment naming why it
# cannot go negative/zero.
python - <<'PYEOF'
import pathlib, re, sys
GUARD_TOKENS = ("maximum", "abs", "clip", "eps", "finfo", "1.0 +",
                "guarded:")
bad = []
files = sorted(pathlib.Path("raft_tpu/linalg").glob("*.py")) + \
    sorted(pathlib.Path("raft_tpu/sparse/solver").glob("*.py"))
for p in files:
    lines = p.read_text().splitlines()
    for i, line in enumerate(lines, 1):
        if re.search(r"except\s+Exception\b", line):
            bad.append(f"{p}:{i}: bare 'except Exception' (catch typed "
                       "NumericalError kinds from core/guards.py)")
        # sqrt of a quantity that can silently go negative: require a
        # guard token on the line or an explanatory `# guarded:` comment
        if "jnp.sqrt(" in line and not any(t in line for t in GUARD_TOKENS):
            bad.append(f"{p}:{i}: unguarded jnp.sqrt — clamp the operand "
                       "(jnp.maximum(x, 0)) or annotate '# guarded: <why>'")
        # division by a computed norm: zero vectors divide to NaN/inf
        if re.search(r"/\s*jnp\.linalg\.norm\(", line) and \
                not any(t in line for t in GUARD_TOKENS):
            bad.append(f"{p}:{i}: unguarded divide by jnp.linalg.norm — "
                       "floor it or annotate '# guarded: <why>'")
print("\n".join(bad) if bad else "numeric error-hygiene lint: clean")
sys.exit(1 if bad else 0)
PYEOF

python -m pytest tests/ -x -q

# Guard-mode gate (ISSUE 3): the solver tests must also pass with the
# numerical sentinels ARMED — 'check' raising on any non-finite value a
# solver manufactures internally is exactly the regression this catches.
RAFT_TPU_GUARD_MODE=check JAX_PLATFORMS=cpu python -m pytest \
    tests/test_guards.py tests/test_linalg.py \
    tests/test_solvers_label_spectral.py -q

# Chaos smoke: the comms fault-injection suite on the CPU backend —
# deterministic fault schedules, typed errors, fast dead-peer detection.
JAX_PLATFORMS=cpu python -m pytest tests/test_comms_faults.py -q

# Checkpoint-format gate: the committed v1 fixture must keep loading —
# a failure here means the format changed without a VERSION bump.
JAX_PLATFORMS=cpu python - <<'PYEOF'
import numpy as np
from raft_tpu.core.checkpoint import restore_checkpoint
out = restore_checkpoint("tests/data/checkpoint_v1.ckpt")
assert out["n_iter"] == 17 and out["prev_inertia"] == 123.4375
assert out["centroids"].shape == (3, 4)
np.testing.assert_array_equal(
    out["centroids"],
    np.arange(12, dtype=np.float32).reshape(3, 4) / 7.0)
print("checkpoint v1 fixture: loads OK")
PYEOF

# Kill-a-rank chaos smoke: 4 real processes, one SIGKILL'd mid-iteration,
# survivors shrink + resume from checkpoint bit-for-bit (the elastic
# acceptance run).
JAX_PLATFORMS=cpu python -m pytest \
    tests/test_elastic.py::TestMultiprocessSigkill -q
echo "smoke: PASS"
