#!/usr/bin/env python
"""Derive select_k dispatch thresholds from the hardware tournament.

Reads matrix/select_k* rows from a bench JSONL (direct vs tiled per
(len, k) cell), prints the winner map + a recommended `_choose_tiled`
predicate, and flags cells where `lax.top_k` (direct) falls below the
bandwidth roofline — the explicit evidence gate the design note in
raft_tpu/matrix/select_k.py names for ever writing a Pallas radix
kernel (ref heuristic being replaced: detail/select_k-inl.cuh:38-63).

Usage: python ci/derive_select_k.py tpu_battery_out/bench_full.jsonl
"""

import json
import sys
from collections import defaultdict

HBM_GB_S = 819.0     # v5e


def main(path):
    cells = defaultdict(dict)    # (length, k) -> {algo: row}
    for line in open(path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            r = json.loads(line)
        except ValueError:
            continue
        name = r.get("bench", "")
        if not name.startswith("matrix/select_k_len"):
            continue
        if r.get("partial"):
            continue
        cells[(r["length"], r["k"])][r["algo"]] = r

    if not cells:
        print("(no select_k tournament rows found)")
        return

    print(f"{'len':>9} {'k':>6} {'direct ms':>10} {'tiled ms':>9} "
          f"{'winner':>7} {'direct GB/s':>12} {'hbm frac':>9}")
    tiled_wins = []
    for (length, k), algos in sorted(cells.items()):
        d = algos.get("direct")
        t = algos.get("tiled")
        if not d or not t:
            continue
        dm, tm = d["median_ms"], t["median_ms"]
        win = "tiled" if tm < dm else "direct"
        if win == "tiled":
            tiled_wins.append((length, k, dm / tm))
        # the selection streams batch*len f32 once: the bandwidth floor
        gbs = d["batch"] * length * 4 / (dm / 1e3) / 1e9
        print(f"{length:>9} {k:>6} {dm:>10.2f} {tm:>9.2f} {win:>7} "
              f"{gbs:>12.1f} {gbs / HBM_GB_S:>9.2f}")

    print()
    if tiled_wins:
        min_len = min(w[0] for w in tiled_wins)
        max_k = max(w[1] for w in tiled_wins)
        print(f"tiled wins at: {tiled_wins}")
        print(f"recommended _choose_tiled: n_cols >= {min_len} and "
              f"k <= {max_k}")
    else:
        print("direct (lax.top_k) wins every cell: "
              "_choose_tiled should return False everywhere measured")
    print("\nPallas-radix gate: any cell with winner-side hbm frac well "
          "below ~0.5 at len >= 64k is evidence lax.top_k leaves "
          "bandwidth on the table (see select_k.py design note).")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else
         "tpu_battery_out/bench_full.jsonl")
