#!/usr/bin/env python
"""Derive select_k dispatch thresholds from the hardware tournament.

Reads matrix/select_k* rows from a bench JSONL (the five-way
direct/tiled/stream/radix/insert tournament per (len, k) cell), prints the
winner map + a recommended dispatch predicate, and quotes the winner's
HBM fraction — the roofline evidence that originally triggered building
the Pallas radix-rank kernel (raft_tpu/matrix/radix_select.py; ref
heuristic being replaced: detail/select_k-inl.cuh:38-63).

Usage: python ci/derive_select_k.py tpu_battery_out/bench_full.jsonl
"""

import json
import sys
from collections import defaultdict

HBM_GB_S = 819.0     # v5e


def current_rows(rows):
    """Provenance filter (mirrors benches.harness.is_current_row —
    inlined because ci/ scripts run outside the package path): drop
    superseded rows and, per bench name, rows older than the newest
    era present (pre-stamping rows count as era 0)."""
    rows = [r for r in rows if not r.get("superseded_by")]
    newest = {}
    for r in rows:
        e = int(r.get("era", 0) or 0)
        newest[r["bench"]] = max(newest.get(r["bench"], 0), e)
    return [r for r in rows
            if int(r.get("era", 0) or 0) >= newest[r["bench"]]]


def main(path):
    rows = []
    for line in open(path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            r = json.loads(line)
        except ValueError:
            continue
        name = r.get("bench", "")
        if not name.startswith("matrix/select_k_len"):
            continue
        if r.get("partial"):
            continue
        rows.append(r)
    cells = defaultdict(dict)    # (length, k) -> {algo: row}
    for r in current_rows(rows):
        cells[(r["length"], r["k"])][r["algo"]] = r

    if not cells:
        print("(no select_k tournament rows found)")
        return

    print(f"{'len':>9} {'k':>6} {'direct ms':>10} {'tiled ms':>9} "
          f"{'stream ms':>10} {'radix ms':>9} {'insert ms':>10} "
          f"{'winner':>7} {'win GB/s':>9} {'hbm frac':>9}")
    wins = {}
    for (length, k), algos in sorted(cells.items()):
        d = algos.get("direct")
        if not d:
            continue
        times = {a: algos[a]["median_ms"]
                 for a in ("direct", "tiled", "stream", "radix", "insert")
                 if a in algos}
        win = min(times, key=times.get)
        wins.setdefault(win, []).append((length, k, times))
        # the selection streams batch*len f32 once: the bandwidth floor
        # quoted for the WINNER (is the best algo leaving bandwidth idle?)
        gbs = d["batch"] * length * 4 / (times[win] / 1e3) / 1e9

        def fmt(a):
            return f"{times[a]:.2f}" if a in times else "-"
        print(f"{length:>9} {k:>6} {fmt('direct'):>10} {fmt('tiled'):>9} "
              f"{fmt('stream'):>10} {fmt('radix'):>9} "
              f"{fmt('insert'):>10} {win:>7} "
              f"{gbs:>9.1f} {gbs / HBM_GB_S:>9.2f}")

    print()
    for algo in ("tiled", "stream", "radix", "insert"):
        if wins.get(algo):
            cells_won = [(w[0], w[1]) for w in wins[algo]]
            print(f"{algo} wins at: {cells_won}")
            print(f"  -> dispatch should pick {algo} for n_cols >= "
                  f"{min(c[0] for c in cells_won)} and k <= "
                  f"{max(c[1] for c in cells_won)}")
    if set(wins) == {"direct"}:
        print("direct (lax.top_k) wins every cell: "
              "_choose_tiled should return False everywhere measured")
    print("\nPallas-radix gate: any cell with winner-side hbm frac well "
          "below ~0.5 at len >= 64k is evidence lax.top_k leaves "
          "bandwidth on the table (see select_k.py design note).")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else
         "tpu_battery_out/bench_full.jsonl")
