#!/usr/bin/env python
"""Derive select_k dispatch thresholds from the hardware tournament.

Reads matrix/select_k* rows from a bench JSONL (the five-way
direct/tiled/stream/radix/insert tournament per (len, k) cell), prints the
winner map + a recommended dispatch predicate, and quotes the winner's
HBM fraction — the roofline evidence that originally triggered building
the Pallas radix-rank kernel (raft_tpu/matrix/radix_select.py; ref
heuristic being replaced: detail/select_k-inl.cuh:38-63).

Adjudication contract (ISSUE 7): a contender the bench grid ARMS for a
cell (see expected_algos — the same predicates bench_prims uses to
enter an algo into the tournament) must have a row for that cell.
``partial: true`` rows (smoke-scale, e.g. the CPU tier's
matrix/select_k_smoke family) populate a column structurally — they
render with a ``~`` marker and only break ties when no full-scale row
exists — but an armed contender with NO row at all (the round-5 empty
insert column) is a loud failure: exit 2 listing every missing
(cell, algo), so a battery that silently dropped a column can never
adjudicate.

Usage: python ci/derive_select_k.py tpu_battery_out/bench_full.jsonl
"""

import json
import sys
from collections import defaultdict

HBM_GB_S = 819.0     # v5e

ALGOS = ("direct", "tiled", "stream", "radix", "insert")


def expected_algos(length, k):
    """Which contenders the bench grid arms for a (len, k) cell —
    mirrors benches.bench_prims._select_k_grid (inlined because ci/
    scripts run outside the package path): stream only above the 8192
    tile (below it the stream path dispatches to direct), radix inside
    its supports() envelope, insert at k <= 256 (topk_insert.MAX_K)."""
    algos = {"direct", "tiled"}
    if length > 8192:
        algos.add("stream")
    if k <= length and k <= 16384 and length <= (1 << 24):
        algos.add("radix")
    if k <= 256:
        algos.add("insert")
    return algos


def current_rows(rows):
    """Provenance filter (mirrors benches.harness.is_current_row —
    inlined because ci/ scripts run outside the package path): drop
    superseded rows and, per bench name, rows older than the newest
    era present (pre-stamping rows count as era 0)."""
    rows = [r for r in rows if not r.get("superseded_by")]
    newest = {}
    for r in rows:
        e = int(r.get("era", 0) or 0)
        newest[r["bench"]] = max(newest.get(r["bench"], 0), e)
    return [r for r in rows
            if int(r.get("era", 0) or 0) >= newest[r["bench"]]]


def main(path):
    rows = []
    for line in open(path):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            r = json.loads(line)
        except ValueError:
            continue
        name = r.get("bench", "")
        if not name.startswith("matrix/select_k_len"):
            continue
        rows.append(r)
    # (length, k) -> {algo: row}; a full-scale row always beats a
    # partial (smoke-scale) row for the same cell+algo
    cells = defaultdict(dict)
    for r in current_rows(rows):
        cell, algo = (r["length"], r["k"]), r["algo"]
        prev = cells[cell].get(algo)
        if prev is None or (prev.get("partial") and not r.get("partial")):
            cells[cell][algo] = r

    if not cells:
        print("(no select_k tournament rows found)")
        return 0

    print(f"{'len':>9} {'k':>6} {'direct ms':>10} {'tiled ms':>9} "
          f"{'stream ms':>10} {'radix ms':>9} {'insert ms':>10} "
          f"{'winner':>7} {'win GB/s':>9} {'hbm frac':>9}")
    wins = {}
    missing = []
    for (length, k), algos in sorted(cells.items()):
        for a in sorted(expected_algos(length, k) - set(algos)):
            missing.append(((length, k), a))
        d = algos.get("direct")
        if not d:
            continue
        times = {a: algos[a]["median_ms"] for a in ALGOS if a in algos}
        # partial rows adjudicate only among themselves: a smoke-scale
        # timing must never outvote a hardware row in the same cell
        full = {a: t for a, t in times.items()
                if not algos[a].get("partial")}
        win = min(full or times, key=(full or times).get)
        cell_partial = not full
        wins.setdefault(win, []).append((length, k, times))
        # the selection streams batch*len f32 once: the bandwidth floor
        # quoted for the WINNER (is the best algo leaving bandwidth idle?)
        gbs = d["batch"] * length * 4 / (times[win] / 1e3) / 1e9

        def fmt(a):
            if a not in times:
                return "-"
            mark = "~" if algos[a].get("partial") else ""
            return f"{mark}{times[a]:.2f}"
        print(f"{length:>9} {k:>6} {fmt('direct'):>10} {fmt('tiled'):>9} "
              f"{fmt('stream'):>10} {fmt('radix'):>9} "
              f"{fmt('insert'):>10} "
              f"{('~' if cell_partial else '') + win:>7} "
              f"{gbs:>9.1f} {gbs / HBM_GB_S:>9.2f}")

    print("\n(~ = partial/smoke-scale row: populates the column, "
          "never outvotes a full-scale row)")
    for algo in ("tiled", "stream", "radix", "insert"):
        if wins.get(algo):
            cells_won = [(w[0], w[1]) for w in wins[algo]]
            print(f"{algo} wins at: {cells_won}")
            print(f"  -> dispatch should pick {algo} for n_cols >= "
                  f"{min(c[0] for c in cells_won)} and k <= "
                  f"{max(c[1] for c in cells_won)}")
    if set(wins) == {"direct"}:
        print("direct (lax.top_k) wins every cell: "
              "_choose_tiled should return False everywhere measured")
    print("\nPallas-radix gate: any cell with winner-side hbm frac well "
          "below ~0.5 at len >= 64k is evidence lax.top_k leaves "
          "bandwidth on the table (see select_k.py design note).")

    if missing:
        print("\nERROR: armed-but-unmeasured contenders — the tournament "
              "cannot adjudicate with an empty column:", file=sys.stderr)
        for (length, k), algo in missing:
            print(f"  (len={length}, k={k}): no '{algo}' row "
                  f"(not even partial)", file=sys.stderr)
        print("  -> re-run the battery family, or populate smoke-scale "
              "partial rows (benches matrix/select_k_smoke)",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else
                  "tpu_battery_out/bench_full.jsonl"))
