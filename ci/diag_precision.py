"""On-chip precision diagnosis for the smoke-tier accuracy failures.

Round-3 smoke run (01:06 window) failed pairwise_l2 / fused_argmin-small /
fused_lloyd / knn / precision_tiers / lloyd_in_shard_map at the default
'high' tier while cosine / tiled-argmin / select_k passed — consistent with
the bf16x3 split NOT delivering its ~2^-17 contract on the real chip. This
script isolates where: plain XLA dots at each lax.Precision, the in-kernel
_kernel_dot tiers, the pre-split kernel path, and the fused epilogue —
one JSON line per probe, flushed immediately (a wedged tunnel loses the
tail, not the run).
"""

import json
import os
import sys

import numpy as np

# Runnable as `python ci/diag_precision.py` from the repo root: sys.path[0]
# is ci/, which hides the raft_tpu package (the 03:18 window lost the
# pallas/tier probes to exactly this).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def emit(**kw):
    print(json.dumps(kw), flush=True)


def rel_err(got, ref):
    got = np.asarray(got, np.float64)
    return float((np.abs(got - ref) / np.maximum(np.abs(ref), 1e-9)).max())


def main():
    import jax
    import jax.numpy as jnp

    emit(probe="backend", backend=jax.default_backend(),
         device=str(jax.devices()[0]))

    rng = np.random.default_rng(11)
    # POSITIVE entries: dot outputs are O(k) with no cancellation, so
    # max-rel-err is a faithful precision probe (gaussian inputs produce
    # near-zero dot entries whose rel err explodes at any precision)
    a = rng.uniform(0.5, 1.5, size=(512, 96)).astype(np.float32)
    b = rng.uniform(0.5, 1.5, size=(96, 256)).astype(np.float32)
    ref = a.astype(np.float64) @ b.astype(np.float64)

    # 1. plain XLA dot at each lax.Precision — does the chip honor the
    # precision attribute at all outside Pallas?
    for prec in ("default", "high", "highest"):
        try:
            d = jax.jit(lambda x, y: jnp.dot(
                x, y, precision=prec))(a, b)
            emit(probe="xla_dot", precision=prec, rel_err=rel_err(d, ref))
        except Exception as e:   # noqa: BLE001
            emit(probe="xla_dot", precision=prec,
                 error=f"{type(e).__name__}: {e}"[:200])

    # 2. manual bf16x3 split OUTSIDE Pallas (plain XLA) — is the split
    # algebra sound on this chip?
    try:
        def split3(x, y):
            xh = x.astype(jnp.bfloat16)
            xl = (x - xh.astype(jnp.float32)).astype(jnp.bfloat16)
            yh = y.astype(jnp.bfloat16)
            yl = (y - yh.astype(jnp.float32)).astype(jnp.bfloat16)
            f32 = jnp.float32
            kw = dict(preferred_element_type=f32,
                      precision=jax.lax.Precision.DEFAULT)
            return (jnp.dot(xh, yh, **kw) + jnp.dot(xh, yl, **kw)
                    + jnp.dot(xl, yh, **kw))
        d = jax.jit(split3)(a, b)
        emit(probe="xla_manual_split3", rel_err=rel_err(d, ref))
    except Exception as e:   # noqa: BLE001
        emit(probe="xla_manual_split3", error=f"{type(e).__name__}: {e}"[:200])

    # 3. _kernel_dot inside a minimal pallas_call at each tier
    import raft_tpu
    from jax.experimental import pallas as pl
    from raft_tpu.linalg import contractions as C

    def dot_kernel(x_ref, y_ref, o_ref):
        o_ref[:] = C._kernel_dot(x_ref[:], y_ref[:])

    for tier in ("default", "high", "highest"):
        try:
            raft_tpu.set_matmul_precision(tier)
            d = pl.pallas_call(
                dot_kernel,
                out_shape=jax.ShapeDtypeStruct((512, 256), jnp.float32),
            )(a, b)
            emit(probe="pallas_kernel_dot", tier=tier,
                 rel_err=rel_err(d, ref))
        except Exception as e:   # noqa: BLE001
            emit(probe="pallas_kernel_dot", tier=tier,
                 error=f"{type(e).__name__}: {e}"[:250])

    # 4. the actual failing entry points at each tier
    x = rng.normal(size=(300, 70)).astype(np.float32)
    y = rng.normal(size=(150, 70)).astype(np.float32)
    l2_ref = ((x[:, None, :].astype(np.float64)
               - y[None, :, :].astype(np.float64)) ** 2).sum(-1)
    for tier in ("default", "high", "highest"):
        try:
            raft_tpu.set_matmul_precision(tier)
            d = C.pairwise_l2_pallas(x, y)
            emit(probe="pairwise_l2", tier=tier, rel_err=rel_err(d, l2_ref))
        except Exception as e:   # noqa: BLE001
            emit(probe="pairwise_l2", tier=tier,
                 error=f"{type(e).__name__}: {e}"[:250])

    # 5. fused_lloyd sums vs oracle built from ITS OWN labels (r2 failure
    # showed 27% rel on sums — label-independent check of the one-hot
    # accumulation path)
    try:
        raft_tpu.set_matmul_precision("high")
        xs = rng.normal(size=(1000, 33)).astype(np.float32)
        ys = rng.normal(size=(37, 33)).astype(np.float32)
        sums, counts, val, idx = C.fused_lloyd_pallas(xs, ys)
        lab = np.asarray(idx)
        sums_ref = np.zeros((37, 33), np.float64)
        np.add.at(sums_ref, lab, xs.astype(np.float64))
        bad = np.abs(np.asarray(sums, np.float64) - sums_ref)
        emit(probe="fused_lloyd_sums", tier="high",
             max_abs_err=float(bad.max()),
             count_ok=bool((np.asarray(counts)
                            == np.bincount(lab, minlength=37)).all()))
    except Exception as e:   # noqa: BLE001
        emit(probe="fused_lloyd_sums", error=f"{type(e).__name__}: {e}"[:250])

    # 6. argmin agreement at 'high' on the small failing shape
    try:
        raft_tpu.set_matmul_precision("high")
        xa = rng.normal(size=(257, 19)).astype(np.float32)
        ya = rng.normal(size=(31, 19)).astype(np.float32)
        dref = ((xa[:, None, :].astype(np.float64)
                 - ya[None, :, :].astype(np.float64)) ** 2).sum(-1)
        val, idx = C.fused_l2_argmin_pallas(xa, ya)
        agree = float((np.asarray(idx) == dref.argmin(1)).mean())
        emit(probe="fused_argmin_small", tier="high", agreement=agree)
    except Exception as e:   # noqa: BLE001
        emit(probe="fused_argmin_small",
             error=f"{type(e).__name__}: {e}"[:250])


if __name__ == "__main__":
    main()
