"""Deviceless v5e AOT preflight: compile every bench-critical entry
point against the real TPU toolchain WITHOUT the chip or tunnel
(ci/aot_compile.py). Run before arming the battery — a case that fails
here WILL fail on hardware with the same Mosaic error.

Each case compiles in a subprocess (a compiler SIGABRT must not kill the
sweep). Exit code 0 iff every case compiles.

Usage:  python ci/aot_preflight.py [case ...]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HDR = """
import sys; sys.path.insert(0, %r)
import functools
import numpy as np
import jax, jax.numpy as jnp
from ci.aot_compile import tpu_aot_compile, tpu_struct
""" % os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CASES = {
    # -- the north star: fused Lloyd at the headline shape, tier high --
    "lloyd_northstar": HDR + """
import raft_tpu
from raft_tpu.cluster.kmeans import lloyd_step
raft_tpu.set_matmul_precision("high")
f = functools.partial(lloyd_step, n_clusters=1024)
tpu_aot_compile(f, ((1 << 20, 128), jnp.float32), ((1024, 128),
                jnp.float32))
print("PRE_OK")
""",
    # -- kNN at the bench shape: fused path (k=64 one-vreg, k=200/256
    #    two-vreg best) + the chunked-radix fallback arm
    #    (k=512 > fused MAX_K) ------------------------------------------
    "knn_bench": HDR + """
import raft_tpu
from raft_tpu.neighbors import knn
raft_tpu.set_matmul_precision("high")
for k in (64, 200, 256, 512):
    f = functools.partial(knn, None, k=k)
    tpu_aot_compile(f, ((1 << 20, 128), jnp.float32),
                    ((4096, 128), jnp.float32))
print("PRE_OK")
""",
    # -- unexpanded pairwise metrics tile engine ----------------------
    "pairwise_unexpanded": HDR + """
from raft_tpu.linalg.contractions import pairwise_unexpanded_pallas
f = functools.partial(pairwise_unexpanded_pallas, metric="l1")
tpu_aot_compile(f, ((4096, 1024), jnp.float32), ((256, 1024),
                jnp.float32))
print("PRE_OK")
""",
    # -- select_k four ways at battery shapes -------------------------
    "select_k_paths": HDR + """
from raft_tpu.matrix.select_k import (_direct_select, _stream_select,
                                      _tiled_select)
from raft_tpu.matrix import radix_select
from raft_tpu.matrix.topk_insert import insert_select
for impl, L, k in ((_tiled_select, 65536, 256),
                   (_direct_select, 65536, 256),
                   (_stream_select, 65536, 256),
                   (insert_select, 65536, 256),
                   (insert_select, 65536, 64)):
    tpu_aot_compile(functools.partial(impl, k=k, select_min=True),
                    ((64, L), jnp.float32))
for L, k in ((8192, 16), (65536, 2048), (1 << 20, 10000),
             (1 << 22, 256)):
    tpu_aot_compile(functools.partial(radix_select.radix_select_k,
                                      k=k, select_min=True),
                    ((16, L), jnp.float32))
print("PRE_OK")
""",
    # -- grid SpMV / fused SpMM / lanczos-grid ------------------------
    "grid_sparse": HDR + """
import scipy.sparse as sp
from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.sparse import grid_spmv
rng = np.random.default_rng(0)
n = 1 << 15
deg = 10
cols = rng.integers(0, n, size=(n, deg)).astype(np.int32)
data = rng.random((n, deg)).astype(np.float32)
indptr = np.arange(n + 1, dtype=np.int64) * deg
a = sp.csr_matrix((data.ravel(), cols.ravel(), indptr), shape=(n, n))
plan = grid_spmv.prepare(CSRMatrix.from_scipy(a))
jax.jit(grid_spmv.spmv).lower(plan, tpu_struct((n,), jnp.float32)
                              ).compile()
jax.jit(grid_spmv.spmm).lower(plan, tpu_struct((n, 16), jnp.float32)
                              ).compile()
# the WIDE auto-shard variant (512-row unrolled tree) that full-scale
# benches pick — a narrow-only preflight would miss its failures
plan_w = grid_spmv.prepare(CSRMatrix.from_scipy(a),
                           shard_w=grid_spmv.SHARD_W_MAX)
jax.jit(grid_spmv.spmv).lower(plan_w, tpu_struct((n,), jnp.float32)
                              ).compile()
print("PRE_OK")
""",
    # -- MST grid E-stage ---------------------------------------------
    "mst_grid": HDR + """
import scipy.sparse as sp
from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.sparse.solver import mst_grid
rng = np.random.default_rng(0)
n = 1 << 13
m = 6 * n
r = rng.integers(0, n, m); c = rng.integers(0, n, m)
keep = r != c
r, c = r[keep], c[keep]
w = rng.random(len(r)).astype(np.float32)
a = sp.csr_matrix((np.concatenate([w, w]),
                   (np.concatenate([r, c]), np.concatenate([c, r]))),
                  shape=(n, n))
a.sum_duplicates()
mp = mst_grid.prepare_mst(CSRMatrix.from_scipy(a))
jax.jit(mst_grid.per_vertex_min_edge).lower(
    mp, tpu_struct((n,), jnp.int32)).compile()
print("PRE_OK")
""",
    # -- segment SpMV + ELL (the baselines the bench compares) --------
    "sparse_baselines": HDR + """
import scipy.sparse as sp
from raft_tpu.core.sparse_types import CSRMatrix
from raft_tpu.sparse.ell import from_csr, spmv as ell_spmv
from raft_tpu.sparse.linalg import _segment_spmv
rng = np.random.default_rng(0)
n = 1 << 14
deg = 10
cols = rng.integers(0, n, size=(n, deg)).astype(np.int32)
data = rng.random((n, deg)).astype(np.float32)
indptr = np.arange(n + 1, dtype=np.int64) * deg
a = sp.csr_matrix((data.ravel(), cols.ravel(), indptr), shape=(n, n))
csr = CSRMatrix.from_scipy(a)
ell = from_csr(csr)
rid = csr.row_ids()
def seg(r, i, d, v):
    return _segment_spmv(r, i, d, v, csr.n_rows, limit=csr.indptr[-1])
jax.jit(seg).lower(tpu_struct(rid.shape, rid.dtype),
                   tpu_struct(csr.indices.shape, csr.indices.dtype),
                   tpu_struct(csr.data.shape, csr.data.dtype),
                   tpu_struct((n,), jnp.float32)).compile()
jax.jit(lambda v: ell_spmv(ell, v)).lower(
    tpu_struct((n,), jnp.float32)).compile()
print("PRE_OK")
""",
    # -- histogram strategies + keyed rowsum --------------------------
    "stats_kernels": HDR + """
from raft_tpu.stats import histogram
from raft_tpu.stats.histogram import HistType
f1 = functools.partial(histogram, n_bins=64,
                       binner=lambda v, r, c: v * 64,
                       hist_type=HistType.Smem)
f2 = functools.partial(histogram, n_bins=2048,
                       binner=lambda v, r, c: v * 2048)
tpu_aot_compile(f1, ((1 << 18, 8), jnp.float32))
tpu_aot_compile(f2, ((1 << 18, 8), jnp.float32))
print("PRE_OK")
""",
}


def run_case(name):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_SKIP_MDS_QUERY"] = "1"
    env["TPU_ACCELERATOR_TYPE"] = "v5litepod-1"
    env["RAFT_TPU_PALLAS_INTERPRET"] = "0"
    try:
        r = subprocess.run([sys.executable, "-c", CASES[name]],
                           capture_output=True, text=True, timeout=1200,
                           env=env)
    except subprocess.TimeoutExpired:
        print(json.dumps({"case": name, "ok": False, "key": "timeout"}),
              flush=True)
        return False
    ok = r.returncode == 0 and "PRE_OK" in (r.stdout or "")
    key = ""
    if not ok:
        for line in (r.stderr or "").splitlines():
            if ("Not implemented" in line or "Check failed" in line
                    or "RESOURCE_EXHAUSTED" in line
                    or "INTERNAL" in line or "Invalid" in line
                    or "Error" in line):
                key = line.strip()[:250]
                break
    print(json.dumps({"case": name, "ok": ok,
                      "key": key if not ok else ""}), flush=True)
    return ok


if __name__ == "__main__":
    names = sys.argv[1:] or list(CASES)
    unknown = [n for n in names if n not in CASES]
    for n in unknown:
        print(json.dumps({"case": n, "ok": False, "key": "unknown case"}),
              flush=True)
    bad = [n for n in names if n in CASES and not run_case(n)]
    sys.exit(1 if (bad or unknown) else 0)
