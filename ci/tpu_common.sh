# Shared TPU-tunnel helpers, sourced by ci/tpu_battery.sh and
# ci/diag_then_battery.sh — ONE definition of "TPU reachable" so the
# gate and the battery can't drift apart.

# Persistent XLA compilation cache for every battery child process:
# matrix/select_k's four-way grid rc=124'd at 900 s with the whole
# budget in compiles (17:38 window, round 5). Caching executables
# across family processes and battery passes turns reruns into
# replays; if the backend can't serialize an executable the cache
# degrades to a no-op warning, never an error.
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/root/repo/.jax_cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-2}"

probe() {
    timeout -k 15 240 python -c "import jax; assert jax.default_backend()=='tpu'" \
        >/dev/null 2>&1
}

wait_for_tpu() {
    for i in $(seq 1 2000); do
        if probe; then
            echo "[tpu] reachable (attempt $i) $(date +%H:%M:%S)"
            return 0
        fi
        sleep 120
    done
    echo "[tpu] never came back; giving up"
    return 1
}
