# Shared TPU-tunnel helpers, sourced by ci/tpu_battery.sh and
# ci/diag_then_battery.sh — ONE definition of "TPU reachable" so the
# gate and the battery can't drift apart.

probe() {
    timeout -k 15 240 python -c "import jax; assert jax.default_backend()=='tpu'" \
        >/dev/null 2>&1
}

wait_for_tpu() {
    for i in $(seq 1 2000); do
        if probe; then
            echo "[tpu] reachable (attempt $i) $(date +%H:%M:%S)"
            return 0
        fi
        sleep 120
    done
    echo "[tpu] never came back; giving up"
    return 1
}
