#!/usr/bin/env python
"""Bench regression sentry (ISSUE 13): guard the BENCH history.

Reads the repo's bench artifacts — ``BENCH_r*.json`` (north-star rounds;
the measurement row lives under ``"parsed"``) and
``bench_small_cpu_r*.jsonl`` (per-bench JSONL rows) — applies the
era/``superseded_by`` provenance rules from ``benches/harness.py``, and
keeps the **best current-era row per bench family** as the baseline.

Two modes:

* **audit** (no ``--fresh``): parse everything, print the per-family
  baselines, exit 0. Exit 2 on unreadable/corrupt artifacts — a silent
  parse failure would hollow the gate out.
* **compare** (``--fresh FILE``, repeatable): every row in each fresh
  file is checked against its family baseline. Failures (exit 1):

  - regression beyond tolerance — ``median_ms`` rows fail when fresh >
    best × tol (lower is better); ``value`` rows (iters/sec) fail when
    fresh < best / tol (higher is better);
  - stale era — a fresh row whose era predates the newest era already
    shipped for its family is measuring a retired code path, never a
    valid pass;
  - rows carrying ``superseded_by`` are skipped (already retired by
    their own provenance), and families with no shipped baseline pass
    with a note.

Tolerance is a ratio (>= 1): ``--tol`` for the default (falls back to
the registered ``RAFT_TPU_SENTRY_TOL`` knob, default 1.5), and
``--family-tol FAMILY=RATIO`` (repeatable) per family — the shipped CPU
rounds drift up to ~2x across container sessions, so per-family
tightening is how a stable family gets a real gate without the noisy
ones crying wolf.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _is_current_row(d: dict, newest_era: int) -> bool:
    """benches.harness.is_current_row, inlined as the fallback for
    environments where the benches package (which imports jax) cannot
    load; the import below overrides this with the canonical one."""
    if d.get("superseded_by"):
        return False
    return int(d.get("era", 0) or 0) >= newest_era


try:                                      # canonical provenance rules
    from benches.harness import is_current_row
except Exception:                         # no jax in this interpreter
    is_current_row = _is_current_row


def _default_tol() -> float:
    """RAFT_TPU_SENTRY_TOL via the registered env knob (fail-loud on a
    malformed value), with a registry-free fallback mirroring the same
    contract."""
    try:
        from raft_tpu.core import env as _env_mod
        return float(_env_mod.read("RAFT_TPU_SENTRY_TOL"))
    except (ImportError, KeyError):
        raw = os.environ.get("RAFT_TPU_SENTRY_TOL", "")
        if not raw:
            return 1.5
        val = float(raw)                  # malformed raises — fail loud
        if not val >= 1.0:
            raise ValueError(
                f"RAFT_TPU_SENTRY_TOL: tolerance ratio must be >= 1.0, "
                f"got {raw!r}")
        return val


# ---------------------------------------------------------------------------
# row model: one measurement with a family key and a direction
# ---------------------------------------------------------------------------

def family_of(row: dict):
    """Family key + (metric value, higher_is_better) for one row, or
    None for rows that are not measurements (markers, notes)."""
    backend = row.get("backend")
    if "bench" in row and row.get("median_ms") is not None:
        fam = str(row["bench"]) + (f"@{backend}" if backend else "")
        return fam, float(row["median_ms"]), False
    if "metric" in row and row.get("value") is not None:
        fam = str(row["metric"]) + (f"@{backend}" if backend else "")
        return fam, float(row["value"]), True
    return None


def load_rows(path: str):
    """Rows from one artifact: a BENCH_r*.json round (dict with a
    ``parsed`` measurement) or a JSONL file (one row per line).
    Raises on unreadable/corrupt input — the gate must fail loud."""
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    stripped = text.lstrip()
    rows = []
    if stripped.startswith("{") and "\n{" not in stripped.strip():
        doc = json.loads(text)
        parsed = doc.get("parsed")
        if isinstance(parsed, dict):
            rows.append(parsed)
        elif family_of(doc):
            rows.append(doc)
        return rows
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}:{ln}: bad JSON row: {e}") from None
        if isinstance(row, dict):
            rows.append(row)
    return rows


def collect_history(history_dir: str):
    """(families, newest_era_by_family): per family, the best current
    row (after provenance filtering) and the newest era shipped."""
    paths = sorted(glob.glob(os.path.join(history_dir, "BENCH_r*.json")))
    paths += sorted(glob.glob(os.path.join(history_dir,
                                           "bench_small_cpu_r*.jsonl")))
    measured = []                         # (family, val, higher, era, row)
    for path in paths:
        for row in load_rows(path):
            fam = family_of(row)
            if fam is None:
                continue
            name, val, higher = fam
            measured.append((name, val, higher,
                             int(row.get("era", 0) or 0), row))
    newest_era = {}
    for name, _, _, era, row in measured:
        if not row.get("superseded_by"):
            newest_era[name] = max(newest_era.get(name, 0), era)
    best = {}
    for name, val, higher, _, row in measured:
        if not is_current_row(row, newest_era.get(name, 0)):
            continue
        cur = best.get(name)
        if cur is None or (val > cur[0] if higher else val < cur[0]):
            best[name] = (val, higher)
    return best, newest_era


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------

def check_fresh(rows, best, newest_era, tol: float, family_tol: dict):
    """Yield (level, message) findings; level 'fail' trips the gate."""
    for row in rows:
        fam = family_of(row)
        if fam is None:
            continue
        name, val, higher = fam
        if row.get("superseded_by"):
            yield ("note", f"{name}: fresh row is superseded by "
                           f"{row['superseded_by']!r}; skipped")
            continue
        base = best.get(name)
        if base is None:
            yield ("note", f"{name}: no shipped baseline; passes by "
                           f"default")
            continue
        era = int(row.get("era", 0) or 0)
        newest = newest_era.get(name, 0)
        if era < newest:
            yield ("fail", f"{name}: fresh row is era {era} but the "
                           f"shipped history is already era {newest} — "
                           f"a stale-era measurement cannot gate "
                           f"anything")
            continue
        base_val, _ = base
        t = family_tol.get(name, tol)
        if higher:
            floor = base_val / t
            if val < floor:
                yield ("fail", f"{name}: {val:g} is below the best "
                               f"current-era baseline {base_val:g} / "
                               f"tol {t:g} = {floor:g} "
                               f"(higher is better)")
            else:
                yield ("ok", f"{name}: {val:g} vs baseline "
                             f"{base_val:g} (tol {t:g})")
        else:
            ceil = base_val * t
            if val > ceil:
                yield ("fail", f"{name}: {val:g} ms exceeds the best "
                               f"current-era baseline {base_val:g} ms "
                               f"x tol {t:g} = {ceil:g} ms "
                               f"(lower is better)")
            else:
                yield ("ok", f"{name}: {val:g} ms vs baseline "
                             f"{base_val:g} ms (tol {t:g})")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--history", default=REPO_ROOT,
                    help="directory holding BENCH_r*.json / "
                         "bench_small_cpu_r*.jsonl (default: repo root)")
    ap.add_argument("--fresh", action="append", default=[],
                    help="fresh result file (JSONL rows or a BENCH "
                         "round artifact) to compare; repeatable")
    ap.add_argument("--tol", type=float, default=None,
                    help="default tolerance ratio >= 1 (default: the "
                         "RAFT_TPU_SENTRY_TOL knob, 1.5)")
    ap.add_argument("--family-tol", action="append", default=[],
                    metavar="FAMILY=RATIO",
                    help="per-family tolerance override; repeatable")
    args = ap.parse_args(argv)

    try:
        tol = args.tol if args.tol is not None else _default_tol()
        if not tol >= 1.0:
            raise ValueError(f"--tol must be >= 1.0, got {tol}")
        family_tol = {}
        for spec in args.family_tol:
            name, sep, ratio = spec.rpartition("=")
            if not sep or not name:
                raise ValueError(
                    f"--family-tol wants FAMILY=RATIO, got {spec!r}")
            r = float(ratio)
            if not r >= 1.0:
                raise ValueError(
                    f"--family-tol ratio must be >= 1.0, got {spec!r}")
            family_tol[name] = r
        best, newest_era = collect_history(args.history)
    except (OSError, ValueError) as e:
        print(f"perf_sentry: ERROR: {e}", file=sys.stderr)
        return 2

    if not best:
        print(f"perf_sentry: ERROR: no bench history under "
              f"{args.history}", file=sys.stderr)
        return 2

    if not args.fresh:
        print(f"perf_sentry: audit of {len(best)} bench families "
              f"(best current-era baselines):")
        for name in sorted(best):
            val, higher = best[name]
            unit = "" if higher else " ms"
            era = newest_era.get(name, 0)
            print(f"  {name}: {val:g}{unit} (era {era}, "
                  f"{'higher' if higher else 'lower'} is better)")
        print("perf_sentry: PASS (audit)")
        return 0

    failures = 0
    for path in args.fresh:
        try:
            rows = load_rows(path)
        except (OSError, ValueError) as e:
            print(f"perf_sentry: ERROR: {e}", file=sys.stderr)
            return 2
        for level, msg in check_fresh(rows, best, newest_era, tol,
                                      family_tol):
            tag = {"fail": "FAIL", "ok": "ok", "note": "note"}[level]
            print(f"perf_sentry: {tag}: {msg}")
            failures += level == "fail"
    if failures:
        print(f"perf_sentry: FAIL ({failures} regression(s))",
              file=sys.stderr)
        return 1
    print("perf_sentry: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
