// raft_tpu native host runtime (C++17, no external deps).
//
// TPU-native equivalents of the reference's native host-side runtime
// (SURVEY.md §2.1/§2.2): the pieces that are C++ in RAFT and must be C++
// here — the memory-resource layer (raft/mr/: statistics_adaptor.hpp:25,
// notifying_adaptor.hpp:25, resource_monitor.hpp:29-66,
// mmap_memory_resource.hpp:31, cpp/src/util/memory_pool.cpp), the
// cooperative-cancellation registry (core/interruptible.hpp:63-110), the
// .npy serializer core (core/detail/mdspan_numpy_serializer.hpp), and a
// worker-pool executor standing in for the handle's stream pool
// (core/resource/cuda_stream_pool.hpp) for host-side IO/copy jobs.
//
// Exposed as a flat C ABI consumed from Python via ctypes (the repo's
// pybind11-free binding policy).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <sys/mman.h>
#include <unistd.h>

#define RT_EXPORT extern "C" __attribute__((visibility("default")))

// ---------------------------------------------------------------------------
// Tracked host memory pool
// (ref: mr/statistics_adaptor.hpp — bytes/alloc counters wrapping an
//  upstream resource; mr/mmap_memory_resource.hpp — mmap-backed host
//  allocations; cpp/src/util/memory_pool.cpp — pool helper)
// ---------------------------------------------------------------------------

namespace {

struct PoolStats {
  std::atomic<int64_t> bytes_allocated{0};
  std::atomic<int64_t> peak_bytes{0};
  std::atomic<int64_t> n_allocations{0};
  std::atomic<int64_t> n_deallocations{0};
};

struct Pool {
  PoolStats stats;
  std::mutex lock;
  std::map<void*, size_t> live;  // ptr -> size
  bool use_mmap = false;
  // notifying_adaptor hook (ref: mr/notifying_adaptor.hpp:25,77):
  // called as fn(is_alloc, nbytes, user_data) after every event.
  void (*notify_cb)(int, int64_t, void*) = nullptr;
  void* notify_data = nullptr;
};

void bump_peak(PoolStats& s) {
  int64_t cur = s.bytes_allocated.load();
  int64_t prev = s.peak_bytes.load();
  while (cur > prev && !s.peak_bytes.compare_exchange_weak(prev, cur)) {
  }
}

}  // namespace

RT_EXPORT void* rt_pool_create(int use_mmap) {
  auto* p = new Pool();
  p->use_mmap = use_mmap != 0;
  return p;
}

RT_EXPORT void rt_pool_destroy(void* pool) {
  auto* p = static_cast<Pool*>(pool);
  std::lock_guard<std::mutex> g(p->lock);
  for (auto& kv : p->live) {
    if (p->use_mmap) {
      munmap(kv.first, kv.second);
    } else {
      std::free(kv.first);
    }
  }
  p->live.clear();
  delete p;
}

RT_EXPORT void* rt_pool_alloc(void* pool, int64_t nbytes) {
  auto* p = static_cast<Pool*>(pool);
  void* ptr = nullptr;
  if (p->use_mmap) {
    ptr = mmap(nullptr, static_cast<size_t>(nbytes), PROT_READ | PROT_WRITE,
               MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (ptr == MAP_FAILED) return nullptr;
  } else {
    ptr = std::malloc(static_cast<size_t>(nbytes));
    if (ptr == nullptr) return nullptr;
  }
  {
    std::lock_guard<std::mutex> g(p->lock);
    p->live[ptr] = static_cast<size_t>(nbytes);
  }
  p->stats.bytes_allocated += nbytes;
  p->stats.n_allocations += 1;
  bump_peak(p->stats);
  if (p->notify_cb) p->notify_cb(1, nbytes, p->notify_data);
  return ptr;
}

RT_EXPORT int rt_pool_dealloc(void* pool, void* ptr) {
  auto* p = static_cast<Pool*>(pool);
  size_t nbytes = 0;
  {
    std::lock_guard<std::mutex> g(p->lock);
    auto it = p->live.find(ptr);
    if (it == p->live.end()) return -1;
    nbytes = it->second;
    p->live.erase(it);
  }
  if (p->use_mmap) {
    munmap(ptr, nbytes);
  } else {
    std::free(ptr);
  }
  p->stats.bytes_allocated -= static_cast<int64_t>(nbytes);
  p->stats.n_deallocations += 1;
  if (p->notify_cb) p->notify_cb(0, static_cast<int64_t>(nbytes),
                                 p->notify_data);
  return 0;
}

RT_EXPORT void rt_pool_stats(void* pool, int64_t* out4) {
  auto* p = static_cast<Pool*>(pool);
  out4[0] = p->stats.bytes_allocated.load();
  out4[1] = p->stats.peak_bytes.load();
  out4[2] = p->stats.n_allocations.load();
  out4[3] = p->stats.n_deallocations.load();
}

RT_EXPORT void rt_pool_set_notify(void* pool,
                                  void (*cb)(int, int64_t, void*),
                                  void* user_data) {
  auto* p = static_cast<Pool*>(pool);
  p->notify_cb = cb;
  p->notify_data = user_data;
}

// ---------------------------------------------------------------------------
// Resource monitor: background sampler -> CSV
// (ref: mr/resource_monitor.hpp:29-66 — thread samples allocation stats on
//  an interval, each row tagged with the active trace range)
// ---------------------------------------------------------------------------

namespace {

struct Monitor {
  Pool* pool;
  std::FILE* out;
  int interval_ms;
  std::thread worker;
  std::atomic<bool> stop{false};
  std::mutex tag_lock;
  std::string tag;
};

}  // namespace

RT_EXPORT void* rt_monitor_start(void* pool, const char* csv_path,
                                 int interval_ms) {
  auto* m = new Monitor();
  m->pool = static_cast<Pool*>(pool);
  m->out = std::fopen(csv_path, "w");
  if (m->out == nullptr) {
    delete m;
    return nullptr;
  }
  std::fprintf(m->out, "timestamp_us,tag,bytes,peak_bytes,allocs,deallocs\n");
  m->interval_ms = interval_ms;
  m->worker = std::thread([m]() {
    while (!m->stop.load()) {
      int64_t s[4];
      rt_pool_stats(m->pool, s);
      auto now = std::chrono::duration_cast<std::chrono::microseconds>(
                     std::chrono::system_clock::now().time_since_epoch())
                     .count();
      std::string tag;
      {
        std::lock_guard<std::mutex> g(m->tag_lock);
        tag = m->tag;
      }
      std::fprintf(m->out, "%lld,%s,%lld,%lld,%lld,%lld\n",
                   static_cast<long long>(now), tag.c_str(),
                   static_cast<long long>(s[0]), static_cast<long long>(s[1]),
                   static_cast<long long>(s[2]), static_cast<long long>(s[3]));
      std::fflush(m->out);
      std::this_thread::sleep_for(std::chrono::milliseconds(m->interval_ms));
    }
  });
  return m;
}

RT_EXPORT void rt_monitor_set_tag(void* monitor, const char* tag) {
  auto* m = static_cast<Monitor*>(monitor);
  std::lock_guard<std::mutex> g(m->tag_lock);
  m->tag = tag ? tag : "";
}

RT_EXPORT void rt_monitor_stop(void* monitor) {
  auto* m = static_cast<Monitor*>(monitor);
  m->stop.store(true);
  if (m->worker.joinable()) m->worker.join();
  std::fclose(m->out);
  delete m;
}

// ---------------------------------------------------------------------------
// Cooperative cancellation registry
// (ref: core/interruptible.hpp:63-110 — one token per thread id,
//  cancel() flips it, synchronize() polls and throws)
// ---------------------------------------------------------------------------

namespace {
std::mutex g_tok_lock;
std::map<int64_t, std::atomic<int>*> g_tokens;

std::atomic<int>* token_for(int64_t tid) {
  std::lock_guard<std::mutex> g(g_tok_lock);
  auto it = g_tokens.find(tid);
  if (it == g_tokens.end()) {
    auto* t = new std::atomic<int>(0);
    g_tokens[tid] = t;
    return t;
  }
  return it->second;
}
}  // namespace

RT_EXPORT void rt_interruptible_cancel(int64_t tid) {
  token_for(tid)->store(1);
}

// Returns 1 and clears if the token was cancelled (flag-consuming check).
RT_EXPORT int rt_interruptible_check(int64_t tid) {
  return token_for(tid)->exchange(0);
}

RT_EXPORT int rt_interruptible_cancelled(int64_t tid) {
  return token_for(tid)->load();
}

// ---------------------------------------------------------------------------
// .npy serializer core
// (ref: core/detail/mdspan_numpy_serializer.hpp — header build/parse;
//  the heavy path, bulk IO, belongs in native code)
// ---------------------------------------------------------------------------

namespace {

std::string npy_header(const char* descr, const int64_t* shape, int ndim) {
  std::string dict = "{'descr': '";
  dict += descr;
  dict += "', 'fortran_order': False, 'shape': (";
  for (int i = 0; i < ndim; ++i) {
    dict += std::to_string(shape[i]);
    dict += (ndim == 1 || i + 1 < ndim) ? "," : "";
    if (i + 1 < ndim) dict += " ";
  }
  dict += "), }";
  // pad with spaces so total header size (magic 8 + 2 len + dict + \n) % 64 == 0
  size_t base = 10 + dict.size() + 1;
  size_t pad = (64 - base % 64) % 64;
  dict += std::string(pad, ' ');
  dict += '\n';
  std::string out = "\x93NUMPY";
  out += '\x01';
  out += '\x00';
  uint16_t hlen = static_cast<uint16_t>(dict.size());
  out += static_cast<char>(hlen & 0xff);
  out += static_cast<char>((hlen >> 8) & 0xff);
  out += dict;
  return out;
}

}  // namespace

RT_EXPORT int rt_npy_write(const char* path, const char* descr,
                           const int64_t* shape, int ndim, const void* data,
                           int64_t nbytes) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  std::string hdr = npy_header(descr, shape, ndim);
  if (std::fwrite(hdr.data(), 1, hdr.size(), f) != hdr.size()) {
    std::fclose(f);
    return -2;
  }
  if (nbytes > 0 &&
      std::fwrite(data, 1, static_cast<size_t>(nbytes), f) !=
          static_cast<size_t>(nbytes)) {
    std::fclose(f);
    return -3;
  }
  std::fclose(f);
  return 0;
}

// Parses the header; returns data offset, fills descr (caller buffer of 16),
// shape (caller buffer of 32), ndim and fortran_order. Returns <0 on error.
RT_EXPORT int64_t rt_npy_read_header(const char* path, char* descr,
                                     int64_t* shape, int* ndim,
                                     int* fortran_order) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char magic[8];
  if (std::fread(magic, 1, 8, f) != 8 || std::memcmp(magic, "\x93NUMPY", 6)) {
    std::fclose(f);
    return -2;
  }
  unsigned char lenb[2];
  if (std::fread(lenb, 1, 2, f) != 2) {
    std::fclose(f);
    return -3;
  }
  size_t hlen = lenb[0] | (lenb[1] << 8);
  std::string dict(hlen, '\0');
  if (std::fread(dict.data(), 1, hlen, f) != hlen) {
    std::fclose(f);
    return -4;
  }
  std::fclose(f);
  *fortran_order = dict.find("'fortran_order': True") != std::string::npos;
  auto dpos = dict.find("'descr':");
  auto q1 = dict.find('\'', dpos + 8);
  auto q2 = dict.find('\'', q1 + 1);
  std::string d = dict.substr(q1 + 1, q2 - q1 - 1);
  std::snprintf(descr, 16, "%s", d.c_str());
  auto spos = dict.find("'shape':");
  auto p1 = dict.find('(', spos);
  auto p2 = dict.find(')', p1);
  std::string tup = dict.substr(p1 + 1, p2 - p1 - 1);
  int n = 0;
  const char* s = tup.c_str();
  while (*s && n < 32) {
    while (*s == ' ' || *s == ',') ++s;
    if (!*s) break;
    shape[n++] = std::strtoll(s, const_cast<char**>(&s), 10);
  }
  *ndim = n;
  return static_cast<int64_t>(10 + hlen);
}

RT_EXPORT int rt_npy_read_data(const char* path, int64_t offset, void* out,
                               int64_t nbytes) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  if (std::fseek(f, static_cast<long>(offset), SEEK_SET) != 0) {
    std::fclose(f);
    return -2;
  }
  size_t got = std::fread(out, 1, static_cast<size_t>(nbytes), f);
  std::fclose(f);
  return got == static_cast<size_t>(nbytes) ? 0 : -3;
}

// ---------------------------------------------------------------------------
// Worker-pool executor
// (stream-pool analogue for host jobs: core/resource/cuda_stream_pool.hpp;
//  submit(fn) → future-like handle; used for parallel chunked IO/copies)
// ---------------------------------------------------------------------------

namespace {

struct ThreadPool {
  std::vector<std::thread> workers;
  std::deque<std::function<void()>> jobs;
  std::mutex lock;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::atomic<int64_t> submitted{0};
  std::atomic<int64_t> completed{0};
  bool stop = false;

  explicit ThreadPool(int n) {
    for (int i = 0; i < n; ++i) {
      workers.emplace_back([this]() {
        for (;;) {
          std::function<void()> job;
          {
            std::unique_lock<std::mutex> g(lock);
            cv.wait(g, [this]() { return stop || !jobs.empty(); });
            if (stop && jobs.empty()) return;
            job = std::move(jobs.front());
            jobs.pop_front();
          }
          job();
          {
            // increment under the mutex or a waiter that just evaluated
            // the predicate could miss this notify (lost wakeup)
            std::lock_guard<std::mutex> g(lock);
            completed += 1;
          }
          done_cv.notify_all();
        }
      });
    }
  }

  ~ThreadPool() {
    {
      std::lock_guard<std::mutex> g(lock);
      stop = true;
    }
    cv.notify_all();
    for (auto& w : workers) w.join();
  }

  void submit(std::function<void()> job) {
    {
      std::lock_guard<std::mutex> g(lock);
      jobs.push_back(std::move(job));
      // under the mutex, like `completed` — otherwise a waiter can observe
      // completed == submitted+1, re-sleep, and miss the final notify
      submitted += 1;
    }
    cv.notify_one();
  }

  void wait_all() {
    std::unique_lock<std::mutex> g(lock);
    done_cv.wait(g, [this]() { return completed.load() == submitted.load(); });
  }
};

}  // namespace

RT_EXPORT void* rt_threadpool_create(int n_threads) {
  if (n_threads <= 0) {
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 4;
  }
  return new ThreadPool(n_threads);
}

RT_EXPORT void rt_threadpool_destroy(void* tp) {
  delete static_cast<ThreadPool*>(tp);
}

// Parallel memcpy: splits [nbytes] into chunks over the pool.
RT_EXPORT void rt_threadpool_memcpy(void* tp, void* dst, const void* src,
                                    int64_t nbytes, int64_t chunk) {
  auto* pool = static_cast<ThreadPool*>(tp);
  if (chunk <= 0) chunk = 8 << 20;
  for (int64_t off = 0; off < nbytes; off += chunk) {
    int64_t n = std::min(chunk, nbytes - off);
    char* d = static_cast<char*>(dst) + off;
    const char* s = static_cast<const char*>(src) + off;
    pool->submit([d, s, n]() { std::memcpy(d, s, static_cast<size_t>(n)); });
  }
  pool->wait_all();
}

// Generic job submission via C callback (for Python-driven pipelines).
RT_EXPORT void rt_threadpool_submit(void* tp, void (*fn)(void*), void* arg) {
  static_cast<ThreadPool*>(tp)->submit([fn, arg]() { fn(arg); });
}

RT_EXPORT void rt_threadpool_wait(void* tp) {
  static_cast<ThreadPool*>(tp)->wait_all();
}

// ---------------------------------------------------------------------------
// Sparse slot-grid packer — the sequential hot loop of the grid-SpMV format
// builder (raft_tpu/sparse/grid_spmv.py; role of the cuSPARSE analysis/
// preprocessing step, ref sparse/detail/cusparse_wrappers.h SpMV_preprocess).
//
// Packs a row-sorted entry stream into (tile, sub-row, lane) slots under the
// kernel's structural rules:
//   - a tile is 8 sub-rows x 128 lanes;
//   - a row's entries within a sub-row are contiguous (one run piece);
//   - a run piece crosses into the next sub-row only when it fills the
//     current one to lane 127 (the kernel's cross-sub-row carry contract);
//   - all rows in a tile lie within `span_windows` 128-row windows of the
//     tile's base window (the emission target range);
//   - otherwise the sub-row (or tile) is padded out and a new one begins.
//
// Writes slot_src[pos] = source entry index (or -1 for padding) and
// tile_base[t] = base row-window per tile. Returns the slot count (a
// multiple of 1024), or -1 if `cap` would be exceeded (caller re-sizes).
RT_EXPORT int64_t rt_spmv_pack(const int32_t* row, int64_t nnz,
                               int32_t span_windows, int32_t* slot_src,
                               int64_t cap, int32_t* tile_base,
                               int64_t tile_cap) {
  const int64_t kTile = 1024, kLane = 128;
  int64_t pos = 0;
  int32_t base = -1;
  int64_t i = 0;
  auto ensure = [&](int64_t need) { return pos + need <= cap; };
  while (i < nnz) {
    int32_t r = row[i];
    int64_t j = i;
    while (j < nnz && row[j] == r) ++j;
    int64_t run = j - i;
    while (run > 0) {
      if (pos % kTile == 0) base = -1;
      if (base < 0) {
        if (pos / kTile >= tile_cap) return -1;
        base = r >> 7;
        tile_base[pos / kTile] = base;
      }
      if ((r >> 7) - base >= span_windows) {
        // row outside the tile's emission range: pad to the tile edge
        int64_t pad = kTile - (pos % kTile);
        if (!ensure(pad)) return -1;
        for (int64_t p = 0; p < pad; ++p) slot_src[pos++] = -1;
        continue;
      }
      int64_t lane = pos % kLane;
      int64_t rem = kLane - lane;
      if (run <= rem) {
        if (!ensure(run)) return -1;
        for (int64_t p = 0; p < run; ++p) slot_src[pos++] = (int32_t)(i++);
        run = 0;
      } else if (lane == 0) {
        // fill the whole sub-row; the piece chains into the next one
        if (!ensure(kLane)) return -1;
        for (int64_t p = 0; p < kLane; ++p) slot_src[pos++] = (int32_t)(i++);
        run -= kLane;
      } else {
        // piece would straddle mid-sub-row: pad to the sub-row edge
        if (!ensure(rem)) return -1;
        for (int64_t p = 0; p < rem; ++p) slot_src[pos++] = -1;
      }
    }
  }
  // pad the final partial tile
  int64_t tail = (kTile - pos % kTile) % kTile;
  if (!ensure(tail)) return -1;
  for (int64_t p = 0; p < tail; ++p) slot_src[pos++] = -1;
  return pos;
}

RT_EXPORT int rt_version() { return 1; }
