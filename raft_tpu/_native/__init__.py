"""Native host runtime: build-on-demand C++ core bound via ctypes.

The reference ships its host runtime as compiled C++ (`libraft.so`,
cpp/CMakeLists.txt:274-341) loaded by the `libraft` Python package's
`load_library()` (python/libraft/libraft/load.py:8-35). The analogue here
compiles `raft_tpu_native.cpp` with the ambient g++ on first use (cached
next to the source keyed by content hash) and binds the flat C ABI with
ctypes — no pybind11 dependency by design.

If no toolchain is available the import still succeeds with
``native_available() == False`` and pure-Python fallbacks take over
(mirroring the header-only vs compiled split of the reference).
"""

from __future__ import annotations

import contextlib
import ctypes
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "raft_tpu_native.cpp")
# Prebuilt artifact written by setup.py's build hook + its source digest
# sidecar (stale-detection: an edited .cpp must beat a cached binary).
_PREBUILT = os.path.join(_HERE, "libraft_tpu_native.so")
_PREBUILT_DIGEST = _PREBUILT + ".sha"

_lib = None        # None = not tried, False = build failed, else CDLL
_lib_err: str = ""
_lock = threading.Lock()


def source_digest() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:16]


def build_command(src: str, out: str) -> list:
    """The one true g++ invocation — shared with setup.py so the packaged
    and the on-demand artifacts can never be compiled differently."""
    return ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
            "-fvisibility=hidden", "-pthread", src, "-o", out]


def _build_and_load():
    global _lib, _lib_err
    digest = source_digest()
    # Prefer the prebuilt artifact shipped by the package build (setup.py's
    # build_py hook — the analogue of loading the packaged libraft.so,
    # ref python/libraft/libraft/load.py:8-35) — but only when its digest
    # sidecar matches the current source, so an edited .cpp falls through
    # to the on-demand content-hash dev build below.
    try:
        with open(_PREBUILT_DIGEST) as f:
            prebuilt_fresh = f.read().strip() == digest
    except OSError:
        prebuilt_fresh = False
    if prebuilt_fresh and os.path.exists(_PREBUILT):
        try:
            lib = ctypes.CDLL(_PREBUILT)
            _bind(lib)
            return lib
        except (OSError, AttributeError) as e:
            _lib_err = str(e)   # foreign-arch artifact → on-demand build
    so_path = os.path.join(_HERE, f"libraft_tpu_native_{digest}.so")
    if not os.path.exists(so_path):
        # pid-suffixed temp + atomic rename: concurrent builders (multi-rank
        # hosts, pytest-xdist) each write their own file and whoever renames
        # last wins with an identical artifact
        tmp = f"{so_path}.tmp{os.getpid()}"
        cmd = build_command(_SRC, tmp)
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True,
                           timeout=300)
            os.replace(tmp, so_path)
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                FileNotFoundError) as e:
            _lib_err = getattr(e, "stderr", str(e)) or str(e)
            return None
    try:
        lib = ctypes.CDLL(so_path)
        _bind(lib)
        _lib_err = ""    # a stale prebuilt error must not outlive success
    except OSError as e:
        # corrupt cached artifact: drop it so the next import rebuilds,
        # and report unavailable instead of raising out of get_lib()
        _lib_err = str(e)
        with contextlib.suppress(OSError):
            os.remove(so_path)
        return None
    return lib


def _bind(lib):
    c = ctypes
    lib.rt_pool_create.restype = c.c_void_p
    lib.rt_pool_create.argtypes = [c.c_int]
    lib.rt_pool_destroy.argtypes = [c.c_void_p]
    lib.rt_pool_alloc.restype = c.c_void_p
    lib.rt_pool_alloc.argtypes = [c.c_void_p, c.c_int64]
    lib.rt_pool_dealloc.restype = c.c_int
    lib.rt_pool_dealloc.argtypes = [c.c_void_p, c.c_void_p]
    lib.rt_pool_stats.argtypes = [c.c_void_p, c.POINTER(c.c_int64)]
    lib.rt_pool_set_notify.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
    lib.rt_monitor_start.restype = c.c_void_p
    lib.rt_monitor_start.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.rt_monitor_set_tag.argtypes = [c.c_void_p, c.c_char_p]
    lib.rt_monitor_stop.argtypes = [c.c_void_p]
    lib.rt_interruptible_cancel.argtypes = [c.c_int64]
    lib.rt_interruptible_check.restype = c.c_int
    lib.rt_interruptible_check.argtypes = [c.c_int64]
    lib.rt_interruptible_cancelled.restype = c.c_int
    lib.rt_interruptible_cancelled.argtypes = [c.c_int64]
    lib.rt_npy_write.restype = c.c_int
    lib.rt_npy_write.argtypes = [c.c_char_p, c.c_char_p,
                                 c.POINTER(c.c_int64), c.c_int, c.c_void_p,
                                 c.c_int64]
    lib.rt_npy_read_header.restype = c.c_int64
    lib.rt_npy_read_header.argtypes = [c.c_char_p, c.c_char_p,
                                       c.POINTER(c.c_int64),
                                       c.POINTER(c.c_int),
                                       c.POINTER(c.c_int)]
    lib.rt_npy_read_data.restype = c.c_int
    lib.rt_npy_read_data.argtypes = [c.c_char_p, c.c_int64, c.c_void_p,
                                     c.c_int64]
    lib.rt_threadpool_create.restype = c.c_void_p
    lib.rt_threadpool_create.argtypes = [c.c_int]
    lib.rt_threadpool_destroy.argtypes = [c.c_void_p]
    lib.rt_threadpool_memcpy.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                         c.c_int64, c.c_int64]
    lib.rt_threadpool_submit.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
    lib.rt_threadpool_wait.argtypes = [c.c_void_p]
    lib.rt_spmv_pack.restype = c.c_int64
    lib.rt_spmv_pack.argtypes = [c.POINTER(c.c_int32), c.c_int64, c.c_int32,
                                 c.POINTER(c.c_int32), c.c_int64,
                                 c.POINTER(c.c_int32), c.c_int64]
    lib.rt_version.restype = c.c_int


def get_lib():
    """The loaded native library, building it if necessary; None if no
    toolchain is available (callers fall back to Python)."""
    global _lib
    if _lib is None:
        with _lock:
            if _lib is None:
                # cache failure as False so a broken toolchain is probed
                # once, not on every call
                _lib = _build_and_load() or False
    return _lib or None


def native_available() -> bool:
    return get_lib() is not None


def build_error() -> str:
    return _lib_err
