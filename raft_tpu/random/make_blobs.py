"""Isotropic Gaussian cluster generator (ref: random/make_blobs.cuh,
kernel detail/make_blobs.cuh:88-160)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState


def make_blobs(res, state: RngState, n_rows: int, n_cols: int,
               n_clusters: int = 5, cluster_std: float = 1.0,
               center_box: Tuple[float, float] = (-10.0, 10.0),
               centers: Optional[jnp.ndarray] = None,
               shuffle: bool = True, dtype=jnp.float32):
    """Generate (X[n_rows, n_cols], labels[n_rows], centers).

    Matches the reference's semantics: centers drawn uniformly in
    ``center_box`` unless provided; points = center[label] + N(0, std);
    labels assigned in round-robin then shuffled.
    """
    kc, kl, kn, ks = jax.random.split(state.next_key(), 4)
    if centers is None:
        centers = jax.random.uniform(
            kc, (n_clusters, n_cols), dtype=dtype,
            minval=center_box[0], maxval=center_box[1])
    else:
        centers = jnp.asarray(centers, dtype=dtype)
        n_clusters = centers.shape[0]

    labels = jnp.arange(n_rows, dtype=jnp.int32) % n_clusters
    if shuffle:
        labels = jax.random.permutation(kl, labels)
    noise = jax.random.normal(kn, (n_rows, n_cols), dtype=dtype) * cluster_std
    X = centers[labels] + noise
    return X, labels, centers
