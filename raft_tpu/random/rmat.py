"""R-MAT (stochastic Kronecker) graph edge generator
(ref: random/rmat_rectangular_generator.cuh, detail kernels
rmat_rectangular_generator.cuh:23,67,127).

The reference walks ``r_scale`` quadrant-split bits per edge with one thread
per edge.  TPU formulation: the bit walk is a vectorized scan over bit
positions — all edges advance one bit per step, which XLA fuses into a tight
[n_edges]-wide loop with no gather irregularity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState


def rmat_rectangular_gen(res, state: RngState, r_scale: int, c_scale: int,
                         n_edges: int, theta=None, a: float = 0.57,
                         b: float = 0.19, c: float = 0.19,
                         dtype=jnp.int32):
    """Generate ``n_edges`` edges of a 2^r_scale × 2^c_scale R-MAT graph.

    ``theta`` may be a per-level [max_scale, 4] probability table (the
    reference's general API) or None to use the scalar (a,b,c,d) quadrant
    probabilities at every level.  Returns (src[n_edges], dst[n_edges]).
    """
    max_scale = max(r_scale, c_scale)
    if theta is None:
        d = 1.0 - (a + b + c)
        theta = jnp.tile(jnp.asarray([[a, b, c, d]], dtype=jnp.float32),
                         (max_scale, 1))
    else:
        theta = jnp.asarray(theta, dtype=jnp.float32).reshape(max_scale, 4)
    # Per-level quadrant thresholds for a 2-bit draw:
    #   P(hi_r=1) depends on whether we are past c_scale/r_scale (rectangle).
    u = jax.random.uniform(state.next_key(), (max_scale, n_edges),
                           dtype=jnp.float32)

    carry_dtype = jnp.int64 if (jnp.dtype(dtype).itemsize > 4 and
                                jax.config.jax_enable_x64) else jnp.int32
    if max(r_scale, c_scale) > 31 and carry_dtype == jnp.int32:
        raise ValueError("r_scale/c_scale > 31 requires an int64 dtype with "
                         "x64 enabled")

    def level(carry, inputs):
        src, dst = carry
        lvl, u_lvl = inputs
        t = theta[lvl]
        pa, pb, pc = t[0], t[1], t[2]
        # Rectangular handling (ref: gen_and_update_bits,
        # detail/rmat_rectangular_generator.cuh:23): the draw always uses the
        # full (a, a+b, a+b+c) CDF; when a dimension's scale is exhausted its
        # bit is simply dropped — no renormalization, preserving the marginal
        # distribution of the remaining dimension.
        r_active = lvl < r_scale
        c_active = lvl < c_scale
        # Draw quadrant: 0=a(0,0) 1=b(0,1) 2=c(1,0) 3=d(1,1)
        q = (jnp.where(u_lvl < pa, 0,
             jnp.where(u_lvl < pa + pb, 1,
             jnp.where(u_lvl < pa + pb + pc, 2, 3)))).astype(jnp.int32)
        r_bit = (q >> 1) & 1
        c_bit = q & 1
        src = jnp.where(r_active, (src << 1) | r_bit, src)
        dst = jnp.where(c_active, (dst << 1) | c_bit, dst)
        return (src, dst), None

    init = (jnp.zeros((n_edges,), dtype=carry_dtype),
            jnp.zeros((n_edges,), dtype=carry_dtype))
    (src, dst), _ = jax.lax.scan(
        level, init, (jnp.arange(max_scale, dtype=jnp.int32), u))
    return src.astype(dtype), dst.astype(dtype)
