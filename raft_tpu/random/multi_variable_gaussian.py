"""Multi-variable Gaussian sampling (ref: random/multi_variable_gaussian.cuh).

The reference decomposes the covariance with a selectable method
(``enum Decomposer { chol_decomp, jacobi, qr }``,
detail/multi_variable_gaussian.cuh:121) via cuSOLVER; here the same three
spellings map to `jnp.linalg` Cholesky / eigendecomposition / QR-of-sqrt.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState


class Decomposer(enum.Enum):
    CHOLESKY = "chol_decomp"
    JACOBI = "jacobi"      # symmetric eigendecomposition
    QR = "qr"


def multi_variable_gaussian(res, state: RngState, mean, cov, n_samples: int,
                            method: Decomposer = Decomposer.CHOLESKY,
                            dtype=jnp.float32):
    """Draw ``n_samples`` from N(mean, cov); returns [n_samples, dim]."""
    mean = jnp.asarray(mean, dtype=jnp.float32)
    cov = jnp.asarray(cov, dtype=jnp.float32)
    dim = mean.shape[0]

    if method == Decomposer.CHOLESKY:
        factor = jnp.linalg.cholesky(cov)
    elif method == Decomposer.JACOBI:
        w, v = jnp.linalg.eigh(cov)
        factor = v * jnp.sqrt(jnp.maximum(w, 0.0))[None, :]
    elif method == Decomposer.QR:
        # cov = (v sqrt(w))(v sqrt(w))^T; QR of the square root gives an
        # equivalent factor with orthogonal mixing, matching the reference's
        # qr decomposer semantics (any F with F F^T = cov works).
        w, v = jnp.linalg.eigh(cov)
        root = v * jnp.sqrt(jnp.maximum(w, 0.0))[None, :]
        q, r = jnp.linalg.qr(root.T)
        factor = r.T
    else:
        raise ValueError(f"unknown decomposer {method}")

    z = jax.random.normal(state.next_key(), (n_samples, dim),
                          dtype=jnp.float32)
    return (mean[None, :] + z @ factor.T).astype(dtype)
