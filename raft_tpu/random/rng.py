"""Random distribution generators (ref: random/rng.cuh:43-794).

Every function takes ``(res, state, shape, ...)`` and returns a fresh array;
``state`` is an :class:`RngState` whose subsequence advances per call, so
repeated calls produce independent streams (the reference's contract where
each kernel launch consumes a subsequence).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState

Shape = Union[int, Sequence[int]]


def _shape(shape: Shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(res, state: RngState, shape: Shape, low=0.0, high=1.0,
            dtype=jnp.float32):
    """U[low, high) (ref: rng.cuh uniform)."""
    return jax.random.uniform(state.next_key(), _shape(shape), dtype=dtype,
                              minval=low, maxval=high)


def uniform_int(res, state: RngState, shape: Shape, low: int, high: int,
                dtype=jnp.int32):
    """Integers in [low, high) (ref: rng.cuh uniformInt)."""
    return jax.random.randint(state.next_key(), _shape(shape), low, high,
                              dtype=dtype)


def normal(res, state: RngState, shape: Shape, mu=0.0, sigma=1.0,
           dtype=jnp.float32):
    return jax.random.normal(state.next_key(), _shape(shape),
                             dtype=dtype) * sigma + mu


def normal_int(res, state: RngState, shape: Shape, mu: int, sigma: int,
               dtype=jnp.int32):
    """Rounded normal (ref: rng.cuh normalInt)."""
    vals = jax.random.normal(state.next_key(), _shape(shape),
                             dtype=jnp.float32) * sigma + mu
    return jnp.round(vals).astype(dtype)


def normal_table(res, state: RngState, n_rows: int, mu_vec, sigma_vec,
                 dtype=jnp.float32):
    """Per-column mean/sigma normal table (ref: rng.cuh normalTable)."""
    mu_vec = jnp.asarray(mu_vec, dtype=dtype)
    sigma_vec = jnp.asarray(sigma_vec, dtype=dtype)
    n_cols = mu_vec.shape[0]
    z = jax.random.normal(state.next_key(), (n_rows, n_cols), dtype=dtype)
    return z * sigma_vec[None, :] + mu_vec[None, :]


def fill(res, state: RngState, shape: Shape, value, dtype=jnp.float32):
    return jnp.full(_shape(shape), value, dtype=dtype)


def bernoulli(res, state: RngState, shape: Shape, prob: float):
    return jax.random.bernoulli(state.next_key(), prob, _shape(shape))


def scaled_bernoulli(res, state: RngState, shape: Shape, prob: float,
                     scale: float, dtype=jnp.float32):
    """±scale with P(positive)=1-prob (ref: rng.cuh scaled_bernoulli)."""
    b = jax.random.bernoulli(state.next_key(), prob, _shape(shape))
    return jnp.where(b, -scale, scale).astype(dtype)


def gumbel(res, state: RngState, shape: Shape, mu=0.0, beta=1.0,
           dtype=jnp.float32):
    return (jax.random.gumbel(state.next_key(), _shape(shape), dtype=dtype)
            * beta + mu)


def laplace(res, state: RngState, shape: Shape, mu=0.0, scale=1.0,
            dtype=jnp.float32):
    return (jax.random.laplace(state.next_key(), _shape(shape), dtype=dtype)
            * scale + mu)


def logistic(res, state: RngState, shape: Shape, mu=0.0, scale=1.0,
             dtype=jnp.float32):
    return (jax.random.logistic(state.next_key(), _shape(shape), dtype=dtype)
            * scale + mu)


def lognormal(res, state: RngState, shape: Shape, mu=0.0, sigma=1.0,
              dtype=jnp.float32):
    z = jax.random.normal(state.next_key(), _shape(shape), dtype=dtype)
    return jnp.exp(z * sigma + mu)


def rayleigh(res, state: RngState, shape: Shape, sigma=1.0,
             dtype=jnp.float32):
    u = jax.random.uniform(state.next_key(), _shape(shape), dtype=dtype,
                           minval=jnp.finfo(dtype).tiny, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def exponential(res, state: RngState, shape: Shape, lam=1.0,
                dtype=jnp.float32):
    return jax.random.exponential(state.next_key(), _shape(shape),
                                  dtype=dtype) / lam


def sample(res, state: RngState, n_samples: int, weights,
           replace: bool = True, dtype=jnp.int32):
    """Weighted discrete sampling (ref: rng.cuh discrete / sample)."""
    weights = jnp.asarray(weights)
    idx = jax.random.choice(state.next_key(), weights.shape[0],
                            shape=(n_samples,), replace=replace, p=weights /
                            jnp.sum(weights))
    return idx.astype(dtype)


def sample_without_replacement(res, state: RngState, n_samples: int,
                               weights=None, pool_size: Optional[int] = None,
                               dtype=jnp.int32):
    """Weighted sampling without replacement via the Gumbel-top-k trick —
    the one-pass equivalent of the reference's Fisher-Yates-free kernel
    (ref: rng.cuh sampleWithoutReplacement,
    random/sample_without_replacement.cuh:90)."""
    if weights is None:
        if pool_size is None:
            raise ValueError("need weights or pool_size")
        logits = jnp.zeros((pool_size,), dtype=jnp.float32)
    else:
        weights = jnp.asarray(weights, dtype=jnp.float32)
        logits = jnp.log(jnp.maximum(weights, jnp.finfo(jnp.float32).tiny))
        pool_size = weights.shape[0]
    if n_samples > pool_size:
        raise ValueError("n_samples exceeds pool size")
    g = jax.random.gumbel(state.next_key(), (pool_size,), dtype=jnp.float32)
    _, idx = jax.lax.top_k(logits + g, n_samples)
    return idx.astype(dtype)


def excess_subsample(res, state: RngState, n_samples: int, pool_size: int,
                     dtype=jnp.int32):
    """Uniform subsample of [0, pool_size) without replacement
    (ref: random/excess_sampling / matrix::sample_rows backend)."""
    return sample_without_replacement(res, state, n_samples,
                                      pool_size=pool_size, dtype=dtype)
