"""Random permutations (ref: random/permute.cuh)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState


def permute(res, state: RngState, n: int, dtype=jnp.int32):
    """Random permutation of [0, n) (ref: raft::random::permute perms out)."""
    return jax.random.permutation(state.next_key(), n).astype(dtype)


def permute_rows(res, state: RngState, X):
    """Row-permuted copy of X plus the permutation used."""
    X = jnp.asarray(X)
    perm = jax.random.permutation(state.next_key(), X.shape[0])
    return X[perm], perm.astype(jnp.int32)
