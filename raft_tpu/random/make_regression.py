"""Linear-model dataset generator (ref: random/make_regression.cuh).

X is Gaussian (optionally with low effective rank), y = X·w + bias + noise,
with ``n_informative`` nonzero weight rows — the reference's gemm(+optional
qr) pipeline expressed as XLA matmuls.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState


def make_regression(res, state: RngState, n_rows: int, n_cols: int,
                    n_informative: Optional[int] = None, n_targets: int = 1,
                    bias: float = 0.0, effective_rank: Optional[int] = None,
                    tail_strength: float = 0.5, noise: float = 0.0,
                    shuffle: bool = True, dtype=jnp.float32):
    """Returns (X[n_rows,n_cols], y[n_rows,n_targets], w[n_cols,n_targets])."""
    n_informative = n_informative if n_informative is not None else n_cols
    kx, kw, kn, kp, kr = jax.random.split(state.next_key(), 5)

    if effective_rank is None:
        X = jax.random.normal(kx, (n_rows, n_cols), dtype=dtype)
    else:
        # Low-rank X with bell-shaped singular profile, as in the reference's
        # make_low_rank_matrix path.
        k1, k2 = jax.random.split(kx)
        rank = min(n_rows, n_cols)
        u, _ = jnp.linalg.qr(jax.random.normal(k1, (n_rows, rank),
                                               dtype=jnp.float32))
        v, _ = jnp.linalg.qr(jax.random.normal(k2, (n_cols, rank),
                                               dtype=jnp.float32))
        sing_idx = jnp.arange(rank, dtype=jnp.float32) / effective_rank
        low_rank = (1 - tail_strength) * jnp.exp(-(sing_idx ** 2))
        tail = tail_strength * jnp.exp(-0.1 * sing_idx)
        s = low_rank + tail
        X = ((u * s[None, :]) @ v.T).astype(dtype)

    w = jnp.zeros((n_cols, n_targets), dtype=dtype)
    w_inf = 100.0 * jax.random.uniform(kw, (n_informative, n_targets),
                                       dtype=dtype)
    w = w.at[:n_informative].set(w_inf)

    y = X @ w + jnp.asarray(bias, dtype=dtype)
    if noise > 0.0:
        y = y + noise * jax.random.normal(kn, y.shape, dtype=dtype)

    if shuffle:
        row_perm = jax.random.permutation(kp, n_rows)
        col_perm = jax.random.permutation(kr, n_cols)
        X = X[row_perm][:, col_perm]
        w = w[col_perm]
        y = y[row_perm]
    return X, y, w
