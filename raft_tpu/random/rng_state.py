"""PRNG state (ref: random/rng_state.hpp:19-45).

The reference's ``RngState`` carries {seed, base_subsequence, generator_type}
for counter-based Philox/PCG device generators.  JAX's PRNG is already
counter-based (threefry2x32 default; rbg available), so the TPU rebuild keeps
the same shape: a seed plus an advancing subsequence counter, deterministic
and order-independent across calls — each kernel launch folds
(seed, subsequence) into a fresh key.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp


class GeneratorType(enum.Enum):
    """ref: GeneratorType enum (GenPhilox/GenPC).  JAX exposes threefry and
    rbg; both are counter-based like the originals."""

    THREEFRY = "threefry"
    RBG = "rbg"


class RngState:
    def __init__(self, seed: int = 0, base_subsequence: int = 0,
                 type: GeneratorType = GeneratorType.THREEFRY):
        self.seed = int(seed)
        self.base_subsequence = int(base_subsequence)
        self.type = type

    def advance(self, max_streams_used: int = 1,
                max_calls_per_subsequence: int = 1) -> None:
        """Advance the subsequence so the next call sees fresh streams
        (ref: rng_state.hpp `advance`)."""
        self.base_subsequence += int(max_streams_used) * int(
            max_calls_per_subsequence)

    def key(self) -> jax.Array:
        """The jax PRNG key for the *current* subsequence.

        GeneratorType.RBG selects jax's 'rbg' implementation — on TPU it
        drives the hardware RNG instructions instead of computing
        threefry rounds on the VPU (the r2 sweep measured threefry
        uniform generation at 18% of HBM rate, compute-bound). Same
        counter-based key semantics (fold_in/split supported); streams
        are NOT cross-implementation reproducible, matching the
        reference's contract that GenPhilox/GenPC draw different
        sequences (rng_state.hpp:19-45)."""
        # explicit impl for BOTH types: impl=None would follow the
        # global jax_default_prng_impl, so the enum wouldn't pin the
        # generator (an embedding app flipping the global default must
        # not silently change RngState streams)
        impl = "rbg" if self.type == GeneratorType.RBG else "threefry2x32"
        base = jax.random.key(self.seed, impl=impl)
        return jax.random.fold_in(base, self.base_subsequence)

    def next_key(self) -> jax.Array:
        """Key for this call, then advance — one key per kernel launch."""
        k = self.key()
        self.advance()
        return k

    def split(self, n: int):
        """n independent keys for intra-call parallel streams."""
        return jax.random.split(self.next_key(), n)

    def __repr__(self):
        return (f"RngState(seed={self.seed}, "
                f"base_subsequence={self.base_subsequence}, "
                f"type={self.type.value})")
