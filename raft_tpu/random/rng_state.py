"""PRNG state (ref: random/rng_state.hpp:19-45).

The reference's ``RngState`` carries {seed, base_subsequence, generator_type}
for counter-based Philox/PCG device generators.  JAX's PRNG is already
counter-based (threefry2x32 default; rbg available), so the TPU rebuild keeps
the same shape: a seed plus an advancing subsequence counter, deterministic
and order-independent across calls — each kernel launch folds
(seed, subsequence) into a fresh key.
"""

from __future__ import annotations

import enum

import jax
import jax.numpy as jnp


class GeneratorType(enum.Enum):
    """ref: GeneratorType enum (GenPhilox/GenPC).  JAX exposes threefry and
    rbg; both are counter-based like the originals."""

    THREEFRY = "threefry"
    RBG = "rbg"


class RngState:
    def __init__(self, seed: int = 0, base_subsequence: int = 0,
                 type: GeneratorType = GeneratorType.THREEFRY):
        self.seed = int(seed)
        self.base_subsequence = int(base_subsequence)
        self.type = type

    def advance(self, max_streams_used: int = 1,
                max_calls_per_subsequence: int = 1) -> None:
        """Advance the subsequence so the next call sees fresh streams
        (ref: rng_state.hpp `advance`)."""
        self.base_subsequence += int(max_streams_used) * int(
            max_calls_per_subsequence)

    def key(self) -> jax.Array:
        """The jax PRNG key for the *current* subsequence."""
        base = jax.random.key(self.seed)
        return jax.random.fold_in(base, self.base_subsequence)

    def next_key(self) -> jax.Array:
        """Key for this call, then advance — one key per kernel launch."""
        k = self.key()
        self.advance()
        return k

    def split(self, n: int):
        """n independent keys for intra-call parallel streams."""
        return jax.random.split(self.next_key(), n)

    def __repr__(self):
        return (f"RngState(seed={self.seed}, "
                f"base_subsequence={self.base_subsequence}, "
                f"type={self.type.value})")
