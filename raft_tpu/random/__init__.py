"""Random generation (ref: cpp/include/raft/random/)."""

from raft_tpu.random.rng_state import RngState, GeneratorType  # noqa: F401
from raft_tpu.random.rng import (  # noqa: F401
    uniform,
    uniform_int,
    normal,
    normal_int,
    normal_table,
    fill,
    bernoulli,
    scaled_bernoulli,
    gumbel,
    laplace,
    logistic,
    lognormal,
    rayleigh,
    exponential,
    sample,
    sample_without_replacement,
    excess_subsample,
)
from raft_tpu.random.make_blobs import make_blobs  # noqa: F401
from raft_tpu.random.make_regression import make_regression  # noqa: F401
from raft_tpu.random.permute import permute, permute_rows  # noqa: F401
from raft_tpu.random.multi_variable_gaussian import (  # noqa: F401
    multi_variable_gaussian,
    Decomposer,
)
from raft_tpu.random.rmat import rmat_rectangular_gen  # noqa: F401

# Reference-spelling alias (rng.cuh `discrete` — weighted discrete draw).
from raft_tpu.random.rng import sample as discrete  # noqa: F401,E402
