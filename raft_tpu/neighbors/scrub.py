"""Background scrub + read-repair for the streaming journal (ISSUE 18
tentpole part 3).

The WAL and epoch containers carry per-entry CRC32s, but PR 17 only
ever *checked* them on the recovery path — damage sat latent until the
worst possible moment (a restart). The scrubber moves detection to a
background interval walk:

- every epoch snapshot and WAL record on disk is re-parsed end to end
  (every entry CRC checked) each pass;
- a damaged file is QUARANTINED — renamed to ``<name>.quarantined`` so
  no recovery walk ever reads it again — then repaired up a ladder:
  the healthy in-memory index rewrites a fresh epoch snapshot
  (durability restored from RAM), else a ``repair_source`` callback
  fetches a healthy replica's epoch entries (the WAL-shipping fleet's
  read-repair), else another intact epoch on disk already covers it;
  when nothing on the ladder holds, the typed
  :class:`~raft_tpu.neighbors.streaming.ShardCorruptError` surfaces —
  corruption is never silently tolerated;
- the in-memory packed state gets a sidecar check: each pass records
  ``(snapshot version, CRC over packed_db ‖ packed_ids ‖ tombstones)``;
  the same version reappearing with a different CRC means RAM damage
  (nothing mutated, bytes changed) — repaired from ``repair_source``
  or raised.

Metered through obs: ``scrub_passes_total``,
``scrub_corruptions_total{outcome=repaired|quarantined}``,
``scrub_memory_repairs_total``. Injection for the witnesses comes from
:meth:`raft_tpu.comms.faults.FaultInjector.corrupt_bytes`.
"""

from __future__ import annotations

import os
import threading
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from raft_tpu import obs
from raft_tpu.core import env, trace
from raft_tpu.core.checkpoint import CheckpointError, restore_checkpoint
from raft_tpu.neighbors.streaming import (MutationLog, ShardCorruptError,
                                          StreamingError, StreamingIndex,
                                          _WAL_RE)

__all__ = ["Scrubber", "ScrubReport"]


@dataclass
class ScrubReport:
    """What one scrub pass found and did."""

    files_checked: int = 0
    corrupt: List[str] = field(default_factory=list)
    repaired: List[str] = field(default_factory=list)
    quarantined: List[str] = field(default_factory=list)
    memory_repaired: bool = False


class Scrubber:
    """Interval scrub walk over one streaming journal (and optionally
    the in-memory packed state).

    ``index`` gives the full ladder (in-memory rewrite + sidecar
    check); ``log`` alone scrubs a cold directory (a dead replica's
    journal before restart). ``repair_source`` is a zero-arg callable
    returning a healthy replica's epoch entries — the WAL-shipping
    fleet passes a leader snapshot fetch here. ``interval`` defaults to
    the fail-loud ``RAFT_TPU_SCRUB_INTERVAL`` knob. Background worker
    errors surface at :meth:`stop` (the Compactor discipline).
    """

    def __init__(self, index: Optional[StreamingIndex] = None, *,
                 log: Optional[MutationLog] = None,
                 interval: Optional[float] = None,
                 repair_source: Optional[Callable[[], Dict]] = None):
        if index is not None:
            if log is not None and log is not index.log:
                raise ValueError("pass index= OR log=, not both")
            log = index.log
        if log is None:
            raise ValueError(
                "scrubbing needs a journal: a journaled index= or an "
                "explicit log=")
        self.index = index
        self.log = log
        self.repair_source = repair_source
        self.interval = float(env.read("RAFT_TPU_SCRUB_INTERVAL")
                              if interval is None else interval)
        if self.interval <= 0:
            raise ValueError(f"interval must be > 0, got {self.interval}")
        self.passes = 0
        self.corruptions = 0
        self._sidecar: Optional[Tuple[int, int]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    # -- one pass ------------------------------------------------------

    def _walk(self) -> List[str]:
        """Every journal container on disk: epoch snapshots (via the
        manager's registry) then WAL records, ascending."""
        paths = [self.log.epoch_path(s) for s in self.log.epoch_steps()]
        paths += [os.path.join(self.log.directory, f)
                  for f in sorted(os.listdir(self.log.directory))
                  if _WAL_RE.match(f)]
        return paths

    def run_once(self) -> ScrubReport:
        """One full scrub pass; returns the report. Raises
        :class:`ShardCorruptError` when damage is found that NOTHING on
        the repair ladder covers (the shard stays quarantined)."""
        self.passes += 1
        if obs.enabled():
            obs.inc("scrub_passes_total")
        report = ScrubReport()
        for path in self._walk():
            report.files_checked += 1
            try:
                restore_checkpoint(path)
            except FileNotFoundError:
                continue  # pruned between walk and verify — fine
            except CheckpointError as exc:
                self._handle_corrupt(path, str(exc), report)
        self._check_memory(report)
        trace.record_event("scrub.pass", files=report.files_checked,
                           corrupt=len(report.corrupt),
                           repaired=len(report.repaired))
        return report

    def _handle_corrupt(self, path: str, detail: str,
                        report: ScrubReport) -> None:
        name = os.path.basename(path)
        self.corruptions += 1
        report.corrupt.append(name)
        # quarantine FIRST: the suffix stops every journal regex from
        # matching, so no recovery walk can ever read the damage —
        # repair then restores redundancy next to it
        os.replace(path, path + ".quarantined")
        report.quarantined.append(name)
        trace.record_event("scrub.quarantine", file=name, error=detail)
        repaired = False
        if self.index is not None:
            # the in-memory state is the authority while the process
            # lives: rewrite the current epoch (folds the WAL too, so a
            # damaged WAL record is also superseded)
            with self.index._lock:
                self.index._write_epoch_locked(crash=False)
            repaired = True
        elif self._intact_epoch_exists():
            # redundancy already covers the loss: the newest intact
            # epoch + surviving WAL reconstruct the state
            repaired = True
        elif self.repair_source is not None:
            # cold directory (dead replica's journal): land a healthy
            # peer's epoch entries as a fresh snapshot so the next
            # recover() has something intact to restore
            steps = self.log.epoch_steps()
            self.log.write_epoch((max(steps) + 1) if steps else 0,
                                 dict(self.repair_source()))
            repaired = True
        if obs.enabled():
            obs.inc("scrub_corruptions_total",
                    outcome="repaired" if repaired else "quarantined")
        if repaired:
            report.repaired.append(name)
        else:
            raise ShardCorruptError(
                name, f"{detail} — no healthy index, repair source, or "
                      "intact epoch to repair from")

    def _intact_epoch_exists(self) -> bool:
        for step in reversed(self.log.epoch_steps()):
            try:
                restore_checkpoint(self.log.epoch_path(step))
                return True
            except (CheckpointError, FileNotFoundError):
                continue
        return False

    def _check_memory(self, report: ScrubReport) -> None:
        """Sidecar check on the live packed state: same snapshot
        version, different bytes ⇒ RAM damage (nothing mutated — the
        version is bumped by every publish)."""
        if self.index is None:
            return
        with self.index._lock:
            snap = self.index.snapshot
            crc = zlib.crc32(np.ascontiguousarray(
                np.asarray(snap.flat.packed_db)).tobytes())
            crc = zlib.crc32(np.ascontiguousarray(
                np.asarray(snap.flat.packed_ids, np.int32)).tobytes(),
                crc)
            crc = zlib.crc32(np.ascontiguousarray(
                self.index._tomb_host).tobytes(), crc)
            version = snap.version
        if self._sidecar is not None and self._sidecar[0] == version \
                and self._sidecar[1] != crc:
            self.corruptions += 1
            trace.record_event("scrub.memory_corrupt", version=version)
            if self.repair_source is None:
                if obs.enabled():
                    obs.inc("scrub_corruptions_total",
                            outcome="quarantined")
                raise ShardCorruptError(
                    "memory", f"packed state changed under version "
                              f"{version} with no mutation — RAM "
                              "damage and no repair source")
            self.index.install_snapshot(self.repair_source())
            report.memory_repaired = True
            if obs.enabled():
                obs.inc("scrub_corruptions_total", outcome="repaired")
                obs.inc("scrub_memory_repairs_total")
            # re-baseline against the freshly installed state next pass
            self._sidecar = None
            return
        self._sidecar = (version, crc)

    # -- worker thread -------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.run_once()
            except Exception as exc:  # noqa: BLE001 — surfaced at stop
                self._error = exc
                obs.record_failure(exc)
                trace.record_event("scrub.worker_error", error=str(exc))
                return

    def start(self) -> "Scrubber":
        if self._thread is not None:
            raise StreamingError("scrubber already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="raft-tpu-scrubber")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the worker and re-raise any failure it died on."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise StreamingError("background scrubber failed") from err

    def __enter__(self) -> "Scrubber":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
