"""Leader failover for the durable streaming fleet: term-fenced
election, zero-loss promotion (ISSUE 20 tentpole).

PR 18 made every FOLLOWER failure survivable; the fleet still had one
unprotected single point of failure — the WAL-shipping leader. This
module removes it. Every node runs an :class:`ElectionNode` alongside
its :class:`~raft_tpu.neighbors.wal_ship.WalShipper` (leader role) or
:class:`~raft_tpu.neighbors.wal_ship.WalFollower` (follower role):

- the **leader** broadcasts a heartbeat (term, applied horizon, term
  boundary) to every fleet peer each ``heartbeat_interval``;
- a **follower** that hears nothing for ``RAFT_TPU_ELECTION_TIMEOUT``
  seconds — or whose mailbox failure detector marks the leader dead —
  runs an election among the survivor clique
  (:meth:`~raft_tpu.comms.comms.MeshComms.agree_on_survivors` reuse:
  the same consensus barrier the MNMG heal path trusts);
- every survivor exchanges a round-stamped **ballot** ``(term,
  applied_seq)`` and all compute the SAME winner deterministically:
  highest ``(term, applied_seq)``, lowest rank on a split vote. The
  winner is the most-caught-up mirror journal, so **promotion moves no
  data**: the index it already serves IS the new authority — it
  attaches a fresh shipper, journals a :data:`KIND_TERM` record under
  ``max(terms) + 1`` (the durable term boundary, shipped like any
  record), and resumes ingest. Losers re-point their follower at the
  winner and adopt the term; any backlog heals through the existing
  catch-up ladder.

**Fencing** is what makes the old leader harmless instead of fatal: a
deposed leader that was merely partitioned keeps shipping records
stamped with its stale term, and every replica rejects them with the
typed :class:`~raft_tpu.neighbors.streaming.TermFencedError` carrying
the divergence sequence (where the new term began). The deposed node
learns its fate from a fence NACK or from any higher-term heartbeat,
then **demotes**: truncate the unreplicated WAL suffix from the
divergence point (:meth:`MutationLog.truncate_from`), reset the
cursor, rejoin as a follower, and heal via snapshot catch-up — landing
``content_crc`` bit-equal to the fleet.

Ack modes ride the shipper (``acks="majority"``): quorum-acked writes
bound acked-write loss to ZERO across any single failure; async keeps
today's latency with the loss window now measured per follower by the
``wal_replication_lag_seconds`` gauge.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from raft_tpu import obs
from raft_tpu.comms.errors import (CommsAbortedError, CommsError,
                                   CommsTimeoutError, PeerFailedError)
from raft_tpu.core import env, trace
from raft_tpu.neighbors.streaming import (StreamingError, StreamingIndex,
                                          TermFencedError)
from raft_tpu.neighbors.wal_ship import (TAG_WAL, WalFollower,
                                         WalFrameError, WalShipper,
                                         decode_frame, encode_frame)

__all__ = [
    "TAG_HEARTBEAT", "TAG_BALLOT", "TAG_FENCE",
    "ElectionError", "ElectionRecord", "ElectionNode",
]

# Import-time knob validation (fail-loud): a malformed election
# timeout or quorum mode must fail the IMPORT, not the first failover —
# a fleet must never come up with a silently-wrong succession config.
env.read("RAFT_TPU_ELECTION_TIMEOUT")
env.read("RAFT_TPU_WAL_QUORUM")

# mailbox tags — the failover band, above the WAL-shipping band (73xx)
TAG_HEARTBEAT = 7310  # leader → all: {"term","applied","term_start"}
TAG_BALLOT = 7311     # survivor ↔ survivor: {"round","term","applied"}
TAG_FENCE = 7312      # replica → stale leader: {"term","term_start",
#                       "leader"} — the explicit you-are-deposed NACK


class ElectionError(StreamingError):
    """The survivor clique could not complete an election (no quorum,
    or repeated mid-election participant loss)."""


@dataclass
class ElectionRecord:
    """What one completed election decided (every survivor records an
    identical one — determinism is the protocol's correctness core)."""

    winner: int                       # promoted rank
    term: int                         # the new term
    round: int                        # this node's election round
    survivors: Tuple[int, ...]        # the clique that voted
    votes: Dict[int, Tuple[int, int]]  # rank → (term, applied_seq)
    seconds: float                    # detection → role switch
    promoted: bool = False            # True on the winner's record
    attempts: int = 1                 # survivor-set retries used
    extra: Dict = field(default_factory=dict)


class ElectionNode:
    """One fleet member's failover state machine (see module docstring).

    Owns the role: as ``"leader"`` it heartbeats and watches for rival
    (higher-term) leaders; as ``"follower"`` it drains shipped WAL
    records, watches the leader's pulse, and runs the election when the
    pulse stops. Role transitions — promotion, re-point, stale-leader
    demotion — happen on the node's own worker thread (or synchronously
    via :meth:`run_election` in tests). Worker errors surface at
    :meth:`stop`, never swallowed (the Compactor discipline).

    Parameters
    ----------
    index : the node's :class:`StreamingIndex` (journaled).
    mailbox : fleet transport (``TcpMailbox`` or the in-proc twin).
    rank / fleet : this node's rank and ALL fleet ranks.
    role : ``"leader"`` or ``"follower"``.
    leader : the current leader's rank.
    comms : optional :class:`~raft_tpu.comms.comms.MeshComms` view of
        this rank — when given, elections reuse its
        ``agree_on_survivors`` consensus barrier; without it the
        mailbox failure detector is snapshotted directly.
    acks : the shipper ack mode this node will use WHEN leading
        (``None`` reads ``RAFT_TPU_WAL_QUORUM``).
    on_promote / on_repoint / on_demote : role-change callbacks (the
        serve tier re-points routing here), called on the worker
        thread AFTER the data plane switched.
    """

    def __init__(self, index: StreamingIndex, mailbox, rank: int,
                 fleet: List[int], *, role: str, leader: int,
                 comms=None,
                 heartbeat_interval: Optional[float] = None,
                 election_timeout: Optional[float] = None,
                 acks: "str | int | None" = None,
                 ack_timeout: float = 10.0,
                 shipper: Optional[WalShipper] = None,
                 follower: Optional[WalFollower] = None,
                 on_promote: Optional[Callable[["ElectionNode"], None]]
                 = None,
                 on_repoint: Optional[Callable[["ElectionNode"], None]]
                 = None,
                 on_demote: Optional[Callable[["ElectionNode"], None]]
                 = None,
                 poll_interval: float = 0.01):
        if role not in ("leader", "follower"):
            raise ValueError(f"role must be leader|follower, got "
                             f"{role!r}")
        if index.log is None:
            raise StreamingError(
                "failover needs a journaled index (directory=...)")
        self.index = index
        self.mailbox = mailbox
        self.rank = int(rank)
        self.fleet = sorted(int(r) for r in fleet)
        if self.rank not in self.fleet:
            raise ValueError(f"rank {self.rank} not in fleet "
                             f"{self.fleet}")
        self.role = role
        self.leader = int(leader)
        self.comms = comms
        self.election_timeout = float(
            env.read("RAFT_TPU_ELECTION_TIMEOUT")
            if election_timeout is None else election_timeout)
        self.heartbeat_interval = float(
            self.election_timeout / 4.0
            if heartbeat_interval is None else heartbeat_interval)
        self.acks = env.read("RAFT_TPU_WAL_QUORUM") if acks is None \
            else acks
        self.ack_timeout = float(ack_timeout)
        self.poll_interval = float(poll_interval)
        self.on_promote = on_promote
        self.on_repoint = on_repoint
        self.on_demote = on_demote
        self.shipper = shipper
        self.follower = follower
        if role == "leader" and self.shipper is None:
            self.shipper = WalShipper(
                index, mailbox, self.rank,
                [r for r in self.fleet if r != self.rank],
                acks=self.acks, ack_timeout=self.ack_timeout)
        if role == "follower" and self.follower is None:
            self.follower = WalFollower(index, mailbox, self.rank,
                                        self.leader)
        self.elections = 0            # this node's election round
        self.promotions = 0
        self.demotions = 0
        self.fences_sent = 0
        self.last_election: Optional[ElectionRecord] = None
        self.last_fence: Optional[TermFencedError] = None
        self._last_heartbeat = time.monotonic()
        self._lock = threading.Lock()   # role transitions
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    # -- small helpers -------------------------------------------------

    @property
    def peers(self) -> List[int]:
        return [r for r in self.fleet if r != self.rank]

    def is_leader(self) -> bool:
        return self.role == "leader"

    def _put(self, dst: int, tag: int, frame: Dict) -> bool:
        """Best-effort control-plane send — a dead peer never fails
        the node (the failure detector and catch-up own healing)."""
        try:
            self.mailbox.put(self.rank, dst, tag, encode_frame(frame))
            return True
        except (PeerFailedError, OSError):
            return False

    def _drain(self, src: int, tag: int) -> List[Dict]:
        """Every queued, decodable frame from (src, tag), in order;
        damaged frames are dropped with a trace event (the control
        plane tolerates a torn message — state is re-broadcast)."""
        out: List[Dict] = []
        while True:
            payload = self.mailbox.get_nowait(src, self.rank, tag)
            if payload is None:
                return out
            try:
                out.append(decode_frame(payload))
            except WalFrameError as exc:
                trace.record_event("election.bad_frame", src=src,
                                   tag=tag, error=repr(exc))

    # -- heartbeats ----------------------------------------------------

    def _heartbeat_frame(self) -> Dict:
        return {"term": self.index.term,
                "applied": self.index.applied_seq,
                "term_start": self.index._term_start,
                "leader": self.rank}

    def broadcast_heartbeat(self) -> None:
        """Leader pulse to every fleet peer (dead ones included — a
        rejoining node must hear the current term to heal)."""
        frame = self._heartbeat_frame()
        for p in self.peers:
            self._put(p, TAG_HEARTBEAT, frame)

    def _observe_heartbeats(self) -> None:
        """Follower side: fold every queued heartbeat. The current
        leader's pulse feeds the silence timer; a HIGHER-term pulse
        from any rank means an election happened without us (we were
        mid-catch-up or partitioned) — adopt it and re-point; a
        LOWER-term pulse is a deposed leader that must be fenced."""
        for p in self.peers:
            beats = self._drain(p, TAG_HEARTBEAT)
            if not beats:
                continue
            hb = beats[-1]
            term = int(hb["term"])
            if term < self.index.term:
                self._send_fence(p)
                continue
            if term > self.index.term:
                self.index.adopt_term(term)
                self.index._term_start = int(hb.get("term_start", 0))
            if int(hb.get("leader", p)) != self.leader and \
                    term >= self.index.term:
                self._repoint_to(int(hb.get("leader", p)), term,
                                 reason="heartbeat")
            if p == self.leader:
                self._last_heartbeat = time.monotonic()

    def _send_fence(self, stale: int) -> None:
        """Tell a stale-term sender it is deposed: carry the current
        term, its boundary sequence (= the divergence the deposed node
        truncates from) and who leads now."""
        self.fences_sent += 1
        if obs.enabled():
            obs.inc("election_fences_sent_total")
        self._put(stale, TAG_FENCE,
                  {"term": self.index.term,
                   "term_start": self.index._term_start,
                   "leader": self.leader if self.role != "leader"
                   else self.rank})

    # -- leader-side vigilance ----------------------------------------

    def _leader_tick(self) -> None:
        self.broadcast_heartbeat()
        # a rejoining stale leader heartbeats at a lower term: fence it
        # and re-admit it to the shipping/catch-up set so it can heal.
        # A HIGHER term pulse means WE are the deposed one.
        for p in self.peers:
            beats = self._drain(p, TAG_HEARTBEAT)
            if not beats:
                continue
            hb = beats[-1]
            term = int(hb["term"])
            if term < self.index.term:
                self._send_fence(p)
                if p not in self.shipper.followers:
                    self.shipper.followers.append(p)
                    trace.record_event("election.readmit", rank=p)
            elif term > self.index.term:
                self._demote(term, int(hb.get("term_start", 0)),
                             int(hb.get("leader", p)))
                return
            elif int(hb.get("leader", -1)) == p and p < self.rank:
                # EQUAL-term rival leader: possible only when ballot
                # inputs diverged between election retries. Resolve
                # deterministically the same way the ballot does —
                # lowest rank keeps the term, we demote and heal
                trace.record_event("election.split_brain",
                                   rank=self.rank, rival=p, term=term)
                self._demote(term, int(hb.get("term_start", 0)), p)
                return
        for frames in (self._drain(p, TAG_FENCE) for p in self.peers):
            for f in frames:
                if int(f["term"]) > self.index.term:
                    self._demote(int(f["term"]),
                                 int(f["term_start"]),
                                 int(f["leader"]))
                    return
        # discard queued candidacies: our pulse is the answer, and a
        # stale high-round ballot must not leak into a later election
        for p in self.peers:
            self._drain(p, TAG_BALLOT)

    # -- follower-side vigilance --------------------------------------

    def _leader_silent(self) -> bool:
        if time.monotonic() - self._last_heartbeat \
                > self.election_timeout:
            return True
        failed = getattr(self.mailbox, "peer_failed", None)
        return bool(failed and failed(self.leader))

    def _follower_tick(self) -> None:
        # judge silence IMMEDIATELY after folding fresh heartbeats —
        # draining first would age the pulse timer by however long the
        # apply takes (seconds, on a first-touch jit compile) and
        # manufacture a spurious election against a live leader
        self._observe_heartbeats()
        if self._leader_silent():
            try:
                self.run_election()
            except (ElectionError, CommsError) as exc:
                # the clique is unstable (a peer mid-apply, partitioned,
                # or lagging its own silence detection) — an election
                # failure must never kill the node's vigilance: back
                # off one timeout and watch again
                trace.record_event("election.deferred", rank=self.rank,
                                   error=str(exc))
                self._last_heartbeat = time.monotonic()
            return
        # answer ballot requests even while settled: a candidate whose
        # silence detection leads ours must not starve waiting for our
        # vote — without this, staggered detection ping-pongs through
        # whole deferral timeouts before a clique ever forms
        for p in self.peers:
            for b in self._drain(p, TAG_BALLOT):
                self._put(p, TAG_BALLOT,
                          {"round": int(b.get("round", 0)),
                           "term": self.index.term,
                           "applied": self.index.applied_seq,
                           "rank": self.rank})
        if self.follower is not None:
            try:
                self.follower.drain()
            except TermFencedError as exc:
                # a stale leader's record reached our live channel:
                # reject is already done (typed) — NACK it explicitly
                self.last_fence = exc
                self._send_fence(self.follower.leader)

    # -- the election --------------------------------------------------

    def _survivors(self, exclude: int) -> Tuple[int, ...]:
        """The live clique, minus the rank whose silence triggered us
        (the failure detector may lag the application-level timeout).
        Reuses ``agree_on_survivors`` when a comms view is wired."""
        if self.comms is not None:
            live = self.comms.agree_on_survivors()
        else:
            failed = self.mailbox.failed_peers() \
                if hasattr(self.mailbox, "failed_peers") else {}
            live = [r for r in self.fleet if r not in failed]
        return tuple(r for r in live if r != exclude)

    def _ballot_exchange(self, survivors: Tuple[int, ...], round_: int
                         ) -> Optional[Dict[int, Tuple[int, int]]]:
        """All-to-all (term, applied) exchange among the survivors;
        returns None when a peer died mid-exchange (caller retries
        with a fresh survivor set). Ballots are round-stamped; stale
        rounds from an earlier election are drained and ignored."""
        votes: Dict[int, Tuple[int, int]] = {
            self.rank: (self.index.term, self.index.applied_seq)}
        frame = {"round": round_, "term": votes[self.rank][0],
                 "applied": votes[self.rank][1], "rank": self.rank}
        others = [s for s in survivors if s != self.rank]
        for p in others:
            self._put(p, TAG_BALLOT, frame)
        deadline = time.monotonic() + max(self.election_timeout, 0.5)
        for p in others:
            got = None
            while got is None:
                for b in self._drain(p, TAG_BALLOT):
                    if int(b.get("round", -1)) >= round_:
                        got = b
                if got is not None:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    trace.record_event("election.ballot_timeout",
                                       rank=self.rank, peer=p)
                    return None
                try:
                    payload = self.mailbox.get(
                        p, self.rank, TAG_BALLOT,
                        timeout=min(remaining, 0.25))
                except (CommsTimeoutError, PeerFailedError):
                    continue
                try:
                    b = decode_frame(payload)
                except WalFrameError:
                    continue
                if int(b.get("round", -1)) >= round_:
                    got = b
            votes[p] = (int(got["term"]), int(got["applied"]))
        return votes

    def run_election(self) -> ElectionRecord:
        """Elect and switch roles. Deterministic across the clique:
        every survivor computes the same winner — max ``(term,
        applied_seq)``, lowest rank on an exact tie — and the same new
        term, ``max(terms) + 1``. A participant death mid-exchange
        retries with a fresh ``agree_on_survivors`` clique (bounded)."""
        t0 = time.monotonic()
        old_leader = self.leader
        self.elections += 1
        round_ = self.elections
        attempts = 0
        quorum = len(self.fleet) // 2 + 1
        for attempts in range(1, 6):
            survivors = self._survivors(exclude=old_leader)
            if self.rank not in survivors:
                raise ElectionError(
                    f"rank {self.rank}: not in survivor clique "
                    f"{survivors}")
            if len(survivors) < quorum:
                # a minority clique must NEVER elect: a follower that
                # merely lost the leader's pulse for one timeout (GIL
                # stall, partition) would otherwise crown itself with
                # its own single vote and split the brain
                raise ElectionError(
                    f"rank {self.rank}: survivors {survivors} below "
                    f"quorum {quorum} of fleet {self.fleet} — "
                    f"refusing a minority election")
            votes = self._ballot_exchange(survivors, round_)
            if votes is not None:
                break
            trace.record_event("election.retry", rank=self.rank,
                               attempt=attempts)
        else:
            raise ElectionError(
                f"rank {self.rank}: no stable survivor clique after "
                f"{attempts} attempts")
        winner = max(votes, key=lambda r: (votes[r][0], votes[r][1],
                                           -r))
        new_term = max(t for t, _ in votes.values()) + 1
        promoted = winner == self.rank
        if promoted:
            self._promote(new_term, survivors)
        else:
            # the winner's KIND_TERM record deterministically lands at
            # its applied horizon + 1 — every loser can set the term
            # boundary NOW, so stale-term fencing is armed while the
            # legitimately-old-term records below it still replay
            self._repoint_to(winner, new_term,
                             term_start=votes[winner][1] + 1,
                             reason="election")
        dt = time.monotonic() - t0
        rec = ElectionRecord(winner=winner, term=new_term,
                             round=round_, survivors=survivors,
                             votes=votes, seconds=dt,
                             promoted=promoted, attempts=attempts)
        self.last_election = rec
        if obs.enabled():
            obs.inc("elections_total",
                    outcome="promoted" if promoted else "repointed")
            obs.observe("election_seconds", dt)
            obs.set_gauge("fleet_term", new_term)
        trace.record_event("election.decided", rank=self.rank,
                           winner=winner, term=new_term,
                           survivors=survivors,
                           seconds=round(dt, 4), promoted=promoted)
        return rec

    # -- role transitions ---------------------------------------------

    def _promote(self, new_term: int, survivors: Tuple[int, ...]
                 ) -> None:
        """Winner path: the index this node already serves IS the most
        caught-up mirror — promotion attaches a shipper and journals
        the term boundary; NO data moves and the serving executables
        survive untouched (the zero-recompile contract the serve tier
        asserts via ``ExecutorStats.traces``)."""
        with self._lock:
            self.role = "leader"
            self.leader = self.rank
            self.follower = None
            self.shipper = WalShipper(
                self.index, self.mailbox, self.rank,
                [s for s in survivors if s != self.rank],
                acks=self.acks, ack_timeout=self.ack_timeout)
            self.shipper.attach()
            # the new term's first durable record — consumes the next
            # seq and ships through the just-attached hook, so every
            # follower journal records the boundary
            self.index.begin_term(new_term)
            self.shipper.start()
            self.promotions += 1
        self.broadcast_heartbeat()
        if obs.enabled():
            obs.inc("election_promotions_total")
        trace.record_event("election.promoted", rank=self.rank,
                           term=new_term, followers=self.shipper.followers)
        if self.on_promote is not None:
            self.on_promote(self)

    def _repoint_to(self, winner: int, new_term: int, *,
                    term_start: Optional[int] = None,
                    reason: str) -> None:
        """Loser path: adopt the term (and its boundary, when known —
        records BELOW it legitimately carry older terms and must still
        replay), re-point the follower at the winner. Any applied-seq
        deficit heals automatically — the next shipped record gaps and
        :meth:`WalFollower.drain` resyncs via the existing catch-up
        ladder."""
        with self._lock:
            self.index.adopt_term(new_term)
            if term_start is not None:
                self.index._term_start = max(self.index._term_start,
                                             int(term_start))
            self.leader = int(winner)
            if self.follower is None:
                self.follower = WalFollower(self.index, self.mailbox,
                                            self.rank, self.leader)
            else:
                self.follower.repoint(self.leader)
            self.role = "follower"
            self._last_heartbeat = time.monotonic()
        trace.record_event("election.repointed", rank=self.rank,
                           leader=self.leader, term=new_term,
                           reason=reason)
        if self.on_repoint is not None:
            self.on_repoint(self)

    def _demote(self, new_term: int, term_start: int,
                new_leader: int) -> None:
        """Deposed-leader path: record the typed fence, truncate the
        unreplicated WAL suffix from the divergence sequence, reset
        the cursor, rejoin as a follower, and heal via snapshot
        catch-up — converging ``content_crc`` bit-equal to the fleet."""
        fence = TermFencedError(stale_term=self.index.term,
                                current_term=new_term,
                                divergence=term_start)
        self.last_fence = fence
        with self._lock:
            try:
                self.shipper.stop()
            except StreamingError as exc:
                trace.record_event("election.demote_shipper_error",
                                   error=str(exc))
            self.shipper.detach()
            truncated = self.index.log.truncate_from(term_start)
            # the in-memory state contains the truncated suffix: force
            # a full snapshot resync (cursor −1 → the new leader ships
            # its epoch entries wholesale)
            with self.index._lock:
                self.index._applied_seq = -1
            self.index.adopt_term(new_term)
            self.index._term_start = int(term_start)
            self.role = "follower"
            self.leader = int(new_leader)
            self.follower = WalFollower(self.index, self.mailbox,
                                        self.rank, self.leader)
            self.demotions += 1
            self._last_heartbeat = time.monotonic()
        if obs.enabled():
            obs.inc("election_demotions_total")
        trace.record_event("election.demoted", rank=self.rank,
                           term=new_term, divergence=term_start,
                           truncated=truncated, leader=new_leader)
        rpt = self.follower.catch_up(timeout=self.ack_timeout)
        trace.record_event("election.demote_healed",
                           snapshot=rpt.snapshot,
                           through_seq=rpt.through_seq)
        if self.on_demote is not None:
            self.on_demote(self)

    # -- worker --------------------------------------------------------

    def tick(self) -> None:
        """One vigilance cycle (public for deterministic tests)."""
        if self.role == "leader":
            self._leader_tick()
        else:
            self._follower_tick()

    def _run(self) -> None:
        interval = min(self.heartbeat_interval, self.poll_interval) \
            if self.role == "leader" else self.poll_interval
        while not self._stop.wait(interval):
            try:
                self.tick()
            except (CommsAbortedError, CommsError, StreamingError,
                    Exception) as exc:  # noqa: BLE001 — surfaced at stop
                self._error = exc
                obs.record_failure(exc)
                trace.record_event("election.node_error",
                                   rank=self.rank, error=repr(exc))
                return

    def start(self) -> "ElectionNode":
        if self._thread is not None:
            raise StreamingError("election node already started")
        if self.role == "leader" and self.shipper is not None and \
                self.shipper._thread is None:
            self.shipper.attach()
            self.shipper.start()
        self._last_heartbeat = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"raft-tpu-election-{self.rank}")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the worker (and this node's shipper, when leading) and
        re-raise any failure the worker died on."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self.shipper is not None and self.role == "leader":
            self.shipper.stop()
            self.shipper.detach()
        if self._error is not None:
            err, self._error = self._error, None
            raise StreamingError("election node failed") from err

    def __enter__(self) -> "ElectionNode":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
