"""WAL shipping: replicate the streaming mutation log over the mailbox
transport (ISSUE 18 tentpole part 2).

PR 17 made ONE streaming index crash-safe: its journal directory holds
everything recovery needs. This module removes the "its" — a replica
whose disk died with its process catches up from a live peer instead:

- the **leader** side (:class:`WalShipper`) hooks
  :attr:`MutationLog.on_append` and streams every durable WAL record to
  each follower the moment it commits (record-then-ship: a shipped
  record is always at least as durable at the source as anywhere else),
  and answers catch-up requests from its on-disk WAL — or, when the
  requested range was already pruned into an epoch snapshot, with the
  snapshot itself;
- the **follower** side (:class:`WalFollower`) applies records in
  strict sequence order, MIRRORING each one into its own journal first
  (``append_mirror`` keeps the leader's numbering, so the follower's
  WAL is a verbatim suffix of the leader's and a restart resumes from
  exactly the right cursor). A gap raises the typed
  :class:`~raft_tpu.neighbors.streaming.WalGapError`; :meth:`drain`
  turns it into a snapshot-resync :meth:`catch_up` — the protocol the
  acceptance witness drives: SIGKILL a follower mid-stream, restart it
  (or bootstrap a blank one), and it converges to the leader's
  ``content_crc`` bit-for-bit.

Wire format: every frame is a v1 checkpoint container (same per-entry
CRCs as the on-disk WAL) serialized into a uint8 array, because the TCP
mailbox only carries numpy payloads (``np.save(allow_pickle=False)``).
Delivery is at-least-once per link (TCP reconnect resend) — the
follower dedupes by sequence number; ordering per link is FIFO, so a
gap means records were genuinely pruned or lost, never reordered.
"""

from __future__ import annotations

import collections
import io
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

import numpy as np

from raft_tpu import obs
from raft_tpu.comms.errors import PeerFailedError
from raft_tpu.core import env, trace
from raft_tpu.core.checkpoint import (CheckpointError, dump_checkpoint,
                                      load_checkpoint)
from raft_tpu.neighbors.streaming import (KIND_CENTROIDS, KIND_DELETE,
                                          KIND_INSERT, KIND_TERM,
                                          MutationLog, StreamingError,
                                          StreamingIndex,
                                          TermFencedError, WalGapError,
                                          _epoch_entries,
                                          _flat_from_live)

__all__ = [
    "TAG_WAL", "TAG_CATCHUP_REQ", "TAG_CATCHUP", "TAG_WAL_ACK",
    "FRAME_WAL", "FRAME_SNAPSHOT", "FRAME_END",
    "encode_frame", "decode_frame", "frame_kind",
    "WalFrameError", "WalQuorumError",
    "WalShipper", "WalFollower", "CatchupReport", "bootstrap_follower",
]

# mailbox tags — high constants so they never collide with the solver
# protocols that share a clique's mailbox
TAG_WAL = 7301          # leader → follower: one live WAL record
TAG_CATCHUP_REQ = 7302  # follower → leader: {"from_seq": n}
TAG_CATCHUP = 7303      # leader → follower: catch-up frame stream
TAG_WAL_ACK = 7304      # follower → leader: {"applied", "rank", "term"}

FRAME_WAL = 0       # one WAL record (keys of MutationLog.append + seq)
FRAME_SNAPSHOT = 1  # full epoch entries (gap too wide — resync)
FRAME_END = 2       # {"through_seq": n} — catch-up stream terminator

_FRAME_KINDS = (FRAME_WAL, FRAME_SNAPSHOT, FRAME_END)


class WalFrameError(StreamingError):
    """A wire frame failed to encode, decode, or identify itself — a
    damaged payload (bit-flip, truncation), a non-frame message on a
    frame tag, or an unknown ``_frame`` kind. Typed so transport
    corruption surfaces as one catchable error instead of the raw
    ``KeyError``/pickle taxonomy of whatever broke first (ISSUE 20
    satellite)."""


class WalQuorumError(StreamingError):
    """A quorum-ack mutation timed out before enough followers
    confirmed the sequence durable in their mirror journals. The write
    IS durable on the leader (journal-first) — the caller must treat it
    as indeterminate and retry idempotently (``write_id`` dedup), never
    as definitely-lost (ISSUE 20)."""

    def __init__(self, *, seq: int, acked: int, needed: int):
        super().__init__(
            f"quorum ack timeout: seq {seq} confirmed by {acked} "
            f"follower(s), needed {needed} — write is durable locally "
            f"but NOT quorum-replicated; retry idempotently")
        self.seq = int(seq)
        self.acked = int(acked)
        self.needed = int(needed)


def encode_frame(entries: Dict) -> np.ndarray:
    """Serialize a frame dict into a uint8 array: the same CRC'd v1
    checkpoint container the WAL writes, so one integrity format guards
    both rest and wire. Raises :class:`WalFrameError` on an
    unserializable frame."""
    bio = io.BytesIO()
    try:
        dump_checkpoint(entries, bio)
    except (CheckpointError, KeyError, ValueError, TypeError) as exc:
        raise WalFrameError(f"frame encode failed: {exc}") from exc
    return np.frombuffer(bio.getvalue(), np.uint8)


def decode_frame(payload: np.ndarray) -> Dict:
    """Inverse of :func:`encode_frame`. A damaged payload (bit-flip,
    truncation, wrong format) raises :class:`WalFrameError` carrying
    the underlying cause — never the raw ``KeyError``/pickle
    taxonomy."""
    try:
        raw = np.asarray(payload, np.uint8).tobytes()
        return load_checkpoint(io.BytesIO(raw))
    except (CheckpointError, KeyError, ValueError, TypeError,
            EOFError, OSError) as exc:
        raise WalFrameError(f"frame decode failed: {exc}") from exc


def frame_kind(frame: Dict) -> int:
    """The validated ``_frame`` kind of a decoded frame; raises
    :class:`WalFrameError` when the tag is missing or unknown (a
    well-formed container that is not a protocol frame)."""
    try:
        kind = int(frame["_frame"])
    except (KeyError, TypeError, ValueError) as exc:
        raise WalFrameError(
            f"frame has no usable _frame tag: {exc}") from exc
    if kind not in _FRAME_KINDS:
        raise WalFrameError(f"unknown _frame kind {kind}")
    return kind


@dataclass
class CatchupReport:
    """What one :meth:`WalFollower.catch_up` round did."""

    records: int          # WAL records replayed
    snapshot: bool        # True when the leader resync'd via snapshot
    seconds: float
    from_seq: int         # first sequence requested
    through_seq: int      # leader's applied horizon at serve time


class WalShipper:
    """Leader-side WAL replication for one :class:`StreamingIndex`.

    :meth:`attach` hooks the journal's ``on_append`` so every durable
    record streams to each follower rank on ``TAG_WAL``; the background
    poller (:meth:`start`) answers ``TAG_CATCHUP_REQ`` from the on-disk
    WAL — or with a full epoch snapshot when the requested range was
    already pruned (or the follower asks from sequence 0: the epoch-0
    build content never passes through the WAL). Replication is async:
    a dead follower's wire errors are counted (``ship_errors``,
    ``wal_ship_errors_total``) and tolerated — the leader's mutation
    path and the poller both survive, and catch-up heals the follower
    when it returns. Every OTHER worker error surfaces at :meth:`stop`,
    never swallowed (the Compactor discipline).
    """

    def __init__(self, index: StreamingIndex, mailbox, rank: int,
                 followers: Iterable[int], *,
                 poll_interval: float = 0.05,
                 acks: "str | int | None" = None,
                 ack_timeout: float = 10.0):
        if index.log is None:
            raise StreamingError(
                "WAL shipping needs a journaled index (directory=...)")
        self.index = index
        self.mailbox = mailbox
        self.rank = int(rank)
        self.followers = [int(f) for f in followers]
        if self.rank in self.followers:
            raise ValueError(f"rank {self.rank} cannot follow itself")
        self.poll_interval = float(poll_interval)
        if acks is None:
            acks = env.read("RAFT_TPU_WAL_QUORUM")
        if isinstance(acks, str) and acks not in ("async", "majority",
                                                  "all"):
            raise ValueError(
                f"acks must be 'async', 'majority', 'all' or a "
                f"positive follower count, got {acks!r}")
        if not isinstance(acks, str) and int(acks) < 1:
            raise ValueError(f"acks count must be >= 1, got {acks}")
        self.acks = acks
        self.ack_timeout = float(ack_timeout)
        self.shipped = 0
        self.ship_errors = 0
        self.catchups_served = 0
        self.quorum_waits = 0
        # per-follower highest acked sequence + bounded seq → send
        # walltime map feeding the wal_replication_lag_seconds gauge
        self._acked: Dict[int, int] = {}
        self._sent_at: "collections.OrderedDict[int, float]" = \
            collections.OrderedDict()
        self._ack_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def acks_needed(self) -> int:
        """How many FOLLOWER confirmations a mutation must collect
        before it returns (the leader's own journal is the +1 vote):
        0 in async mode, ⌈(n+1)/2⌉−1 for majority over the n+1-node
        fleet, every follower for ``all``."""
        if self.acks == "async":
            return 0
        n = len(self.followers) + 1            # fleet size incl. leader
        if self.acks == "majority":
            return max((n + 1 + 1) // 2 - 1, 0)
        if self.acks == "all":
            return len(self.followers)
        return min(int(self.acks), len(self.followers))

    # -- live shipping -------------------------------------------------

    def attach(self) -> "WalShipper":
        """Register on the journal's append-subscriber list. Exactly
        ONE shipper may source a journal (two would double-ship every
        record), but non-shipper subscribers — election heartbeater,
        scrub trigger — coexist freely (ISSUE 20). Idempotent for the
        same shipper instance. Quorum-ack modes also install the
        index's commit barrier so ``insert()/delete()`` block on
        follower confirmation."""
        log = self.index.log
        other = getattr(log, "_shipper", None)
        if other is self:
            return self
        if other is not None:
            raise StreamingError(
                "journal already has an on_append WAL-shipping hook")
        log._shipper = self
        log.add_on_append(self._on_append)
        if self.acks_needed() > 0:
            self.index._commit_barrier = self._quorum_barrier
        return self

    def detach(self) -> None:
        log = self.index.log
        if getattr(log, "_shipper", None) is self:
            log._shipper = None
        log.remove_on_append(self._on_append)
        if self.index._commit_barrier is self._quorum_barrier:
            self.index._commit_barrier = None

    def _on_append(self, rec: Dict) -> None:
        fr = dict(rec)
        fr["_frame"] = FRAME_WAL
        payload = encode_frame(fr)
        with self._ack_lock:
            self._sent_at[int(rec["seq"])] = time.monotonic()
            while len(self._sent_at) > 4096:
                self._sent_at.popitem(last=False)
        ok = 0
        for f in self.followers:
            # replication is async: a dead follower must never fail the
            # leader's mutation path (the record is already durable
            # locally — catch-up heals the follower when it returns)
            try:
                self.mailbox.put(self.rank, f, TAG_WAL, payload)
                ok += 1
            except (PeerFailedError, OSError) as exc:
                self.ship_errors += 1
                trace.record_event("wal_ship.ship_failed", follower=f,
                                   seq=int(rec["seq"]), error=repr(exc))
                if obs.enabled():
                    obs.inc("wal_ship_errors_total")
        self.shipped += 1
        if obs.enabled() and ok:
            obs.inc("wal_records_shipped_total", ok)

    # -- replication acks ---------------------------------------------

    def drain_acks(self) -> int:
        """Fold every queued follower ack into the per-follower acked
        cursor and the ``wal_replication_lag_seconds`` gauge; returns
        how many acks were processed. Runs on the poller thread AND
        inside the quorum wait — both sides only ever advance the
        cursor, so the race is benign."""
        n = 0
        for f in self.followers:
            while True:
                payload = self.mailbox.get_nowait(f, self.rank,
                                                  TAG_WAL_ACK)
                if payload is None:
                    break
                try:
                    ack = decode_frame(payload)
                    applied = int(ack["applied"])
                except (WalFrameError, KeyError, ValueError) as exc:
                    trace.record_event("wal_ship.bad_ack", follower=f,
                                       error=repr(exc))
                    continue
                n += 1
                with self._ack_lock:
                    prev = self._acked.get(f, -1)
                    self._acked[f] = max(prev, applied)
                    sent = self._sent_at.get(applied)
                if sent is not None and obs.enabled():
                    obs.set_gauge("wal_replication_lag_seconds",
                                  time.monotonic() - sent,
                                  follower=str(f))
        return n

    def acked_seq(self, follower: int) -> int:
        """Highest sequence this follower has confirmed durable."""
        with self._ack_lock:
            return self._acked.get(int(follower), -1)

    def _quorum_barrier(self, seq: int) -> None:
        """Block the committing mutation until ``acks_needed()``
        followers confirmed ``seq`` durable in their mirror journals.
        Installed as the index's commit barrier in quorum-ack modes —
        it runs AFTER the local journal+apply, so a timeout leaves the
        leader consistent and raises the typed
        :class:`WalQuorumError` (indeterminate, retry idempotently)."""
        need = self.acks_needed()
        if need <= 0:
            return
        self.quorum_waits += 1
        deadline = time.monotonic() + self.ack_timeout
        while True:
            self.drain_acks()
            with self._ack_lock:
                got = sum(1 for f in self.followers
                          if self._acked.get(f, -1) >= seq)
            if got >= need:
                return
            if time.monotonic() >= deadline:
                if obs.enabled():
                    obs.inc("wal_quorum_timeouts_total")
                raise WalQuorumError(seq=seq, acked=got, needed=need)
            time.sleep(0.001)

    # -- catch-up service ---------------------------------------------

    def serve_catchup_once(self) -> int:
        """Answer every queued catch-up request; returns how many."""
        served = 0
        for f in self.followers:
            req = self.mailbox.get_nowait(f, self.rank, TAG_CATCHUP_REQ)
            while req is not None:
                try:
                    self._serve(f, int(decode_frame(req)["from_seq"]))
                    served += 1
                except (PeerFailedError, OSError) as exc:
                    # follower died mid-stream: drop this round, keep
                    # the poller alive — it re-requests on restart
                    self.ship_errors += 1
                    trace.record_event("wal_ship.serve_failed",
                                       follower=f, error=repr(exc))
                    if obs.enabled():
                        obs.inc("wal_ship_errors_total")
                    break
                req = self.mailbox.get_nowait(f, self.rank,
                                              TAG_CATCHUP_REQ)
        return served

    def _serve(self, follower: int, from_seq: int) -> None:
        # snapshot the consistent (records, horizon, entries) triple
        # under the mutation lock: a mutation racing the walk could
        # otherwise journal a record newer than the entries we ship
        with self.index._lock:
            recs = {int(r["seq"]): r
                    for r in self.index.log.wal_records()}
            last = self.index._applied_seq
            want = list(range(max(from_seq, 0), last + 1))
            gap = from_seq <= 0 or any(s not in recs for s in want)
            snap = _epoch_entries(self.index) if gap else None
        frames: List[Dict] = []
        if snap is not None:
            snap = dict(snap)
            snap["_frame"] = FRAME_SNAPSHOT
            frames.append(snap)
            through = int(snap["wal_horizon"])
        else:
            for s in want:
                rec = dict(recs[s])
                rec["_frame"] = FRAME_WAL
                frames.append(rec)
            through = last
        frames.append({"_frame": FRAME_END, "through_seq": through})
        for fr in frames:
            self.mailbox.put(self.rank, follower, TAG_CATCHUP,
                             encode_frame(fr))
        self.catchups_served += 1
        trace.record_event("wal_ship.serve_catchup", follower=follower,
                           from_seq=from_seq, through_seq=through,
                           snapshot=snap is not None)

    # -- worker thread -------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.serve_catchup_once()
                self.drain_acks()
            except Exception as exc:  # noqa: BLE001 — surfaced at stop
                self._error = exc
                obs.record_failure(exc)
                trace.record_event("wal_ship.shipper_error",
                                   error=str(exc))
                return

    def start(self) -> "WalShipper":
        if self._thread is not None:
            raise StreamingError("shipper already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="raft-tpu-wal-shipper")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the poller and re-raise any failure it died on."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise StreamingError("wal shipper failed") from err

    def __enter__(self) -> "WalShipper":
        self.attach()
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
        self.detach()


class WalFollower:
    """Follower-side WAL application for one :class:`StreamingIndex`.

    Records apply in strict sequence order: duplicates (at-least-once
    delivery) are skipped, a gap raises
    :class:`~raft_tpu.neighbors.streaming.WalGapError` — which
    :meth:`drain` converts into a :meth:`catch_up` round against the
    leader (records when its WAL still has them, an
    :meth:`~raft_tpu.neighbors.streaming.StreamingIndex
    .install_snapshot` resync when it doesn't). Every applied record is
    mirrored into the follower's own journal FIRST (leader numbering),
    so a SIGKILL'd follower restarts from its epoch + mirrored WAL and
    resumes catch-up at exactly the right cursor.
    """

    def __init__(self, index: StreamingIndex, mailbox, rank: int,
                 leader: int, *, send_acks: bool = True):
        self.index = index
        self.mailbox = mailbox
        self.rank = int(rank)
        self.leader = int(leader)
        if self.rank == self.leader:
            raise ValueError(f"rank {self.rank} cannot follow itself")
        self.send_acks = bool(send_acks)
        self.applied = 0
        self.dups = 0
        self.resyncs = 0
        self.fenced = 0

    @property
    def applied_seq(self) -> int:
        """Highest sequence folded into the follower's index (its
        catch-up cursor — survives restart via the mirrored journal)."""
        return self.index._applied_seq

    def repoint(self, new_leader: int) -> None:
        """Re-point this follower at a NEW leader (the election-loser
        step, ISSUE 20): live records and catch-up rounds now flow
        from ``new_leader``; the cursor and mirrored journal carry
        over untouched — sequence numbers are fleet-wide, not
        per-leader."""
        new_leader = int(new_leader)
        if new_leader == self.rank:
            raise ValueError(
                f"rank {self.rank} cannot follow itself")
        old, self.leader = self.leader, new_leader
        trace.record_event("wal_ship.repoint", old_leader=old,
                           new_leader=new_leader, rank=self.rank)

    # -- record application -------------------------------------------

    def _ack(self) -> None:
        """Confirm our durable cursor to the leader (the quorum-ack
        vote AND the replication-lag sample — sent in async mode too,
        so the gauge works without the blocking mode's cost). A dead
        leader is tolerated: the ack is advisory, the election notices
        the death."""
        if not self.send_acks:
            return
        try:
            self.mailbox.put(
                self.rank, self.leader, TAG_WAL_ACK,
                encode_frame({"applied": self.index._applied_seq,
                              "rank": self.rank,
                              "term": self.index._term}))
        except (PeerFailedError, OSError):
            pass

    def apply_record(self, rec: Dict) -> bool:
        """Mirror + apply ONE shipped record; returns True when it
        advanced the index (False = duplicate). Raises
        :class:`WalGapError` when ``rec`` is not the next sequence and
        :class:`~raft_tpu.neighbors.streaming.TermFencedError` when it
        is stamped with a term OLDER than this replica's — a deposed
        leader's write, rejected before it can touch the journal."""
        seq = int(rec["seq"])
        with self.index._lock:
            term = int(rec.get("term", 0))
            cur = self.index._term
            if term < cur and seq >= self.index._term_start:
                # fence FIRST — a stale-term record at or past the
                # current term's boundary is a deposed leader's
                # divergent write (even as a duplicate seq); it must
                # learn to demote. Records BELOW the boundary
                # legitimately carry older terms (catch-up replays
                # history) and fall through to the dup/gap checks.
                self.fenced += 1
                if obs.enabled():
                    obs.inc("wal_fenced_records_total")
                raise TermFencedError(
                    stale_term=term, current_term=cur,
                    divergence=self.index._term_start)
            applied = self.index._applied_seq
            if seq <= applied:
                self.dups += 1
                return False
            if seq != applied + 1:
                raise WalGapError(expected=applied + 1, got=seq)
            if self.index.log is not None:
                self.index.log.append_mirror(
                    {k: v for k, v in rec.items() if k != "_frame"})
            # mark applied BEFORE the dispatch (the recovery-replay
            # discipline): an apply that repacks folds this record into
            # the epoch it commits, so the horizon must cover it
            self.index._applied_seq = seq
            if term > cur:
                self.index._term = term
            kind = int(rec["kind"])
            if kind == KIND_INSERT:
                ids = self.index._apply_insert(
                    np.asarray(rec["data"]),
                    np.asarray(rec["labels"], np.int64), journal=False)
                if "write_id" in rec:
                    self.index.note_write_id(int(rec["write_id"]), ids)
            elif kind == KIND_DELETE:
                self.index._apply_delete(
                    np.asarray(rec["data"], np.int64), journal=False)
            elif kind == KIND_CENTROIDS:
                self.index._repack_locked(
                    centroids=np.asarray(rec["data"], np.float32),
                    reason="refit_shipped")
            elif kind == KIND_TERM:
                new_t = int(np.asarray(rec["data"]).ravel()[0])
                self.index._term = max(self.index._term, new_t)
                self.index._term_start = seq
            else:
                raise StreamingError(
                    f"unknown shipped WAL record kind {kind}")
        self.applied += 1
        return True

    def drain(self, *, resync: bool = True) -> int:
        """Apply every queued live record; returns how many advanced
        the index. A detected gap triggers a :meth:`catch_up` when
        ``resync`` (the steady-state loop), else propagates (tests).
        Confirms the durable cursor back to the leader after every
        batch that moved it (or re-confirms on duplicates — the
        at-least-once resend path needs re-acks)."""
        n = 0
        saw = 0
        try:
            while True:
                payload = self.mailbox.get_nowait(self.leader,
                                                  self.rank, TAG_WAL)
                if payload is None:
                    return n
                rec = decode_frame(payload)
                if frame_kind(rec) != FRAME_WAL:
                    raise WalFrameError(
                        f"expected FRAME_WAL on TAG_WAL, got "
                        f"{rec.get('_frame')!r}")
                saw += 1
                try:
                    if self.apply_record(rec):
                        n += 1
                except WalGapError:
                    if not resync:
                        raise
                    rpt = self.catch_up()
                    n += rpt.records
                    # the gapped record is ≤ the catch-up horizon now —
                    # re-offer it so a post-horizon record still applies
                    if int(rec["seq"]) > self.index._applied_seq:
                        if self.apply_record(rec):
                            n += 1
        finally:
            if saw:
                self._ack()

    # -- catch-up ------------------------------------------------------

    def catch_up(self, *, timeout: Optional[float] = None
                 ) -> CatchupReport:
        """One request/stream round against the leader: ask for
        everything past our cursor, fold the reply (records or a full
        snapshot), and report. Metered as ``replica_catchup_seconds`` —
        the restart-to-converged time the durability benches track."""
        t0 = time.monotonic()
        from_seq = self.index._applied_seq + 1
        self.mailbox.put(self.rank, self.leader, TAG_CATCHUP_REQ,
                         encode_frame({"from_seq": from_seq}))
        records = 0
        snapshot = False
        through = self.index._applied_seq
        while True:
            frame = decode_frame(
                self.mailbox.get(self.leader, self.rank, TAG_CATCHUP,
                                 timeout))
            kind = frame_kind(frame)
            if kind == FRAME_END:
                through = int(frame["through_seq"])
                break
            if kind == FRAME_SNAPSHOT:
                self.index.install_snapshot(frame)
                snapshot = True
                self.resyncs += 1
            elif self.apply_record(frame):
                # a gap INSIDE the served stream is a protocol error —
                # let WalGapError propagate; duplicates are fine
                records += 1
        self._ack()
        dt = time.monotonic() - t0
        if obs.enabled():
            obs.observe("replica_catchup_seconds", dt)
            obs.inc("replica_catchups_total",
                    outcome="snapshot" if snapshot else "records")
        trace.record_event("wal_ship.catch_up", from_seq=from_seq,
                           through_seq=through, records=records,
                           snapshot=snapshot, seconds=round(dt, 4))
        return CatchupReport(records=records, snapshot=snapshot,
                             seconds=dt, from_seq=from_seq,
                             through_seq=through)


def bootstrap_follower(res, *, dim: int, n_lists: int,
                       metric: str = "l2",
                       directory: Optional[str] = None,
                       faults=None,
                       retain: Optional[int] = None) -> StreamingIndex:
    """A blank follower index (zero rows, placeholder centroids) whose
    first :meth:`WalFollower.catch_up` necessarily snapshot-resyncs
    (cursor −1 → the leader ships its full epoch entries, trained
    centroids included) — the disk-less spawn path: a brand-new replica
    converges to the leader's ``content_crc`` with no local history."""
    flat = _flat_from_live(np.zeros((0, dim), np.float32),
                           np.zeros((0,), np.int64),
                           np.zeros((n_lists, dim), np.float32), metric)
    log = (MutationLog(directory, retain=retain)
           if directory is not None else None)
    return StreamingIndex(flat, log=log, res=res, faults=faults)
