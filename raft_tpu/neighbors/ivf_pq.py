"""IVF-PQ: product-quantized sub-linear kNN composed from this tree's
own primitives (lineage: cuvs::neighbors::ivf_pq — the IVFADC design of
Jégou et al., "Product Quantization for Nearest Neighbor Search", TPAMI
2011; cuVS recomposes it from the same layers this repo owns: kmeans
quantizers, pairwise distance, gather, select_k).

Index layout (the TPU formulation): the IVF-Flat skeleton, with the raw
row payload replaced by PQ codes. The coarse quantizer partitions the
database into ``n_lists`` inverted lists exactly as IVF-Flat does
(:func:`raft_tpu.neighbors.ivf_flat._pack` — same SLOT_ALIGN padded
spans, same CSR ``starts``/``sizes``, same ascending-id stable order so
``extend`` == rebuild on fitting tail appends). Each row is stored as
its RESIDUAL against its list centroid, product-quantized: the ``d``
dims split into ``m`` subspaces of ``d/m`` dims, each encoded as the
index of the nearest of ``2**nbits`` per-subspace codebook centroids
(codebooks trained with the compiled-driver
:func:`raft_tpu.cluster.kmeans.kmeans_fit`, so checkpoint / deadline /
trace hooks ride along). The packed payload is ``[cap_total, m]``
uint8 — a d=128 float32 row becomes m=16 bytes, the 32x row compression
that lets ~10M×128 vectors sit where IVF-Flat held ~1M.

Asymmetric-distance search (ADC): per query, the query→codebook lookup
tables for every probed list are built as ONE batched contraction
(``einsum`` of the per-list query residuals against the codebook table —
the "one small matmul"), the probed spans' codes arrive through the same
single padded :func:`raft_tpu.matrix.take_rows` gather IVF-Flat uses,
and the LUT-sum either gathers per-code LUT entries or rides the
:func:`raft_tpu.matrix.epilogue.slot_onehot` one-hot contraction (MXU
formulation, preferred on the tpu backend; both spellings are
bit-identical — the one-hot adds exact zeros). Selection finishes in the
shared :func:`raft_tpu.matrix.epilogue.masked_topk` radix/top-k band.

Exactness + refinement: PQ codes are lossy, so the raw rows are kept
HOST-side (``db_host`` — deliberately never resident in device memory;
the device footprint is the compressed index). ``refine=r`` re-scores
the top ``max(k, r)`` ADC candidates against their raw rows (host
gather of just those rows, one small exact-distance launch) — the
recall-vs-latency lever the ``neighbors/ivf_pq_recall`` bench family
sweeps. ``nprobe >= n_lists`` delegates to
:func:`raft_tpu.neighbors.brute_force.knn` on the raw rows, so the
full-probe(+refine) setting is bit-identical to brute force, ties and
NaN rows included — the exactness boundary CI gates on.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core import trace
from raft_tpu.matrix.epilogue import masked_topk, slot_onehot
from raft_tpu.matrix.gather import take_rows
from raft_tpu.neighbors.ivf_flat import (_METRICS, SLOT_ALIGN,
                                         _coarse_labels, _pack,
                                         _resolve_metric, _use_radix)
from raft_tpu.util import precision
from raft_tpu.util.precision import with_matmul_precision

__all__ = ["IvfPqIndex", "build", "search", "extend", "SLOT_ALIGN"]

# rows encoded per device launch during build/extend (bounds the
# transient residual block; the packed index itself is the small thing)
_ENCODE_CHUNK = 1 << 16


@dataclasses.dataclass
class IvfPqIndex:
    """Built IVF-PQ index: coarse centroids + per-subspace codebooks +
    packed PQ codes in the IVF-Flat inverted-list layout.

    ``packed_codes`` is the device-resident payload (uint8, one byte
    per subspace per row); ``db_host`` keeps the ORIGINAL rows on the
    host for the refine stage and the exact nprobe>=n_lists delegation
    — it is never shipped wholesale to the device, which is the whole
    memory point. ``packed_ids`` is -1 in pad slots; ``starts``/
    ``sizes`` are the CSR span table; the host ``caps`` mirror is what
    ``extend`` consults without a device sync."""

    centroids: jnp.ndarray          # [n_lists, d] float32
    codebooks: jnp.ndarray          # [m, 2**nbits, d/m] float32
    packed_codes: jnp.ndarray       # [cap_total, m] uint8
    packed_ids: jnp.ndarray         # [cap_total] int32, -1 = pad slot
    starts: jnp.ndarray             # [n_lists] int32 (exclusive cumsum)
    sizes: jnp.ndarray              # [n_lists] int32 live rows per list
    caps: np.ndarray                # [n_lists] host int64 padded widths
    cap_max: int                    # static gather width = caps.max()
    n_db: int                       # live database rows
    metric: str
    db_host: np.ndarray = dataclasses.field(repr=False, compare=False,
                                            default=None)
    _raw_cache: Optional[jnp.ndarray] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def n_lists(self) -> int:
        return int(self.centroids.shape[0])

    @property
    def dim(self) -> int:
        return int(self.centroids.shape[1])

    @property
    def m(self) -> int:
        return int(self.codebooks.shape[0])

    @property
    def n_codes(self) -> int:
        return int(self.codebooks.shape[1])

    @property
    def nbits(self) -> int:
        return int(self.n_codes - 1).bit_length()

    @property
    def dsub(self) -> int:
        return int(self.codebooks.shape[2])

    def scanned_fraction(self, nprobe: int) -> float:
        """Fraction of the index a search at ``nprobe`` plans to scan
        (list-count fraction — the ``ivf_pq.search`` trace number)."""
        return min(1.0, nprobe / max(self.n_lists, 1))

    def device_bytes(self) -> int:
        """Device-resident index footprint — the number the compression
        claim is asserted FROM (packed arrays, not an estimate)."""
        return int(self.packed_codes.nbytes + self.packed_ids.nbytes
                   + self.centroids.nbytes + self.codebooks.nbytes
                   + self.starts.nbytes + self.sizes.nbytes)

    def raw(self) -> jnp.ndarray:
        """The ORIGINAL database rows (host mirror shipped on demand) —
        the refine oracle and the nprobe>=n_lists exact path. Cached;
        ``extend`` invalidates."""
        if self._raw_cache is None:
            self._raw_cache = jnp.asarray(self.db_host)
        return self._raw_cache

    def decode(self) -> np.ndarray:
        """Approximate reconstruction (list centroid + codebook
        entries) in original row order — the quantized view the ADC
        distances score against; the round-trip error bound tests
        measure against it."""
        ids = np.asarray(self.packed_ids)
        live = ids >= 0
        codes = np.asarray(self.packed_codes)[live].astype(np.int64)
        labels = np.repeat(np.arange(self.n_lists), self.caps)[live]
        cb = np.asarray(self.codebooks)
        parts = [cb[s][codes[:, s]] for s in range(self.m)]
        resid = np.concatenate(parts, axis=1)
        rows = np.asarray(self.centroids)[labels] + resid
        out = np.empty((self.n_db, self.dim), np.float32)
        out[ids[live]] = rows
        return out


def _encode(db, centroids, labels, codebooks) -> np.ndarray:
    """Residual PQ codes for ``db`` rows already routed to ``labels``:
    per subspace, nearest codebook entry through the SAME fused assign
    kernel the quantizer training uses — build and extend must encode a
    row identically or extend == rebuild breaks. Chunked host loop so
    the f32 residual transient never exceeds ``_ENCODE_CHUNK`` rows."""
    from raft_tpu.cluster.kmeans import _assign

    db = np.asarray(db)
    labels = np.asarray(labels)
    m, _, dsub = (int(s) for s in codebooks.shape)
    cents = jnp.asarray(centroids, jnp.float32)
    out = np.empty((db.shape[0], m), np.uint8)
    with precision.scope():
        for lo in range(0, db.shape[0], _ENCODE_CHUNK):
            rows = jnp.asarray(db[lo:lo + _ENCODE_CHUNK], jnp.float32)
            resid = rows - cents[jnp.asarray(labels[lo:lo + _ENCODE_CHUNK])]
            for s in range(m):
                sub = lax.slice_in_dim(resid, s * dsub, (s + 1) * dsub,
                                       axis=1)
                _, code = _assign(sub, codebooks[s])
                out[lo:lo + _ENCODE_CHUNK, s] = np.asarray(code)
    return out


def _train_codebooks(res, resid, n_codes: int, m: int, dsub: int,
                     max_iter: int, seed: int) -> jnp.ndarray:
    """Per-subspace codebooks via the compiled-driver
    :func:`~raft_tpu.cluster.kmeans.kmeans_fit` on the residual
    subvectors — one fit per subspace, each inheriting the chunk
    runner's checkpoint/deadline/trace hooks."""
    from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

    books = []
    for s in range(m):
        sub = lax.slice_in_dim(resid, s * dsub, (s + 1) * dsub, axis=1)
        params = KMeansParams(n_clusters=n_codes, max_iter=max_iter,
                              seed=seed + 101 + s)
        c, _, _, _ = kmeans_fit(res, params, sub)
        books.append(c)
    return jnp.stack(books).astype(jnp.float32)


def build(res, db, n_lists: int, metric: str = "l2", *, m: int = 8,
          nbits: int = 8, max_iter: int = 25, pq_max_iter: int = 10,
          seed: int = 0, train_rows: int = 65536, centroids=None,
          codebooks=None) -> IvfPqIndex:
    """Train the coarse quantizer + per-subspace codebooks and pack the
    residual PQ codes into the inverted-list layout.

    Both quantizers ride :func:`raft_tpu.cluster.kmeans.kmeans_fit`
    (the PR-8 compiled-driver path) unless supplied: a repack /
    extend-rebuild passes the trained ``centroids`` AND ``codebooks``
    through so routing and encoding are identical. Codebook training
    subsamples to ``train_rows`` residuals (deterministic in ``seed``)
    — quantizer quality saturates long before the full corpus, and the
    fit cost must not scale with n_db. ``d`` must split evenly into
    ``m`` subspaces; ``nbits <= 8`` keeps one byte per code."""
    db = jnp.asarray(db)
    if db.ndim != 2:
        raise ValueError(f"db must be [n, d], got {db.shape}")
    n, d = int(db.shape[0]), int(db.shape[1])
    if not 0 < n_lists <= n:
        raise ValueError(f"need 0 < n_lists <= n_db, got n_lists="
                         f"{n_lists}, n_db={n}")
    _resolve_metric(metric)
    if m < 1 or d % m:
        raise ValueError(f"m must divide d: d={d}, m={m}")
    if not 1 <= nbits <= 8:
        raise ValueError(f"nbits must be in [1, 8] (uint8 codes), got "
                         f"{nbits}")
    n_codes, dsub = 1 << nbits, d // m
    if centroids is None:
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        params = KMeansParams(n_clusters=n_lists, max_iter=max_iter,
                              seed=seed)
        centroids, _, _, _ = kmeans_fit(res, params,
                                        db.astype(jnp.float32))
    centroids = jnp.asarray(centroids, jnp.float32)
    if centroids.shape != (n_lists, d):
        raise ValueError(f"centroids must be [{n_lists}, {d}], got "
                         f"{centroids.shape}")
    labels = _coarse_labels(db, centroids)
    if codebooks is None:
        if n < n_codes:
            raise ValueError(f"need n_db >= 2**nbits = {n_codes} "
                             f"residuals to train codebooks, got {n}")
        sel = np.arange(n)
        if n > train_rows:
            sel = np.sort(np.random.default_rng(seed).choice(
                n, train_rows, replace=False))
        with precision.scope():
            resid = (db[sel].astype(jnp.float32)
                     - centroids[jnp.asarray(labels[sel])])
        codebooks = _train_codebooks(res, resid, n_codes, m, dsub,
                                     pq_max_iter, seed)
    codebooks = jnp.asarray(codebooks, jnp.float32)
    if codebooks.shape != (m, n_codes, dsub):
        raise ValueError(f"codebooks must be [{m}, {n_codes}, {dsub}], "
                         f"got {codebooks.shape}")
    codes = _encode(db, centroids, labels, codebooks)
    packed_codes, packed_ids, starts, counts, caps = _pack(
        codes, np.arange(n, dtype=np.int32), labels, n_lists)
    return IvfPqIndex(
        centroids=centroids, codebooks=codebooks,
        packed_codes=jnp.asarray(packed_codes),
        packed_ids=jnp.asarray(packed_ids),
        starts=jnp.asarray(starts, jnp.int32),
        sizes=jnp.asarray(counts, jnp.int32),
        caps=caps, cap_max=int(caps.max(initial=0)), n_db=n,
        metric=metric, db_host=np.asarray(db))


def extend(res, index: IvfPqIndex, new_rows) -> IvfPqIndex:
    """Append rows (new ids continue from ``n_db``): encode against the
    EXISTING quantizers and drop the codes into the padded tails when
    they fit — a pure append; any overflowing tail triggers a full
    repack via :func:`build` with the same centroids and codebooks.
    Both branches are bit-identical to that rebuild (same routing, same
    encoder, ascending-id stable pack — the IVF-Flat argument, verbatim,
    applied to the code payload)."""
    new_rows = np.asarray(new_rows, dtype=index.db_host.dtype)
    if new_rows.ndim != 2 or new_rows.shape[1] != index.dim:
        raise ValueError(f"new_rows must be [m, {index.dim}], got "
                         f"{new_rows.shape}")
    labels = _coarse_labels(new_rows, index.centroids)
    sizes = np.asarray(index.sizes, np.int64)
    add = np.bincount(labels, minlength=index.n_lists).astype(np.int64)
    full_db = np.concatenate([index.db_host, new_rows], axis=0)
    if np.any(sizes + add > index.caps):
        return build(res, full_db, index.n_lists, index.metric,
                     m=index.m, nbits=index.nbits,
                     centroids=index.centroids,
                     codebooks=index.codebooks)
    codes = _encode(new_rows, index.centroids, labels, index.codebooks)
    starts = np.asarray(index.starts, np.int64)
    order = np.argsort(labels, kind="stable")
    excl = np.zeros(index.n_lists, np.int64)
    np.cumsum(add[:-1], out=excl[1:])
    within = np.arange(len(labels)) - np.repeat(excl, add)
    slots = (starts + sizes)[labels[order]] + within
    packed_codes = np.asarray(index.packed_codes).copy()
    packed_ids = np.asarray(index.packed_ids).copy()
    new_ids = np.arange(index.n_db, index.n_db + len(labels),
                        dtype=np.int32)
    packed_codes[slots] = codes[order]
    packed_ids[slots] = new_ids[order]
    return IvfPqIndex(
        centroids=index.centroids, codebooks=index.codebooks,
        packed_codes=jnp.asarray(packed_codes),
        packed_ids=jnp.asarray(packed_ids),
        starts=index.starts,
        sizes=jnp.asarray(sizes + add, jnp.int32),
        caps=index.caps, cap_max=index.cap_max,
        n_db=index.n_db + int(new_rows.shape[0]), metric=index.metric,
        db_host=full_db)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _use_onehot_lut() -> bool:
    """MXU one-hot contraction vs per-code gather for the LUT-sum: the
    contraction wins where gathers are slow (tpu); the gather wins on
    the reference backends. Both spellings are bit-identical."""
    return jax.default_backend() == "tpu"


def _lut_sum(lut, codes, use_onehot: bool):
    """The ADC inner sum ``out[q,p,c] = Σ_s lut[q,p,s,codes[q,p,c,s]]``.

    ``use_onehot`` rides :func:`~raft_tpu.matrix.epilogue.slot_onehot`:
    each subspace's code column becomes a ``(·, n_codes)`` one-hot
    contracted against that subspace's LUT slice — an MXU batched
    matvec instead of a gather. The contraction's non-selected terms
    are exact zeros and BOTH spellings accumulate subspaces in the same
    sequential order, so the two return the same bits (XLA does not
    reassociate the chained f32 adds)."""
    qn, p, m, n_codes = (int(s) for s in lut.shape)
    idx = codes.astype(jnp.int32)
    acc = jnp.zeros(idx.shape[:3], jnp.float32)
    if use_onehot:
        for s in range(m):
            oh = slot_onehot(idx[..., s].reshape(-1, 1), n_codes)
            oh = oh.reshape(idx.shape[:3] + (n_codes,))
            acc = acc + jnp.einsum("qpcj,qpj->qpc", oh, lut[:, :, s])
        return acc
    qi = jnp.arange(qn, dtype=jnp.int32)[:, None, None]
    pi = jnp.arange(p, dtype=jnp.int32)[None, :, None]
    for s in range(m):
        acc = acc + lut[qi, pi, s, idx[..., s]]
    return acc


def _adc_topk(queries, centroids, codebooks, packed_codes, packed_ids,
              starts, sizes, *, k: int, nprobe: int, cap_max: int,
              metric: str, use_radix: bool, use_onehot: bool):
    """The ADC probe scan up to (but not including) the metric
    finalize: coarse pairwise -> top-nprobe lists -> ONE batched
    query-residual × codebook contraction (the per-list LUTs) -> one
    padded span gather of the codes -> LUT-sum -> radix / top_k
    epilogue. Returns RAW ascending selection keys plus ids, the same
    mergeable form as :func:`raft_tpu.neighbors.ivf_flat._probe_topk`."""
    kernel = _METRICS[metric]
    m, n_codes, dsub = (int(s) for s in codebooks.shape)
    with precision.scope():
        q = queries.astype(jnp.float32)
        c = centroids.astype(jnp.float32)
        cb = codebooks.astype(jnp.float32)
        qn = q.shape[0]
        ip = q @ c.T
        if kernel == "l2":
            coarse = (jnp.sum(c * c, axis=1)[None, :] - 2.0 * ip
                      + jnp.sum(q * q, axis=1)[:, None])
        else:
            coarse = -ip
        _, probed = lax.top_k(-coarse, nprobe)          # [q, nprobe]
        # per-(query, probed-list) LUTs as one batched contraction:
        # l2:    lut[q,p,s,j] = ||cb[s,j]||^2 - 2 r_{q,p,s}·cb[s,j],
        #        base[q,p]    = ||r_{q,p}||^2   (r = q - c_probed)
        # inner: lut[q,s,j]   = -q_s·cb[s,j]  (list-independent),
        #        base[q,p]    = -q·c_probed
        if kernel == "l2":
            resid = q[:, None, :] - c[probed]           # [q, p, d]
            r = resid.reshape(qn, nprobe, m, dsub)
            cross = jnp.einsum("qpmd,mjd->qpmj", r, cb)
            cb_sq = jnp.sum(cb * cb, axis=-1)           # [m, j]
            lut = cb_sq[None, None] - 2.0 * cross
            base = jnp.sum(resid * resid, axis=-1)      # [q, p]
        else:
            cross = jnp.einsum("qmd,mjd->qmj",
                               q.reshape(qn, m, dsub), cb)
            lut = jnp.broadcast_to(-cross[:, None],
                                   (qn, nprobe, m, n_codes))
            base = -jnp.take_along_axis(ip, probed, axis=1)
        codes, _ = take_rows(None, packed_codes, starts[probed],
                             sizes[probed], cap_max)
        ids, valid = take_rows(None, packed_ids, starts[probed],
                               sizes[probed], cap_max, fill_value=-1)
        adc = _lut_sum(lut, codes, use_onehot)          # [q, p, cap]
        dist = base[:, :, None] + adc
        L = nprobe * cap_max
        dist = dist.reshape(qn, L)
        ids = ids.reshape(qn, L)
        valid = valid.reshape(qn, L)
        vals, pos = masked_topk(dist, valid, k, use_radix=use_radix)
        out_ids = jnp.take_along_axis(ids, pos, axis=1)
        out_ids = jnp.where(jnp.isfinite(vals), out_ids, -1)
        return vals, out_ids


def _search_body(queries, centroids, codebooks, packed_codes,
                 packed_ids, starts, sizes, *, k: int, nprobe: int,
                 cap_max: int, metric: str, use_radix: bool,
                 use_onehot: bool):
    """The traced ADC scan (:func:`_adc_topk` + metric finalize).
    Row-independent per query — the serving invariant."""
    from raft_tpu.neighbors.brute_force import _finalize

    vals, out_ids = _adc_topk(
        queries, centroids, codebooks, packed_codes, packed_ids,
        starts, sizes, k=k, nprobe=nprobe, cap_max=cap_max,
        metric=metric, use_radix=use_radix, use_onehot=use_onehot)
    return _finalize(vals, metric), out_ids


_search_jit = functools.partial(
    jax.jit, static_argnames=("k", "nprobe", "cap_max", "metric",
                              "use_radix", "use_onehot"))(_search_body)


def _refine_body(queries, cand, cand_ids, *, k: int, metric: str,
                 use_radix: bool):
    """Exact re-score of the gathered raw candidate rows: the same
    expanded fine-distance form as the IVF-Flat probe scan, masked
    top-k over ``cand_ids >= 0``, metric finalize. Row-independent."""
    from raft_tpu.neighbors.brute_force import _finalize

    kernel = _METRICS[metric]
    with precision.scope():
        q = queries.astype(jnp.float32)
        c = cand.astype(jnp.float32)
        ipf = jnp.einsum("qd,qrd->qr", q, c)
        if kernel == "l2":
            dist = (jnp.sum(c * c, axis=-1) - 2.0 * ipf
                    + jnp.sum(q * q, axis=1)[:, None])
        else:
            dist = -ipf
        vals, pos = masked_topk(dist, cand_ids >= 0, k,
                                use_radix=use_radix)
        out_ids = jnp.take_along_axis(cand_ids, pos, axis=1)
        out_ids = jnp.where(jnp.isfinite(vals), out_ids, -1)
        return _finalize(vals, metric), out_ids


_refine_jit = functools.partial(
    jax.jit, static_argnames=("k", "metric", "use_radix"))(_refine_body)


@with_matmul_precision
def search(res, index: IvfPqIndex, queries, k: int, nprobe: int,
           refine: int = 0) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest database rows per query over ``nprobe`` probed lists,
    scored by asymmetric PQ distance. Returns (distances [q, k],
    indices [q, k]) nearest first, original row numbering; rows with
    fewer than k reachable candidates pad with index -1 / distance +inf.

    ``refine=r`` re-scores the top ``max(k, r)`` ADC candidates against
    their RAW rows (host-side ``db_host`` gather + one exact-distance
    launch) — distances become exact for the surviving candidates, and
    recall recovers most of the quantization loss for r a few multiples
    of k. ``refine=0`` returns pure ADC distances (approximate).

    ``nprobe >= n_lists`` scans everything: delegates to
    :func:`raft_tpu.neighbors.brute_force.knn` on the raw rows —
    bit-identical to brute force (ties/NaN included), the exactness
    boundary CI gates on, with any ``refine`` trivially satisfied.

    Admission (the PR-5 contract): with a ``runtime.limits`` budget
    active, a launch whose LUT block + gathered code tile would overrun
    it degrades to query-row chunks (bit-identical — rows are
    independent) or raises
    :class:`~raft_tpu.runtime.limits.RejectedError`. Every search
    records an ``ivf_pq.search`` trace event carrying nprobe, refine
    and the scanned fraction.
    """
    from raft_tpu.runtime import limits

    queries = jnp.asarray(queries)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be [q, {index.dim}], got "
                         f"{queries.shape}")
    if not 0 < k <= index.n_db:
        raise ValueError(f"need 0 < k <= n_db, got k={k}, "
                         f"n_db={index.n_db}")
    if nprobe <= 0:
        raise ValueError(f"need nprobe > 0, got {nprobe}")
    if refine < 0:
        raise ValueError(f"need refine >= 0, got {refine}")
    metric = index.metric
    if nprobe >= index.n_lists:
        from raft_tpu.neighbors.brute_force import knn

        trace.record_event("ivf_pq.search", nprobe=index.n_lists,
                           n_lists=index.n_lists, k=k, refine=refine,
                           scanned_frac=1.0, path="exact")
        return knn(res, index.raw(), queries, k, metric=metric)
    rr = max(k, int(refine))
    probe_rows = nprobe * index.cap_max
    if probe_rows < rr:
        raise ValueError(
            f"nprobe={nprobe} reaches at most {probe_rows} candidates "
            f"< max(k, refine)={rr}; raise nprobe (>= n_lists scans "
            f"exactly)")
    trace.record_event("ivf_pq.search", nprobe=nprobe,
                       n_lists=index.n_lists, k=k, refine=refine,
                       scanned_frac=round(
                           index.scanned_fraction(nprobe), 4),
                       path="ivf_pq")
    use_radix = _use_radix(probe_rows, rr, index.packed_ids, queries)
    use_onehot = _use_onehot_lut()
    run_adc = functools.partial(
        _search_jit, centroids=index.centroids,
        codebooks=index.codebooks, packed_codes=index.packed_codes,
        packed_ids=index.packed_ids, starts=index.starts,
        sizes=index.sizes, k=rr, nprobe=nprobe, cap_max=index.cap_max,
        metric=metric, use_radix=use_radix, use_onehot=use_onehot)

    def run(qblock):
        vals, ids = run_adc(queries=qblock)
        if refine <= 0:
            return vals, ids
        ids_np = np.asarray(ids)
        cand = index.db_host[np.maximum(ids_np, 0)]
        return _refine_jit(qblock, jnp.asarray(cand), ids, k=k,
                           metric=metric,
                           use_radix=_use_radix(rr, k, ids, qblock))

    budget = limits.active_budget()
    if budget is not None:
        op = "neighbors.ivf_pq_search"
        qn = int(queries.shape[0])
        itemsize = index.db_host.dtype.itemsize
        dims = dict(nprobe=nprobe, probe_rows=probe_rows,
                    n_dims=index.dim, k=rr, m=index.m,
                    n_codes=index.n_codes, refine=int(refine),
                    itemsize=itemsize)
        est = limits.estimate_bytes(
            op, n_queries=qn,
            packed_rows=int(index.packed_codes.shape[0]), **dims)
        if not limits.admit(op, est, budget=budget):
            # degrade: row-chunk the queries — per-row results are
            # independent of batch shape, so the bits are identical
            fixed_bytes = (index.packed_codes.nbytes
                           + index.packed_ids.nbytes
                           + index.codebooks.nbytes
                           + index.centroids.nbytes)
            per_row = limits.estimate_bytes(op, n_queries=1, **dims)
            chunk = (budget.limit_bytes - fixed_bytes) // max(per_row,
                                                              1)
            if chunk < 1:
                limits.reject(op, est, budget=budget,
                              detail="even a single query row's LUT + "
                                     "gathered code tile overflows the "
                                     "budget")
            limits.record_degraded(op)
            outs = [run(queries[i:i + int(chunk)])
                    for i in range(0, qn, int(chunk))]
            return (jnp.concatenate([o[0] for o in outs], axis=0),
                    jnp.concatenate([o[1] for o in outs], axis=0))
    return run(queries)
