"""Brute-force k-NN (lineage: cuvs::neighbors::brute_force, built on this
repo's analogues of the layers it consumes — the contraction engine's
pairwise tiles, matrix/select_k's tournament).

TPU formulation: the database streams through in column tiles under
`lax.scan`; each step computes a queries×tile distance block with the
fused metric epilogue (MXU) and folds it into the running per-query
top-k via one select over the [k | tile-top-k] candidate pool — HBM
traffic O(q·n_tiles·k) beyond the required reads, never the full q×n
matrix.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core import trace
from raft_tpu.linalg.contractions import pairwise_pallas
from raft_tpu.matrix.epilogue import masked_topk
from raft_tpu.util.math import cdiv, round_up_to_multiple
from raft_tpu.util.precision import with_matmul_precision



_METRIC_ALIASES = {"l2": "l2", "sqeuclidean": "l2", "euclidean": "l2",
                   "cosine": "cosine", "inner": "inner",
                   # unexpanded metrics (ref: brute-force kNN accepts the
                   # full pairwise vocabulary): VPU reduction tile
                   # (contractions.pairwise_unexpanded_pallas)
                   "l1": "l1", "manhattan": "l1", "cityblock": "l1",
                   "linf": "linf", "chebyshev": "linf",
                   "canberra": "canberra"}

_UNEXPANDED = ("l1", "linf", "canberra")


def _tile_distances(queries, tile_db, metric: str):
    if metric in _UNEXPANDED:
        from raft_tpu.linalg.contractions import pairwise_unexpanded_pallas

        return pairwise_unexpanded_pallas(queries, tile_db, metric)
    return pairwise_pallas(queries, tile_db, metric=metric)


def _resolve_metric(metric: str) -> str:
    kernel_metric = _METRIC_ALIASES.get(metric)
    if kernel_metric is None:
        raise ValueError(f"unknown metric {metric!r}")
    return kernel_metric


def _validate(db, queries, k: int) -> None:
    if db.ndim != 2 or queries.ndim != 2 or db.shape[1] != queries.shape[1]:
        raise ValueError(
            f"shape mismatch: db {db.shape} vs queries {queries.shape}")
    if not 0 < k <= db.shape[0]:
        raise ValueError(f"need 0 < k <= n_db, got k={k}, n={db.shape[0]}")


def _finalize(vals, metric: str):
    if metric == "euclidean":
        return jnp.sqrt(jnp.maximum(vals, 0.0))
    if metric in ("l2", "sqeuclidean"):
        return jnp.maximum(vals, 0.0)
    if metric == "inner":
        return -vals                   # back to similarity, desc order
    return vals


def _clamp_tile(tile: int, k: int, n: int) -> int:
    """Tile width: lane-aligned, no wider than the (padded) database, and
    never below k — the per-tile lax.top_k needs k ≤ tile."""
    t = min(round_up_to_multiple(tile, 128), round_up_to_multiple(n, 128))
    return max(t, round_up_to_multiple(k, 128))


@functools.partial(jax.jit, static_argnames=("k", "tile", "metric"))
def _knn_scan(queries, db, k: int, tile: int, metric: str, n_valid=None):
    """Running top-k over database column tiles. ``n_valid`` (traced
    scalar, default = db rows) masks trailing padded rows — the MNMG path
    passes each shard's true row count."""
    q, d = queries.shape
    n = db.shape[0]
    if n_valid is None:
        n_valid = jnp.int32(n)
    n_tiles = cdiv(n, tile)
    npad = n_tiles * tile
    dbp = jnp.pad(db, ((0, npad - n), (0, 0)))
    tiles = dbp.reshape(n_tiles, tile, d)
    offsets = jnp.arange(n_tiles, dtype=jnp.int32) * tile

    from raft_tpu.util.pallas_utils import join_vma, pcast_to

    vma, _ = join_vma(queries, db)
    init = pcast_to(vma, jnp.full((q, k), jnp.inf, jnp.float32),
                    jnp.zeros((q, k), jnp.int32))

    def step(carry, inp):
        best_v, best_i = carry
        tile_db, off = inp
        dist = _tile_distances(queries, tile_db, metric)
        col = lax.broadcasted_iota(jnp.int32, dist.shape, 1) + off
        # padded db rows masked out of the tournament by the shared
        # scoring epilogue (epilogue.masked_topk); tile top-k (min)
        tv, tp = masked_topk(dist, col < n_valid, k, use_radix=False)
        ti = jnp.take_along_axis(col, tp, axis=1)
        pool_v = jnp.concatenate([best_v, tv], axis=1)
        pool_i = jnp.concatenate([best_i, ti], axis=1)
        mv, mp = lax.top_k(-pool_v, k)
        return (-mv, jnp.take_along_axis(pool_i, mp, axis=1)), None

    (vals, idx), _ = lax.scan(step, init, (tiles, offsets))
    return vals, idx


def _chunk_for(q: int, n: int, k: int, tile_cap: int = 0) -> int:
    """Database chunk width for the radix path: large enough that the
    per-chunk radix select amortizes (the whole point — fewer, bigger
    selects), small enough that the materialized (q, chunk) f32 distance
    block stays under ~512 MB (cap rounded DOWN to lane alignment — the
    bound is a promise, not a hint). ``tile_cap``: a caller-supplied
    explicit tile is ALSO a memory bound — the chunk never exceeds it.
    Returns 0 when the radix path should not run: short databases,
    k outside the preferred band (radix_select.preferred — shared with
    select_k AUTO, incl. its MIN_COLS floor), or a cap below that
    floor."""
    from raft_tpu.matrix import radix_select

    floor = radix_select.MIN_COLS
    cap = (512 << 20) // max(q * 4, 1)
    cap -= cap % 128                  # round DOWN: honor the bound
    if tile_cap:
        cap = min(cap, tile_cap)
    if cap < floor:
        return 0                      # block cap unmeetable at this q
    chunk = min(round_up_to_multiple(n, 128), 1 << 20, cap)
    if n < 2 * floor or not radix_select.preferred(chunk, k):
        return 0
    if not radix_select.supports(jnp.float32, chunk, k):
        return 0
    return chunk


@functools.partial(jax.jit, static_argnames=("k", "chunk", "metric"))
def _knn_chunked(queries, db, k: int, chunk: int, metric: str,
                 n_valid=None):
    """Chunked-radix formulation: materialize a (q, chunk) distance
    block per step (MXU-rate), radix-select its top-k (the grid showed
    lax.top_k ~50x under the bandwidth roofline in this regime — the
    per-TILE top_k of the scan path was the old bottleneck), then merge
    into the running best via one cheap (q, 2k) top_k."""
    q, d = queries.shape
    n = db.shape[0]
    if n_valid is None:
        n_valid = jnp.int32(n)
    n_chunks = cdiv(n, chunk)
    npad = n_chunks * chunk
    dbp = jnp.pad(db, ((0, npad - n), (0, 0)))
    tiles = dbp.reshape(n_chunks, chunk, d)
    offsets = jnp.arange(n_chunks, dtype=jnp.int32) * chunk

    from raft_tpu.util.pallas_utils import join_vma, pcast_to

    vma, _ = join_vma(queries, db)
    init = pcast_to(vma, jnp.full((q, k), jnp.inf, jnp.float32),
                    jnp.zeros((q, k), jnp.int32))

    def step(carry, inp):
        best_v, best_i = carry
        tile_db, off = inp
        dist = _tile_distances(queries, tile_db, metric)
        col = lax.broadcasted_iota(jnp.int32, dist.shape, 1) + off
        tv, tp = masked_topk(dist, col < n_valid, k, use_radix=True)
        pool_v = jnp.concatenate([best_v, tv], axis=1)
        pool_i = jnp.concatenate([best_i, tp + off], axis=1)
        mv, mp = lax.top_k(-pool_v, k)
        return (-mv, jnp.take_along_axis(pool_i, mp, axis=1)), None

    (vals, idx), _ = lax.scan(step, init, (tiles, offsets))
    return vals, idx


@with_matmul_precision
def knn(res, db, queries, k: int, metric: str = "l2",
        tile: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest database rows per query. Returns (distances [q, k],
    indices [q, k]), nearest first.

    ``metric``: 'l2' (squared L2), 'sqeuclidean' (alias), 'euclidean'
    (rooted), 'cosine', or 'inner' (largest inner product first).

    ``tile``: explicit working-block width; also acts as a memory bound
    on the chunked path's distance block (an explicit small tile forces
    the scan path rather than being silently ignored). Default: auto.

    Dispatch (:func:`knn_plan` is the single source of truth): k <= 256
    runs the fused distance+top-k kernel
    (:mod:`raft_tpu.neighbors.fused_topk` — distances never leave VMEM,
    merges bound-gated; round-5 capture showed every materializing
    formulation select-bound at ~1.3 G items/s). Above k=256 the
    digit-histogram radix chains as the epilogue (:func:`_knn_chunked`:
    per-chunk distance blocks selected at bandwidth class — distances
    never round-trip through materialize+full-select); only databases
    too short for the radix floor fall to the streaming scan with
    per-tile top_k (:func:`_knn_scan`).

    Admission (ISSUE 5): with a ``runtime.limits`` work budget active, a
    launch whose monolithic q×n distance block would overrun it is
    degraded by tightening ``tile`` to the largest budget-fitting width
    — the existing streamed top-k machinery then bounds the materialized
    block, and per-element distances (hence the selected top-k values)
    are identical across tile widths. A request that cannot fit even the
    minimum k-wide tile raises
    :class:`~raft_tpu.runtime.limits.RejectedError` with the estimate.
    With no budget active the dispatch is untouched.

    >>> import numpy as np
    >>> from raft_tpu.neighbors import knn
    >>> db = np.array([[0., 0.], [1., 0.], [5., 5.]], np.float32)
    >>> d, i = knn(None, db, np.array([[0.9, 0.]], np.float32), k=2)
    >>> np.asarray(i).tolist()
    [[1, 0]]
    """
    from raft_tpu.runtime import limits
    from raft_tpu.util.pallas_utils import interpret_needs_ref

    db = jnp.asarray(db)
    queries = jnp.asarray(queries)
    _validate(db, queries, k)
    kernel_metric = _resolve_metric(metric)

    budget = limits.active_budget()
    if budget is not None:
        op = "neighbors.brute_force_knn"
        q, n = queries.shape[0], db.shape[0]
        est = limits.estimate_bytes(op, n_queries=q, n_db=n,
                                    n_dims=db.shape[1], k=k,
                                    itemsize=db.dtype.itemsize)
        if not limits.admit(op, est, budget=budget):
            # degrade: cap the db tile so the streamed (q, tile) f32
            # distance block + resident operands + running best fit
            fixed = ((q + n) * db.shape[1] * db.dtype.itemsize
                     + q * k * 8)
            cap = (budget.limit_bytes - fixed) // max(q * 4, 1)
            cap -= cap % 128              # round DOWN: honor the bound
            if cap < round_up_to_multiple(k, 128):
                limits.reject(op, est, budget=budget,
                              detail="even the minimum k-wide tile "
                                     "overflows the budget")
            tile = int(cap if tile is None else min(tile, cap))
            limits.record_degraded(op)
    # interpret+vma cannot replay vma-carrying kernels — only there does
    # the dispatch fall back (compiled shard_map uses the fused path)
    from raft_tpu.neighbors import fused_topk

    path, chunk = knn_plan(queries.shape[0], db.shape[0], k,
                           metric=metric, tile=tile,
                           vma_blocked=interpret_needs_ref(db, queries))
    # host-side dispatch record (the serve-path gate and the dispatch
    # tests assert on it); under jit this fires once per compile
    trace.record_event("knn.dispatch", path=path, k=k,
                       n_queries=queries.shape[0], n_db=db.shape[0],
                       chunk=chunk)
    if path == "fused":
        vals, idx = fused_topk.knn_fused(
            queries.astype(jnp.float32), db.astype(jnp.float32), k,
            kernel_metric, tn=min(tile or 1024, 1024))
        return _finalize(vals, metric), idx
    if path == "radix":
        vals, idx = _knn_chunked(queries.astype(jnp.float32),
                                 db.astype(jnp.float32), k, chunk,
                                 kernel_metric)
    else:
        tile_w = _clamp_tile(tile or 8192, k, db.shape[0])
        vals, idx = _knn_scan(queries.astype(jnp.float32),
                              db.astype(jnp.float32), k, tile_w,
                              kernel_metric)
    return _finalize(vals, metric), idx


def knn_plan(n_queries: int, n_db: int, k: int, metric: str = "l2",
             tile: Optional[int] = None, vma_blocked: bool = False,
             n_lists: Optional[int] = None, nprobe: Optional[int] = None,
             pq: bool = False) -> Tuple[str, int]:
    """Pure dispatch predictor for :func:`knn`: ("ivf" | "ivf_pq" |
    "fused" | "radix" | "scan", chunk). knn() itself routes through
    this, so the answer can never drift from the real dispatch — the
    serving executor quotes it per warmed service and the dispatch
    tests assert on it. "radix" is the digit-histogram epilogue
    (:func:`_knn_chunked`): above the fused kernel's k <= 256 it is the
    only non-materialize+full-select path, per-chunk distances bounded
    and selected at bandwidth class. ``vma_blocked``: the caller saw
    vma-carrying operands under the interpreter
    (pallas_utils.interpret_needs_ref) — both Pallas paths fall back to
    the scan there. ``n_lists``/``nprobe``: an IVF caller
    (:mod:`raft_tpu.neighbors.ivf_flat` / :mod:`raft_tpu.neighbors
    .ivf_pq` / the serving Ivf[Pq]KnnService) quoting its route —
    partial probes take the probe scan, "ivf_pq" when ``pq`` marks the
    index as product-quantized (the ADC LUT formulation); nprobe >=
    n_lists is a full scan and falls through to the exact brute-force
    plan both delegate to."""
    from raft_tpu.neighbors import fused_topk

    kernel_metric = _resolve_metric(metric)
    if n_lists is not None and nprobe is not None and nprobe < n_lists:
        return ("ivf_pq" if pq else "ivf"), 0
    if (fused_topk.supports(k) and (tile is None or tile >= 128)
            and kernel_metric in ("l2", "cosine", "inner")
            and not vma_blocked):
        return "fused", 0
    chunk = _chunk_for(n_queries, n_db, k, tile_cap=tile or 0)
    if chunk and not vma_blocked:
        return "radix", chunk
    return "scan", 0


@with_matmul_precision
def knn_mnmg(res, db, queries, k: int, metric: str = "l2",
             tile: Optional[int] = None, mesh=None,
             data_axis: str = "data"
             ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MNMG brute-force k-NN: database rows sharded over ``data_axis``,
    queries replicated; per-shard running top-k, then one all-gather of
    the n_dev·k candidate pool and a final merge — the row-partitioned
    convention of the reference's MNMG algorithms
    (docs/source/using_raft_comms.rst) with the k-merge riding ICI.

    Returns replicated (distances [q, k], indices [q, k]) in GLOBAL
    database row numbering.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.core import resources as core_res

    db = jnp.asarray(db, jnp.float32)
    queries = jnp.asarray(queries, jnp.float32)
    _validate(db, queries, k)
    kernel_metric = _resolve_metric(metric)
    if mesh is None:
        mesh = core_res.get_mesh(core_res.default_resources(res))
    ndev = mesh.shape[data_axis]
    n = db.shape[0]
    per = cdiv(n, ndev)
    if k > per:
        # a single shard cannot hold k candidates; degenerate scale —
        # run single-device (the reference's MNMG paths assume k ≪ n/dev)
        return knn(res, db, queries, k, metric=metric, tile=tile)
    from raft_tpu.neighbors import fused_topk

    # L2 shards ride the fused distance+top-k kernel: its n_valid is
    # compile-static, so instead of a traced per-shard row count the
    # pad rows carry a LARGE sentinel coordinate — their distances are
    # astronomically large but finite (1e15² · d ≈ 1e32 ≪ f32 max), so
    # they can never survive a top-k that has k real candidates
    # anywhere in the pool. Cosine/inner pad rows are NOT self-excluding
    # (angle/sign of a sentinel is data-dependent), so those metrics
    # keep the scan body with its traced n_valid mask.
    # gate on interpret mode itself (not on these pre-shard_map plain
    # arrays): the shard body's operands ALWAYS carry vma, which the
    # interpreter cannot replay — compiled backend only
    from raft_tpu.util.pallas_utils import use_interpret

    use_fused = (fused_topk.supports(k) and kernel_metric == "l2"
                 and (tile is None or tile >= 128)
                 and not use_interpret())
    pad_val = 1e15 if use_fused else 0.0
    dbp = jnp.pad(db, ((0, per * ndev - n), (0, 0)),
                  constant_values=pad_val)
    tile_ = _clamp_tile(tile or 8192, k, per)

    def shard_fn(db_shard, q):
        me = lax.axis_index(data_axis)
        start = me * per
        if use_fused:
            v, i = fused_topk.knn_fused(q, db_shard, k, kernel_metric,
                                        tn=min(tile or 1024, 1024))
        else:
            # this shard's real row count (last shard may be short)
            n_local = jnp.clip(jnp.int32(n) - start, 0, per)
            v, i = _knn_scan(q, db_shard, k, tile_, kernel_metric,
                             n_valid=n_local)
        return v[None], (i + start)[None]            # [1, q, k] per shard

    @jax.jit
    def step(dbs, qs):
        # per-shard candidates out of shard_map, global k-merge outside
        # (XLA inserts the ICI gather for the replicated merge)
        av, ai = jax.shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P(data_axis), P()),
            out_specs=(P(data_axis), P(data_axis)))(dbs, qs)
        pool_v = jnp.moveaxis(av, 0, 1).reshape(qs.shape[0], ndev * k)
        pool_i = jnp.moveaxis(ai, 0, 1).reshape(qs.shape[0], ndev * k)
        mv, mp = lax.top_k(-pool_v, k)
        return -mv, jnp.take_along_axis(pool_i, mp, axis=1)

    dbs = jax.device_put(dbp, NamedSharding(mesh, P(data_axis)))
    qs = jax.device_put(queries, NamedSharding(mesh, P()))
    vals, idx = step(dbs, qs)
    return _finalize(vals, metric), idx
