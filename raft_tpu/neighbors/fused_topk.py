"""Fused distance + running top-k: the kNN hot path without the HBM
distance matrix.

The chunked/scan kNN formulations materialize (q, chunk) distance blocks
to HBM and hand them to a general select kernel; at 1M x 128, q=4096,
k=64 the select dominates end-to-end (round-5 capture: 3.6 s at
~1.3 G items/s select rate — the VPU sorting floor). This kernel keeps
every distance tile in VMEM and exploits what a general select cannot:
after a handful of database tiles the per-query k-th-best bound is tight
enough that almost no later tile contains ANY update, so the (expensive)
merge is gated on a one-pass compare + scalar any-reduce and simply
skipped for dead tiles. MXU computes tiles at matmul rate; the VPU pays
full merge cost only on the ~k·ln(n/tn) tiles that still matter.

Reference lineage: the fused L2-NN + warp-select composition
(cpp/include/raft/distance/detail/fused_distance_nn/ and
matrix/detail/select_k variants) — same fusion idea, re-derived for a
machine whose selection primitive is VPU passes instead of warp shuffles,
which makes BOUND-GATING (not a faster sorter) the structural win.

Epilogue algorithm (v3, round 5): INSERTION, not merge. The running best
(val, idx) lanes are kept SORTED ascending; each tile's distance block
becomes a candidate pool, and a `lax.while_loop` extracts the per-row
pool minimum and inserts it into the sorted best by one compare-shift
(`pltpu.roll` + prefix mask) per round, until no row's pool holds
anything below its own k-th bound. Work is O(actual updates): a tile
with no improving candidate costs ZERO rounds (the while condition is
the gate), and a tile with c of them costs ~c rounds at full 256-row
vector width.

Two prior shapes measured worse on chip (bench_full.jsonl,
neighbors/knn_l2 1M×128 q=4096 k=64): (a) block-gated k-round merges —
gates never skip at 256-row granularity, 1883 ms; (b) per-8-row-gated
merges — gates still fire ~60% of the time at k=64 (P(fire) =
1-e^{-8k/j} over j = 1..1024 db tiles) and each fired merge pays all
k rounds at 1/32 the vector width, 6193 ms. Insertion keeps the full
vector width AND pays per candidate, not per k: expected rounds per
256-row block stream are ~sum_j max_rows(Poisson(k/j)) ≈ k·ln(k) +
few·n_tiles ≈ thousands, not the merge formulations' hundreds of
thousands of vector passes.

Mosaic legality notes: reduce-min + masked-iota argmin
(contractions._mask_argmin rationale), `pltpu.roll` lane shifts (the
concat-of-slices alternative needs illegal relayouts), and
`lax.while_loop` with (tm, tn) vector carries + any-reduce condition —
probed via the deviceless AOT harness (ci/aot_compile.py) before this
kernel was written; a (tm, 1)-index vector gather from the (tm, 128)
best is NOT legal (same-shape operand rule), which is why the k-th
bound is read by a masked one-lane reduce.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.linalg.contractions import (_metric_tile, _metric_tile_split,
                                          _pad2, _split_operands,
                                          _use_split)
from raft_tpu.matrix.epilogue import (LANES, MAX_K,  # noqa: F401
                                      best_width as _best_width,
                                      insert_drain as _topk_body,
                                      masked_fold as _masked_fold,
                                      resolve_tn_sw,
                                      row_min_arg as _row_min_arg)
from raft_tpu.util.math import round_up_to_multiple
from raft_tpu.util.pallas_utils import (join_vma, out_struct, pallas_call)


def _tile_in_specs(tm: int, tn: int, kp: int, split: bool):
    """The (query-tile, db-tile) input BlockSpecs shared by every kernel
    in this file — ONE spelling so the tune probes price the same
    operand pipeline as the fused kernels (plain: x, y; split: xh, xl,
    xn, yh, yl, yn with norms as (1, t) lane rows)."""
    if not split:
        return [
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ]
    return [
        pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, tm), lambda i, j: (0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, tn), lambda i, j: (0, j),
                     memory_space=pltpu.VMEM),
    ]


def _topk_kernel(x_ref, y_ref, val_ref, idx_ref, *, tn: int, k: int,
                 n_valid: int, metric: str, sw: int = 0):
    j = pl.program_id(1)
    dist = _metric_tile(x_ref[:], y_ref[:], metric)
    _topk_body(dist, val_ref, idx_ref, j, tn, k, n_valid, sw)


def _topk_kernel_split(xh_ref, xl_ref, xn_ref, yh_ref, yl_ref, yn_ref,
                       val_ref, idx_ref, *, tn: int, k: int,
                       n_valid: int, metric: str, sw: int = 0):
    j = pl.program_id(1)
    dist = _metric_tile_split(xh_ref[:], xl_ref[:], xn_ref[:].T,
                              yh_ref[:], yl_ref[:], yn_ref[:], metric)
    _topk_body(dist, val_ref, idx_ref, j, tn, k, n_valid, sw)


@functools.partial(jax.jit,
                   static_argnames=("tm", "tn", "k", "n_valid", "metric",
                                    "sw"))
def _fused_topk_padded(x, y, tm: int, tn: int, k: int, n_valid: int,
                       metric: str, sw: int = 0):
    m, kp = x.shape
    n = y.shape[0]
    bw = _best_width(k)
    vma, (x, y) = join_vma(x, y)
    kernel = functools.partial(_topk_kernel, tn=tn, k=k, n_valid=n_valid,
                               metric=metric, sw=sw)
    return pallas_call(
        kernel,
        grid=(m // tm, n // tn),
        in_specs=_tile_in_specs(tm, tn, kp, split=False),
        out_specs=[
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((m, bw), jnp.float32, vma),
            out_struct((m, bw), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(x, y)


@functools.partial(jax.jit,
                   static_argnames=("tm", "tn", "k", "n_valid", "metric",
                                    "sw"))
def _fused_topk_padded_split(xh, xl, xn, yh, yl, yn, tm: int, tn: int,
                             k: int, n_valid: int, metric: str,
                             sw: int = 0):
    m, kp = xh.shape
    n = yh.shape[0]
    bw = _best_width(k)
    vma, (xh, xl, xn, yh, yl, yn) = join_vma(xh, xl, xn, yh, yl, yn)
    kernel = functools.partial(_topk_kernel_split, tn=tn, k=k,
                               n_valid=n_valid, metric=metric, sw=sw)
    return pallas_call(
        kernel,
        grid=(m // tm, n // tn),
        in_specs=_tile_in_specs(tm, tn, kp, split=True),
        out_specs=[
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((m, bw), jnp.float32, vma),
            out_struct((m, bw), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(xh, xl, xn, yh, yl, yn)


def _minonly_body(dist, val_ref, idx_ref, j, tn: int, n_valid: int):
    """Single running min-fold epilogue — the floor any fused
    formulation pays at these tiles (matmul rate + one vector pass per
    tile). benches/tune_knn.py times this against the full insertion
    kernel; the gap IS the epilogue's price."""
    col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1) + j * tn
    pool = jnp.where(col < n_valid, dist,
                     jnp.asarray(jnp.inf, jnp.float32))
    pm, pidx = _row_min_arg(pool, col)
    # outputs ride (1, tm) blocks — tm on lanes, the proven _lloyd_kernel
    # layout (a 1-wide lane dim forces degenerate vreg tiling); the
    # init-then-fold is epilogue.masked_fold (pidx is already global:
    # offset 0)
    _masked_fold(val_ref, idx_ref, pm, pidx, 0)


def _minonly_kernel(x_ref, y_ref, val_ref, idx_ref, *, tn: int,
                    n_valid: int, metric: str):
    j = pl.program_id(1)
    dist = _metric_tile(x_ref[:], y_ref[:], metric)
    _minonly_body(dist, val_ref, idx_ref, j, tn, n_valid)


def _minonly_kernel_split(xh_ref, xl_ref, xn_ref, yh_ref, yl_ref, yn_ref,
                          val_ref, idx_ref, *, tn: int, n_valid: int,
                          metric: str):
    j = pl.program_id(1)
    dist = _metric_tile_split(xh_ref[:], xl_ref[:], xn_ref[:].T,
                              yh_ref[:], yl_ref[:], yn_ref[:], metric)
    _minonly_body(dist, val_ref, idx_ref, j, tn, n_valid)


@functools.partial(jax.jit, static_argnames=("tm", "tn"))
def _minonly_probe(queries, db, tm: int = 256, tn: int = 1024):
    """Tune-only probe: 1-NN by running min at the fused kernel's grid
    (NOT a user API — knn callers want k results; see tune_knn.py).
    Mirrors knn_fused's precision dispatch (pre-split operands at tier
    'high') so the floor it measures prices the SAME distance pipeline
    as the kernel it is compared against."""
    q, d = queries.shape
    n = db.shape[0]
    tm = max(128, tm - tm % 128)   # (1, tm) output blocks: tm on lanes
    tn = max(128, min(tn - tn % 128, round_up_to_multiple(n, 128)))
    mp = round_up_to_multiple(q, tm)
    np_ = round_up_to_multiple(n, tn)
    kp = round_up_to_multiple(d, 128)
    grid = (mp // tm, np_ // tn)
    out_specs = [
        pl.BlockSpec((1, tm), lambda i, j: (0, i),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, tm), lambda i, j: (0, i),
                     memory_space=pltpu.VMEM),
    ]
    params = pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"))
    if _use_split(queries, db):
        ops = _split_operands(queries, db, mp, np_, kp)
        vma, ops = join_vma(*ops)
        vals, idx = pallas_call(
            functools.partial(_minonly_kernel_split, tn=tn, n_valid=n,
                              metric="l2"),
            grid=grid,
            in_specs=_tile_in_specs(tm, tn, kp, split=True),
            out_specs=out_specs,
            out_shape=[
                out_struct((1, mp), jnp.float32, vma),
                out_struct((1, mp), jnp.int32, vma),
            ],
            compiler_params=params,
        )(*ops)
    else:
        x, y = _pad2(queries, mp, kp), _pad2(db, np_, kp)
        vma, (x, y) = join_vma(x, y)
        vals, idx = pallas_call(
            functools.partial(_minonly_kernel, tn=tn, n_valid=n,
                              metric="l2"),
            grid=grid,
            in_specs=_tile_in_specs(tm, tn, kp, split=False),
            out_specs=out_specs,
            out_shape=[
                out_struct((1, mp), jnp.float32, vma),
                out_struct((1, mp), jnp.int32, vma),
            ],
            compiler_params=params,
        )(x, y)
    return vals[0, :q], idx[0, :q]


def supports(k: int) -> bool:
    """The fused path holds <= 2 vregs of sorted best per query row."""
    return 1 <= k <= MAX_K


def epilogue(k: int) -> str:
    """Which selection epilogue serves this k on the kNN hot path:
    "insert" (this kernel's in-VMEM bound-gated insertion, k <= MAX_K
    = 256) or "radix" — above the insertion band the digit-histogram
    radix select chains as the epilogue (brute_force._knn_chunked
    materializes bounded per-chunk distance blocks and selects each at
    bandwidth class; brute_force.knn_plan decides whether a concrete
    (q, n, k) actually clears the radix floor). The two bands share
    the boundary here so neither side can drift."""
    return "insert" if supports(k) else "radix"


def knn_fused(queries, db, k: int, metric: str = "l2",
              tm: int = 256, tn: int = 1024, sw=None):
    """Fused-kernel kNN: (vals [q, k], idx [q, k]), nearest first.

    Callers dispatch here for k <= 256 on the compiled backend (see
    brute_force.knn); inputs are f32 (cast by the caller), metric is the
    kernel vocabulary ('l2' squared / 'cosine' / 'inner'). ``sw`` sets
    the drain-strip width (0 = whole tile; None picks the spent
    epilogue lever — epilogue.DRAIN_SW when it divides the tile — which
    cuts the per-round drain extraction ~4x at the default tn=1024; see
    epilogue.insert_drain and the DRAIN_SW cost model). Output is
    identical for ANY sw (same candidate set, same tie contract)."""
    q, d = queries.shape
    n = db.shape[0]
    tm = min(tm, round_up_to_multiple(q, 8))
    tn, sw = resolve_tn_sw(tn, sw, n)     # shared strip-width contract
    mp = round_up_to_multiple(q, tm)
    np_ = round_up_to_multiple(n, tn)
    kp = round_up_to_multiple(d, 128)
    if _use_split(queries, db):
        vals, idx = _fused_topk_padded_split(
            *_split_operands(queries, db, mp, np_, kp), tm, tn, k, n,
            metric, sw)
    else:
        vals, idx = _fused_topk_padded(
            _pad2(queries, mp, kp), _pad2(db, np_, kp), tm, tn, k, n,
            metric, sw)
    return vals[:q, :k], idx[:q, :k]
