"""Fused distance + running top-k: the kNN hot path without the HBM
distance matrix.

The chunked/scan kNN formulations materialize (q, chunk) distance blocks
to HBM and hand them to a general select kernel; at 1M x 128, q=4096,
k=64 the select dominates end-to-end (round-5 capture: 3.6 s at
~1.3 G items/s select rate — the VPU sorting floor). This kernel keeps
every distance tile in VMEM and exploits what a general select cannot:
after a handful of database tiles the per-query k-th-best bound is tight
enough that almost no later tile contains ANY update, so the (expensive)
merge is gated on a one-pass compare + scalar any-reduce and simply
skipped for dead tiles. MXU computes tiles at matmul rate; the VPU pays
full merge cost only on the ~k·ln(n/tn) tiles that still matter.

Reference lineage: the fused L2-NN + warp-select composition
(cpp/include/raft/distance/detail/fused_distance_nn/ and
matrix/detail/select_k variants) — same fusion idea, re-derived for a
machine whose selection primitive is VPU passes instead of warp shuffles,
which makes BOUND-GATING (not a faster sorter) the structural win.

Merge algorithm (per live tile): the running best (val, idx) lanes are
kept SORTED ascending; the tile's candidates are consumed by k rounds of
a vectorized two-pointer merge — row-min + first-min argmin over the
tile pool, a masked one-lane reduce reads each row's current best at its
own pointer (Mosaic's vector gather demands same-shape operands, so a
(tm, 1)-index gather from the (tm, 128) best is NOT legal — the masked
reduce is), the smaller of the two is appended, and the consumed source
is masked (pool) or advanced past (pointer). Every op class is proven on
this backend: reduce-min, masked-iota argmin (contractions._mask_argmin
rationale), scalar any-reduce under pl.when (radix dead-chunk skip).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.linalg.contractions import (_metric_tile, _metric_tile_split,
                                          _pad2, _split_operands,
                                          _use_split)
from raft_tpu.util.math import round_up_to_multiple
from raft_tpu.util.pallas_utils import (join_vma, out_struct, pallas_call)

LANES = 128
MAX_K = LANES  # one vreg of best per query row; larger k takes other paths


def _row_min_arg(pool, col):
    """Per-row (min, first-min argmin) of a (tm, tn) pool — reduce-min +
    masked-iota, the Mosaic-safe argmin spelling (see
    contractions._mask_argmin for why lax.argmin is not used)."""
    pm = jnp.min(pool, axis=1, keepdims=True)
    sentinel = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
    pidx = jnp.min(jnp.where(pool == pm, col, sentinel), axis=1,
                   keepdims=True)
    return pm, pidx


def _merge_subgroup(val_ref, idx_ref, dist, col_g, g: int, k: int):
    """Merge one gated subgroup's candidate pool into its sorted
    running best (rows [g, g+GATE_ROWS) of the block).

    k rounds of vectorized two-pointer merge; O(k) passes over the
    subgroup's pool slice. The pool is READ-ONLY: instead of masking
    consumed elements (k live temporaries — a Mosaic stack-VMEM OOM at
    the bench shape), a per-row lexicographic (value, index) cursor
    excludes everything already taken, so per-round state is a handful
    of (rows, 1) vectors and the rounds ride a fori_loop. Ties prefer
    the running best (earlier database tiles, then smaller index within
    a tile via the first-min argmin) — the global smallest-index-wins
    rule."""
    tm = dist.shape[0]
    inf = jnp.asarray(jnp.inf, jnp.float32)
    sent = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
    lane = jax.lax.broadcasted_iota(jnp.int32, (tm, LANES), 1)
    best_v = val_ref[g:g + tm]
    best_i = idx_ref[g:g + tm]

    def round_(r, carry):
        out_v, out_i, bptr, pv, pi = carry
        # pool elements strictly after the (pv, pi) cursor, (value, col)
        # lexicographic — exactly the not-yet-consumed candidates
        elig = (dist > pv) | ((dist == pv) & (col_g > pi))
        pool = jnp.where(elig, dist, inf)
        pm, pidx = _row_min_arg(pool, col_g)
        sel = lane == bptr                    # exactly one lane per row
        bv = jnp.min(jnp.where(sel, best_v, inf), axis=1, keepdims=True)
        bi = jnp.min(jnp.where(sel, best_i, sent), axis=1, keepdims=True)
        use_b = bv <= pm
        pick_v = jnp.where(use_b, bv, pm)
        pick_i = jnp.where(use_b, bi, pidx)
        out_v = jnp.where(lane == r, pick_v, out_v)
        out_i = jnp.where(lane == r, pick_i, out_i)
        bptr = bptr + use_b.astype(jnp.int32)
        pv = jnp.where(use_b, pv, pm)
        pi = jnp.where(use_b, pi, pidx)
        return out_v, out_i, bptr, pv, pi

    init = (jnp.full((tm, LANES), jnp.inf, jnp.float32),
            jnp.zeros((tm, LANES), jnp.int32),
            jnp.zeros((tm, 1), jnp.int32),
            jnp.full((tm, 1), -jnp.inf, jnp.float32),
            jnp.full((tm, 1), -1, jnp.int32))
    out_v, out_i, _, _, _ = jax.lax.fori_loop(0, k, round_, init)
    val_ref[g:g + tm] = out_v
    idx_ref[g:g + tm] = out_i


GATE_ROWS = 8   # merge-gating granularity: one vreg of sublanes


def _topk_body(dist, val_ref, idx_ref, j, tn: int, k: int,
               n_valid: int):
    """Shared epilogue of the plain and split kernels: mask the tile's
    padding columns, then merge PER 8-QUERY SUBGROUP, each gated on its
    own rows' running k-th bound.

    Gating granularity is the whole design (round-5 capture, 19:20):
    one gate across a tm=256 block fires when ANY of 256 queries
    improves — probability 1-exp(-256·k/t) at database tile t, ~1 for
    every tile in a 1024-tile database, so the first version's merge
    NEVER skipped (1883 ms). Per-8-row gates skip with probability
    exp(-8·k/t): expected live merge events are ~sum_t 32·(1-e^{-512/t})
    ≈ 28k for the 1M-row bench — ~100 ms of merges instead of 16k full-
    block merges. Correctness never depends on a gate: a gate fires iff
    its rows have an improving candidate, and each merge runs the full
    k rounds."""
    tm = dist.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, dist.shape, 1)
    col_g = col + j * tn
    inf = jnp.asarray(jnp.inf, jnp.float32)
    dist = jnp.where(col_g < n_valid, dist, inf)

    @pl.when(j == 0)
    def _init():
        val_ref[:] = jnp.full((tm, LANES), jnp.inf, jnp.float32)
        idx_ref[:] = jnp.zeros((tm, LANES), jnp.int32)

    th = val_ref[:, k - 1:k]                          # current k-th best
    # one full-tile compare pass; per-subgroup any-reduces over its rows
    # (i32 max: bool any reduces through f64 under x64 — radix_select
    # precedent)
    upd = (dist < th).astype(jnp.int32)
    # column indices are row-independent: ONE fresh (GATE_ROWS, tn)
    # iota serves every subgroup — a sublane-SLICED iota value crashes
    # Mosaic's layout inference (Check failed: limits[i] <= dim(i),
    # bisected 19:28 via the deviceless harness); dist row-slices are
    # fine
    col_sub = (jax.lax.broadcasted_iota(jnp.int32, (GATE_ROWS,
                                                    dist.shape[1]), 1)
               + j * tn)
    for g in range(0, tm, GATE_ROWS):
        live_g = jnp.max(upd[g:g + GATE_ROWS]) > 0

        @pl.when(live_g)
        def _merge(g=g):
            _merge_subgroup(val_ref, idx_ref, dist[g:g + GATE_ROWS],
                            col_sub, g, k)


def _topk_kernel(x_ref, y_ref, val_ref, idx_ref, *, tn: int, k: int,
                 n_valid: int, metric: str):
    j = pl.program_id(1)
    dist = _metric_tile(x_ref[:], y_ref[:], metric)
    _topk_body(dist, val_ref, idx_ref, j, tn, k, n_valid)


def _topk_kernel_split(xh_ref, xl_ref, xn_ref, yh_ref, yl_ref, yn_ref,
                       val_ref, idx_ref, *, tn: int, k: int,
                       n_valid: int, metric: str):
    j = pl.program_id(1)
    dist = _metric_tile_split(xh_ref[:], xl_ref[:], xn_ref[:].T,
                              yh_ref[:], yl_ref[:], yn_ref[:], metric)
    _topk_body(dist, val_ref, idx_ref, j, tn, k, n_valid)


@functools.partial(jax.jit,
                   static_argnames=("tm", "tn", "k", "n_valid", "metric"))
def _fused_topk_padded(x, y, tm: int, tn: int, k: int, n_valid: int,
                       metric: str):
    m, kp = x.shape
    n = y.shape[0]
    vma, (x, y) = join_vma(x, y)
    kernel = functools.partial(_topk_kernel, tn=tn, k=k, n_valid=n_valid,
                               metric=metric)
    return pallas_call(
        kernel,
        grid=(m // tm, n // tn),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((m, LANES), jnp.float32, vma),
            out_struct((m, LANES), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(x, y)


@functools.partial(jax.jit,
                   static_argnames=("tm", "tn", "k", "n_valid", "metric"))
def _fused_topk_padded_split(xh, xl, xn, yh, yl, yn, tm: int, tn: int,
                             k: int, n_valid: int, metric: str):
    m, kp = xh.shape
    n = yh.shape[0]
    vma, (xh, xl, xn, yh, yl, yn) = join_vma(xh, xl, xn, yh, yl, yn)
    kernel = functools.partial(_topk_kernel_split, tn=tn, k=k,
                               n_valid=n_valid, metric=metric)
    return pallas_call(
        kernel,
        grid=(m // tm, n // tn),
        in_specs=[
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, kp), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tm), lambda i, j: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tn, kp), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tn), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, LANES), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((m, LANES), jnp.float32, vma),
            out_struct((m, LANES), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(xh, xl, xn, yh, yl, yn)


def supports(k: int) -> bool:
    """The fused path holds one vreg of sorted best per query row."""
    return 1 <= k <= MAX_K


def knn_fused(queries, db, k: int, metric: str = "l2",
              tm: int = 256, tn: int = 1024):
    """Fused-kernel kNN: (vals [q, k], idx [q, k]), nearest first.

    Callers dispatch here for k <= 128 on the compiled backend (see
    brute_force.knn); inputs are f32 (cast by the caller), metric is the
    kernel vocabulary ('l2' squared / 'cosine' / 'inner')."""
    q, d = queries.shape
    n = db.shape[0]
    tm = min(tm, round_up_to_multiple(q, 8))
    tn = max(128, tn - tn % 128)          # lane-aligned working width
    tn = min(tn, round_up_to_multiple(n, 128))
    mp = round_up_to_multiple(q, tm)
    np_ = round_up_to_multiple(n, tn)
    kp = round_up_to_multiple(d, 128)
    if _use_split(queries, db):
        vals, idx = _fused_topk_padded_split(
            *_split_operands(queries, db, mp, np_, kp), tm, tn, k, n,
            metric)
    else:
        vals, idx = _fused_topk_padded(
            _pad2(queries, mp, kp), _pad2(db, np_, kp), tm, tn, k, n,
            metric)
    return vals[:q, :k], idx[:q, :k]
