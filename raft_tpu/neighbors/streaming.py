"""Streaming index lifecycle: crash-safe mutation, zero-pause
compaction, drift-aware refit (ISSUE 17 — ROADMAP item 3).

The batch-offline IVF-Flat index (PR 9/11) becomes a mutable, servable
object with a FreshDiskANN-shaped lifecycle — mutation log + tombstones
+ background consolidation + atomic swap, never a serving pause:

- **insert** rides the padded-tail ``extend`` idiom: rows that fit the
  aligned list tails append in place (packed shapes unchanged — the
  serving executable never retraces), an overflowing tail triggers a
  full repack under a new epoch.
- **delete** sets a bit in a packed tombstone bitset over GLOBAL row
  ids. The bitset words AND into the probe scan's validity mask
  (:func:`raft_tpu.neighbors.ivf_flat._probe_topk` ``tomb_words``) —
  same array shape every delete, so the compiled search is reused
  unchanged and untouched ids score bit-identically.
- **journal**: every mutation is journaled to an epoch-stamped
  write-ahead log (``core/checkpoint.py`` containers — CRC-checked,
  atomically renamed) BEFORE it is applied, so a SIGKILL'd process
  replays to the exact pre-crash index.
- **compaction** (:class:`Compactor`): when the tombstone or
  tail-overflow fraction crosses its threshold, live rows repack into a
  double-buffered packed matrix off the serve path; the commit writes
  the new epoch file, prunes the superseded WAL, and atomically swaps
  the serve snapshot. Dying at ANY :meth:`FaultInjector.crash_point`
  leaves either the old or the new epoch fully intact — the recovery
  walk (:meth:`StreamingIndex.recover`) loads the newest intact epoch
  and replays only WAL records stamped with it.
- **drift → refit** (:class:`DriftGauge`): an EMA of ingested rows'
  nearest-centroid distance against the build-time baseline, exported
  as the ``streaming_drift_ratio`` gauge; crossing
  ``RAFT_TPU_DRIFT_THRESHOLD`` triggers mini-batch
  :func:`raft_tpu.cluster.kmeans.kmeans_partial_fit` on the recent-row
  reservoir and a repack under the refitted centroids.

Identity contract: external row ids are assigned at insert in arrival
order and NEVER renumbered — a repack packs live rows under their
original ids (:func:`ivf_flat._pack` takes explicit ids), so tombstone
bits and search results stay stable across compactions. The
crash-consistency witness is :meth:`StreamingIndex.content_crc`: a CRC
over the canonical live content (ids ‖ rows in id order ‖ centroids),
invariant to packing layout — equal before and after a pure compaction,
and equal between a recovered replica and a clean twin run.

Concurrency: one mutation lock serializes insert/delete/compact-commit;
searches NEVER take it — they read an immutable snapshot tuple swapped
atomically at commit (the serve tier reads the same snapshot through
``serve/ingest.StreamingKnnService.refresh``).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import re
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from raft_tpu import obs
from raft_tpu.core import env, trace
from raft_tpu.core.bitset import WORD_BITS
from raft_tpu.core.checkpoint import (CheckpointError, CheckpointManager,
                                      dump_checkpoint, load_checkpoint,
                                      save_checkpoint)
from raft_tpu.neighbors.ivf_flat import (SLOT_ALIGN, IvfFlatIndex,
                                         _coarse_labels, _pack,
                                         _resolve_metric, _search_jit,
                                         _use_radix, build)

__all__ = [
    "StreamingError", "RecoveryError", "WalGapError",
    "ShardCorruptError", "TermFencedError", "MutationLog", "DriftGauge",
    "StreamingIndex", "Compactor", "StreamingMnmg", "stream_build",
    "KIND_INSERT", "KIND_DELETE", "KIND_CENTROIDS", "KIND_TERM",
]

#: WAL record kinds (checkpoint entries carry scalars, not strings).
KIND_INSERT = 0
KIND_DELETE = 1
#: a refit's new coarse centroids, journaled so WAL SHIPPING carries
#: the quantizer change to followers (a repack itself emits no WAL —
#: it's content-neutral — but a refit changes centroids, which are part
#: of the content_crc witness)
KIND_CENTROIDS = 2
#: a leadership change (ISSUE 20): the first record a freshly promoted
#: leader journals under its new term. Content-neutral (no rows move),
#: but it consumes a sequence number and ships like any record, so
#: every follower's durable journal records exactly where the term
#: boundary falls — the fencing line a deposed leader truncates to
KIND_TERM = 3

_WAL_RE = re.compile(r"^wal-(\d{8})\.ckpt$")
_EPOCH_RE = re.compile(r"^epoch-(\d{8})\.ckpt$")


class StreamingError(RuntimeError):
    """Typed base for streaming-lifecycle failures (R4 discipline)."""


class RecoveryError(StreamingError):
    """No intact epoch snapshot could be recovered from the directory."""


class WalGapError(StreamingError):
    """A shipped WAL record skipped ahead of the next expected sequence
    number — records were lost (pruned at the source, dropped on the
    wire, or missed while this replica was down). The typed signal the
    follower answers with a snapshot resync (ISSUE 18)."""

    def __init__(self, *, expected: int, got: int):
        super().__init__(
            f"WAL sequence gap: expected record {expected}, got {got} "
            f"— {got - expected} record(s) missing; snapshot resync "
            f"required")
        self.expected = int(expected)
        self.got = int(got)


class TermFencedError(StreamingError):
    """A WAL record stamped with a STALE term reached a replica that
    has already seen a higher one — the writer is a deposed leader
    that missed an election (partitioned, paused, or restarted from an
    old journal). The record is rejected, never applied; the carried
    ``divergence`` sequence tells the deposed leader exactly where its
    journal forked from the fleet's, i.e. the first sequence it must
    truncate before demoting to follower and healing via catch-up
    (ISSUE 20)."""

    def __init__(self, *, stale_term: int, current_term: int,
                 divergence: int):
        super().__init__(
            f"term fence: record stamped term {stale_term} rejected by "
            f"a replica at term {current_term}; journals diverge at "
            f"seq {divergence} — truncate the unreplicated suffix and "
            f"rejoin as a follower")
        self.stale_term = int(stale_term)
        self.current_term = int(current_term)
        self.divergence = int(divergence)


class ShardCorruptError(StreamingError):
    """A scrub pass found at-rest damage (a failed container CRC) that
    no healthy source could repair — the shard is quarantined, not
    silently served (ISSUE 18)."""

    def __init__(self, shard: str, detail: str):
        super().__init__(f"shard {shard!r} corrupt and unrepairable: "
                         f"{detail}")
        self.shard = shard
        self.detail = detail


def _coarse_assign(rows, centroids) -> Tuple[np.ndarray, np.ndarray]:
    """(nearest-centroid distance, label) per row through the SAME fused
    path :func:`ivf_flat._coarse_labels` uses — routing and the drift
    gauge must agree with build/extend or extend==rebuild breaks."""
    from raft_tpu.cluster.kmeans import _assign
    from raft_tpu.util import precision

    with precision.scope():
        dist, labels = _assign(jnp.asarray(rows, jnp.float32),
                               jnp.asarray(centroids, jnp.float32))
    return np.asarray(dist), np.asarray(labels)


# ---------------------------------------------------------------------------
# mutation log: epoch snapshots + write-ahead records in one directory
# ---------------------------------------------------------------------------


class MutationLog:
    """Epoch-stamped WAL + epoch snapshots in one directory.

    WAL records are ``wal-<seq:08d>.ckpt``, epoch snapshots
    ``epoch-<n:08d>.ckpt`` — both v1 checkpoint containers, both written
    via atomic replace, so a reader never sees a torn file: a record is
    either absent or intact (its per-entry CRCs still guard against
    at-rest damage). Epoch snapshots live in a
    :class:`~raft_tpu.core.checkpoint.CheckpointManager` (ISSUE 18):
    same filenames, but retention (``RAFT_TPU_WAL_RETAIN``, override
    via ``retain=``) and the atomic write protocol are the shared
    container machinery every solver checkpoint already rides.

    Recovery loads the newest intact epoch and replays the WAL records
    past its ``wal_horizon`` (the highest sequence folded into it), in
    sequence order; committing a new epoch prunes the records it folds.
    ``add_on_append`` registers an append subscriber (callable, one
    durable record dict): subscribers fire in registration order AFTER
    the record hits disk, so a shipped record is always at least as
    durable at the source as at any follower. The WAL shipper, the
    election heartbeater, and any scrub trigger coexist as independent
    subscribers (ISSUE 20); the legacy single-slot ``on_append``
    assignment still works through a property shim.
    """

    def __init__(self, directory: str, *, retain: Optional[int] = None):
        self.directory = os.fspath(directory)
        self.retain = int(env.read("RAFT_TPU_WAL_RETAIN")
                          if retain is None else retain)
        if self.retain < 1:
            raise ValueError(f"retain must be >= 1, got {self.retain}")
        self._epochs = CheckpointManager(self.directory, prefix="epoch",
                                         keep=self.retain)
        self._lock = threading.Lock()
        seqs = [int(m.group(1)) for f in os.listdir(self.directory)
                if (m := _WAL_RE.match(f))]
        self._next_seq = max(seqs, default=-1) + 1
        self._on_append: List[Callable[[Dict], None]] = []

    # -- append subscribers -------------------------------------------

    @property
    def on_append(self) -> Optional[Callable[[Dict], None]]:
        """Legacy single-slot view of the subscriber list: ``None``
        when empty, the callable when exactly one, the ordered tuple
        when several (so ``log.on_append is not None`` keeps meaning
        'someone is listening')."""
        if not self._on_append:
            return None
        if len(self._on_append) == 1:
            return self._on_append[0]
        return tuple(self._on_append)

    @on_append.setter
    def on_append(self, fn: Optional[Callable[[Dict], None]]) -> None:
        """Single-slot assignment shim: replaces the WHOLE subscriber
        list (``None`` clears it) — the pre-ISSUE-20 contract."""
        with self._lock:
            self._on_append = [] if fn is None else [fn]

    def add_on_append(self, fn: Callable[[Dict], None]) -> None:
        """Register an append subscriber; idempotent — re-adding the
        same callable keeps its original position."""
        with self._lock:
            if fn not in self._on_append:
                self._on_append.append(fn)

    def remove_on_append(self, fn: Callable[[Dict], None]) -> None:
        """Unregister a subscriber; idempotent — removing a callable
        that is not registered is a no-op."""
        with self._lock:
            self._on_append = [h for h in self._on_append if h is not fn]

    # -- WAL ----------------------------------------------------------

    @property
    def last_seq(self) -> int:
        """Highest sequence number this log has issued or mirrored
        (-1 when none) — the follower's catch-up cursor."""
        with self._lock:
            return self._next_seq - 1

    def bump_seq(self, floor_next: int) -> None:
        """Raise the next sequence number to at least ``floor_next`` —
        recovery calls this with the restored snapshot's horizon so a
        restarted replica never re-issues a sequence number the fleet
        already saw (its own WAL files may have been pruned away)."""
        with self._lock:
            self._next_seq = max(self._next_seq, int(floor_next))

    def append(self, entries: Dict) -> int:
        """Atomically write one WAL record; returns its sequence number.
        ``entries`` must not contain ``seq`` (stamped here). Fires the
        ``on_append`` subscribers in order after the record is
        durable."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        rec = dict(entries)
        rec["seq"] = seq
        save_checkpoint(
            os.path.join(self.directory, f"wal-{seq:08d}.ckpt"), rec)
        for hook in list(self._on_append):
            hook(rec)
        return seq

    def append_mirror(self, rec: Dict) -> int:
        """Durably mirror one ALREADY-sequenced record (a WAL-shipping
        follower's journal-first step): the record keeps its origin
        sequence number, so the follower's on-disk WAL is a verbatim
        suffix of the leader's and a restart resumes catch-up from
        exactly the right cursor. Does not fire ``on_append`` — a
        mirror is a sink, not a source."""
        seq = int(rec["seq"])
        with self._lock:
            self._next_seq = max(self._next_seq, seq + 1)
        save_checkpoint(
            os.path.join(self.directory, f"wal-{seq:08d}.ckpt"),
            dict(rec))
        return seq

    def wal_records(self) -> List[Dict]:
        """Every WAL record on disk, ascending sequence order."""
        names = sorted(f for f in os.listdir(self.directory)
                       if _WAL_RE.match(f))
        out = []
        for name in names:
            with open(os.path.join(self.directory, name), "rb") as f:
                out.append(load_checkpoint(f))
        return out

    def prune_wal(self, *, before_epoch: Optional[int] = None,
                  through_seq: Optional[int] = None) -> int:
        """Delete records folded into an epoch snapshot: either every
        record with ``seq <= through_seq`` (the horizon stamped into
        the snapshot — works for mirrored records whose epoch numbers
        belong to the LEADER), or the legacy epoch-stamp filter
        (``epoch < before_epoch``). Returns how many were removed."""
        if (before_epoch is None) == (through_seq is None):
            raise ValueError(
                "prune_wal takes exactly one of before_epoch= / "
                "through_seq=")
        removed = 0
        for name in sorted(f for f in os.listdir(self.directory)
                           if _WAL_RE.match(f)):
            path = os.path.join(self.directory, name)
            with open(path, "rb") as f:
                rec = load_checkpoint(f)
            if through_seq is not None:
                fold = int(rec["seq"]) <= through_seq
            else:
                fold = int(rec["epoch"]) < before_epoch
            if fold:
                os.remove(path)
                removed += 1
        return removed

    def truncate_from(self, from_seq: int) -> int:
        """Delete every WAL record with ``seq >= from_seq`` — the
        deposed-leader heal step (ISSUE 20): a stale leader that kept
        appending past the fleet's divergence point holds a suffix the
        quorum never saw, which fencing guarantees will NEVER be
        accepted; it is cut here before the node demotes to follower
        and resyncs. Rewinds the issue cursor so the mirrored records
        that replace the suffix keep the fleet's numbering. Returns how
        many records were removed."""
        from_seq = int(from_seq)
        removed = 0
        with self._lock:
            for name in sorted(f for f in os.listdir(self.directory)
                               if _WAL_RE.match(f)):
                if int(_WAL_RE.match(name).group(1)) >= from_seq:
                    os.remove(os.path.join(self.directory, name))
                    removed += 1
            self._next_seq = min(self._next_seq, from_seq)
        if removed:
            trace.record_event("streaming.wal_truncate",
                               from_seq=from_seq, removed=removed)
        return removed

    # -- epoch snapshots ----------------------------------------------

    def epoch_path(self, epoch: int) -> str:
        return self._epochs.path_for(epoch)

    def epoch_steps(self) -> List[int]:
        """Epoch numbers present on disk, ascending (the scrub walk)."""
        return self._epochs.steps()

    def write_epoch(self, epoch: int, entries: Dict, *,
                    faults=None) -> None:
        """Two-step atomic epoch write through the checkpoint manager,
        with the ``compact.mid_write`` crash point BETWEEN the fsynced
        temp file and the rename — the torn-state window the protocol
        must survive: a kill there leaves only ``.tmp`` debris, which
        recovery never reads. The manager's retention prunes epochs
        older than ``retain`` on the same call."""
        hook = None
        if faults is not None:
            hook = lambda: faults.crash_point("compact.mid_write")  # noqa: E731
        self._epochs.save(epoch, entries, pre_replace=hook)

    def load_latest_epoch(self) -> Tuple[int, Dict]:
        """The newest INTACT epoch snapshot (number, entries). Walks
        newest-first; an at-rest-damaged file is skipped with a trace
        event and the previous epoch is used. Raises
        :class:`RecoveryError` when none survives."""
        nums = sorted(self._epochs.steps(), reverse=True)
        for n in nums:
            try:
                with open(self.epoch_path(n), "rb") as f:
                    return n, load_checkpoint(f)
            except CheckpointError as exc:
                trace.record_event("streaming.epoch_skip", epoch=n,
                                   error=str(exc))
        raise RecoveryError(
            f"no intact epoch snapshot in {self.directory!r} "
            f"(tried {len(nums)} files)")

    def prune_epochs(self, keep: Optional[int] = None) -> None:
        """Retention sweep (``keep=None`` uses the log's configured
        retain). ``write_epoch`` already prunes on every commit; this
        is the explicit surface for tests and manual compaction."""
        keep = self.retain if keep is None else int(keep)
        nums = sorted(self._epochs.steps())
        for n in nums[:-keep] if keep else nums:
            os.remove(self.epoch_path(n))


# ---------------------------------------------------------------------------
# drift gauge
# ---------------------------------------------------------------------------


class DriftGauge:
    """EMA of ingested rows' mean nearest-centroid distance, as a ratio
    against the baseline captured at build/refit time. Ratio 1.0 means
    the stream looks like the training distribution; crossing the
    threshold (``RAFT_TPU_DRIFT_THRESHOLD``) means the coarse quantizer
    no longer routes the stream well and a refit is due. Exported as
    the ``streaming_drift_ratio`` gauge when obs is on."""

    def __init__(self, threshold: Optional[float] = None,
                 alpha: float = 0.25):
        self.threshold = float(env.read("RAFT_TPU_DRIFT_THRESHOLD")
                               if threshold is None else threshold)
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._baseline: Optional[float] = None
        self._ema: Optional[float] = None

    def set_baseline(self, mean_dist: float) -> None:
        with self._lock:
            self._baseline = max(float(mean_dist), 1e-30)
            self._ema = None

    def observe_batch(self, mean_dist: float) -> float:
        """Fold one ingest batch's mean coarse distance into the EMA;
        returns the current ratio (1.0 until a baseline exists)."""
        with self._lock:
            if self._ema is None:
                self._ema = float(mean_dist)
            else:
                self._ema += self.alpha * (float(mean_dist) - self._ema)
            ratio = self._ratio_locked()
        if obs.enabled():
            obs.set_gauge("streaming_drift_ratio", ratio)
        return ratio

    def _ratio_locked(self) -> float:
        if self._baseline is None or self._ema is None:
            return 1.0
        return self._ema / self._baseline

    @property
    def ratio(self) -> float:
        with self._lock:
            return self._ratio_locked()

    @property
    def triggered(self) -> bool:
        return self.ratio > self.threshold


# ---------------------------------------------------------------------------
# the streaming index
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Snapshot:
    """Immutable serve-side view, swapped atomically at every commit —
    the search path reads ONE attribute and never takes the mutation
    lock (the zero-pause property)."""

    flat: IvfFlatIndex
    tomb_words: jnp.ndarray       # [n_words] uint32, global-id indexed
    n_live: int
    epoch: int
    version: int


class StreamingIndex:
    """A mutable, crash-safe IVF-Flat index (see module docstring).

    Build with :func:`stream_build` (fresh) or
    :meth:`StreamingIndex.recover` (from a journal directory after a
    crash). ``directory=None`` runs in-memory without durability — the
    mutation/compaction/drift machinery is identical, only the journal
    writes are skipped.
    """

    def __init__(self, flat: IvfFlatIndex, *,
                 log: Optional[MutationLog] = None,
                 faults=None, res=None,
                 drift: Optional[DriftGauge] = None,
                 epoch: int = 0, next_id: Optional[int] = None,
                 tomb_host: Optional[np.ndarray] = None,
                 n_live: Optional[int] = None,
                 reservoir_cap: int = 4096,
                 repack_slack: int = SLOT_ALIGN,
                 term: int = 0):
        self._lock = threading.RLock()
        self.log = log
        # highest WAL sequence folded into the in-memory state — the
        # horizon an epoch snapshot stamps (NOT log.last_seq: during a
        # recovery replay the disk holds records ahead of the applied
        # state, and a mid-replay repack must not claim — or prune —
        # records it hasn't folded yet)
        self._applied_seq = log.last_seq if log is not None else -1
        # leadership term (ISSUE 20): stamped into every journaled
        # record and every epoch snapshot; only ever advances. A record
        # from a LOWER term is a deposed leader's write — fenced, never
        # applied (WalFollower.apply_record raises TermFencedError)
        self._term = int(term)
        # the sequence number at which the current term began (the
        # KIND_TERM record's seq): the divergence point a fence error
        # carries — a deposed leader truncates its journal from here
        self._term_start = 0
        # optional post-commit barrier (ISSUE 20 quorum acks): called
        # with the mutation's seq AFTER journal+apply, OUTSIDE the
        # lock; a WalShipper in quorum mode installs its ack wait here
        self._commit_barrier: Optional[Callable[[int], None]] = None
        # bounded client-write dedup map (ISSUE 20): write_id → the ids
        # the insert assigned, populated on apply/replay/mirror so an
        # in-flight batch replayed at the NEW leader after a failover
        # returns its original ids instead of double-inserting
        self._write_ids: "collections.OrderedDict[int, np.ndarray]" = \
            collections.OrderedDict()
        self._write_ids_cap = 1024
        self.faults = faults
        self.res = res
        self.drift = drift if drift is not None else DriftGauge()
        self._flat = flat
        self._epoch = int(epoch)
        self._version = 0
        self._next_id = int(flat.n_db if next_id is None else next_id)
        self._n_live = int(flat.n_db if n_live is None else n_live)
        if tomb_host is None:
            tomb_host = np.zeros(self._tomb_n_words(flat, self._next_id),
                                 np.uint32)
        self._tomb_host = np.asarray(tomb_host, np.uint32).copy()
        self._reservoir: List[np.ndarray] = []
        self._reservoir_rows = 0
        self._reservoir_cap = int(reservoir_cap)
        # free tail slots per list every repack provisions — size it
        # to the expected insert batch so sustained ingest rides the
        # in-place tail-append path instead of repacking per batch
        self.repack_slack = max(int(repack_slack), SLOT_ALIGN)
        self._pf_counts: Optional[np.ndarray] = None
        self._snapshot = _Snapshot(
            flat=flat, tomb_words=jnp.asarray(self._tomb_host),
            n_live=self._n_live, epoch=self._epoch, version=0)
        self._history: collections.deque = collections.deque(maxlen=8)
        self._history.append(self._snapshot)

    # -- construction helpers -----------------------------------------

    @staticmethod
    def _tomb_n_words(flat: IvfFlatIndex, next_id: int) -> int:
        """Word count covering every id this epoch's arrays can ever
        hold: ids already assigned plus one per free padded slot (a
        fitting insert consumes a slot; an overflowing one repacks into
        a NEW epoch with a new bitset). Fixed per epoch — a delete only
        swaps same-shape words, so the compiled search never retraces."""
        free = int(flat.packed_db.shape[0]) - int(flat.n_db)
        n_bits = max(int(next_id) + max(free, 0), 1)
        return (n_bits + WORD_BITS - 1) // WORD_BITS

    @classmethod
    def recover(cls, res, directory: str, *, faults=None,
                drift: Optional[DriftGauge] = None,
                retain: Optional[int] = None) -> "StreamingIndex":
        """Rebuild the exact pre-crash index from the journal: load the
        newest intact epoch snapshot, then replay WAL records PAST its
        ``wal_horizon`` (the highest sequence folded into the snapshot)
        in sequence order; the atomic-replace write protocol guarantees
        every file present is whole. Snapshots written before ISSUE 18
        carry no horizon — those fall back to the legacy epoch-stamp
        filter (the frozen ``streaming_epoch_v1.ckpt`` fixture's
        contract). The replayed mutations re-journal nothing — the
        records are already durable — and the WAL cursor is bumped past
        the horizon so a restarted replica never re-issues a sequence
        number the fleet already saw."""
        log = MutationLog(directory, retain=retain)
        epoch, ent = log.load_latest_epoch()
        flat = _flat_from_entries(ent)
        idx = cls(flat, log=log, faults=faults, res=res, drift=drift,
                  epoch=epoch, next_id=int(ent["next_id"]),
                  tomb_host=np.asarray(ent["tomb_words"], np.uint32),
                  n_live=int(ent["n_live"]),
                  term=int(ent.get("wal_term", 0)))
        idx._term_start = int(ent.get("wal_term_start", 0))
        horizon = int(ent["wal_horizon"]) if "wal_horizon" in ent \
            else None
        if horizon is not None:
            idx._applied_seq = horizon
        replayed = 0
        for rec in log.wal_records():
            if horizon is not None:
                if int(rec["seq"]) <= horizon:
                    continue
            elif int(rec["epoch"]) != epoch:
                continue
            kind = int(rec["kind"])
            # mark applied BEFORE the dispatch (journal-first's replay
            # twin): if the apply itself repacks (insert overflow,
            # centroids refit), the epoch it commits folds THIS record
            # — its horizon must cover it, or a re-crash would replay
            # it a second time against state that already contains it
            if "seq" in rec:
                idx._applied_seq = int(rec["seq"])
            idx._term = max(idx._term, int(rec.get("term", 0)))
            if kind == KIND_INSERT:
                ids = idx._apply_insert(
                    np.asarray(rec["data"]),
                    np.asarray(rec["labels"], np.int64),
                    journal=False)
                if "write_id" in rec:
                    idx.note_write_id(int(rec["write_id"]), ids)
            elif kind == KIND_DELETE:
                idx._apply_delete(np.asarray(rec["data"], np.int64),
                                  journal=False)
            elif kind == KIND_CENTROIDS:
                with idx._lock:
                    idx._repack_locked(
                        centroids=np.asarray(rec["data"], np.float32),
                        reason="refit_replay")
            elif kind == KIND_TERM:
                idx._term = max(idx._term,
                                int(np.asarray(rec["data"]).ravel()[0]))
                idx._term_start = int(rec["seq"])
            else:
                raise RecoveryError(f"unknown WAL record kind {kind}")
            replayed += 1
        if horizon is not None:
            log.bump_seq(horizon + 1)
        if obs.enabled():
            obs.inc("streaming_replay_records_total", replayed)
        trace.record_event("streaming.recover", epoch=epoch,
                           replayed=replayed, n_live=idx.n_live)
        return idx

    def install_snapshot(self, ent: Dict) -> None:
        """Replace this index's entire content with a SHIPPED epoch
        snapshot (a WAL-shipping catch-up whose gap was too wide to
        replay record-by-record — the leader already pruned the
        records). Under the mutation lock: rebuild the packed state
        from the entries, bump the LOCAL epoch (leader and follower
        epoch counters legitimately diverge — compactions emit no WAL
        records — but :meth:`content_crc` is packing-invariant, so
        content equality is still the witness), persist it as a local
        epoch snapshot, advance the WAL cursor past the snapshot's
        horizon, and publish."""
        with self._lock:
            flat = _flat_from_entries(ent)
            self._flat = flat
            self._epoch += 1
            self._next_id = int(ent["next_id"])
            self._n_live = int(ent["n_live"])
            self._tomb_host = np.asarray(ent["tomb_words"],
                                         np.uint32).copy()
            self._applied_seq = int(ent.get("wal_horizon", -1))
            self._term = max(self._term, int(ent.get("wal_term", 0)))
            self._term_start = max(self._term_start,
                                   int(ent.get("wal_term_start", 0)))
            if self.log is not None:
                self.log.bump_seq(self._applied_seq + 1)
            self._write_epoch_locked(crash=False)
            self._publish_locked()
        if obs.enabled():
            obs.inc("streaming_snapshot_installs_total")
        trace.record_event("streaming.install_snapshot",
                           epoch=self._epoch, n_live=self._n_live)

    # -- read-side properties (snapshot-backed, lock-free) ------------

    @property
    def snapshot(self) -> _Snapshot:
        return self._snapshot

    @property
    def flat(self) -> IvfFlatIndex:
        return self._snapshot.flat

    @property
    def n_live(self) -> int:
        return self._snapshot.n_live

    @property
    def epoch(self) -> int:
        return self._snapshot.epoch

    @property
    def version(self) -> int:
        return self._snapshot.version

    @property
    def next_id(self) -> int:
        with self._lock:
            return self._next_id

    @property
    def term(self) -> int:
        """Current leadership term (monotone; see :class:`TermFencedError`)."""
        with self._lock:
            return self._term

    @property
    def applied_seq(self) -> int:
        """Highest WAL sequence folded into the in-memory state — the
        election's catch-up yardstick: the survivor with the highest
        ``(term, applied_seq)`` is the most complete mirror and wins
        promotion."""
        with self._lock:
            return self._applied_seq

    def begin_term(self, new_term: int) -> int:
        """Adopt a HIGHER leadership term and journal the boundary as a
        :data:`KIND_TERM` record — the freshly elected leader's first
        write. The record consumes a sequence number and ships through
        the normal on_append path, so every follower's journal durably
        records where the old term ended. Returns the record's seq."""
        with self._lock:
            if int(new_term) <= self._term:
                raise StreamingError(
                    f"begin_term: new term {int(new_term)} must exceed "
                    f"current term {self._term}")
            self._term = int(new_term)
            self._journal(KIND_TERM,
                          np.asarray([self._term], np.int64))
            seq = self._applied_seq
            self._term_start = seq
        trace.record_event("streaming.begin_term", term=int(new_term),
                           seq=seq)
        return seq

    def adopt_term(self, new_term: int) -> None:
        """Raise the local term WITHOUT journaling (the follower side
        of an election: the journal boundary arrives as the new
        leader's shipped :data:`KIND_TERM` record)."""
        with self._lock:
            self._term = max(self._term, int(new_term))

    def note_write_id(self, write_id: int, ids: np.ndarray) -> None:
        """Record a client write-id → assigned-ids mapping in the
        bounded dedup map (see :meth:`insert`)."""
        with self._lock:
            self._write_ids[int(write_id)] = np.asarray(ids, np.int64)
            self._write_ids.move_to_end(int(write_id))
            while len(self._write_ids) > self._write_ids_cap:
                self._write_ids.popitem(last=False)

    def seen_write_id(self, write_id: int) -> Optional[np.ndarray]:
        """The ids a previously applied ``write_id`` was assigned, or
        None — the idempotent-replay check."""
        with self._lock:
            ids = self._write_ids.get(int(write_id))
            return None if ids is None else ids.copy()

    def tombstone_fraction(self) -> float:
        """Dead rows still occupying packed slots / packed rows."""
        snap = self._snapshot
        packed = int(snap.flat.n_db)
        return (packed - snap.n_live) / max(packed, 1)

    def tail_full_fraction(self) -> float:
        """Fraction of lists whose padded tail is exhausted — the
        overflow pressure that turns the next routed insert into a
        full repack."""
        snap = self._snapshot
        sizes = np.asarray(snap.flat.sizes, np.int64)
        return float(np.mean(sizes >= snap.flat.caps)) if len(sizes) \
            else 0.0

    def _dead_host(self, ids: np.ndarray) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        return (self._tomb_host[ids // WORD_BITS]
                >> (ids % WORD_BITS).astype(np.uint32)) & 1

    def live_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """(rows, ids) of every live row, ascending external id — the
        canonical content order (compaction input, CRC input, and the
        exact-search database)."""
        snap = self._snapshot
        ids = np.asarray(snap.flat.packed_ids, np.int64)
        db = np.asarray(snap.flat.packed_db)
        occupied = ids >= 0
        ids_o = ids[occupied]
        with self._lock:
            dead = self._dead_host(ids_o).astype(bool)
        ids_l = ids_o[~dead]
        rows_l = db[occupied][~dead]
        order = np.argsort(ids_l, kind="stable")
        return rows_l[order], ids_l[order]

    def content_crc(self) -> int:
        """CRC32 over the canonical live content: ids ‖ rows in id
        order ‖ centroids. Invariant to packing layout, so a pure
        compaction leaves it unchanged and a recovered replica matches
        a clean twin run bit-for-bit — the crash-consistency witness."""
        rows, ids = self.live_rows()
        snap = self._snapshot
        c = zlib.crc32(np.ascontiguousarray(ids, np.int64).tobytes())
        c = zlib.crc32(np.ascontiguousarray(rows).tobytes(), c)
        c = zlib.crc32(np.ascontiguousarray(
            np.asarray(snap.flat.centroids, np.float32)).tobytes(), c)
        return c

    # -- journaling ----------------------------------------------------

    def _crash(self, name: str) -> None:
        if self.faults is not None:
            self.faults.crash_point(name)

    def _journal(self, kind: int, data: np.ndarray,
                 labels: Optional[np.ndarray] = None,
                 write_id: Optional[int] = None) -> None:
        if self.log is None:
            return
        rec: Dict = {"kind": kind, "epoch": self._epoch, "data": data,
                     "term": self._term}
        if labels is not None:
            rec["labels"] = np.asarray(labels, np.int64)
        if write_id is not None:
            rec["write_id"] = int(write_id)
        # journal-first: the apply follows under the same lock, so the
        # applied horizon may advance with the durable write
        self._applied_seq = self.log.append(rec)

    def _write_epoch_locked(self, *, crash: bool = True) -> None:
        """Persist the CURRENT in-memory state as this epoch's snapshot
        (called after a repack bumped ``self._epoch``), then prune the
        WAL records the snapshot supersedes. The write itself is the
        two-step atomic protocol with the mid-write crash point;
        ``crash=False`` skips the protocol crash points (the epoch-0
        build write — not part of the compaction state machine)."""
        if self.log is None:
            return
        ent = _epoch_entries(self)
        self.log.write_epoch(self._epoch, ent,
                             faults=self.faults if crash else None)
        if crash:
            self._crash("compact.post_commit")
        # prune by the horizon STAMPED INTO the snapshot, not by epoch
        # stamp: a WAL-shipping follower mirrors records carrying the
        # LEADER's epoch numbers, which its own epoch counter never
        # matches — sequence numbers are the one fleet-wide ordering
        self.log.prune_wal(through_seq=int(ent["wal_horizon"]))

    # -- mutation ------------------------------------------------------

    def insert(self, rows, labels: Optional[np.ndarray] = None, *,
               write_id: Optional[int] = None) -> np.ndarray:
        """Append rows; returns their external ids (assigned in arrival
        order, stable forever). Journal-first: the WAL record (rows +
        routing labels, so replay is deterministic even under MNMG load
        routing) is durable before the in-memory apply — a kill between
        the two replays the insert on recovery.

        ``write_id`` (optional client token, ISSUE 20) makes the insert
        idempotent across a leader failover: a batch replayed at the
        new leader with a write_id the journal already applied returns
        its ORIGINAL ids without re-inserting (delete is naturally
        idempotent — tombstones converge — so only insert needs the
        token).

        Rows that fit the padded tails apply as a pure in-place append
        (same shapes — zero retrace); an overflow repacks live rows
        + the new rows under a new epoch."""
        rows = np.asarray(rows)
        if rows.ndim != 2 or rows.shape[1] != self._snapshot.flat.dim:
            raise ValueError(
                f"rows must be [m, {self._snapshot.flat.dim}], got "
                f"{rows.shape}")
        if rows.shape[0] == 0:
            return np.zeros((0,), np.int64)
        with self._lock:
            if write_id is not None:
                prior = self.seen_write_id(write_id)
                if prior is not None:
                    if obs.enabled():
                        obs.inc("streaming_write_dedups_total")
                    return prior
            if labels is None:
                dist, labels = _coarse_assign(rows,
                                              self._flat.centroids)
                self.drift.observe_batch(float(np.mean(dist)))
            labels = np.asarray(labels, np.int64)
            if labels.shape != (rows.shape[0],) or \
                    labels.min(initial=0) < 0 or \
                    labels.max(initial=0) >= self._flat.n_lists:
                raise ValueError(
                    f"labels must be [{rows.shape[0]}] list indices in "
                    f"[0, {self._flat.n_lists})")
            self._crash("ingest.pre_journal")
            self._journal(KIND_INSERT, rows, labels,
                          write_id=write_id)
            self._crash("ingest.post_journal")
            seq = self._applied_seq
            ids = self._apply_insert(rows, labels, journal=True)
            if write_id is not None:
                self.note_write_id(write_id, ids)
        # quorum-ack barrier OUTSIDE the lock (ISSUE 20): the write is
        # journaled+applied locally either way — the barrier only
        # decides when the CLIENT may consider it replicated, and a
        # timeout raises the typed indeterminate error
        barrier = self._commit_barrier
        if barrier is not None:
            barrier(seq)
        if obs.enabled():
            obs.inc("streaming_inserts_total", int(rows.shape[0]))
        return ids

    def _apply_insert(self, rows: np.ndarray, labels: np.ndarray,
                      *, journal: bool) -> np.ndarray:
        with self._lock:
            flat = self._flat
            m = int(rows.shape[0])
            ids = np.arange(self._next_id, self._next_id + m,
                            dtype=np.int64)
            sizes = np.asarray(flat.sizes, np.int64)
            add = np.bincount(labels, minlength=flat.n_lists
                              ).astype(np.int64)
            tomb_bits = self._tomb_host.shape[0] * WORD_BITS
            if np.any(sizes + add > flat.caps) or \
                    ids[-1] >= tomb_bits:
                # overflow: fold live rows + new rows into a new epoch.
                # next_id must advance BEFORE the epoch snapshot is
                # written — the new rows ride into the epoch file, and
                # a recovery that replayed later WAL records against
                # the pre-insert next_id would re-assign their ids
                self._next_id += m
                self._repack_locked(extra_rows=rows, extra_ids=ids,
                                    reason="insert_overflow")
                self._reserve(rows)
                return ids
            else:
                starts = np.asarray(flat.starts, np.int64)
                order = np.argsort(labels, kind="stable")
                excl = np.zeros(flat.n_lists, np.int64)
                np.cumsum(add[:-1], out=excl[1:])
                within = np.arange(m) - np.repeat(excl, add)
                slots = (starts + sizes)[labels[order]] + within
                packed_db = np.asarray(flat.packed_db).copy()
                packed_ids = np.asarray(flat.packed_ids).copy()
                packed_db[slots] = rows.astype(packed_db.dtype)[order]
                packed_ids[slots] = ids[order].astype(np.int32)
                self._flat = IvfFlatIndex(
                    centroids=flat.centroids,
                    packed_db=jnp.asarray(packed_db),
                    packed_ids=jnp.asarray(packed_ids),
                    starts=flat.starts,
                    sizes=jnp.asarray(sizes + add, jnp.int32),
                    caps=flat.caps, cap_max=flat.cap_max,
                    n_db=flat.n_db + m, metric=flat.metric)
                self._n_live += m
                self._publish_locked()
            self._next_id += m
            self._reserve(rows)
            return ids

    def delete(self, ids) -> int:
        """Tombstone external ids; returns how many flipped live→dead
        (already-dead ids are an idempotent no-op, so a replayed delete
        converges). Journal-first like :meth:`insert`. The device
        bitset swap is same-shape — the serving executable never
        retraces on a delete."""
        ids = np.asarray(ids, np.int64).ravel()
        if ids.size == 0:
            return 0
        with self._lock:
            if ids.min() < 0 or ids.max() >= self._next_id:
                raise ValueError(
                    f"ids must be in [0, {self._next_id}), got range "
                    f"[{ids.min()}, {ids.max()}]")
            self._crash("ingest.pre_journal")
            self._journal(KIND_DELETE, ids)
            self._crash("ingest.post_journal")
            seq = self._applied_seq
            flipped = self._apply_delete(ids, journal=True)
        barrier = self._commit_barrier
        if barrier is not None:
            barrier(seq)
        if obs.enabled():
            obs.inc("streaming_deletes_total", flipped)
            obs.set_gauge("streaming_tombstone_frac",
                          self.tombstone_fraction())
        return flipped

    def _apply_delete(self, ids: np.ndarray, *, journal: bool) -> int:
        with self._lock:
            ids = np.asarray(ids, np.int64).ravel()
            was_dead = self._dead_host(ids).astype(bool)
            fresh = np.unique(ids[~was_dead])
            np.bitwise_or.at(
                self._tomb_host, fresh // WORD_BITS,
                np.uint32(1) << (fresh % WORD_BITS).astype(np.uint32))
            self._n_live -= int(fresh.size)
            self._publish_locked()
            return int(fresh.size)

    # -- snapshot publication -----------------------------------------

    def _publish_locked(self) -> None:
        self._version += 1
        self._snapshot = _Snapshot(
            flat=self._flat, tomb_words=jnp.asarray(self._tomb_host),
            n_live=self._n_live, epoch=self._epoch,
            version=self._version)
        self._history.append(self._snapshot)

    def recent_snapshots(self) -> List[_Snapshot]:
        """The last few published snapshots, oldest first (bounded
        ring). A query in flight across a swap legitimately serves ANY
        one consistent version from its submit→complete window — this
        is what lets loadgen's recall scorer distinguish a stale-but-
        consistent answer (fine) from a torn one (matches no version)."""
        with self._lock:
            return list(self._history)

    # -- compaction / repack ------------------------------------------

    def _repack_locked(self, *, extra_rows: Optional[np.ndarray] = None,
                       extra_ids: Optional[np.ndarray] = None,
                       centroids=None, reason: str = "compact") -> None:
        """Pack live rows (+ optional new rows) under their ORIGINAL
        external ids into fresh arrays, bump the epoch, persist its
        snapshot, prune the superseded WAL, and swap the serve
        snapshot. Every caller already holds the mutation lock; the
        background compactor does its expensive pack OUTSIDE the lock
        first and only re-enters here for the commit (see
        :meth:`compact`)."""
        t0 = time.monotonic()
        rows, ids = self.live_rows()
        if extra_rows is not None:
            rows = np.concatenate(
                [rows, np.asarray(extra_rows, rows.dtype)], axis=0)
            ids = np.concatenate([ids, np.asarray(extra_ids, np.int64)])
        centroids = self._flat.centroids if centroids is None \
            else jnp.asarray(centroids, jnp.float32)
        flat = _flat_from_live(rows, ids, centroids, self._flat.metric,
                               slack_slots=self.repack_slack)
        self._flat = flat
        self._epoch += 1
        self._n_live = int(rows.shape[0])
        self._tomb_host = np.zeros(
            self._tomb_n_words(flat, max(self._next_id,
                                         int(ids.max(initial=-1)) + 1)),
            np.uint32)
        self._crash("compact.pre_commit")
        self._write_epoch_locked()
        self._publish_locked()
        self._crash("compact.post_swap")
        if obs.enabled():
            obs.inc("streaming_compactions_total")
            obs.observe("streaming_compact_seconds",
                        time.monotonic() - t0)
        trace.record_event("streaming.compact", reason=reason,
                           epoch=self._epoch, n_live=self._n_live,
                           seconds=round(time.monotonic() - t0, 4))

    def compact(self, *, reason: str = "compact") -> None:
        """One full compaction cycle: double-buffered pack of the live
        rows off the mutation lock, then a short locked commit that
        folds in any mutations that raced the pack. Serving never
        pauses — searches keep reading the old snapshot until the
        atomic swap at the end of the commit.

        The compile/commit admission is priced through the
        ``neighbors.streaming_compact`` cost model (R13) so a budget'd
        deployment sees the repack's bytes before it runs."""
        from raft_tpu.runtime import limits

        self._crash("compact.pre_pack")
        with self._lock:
            snap_version = self._version
            snap_next = self._next_id
        # double buffer: pack from the snapshot OUTSIDE the lock
        rows, ids = self.live_rows()
        snap = self._snapshot
        est = limits.estimate_bytes(
            "neighbors.streaming_compact",
            packed_rows=int(snap.flat.packed_db.shape[0]),
            n_dims=snap.flat.dim,
            itemsize=snap.flat.packed_db.dtype.itemsize)
        with obs.span("streaming.compact"):
            new_flat = _flat_from_live(rows, ids, snap.flat.centroids,
                                       snap.flat.metric,
                                       slack_slots=self.repack_slack)
            with self._lock:
                if self._version != snap_version:
                    # mutations raced the pack: fold the delta in under
                    # the lock (rare, small) — rows inserted since the
                    # snapshot, deletes applied since the snapshot
                    trace.record_event("streaming.compact_delta",
                                       from_version=snap_version,
                                       to_version=self._version)
                    self._repack_locked(reason=reason + "_delta")
                else:
                    self._flat = new_flat
                    self._epoch += 1
                    self._n_live = int(rows.shape[0])
                    self._tomb_host = np.zeros(
                        self._tomb_n_words(new_flat,
                                           max(snap_next, 1)),
                        np.uint32)
                    self._crash("compact.pre_commit")
                    self._write_epoch_locked()
                    self._publish_locked()
                    self._crash("compact.post_swap")
                    if obs.enabled():
                        obs.inc("streaming_compactions_total")
                    trace.record_event(
                        "streaming.compact", reason=reason,
                        epoch=self._epoch, n_live=self._n_live,
                        est_bytes=int(est))

    # -- drift-aware refit --------------------------------------------

    def _reserve(self, rows: np.ndarray) -> None:
        """Keep the most recent inserted rows (bounded) as the refit
        mini-batch reservoir."""
        self._reservoir.append(np.asarray(rows, np.float32))
        self._reservoir_rows += int(rows.shape[0])
        while self._reservoir and \
                self._reservoir_rows - self._reservoir[0].shape[0] \
                >= self._reservoir_cap:
            self._reservoir_rows -= self._reservoir[0].shape[0]
            self._reservoir.pop(0)

    def maybe_refit(self, *, force: bool = False) -> bool:
        """Refit the coarse quantizer when the drift gauge crossed its
        threshold (or ``force``): mini-batch
        :func:`~raft_tpu.cluster.kmeans.kmeans_partial_fit` on the
        recent-insert reservoir seeded with the per-list live mass,
        then a repack under the refitted centroids (a refit epoch) and
        a baseline reset. Returns True when a refit ran."""
        if not (force or self.drift.triggered):
            return False
        with self._lock:
            if not self._reservoir:
                return False
            batch = np.concatenate(self._reservoir, axis=0)
            flat = self._flat
            sizes = np.asarray(flat.sizes, np.float32)
        from raft_tpu.cluster.kmeans import kmeans_partial_fit

        # journaled indexes checkpoint the refit at every chunk
        # boundary (ISSUE 18 satellite): a SIGKILL mid-refit resumes
        # from the saved (centroids, counts, chunk) cursor instead of
        # re-running the whole mini-batch pass
        ckpt: Dict = {}
        if self.log is not None:
            ckpt = dict(
                checkpoint_dir=os.path.join(self.log.directory,
                                            "refit"),
                checkpoint_every=1)
        new_c, counts = kmeans_partial_fit(
            self.res, flat.centroids, jnp.asarray(batch),
            counts=jnp.asarray(sizes), **ckpt)
        with self._lock:
            self._pf_counts = np.asarray(counts)
            # journal-first like insert/delete: the new quantizer is a
            # CONTENT change (centroids are in the crc witness), so it
            # must ship to WAL followers — the repack itself stays
            # journal-silent (content-neutral)
            self._journal(KIND_CENTROIDS, np.asarray(new_c, np.float32))
            self._repack_locked(centroids=new_c, reason="refit")
        dist, _ = _coarse_assign(batch, new_c)
        self.drift.set_baseline(float(np.mean(dist)))
        if obs.enabled():
            obs.inc("streaming_refits_total")
        trace.record_event("streaming.refit", rows=int(batch.shape[0]),
                           epoch=self.epoch)
        return True

    # -- search --------------------------------------------------------

    def search(self, queries, k: int, nprobe: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """k nearest LIVE rows per query, external-id numbering, same
        output contract as :func:`ivf_flat.search`. Tombstoned rows are
        excluded in-mask on the partial-probe path (bit-identical to a
        rebuild without them for the candidates scanned) and excluded
        from the database on the exact path (``nprobe >= n_lists``),
        which IS brute force over the live rows — ties and NaN rows
        resolve exactly as a rebuild would."""
        snap = self._snapshot
        flat = snap.flat
        queries = jnp.asarray(queries)
        if queries.ndim != 2 or queries.shape[1] != flat.dim:
            raise ValueError(f"queries must be [q, {flat.dim}], got "
                             f"{queries.shape}")
        if not 0 < k <= snap.n_live:
            raise ValueError(f"need 0 < k <= n_live, got k={k}, "
                             f"n_live={snap.n_live}")
        if nprobe <= 0:
            raise ValueError(f"need nprobe > 0, got {nprobe}")
        if nprobe >= flat.n_lists:
            from raft_tpu.neighbors.brute_force import knn

            rows, ids = self.live_rows()
            trace.record_event("streaming.search", nprobe=flat.n_lists,
                               k=k, path="exact", epoch=snap.epoch)
            dist, idx = knn(self.res, jnp.asarray(rows), queries, k,
                            metric=flat.metric)
            ids_j = jnp.asarray(ids, jnp.int32)
            ext = jnp.where(idx >= 0,
                            ids_j[jnp.maximum(idx, 0)], -1)
            return dist, ext
        probe_rows = nprobe * flat.cap_max
        if probe_rows < k:
            raise ValueError(
                f"nprobe={nprobe} reaches at most {probe_rows} "
                f"candidates < k={k}; raise nprobe")
        trace.record_event("streaming.search", nprobe=nprobe, k=k,
                           path="ivf", epoch=snap.epoch)
        use_radix = _use_radix(probe_rows, k, flat.packed_db, queries)
        return _search_jit(
            queries, flat.centroids, flat.packed_db, flat.packed_ids,
            flat.starts, flat.sizes, snap.tomb_words, k=k,
            nprobe=nprobe, cap_max=flat.cap_max, metric=flat.metric,
            use_radix=use_radix)


def _flat_from_live(rows: np.ndarray, ids: np.ndarray, centroids,
                    metric: str,
                    slack_slots: int = SLOT_ALIGN) -> IvfFlatIndex:
    """Pack (rows, ids) — ids arbitrary but unique — into a fresh
    IvfFlatIndex under the given centroids. ``slack_slots`` free tail
    slots per list beyond alignment: a repack must LEAVE headroom, or
    re-filling every aligned-full tail would re-fire the tail-full
    compaction criterion forever (size it to the expected insert batch
    via ``StreamingIndex.repack_slack``). The streaming repack twin
    of :func:`ivf_flat.build`: same labeling pass, same packer, but
    ids are PRESERVED, not renumbered (the stable-identity contract)."""
    centroids = jnp.asarray(centroids, jnp.float32)
    n_lists = int(centroids.shape[0])
    if rows.shape[0] == 0:
        caps = np.zeros(n_lists, np.int64)
        return IvfFlatIndex(
            centroids=centroids,
            packed_db=jnp.zeros((0, int(centroids.shape[1])),
                                jnp.asarray(rows).dtype),
            packed_ids=jnp.zeros((0,), jnp.int32),
            starts=jnp.zeros((n_lists,), jnp.int32),
            sizes=jnp.zeros((n_lists,), jnp.int32),
            caps=caps, cap_max=0, n_db=0, metric=metric)
    labels = _coarse_labels(rows, centroids)
    # _pack's within-list order key is position in the (label-stable)
    # sort; feeding rows in ascending external id keeps lists id-sorted,
    # the invariant extend's tail append relies on
    order = np.argsort(np.asarray(ids, np.int64), kind="stable")
    rows = np.asarray(rows)[order]
    ids32 = np.asarray(ids, np.int64)[order].astype(np.int32)
    labels = np.asarray(labels)[order]
    packed_db, packed_ids, starts, counts, caps = _pack(
        rows, ids32, labels, n_lists, slack_slots=slack_slots)
    return IvfFlatIndex(
        centroids=centroids,
        packed_db=jnp.asarray(packed_db),
        packed_ids=jnp.asarray(packed_ids),
        starts=jnp.asarray(starts, jnp.int32),
        sizes=jnp.asarray(counts, jnp.int32),
        caps=caps, cap_max=int(caps.max(initial=0)),
        n_db=int(rows.shape[0]), metric=metric)


def stream_build(res, db, n_lists: int, metric: str = "l2", *,
                 directory: Optional[str] = None, max_iter: int = 25,
                 seed: int = 0, faults=None,
                 drift: Optional[DriftGauge] = None,
                 repack_slack: int = SLOT_ALIGN) -> StreamingIndex:
    """Build a fresh streaming index (train + pack via
    :func:`ivf_flat.build`), journal its epoch-0 snapshot when a
    ``directory`` is given, and seed the drift baseline with the
    training rows' mean coarse distance."""
    flat = build(res, db, n_lists, metric, max_iter=max_iter, seed=seed)
    log = MutationLog(directory) if directory is not None else None
    idx = StreamingIndex(flat, log=log, faults=faults, res=res,
                         drift=drift, repack_slack=repack_slack)
    dist, _ = _coarse_assign(np.asarray(db), flat.centroids)
    idx.drift.set_baseline(float(np.mean(dist)))
    if log is not None:
        with idx._lock:
            idx._write_epoch_locked(crash=False)
    return idx


def _epoch_entries(idx: StreamingIndex) -> Dict:
    flat = idx._flat
    return {
        "epoch": idx._epoch,
        "next_id": idx._next_id,
        "n_live": idx._n_live,
        "n_db": int(flat.n_db),
        # highest WAL sequence folded into this snapshot: recovery
        # replays strictly past it, the commit prunes through it
        "wal_horizon": idx._applied_seq,
        # leadership term at the snapshot (ISSUE 20): restored on
        # recovery so a restarted replica rejoins fenced at the term
        # it last saw, never accepting a deposed leader's writes
        "wal_term": idx._term,
        "wal_term_start": idx._term_start,
        "metric": np.frombuffer(flat.metric.encode(), np.uint8),
        "centroids": np.asarray(flat.centroids, np.float32),
        "packed_db": np.asarray(flat.packed_db),
        "packed_ids": np.asarray(flat.packed_ids, np.int32),
        "starts": np.asarray(flat.starts, np.int64),
        "sizes": np.asarray(flat.sizes, np.int64),
        "caps": np.asarray(flat.caps, np.int64),
        "tomb_words": idx._tomb_host.copy(),
    }


def _flat_from_entries(ent: Dict) -> IvfFlatIndex:
    """Rebuild the packed :class:`IvfFlatIndex` from an epoch
    snapshot's entries — the inverse of :func:`_epoch_entries`, shared
    by :meth:`StreamingIndex.recover` (disk) and
    :meth:`StreamingIndex.install_snapshot` (wire)."""
    metric = bytes(np.asarray(ent["metric"], np.uint8)).decode()
    _resolve_metric(metric)
    caps = np.asarray(ent["caps"], np.int64)
    return IvfFlatIndex(
        centroids=jnp.asarray(np.asarray(ent["centroids"],
                                         np.float32)),
        packed_db=jnp.asarray(np.asarray(ent["packed_db"])),
        packed_ids=jnp.asarray(np.asarray(ent["packed_ids"],
                                          np.int32)),
        starts=jnp.asarray(np.asarray(ent["starts"], np.int32)),
        sizes=jnp.asarray(np.asarray(ent["sizes"], np.int32)),
        caps=caps, cap_max=int(caps.max(initial=0)),
        n_db=int(ent["n_db"]), metric=metric)


# ---------------------------------------------------------------------------
# background compactor
# ---------------------------------------------------------------------------


class Compactor:
    """Background compaction worker: polls the streaming index every
    ``RAFT_TPU_COMPACT_INTERVAL`` seconds and runs one
    :meth:`StreamingIndex.compact` cycle whenever the tombstone
    fraction crosses ``RAFT_TPU_COMPACT_TOMBSTONE_FRAC`` or any list
    tail is exhausted (the next routed insert would repack on the
    ingest path — doing it here keeps ingest latency flat). Also drives
    :meth:`StreamingIndex.maybe_refit` so the drift loop needs no extra
    thread. A worker-side failure is recorded to the obs flight
    recorder and re-raised from :meth:`stop` — never swallowed."""

    def __init__(self, index: StreamingIndex, *,
                 interval: Optional[float] = None,
                 tombstone_frac: Optional[float] = None,
                 refit: bool = True,
                 on_change: Optional[Callable[[], None]] = None):
        self.index = index
        # serving-side hook: runs after any cycle that changed the
        # index (compaction or refit), on the worker thread — the
        # ingest controller uses it to re-snapshot + pre-warm its
        # serve executables off the query path
        self.on_change = on_change
        self.interval = float(env.read("RAFT_TPU_COMPACT_INTERVAL")
                              if interval is None else interval)
        self.tombstone_frac = float(
            env.read("RAFT_TPU_COMPACT_TOMBSTONE_FRAC")
            if tombstone_frac is None else tombstone_frac)
        self.refit = bool(refit)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self.cycles = 0
        self.compactions = 0

    def should_compact(self) -> bool:
        """Due when dead rows hold too many packed slots OR too many
        list tails are exhausted (same threshold — both are 'wasted
        capacity the next insert pays for' fractions)."""
        return (self.index.tombstone_fraction() >= self.tombstone_frac
                or self.index.tail_full_fraction()
                >= self.tombstone_frac)

    def run_once(self) -> bool:
        """One poll: compact and/or refit if due; returns True when a
        compaction ran."""
        self.cycles += 1
        ran = False
        if self.should_compact():
            self.index.compact(reason="background")
            self.compactions += 1
            ran = True
        if self.refit and self.index.maybe_refit():
            ran = True
        if ran and self.on_change is not None:
            self.on_change()
        return ran

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.run_once()
            except Exception as exc:  # noqa: BLE001 — surfaced at stop
                self._error = exc
                obs.record_failure(exc)
                trace.record_event("streaming.compactor_error",
                                   error=str(exc))
                return

    def start(self) -> "Compactor":
        if self._thread is not None:
            raise StreamingError("compactor already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="raft-tpu-compactor")
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the worker and re-raise any failure it died on."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise StreamingError(
                "background compactor failed") from err

    def __enter__(self) -> "Compactor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# MNMG: routed ingest + rebalance over the sharded index
# ---------------------------------------------------------------------------


class StreamingMnmg:
    """Streaming facade over the sharded MNMG index: mutations apply to
    the underlying :class:`StreamingIndex` (single journal, single
    epoch protocol — replicas recover the flat state and re-shard), the
    search path re-shards lazily whenever the streaming version moved
    and serves through :func:`ivf_mnmg.search_mnmg` with the tombstone
    words replicated to every rank.

    ``route="nearest"`` keeps bit-identity with the single-rank index.
    ``route="load"`` sends a row whose second-nearest centroid is
    within ``slack``× of its nearest to whichever of the two lists is
    owned by the less-loaded rank — skew relief at ingest; the next
    compaction's :func:`ivf_mnmg.rebalance_mnmg` (the heal-path repack)
    restores nearest placement while LPT re-levels rank loads."""

    ROUTES = ("nearest", "load")

    def __init__(self, stream: StreamingIndex, n_ranks: int, *,
                 mesh=None, axis: str = "ranks",
                 route: str = "nearest", slack: float = 1.05):
        from raft_tpu.neighbors.ivf_mnmg import _from_flat

        if route not in self.ROUTES:
            raise ValueError(f"route must be one of {self.ROUTES}, "
                             f"got {route!r}")
        self.stream = stream
        self.n_ranks = int(n_ranks)
        self.route = route
        self.slack = float(slack)
        self._lock = threading.Lock()
        self._mnmg = _from_flat(stream.flat, self.n_ranks, mesh=mesh,
                                axis=axis)
        self._sharded_version = stream.version

    @property
    def mnmg(self):
        self._refresh()
        return self._mnmg

    def _refresh(self) -> None:
        from raft_tpu.neighbors.ivf_mnmg import rebalance_mnmg

        with self._lock:
            v = self.stream.version
            if v != self._sharded_version:
                self._mnmg = rebalance_mnmg(self._mnmg,
                                            flat=self.stream.flat,
                                            mesh=self._mnmg.mesh)
                self._sharded_version = v
                trace.record_event("streaming.reshard", version=v,
                                   n_ranks=self.n_ranks)

    def rank_loads(self) -> np.ndarray:
        """Packed rows currently owned per rank (the skew the load
        route levels)."""
        idx = self.mnmg
        sizes = np.asarray(self.stream.flat.sizes, np.int64)
        loads = np.zeros(self.n_ranks, np.int64)
        np.add.at(loads, idx.owner, sizes)
        return loads

    def _route_labels(self, rows: np.ndarray) -> np.ndarray:
        """Per-row list assignment under the configured route. The
        nearest label always comes from the SAME fused assign pass the
        single-rank index routes with (ties and precision included), so
        ``route="nearest"`` stays bit-identical; the load route only
        ever diverges to the runner-up list when it is a near-tie
        (within ``slack``×) owned by a less-loaded rank."""
        centroids = self.stream.flat.centroids
        dist, labels = _coarse_assign(rows, centroids)
        self.stream.drift.observe_batch(float(np.mean(dist)))
        labels = np.asarray(labels, np.int64)
        n_lists = int(centroids.shape[0])
        if self.route == "nearest" or n_lists < 2:
            return labels
        cents = np.asarray(centroids, np.float32)
        rows = np.asarray(rows, np.float32)
        d2 = (np.sum(rows * rows, 1)[:, None]
              - 2.0 * rows @ cents.T
              + np.sum(cents * cents, 1)[None, :])
        ar = np.arange(len(rows))
        d2[ar, labels] = np.inf                   # mask the winner
        second = np.argmin(d2, axis=1)
        owner = self.mnmg.owner
        loads = self.rank_loads().astype(np.float64)
        tie = d2[ar, second] <= \
            np.maximum(dist.astype(np.float64), 1e-30) * self.slack ** 2
        prefer_second = loads[owner[second]] < loads[owner[labels]]
        return np.where(tie & prefer_second, second, labels)

    def insert(self, rows) -> np.ndarray:
        """Routed insert: labels chosen by the route policy and
        JOURNALED with the rows, so replay reproduces the placement
        regardless of recovery-time rank loads."""
        rows = np.asarray(rows)
        labels = self._route_labels(rows)
        ids = self.stream.insert(rows, labels=labels)
        self._refresh()
        return ids

    def delete(self, ids) -> int:
        n = self.stream.delete(ids)
        self._refresh()
        return n

    def rebalance(self) -> None:
        """Compact + re-shard: the explicit post-skew rebalance (the
        same repack :func:`ivf_mnmg.shrink_mnmg` runs after a rank
        death — heal doubles as rebalance)."""
        self.stream.compact(reason="rebalance")
        self._refresh()

    def search(self, res, queries, k: int, nprobe: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        from raft_tpu.neighbors.ivf_mnmg import search_mnmg

        snap = self.stream.snapshot
        if nprobe >= snap.flat.n_lists:
            # the streaming layer owns the exact path (live rows only)
            return self.stream.search(queries, k, nprobe)
        self._refresh()
        return search_mnmg(res, self._mnmg, queries, k, nprobe,
                           tomb_words=snap.tomb_words)
