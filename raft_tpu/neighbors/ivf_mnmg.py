"""Pod-scale sharded IVF-Flat: the inverted-file index as a distributed
service primitive (ROADMAP item 1; lineage: cuVS multi-GPU ANN in
sharded mode, composed from the MNMG comms layer exactly the way
``knn_mnmg`` shards brute force).

Index layout: :func:`build_mnmg` partitions the packed inverted lists of
an :class:`~raft_tpu.neighbors.ivf_flat.IvfFlatIndex` across ``n_ranks``
shards — a deterministic longest-processing-time assignment of whole
lists by padded capacity (:func:`partition_lists`), so the partition is
a pure function of (list capacities, rank count). Each rank holds one
dense ``[cap_rank_max, d]`` packed matrix (its lists repacked
back-to-back, global slot order preserved within each list) plus full
``[n_lists]`` CSR mirrors in which non-owned lists have size 0 — the
``take_rows`` valid mask then erases them for free, and every rank runs
the *identical* static-shape program. Coarse centroids are replicated.

Query path: :func:`search_mnmg` is ONE compiled ``shard_map`` program —
the coarse probe replicates per rank (a tiny [q, n_lists] block), each
rank gathers and scores only its local probed spans via
:func:`raft_tpu.matrix.take_rows` and selects its local top-k with the
PR-7 radix / top_k epilogue (:func:`raft_tpu.neighbors.ivf_flat._probe_topk`
— the same traced body the single-rank search runs, stopped before the
metric finalize so raw keys stay mergeable), then the per-rank k
candidates all-gather in-graph and one final select over the
``[q, n_ranks·k]`` pool produces the replicated answer. No host hop sits
anywhere in the query path; the query buffer is donated
(compiled-driver donation pattern — the loadgen's per-launch carry).

Exactness boundary (shared with the single-rank index):
``nprobe >= n_lists`` delegates to
:func:`raft_tpu.neighbors.brute_force.knn` on the exactly-reconstructed
database — the SAME delegation ``ivf_flat.search`` makes, so the
full-probe setting is bit-identical (ids and distances, ties and NaN
included) across 1/2/4 ranks, to single-rank search, and to brute
force, by construction. Partial probes keep per-element distance values
identical across rank counts (each candidate's fine distance is an
independent dot product of the same f32 rows in a tile of the same
static shape); only tie ordering inside the merged pool may differ.

Elasticity: :func:`shrink_mnmg` repacks for the survivor count from the
host-side flat index — and because :func:`partition_lists` is pure, the
repacked shards are bit-for-bit the shards a fresh :func:`build_mnmg`
on that rank count would produce (the chaos gate's equality witness).
:func:`search_local` / :func:`merge_pool` expose the per-rank half and
the merge half separately for cross-process serving cliques, where the
candidate exchange must ride the host mailbox (XLA collectives cannot
outlive a SIGKILL'd participant; the elastic kmeans made the same
move).
"""

from __future__ import annotations

import dataclasses
import functools
import warnings
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.core import trace
from raft_tpu.neighbors.ivf_flat import (IvfFlatIndex, _probe_topk,
                                         _resolve_metric, _use_radix,
                                         build as build_flat)
from raft_tpu.util.precision import with_matmul_precision

__all__ = ["IvfMnmgIndex", "build_mnmg", "search_mnmg", "shrink_mnmg",
           "partition_lists", "search_local", "merge_pool",
           "DEFAULT_AXIS"]

#: mesh axis name the sharded index lives on (distinct from the solver
#: meshes' "data" so a serving mesh can coexist with a compute mesh)
DEFAULT_AXIS = "shard"

# donating the query buffer on CPU trips XLA's "not usable" warning;
# same noise-suppression the compiled driver applies (it still donates
# where the backend supports aliasing)
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def partition_lists(caps, n_ranks: int) -> np.ndarray:
    """Deterministic list -> rank assignment: longest-processing-time
    greedy over padded list capacities (largest list first, ties by
    ascending list id, each placed on the least-loaded rank, ties by
    lowest rank). A pure function of ``(caps, n_ranks)`` — the property
    the shrink-equals-fresh-build chaos witness rests on."""
    caps = np.asarray(caps, np.int64)
    if n_ranks < 1:
        raise ValueError(f"need n_ranks >= 1, got {n_ranks}")
    owner = np.empty(len(caps), np.int32)
    load = np.zeros(n_ranks, np.int64)
    for lst in sorted(range(len(caps)), key=lambda i: (-caps[i], i)):
        r = int(np.argmin(load))              # first occurrence = lowest
        owner[lst] = r
        load[r] += caps[lst]
    return owner


@dataclasses.dataclass
class IvfMnmgIndex:
    """Sharded IVF-Flat index: one rank-stacked shard set + the host
    flat index it was carved from.

    ``flat`` stays the source of truth for reconstruction and repack
    (shrink rebuilds shards from it without touching devices mid-
    recovery). The stacked arrays are ready for the one-program
    ``shard_map`` search: leading dim = rank; ``starts``/``sizes`` are
    LOCAL span tables over the full global list id space, with size 0
    for lists a rank does not own (the gather's valid mask then masks
    them to +inf)."""

    flat: IvfFlatIndex
    owner: np.ndarray               # [n_lists] host int32, list -> rank
    packed_db_sh: jnp.ndarray       # [R, cap_rank_max, d] original dtype
    packed_ids_sh: jnp.ndarray      # [R, cap_rank_max] int32, -1 = pad
    starts_sh: jnp.ndarray          # [R, n_lists] int32 local starts
    sizes_sh: jnp.ndarray           # [R, n_lists] int32, 0 = not owned
    cap_rank_max: int               # static per-rank packed rows
    mesh: Mesh = dataclasses.field(repr=False, compare=False,
                                   default=None)
    axis: str = DEFAULT_AXIS

    @property
    def n_ranks(self) -> int:
        return int(self.packed_db_sh.shape[0])

    @property
    def n_lists(self) -> int:
        return self.flat.n_lists

    @property
    def dim(self) -> int:
        return self.flat.dim

    @property
    def metric(self) -> str:
        return self.flat.metric

    @property
    def cap_max(self) -> int:
        return self.flat.cap_max

    @property
    def n_db(self) -> int:
        return self.flat.n_db

    def scanned_fraction(self, nprobe: int) -> float:
        return self.flat.scanned_fraction(nprobe)

    def reconstruct(self) -> jnp.ndarray:
        """The database in original row order, bit-exact (delegates to
        the flat mirror — shard packing never rewrites a row)."""
        return self.flat.reconstruct()

    def shard(self, rank: int) -> Tuple[jnp.ndarray, jnp.ndarray,
                                        jnp.ndarray, jnp.ndarray]:
        """One rank's (packed_db, packed_ids, starts, sizes) — the
        operand set a cross-process serving rank holds locally."""
        return (self.packed_db_sh[rank], self.packed_ids_sh[rank],
                self.starts_sh[rank], self.sizes_sh[rank])


def _default_mesh(n_ranks: int, axis: str) -> Mesh:
    devs = jax.devices()
    if n_ranks > len(devs):
        raise ValueError(
            f"n_ranks={n_ranks} exceeds the {len(devs)} visible devices")
    return Mesh(np.asarray(devs[:n_ranks]), axis_names=(axis,))


def _shard_arrays(flat: IvfFlatIndex, owner: np.ndarray, n_ranks: int):
    """Carve the flat index's packed arrays into rank-stacked shards on
    the host (pure numpy — the repack path must not need devices)."""
    caps = np.asarray(flat.caps, np.int64)
    gstarts = np.asarray(flat.starts, np.int64)
    sizes = np.asarray(flat.sizes, np.int64)
    db_np = np.asarray(flat.packed_db)
    ids_np = np.asarray(flat.packed_ids)
    n_lists = flat.n_lists
    cap_rank = np.asarray([int(caps[owner == r].sum())
                           for r in range(n_ranks)], np.int64)
    cap_rank_max = max(int(cap_rank.max(initial=0)), 1)
    db_sh = np.zeros((n_ranks, cap_rank_max, flat.dim), db_np.dtype)
    ids_sh = np.full((n_ranks, cap_rank_max), -1, np.int32)
    starts_sh = np.zeros((n_ranks, n_lists), np.int64)
    sizes_sh = np.zeros((n_ranks, n_lists), np.int64)
    for r in range(n_ranks):
        at = 0
        for lst in np.flatnonzero(owner == r):
            c = int(caps[lst])
            g = int(gstarts[lst])
            db_sh[r, at:at + c] = db_np[g:g + c]
            ids_sh[r, at:at + c] = ids_np[g:g + c]
            starts_sh[r, lst] = at
            sizes_sh[r, lst] = sizes[lst]
            at += c
    return db_sh, ids_sh, starts_sh, sizes_sh, cap_rank_max


def _from_flat(flat: IvfFlatIndex, n_ranks: int, *,
               mesh: Optional[Mesh] = None,
               axis: str = DEFAULT_AXIS) -> IvfMnmgIndex:
    """Shared build/repack entry: partition + carve + place. Called by
    both :func:`build_mnmg` and :func:`shrink_mnmg`, so a post-shrink
    index IS a fresh build on the survivor count."""
    if mesh is None:
        mesh = _default_mesh(n_ranks, axis)
    elif mesh.shape[axis] != n_ranks:
        raise ValueError(f"mesh axis {axis!r} has {mesh.shape[axis]} "
                         f"devices, need n_ranks={n_ranks}")
    owner = partition_lists(flat.caps, n_ranks)
    db_sh, ids_sh, starts_sh, sizes_sh, cap_rank_max = _shard_arrays(
        flat, owner, n_ranks)
    sharded = NamedSharding(mesh, P(axis))
    return IvfMnmgIndex(
        flat=flat, owner=owner,
        packed_db_sh=jax.device_put(db_sh, sharded),
        packed_ids_sh=jax.device_put(ids_sh, sharded),
        starts_sh=jax.device_put(starts_sh.astype(np.int32), sharded),
        sizes_sh=jax.device_put(sizes_sh.astype(np.int32), sharded),
        cap_rank_max=cap_rank_max, mesh=mesh, axis=axis)


def build_mnmg(res, db, n_lists: int, n_ranks: int,
               metric: str = "l2", *, mesh: Optional[Mesh] = None,
               axis: str = DEFAULT_AXIS, max_iter: int = 25,
               seed: int = 0, centroids=None,
               flat: Optional[IvfFlatIndex] = None) -> IvfMnmgIndex:
    """Train (or adopt) a flat IVF index and partition its inverted
    lists across ``n_ranks`` shards.

    Pass ``flat`` to shard an already-built
    :class:`~raft_tpu.neighbors.ivf_flat.IvfFlatIndex` without
    retraining (the serving tier's repack path); otherwise the coarse
    quantizer trains exactly as :func:`raft_tpu.neighbors.ivf_flat.build`
    does. The partition is deterministic, so two builds from the same
    flat index at the same rank count produce bit-identical shards.
    """
    if flat is None:
        flat = build_flat(res, db, n_lists, metric, max_iter=max_iter,
                          seed=seed, centroids=centroids)
    else:
        _resolve_metric(flat.metric)
    return _from_flat(flat, n_ranks, mesh=mesh, axis=axis)


def shrink_mnmg(index: IvfMnmgIndex, survivors: Sequence[int], *,
                mesh: Optional[Mesh] = None) -> IvfMnmgIndex:
    """Repack for the survivor set after a rank death: rebuild the
    shard partition from the host flat mirror at the new rank count.
    Bit-for-bit equal to ``build_mnmg(flat=index.flat,
    n_ranks=len(survivors))`` — :func:`partition_lists` is a pure
    function of (caps, n_ranks), which is what lets the chaos gate
    compare a survivor repack against a fresh build."""
    n_ranks = len(set(int(r) for r in survivors))
    if n_ranks < 1:
        raise ValueError("need at least one survivor")
    return _from_flat(index.flat, n_ranks, mesh=mesh, axis=index.axis)


def rebalance_mnmg(index: IvfMnmgIndex, *,
                   flat: Optional[IvfFlatIndex] = None,
                   mesh: Optional[Mesh] = None) -> IvfMnmgIndex:
    """Repack the current (or a freshly mutated) flat mirror across the
    SAME rank count — the heal-path repack doubling as the rebalance
    after skewed streaming ingest (ISSUE 17). :func:`partition_lists`
    re-runs LPT on the post-ingest caps, so lists that grew under
    routed inserts redistribute exactly as a fresh build would place
    them; passing ``flat`` adopts a compacted epoch's arrays."""
    if flat is None:
        flat = index.flat
    return _from_flat(flat, index.n_ranks, mesh=mesh, axis=index.axis)


# ---------------------------------------------------------------------------
# search: one shard_map program
# ---------------------------------------------------------------------------


def _merge_body(pool_v, pool_i, *, k: int, metric: str,
                use_radix: bool):
    """Final select over the all-gathered [q, R·k] raw-key pool + the
    single metric finalize (the PR-7 epilogue applied once, globally)."""
    from raft_tpu.neighbors.brute_force import _finalize

    if use_radix:
        from raft_tpu.matrix.radix_select import radix_select_k

        vals, pos = radix_select_k(pool_v, k)
    else:
        neg, pos = lax.top_k(-pool_v, k)
        vals = -neg
    out_ids = jnp.take_along_axis(pool_i, pos, axis=1)
    out_ids = jnp.where(jnp.isfinite(vals), out_ids, -1)
    return _finalize(vals, metric), out_ids


@functools.lru_cache(maxsize=None)
def _mnmg_searcher(mesh: Mesh, axis: str, n_ranks: int, k: int,
                   nprobe: int, cap_max: int, metric: str,
                   use_radix: bool, use_radix_merge: bool,
                   masked: bool = False):
    """Compiled sharded search program for one (mesh, config): per-rank
    probe scan inside ``shard_map``, in-graph all-gather of the k
    candidates per rank (XLA inserts the collective for the replicated
    merge — same idiom as ``knn_mnmg``), one global select, one
    finalize. The query buffer is donated: searches stream through the
    serving loop and the previous launch's queries are dead weight.

    ``masked=True`` is the streaming-delete variant (ISSUE 17): the
    body takes one extra replicated operand — the packed tombstone
    bitset over global ids — which every rank ANDs into its gather
    validity mask (:func:`ivf_flat._probe_topk`'s ``tomb_words``).
    The unmasked program is byte-identical to the pre-streaming one."""

    def shard_fn(db_s, ids_s, st_s, sz_s, q, c, *tw):
        vals, ids = _probe_topk(
            q, c, db_s[0], ids_s[0], st_s[0], sz_s[0], k=k,
            nprobe=nprobe, cap_max=cap_max, metric=metric,
            use_radix=use_radix, tomb_words=tw[0] if tw else None)
        return vals[None], ids[None]              # [1, q, k] per rank

    def body(queries, centroids, db_sh, ids_sh, starts_sh, sizes_sh,
             *tomb):
        specs = (P(axis), P(axis), P(axis), P(axis), P(), P())
        if masked:
            specs = specs + (P(),)
        av, ai = jax.shard_map(
            shard_fn, mesh=mesh, in_specs=specs,
            out_specs=(P(axis), P(axis)))(
                db_sh, ids_sh, starts_sh, sizes_sh, queries, centroids,
                *tomb)
        pool_v = jnp.moveaxis(av, 0, 1).reshape(
            queries.shape[0], n_ranks * k)
        pool_i = jnp.moveaxis(ai, 0, 1).reshape(
            queries.shape[0], n_ranks * k)
        return _merge_body(pool_v, pool_i, k=k, metric=metric,
                           use_radix=use_radix_merge)

    return jax.jit(body, donate_argnums=(0,))


def _radix_flags(index: IvfMnmgIndex, k: int, nprobe: int, *arrays):
    """(local, merge) radix gating, through the same predicate the
    single-rank search uses — local select over the nprobe·cap_max tile,
    merge select over the n_ranks·k pool. The local select runs INSIDE
    the shard_map body, whose operands always carry vma — which the
    Pallas interpreter cannot replay — so interpret mode gates it off
    directly (same move ``knn_mnmg`` makes for its fused shard kernel);
    the merge runs outside the shard body and needs no such gate."""
    from raft_tpu.util.pallas_utils import use_interpret

    return (not use_interpret()
            and _use_radix(nprobe * index.cap_max, k, *arrays),
            _use_radix(index.n_ranks * k, k, *arrays))


@with_matmul_precision
def search_mnmg(res, index: IvfMnmgIndex, queries, k: int, nprobe: int,
                *, tomb_words=None
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest database rows per query over the sharded index:
    replicated (distances [q, k], indices [q, k]) in GLOBAL database
    row numbering, nearest first, pad id -1 / distance +inf exactly as
    :func:`raft_tpu.neighbors.ivf_flat.search`.

    ``nprobe >= n_lists`` delegates to brute force on the reconstructed
    database — the shared exactness boundary, bit-identical to the
    single-rank full probe at every rank count (the CI-gated claim).
    Partial probes run the one-program ``shard_map`` path: no host hop,
    donated query carry, per-element distance values identical across
    rank counts.

    Admission (PR-5 contract): with a ``runtime.limits`` budget active,
    an over-budget launch degrades to query-row chunks (rows are
    independent — bits identical) or raises the typed rejection.
    """
    from raft_tpu.runtime import limits

    queries = jnp.asarray(queries)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be [q, {index.dim}], got "
                         f"{queries.shape}")
    if not 0 < k <= index.n_db:
        raise ValueError(f"need 0 < k <= n_db, got k={k}, "
                         f"n_db={index.n_db}")
    if nprobe <= 0:
        raise ValueError(f"need nprobe > 0, got {nprobe}")
    metric = index.metric
    if nprobe >= index.n_lists:
        if tomb_words is not None:
            raise ValueError(
                "tomb_words is only supported on the partial-probe "
                "path; the streaming layer owns the exact path (it "
                "brute-forces the live-row reconstruction instead)")
        from raft_tpu.neighbors.brute_force import knn

        trace.record_event("ivf_mnmg.search", nprobe=index.n_lists,
                           n_lists=index.n_lists, k=k,
                           n_ranks=index.n_ranks, scanned_frac=1.0,
                           path="exact")
        return knn(res, index.reconstruct(), queries, k, metric=metric)
    probe_rows = nprobe * index.cap_max
    if probe_rows < k:
        raise ValueError(
            f"nprobe={nprobe} reaches at most {probe_rows} candidates "
            f"< k={k}; raise nprobe (>= n_lists scans exactly)")
    trace.record_event("ivf_mnmg.search", nprobe=nprobe,
                       n_lists=index.n_lists, k=k,
                       n_ranks=index.n_ranks,
                       scanned_frac=round(
                           index.scanned_fraction(nprobe), 4),
                       path="ivf_mnmg")
    use_radix, use_radix_merge = _radix_flags(
        index, k, nprobe, index.packed_db_sh, queries)
    run = _mnmg_searcher(index.mesh, index.axis, index.n_ranks, k,
                         nprobe, index.cap_max, metric, use_radix,
                         use_radix_merge, tomb_words is not None)
    tomb = () if tomb_words is None else (jax.device_put(
        jnp.asarray(tomb_words), NamedSharding(index.mesh, P())),)

    def launch(qrows):
        # a fresh replicated buffer per launch: the donated carry must
        # be owned by this call, never an alias of the caller's array
        qbuf = jax.device_put(
            jnp.array(qrows),
            NamedSharding(index.mesh, P()))
        return run(qbuf, index.flat.centroids, index.packed_db_sh,
                   index.packed_ids_sh, index.starts_sh,
                   index.sizes_sh, *tomb)

    budget = limits.active_budget()
    if budget is not None:
        op = "neighbors.ivf_mnmg_search"
        qn = int(queries.shape[0])
        itemsize = index.packed_db_sh.dtype.itemsize
        est = limits.estimate_bytes(
            op, n_queries=qn, probe_rows=probe_rows, n_dims=index.dim,
            k=k, n_ranks=index.n_ranks, itemsize=itemsize,
            packed_rows=index.cap_rank_max)
        if not limits.admit(op, est, budget=budget):
            fixed_bytes = (index.cap_rank_max * index.dim * itemsize
                           + index.cap_rank_max * 4)
            per_row = limits.estimate_bytes(
                op, n_queries=1, probe_rows=probe_rows,
                n_dims=index.dim, k=k, n_ranks=index.n_ranks,
                itemsize=itemsize)
            chunk = (budget.limit_bytes - fixed_bytes) // max(per_row, 1)
            if chunk < 1:
                limits.reject(op, est, budget=budget,
                              detail="even a single query row's "
                                     "per-rank candidate tile overflows "
                                     "the budget")
            limits.record_degraded(op)
            outs = [launch(queries[i:i + int(chunk)])
                    for i in range(0, qn, int(chunk))]
            return (jnp.concatenate([o[0] for o in outs], axis=0),
                    jnp.concatenate([o[1] for o in outs], axis=0))
    return launch(queries)


# ---------------------------------------------------------------------------
# split halves for cross-process serving cliques
# ---------------------------------------------------------------------------

_local_jit = functools.partial(
    jax.jit, static_argnames=("k", "nprobe", "cap_max", "metric",
                              "use_radix"))(_probe_topk)

_merge_jit = functools.partial(
    jax.jit, static_argnames=("k", "metric", "use_radix"))(_merge_body)


def search_local(index: IvfMnmgIndex, rank: int, queries, k: int,
                 nprobe: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One rank's half of the sharded search: raw ascending selection
    keys [q, k] + global ids [q, k] from this rank's shard only
    (+inf / -1 where the rank owns fewer than k reachable candidates).
    A cross-process serving clique runs this per rank, exchanges the
    (keys, ids) pool over the host mailbox — the transport that
    survives a SIGKILL'd peer, unlike an XLA collective — and merges
    with :func:`merge_pool`. The numerics are the SAME traced body the
    one-program ``shard_map`` path runs per rank."""
    db_s, ids_s, st_s, sz_s = index.shard(rank)
    use_radix = _use_radix(nprobe * index.cap_max, k, db_s, queries)
    return _local_jit(jnp.asarray(queries), index.flat.centroids,
                      db_s, ids_s, st_s, sz_s, k=k, nprobe=nprobe,
                      cap_max=index.cap_max, metric=index.metric,
                      use_radix=use_radix)


def merge_pool(vals_stack, ids_stack, k: int, metric: str
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge rank-stacked candidate pools ``[R, q, k]`` (raw keys from
    :func:`search_local`, rank-major order) into the final replicated
    (distances, ids) — the same global select + single finalize the
    in-graph merge performs, so in-process and cross-process serving
    agree bit-for-bit for a given rank order."""
    vals_stack = jnp.asarray(vals_stack)
    ids_stack = jnp.asarray(ids_stack)
    r, q, kk = vals_stack.shape
    pool_v = jnp.moveaxis(vals_stack, 0, 1).reshape(q, r * kk)
    pool_i = jnp.moveaxis(ids_stack, 0, 1).reshape(q, r * kk)
    use_radix = _use_radix(r * kk, k)
    return _merge_jit(pool_v, pool_i, k=k, metric=metric,
                      use_radix=use_radix)
