"""Brute-force k-nearest neighbors rebuilt from the primitives layer.

The reference migrated its k-NN/ANN algorithm tier to cuVS
(README.md:99-135) but kept the layers they are built FROM — the
contraction engine and select_k. This module is the canonical consumer
composition (cuvs::neighbors::brute_force lineage): tiled fused-metric
distances + running top-k merges, the same way the kmeans flagship
composes fused L2-argmin + one-hot updates. :mod:`ivf_flat` stacks the
next layer — the coarse-quantized inverted-file index that turns the
O(n) scan into probes over a few lists.
"""

from raft_tpu.neighbors import election  # noqa: F401
from raft_tpu.neighbors import ivf_flat  # noqa: F401
from raft_tpu.neighbors import ivf_mnmg  # noqa: F401
from raft_tpu.neighbors import ivf_pq  # noqa: F401
from raft_tpu.neighbors import scrub  # noqa: F401
from raft_tpu.neighbors import streaming  # noqa: F401
from raft_tpu.neighbors import wal_ship  # noqa: F401
from raft_tpu.neighbors.brute_force import knn, knn_mnmg  # noqa: F401
from raft_tpu.neighbors.election import (ElectionError,  # noqa: F401
                                         ElectionNode, ElectionRecord)
from raft_tpu.neighbors.ivf_flat import IvfFlatIndex  # noqa: F401
from raft_tpu.neighbors.ivf_mnmg import (IvfMnmgIndex,  # noqa: F401
                                         build_mnmg, rebalance_mnmg,
                                         search_mnmg, shrink_mnmg)
from raft_tpu.neighbors.ivf_pq import IvfPqIndex  # noqa: F401
from raft_tpu.neighbors.scrub import Scrubber, ScrubReport  # noqa: F401
from raft_tpu.neighbors.streaming import (Compactor,  # noqa: F401
                                          DriftGauge, MutationLog,
                                          RecoveryError,
                                          ShardCorruptError,
                                          StreamingError,
                                          StreamingIndex,
                                          StreamingMnmg,
                                          TermFencedError, WalGapError,
                                          stream_build)
from raft_tpu.neighbors.wal_ship import (CatchupReport,  # noqa: F401
                                         WalFollower, WalFrameError,
                                         WalQuorumError, WalShipper,
                                         bootstrap_follower)

__all__ = ["knn", "knn_mnmg", "ivf_flat", "IvfFlatIndex",
           "ivf_pq", "IvfPqIndex",
           "ivf_mnmg", "IvfMnmgIndex", "build_mnmg", "search_mnmg",
           "shrink_mnmg", "rebalance_mnmg",
           "streaming", "StreamingIndex", "StreamingMnmg",
           "stream_build", "Compactor", "DriftGauge", "MutationLog",
           "StreamingError", "RecoveryError",
           "wal_ship", "WalShipper", "WalFollower", "CatchupReport",
           "bootstrap_follower", "WalGapError", "WalFrameError",
           "WalQuorumError", "TermFencedError",
           "election", "ElectionNode", "ElectionRecord",
           "ElectionError",
           "scrub", "Scrubber", "ScrubReport", "ShardCorruptError"]
