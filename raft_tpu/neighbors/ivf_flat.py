"""IVF-Flat: sub-linear kNN composed from this tree's own primitives
(lineage: cuvs::neighbors::ivf_flat, the inverted-file design of Jégou
et al.'s IVFADC — RAFT's ANN layer was always built FROM the layers this
repo owns: kmeans coarse quantizer, pairwise distance, select_k, gather).

Index layout (the TPU formulation): the database is partitioned by the
coarse quantizer into ``n_lists`` inverted lists, packed back-to-back in
one dense ``[cap_total, d]`` matrix. Each list's slot span is padded to
``SLOT_ALIGN`` so list tails stay bucket-aligned (``extend`` appends
in-place until a tail overflows) and CSR-style ``starts``/``sizes``
describe the spans. Probe scans then gather ``nprobe`` whole spans with
ONE padded index matrix (:func:`raft_tpu.matrix.take_rows`) into a dense
``[q, nprobe·cap_max, d]`` candidate tile — fine distances stay MXU
work, pad slots are masked to +inf, and the PR-7 radix / top-k epilogue
selects per query. Rows within a list are stored in ascending original
id, so ``extend`` followed by ``search`` is bit-identical to a rebuild
with the same centroids whenever the new rows fit the padded tails (new
ids sort after every old id by construction; an overflowing tail
triggers a full repack, which IS the rebuild).

Exactness boundary: ``nprobe >= n_lists`` means every list is scanned —
the search delegates to :func:`raft_tpu.neighbors.brute_force.knn` on
the exactly-reconstructed database (packed rows are the original rows,
unmodified), so the full-scan setting is bit-identical to brute force,
ties and NaN rows included. Partial probes are approximate: a query's
true neighbor in an unprobed list is missed — the recall-vs-latency
trade the ``neighbors/ivf_recall`` bench family quantifies. Rows with
fewer than k reachable candidates pad with id -1 / +inf distance.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.core import trace
from raft_tpu.matrix.epilogue import masked_topk
from raft_tpu.matrix.gather import take_rows
from raft_tpu.util import precision
from raft_tpu.util.math import round_up_to_multiple
from raft_tpu.util.precision import with_matmul_precision

__all__ = ["IvfFlatIndex", "build", "search", "extend", "SLOT_ALIGN"]

# List capacities round up to this many slots: tails absorb extends
# without repacking, and every span stays aligned for the padded gather.
SLOT_ALIGN = 8

# metric -> fine-distance kernel family ("l2" expanded / "inner"), the
# subset whose coarse routing is well-defined by the same quantizer.
_METRICS = {"l2": "l2", "sqeuclidean": "l2", "euclidean": "l2",
            "inner": "inner"}


def _resolve_metric(metric: str) -> str:
    kernel = _METRICS.get(metric)
    if kernel is None:
        raise ValueError(
            f"ivf_flat supports metrics {sorted(_METRICS)}, got "
            f"{metric!r} (cosine et al.: normalize + 'inner', or use "
            f"brute force)")
    return kernel


@dataclasses.dataclass
class IvfFlatIndex:
    """Built IVF-Flat index: coarse centroids + packed inverted lists.

    ``packed_db`` keeps the ORIGINAL row dtype and bytes (reconstruction
    is exact — the nprobe=n_lists path depends on it); ``packed_ids`` is
    -1 in pad slots; ``starts``/``sizes`` are the CSR span table; the
    host-side ``caps`` mirror (padded span widths) is what ``extend``
    consults without a device sync."""

    centroids: jnp.ndarray          # [n_lists, d] float32
    packed_db: jnp.ndarray          # [cap_total, d] original dtype
    packed_ids: jnp.ndarray         # [cap_total] int32, -1 = pad slot
    starts: jnp.ndarray             # [n_lists] int32 (exclusive cumsum)
    sizes: jnp.ndarray              # [n_lists] int32 live rows per list
    caps: np.ndarray                # [n_lists] host int64 padded widths
    cap_max: int                    # static gather width = caps.max()
    n_db: int                       # live database rows
    metric: str
    _db_cache: Optional[jnp.ndarray] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def n_lists(self) -> int:
        return int(self.centroids.shape[0])

    @property
    def dim(self) -> int:
        return int(self.centroids.shape[1])

    def scanned_fraction(self, nprobe: int) -> float:
        """Fraction of the index a search at ``nprobe`` plans to scan
        (list-count fraction — the number the ``ivf.search`` trace event
        carries)."""
        return min(1.0, nprobe / max(self.n_lists, 1))

    def reconstruct(self) -> jnp.ndarray:
        """The database in original row order, bit-exact (inverse of the
        packing permutation). Cached; ``extend`` invalidates."""
        if self._db_cache is None:
            ids = np.asarray(self.packed_ids)
            live = ids >= 0
            db = np.empty((self.n_db, self.dim),
                          np.asarray(self.packed_db).dtype)
            db[ids[live]] = np.asarray(self.packed_db)[live]
            self._db_cache = jnp.asarray(db)
        return self._db_cache


def _coarse_labels(db, centroids):
    """Nearest-centroid assignment through the SAME fused path kmeans
    uses (:func:`raft_tpu.cluster.kmeans._assign` under the shared
    precision scope) — build and extend must route a row to the same
    list or extend==rebuild breaks."""
    from raft_tpu.cluster.kmeans import _assign

    with precision.scope():
        _, labels = _assign(jnp.asarray(db, jnp.float32),
                            jnp.asarray(centroids, jnp.float32))
    return np.asarray(labels)


def _pack(db_np: np.ndarray, ids_np: np.ndarray, labels: np.ndarray,
          n_lists: int, slack_slots: int = 0):
    """Stable-pack rows into padded spans: within a list, ascending
    original id (stable sort key). Returns the packed arrays + host
    span table. ``slack_slots`` reserves at least that many free tail
    slots per list beyond alignment padding — the streaming repack
    passes it so compaction leaves growth headroom (a repack that
    re-fills every tail would re-trigger the tail-full compaction
    criterion forever)."""
    counts = np.bincount(labels, minlength=n_lists).astype(np.int64)
    caps = np.asarray(
        [round_up_to_multiple(int(c) + int(slack_slots), SLOT_ALIGN)
         for c in counts], np.int64)
    starts = np.zeros(n_lists, np.int64)
    np.cumsum(caps[:-1], out=starts[1:])
    order = np.argsort(labels, kind="stable")       # (label, id) order
    excl = np.zeros(n_lists, np.int64)
    np.cumsum(counts[:-1], out=excl[1:])
    within = np.arange(len(labels)) - np.repeat(excl, counts)
    slots = starts[labels[order]] + within
    cap_total = int(caps.sum())
    packed_db = np.zeros((cap_total, db_np.shape[1]), db_np.dtype)
    packed_ids = np.full(cap_total, -1, np.int32)
    packed_db[slots] = db_np[order]
    packed_ids[slots] = ids_np[order]
    return packed_db, packed_ids, starts, counts, caps


def build(res, db, n_lists: int, metric: str = "l2", *,
          max_iter: int = 25, seed: int = 0,
          centroids=None) -> IvfFlatIndex:
    """Train the coarse quantizer and pack the inverted lists.

    The quantizer is :func:`raft_tpu.cluster.kmeans.kmeans_fit` on the
    database (the PR-8 compiled-driver path — ``sync_every`` defaults
    from the cost model), unless ``centroids`` are supplied (a repack /
    extend-rebuild passes the trained ones through so assignment is
    identical). Final list assignment always re-runs the fused
    nearest-centroid pass against the FINAL centroids.
    """
    db = jnp.asarray(db)
    if db.ndim != 2:
        raise ValueError(f"db must be [n, d], got {db.shape}")
    n = int(db.shape[0])
    if not 0 < n_lists <= n:
        raise ValueError(f"need 0 < n_lists <= n_db, got n_lists="
                         f"{n_lists}, n_db={n}")
    _resolve_metric(metric)
    if centroids is None:
        from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

        params = KMeansParams(n_clusters=n_lists, max_iter=max_iter,
                              seed=seed)
        centroids, _, _, _ = kmeans_fit(res, params,
                                        db.astype(jnp.float32))
    centroids = jnp.asarray(centroids, jnp.float32)
    if centroids.shape != (n_lists, db.shape[1]):
        raise ValueError(f"centroids must be [{n_lists}, {db.shape[1]}]"
                         f", got {centroids.shape}")
    labels = _coarse_labels(db, centroids)
    packed_db, packed_ids, starts, counts, caps = _pack(
        np.asarray(db), np.arange(n, dtype=np.int32), labels, n_lists)
    return IvfFlatIndex(
        centroids=centroids,
        packed_db=jnp.asarray(packed_db),
        packed_ids=jnp.asarray(packed_ids),
        starts=jnp.asarray(starts, jnp.int32),
        sizes=jnp.asarray(counts, jnp.int32),
        caps=caps, cap_max=int(caps.max(initial=0)), n_db=n,
        metric=metric)


def extend(res, index: IvfFlatIndex, new_rows) -> IvfFlatIndex:
    """Append rows to the index (new ids continue from ``n_db``).

    New rows land in their lists' padded tails when they fit — a pure
    append, no repartitioning. Any overflowing tail triggers a full
    repack: rebuild from the reconstructed database + new rows with the
    SAME centroids. Both branches produce bit-identical search results
    to that rebuild (tail appends preserve the ascending-id pack order
    because every new id exceeds every old id, and a fitting append
    leaves every padded span width unchanged:
    round_up(old+new, SLOT_ALIGN) == round_up(old, SLOT_ALIGN) whenever
    old+new still fits the old span)."""
    new_rows = jnp.asarray(new_rows, index.packed_db.dtype)
    if new_rows.ndim != 2 or new_rows.shape[1] != index.dim:
        raise ValueError(f"new_rows must be [m, {index.dim}], got "
                         f"{new_rows.shape}")
    labels = _coarse_labels(new_rows, index.centroids)
    sizes = np.asarray(index.sizes, np.int64)
    add = np.bincount(labels, minlength=index.n_lists).astype(np.int64)
    if np.any(sizes + add > index.caps):
        full = jnp.concatenate([index.reconstruct(), new_rows], axis=0)
        return build(res, full, index.n_lists, index.metric,
                     centroids=index.centroids)
    starts = np.asarray(index.starts, np.int64)
    order = np.argsort(labels, kind="stable")
    excl = np.zeros(index.n_lists, np.int64)
    np.cumsum(add[:-1], out=excl[1:])
    within = np.arange(len(labels)) - np.repeat(excl, add)
    slots = (starts + sizes)[labels[order]] + within
    packed_db = np.asarray(index.packed_db).copy()
    packed_ids = np.asarray(index.packed_ids).copy()
    new_ids = np.arange(index.n_db, index.n_db + len(labels), dtype=np.int32)
    packed_db[slots] = np.asarray(new_rows)[order]
    packed_ids[slots] = new_ids[order]
    return IvfFlatIndex(
        centroids=index.centroids,
        packed_db=jnp.asarray(packed_db),
        packed_ids=jnp.asarray(packed_ids),
        starts=index.starts,
        sizes=jnp.asarray(sizes + add, jnp.int32),
        caps=index.caps, cap_max=index.cap_max,
        n_db=index.n_db + int(new_rows.shape[0]), metric=index.metric)


# ---------------------------------------------------------------------------
# search
# ---------------------------------------------------------------------------


def _probe_topk(queries, centroids, packed_db, packed_ids, starts,
                sizes, *, k: int, nprobe: int, cap_max: int,
                metric: str, use_radix: bool, tomb_words=None):
    """The probe scan up to (but not including) the metric finalize:
    coarse pairwise -> top-nprobe lists -> one padded span gather ->
    masked fine distances -> radix / top_k epilogue. Returns RAW
    ascending selection keys (smaller = nearer for every metric; +inf =
    unreachable) plus ids — the mergeable form: the MNMG shard body
    (:mod:`raft_tpu.neighbors.ivf_mnmg`) pools these keys across ranks
    and finalizes once after the global merge, so per-rank and
    single-rank candidates carry identical per-element values.

    ``tomb_words`` (streaming-index deletes, ISSUE 17) is an optional
    packed uint32 tombstone bitset over ORIGINAL row ids
    (:class:`raft_tpu.core.bitset.Bitset` words): set bits AND into the
    gather's validity mask exactly like pad slots, so a deleted row is
    never selected and every untouched id scores bit-identically (an
    all-zero bitset is a value-level no-op: ``valid & ~0 == valid``).
    ``None`` keeps the pre-streaming traced graph byte-identical."""
    kernel = _METRICS[metric]
    with precision.scope():
        q = queries.astype(jnp.float32)
        c = centroids.astype(jnp.float32)
        # coarse routing: expanded metric against the centroid table
        # (tiny [q, n_lists] block — select_k AUTO would hand this
        # shape to lax.top_k, so use it directly)
        ip = q @ c.T
        if kernel == "l2":
            coarse = (jnp.sum(c * c, axis=1)[None, :] - 2.0 * ip
                      + jnp.sum(q * q, axis=1)[:, None])
        else:
            coarse = -ip
        _, probed = lax.top_k(-coarse, nprobe)          # [q, nprobe]
        # one padded index matrix gathers all probed spans densely
        blocks, _ = take_rows(None, packed_db, starts[probed],
                              sizes[probed], cap_max)
        ids, valid = take_rows(None, packed_ids, starts[probed],
                               sizes[probed], cap_max, fill_value=-1)
        L = nprobe * cap_max
        cand = blocks.astype(jnp.float32).reshape(q.shape[0], L, -1)
        ids = ids.reshape(q.shape[0], L)
        valid = valid.reshape(q.shape[0], L)
        if tomb_words is not None:
            from raft_tpu.core.bitset import Bitset

            # pad slots carry id -1: clamp for the word gather — their
            # bit is irrelevant because valid is already False there
            tombs = Bitset(int(tomb_words.shape[0]) * 32,
                           words=tomb_words)
            dead = tombs.test(jnp.maximum(ids, 0))
            valid = jnp.logical_and(valid, jnp.logical_not(dead))
        ipf = jnp.einsum("qd,qld->ql", q, cand)
        if kernel == "l2":
            dist = (jnp.sum(cand * cand, axis=-1) - 2.0 * ipf
                    + jnp.sum(q * q, axis=1)[:, None])
        else:
            dist = -ipf
        # masked scoring epilogue: one spelling shared with the chunked
        # kNN formulations (epilogue.masked_topk, ISSUE 14)
        vals, pos = masked_topk(dist, valid, k, use_radix=use_radix)
        out_ids = jnp.take_along_axis(ids, pos, axis=1)
        # pad-slot picks (underfull candidate rows) -> id -1, dist +inf
        out_ids = jnp.where(jnp.isfinite(vals), out_ids, -1)
        return vals, out_ids


def _search_body(queries, centroids, packed_db, packed_ids, starts,
                 sizes, tomb_words=None, *, k: int, nprobe: int,
                 cap_max: int, metric: str, use_radix: bool):
    """The traced probe scan (:func:`_probe_topk` + metric finalize).
    Row-independent per query (the serving invariant: a batched launch
    is bit-identical to per-request launches)."""
    from raft_tpu.neighbors.brute_force import _finalize

    vals, out_ids = _probe_topk(
        queries, centroids, packed_db, packed_ids, starts, sizes, k=k,
        nprobe=nprobe, cap_max=cap_max, metric=metric,
        use_radix=use_radix, tomb_words=tomb_words)
    return _finalize(vals, metric), out_ids


_search_jit = functools.partial(
    jax.jit, static_argnames=("k", "nprobe", "cap_max", "metric",
                              "use_radix"))(_search_body)


def _use_radix(n_candidates: int, k: int, *arrays) -> bool:
    from raft_tpu.matrix import radix_select
    from raft_tpu.util.pallas_utils import interpret_needs_ref

    return (radix_select.preferred(n_candidates, k)
            and radix_select.supports(jnp.float32, n_candidates, k)
            and not interpret_needs_ref(*arrays))


@with_matmul_precision
def search(res, index: IvfFlatIndex, queries, k: int, nprobe: int
           ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest database rows per query over ``nprobe`` probed lists.
    Returns (distances [q, k], indices [q, k]) nearest first, indices in
    original database row numbering; rows with fewer than k reachable
    candidates pad with index -1 / distance +inf (similarity -inf for
    'inner'). Ties within the candidate tile resolve in probe order.

    ``nprobe >= n_lists`` scans everything: delegates to
    :func:`raft_tpu.neighbors.brute_force.knn` on the reconstructed
    database — bit-identical to brute force (ties/NaN included), the
    exactness boundary CI gates on.

    Admission (the PR-5 contract): with a ``runtime.limits`` budget
    active, a launch whose gathered candidate tile would overrun it
    degrades to query-row chunks (bit-identical — rows are independent)
    or raises :class:`~raft_tpu.runtime.limits.RejectedError` when even
    one row cannot fit. Every search records an ``ivf.search`` trace
    event carrying nprobe and the scanned fraction.
    """
    from raft_tpu.runtime import limits

    queries = jnp.asarray(queries)
    if queries.ndim != 2 or queries.shape[1] != index.dim:
        raise ValueError(f"queries must be [q, {index.dim}], got "
                         f"{queries.shape}")
    if not 0 < k <= index.n_db:
        raise ValueError(f"need 0 < k <= n_db, got k={k}, "
                         f"n_db={index.n_db}")
    if nprobe <= 0:
        raise ValueError(f"need nprobe > 0, got {nprobe}")
    metric = index.metric
    if nprobe >= index.n_lists:
        from raft_tpu.neighbors.brute_force import knn

        trace.record_event("ivf.search", nprobe=index.n_lists,
                           n_lists=index.n_lists, k=k,
                           scanned_frac=1.0, path="exact")
        return knn(res, index.reconstruct(), queries, k, metric=metric)
    probe_rows = nprobe * index.cap_max
    if probe_rows < k:
        raise ValueError(
            f"nprobe={nprobe} reaches at most {probe_rows} candidates "
            f"< k={k}; raise nprobe (>= n_lists scans exactly)")
    trace.record_event("ivf.search", nprobe=nprobe,
                       n_lists=index.n_lists, k=k,
                       scanned_frac=round(
                           index.scanned_fraction(nprobe), 4),
                       path="ivf")
    fixed = (index.centroids, index.packed_db, index.packed_ids,
             index.starts, index.sizes)
    use_radix = _use_radix(probe_rows, k, index.packed_db, queries)
    run = functools.partial(_search_jit, centroids=fixed[0],
                            packed_db=fixed[1], packed_ids=fixed[2],
                            starts=fixed[3], sizes=fixed[4], k=k,
                            nprobe=nprobe, cap_max=index.cap_max,
                            metric=metric, use_radix=use_radix)
    budget = limits.active_budget()
    if budget is not None:
        op = "neighbors.ivf_search"
        qn = int(queries.shape[0])
        itemsize = index.packed_db.dtype.itemsize
        est = limits.estimate_bytes(
            op, n_queries=qn, probe_rows=probe_rows, n_dims=index.dim,
            k=k, itemsize=itemsize,
            packed_rows=int(index.packed_db.shape[0]))
        if not limits.admit(op, est, budget=budget):
            # degrade: row-chunk the queries — per-row results are
            # independent of batch shape, so the bits are identical
            fixed_bytes = (index.packed_db.size * itemsize
                           + index.packed_ids.size * 4)
            per_row = limits.estimate_bytes(
                op, n_queries=1, probe_rows=probe_rows,
                n_dims=index.dim, k=k, itemsize=itemsize)
            chunk = (budget.limit_bytes - fixed_bytes) // max(per_row, 1)
            if chunk < 1:
                limits.reject(op, est, budget=budget,
                              detail="even a single query row's "
                                     "gathered candidate tile overflows "
                                     "the budget")
            limits.record_degraded(op)
            outs = [run(queries=queries[i:i + int(chunk)])
                    for i in range(0, qn, int(chunk))]
            return (jnp.concatenate([o[0] for o in outs], axis=0),
                    jnp.concatenate([o[1] for o in outs], axis=0))
    return run(queries=queries)
