"""Owning multi-dimensional arrays + non-owning views over host/device memory.

TPU-native re-design of the reference's mdspan/mdarray stack
(core/mdarray.hpp:93-118, core/device_mdarray.hpp:127-183,
core/device_mdspan.hpp and the host_/managed_/pinned_ variants).

Under JAX there is no user-managed device pointer: a device array *is*
``jax.Array`` (HBM, XLA-managed) and a host array is ``numpy.ndarray``.  An
``MdArray`` is a small mutable holder pairing one of those with its
:class:`MemoryType`; the "view" (`.view()`) is the underlying array itself,
which every raft_tpu primitive accepts directly.  Factory helpers mirror the
reference's ``make_device_matrix/vector/scalar`` family.

Layouts: JAX arrays are logically row-major (layout_c / layout_right); a
column-major view is represented by a transposed row-major array plus the
``layout`` tag, mirroring the reference's layout template parameter.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.core.memory_type import MemoryType
from raft_tpu.core.resources import Resources, default_resources, get_device

ROW_MAJOR = "row_major"     # ref: layout_c_contiguous / layout_right
COL_MAJOR = "col_major"     # ref: layout_f_contiguous / layout_left


class MdArray:
    """Owning n-d array tagged with memory type and layout.

    ``data`` may be replaced (functional updates write a new jax.Array back),
    which stands in for the reference's mutable device buffers.
    """

    def __init__(self, data: Any, memory_type: MemoryType,
                 layout: str = ROW_MAJOR):
        self.data = data
        self.memory_type = memory_type
        self.layout = layout

    # -- mdspan protocol ----------------------------------------------------
    def view(self):
        """The non-owning view: the underlying array itself."""
        return self.data

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape)

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def size(self) -> int:
        return int(np.prod(self.data.shape)) if self.data.shape else 1

    def extent(self, axis: int) -> int:
        return int(self.data.shape[axis])

    def __array__(self, dtype=None):
        arr = np.asarray(jax.device_get(self.data))
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return (f"MdArray(shape={self.shape}, dtype={self.dtype}, "
                f"memory_type={self.memory_type.value}, layout={self.layout})")


# -- factories (ref: core/device_mdarray.hpp:127-183; host_mdarray.hpp) ------

def _zeros(res: Optional[Resources], shape, dtype, memory_type: MemoryType,
           layout: str) -> MdArray:
    if memory_type.is_device_accessible:
        res = default_resources(res)
        dev = get_device(res)
        data = jax.device_put(jnp.zeros(shape, dtype=dtype), dev)
    else:
        data = np.zeros(shape, dtype=dtype)
    return MdArray(data, memory_type, layout)


def make_device_matrix(res, n_rows: int, n_cols: int, dtype=jnp.float32,
                       layout: str = ROW_MAJOR) -> MdArray:
    return _zeros(res, (n_rows, n_cols), dtype, MemoryType.DEVICE, layout)


def make_device_vector(res, n: int, dtype=jnp.float32) -> MdArray:
    return _zeros(res, (n,), dtype, MemoryType.DEVICE, ROW_MAJOR)


def make_device_scalar(res, value=0, dtype=jnp.float32) -> MdArray:
    out = _zeros(res, (), dtype, MemoryType.DEVICE, ROW_MAJOR)
    out.data = jnp.asarray(value, dtype=dtype)
    return out


def make_device_mdarray(res, shape, dtype=jnp.float32,
                        layout: str = ROW_MAJOR) -> MdArray:
    return _zeros(res, tuple(shape), dtype, MemoryType.DEVICE, layout)


def make_host_matrix(n_rows: int, n_cols: int, dtype=np.float32,
                     layout: str = ROW_MAJOR) -> MdArray:
    return _zeros(None, (n_rows, n_cols), dtype, MemoryType.HOST, layout)


def make_host_vector(n: int, dtype=np.float32) -> MdArray:
    return _zeros(None, (n,), dtype, MemoryType.HOST, ROW_MAJOR)


def make_host_scalar(value=0, dtype=np.float32) -> MdArray:
    out = _zeros(None, (), dtype, MemoryType.HOST, ROW_MAJOR)
    out.data = np.asarray(value, dtype=dtype)
    return out


def make_pinned_matrix(n_rows: int, n_cols: int, dtype=np.float32) -> MdArray:
    return _zeros(None, (n_rows, n_cols), dtype, MemoryType.PINNED, ROW_MAJOR)


def make_managed_matrix(res, n_rows: int, n_cols: int,
                        dtype=jnp.float32) -> MdArray:
    return _zeros(res, (n_rows, n_cols), dtype, MemoryType.MANAGED, ROW_MAJOR)


# -- layout/type-converting copy (ref: core/detail/copy.hpp:39,178-193) ------

def copy(res: Optional[Resources], dst: MdArray, src: MdArray) -> None:
    """Copy ``src`` into ``dst``, converting memory type / dtype / layout.

    The reference picks between raft-copy, cuBLAS geam and a custom kernel at
    compile time; XLA's transpose+convert+transfer covers all those cases, so
    the dispatch collapses to "move to the right memory space, transpose if
    layouts differ, cast if dtypes differ".
    """
    if dst.shape != src.shape:
        raise ValueError(f"shape mismatch: dst {dst.shape} vs src {src.shape}")
    data = src.data
    if src.layout != dst.layout and len(src.shape) == 2:
        # The backing buffer of a COL_MAJOR MdArray physically stores the
        # transposed row-major matrix; flipping layout means re-materializing
        # the buffer in the other physical order while the logical values
        # stay identical.
        data = (jnp.asarray(data) if dst.memory_type.is_device_accessible
                else np.asarray(data))
        if dst.layout == COL_MAJOR:
            # row-major buffer -> col-major buffer: store A^T contiguously.
            data = data.T.reshape(src.shape)
        else:
            # col-major buffer (holding A^T contiguously) -> row-major A.
            rows, cols = src.shape
            data = data.reshape(cols, rows).T.reshape(src.shape)
    if dst.memory_type.is_device_accessible:
        res = default_resources(res)
        out = jax.device_put(jnp.asarray(data, dtype=dst.dtype),
                             get_device(res))
    else:
        out = np.asarray(jax.device_get(data)).astype(dst.dtype)
    dst.data = out


# -- mdbuffer (ref: core/mdbuffer.hpp): lazy memory-type/dtype conversion ----

class MdBuffer:
    """Variant buffer that lazily materializes views in other memory types.

    ``view(memory_type, dtype)`` returns (and caches) a copy in the requested
    space, copying only when needed — the reference's ``mdbuffer`` contract.
    """

    def __init__(self, source: Any,
                 memory_type: Optional[MemoryType] = None):
        if isinstance(source, MdArray):
            self._mt = source.memory_type
            self._data = source.data
        else:
            self._mt = memory_type or (
                MemoryType.DEVICE if isinstance(source, jax.Array)
                else MemoryType.HOST)
            self._data = source
        self._cache = {(self._mt, np.dtype(self._data.dtype)): self._data}

    @property
    def memory_type(self) -> MemoryType:
        return self._mt

    def view(self, memory_type: Optional[MemoryType] = None, dtype=None):
        memory_type = memory_type or self._mt
        dtype = np.dtype(dtype) if dtype is not None else np.dtype(
            self._data.dtype)
        key = (memory_type, dtype)
        if key not in self._cache:
            if memory_type.is_device_accessible:
                self._cache[key] = jnp.asarray(self._data, dtype=dtype)
            else:
                self._cache[key] = np.asarray(
                    jax.device_get(self._data)).astype(dtype)
        return self._cache[key]

    def is_copy_required(self, memory_type: MemoryType) -> bool:
        return memory_type.is_device_accessible != self._mt.is_device_accessible


def temporary_device_buffer(res, array) -> Any:
    """Device-accessible temporary view of possibly-host data
    (ref: core/temporary_device_buffer.hpp)."""
    if isinstance(array, jax.Array):
        return array
    return jnp.asarray(array)
