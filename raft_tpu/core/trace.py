"""Tracing/profiling ranges (ref: core/nvtx.hpp:88-136).

The reference emits NVTX domain-tagged push/pop ranges (compiled out unless
``RAFT_NVTX``).  The TPU analogue is `jax.named_scope` (visible in XLA HLO
and Xprof traces) plus `jax.profiler.TraceAnnotation` for host-side spans.
A thread-local range stack mirrors core/detail/nvtx_range_stack.hpp so
observers (e.g. the memory resource_monitor) can ask "what range am I in?".
"""

from __future__ import annotations

import contextlib
import threading
from typing import List, Optional

import jax

_tls = threading.local()


def _stack() -> List[str]:
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def current_range() -> Optional[str]:
    """Innermost active range name (None outside any range)."""
    st = _stack()
    return st[-1] if st else None


def range_stack() -> List[str]:
    return list(_stack())


@contextlib.contextmanager
def push_range(name: str):
    """RAII-style range (ref: nvtx.hpp `class range`); usable as decorator
    via `annotate`."""
    _stack().append(name)
    try:
        with jax.named_scope(name), jax.profiler.TraceAnnotation(name):
            yield
    finally:
        _stack().pop()


# Alias matching the reference's free functions.
range = push_range


# -- host-side instantaneous events (ref: nvtx mark) ------------------------
#
# Retry/failure/fault events from the comms resilience layer land here,
# attributed to the innermost active range of the emitting thread, so an
# observer can answer "what was the system doing when rank 3 died?".
# Since ISSUE 4 the ring itself lives in raft_tpu.obs.export (one emit
# path shared with obs spans and the JSONL sink); these functions are
# thin shims kept for every pre-obs caller. Record shape is unchanged.

def record_event(name: str, **attrs) -> None:
    """Record an instantaneous host-side event in the active range.

    The event carries the emitting thread's innermost range (``range``)
    and full range stack (``range_stack``) at emission time, a monotonic
    timestamp, plus any keyword attributes. Shim over
    :func:`raft_tpu.obs.export.emit_event` (lazy import — obs reads this
    module's range stack)."""
    from raft_tpu import obs
    obs.emit_event(name, **attrs)


def events(name: Optional[str] = None) -> List[dict]:
    """Snapshot of recorded events, newest last; optionally filtered by
    event name."""
    from raft_tpu import obs
    return obs.events(name)


def clear_events() -> None:
    from raft_tpu import obs
    obs.clear_events()


def annotate(name: Optional[str] = None):
    """Decorator form: wraps fn body in a named range."""

    def deco(fn):
        label = name or fn.__qualname__

        def wrapped(*args, **kwargs):
            with push_range(label):
                return fn(*args, **kwargs)

        wrapped.__name__ = fn.__name__
        wrapped.__qualname__ = fn.__qualname__
        wrapped.__doc__ = fn.__doc__
        return wrapped

    return deco
