"""Logging (ref: core/logger.hpp:20,58-67).

The reference uses rapids-logger macros with a compile-time level and an
env-var file sink (``RAFT_DEBUG_LOG_FILE``).  Here: a stdlib logger named
``raft_tpu``, level from ``RAFT_TPU_LOG_LEVEL``, optional file sink from
``RAFT_TPU_DEBUG_LOG_FILE``.
"""

from __future__ import annotations

import logging
import threading

from raft_tpu.core import env as _env_mod

LEVELS = {
    "trace": 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
    "off": logging.CRITICAL + 10,
}

logging.addLevelName(5, "TRACE")

logger = logging.getLogger("raft_tpu")

if not logger.handlers:
    _handler: logging.Handler
    _file = _env_mod.read("RAFT_TPU_DEBUG_LOG_FILE")
    _handler = logging.FileHandler(_file) if _file else logging.StreamHandler()
    _handler.setFormatter(
        logging.Formatter("[%(levelname)s] [%(asctime)s] %(message)s"))
    logger.addHandler(_handler)
    logger.setLevel(
        LEVELS.get(_env_mod.read("RAFT_TPU_LOG_LEVEL"), logging.WARNING))


def set_level(level: str) -> None:
    logger.setLevel(LEVELS[level])


def trace(msg, *args):
    logger.log(5, msg, *args)


def debug(msg, *args):
    logger.debug(msg, *args)


def info(msg, *args):
    logger.info(msg, *args)


def warn(msg, *args):
    logger.warning(msg, *args)


def error(msg, *args):
    logger.error(msg, *args)


def critical(msg, *args):
    logger.critical(msg, *args)


# -- throttled warnings ------------------------------------------------------
#
# Per-frame failure conditions (corrupt frames on a flaky link, repeated
# connect retries) would otherwise log at line rate; warn_once emits the
# first occurrence per key at WARNING and the rest at DEBUG.

_once_lock = threading.Lock()
_once_seen: set = set()


def warn_once(key, msg, *args):
    """Warn once per process for ``key``; later repeats demote to debug."""
    with _once_lock:
        first = key not in _once_seen
        if first:
            _once_seen.add(key)
    if first:
        logger.warning(msg, *args)
    else:
        logger.debug(msg, *args)


def child(name: str) -> "logging.Logger":
    """Namespaced child logger (``raft_tpu.<name>``) sharing the sink and
    level configuration of the package logger."""
    return logger.getChild(name)
