"""Composable operator vocabulary used by map/reduce primitives.

Re-design of the reference's host/device functor vocabulary
(core/operators.hpp:27-391).  In JAX these are ordinary callables traceable
under jit; composition helpers mirror ``compose_op`` / ``plug_const_op`` /
``map_args_op``.
"""

from __future__ import annotations

import jax.numpy as jnp


# -- unary ------------------------------------------------------------------

def identity_op(x, *_):
    return x


def void_op(*_):
    return None


def abs_op(x, *_):
    return jnp.abs(x)


def sq_op(x, *_):
    return x * x


def sqrt_op(x, *_):
    return jnp.sqrt(x)


def nz_op(x, *_):
    return jnp.where(x != 0, jnp.ones_like(x), jnp.zeros_like(x))


def key_op(kvp, *_):
    return kvp[0]


def value_op(kvp, *_):
    return kvp[1]


# -- binary -----------------------------------------------------------------

def add_op(a, b):
    return a + b


def sub_op(a, b):
    return a - b


def mul_op(a, b):
    return a * b


def div_op(a, b):
    return a / b


def div_checkzero_op(a, b):
    zero = jnp.zeros_like(a * b)
    return jnp.where(b == 0, zero, a / jnp.where(b == 0, jnp.ones_like(b), b))


def pow_op(a, b):
    return jnp.power(a, b)


def mod_op(a, b):
    return jnp.mod(a, b)


def min_op(a, b):
    return jnp.minimum(a, b)


def max_op(a, b):
    return jnp.maximum(a, b)


def argmin_op(kvp_a, kvp_b):
    """KeyValuePair argmin reduction (ref: core/kvp.hpp + operators.hpp)."""
    ka, va = kvp_a
    kb, vb = kvp_b
    take_b = (vb < va) | ((vb == va) & (kb < ka))
    return (jnp.where(take_b, kb, ka), jnp.where(take_b, vb, va))


def argmax_op(kvp_a, kvp_b):
    ka, va = kvp_a
    kb, vb = kvp_b
    take_b = (vb > va) | ((vb == va) & (kb < ka))
    return (jnp.where(take_b, kb, ka), jnp.where(take_b, vb, va))


def sqdiff_op(a, b):
    d = a - b
    return d * d


def absdiff_op(a, b):
    return jnp.abs(a - b)


# -- combinators (ref: operators.hpp compose_op/plug_const/map_args) ---------

def compose_op(*fns):
    """compose_op(f, g, h)(x) == f(g(h(x)))."""

    def composed(*args):
        out = fns[-1](*args)
        for fn in reversed(fns[:-1]):
            out = fn(out)
        return out

    return composed


def const_op(value):
    def op(*_):
        return value

    return op


def plug_const_op(fn, const, side="right"):
    """Bind a constant to one side of a binary op."""
    if side == "right":
        return lambda x, *_: fn(x, const)
    return lambda x, *_: fn(const, x)


def add_const_op(c):
    return plug_const_op(add_op, c)


def sub_const_op(c):
    return plug_const_op(sub_op, c)


def mul_const_op(c):
    return plug_const_op(mul_op, c)


def div_const_op(c):
    return plug_const_op(div_op, c)


def pow_const_op(c):
    return plug_const_op(pow_op, c)


def map_args_op(fn, *maps):
    """map_args_op(f, g1, g2)(x1, x2) == f(g1(x1), g2(x2))."""

    def op(*args):
        return fn(*(g(a) for g, a in zip(maps, args)))

    return op
