"""Per-backend hardware peak table (ISSUE 13): the denominator of every
roofline fraction.

One module owns the device peaks the performance-attribution layer
(:mod:`raft_tpu.obs.perf`) divides achieved FLOP/s and bytes/s by — so
a "0.31 of roofline" claim always names the ceiling it was measured
against. Two tables live here:

* :data:`TPU_PEAKS` — per-generation theoretical peaks (bf16 MXU
  FLOP/s, HBM bytes/s), matched against ``device.device_kind``. The
  v5e row is the same ceiling pair ``benches/harness.py`` bakes into
  its roofline columns (197 TFLOP/s, 819 GB/s), so a bench row's
  ``mxu_frac`` and a live ``perf_roofline_frac`` gauge are measured
  against one number.
* :data:`SUSTAINED_FLOP_S` / :data:`SUSTAINED_BYTES_S` — the coarse
  order-of-magnitude sustained throughputs ``runtime/limits.py`` uses
  to seed its fast-fail chunk-seconds estimates (rehomed here from
  limits so the serving admission model and the roofline denominator
  can never drift apart silently; limits re-exports them).

``RAFT_TPU_PERF_PEAKS=flops=<num>,bytes=<num>`` overrides the detected
peaks (either term alone overrides just that axis) — the escape hatch
for a generation this table predates. Malformed values raise at the
read site (the ``RAFT_TPU_HBM_BUDGET`` fail-loud policy): a typo'd
peak silently skewing every roofline fraction is a debugging session.

Dependency discipline: this module imports only ``core/env`` (jax is
touched lazily inside :func:`peaks`), so obs, limits, and the serving
layer can all consume it without cycles.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

from raft_tpu.core import env as _env_mod

__all__ = ["HwPeaks", "peaks", "TPU_PEAKS", "CPU_PEAKS", "GPU_PEAKS",
           "SUSTAINED_FLOP_S", "SUSTAINED_BYTES_S"]


@dataclass(frozen=True)
class HwPeaks:
    """One device's roofline ceilings: peak FLOP/s (bf16 MXU on TPU),
    peak HBM bytes/s, and where the numbers came from (``"table"`` —
    the generation table below; ``"fallback"`` — unrecognized device
    kind; ``"env"`` — a ``RAFT_TPU_PERF_PEAKS`` override)."""

    name: str
    flops_per_s: float
    bytes_per_s: float
    source: str = "table"


# Per-generation theoretical peaks, matched longest-substring-first
# against the lowercased ``device_kind`` (e.g. "TPU v5 lite"). FLOP/s
# figures are the bf16 MXU peaks; bytes/s the HBM bandwidth — both per
# chip. The v5e row matches benches/harness.py's roofline ceilings.
TPU_PEAKS: Tuple[Tuple[str, HwPeaks], ...] = (
    ("v6e", HwPeaks("tpu-v6e", 918e12, 1.64e12)),
    ("v6 lite", HwPeaks("tpu-v6e", 918e12, 1.64e12)),
    ("v5p", HwPeaks("tpu-v5p", 459e12, 2.765e12)),
    ("v5e", HwPeaks("tpu-v5e", 197e12, 8.19e11)),
    ("v5 lite", HwPeaks("tpu-v5e", 197e12, 8.19e11)),
    ("v4", HwPeaks("tpu-v4", 275e12, 1.228e12)),
    ("v3", HwPeaks("tpu-v3", 123e12, 9.0e11)),
    ("v2", HwPeaks("tpu-v2", 45e12, 7.0e11)),
)

# CPU fallback: the order-of-magnitude sustained figures the limits
# cost model has used since PR 5 — a host test backend has no stable
# "theoretical peak" worth pretending to.
CPU_PEAKS = HwPeaks("cpu", 5e10, 2e10)
GPU_PEAKS = HwPeaks("gpu", 5e13, 1e12)
_TPU_FALLBACK = HwPeaks("tpu", 197e12, 8.19e11, source="fallback")

# Coarse sustained throughputs for the limits fast-fail chunk-seconds
# model (formerly limits._PEAK_FLOP_S/_PEAK_BYTES_S; limits re-exports
# these). Intentionally below theoretical peak — they seed an admission
# decision, not a measurement.
SUSTAINED_FLOP_S = {"cpu": 5e10, "gpu": 5e13, "tpu": 6e13}
SUSTAINED_BYTES_S = {"cpu": 2e10, "gpu": 1e12, "tpu": 8.19e11}


def _detect(device=None, backend: Optional[str] = None) -> HwPeaks:
    if backend is None or device is not None:
        import jax

        if device is None:
            devs = jax.devices()
            if not devs:
                return CPU_PEAKS
            device = devs[0]
        backend = device.platform
        kind = (getattr(device, "device_kind", "") or "").lower()
    else:
        kind = ""
    if backend == "tpu":
        for frag, pk in TPU_PEAKS:
            if frag in kind:
                return pk
        return _TPU_FALLBACK
    if backend == "gpu":
        return GPU_PEAKS
    if backend == "cpu":
        return CPU_PEAKS
    return replace(CPU_PEAKS, name=backend or "unknown",
                   source="fallback")


def peaks(device=None, *, backend: Optional[str] = None) -> HwPeaks:
    """Roofline ceilings for ``device`` (default: the first JAX device;
    ``backend`` alone skips device inspection — the spelling limits and
    tests use). ``RAFT_TPU_PERF_PEAKS`` terms override the detected
    values and raise at this read on a malformed spelling."""
    pk = _detect(device, backend)
    override = _env_mod.read("RAFT_TPU_PERF_PEAKS")
    if override:
        pk = HwPeaks(pk.name,
                     override.get("flops", pk.flops_per_s),
                     override.get("bytes", pk.bytes_per_s),
                     source="env")
    return pk
