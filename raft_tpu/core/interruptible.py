"""Cooperative cross-thread cancellation (ref: core/interruptible.hpp:63-110).

The reference interposes on stream synchronization: each thread owns a token;
``synchronize`` spins on ``cudaStreamQuery`` yielding at each poll, and a
concurrent ``cancel()`` flips the token making the waiter throw
``interrupted_exception``.

XLA execution can't be interrupted mid-kernel, so the TPU contract is the
honest subset: cancellation is observed *between* dispatched steps.  Host
driver loops (Lanczos, k-means, MST, LAP) call ``check()`` or ``synchronize``
each iteration; ``cancel()`` from any thread makes the next check raise.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import jax


class InterruptedException(RuntimeError):
    """Raised at a cancellation point (ref: raft::interrupted_exception)."""


class CancelToken:
    """Per-thread cancellation flag (ref: interruptible token store)."""

    def __init__(self):
        self._event = threading.Event()

    def cancel(self) -> None:
        self._event.set()

    def cancelled(self) -> bool:
        return self._event.is_set()

    def check(self) -> None:
        """Cancellation point: raise and clear if cancelled
        (matches the reference's flag-consuming yield)."""
        if self._event.is_set():
            self._event.clear()
            raise InterruptedException("raft_tpu: operation cancelled")


_registry_lock = threading.Lock()
_registry: Dict[int, CancelToken] = {}


def get_token(thread_id: Optional[int] = None) -> CancelToken:
    """Token for a thread (default: calling thread), creating on demand.

    Mirrors ``interruptible::get_token()`` /
    ``get_token(std::thread::id)`` (interruptible.hpp:97-110).
    """
    tid = thread_id if thread_id is not None else threading.get_ident()
    with _registry_lock:
        if tid not in _registry:
            _registry[tid] = CancelToken()
        return _registry[tid]


def cancel(thread_id: Optional[int] = None) -> None:
    get_token(thread_id).cancel()


def yield_now() -> None:
    """Cancellation point (ref: interruptible::yield)."""
    get_token().check()


def yield_no_throw() -> bool:
    token = get_token()
    if token.cancelled():
        token._event.clear()
        return False
    return True


def synchronize(*arrays) -> None:
    """Interruptible sync: block on arrays, observing cancellation before
    and after (ref: interruptible::synchronize, :75-92)."""
    yield_now()
    for a in arrays:
        if hasattr(a, "block_until_ready"):
            a.block_until_ready()
    if not arrays:
        jax.effects_barrier()
    yield_now()
