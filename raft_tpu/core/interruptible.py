"""Cooperative cross-thread cancellation (ref: core/interruptible.hpp:63-110).

The reference interposes on stream synchronization: each thread owns a token;
``synchronize`` spins on ``cudaStreamQuery`` yielding at each poll, and a
concurrent ``cancel()`` flips the token making the waiter throw
``interrupted_exception``.

XLA execution can't be interrupted mid-kernel, so the TPU contract is the
honest subset: cancellation is observed *between* dispatched steps.  Host
driver loops (Lanczos, k-means, MST, LAP) call ``check()`` or ``synchronize``
each iteration; ``cancel()`` from any thread makes the next check raise.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Dict, Optional

import jax


class InterruptedException(RuntimeError):
    """Raised at a cancellation point (ref: raft::interrupted_exception)."""


class CancelToken:
    """Per-thread cancellation flag (ref: interruptible token store).

    Beyond the reference's poll-only contract, a token carries *wakers*:
    callbacks fired by ``cancel()`` so threads blocked in interruptible
    waits (the comms mailbox ``get``, resilience backoff sleeps) are
    nudged immediately instead of at their next poll.  A waker must be
    cheap and thread-safe — typically ``Event.set`` or a condition-
    variable ``notify_all`` wrapper.
    """

    def __init__(self):
        self._event = threading.Event()
        self._wakers: list = []
        self._wlock = threading.Lock()

    def cancel(self) -> None:
        self._event.set()
        with self._wlock:
            wakers = list(self._wakers)
        for w in wakers:
            try:
                w()
            except Exception as e:  # one bad waker must not mask the rest
                from raft_tpu.core import logger
                logger.warn("interruptible: waker %r raised %r", w, e)

    def cancelled(self) -> bool:
        return self._event.is_set()

    def clear(self) -> None:
        """Consume the cancellation flag (what ``check`` does on raise)."""
        self._event.clear()

    def check(self) -> None:
        """Cancellation point: raise and clear if cancelled
        (matches the reference's flag-consuming yield)."""
        if self._event.is_set():
            self._event.clear()
            raise InterruptedException("raft_tpu: operation cancelled")

    def add_waker(self, waker: Callable[[], None]) -> None:
        """Register a callback fired (once) by a subsequent ``cancel()``.
        Duplicates are allowed; pair every add with ``remove_waker`` in a
        ``finally`` so tokens don't accumulate dead wakers."""
        with self._wlock:
            self._wakers.append(waker)

    def remove_waker(self, waker: Callable[[], None]) -> None:
        with self._wlock:
            # benign double-unregister: already removed
            with contextlib.suppress(ValueError):
                self._wakers.remove(waker)


_registry_lock = threading.Lock()
_registry: Dict[int, CancelToken] = {}


def get_token(thread_id: Optional[int] = None) -> CancelToken:
    """Token for a thread (default: calling thread), creating on demand.

    Mirrors ``interruptible::get_token()`` /
    ``get_token(std::thread::id)`` (interruptible.hpp:97-110).
    """
    tid = thread_id if thread_id is not None else threading.get_ident()
    with _registry_lock:
        if tid not in _registry:
            _registry[tid] = CancelToken()
        return _registry[tid]


def cancel(thread_id: Optional[int] = None) -> None:
    get_token(thread_id).cancel()


def yield_now() -> None:
    """Cancellation point (ref: interruptible::yield)."""
    get_token().check()


def yield_no_throw() -> bool:
    token = get_token()
    if token.cancelled():
        token._event.clear()
        return False
    return True


def synchronize(*arrays) -> None:
    """Interruptible sync: block on arrays, observing cancellation before
    and after (ref: interruptible::synchronize, :75-92)."""
    yield_now()
    for a in arrays:
        if hasattr(a, "block_until_ready"):
            a.block_until_ready()
    if not arrays:
        jax.effects_barrier()
    yield_now()
