"""Core runtime: handle/resources, array model, operators, serialization.

TPU-native re-design of the reference's cpp/include/raft/core/ layer.
"""

from raft_tpu.core.resources import (  # noqa: F401
    Resources,
    ResourceType,
    ResourceFactory,
    DeviceResources,
    Handle,
    device_resources,
    get_device_resources,
    default_resources,
    get_device,
    set_device,
    get_mesh,
    set_mesh,
    get_rng_state,
    set_rng_state,
    get_comms,
    set_comms,
    comms_initialized,
    get_subcomm,
    set_subcomm,
    get_workspace_limit,
    set_workspace_limit,
    sync,
)
from raft_tpu.core.memory_type import MemoryType, HOST, DEVICE, PINNED, MANAGED  # noqa: F401
from raft_tpu.core.mdarray import (  # noqa: F401
    MdArray,
    MdBuffer,
    ROW_MAJOR,
    COL_MAJOR,
    copy,
    make_device_matrix,
    make_device_vector,
    make_device_scalar,
    make_device_mdarray,
    make_host_matrix,
    make_host_vector,
    make_host_scalar,
    make_pinned_matrix,
    make_managed_matrix,
    temporary_device_buffer,
)
from raft_tpu.core.sparse_types import CSRMatrix, COOMatrix  # noqa: F401
from raft_tpu.core.bitset import Bitset, Bitmap, popc  # noqa: F401
from raft_tpu.core.kvp import KeyValuePair, make_kvp  # noqa: F401
from raft_tpu.core.interruptible import (  # noqa: F401
    InterruptedException,
    CancelToken,
    synchronize,
)
from raft_tpu.core.guards import (  # noqa: F401
    NumericalError,
    NonFiniteError,
    IllConditionedError,
    ConvergenceError,
    ConvergenceReport,
    ArtifactCorruptError,
    guard_mode,
    set_guard_mode,
    guard_scope,
    finite_sentinel,
)
from raft_tpu.core import operators  # noqa: F401
from raft_tpu.core import serialize  # noqa: F401
from raft_tpu.core import trace  # noqa: F401
from raft_tpu.core import logger  # noqa: F401
from raft_tpu.core import memory  # noqa: F401
