"""Device bitset / bitmap (ref: core/bitset.hpp:90-134,378-425, core/bitmap.hpp).

A bitset is a packed uint32 word array on device with test/set/flip/count
operations, used for masking and sample filtering.  All operations are
functional (return a new Bitset) and jit-friendly; ``count`` is the popc
primitive (ref: util/popc.cuh:23).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

WORD_BITS = 32
_WORD_DTYPE = jnp.uint32


def _n_words(n_bits: int) -> int:
    return (n_bits + WORD_BITS - 1) // WORD_BITS


def popc(words: jnp.ndarray) -> jnp.ndarray:
    """Population count over a packed word array (ref: util/popc.cuh:23)."""
    return jnp.sum(jax.lax.population_count(words.astype(_WORD_DTYPE))
                   .astype(jnp.int32))


class Bitset:
    """Packed bit array of logical length ``n_bits`` over uint32 words."""

    def __init__(self, n_bits: int, words: Optional[jnp.ndarray] = None,
                 default_value: bool = True):
        self.n_bits = int(n_bits)
        if words is None:
            fill = jnp.uint32(0xFFFFFFFF) if default_value else jnp.uint32(0)
            words = jnp.full((_n_words(self.n_bits),), fill, dtype=_WORD_DTYPE)
            words = _mask_tail(words, self.n_bits)
        self.words = words

    # -- constructors -------------------------------------------------------
    @staticmethod
    def from_bools(bools: jnp.ndarray) -> "Bitset":
        bools = jnp.asarray(bools, dtype=jnp.bool_).ravel()
        n = bools.shape[0]
        pad = _n_words(n) * WORD_BITS - n
        b = jnp.pad(bools, (0, pad)).reshape(-1, WORD_BITS)
        weights = (jnp.uint32(1) << jnp.arange(WORD_BITS, dtype=_WORD_DTYPE))
        words = jnp.sum(b.astype(_WORD_DTYPE) * weights, axis=1,
                        dtype=_WORD_DTYPE)
        return Bitset(n, words)

    def to_bools(self) -> jnp.ndarray:
        bits = ((self.words[:, None] >>
                 jnp.arange(WORD_BITS, dtype=_WORD_DTYPE)[None, :]) & 1)
        return bits.ravel()[: self.n_bits].astype(jnp.bool_)

    # -- element ops (ref: bitset.hpp test/set/flip) -------------------------
    def test(self, indices) -> jnp.ndarray:
        indices = jnp.asarray(indices)
        word = self.words[indices // WORD_BITS]
        return ((word >> (indices % WORD_BITS).astype(_WORD_DTYPE)) & 1
                ).astype(jnp.bool_)

    def set(self, indices, value: bool = True) -> "Bitset":
        """Set (or clear) the given bit indices; anything outside
        [0, n_bits) — including negatives and the packed tail of the last
        word — is dropped, identically on both scatter paths."""
        indices = jnp.asarray(indices).ravel().astype(jnp.int32)
        n_words = self.words.shape[0]
        oob = n_words * WORD_BITS                  # beyond the last word
        indices = jnp.where((indices >= 0) & (indices < self.n_bits),
                            indices, oob)
        acc = _scatter_word_mask(n_words, indices)
        if value:
            return Bitset(self.n_bits, self.words | acc)
        return Bitset(self.n_bits, self.words & ~acc)

    def flip(self) -> "Bitset":
        return Bitset(self.n_bits,
                      _mask_tail(~self.words, self.n_bits))

    def reset(self, default_value: bool = True) -> "Bitset":
        return Bitset(self.n_bits, default_value=default_value)

    # -- reductions (ref: bitset.hpp count/any/all/none) ---------------------
    def count(self) -> jnp.ndarray:
        return popc(self.words)

    def any(self) -> jnp.ndarray:
        return self.count() > 0

    def all(self) -> jnp.ndarray:
        return self.count() == self.n_bits

    def none(self) -> jnp.ndarray:
        return self.count() == 0

    @property
    def size(self) -> int:
        return self.n_bits


def _mask_tail(words: jnp.ndarray, n_bits: int) -> jnp.ndarray:
    """Zero bits beyond n_bits in the last word."""
    rem = n_bits % WORD_BITS
    if rem == 0:
        return words
    tail_mask = jnp.uint32((1 << rem) - 1)
    return words.at[-1].set(words[-1] & tail_mask)


# Below this many indices the plane scatter wins (sort overhead dominates);
# above it, the sort+cumsum path avoids TPU's serialized scatter entirely.
_SORT_THRESHOLD = 4096


def _scatter_word_mask(n_words: int, indices: jnp.ndarray) -> jnp.ndarray:
    """Packed word mask with bit ``indices[i]`` set, duplicates combined.

    Two formulations, both scatter-light because TPU serializes scatters
    (the reference leans on global-memory atomics here, bitset.hpp:378):

    - small index sets: one max-scatter into an (n_words, 32) bit plane
      followed by a weighted sum along the bit axis (same packing trick as
      :meth:`Bitset.from_bools`).
    - large index sets: NO scatter — sort the indices, build a (32, n_idx)
      per-bit occurrence plane, 2-D cumsum along the sorted axis, and read
      per-word occurrence counts as cumsum differences at word boundaries
      (boundaries via searchsorted = vectorized binary-search gathers).
      count > 0 → bit set, which also absorbs duplicates for free.
      Everything is dense VPU work + gathers, the ops TPU is fast at.
    """
    indices = indices.astype(jnp.int32)
    if indices.shape[0] <= _SORT_THRESHOLD:
        word_idx = indices // WORD_BITS
        bit_pos = indices % WORD_BITS
        plane = jnp.zeros((n_words, WORD_BITS), _WORD_DTYPE)
        plane = plane.at[word_idx, bit_pos].max(jnp.uint32(1),
                                                mode="drop")
        weights = (jnp.uint32(1) << jnp.arange(WORD_BITS,
                                               dtype=_WORD_DTYPE))
        return jnp.sum(plane * weights, axis=1, dtype=_WORD_DTYPE)

    srt = jnp.sort(indices)
    word_idx = srt // WORD_BITS
    bit_pos = srt % WORD_BITS
    # occurrence counts of bit b among the first i sorted indices
    occ = (bit_pos[None, :] == jnp.arange(WORD_BITS,
                                          dtype=jnp.int32)[:, None])
    cum = jnp.cumsum(occ.astype(jnp.int32), axis=1)
    cum = jnp.pad(cum, ((0, 0), (1, 0)))            # cum[:, 0] = 0
    # first sorted position belonging to each word (and the end sentinel)
    bounds = jnp.searchsorted(word_idx,
                              jnp.arange(n_words + 1, dtype=jnp.int32))
    per_word = cum[:, bounds[1:]] - cum[:, bounds[:-1]]   # (32, n_words)
    weights = (jnp.uint32(1) << jnp.arange(WORD_BITS,
                                           dtype=_WORD_DTYPE))
    return jnp.sum((per_word > 0).astype(_WORD_DTYPE) * weights[:, None],
                   axis=0, dtype=_WORD_DTYPE)


class Bitmap(Bitset):
    """2-D bitset addressed by (row, col) (ref: core/bitmap.hpp)."""

    def __init__(self, n_rows: int, n_cols: int,
                 words: Optional[jnp.ndarray] = None,
                 default_value: bool = False):
        super().__init__(n_rows * n_cols, words, default_value)
        self.n_rows = int(n_rows)
        self.n_cols = int(n_cols)

    @staticmethod
    def from_bool_matrix(mat: jnp.ndarray) -> "Bitmap":
        mat = jnp.asarray(mat, dtype=jnp.bool_)
        bs = Bitset.from_bools(mat.ravel())
        return Bitmap(mat.shape[0], mat.shape[1], bs.words)

    def test_rc(self, rows, cols) -> jnp.ndarray:
        return self.test(jnp.asarray(rows) * self.n_cols + jnp.asarray(cols))

    def to_bool_matrix(self) -> jnp.ndarray:
        return self.to_bools().reshape(self.n_rows, self.n_cols)
