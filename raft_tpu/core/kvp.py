"""Key-value pair used by arg-reductions (ref: core/kvp.hpp).

On TPU a KVP is simply a pair of arrays (keys, values); helpers here build
and reduce them with the tie-breaking rules the reference's device atomics
implement (smallest key wins on equal value).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class KeyValuePair(NamedTuple):
    key: jnp.ndarray
    value: jnp.ndarray


def make_kvp(keys, values) -> KeyValuePair:
    return KeyValuePair(jnp.asarray(keys), jnp.asarray(values))
