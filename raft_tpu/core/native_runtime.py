"""High-level wrappers over the native host runtime
(raft_tpu/_native/raft_tpu_native.cpp).

Each class mirrors a native component of the reference runtime:

- TrackedHostPool      <- mr/statistics_adaptor.hpp + mmap_memory_resource
- NativeResourceMonitor<- mr/resource_monitor.hpp:29-66
- native npy save/load <- core/serialize.hpp + detail/mdspan_numpy_serializer
- NativeThreadPool     <- host-job analogue of the handle's stream pool
- native interruptible <- core/interruptible.hpp token registry

All are optional accelerations: when `_native.native_available()` is False
(no g++), the pure-Python equivalents in core.memory / core.serialize /
core.interruptible remain the implementation.
"""

from __future__ import annotations

import contextlib
import ctypes
import threading
import weakref
from typing import Optional

import numpy as np

from raft_tpu import _native

_DESCR = {
    np.dtype("float32"): "<f4", np.dtype("float64"): "<f8",
    np.dtype("int32"): "<i4", np.dtype("int64"): "<i8",
    np.dtype("int16"): "<i2", np.dtype("int8"): "|i1",
    np.dtype("uint8"): "|u1", np.dtype("uint32"): "<u4",
    np.dtype("uint64"): "<u8", np.dtype("bool"): "|b1",
}
_DESCR_INV = {v: k for k, v in _DESCR.items()}


def native_available() -> bool:
    return _native.native_available()


class TrackedHostPool:
    """Statistics-tracking host allocator (optionally mmap-backed).

    Hands out numpy arrays backed by native allocations; frees on
    release() or pool destruction. ref: mr/statistics_adaptor.hpp:25,66,
    mr/mmap_memory_resource.hpp:31,86."""

    def __init__(self, use_mmap: bool = False):
        self._lib = _native.get_lib()
        if self._lib is None:
            raise RuntimeError(
                f"native runtime unavailable: {_native.build_error()}")
        self._pool = self._lib.rt_pool_create(1 if use_mmap else 0)
        # base address -> (ptr, weakref.finalize); keyed by the allocation's
        # data address (stable), not id() (recyclable)
        self._ptrs: dict[int, tuple] = {}
        self._cb = None  # keep ctypes callback alive
        self._lock = threading.Lock()
        # finalizers consult this shared cell so an array collected after
        # close() doesn't touch the destroyed native pool
        self._alive = {"pool": self._pool, "lib": self._lib}

    def allocate(self, shape, dtype=np.float32) -> np.ndarray:
        dtype = np.dtype(dtype)
        count = int(np.prod(shape))
        if count == 0:
            return np.empty(shape, dtype)   # no native backing needed
        nbytes = count * dtype.itemsize
        ptr = self._lib.rt_pool_alloc(self._pool, nbytes)
        if not ptr:
            raise MemoryError(f"native pool allocation of {nbytes}B failed")
        buf = (ctypes.c_char * nbytes).from_address(ptr)
        flat = np.frombuffer(buf, dtype=dtype)
        arr = flat.reshape(shape)
        alive = self._alive
        lock = self._lock
        ptrs = self._ptrs

        def _finalize(addr=ptr):
            # auto-free when the last view of the allocation is GC'd
            with lock:
                entry = ptrs.pop(addr, None)
            if entry is not None and alive["pool"]:
                alive["lib"].rt_pool_dealloc(alive["pool"], addr)

        # The finalizer must hang off the frombuffer base: every view of
        # `arr` keeps `flat` alive through .base, whereas `arr` itself
        # (a reshape view) can be collected while views of the memory live.
        fin = weakref.finalize(flat, _finalize)
        fin.atexit = False
        with self._lock:
            self._ptrs[ptr] = (ptr, fin)
        return arr

    def release(self, arr: np.ndarray) -> None:
        """Free an array returned by allocate(). Views/copies are rejected
        loudly rather than silently leaking."""
        if arr.size == 0:
            return
        addr = arr.__array_interface__["data"][0]
        with self._lock:
            entry = self._ptrs.pop(addr, None)
        if entry is None:
            raise ValueError(
                "release() got an array this pool did not allocate (or a "
                "view offset from the allocation base)")
        ptr, fin = entry
        fin.detach()
        self._lib.rt_pool_dealloc(self._pool, ptr)

    def stats(self) -> dict:
        out = (ctypes.c_int64 * 4)()
        self._lib.rt_pool_stats(self._pool, out)
        return {"bytes_allocated": out[0], "peak_bytes": out[1],
                "n_allocations": out[2], "n_deallocations": out[3]}

    def set_notify(self, fn) -> None:
        """Observer hook: fn(is_alloc: bool, nbytes: int)
        (ref: mr/notifying_adaptor.hpp)."""
        if fn is None:
            with self._lock:
                self._cb = None
            self._lib.rt_pool_set_notify(self._pool, None, None)
            return
        cb_t = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_int64,
                                ctypes.c_void_p)
        cb = cb_t(lambda is_alloc, nbytes, _:
                  fn(bool(is_alloc), int(nbytes)))
        with self._lock:
            self._cb = cb      # keep the ctypes thunk alive on self
        self._lib.rt_pool_set_notify(
            self._pool, ctypes.cast(cb, ctypes.c_void_p), None)

    def close(self) -> None:
        if getattr(self, "_pool", None):
            with self._lock:
                for _, fin in self._ptrs.values():
                    fin.detach()   # pool destroy frees everything at once
                self._ptrs.clear()
                self._alive["pool"] = None
                pool, self._pool = self._pool, None
            self._lib.rt_pool_destroy(pool)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeResourceMonitor:
    """Background sampler writing pool stats to CSV, rows tagged with the
    active range (ref: mr/resource_monitor.hpp:29-66)."""

    def __init__(self, pool: TrackedHostPool, csv_path: str,
                 interval_ms: int = 50):
        self._lib = _native.get_lib()
        # hold the pool: the sampler thread reads its native state, so the
        # pool must outlive the monitor even if the caller drops it
        self._pool_ref = pool
        self._mon = self._lib.rt_monitor_start(
            pool._pool, csv_path.encode(), interval_ms)
        if not self._mon:
            raise RuntimeError(f"cannot open {csv_path}")

    def set_tag(self, tag: str) -> None:
        self._lib.rt_monitor_set_tag(self._mon, tag.encode())

    def stop(self) -> None:
        if self._mon:
            self._lib.rt_monitor_stop(self._mon)
            self._mon = None
            self._pool_ref = None


def npy_save(path: str, arr: np.ndarray) -> None:
    """Native .npy writer (ref: serialize_mdspan, core/serialize.hpp:26)."""
    lib = _native.get_lib()
    arr = np.ascontiguousarray(arr)
    descr = _DESCR.get(arr.dtype)
    if lib is None or descr is None:
        # fallback via a file object so np.save cannot append ".npy" and
        # diverge from the native writer's exact-path behavior
        with open(path, "wb") as f:
            np.save(f, arr, allow_pickle=False)
        return
    shape = (ctypes.c_int64 * max(arr.ndim, 1))(*arr.shape)
    rc = lib.rt_npy_write(path.encode(), descr.encode(), shape, arr.ndim,
                          arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes)
    if rc != 0:
        raise IOError(f"native npy write failed with code {rc}")


def npy_load(path: str) -> np.ndarray:
    """Native .npy reader (ref: deserialize_mdspan)."""
    lib = _native.get_lib()
    if lib is None:
        return np.load(path, allow_pickle=False)
    descr = ctypes.create_string_buffer(16)
    shape = (ctypes.c_int64 * 32)()
    ndim = ctypes.c_int(0)
    fortran = ctypes.c_int(0)
    off = lib.rt_npy_read_header(path.encode(), descr, shape,
                                 ctypes.byref(ndim), ctypes.byref(fortran))
    if off < 0:
        raise IOError(f"native npy header parse failed with code {off}")
    dtype = _DESCR_INV.get(descr.value.decode())
    if dtype is None:   # exotic dtype: punt to numpy
        return np.load(path, allow_pickle=False)
    shp = tuple(shape[i] for i in range(ndim.value))
    out = np.empty(shp, dtype)
    rc = lib.rt_npy_read_data(path.encode(), off,
                              out.ctypes.data_as(ctypes.c_void_p),
                              out.nbytes)
    if rc != 0:
        raise IOError(f"native npy read failed with code {rc}")
    if fortran.value:
        # bytes on disk are column-major: reinterpret, preserving shape
        out = out.reshape(shp[::-1]).T
    return out


class NativeThreadPool:
    """Host worker pool for IO/copy jobs — the host-side analogue of the
    handle's stream pool (core/resource/cuda_stream_pool.hpp)."""

    def __init__(self, n_threads: int = 0):
        self._lib = _native.get_lib()
        if self._lib is None:
            raise RuntimeError(
                f"native runtime unavailable: {_native.build_error()}")
        self._tp = self._lib.rt_threadpool_create(n_threads)

    def parallel_copy(self, dst: np.ndarray, src: np.ndarray,
                      chunk_bytes: int = 8 << 20) -> None:
        if dst.nbytes != src.nbytes:
            raise ValueError("size mismatch")
        if not dst.flags["C_CONTIGUOUS"]:
            raise ValueError(
                "dst must be C-contiguous: the native memcpy writes a flat "
                "byte range and would corrupt a strided view's base buffer")
        self._lib.rt_threadpool_memcpy(
            self._tp, dst.ctypes.data_as(ctypes.c_void_p),
            np.ascontiguousarray(src).ctypes.data_as(ctypes.c_void_p),
            dst.nbytes, chunk_bytes)

    def close(self) -> None:
        if getattr(self, "_tp", None):
            self._lib.rt_threadpool_destroy(self._tp)
            self._tp = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def native_cancel(thread_id: Optional[int] = None) -> None:
    """Native token registry mirror of core.interruptible
    (ref: core/interruptible.hpp:97 `cancel`). Falls back to the Python
    token registry without a toolchain."""
    lib = _native.get_lib()
    tid = thread_id if thread_id is not None else threading.get_ident()
    if lib is None:
        from raft_tpu.core import interruptible
        interruptible.cancel(tid)
        return
    lib.rt_interruptible_cancel(tid)


def native_check_cancelled(thread_id: Optional[int] = None) -> bool:
    """Flag-consuming check (ref: interruptible `yield_no_throw`). Falls
    back to the Python token registry without a toolchain."""
    lib = _native.get_lib()
    tid = thread_id if thread_id is not None else threading.get_ident()
    if lib is None:
        from raft_tpu.core import interruptible
        token = interruptible.get_token(tid)
        cancelled = token.cancelled()
        if cancelled:
            # consume the flag, mirroring the native check's semantics
            with contextlib.suppress(interruptible.InterruptedException):
                token.check()
        return cancelled
    return bool(lib.rt_interruptible_check(tid))
