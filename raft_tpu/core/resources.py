"""Resources handle: a type-indexed, lazily-constructed resource registry.

TPU-native re-design of the reference's ``raft::resources`` container
(reference cpp/include/raft/core/resources.hpp:39,47-56,103-123) and
``raft::device_resources`` handle (core/device_resources.hpp:53,78-92).

Where the reference's handle holds CUDA streams and cuBLAS/cuSOLVER/cuSPARSE
handles, the TPU handle holds the things an XLA program needs threaded through
it: the target :class:`jax.Device`, a `jax.sharding.Mesh` for multi-chip work,
a counter-based PRNG state, a communicator (``raft_tpu.comms``), sub-comms,
and host-side services (logger, allocation trackers, workspace limits).

Resources are registered as *factories* and constructed lazily, under a lock,
on first access — exactly the reference's scheme (resources.hpp:103-123).
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Callable, Dict, Optional

import jax


class ResourceType(enum.Enum):
    """Vocabulary of resource slots.

    Mirrors the reference's ``resource_type`` enum
    (core/resource/resource_types.hpp:20-46) with CUDA-specific slots
    (CUBLAS_HANDLE, CUDA_STREAM_VIEW, ...) replaced by their TPU-native
    equivalents (DEVICE, MESH, ...).  Slots that have no TPU analogue
    (e.g. per-vendor library handles) are intentionally absent: XLA owns
    the compiled-kernel plumbing.
    """

    DEVICE = "device"                       # jax.Device           (ref: DEVICE_ID)
    MESH = "mesh"                           # jax.sharding.Mesh    (ref: stream pool / SNMG clique)
    PRNG = "prng"                           # RngState             (ref: none; curand was per-call)
    COMMS = "comms"                         # comms_t              (ref: COMMUNICATOR)
    SUB_COMMS = "sub_comms"                 # dict key->comms_t    (ref: SUB_COMMUNICATOR)
    WORKSPACE = "workspace"                 # workspace byte limit (ref: WORKSPACE_RESOURCE)
    LARGE_WORKSPACE = "large_workspace"     # (ref: LARGE_WORKSPACE_RESOURCE)
    MEMORY_STATS = "memory_stats"           # allocation statistics adaptor
    LOGGER = "logger"                       # per-handle logger
    CANCEL_TOKEN = "cancel_token"           # interruptible token  (ref: core/interruptible.hpp)
    MULTI_DEVICE = "multi_device"           # list[Resources], one per local device (ref: multi_gpu.hpp)
    DONATION = "donation"                   # buffer-donation policy knobs


class ResourceFactory:
    """Factory that constructs a resource on first access.

    Reference: ``resource_factory`` virtual pair
    (core/resource/resource_types.hpp:54-88).
    """

    def __init__(self, key: ResourceType, fn: Callable[[], Any]):
        self.key = key
        self.fn = fn

    def make_resource(self) -> Any:
        return self.fn()


class Resources:
    """Lazily-constructed, thread-safe resource registry.

    Shallow-copyable: copies share the registered factories and already
    constructed resources, like the reference's copy semantics
    (core/resources.hpp:47-56).
    """

    def __init__(self, other: Optional["Resources"] = None):
        if other is not None:
            # Shallow copy: share factory and resource tables (+lock).
            self._lock = other._lock
            self._factories = other._factories
            self._resources = other._resources
        else:
            self._lock = threading.RLock()
            self._factories: Dict[ResourceType, ResourceFactory] = {}
            self._resources: Dict[ResourceType, Any] = {}

    # -- registry protocol (ref: resources.hpp:75-123) ------------------------

    def add_resource_factory(self, factory: ResourceFactory) -> None:
        with self._lock:
            self._factories[factory.key] = factory
            # A new factory invalidates a previously-constructed resource.
            self._resources.pop(factory.key, None)

    def has_resource_factory(self, key: ResourceType) -> bool:
        with self._lock:
            return key in self._factories

    def get_resource(self, key: ResourceType) -> Any:
        with self._lock:
            if key not in self._resources:
                if key not in self._factories:
                    raise KeyError(
                        f"no resource factory registered for {key!r}; "
                        f"register one with add_resource_factory()"
                    )
                self._resources[key] = self._factories[key].make_resource()
            return self._resources[key]

    def set_resource(self, key: ResourceType, value: Any) -> None:
        """Directly install a constructed resource (factory-less)."""
        with self._lock:
            self._factories[key] = ResourceFactory(key, lambda: value)
            self._resources[key] = value


# ---------------------------------------------------------------------------
# Accessors — one per resource, registering a default factory on demand,
# mirroring the reference's per-resource headers (core/resource/*.hpp).
# ---------------------------------------------------------------------------


def get_device(res: Resources) -> jax.Device:
    """Target device (ref: core/resource/device_id.hpp)."""
    if not res.has_resource_factory(ResourceType.DEVICE):
        res.set_resource(ResourceType.DEVICE, jax.devices()[0])
    return res.get_resource(ResourceType.DEVICE)


def set_device(res: Resources, device: jax.Device) -> None:
    res.set_resource(ResourceType.DEVICE, device)


def get_mesh(res: Resources):
    """Device mesh for multi-chip execution.

    The TPU analogue of both the stream pool and the SNMG clique: a named-axis
    `jax.sharding.Mesh`.  Defaults to a 1-axis mesh over all local devices.
    """
    if not res.has_resource_factory(ResourceType.MESH):
        def _make():
            import numpy as np
            from jax.sharding import Mesh

            devs = np.asarray(jax.devices())
            return Mesh(devs, axis_names=("data",))

        res.add_resource_factory(ResourceFactory(ResourceType.MESH, _make))
    return res.get_resource(ResourceType.MESH)


def set_mesh(res: Resources, mesh) -> None:
    res.set_resource(ResourceType.MESH, mesh)


def get_rng_state(res: Resources):
    """Per-handle PRNG state (lazily seeded to 0)."""
    if not res.has_resource_factory(ResourceType.PRNG):
        def _make():
            from raft_tpu.random.rng_state import RngState

            return RngState(seed=0)

        res.add_resource_factory(ResourceFactory(ResourceType.PRNG, _make))
    return res.get_resource(ResourceType.PRNG)


def set_rng_state(res: Resources, state) -> None:
    res.set_resource(ResourceType.PRNG, state)


def get_comms(res: Resources):
    """Communicator injected into the handle (ref: core/resource/comms.hpp).

    Raises if none was set — same contract as the reference, where algorithms
    require ``build_comms_*`` / ``initialize_mpi_comms`` to have run first.
    """
    if not res.has_resource_factory(ResourceType.COMMS):
        raise RuntimeError(
            "no communicator set on this handle; call "
            "raft_tpu.comms.build_mesh_comms(res, mesh) first"
        )
    return res.get_resource(ResourceType.COMMS)


def set_comms(res: Resources, comms) -> None:
    res.set_resource(ResourceType.COMMS, comms)


def comms_initialized(res: Resources) -> bool:
    return res.has_resource_factory(ResourceType.COMMS)


def get_subcomm(res: Resources, key: str):
    """Keyed sub-communicator (ref: core/resource/sub_comms.hpp)."""
    if not res.has_resource_factory(ResourceType.SUB_COMMS):
        res.set_resource(ResourceType.SUB_COMMS, {})
    table = res.get_resource(ResourceType.SUB_COMMS)
    if key not in table:
        raise KeyError(f"no sub-communicator registered under key {key!r}")
    return table[key]


def set_subcomm(res: Resources, key: str, comms) -> None:
    if not res.has_resource_factory(ResourceType.SUB_COMMS):
        res.set_resource(ResourceType.SUB_COMMS, {})
    res.get_resource(ResourceType.SUB_COMMS)[key] = comms


def get_workspace_limit(res: Resources) -> int:
    """Soft byte cap primitives use when sizing scratch buffers.

    The reference bounds a dedicated workspace memory resource
    (core/resource/device_memory_resource.hpp); under XLA the compiler owns
    allocation, so this is a *policy* value primitives consult when choosing
    tile/batch sizes for memory-hungry paths.
    """
    if not res.has_resource_factory(ResourceType.WORKSPACE):
        res.set_resource(ResourceType.WORKSPACE, 1 << 30)  # 1 GiB default
    return res.get_resource(ResourceType.WORKSPACE)


def set_workspace_limit(res: Resources, nbytes: int) -> None:
    res.set_resource(ResourceType.WORKSPACE, int(nbytes))


def get_memory_stats(res: Resources):
    """Allocation statistics tracker (ref: mr/statistics_adaptor.hpp:25,66)."""
    if not res.has_resource_factory(ResourceType.MEMORY_STATS):
        from raft_tpu.core.memory import StatisticsTracker

        res.set_resource(ResourceType.MEMORY_STATS, StatisticsTracker())
    return res.get_resource(ResourceType.MEMORY_STATS)


def get_cancel_token(res: Resources):
    """Cooperative-cancellation token (ref: core/interruptible.hpp:63)."""
    if not res.has_resource_factory(ResourceType.CANCEL_TOKEN):
        from raft_tpu.core.interruptible import CancelToken

        res.set_resource(ResourceType.CANCEL_TOKEN, CancelToken())
    return res.get_resource(ResourceType.CANCEL_TOKEN)


def sync(res: Resources, *arrays) -> None:
    """Block until enqueued device work completes.

    The analogue of ``resource::sync_stream`` → ``interruptible::synchronize``
    (core/interruptible.hpp:75-92): JAX dispatch is async; this blocks on the
    given arrays (or does a global barrier if none given), polling the
    handle's cancel token.
    """
    token = get_cancel_token(res)
    token.check()
    if arrays:
        for a in arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()
    else:
        jax.effects_barrier()
    token.check()


# ---------------------------------------------------------------------------
# device_resources — the user-facing handle (ref: core/device_resources.hpp:53)
# ---------------------------------------------------------------------------


class DeviceResources(Resources):
    """The "handle": Resources pre-loaded with device / mesh / PRNG factories.

    Reference: ``raft::device_resources`` registers device_id, stream and
    stream-pool factories in its constructor (device_resources.hpp:78-92);
    here we pre-register the device, the default mesh and the PRNG seed.
    """

    def __init__(self, device: Optional[jax.Device] = None, mesh=None,
                 seed: int = 0, other: Optional[Resources] = None):
        super().__init__(other)
        if other is None:
            if device is not None:
                set_device(self, device)
            if mesh is not None:
                set_mesh(self, mesh)
            from raft_tpu.random.rng_state import RngState

            set_rng_state(self, RngState(seed=seed))

    # Convenience getters, mirroring device_resources.hpp:97-110.
    @property
    def device(self) -> jax.Device:
        return get_device(self)

    @property
    def mesh(self):
        return get_mesh(self)

    def get_comms(self):
        return get_comms(self)

    def sync_stream(self, *arrays) -> None:
        sync(self, *arrays)


def device_resources(device: Optional[jax.Device] = None, mesh=None,
                     seed: int = 0) -> DeviceResources:
    """Create a handle. ``raft::device_resources handle;`` equivalent."""
    return DeviceResources(device=device, mesh=mesh, seed=seed)


# Deprecated alias kept for API parity with the reference's handle_t
# (core/handle.hpp:23).
Handle = DeviceResources


class DeviceResourcesManager:
    """Process-global pool of handles, one per (device, thread) pair.

    Reference: ``device_resources_manager``
    (core/device_resources_manager.hpp:73,99,125-183): lazily builds and
    caches a handle per device so repeated calls are cheap, with settable
    defaults applied to newly built handles.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._handles: Dict[Any, DeviceResources] = {}
        self._default_seed = 0
        self._default_workspace = 1 << 30
        self._default_mesh = None

    def set_seed(self, seed: int) -> None:
        with self._lock:
            self._default_seed = seed

    def set_workspace_limit(self, nbytes: int) -> None:
        with self._lock:
            self._default_workspace = int(nbytes)

    def set_mesh(self, mesh) -> None:
        with self._lock:
            self._default_mesh = mesh

    def get_device_resources(self, device: Optional[jax.Device] = None
                             ) -> DeviceResources:
        device = device if device is not None else jax.devices()[0]
        key = (device, threading.get_ident())
        with self._lock:
            if key not in self._handles:
                h = DeviceResources(device=device, mesh=self._default_mesh,
                                    seed=self._default_seed)
                set_workspace_limit(h, self._default_workspace)
                self._handles[key] = h
            return self._handles[key]


_manager = DeviceResourcesManager()


def get_device_resources(device: Optional[jax.Device] = None) -> DeviceResources:
    """Process-global cached handle (device_resources_manager.hpp:99)."""
    return _manager.get_device_resources(device)


def default_resources(res: Optional[Resources] = None) -> Resources:
    """Return ``res`` or the process-global default handle.

    Primitives take an optional handle first argument; ``None`` means "use
    the global default" (the reference forces explicit handles, but JAX's
    functional style makes the implicit default the common case).
    """
    return res if res is not None else get_device_resources()


class DeviceResourcesSNMG(DeviceResources):
    """Single-process multi-device handle: a root rank plus one child
    handle per device, with rank-loop helpers.

    Reference: ``device_resources_snmg`` (core/device_resources_snmg.hpp:36,
    44,91-144) keeps a `raft::resources` per GPU and switches the current
    device while looping ranks; the TPU analogue keeps one child handle per
    mesh device — device switching is replaced by the mesh axis, and
    ``set_memory_pool`` (per-device RMM pools) by XLA's own allocator, so
    it is accepted and ignored.
    """

    def __init__(self, devices=None, seed: int = 0,
                 axis_name: str = "data"):
        devs = list(devices) if devices is not None else list(jax.devices())
        if not devs:
            raise ValueError("no devices for SNMG handle")
        import numpy as _np
        from jax.sharding import Mesh as _Mesh

        mesh = _Mesh(_np.asarray(devs), axis_names=(axis_name,))
        super().__init__(device=devs[0], mesh=mesh, seed=seed)
        self._axis_name = axis_name
        self._children = [
            DeviceResources(device=d, mesh=mesh, seed=seed + i)
            for i, d in enumerate(devs)
        ]
        from raft_tpu.comms.bootstrap import inject_comms_on_handle

        shared = None
        mailbox = None
        for rank, child in enumerate(self._children):
            view = inject_comms_on_handle(child, mesh, axis_name, rank,
                                          _shared=shared, _mailbox=mailbox)
            shared = view._shared
            mailbox = view._mailbox
        set_comms(self, get_comms(self._children[0]))

    @property
    def n_ranks(self) -> int:
        return len(self._children)

    def rank_resources(self, rank: int) -> DeviceResources:
        """Child handle for one rank (ref: the per-GPU resources vector,
        device_resources_snmg.hpp:44 + multi_gpu.hpp:66-112)."""
        return self._children[rank]

    def __iter__(self):
        return iter(self._children)

    def set_memory_pool(self, percent_of_free: int) -> None:
        """Accepted for parity (ref: device_resources_snmg.hpp:127-144);
        XLA owns device memory on TPU, so this is a no-op."""
