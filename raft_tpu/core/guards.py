"""Numerical guardrails: typed failure taxonomy, in-graph sentinels, and
precision-escalation recovery (the numeric mirror of ``comms/errors.py`` +
``comms/resilience.py``; ref: core/error.hpp ``RAFT_EXPECTS``/``status_t``
and the cuSOLVER ``info`` out-parameter contract).

The reference fails loudly: every entry point validates through
RAFT_EXPECTS and every cuSOLVER factorization returns an ``info`` code the
wrappers check. Our compute path inherited neither — a non-PSD Cholesky
update produced silent NaN, an unconverged Lanczos solve produced a
``logger.warn``. This module gives the numeric layer the same discipline
the comms layer got: a typed taxonomy, cheap in-graph sentinels at output
boundaries, and a recovery choreography that re-runs a failing step one
tier up the precision ladder (``util/numerics.py``).

Taxonomy (every type a ``RuntimeError`` so pre-taxonomy ``except
RuntimeError`` callers keep working):

==========================  =============================================
type                        meaning / reference analogue
==========================  =============================================
``NumericalError``          base of the numeric taxonomy
``NonFiniteError``          NaN/Inf crossed an output (or entered an
                            input) boundary — cuSOLVER ``info > 0`` class
``IllConditionedError``     a factorization breakdown attributable to
                            conditioning (negative Cholesky pivot, zero
                            norm) — ``potrf`` ``info > 0``
``ConvergenceError``        an iterative solver exhausted its budget;
                            carries a :class:`ConvergenceReport`
                            (``syevj``/``gesvdj`` ``info = n+1`` class)
``ArtifactCorruptError``    a persisted compiled artifact failed its
                            integrity check (truncation, bit rot)
==========================  =============================================

Guard modes (env ``RAFT_TPU_GUARD_MODE``, :func:`set_guard_mode`,
:func:`guard_scope`, or a per-call ``guard_mode=`` override):

``off``      hot path pays nothing; outputs bit-identical to the
             unguarded library (NaN propagates, as today).
``check``    cheap sentinels at output-transfer boundaries — one fused
             ``isfinite(...).all()`` reduction folded into work the op
             already does, fetched as a single scalar; failures raise
             typed errors.
``recover``  ``check`` + on a non-finite output or factorization
             breakdown, the failing step is re-run one tier up the
             precision ladder (bf16 → f32 → f64-emulated-on-host),
             logging a ``guards.escalate`` trace event; the error is
             raised only if the top of the ladder still fails.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.core import env as _env_mod
from raft_tpu.core import logger, trace
from raft_tpu import obs

__all__ = [
    "NumericalError", "NonFiniteError", "IllConditionedError",
    "ConvergenceError", "ArtifactCorruptError", "ConvergenceReport",
    "guard_mode", "set_guard_mode", "guard_scope", "resolve_guard_mode",
    "finite_sentinel", "check_finite", "guard_output",
]

GUARD_MODES = ("off", "check", "recover")


# ---------------------------------------------------------------------------
# taxonomy
# ---------------------------------------------------------------------------

class NumericalError(RuntimeError):
    """Base numeric failure (the ``RAFT_FAIL`` of the solver layer).

    Parameters
    ----------
    message : human-readable description (always names the operation).
    op : dotted name of the operation that observed the failure, when
        known (e.g. ``"linalg.cholesky_r1_update"``).
    """

    def __init__(self, message: str, *, op: Optional[str] = None):
        super().__init__(message)
        self.op = op


class NonFiniteError(NumericalError):
    """NaN or Inf crossed a guarded boundary.

    ``stage`` attributes the failure: ``"input"`` means the caller handed
    the op poisoned data (garbage-in — escalation cannot help and is not
    attempted); ``"output"`` means the op manufactured the non-finite
    values from finite inputs (overflow/cancellation — the escalation
    ladder's case)."""

    def __init__(self, message: str, *, op: Optional[str] = None,
                 stage: str = "output"):
        super().__init__(message, op=op)
        self.stage = stage


class IllConditionedError(NumericalError):
    """A direct factorization broke down in a way attributable to the
    conditioning of the input (negative Cholesky pivot on a non-PSD
    update, zero starting vector) — the ``potrf info > 0`` class."""


@dataclasses.dataclass
class ConvergenceReport:
    """Uniform iterative-solver outcome (the typed replacement for the
    scattered ``logger.warn`` + positional ``n_iter`` returns).

    residual is the solver's own convergence measure: max Ritz residual
    for Lanczos, relative inertia change for k-means, off-diagonal
    Frobenius ratio for Jacobi sweeps, unassigned-lane count for LAP.
    ``escalated`` marks a result produced by precision-escalation
    recovery; ``breakdowns`` counts classified breakdown events the
    solver recovered from internally (Lanczos β≈0 restarts)."""

    converged: bool
    n_iter: int
    residual: float
    tol: float
    escalated: bool = False
    breakdowns: int = 0
    detail: str = ""


class ConvergenceError(NumericalError):
    """An iterative solver exhausted its budget under ``strict=True``.

    Carries the full :class:`ConvergenceReport` as ``.report`` — the
    caller that catches it still gets the diagnostic the warn-and-return
    contract used to bury in the log."""

    def __init__(self, message: str, *,
                 report: Optional[ConvergenceReport] = None,
                 op: Optional[str] = None):
        super().__init__(message, op=op)
        self.report = report


class ArtifactCorruptError(RuntimeError):
    """A persisted compiled artifact failed its integrity check (sha256
    mismatch, truncation, or a deserialize failure). ``.path`` names the
    artifact on disk."""

    def __init__(self, message: str, *, path: Optional[str] = None):
        super().__init__(message)
        self.path = path


# ---------------------------------------------------------------------------
# guard-mode knob
# ---------------------------------------------------------------------------

_mode = _env_mod.read("RAFT_TPU_GUARD_MODE")
_tls = threading.local()


def _scope_stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


def guard_mode() -> str:
    """The effective guard mode: innermost :func:`guard_scope` override
    if any, else the process-wide setting."""
    st = _scope_stack()
    return st[-1] if st else _mode


def set_guard_mode(mode: str) -> None:
    """Set the process-wide guard mode ('off' | 'check' | 'recover')."""
    global _mode
    mode = str(mode).lower()
    if mode not in GUARD_MODES:
        raise ValueError(
            f"unknown guard mode {mode!r}; want one of {GUARD_MODES}")
    _mode = mode


@contextlib.contextmanager
def guard_scope(mode: str):
    """Thread-local guard-mode override for a region (the per-call
    analogue of a ``RAFT_EXPECTS``-compiled-out build)."""
    mode = str(mode).lower()
    if mode not in GUARD_MODES:
        raise ValueError(
            f"unknown guard mode {mode!r}; want one of {GUARD_MODES}")
    _scope_stack().append(mode)
    try:
        yield
    finally:
        _scope_stack().pop()


def resolve_guard_mode(override: Optional[str] = None) -> str:
    """Per-call override resolution: an explicit ``guard_mode=`` argument
    wins; None defers to :func:`guard_mode`."""
    if override is None:
        return guard_mode()
    override = str(override).lower()
    if override not in GUARD_MODES:
        raise ValueError(
            f"unknown guard mode {override!r}; want one of {GUARD_MODES}")
    return override


# ---------------------------------------------------------------------------
# in-graph sentinels
# ---------------------------------------------------------------------------

@jax.jit
def _all_finite(a) -> jnp.ndarray:
    # jitted so the isfinite map and the all() reduce fuse into a single
    # pass with no materialized boolean intermediate
    return jnp.isfinite(a).all()


def finite_sentinel(*arrays) -> jnp.ndarray:
    """One fused all-finite reduction over the given arrays.

    Stays IN the graph — a scalar ``jnp.isfinite(...).all()`` folded into
    the op's existing output transfer, not a separate device pass; the
    host fetches one bool alongside data it was fetching anyway. Integer
    and bool arrays are finite by construction and contribute nothing."""
    ok = jnp.asarray(True)
    for a in arrays:
        a = jnp.asarray(a)
        if jnp.issubdtype(a.dtype, jnp.inexact):
            ok = ok & _all_finite(a)
    return ok


def _has_tracer(arrays) -> bool:
    return any(isinstance(a, jax.core.Tracer) for a in arrays)


def check_finite(op: str, *arrays, mode: Optional[str] = None,
                 stage: str = "input") -> None:
    """Host-side finite check at a guarded boundary.

    No-op under ``off`` or inside a jit trace (abstract values carry no
    data; guarded entry points are host-driven). Raises
    :class:`NonFiniteError` naming ``op`` otherwise."""
    mode = resolve_guard_mode(mode)
    if mode == "off" or _has_tracer(arrays):
        return
    if not bool(finite_sentinel(*arrays)):
        obs.inc("guards_sentinel_trips_total", 1, op=op, stage=stage)
        exc = NonFiniteError(
            f"{op}: non-finite values detected at the {stage} boundary "
            f"(guard_mode={mode!r}; run with guard_mode='off' to restore "
            "silent NaN propagation)", op=op, stage=stage)
        obs.record_failure(exc)
        raise exc


def guard_output(op: str, out, *, inputs=(), recover=None,
                 mode: Optional[str] = None):
    """The sentinel choreography at an output-transfer boundary.

    Under ``off`` (or inside a jit trace) returns ``out`` untouched —
    bit-identical, zero added work. Under ``check``/``recover`` fetches
    the fused finite sentinel; on failure it first attributes the fault
    (poisoned ``inputs`` raise ``stage='input'`` — escalation cannot fix
    garbage-in), then, in ``recover`` mode with a ``recover`` thunk, logs
    a ``guards.escalate`` trace event and returns the re-run's output if
    the retry is finite. Raises :class:`NonFiniteError` otherwise."""
    mode = resolve_guard_mode(mode)
    if mode == "off":
        return out
    leaves = [x for x in jax.tree_util.tree_leaves(out)
              if hasattr(x, "dtype")]
    if _has_tracer(leaves):
        return out
    if bool(finite_sentinel(*leaves)):
        return out
    in_leaves = [x for x in jax.tree_util.tree_leaves(tuple(inputs))
                 if hasattr(x, "dtype")]
    if in_leaves and not _has_tracer(in_leaves) \
            and not bool(finite_sentinel(*in_leaves)):
        obs.inc("guards_sentinel_trips_total", 1, op=op, stage="input")
        exc = NonFiniteError(
            f"{op}: non-finite values in the INPUT operands "
            f"(guard_mode={mode!r}) — the output is poisoned by "
            "garbage-in; precision escalation is not attempted",
            op=op, stage="input")
        obs.record_failure(exc)
        raise exc
    obs.inc("guards_sentinel_trips_total", 1, op=op, stage="output")
    if mode == "recover" and recover is not None:
        trace.record_event("guards.escalate", op=op)
        obs.inc("guards_escalations_total", 1, op=op)
        logger.warn(
            "%s: non-finite output with finite inputs; re-running one "
            "tier up the precision ladder (guard_mode='recover')", op)
        out2 = recover()
        leaves2 = [x for x in jax.tree_util.tree_leaves(out2)
                   if hasattr(x, "dtype")]
        if not _has_tracer(leaves2) and bool(finite_sentinel(*leaves2)):
            return out2
        exc = NonFiniteError(
            f"{op}: output still non-finite after precision escalation "
            "(top of the ladder reached)", op=op, stage="output")
        obs.record_failure(exc)
        raise exc
    exc = NonFiniteError(
        f"{op}: non-finite values in the output (guard_mode={mode!r}; "
        "inputs were finite — likely overflow or catastrophic "
        "cancellation; guard_mode='recover' re-runs at higher precision)",
        op=op, stage="output")
    obs.record_failure(exc)
    raise exc
