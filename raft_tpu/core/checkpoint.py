"""Versioned, CRC-checked solver checkpoints (ISSUE 2 tentpole part 3).

The elastic-execution layer (comms abort → survivor consensus → shrink)
only pays off if the surviving ranks have solver state to resume from;
this module is that state's on-disk container.  It deliberately builds
on :mod:`raft_tpu.core.serialize` — each entry's payload is the same
``.npy`` wire format the mdspan serializer writes, so checkpoints
interoperate with NumPy tooling and with the reference's serialized
artifacts — and adds what a crash-safe container needs on top:

* a magic + format-version header (``RAFTCKP1``), so stale readers fail
  loudly instead of misparsing;
* named, typed entries (array / scalar / RngState), each with its own
  CRC32 — a torn or bit-flipped entry is *detected*, raising
  :class:`CheckpointCorruptError` rather than feeding garbage back into
  a solver;
* atomic writes: serialize to ``<path>.tmp`` then ``os.replace`` — a
  rank SIGKILL'd mid-save leaves the previous checkpoint intact, never
  a half-written one (the property the elastic kmeans/eigsh recovery
  path depends on);
* :class:`CheckpointManager` — step-indexed files with retention, whose
  ``latest()`` survivors consult after a shrink.

Binary layout (little-endian throughout)::

    magic    8s   b"RAFTCKP1"
    version  u32  (currently 1)
    n        u32  entry count
    entry*n:
      name_len u16, name utf-8
      kind     u8   (0 = array, 1 = scalar, 2 = rng state)
      nbytes   u64
      payload  nbytes   (serialize.dumps .npy bytes)
      crc32    u32      (of payload)

The format is frozen by a committed fixture
(``tests/data/checkpoint_v1.ckpt``) checked in ci/smoke.sh — changes
must bump the version, not mutate v1.
"""

from __future__ import annotations

import io
import os
import re
import struct
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from raft_tpu import obs
from raft_tpu.core import logger, serialize, trace
from raft_tpu.random.rng_state import GeneratorType, RngState

_log = logger.child("checkpoint")

MAGIC = b"RAFTCKP1"
VERSION = 1

_KIND_ARRAY = 0
_KIND_SCALAR = 1
_KIND_RNGSTATE = 2

_HEADER = struct.Struct("<8sII")          # magic, version, n_entries
_ENTRY_HEAD = struct.Struct("<H")         # name length
_ENTRY_META = struct.Struct("<BQ")        # kind, payload nbytes
_ENTRY_CRC = struct.Struct("<I")          # crc32 of payload


class CheckpointError(RuntimeError):
    """Base error for checkpoint reading/writing."""


class CheckpointCorruptError(CheckpointError):
    """The container is structurally damaged (bad magic, truncation, or
    a CRC mismatch on an entry)."""


class CheckpointVersionError(CheckpointError):
    """The container is a different format version than this reader."""


def _encode_value(value: Any) -> Tuple[int, bytes]:
    if isinstance(value, RngState):
        triple = np.asarray(
            [value.seed, value.base_subsequence,
             0 if value.type == GeneratorType.THREEFRY else 1], np.int64)
        return _KIND_RNGSTATE, serialize.dumps(triple)
    if isinstance(value, (bool, int, float, complex, np.generic)):
        buf = io.BytesIO()
        serialize.serialize_scalar(None, buf, value)
        return _KIND_SCALAR, buf.getvalue()
    return _KIND_ARRAY, serialize.dumps(value)


def _decode_value(kind: int, payload: bytes) -> Any:
    if kind == _KIND_RNGSTATE:
        triple = serialize.loads(payload, to_device=False)
        return RngState(
            seed=int(triple[0]), base_subsequence=int(triple[1]),
            type=(GeneratorType.THREEFRY if int(triple[2]) == 0
                  else GeneratorType.RBG))
    if kind == _KIND_SCALAR:
        return serialize.deserialize_scalar(None, io.BytesIO(payload))
    if kind == _KIND_ARRAY:
        return serialize.loads(payload, to_device=False)
    raise CheckpointCorruptError(f"unknown entry kind {kind}")


def dump_checkpoint(entries: Dict[str, Any], stream) -> None:
    """Serialize ``entries`` (name → array | scalar | RngState) into
    ``stream`` in the v1 container layout."""
    stream.write(_HEADER.pack(MAGIC, VERSION, len(entries)))
    for name, value in entries.items():
        raw_name = name.encode("utf-8")
        if len(raw_name) > 0xFFFF:
            raise ValueError(f"entry name too long: {name[:40]!r}…")
        kind, payload = _encode_value(value)
        stream.write(_ENTRY_HEAD.pack(len(raw_name)))
        stream.write(raw_name)
        stream.write(_ENTRY_META.pack(kind, len(payload)))
        stream.write(payload)
        stream.write(_ENTRY_CRC.pack(zlib.crc32(payload)))


def _read_exact(stream, n: int) -> bytes:
    buf = stream.read(n)
    if len(buf) != n:
        raise CheckpointCorruptError(
            f"truncated checkpoint: wanted {n} bytes, got {len(buf)}")
    return buf


def load_checkpoint(stream) -> Dict[str, Any]:
    """Parse a v1 container; every entry's CRC is verified before its
    payload is decoded."""
    magic, version, n = _HEADER.unpack(_read_exact(stream, _HEADER.size))
    if magic != MAGIC:
        raise CheckpointCorruptError(
            f"bad magic {magic!r} (want {MAGIC!r}) — not a raft_tpu "
            "checkpoint")
    if version != VERSION:
        raise CheckpointVersionError(
            f"checkpoint format v{version}, this reader is v{VERSION}")
    out: Dict[str, Any] = {}
    for _ in range(n):
        (name_len,) = _ENTRY_HEAD.unpack(
            _read_exact(stream, _ENTRY_HEAD.size))
        name = _read_exact(stream, name_len).decode("utf-8")
        kind, nbytes = _ENTRY_META.unpack(
            _read_exact(stream, _ENTRY_META.size))
        payload = _read_exact(stream, nbytes)
        (crc,) = _ENTRY_CRC.unpack(_read_exact(stream, _ENTRY_CRC.size))
        if zlib.crc32(payload) != crc:
            raise CheckpointCorruptError(
                f"entry {name!r}: crc mismatch — checkpoint damaged")
        out[name] = _decode_value(kind, payload)
    return out


def save_checkpoint(path: Union[str, os.PathLike],
                    entries: Dict[str, Any], *,
                    pre_replace: Optional[Any] = None) -> None:
    """Atomically write ``entries`` to ``path``: the bytes land in
    ``<path>.tmp`` first and are renamed into place only after a
    successful flush+fsync, so readers only ever see complete
    checkpoints (a writer killed mid-save leaves the previous file).

    ``pre_replace`` (a zero-arg callable) runs BETWEEN the fsynced temp
    file and the rename — the torn-state window a crash-consistency
    witness must be able to die in (the streaming epoch protocol arms
    its ``compact.mid_write`` crash point here); a kill inside it
    leaves only ``.tmp`` debris, which no reader ever opens."""
    path = os.fspath(path)
    tmp = path + ".tmp"
    t0 = time.monotonic()
    with open(tmp, "wb") as f:
        dump_checkpoint(entries, f)
        f.flush()
        os.fsync(f.fileno())
        nbytes = f.tell()
    if pre_replace is not None:
        pre_replace()
    os.replace(tmp, path)
    if obs.enabled():
        obs.inc("checkpoint_bytes_written_total", nbytes)
        obs.observe("checkpoint_write_seconds", time.monotonic() - t0)
    trace.record_event("checkpoint.save", path=path, entries=len(entries))


def restore_checkpoint(path: Union[str, os.PathLike]) -> Dict[str, Any]:
    path = os.fspath(path)
    with open(path, "rb") as f:
        out = load_checkpoint(f)
    trace.record_event("checkpoint.restore", path=path, entries=len(out))
    return out


class CheckpointManager:
    """Step-indexed checkpoint files with retention.

    Files are ``<directory>/<prefix>-<step:08d>.ckpt``; ``save`` writes
    atomically and prunes to the newest ``keep`` files; ``latest()``
    returns (step, path) of the newest complete checkpoint, which is
    what elastic recovery resumes from.
    """

    def __init__(self, directory: Union[str, os.PathLike],
                 prefix: str = "ckpt", keep: int = 2):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = os.fspath(directory)
        self.prefix = prefix
        self.keep = int(keep)
        os.makedirs(self.directory, exist_ok=True)
        self._pat = re.compile(
            re.escape(prefix) + r"-(\d{8})\.ckpt$")

    def path_for(self, step: int) -> str:
        return os.path.join(self.directory,
                            f"{self.prefix}-{int(step):08d}.ckpt")

    def save(self, step: int, entries: Dict[str, Any], *,
             pre_replace: Optional[Any] = None) -> str:
        path = self.path_for(step)
        save_checkpoint(path, entries, pre_replace=pre_replace)
        self._prune()
        return path

    def verify(self, step: int) -> None:
        """Parse the step's file end to end (every entry CRC checked),
        raising the typed :class:`CheckpointError` taxonomy on damage —
        the scrub walk's per-file primitive."""
        restore_checkpoint(self.path_for(step))

    def steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            m = self._pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self) -> Optional[Tuple[int, str]]:
        steps = self.steps()
        if not steps:
            return None
        step = steps[-1]
        return step, self.path_for(step)

    def restore_latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        latest = self.latest()
        if latest is None:
            return None
        step, path = latest
        return step, restore_checkpoint(path)

    def _prune(self) -> None:
        steps = self.steps()
        for step in steps[:-self.keep]:
            path = self.path_for(step)
            try:
                os.remove(path)
            except OSError as e:
                _log.warning("retention prune of %s failed: %r", path, e)
