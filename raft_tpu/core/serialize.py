"""Array ⇄ NumPy `.npy` stream serialization.

Re-design of the reference's mdspan serializer (core/serialize.hpp:26-112,
core/detail/mdspan_numpy_serializer.hpp): host and device arrays are written
to / read from the NumPy binary format so checkpoints interoperate with
NumPy and with the reference's own serialized artifacts.
"""

from __future__ import annotations

import io
from typing import Any, BinaryIO, Union

import jax
import numpy as np

from raft_tpu.core.mdarray import MdArray

# np.save of an ml_dtypes bfloat16 array silently degrades the dtype to
# raw void bytes ('|V2') — loads would come back typeless. Wire bf16 as
# a one-field structured dtype instead: same bytes, self-describing
# name, detectable on load without guessing.
_BF16_WIRE = np.dtype([("bfloat16", np.uint16)])


def _to_numpy(array: Any) -> np.ndarray:
    if isinstance(array, MdArray):
        array = array.data
    if isinstance(array, jax.Array):
        return np.asarray(jax.device_get(array))
    return np.asarray(array)


def serialize_mdspan(res, stream: BinaryIO, array: Any) -> None:
    """Write an array (host or device) in .npy format
    (ref: serialize_mdspan, core/serialize.hpp:26-68)."""
    arr = _to_numpy(array)
    if arr.dtype.name == "bfloat16":
        arr = np.ascontiguousarray(arr).view(np.uint16).view(_BF16_WIRE)
    np.save(stream, arr, allow_pickle=False)


def deserialize_mdspan(res, stream: BinaryIO, to_device: bool = True):
    """Read a .npy stream back (ref: deserialize_mdspan,
    core/serialize.hpp:70-112)."""
    arr = np.load(stream, allow_pickle=False)
    if arr.dtype.names == ("bfloat16",):
        import ml_dtypes

        arr = arr.view(np.uint16).view(ml_dtypes.bfloat16)
    if to_device:
        import jax.numpy as jnp

        return jnp.asarray(arr)
    return arr


def serialize_scalar(res, stream: BinaryIO, value) -> None:
    np.save(stream, np.asarray(value), allow_pickle=False)


def deserialize_scalar(res, stream: BinaryIO):
    """Read a scalar back as the *native* Python value (ref semantics:
    deserialize_scalar<T> returns T, not an array wrapper — returning
    ``np.float64``/``np.int64`` here leaked NumPy scalars into params
    structs and comparison code)."""
    val = np.load(stream, allow_pickle=False)[()]
    return val.item() if isinstance(val, np.generic) else val


def dumps(array: Any) -> bytes:
    buf = io.BytesIO()
    serialize_mdspan(None, buf, array)
    return buf.getvalue()


def loads(data: Union[bytes, bytearray], to_device: bool = True):
    return deserialize_mdspan(None, io.BytesIO(bytes(data)), to_device)
