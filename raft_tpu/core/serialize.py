"""Array ⇄ NumPy `.npy` stream serialization.

Re-design of the reference's mdspan serializer (core/serialize.hpp:26-112,
core/detail/mdspan_numpy_serializer.hpp): host and device arrays are written
to / read from the NumPy binary format so checkpoints interoperate with
NumPy and with the reference's own serialized artifacts.
"""

from __future__ import annotations

import io
from typing import Any, BinaryIO, Union

import jax
import numpy as np

from raft_tpu.core.mdarray import MdArray


def _to_numpy(array: Any) -> np.ndarray:
    if isinstance(array, MdArray):
        array = array.data
    if isinstance(array, jax.Array):
        return np.asarray(jax.device_get(array))
    return np.asarray(array)


def serialize_mdspan(res, stream: BinaryIO, array: Any) -> None:
    """Write an array (host or device) in .npy format
    (ref: serialize_mdspan, core/serialize.hpp:26-68)."""
    np.save(stream, _to_numpy(array), allow_pickle=False)


def deserialize_mdspan(res, stream: BinaryIO, to_device: bool = True):
    """Read a .npy stream back (ref: deserialize_mdspan,
    core/serialize.hpp:70-112)."""
    arr = np.load(stream, allow_pickle=False)
    if to_device:
        import jax.numpy as jnp

        return jnp.asarray(arr)
    return arr


def serialize_scalar(res, stream: BinaryIO, value) -> None:
    np.save(stream, np.asarray(value), allow_pickle=False)


def deserialize_scalar(res, stream: BinaryIO):
    return np.load(stream, allow_pickle=False)[()]


def dumps(array: Any) -> bytes:
    buf = io.BytesIO()
    serialize_mdspan(None, buf, array)
    return buf.getvalue()


def loads(data: Union[bytes, bytearray], to_device: bool = True):
    return deserialize_mdspan(None, io.BytesIO(bytes(data)), to_device)
