"""Memory-type vocabulary (ref: core/memory_type.hpp:21-29).

On TPU the meaningful distinction is host (numpy, CPU RAM) vs device
(jax.Array in HBM).  ``pinned`` maps to host (XLA stages transfers through
pinned buffers internally) and ``managed`` has no analogue — it behaves as
device with transparent host access via jax.device_get.
"""

from __future__ import annotations

import enum


class MemoryType(enum.Enum):
    HOST = "host"
    PINNED = "pinned"
    DEVICE = "device"
    MANAGED = "managed"

    @property
    def is_device_accessible(self) -> bool:
        # ref: core/memory_type.hpp is_device_accessible trait
        return self in (MemoryType.DEVICE, MemoryType.MANAGED)

    @property
    def is_host_accessible(self) -> bool:
        return self in (MemoryType.HOST, MemoryType.PINNED, MemoryType.MANAGED)


HOST = MemoryType.HOST
PINNED = MemoryType.PINNED
DEVICE = MemoryType.DEVICE
MANAGED = MemoryType.MANAGED
