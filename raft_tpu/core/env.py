"""Central registry of ``RAFT_TPU_*`` environment knobs (ISSUE 12).

Every environment variable the library reads is declared here — name,
parser, default, and what happens on a malformed value — and read
through :func:`read`. Library code never touches ``os.environ`` for a
``RAFT_TPU_*`` key directly; ``tools/raftlint`` rule R7 enforces that
statically, so a new knob cannot ship without appearing in this table
(and in ``docs/architecture.md``'s knob inventory by grep).

Malformed-value policy is per-knob and preserves the contracts earlier
PRs tested:

``raise``
    the fail-loud family (``RAFT_TPU_HBM_BUDGET``,
    ``RAFT_TPU_RECV_TIMEOUT``, ``RAFT_TPU_SPAN_RETAIN``,
    ``RAFT_TPU_SPAN_SAMPLE``, ``RAFT_TPU_MST``, ``RAFT_TPU_SPMV``):
    a typo'd limit must never silently become "unlimited", so the
    ``ValueError`` surfaces at the read site — which for import-time
    knobs means at import.
``warn``
    the safe-default family (``RAFT_TPU_METRICS``,
    ``RAFT_TPU_TRACING``, ``RAFT_TPU_GUARD_MODE``, ...): observability
    and guard toggles degrade to their off/default mode with a visible
    warning — a typo must not take the process down, only the feature.

An empty string is treated as unset everywhere (the pre-registry
readers already did this for every knob whose empty spelling was
reachable).
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass
from typing import Any, Callable, Dict

__all__ = ["EnvVar", "register", "registry", "read", "parse_bytes"]


# -- parsers ----------------------------------------------------------------

def _parse_str(raw: str) -> str:
    return raw


def _parse_lower(raw: str) -> str:
    return raw.lower()


def _parse_onoff(raw: str) -> bool:
    """The metrics/tracing toggle spelling: on/1/true/yes vs
    off/0/false/no."""
    low = raw.lower()
    if low in ("on", "1", "true", "yes"):
        return True
    if low in ("off", "0", "false", "no"):
        return False
    raise ValueError(f"want one of on|off|1|0|true|false|yes|no, "
                     f"got {raw!r}")


def _parse_flag(raw: str) -> bool:
    """Loose boolean: anything but 0/false is on (the
    ``RAFT_TPU_PALLAS_INTERPRET`` / ``RAFT_TPU_SPLIT_PACKED`` family)."""
    return raw.lower() not in ("0", "false")


def _parse_pos_int(raw: str) -> int:
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{raw!r} is not an integer") from None
    if val < 1:
        raise ValueError(f"{raw!r} must be >= 1")
    return val


def _parse_rate(raw: str) -> float:
    try:
        rate = float(raw)
    except ValueError:
        raise ValueError(f"{raw!r} is not a number") from None
    if not (0.0 <= rate <= 1.0):
        raise ValueError(f"{raw!r} must be in [0, 1]")
    return rate


def _parse_float(raw: str) -> float:
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{raw!r} is not a number") from None


def _parse_pos_float(raw: str) -> float:
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{raw!r} is not a number") from None
    if not val > 0.0:
        raise ValueError(f"{raw!r} must be > 0")
    return val


def _parse_quorum(raw: str) -> "str | int":
    """WAL ack mode: ``async`` | ``majority`` | ``all`` | a positive
    integer follower count."""
    val = raw.strip().lower()
    if val in ("async", "majority", "all"):
        return val
    try:
        count = int(val)
    except ValueError:
        raise ValueError(
            f"want async|majority|all or a positive int, got {raw!r}"
        ) from None
    if count < 1:
        raise ValueError(f"explicit ack count must be >= 1, got {raw!r}")
    return count


def _parse_ratio_ge1(raw: str) -> float:
    """A trigger ratio: a float >= 1.0 (1.0 = trigger immediately)."""
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{raw!r} is not a number") from None
    if not val >= 1.0:
        raise ValueError(f"ratio must be >= 1.0, got {raw!r}")
    return val


def _parse_peaks(raw: str) -> Dict[str, float]:
    """``flops=<num>,bytes=<num>`` device-peak override terms (either
    term may be omitted; at least one must be present, both positive)."""
    out: Dict[str, float] = {}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, val = part.partition("=")
        key = key.strip().lower()
        if not sep or key not in ("flops", "bytes"):
            raise ValueError(
                f"want 'flops=<num>,bytes=<num>' terms, got {part!r}")
        try:
            num = float(val)
        except ValueError:
            raise ValueError(f"{val!r} is not a number") from None
        if not num > 0.0:
            raise ValueError(f"{key} peak must be positive, got {val!r}")
        out[key] = num
    if not out:
        raise ValueError(
            "want at least one 'flops=<num>' or 'bytes=<num>' term")
    return out


def _parse_tolerance(raw: str) -> float:
    """A regression-tolerance ratio: a float >= 1.0."""
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{raw!r} is not a number") from None
    if not val >= 1.0:
        raise ValueError(f"tolerance ratio must be >= 1.0, got {raw!r}")
    return val


def _choice(*options: str) -> Callable[[str], str]:
    def parse(raw: str) -> str:
        low = raw.lower()
        if low not in options:
            raise ValueError(f"want one of {'|'.join(options)}, "
                             f"got {raw!r}")
        return low
    return parse


_BYTE_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_bytes(text, *, name: str = "byte count") -> int:
    """Parse a byte count: a plain number or a number with a k/m/g/t
    binary suffix (``"512m"``, ``"2g"``). Raises ``ValueError`` on
    anything else — the fail-loud contract for ``RAFT_TPU_HBM_BUDGET``
    (a typo'd limit must never silently become 'unlimited').

    Canonical home of the parser ``runtime.limits.parse_bytes``
    re-exports (limits imports env; env imports nothing from
    raft_tpu)."""
    s = str(text).strip().lower()
    mult = 1
    if s and s[-1] in _BYTE_SUFFIX:
        mult = _BYTE_SUFFIX[s[-1]]
        s = s[:-1]
    try:
        val = float(s)
    except ValueError:
        raise ValueError(
            f"{name} must be a byte count (optionally with a k/m/g/t "
            f"suffix, e.g. '512m'), got {text!r}") from None
    n = int(val * mult)
    if n <= 0:
        raise ValueError(f"{name} must be positive, got {text!r}")
    return n


# -- registry ---------------------------------------------------------------

@dataclass(frozen=True)
class EnvVar:
    """One declared knob: how to parse it and what a bad value does."""

    name: str
    parse: Callable[[str], Any]
    default: Any
    on_malformed: str               # "raise" | "warn"
    help: str = ""


_REGISTRY: Dict[str, EnvVar] = {}


def register(name: str, parse: Callable[[str], Any], default: Any = None,
             *, on_malformed: str = "raise", help: str = "") -> EnvVar:
    if not name.startswith("RAFT_TPU_"):
        raise ValueError(f"env registry is for RAFT_TPU_* knobs, "
                         f"got {name!r}")
    if on_malformed not in ("raise", "warn"):
        raise ValueError(f"on_malformed must be raise|warn, "
                         f"got {on_malformed!r}")
    spec = EnvVar(name, parse, default, on_malformed, help)
    _REGISTRY[name] = spec
    return spec


def registry() -> Dict[str, EnvVar]:
    """Snapshot of every declared knob (docs and tests iterate this)."""
    return dict(_REGISTRY)


_UNSET = object()


def read(name: str, default: Any = _UNSET) -> Any:
    """Read and parse one registered knob from the process environment.

    Unset or empty returns the default (the registered one, or the
    call-site override — e.g. the per-transport recv-timeout fallback).
    A malformed value raises ``ValueError`` naming the variable, or —
    for ``on_malformed="warn"`` knobs — warns and returns the default.
    Reading an unregistered name is a programming error and raises
    ``KeyError``: declare the knob here first.
    """
    spec = _REGISTRY.get(name)
    if spec is None:
        raise KeyError(f"{name} is not a registered RAFT_TPU_* knob; "
                       f"declare it in raft_tpu/core/env.py")
    fallback = spec.default if default is _UNSET else default
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return fallback
    try:
        return spec.parse(raw)
    except ValueError as e:
        if spec.on_malformed == "warn":
            warnings.warn(f"{name}={raw!r} is invalid ({e}); using "
                          f"{fallback!r}", stacklevel=2)
            return fallback
        raise ValueError(f"{name}: {e}") from None


# -- the knob table ---------------------------------------------------------
# Observability toggles: degrade to off with a warning.
register("RAFT_TPU_METRICS", _parse_onoff, False, on_malformed="warn",
         help="arm the metrics/span subsystem (off = single-bool no-op)")
register("RAFT_TPU_TRACING", _parse_onoff, False, on_malformed="warn",
         help="mint + propagate request TraceContexts")
register("RAFT_TPU_GUARD_MODE", _choice("off", "check", "recover"), "off",
         on_malformed="warn",
         help="numerical sentinel mode (core/guards.py)")
register("RAFT_TPU_MATMUL_PRECISION", _parse_lower, "high",
         on_malformed="warn",
         help="matmul precision policy; canonicalized in util/precision")
register("RAFT_TPU_LOG_LEVEL", _parse_lower, "warn", on_malformed="warn",
         help="raft_tpu logger level; unknown names fall back to warn "
              "silently (core/logger.py owns the level table)")
register("RAFT_TPU_DEBUG_LOG_FILE", _parse_str, None, on_malformed="warn",
         help="route the raft_tpu logger to a file instead of stderr")
register("RAFT_TPU_METRICS_JSONL", _parse_str, None, on_malformed="warn",
         help="auto-attach a JSONL metrics sink at import (metrics on)")
register("RAFT_TPU_FLIGHT_DIR", _parse_str, None, on_malformed="warn",
         help="on-disk flight-recorder bundle directory")
register("RAFT_TPU_PERF", _parse_onoff, False, on_malformed="warn",
         help="arm per-executable performance attribution "
              "(obs/perf.py roofline telemetry); off = single-bool "
              "no-op, bit-identical")

# Fail-loud limits and tuning knobs: malformed raises at the read site
# (import time for the import-read ones) — never a silent fallback.
register("RAFT_TPU_HBM_BUDGET", _parse_str, None,
         help="process-wide HBM admission budget; parsed by "
              "limits.parse_bytes (k/m/g/t suffixes) and raises at "
              "import on a malformed value")
register("RAFT_TPU_RECV_TIMEOUT", _parse_float, None,
         help="default blocking-recv deadline (s) for both transports")
register("RAFT_TPU_SPAN_RETAIN", _parse_pos_int, 2048,
         help="span ring retention (newest N spans)")
register("RAFT_TPU_SPAN_SAMPLE", _parse_rate, 1.0,
         help="span sampling rate in [0, 1] (counter-stride, "
              "deterministic)")
register("RAFT_TPU_MST", _choice("auto", "grid", "xla"), "auto",
         help="force the Borůvka E-stage formulation")
register("RAFT_TPU_SPMV", _choice("auto", "grid", "ell", "segment"), "auto",
         help="force the SpMV formulation")
register("RAFT_TPU_PERF_PEAKS", _parse_peaks, None,
         help="override the core/hw.py device-peak table: "
              "'flops=<num>,bytes=<num>' per-second peaks (either term "
              "alone overrides just that axis); malformed raises at the "
              "read site — a typo'd peak must never silently skew every "
              "roofline fraction")
register("RAFT_TPU_SENTRY_TOL", _parse_tolerance, 1.5,
         help="ci/perf_sentry.py default regression-tolerance ratio "
              "(>= 1.0); malformed raises at the read site")

# Loose flags (any value but 0/false arms them).
register("RAFT_TPU_PALLAS_INTERPRET", _parse_flag, None,
         on_malformed="warn",
         help="force Pallas interpret mode on/off (unset = by backend)")
register("RAFT_TPU_SPLIT_PACKED", _parse_flag, False, on_malformed="warn",
         help="packed-operand spelling for the bf16x3 cross terms")
register("RAFT_TPU_SPARSE_PAD", _parse_flag, True, on_malformed="warn",
         help="pad sparse buffers to lane-friendly capacities")

# Streaming-index lifecycle knobs (ISSUE 17): fail-loud — a typo'd
# compaction threshold must never silently become "never compact" (the
# index would grow tombstones unbounded) or "always compact" (the
# background repack would thrash), so malformed values raise at the
# read site per the R7 registry discipline.
register("RAFT_TPU_COMPACT_TOMBSTONE_FRAC", _parse_rate, 0.25,
         help="tombstone fraction (dead/live rows, in [0, 1]) at which "
              "the background compactor repacks the streaming index")
register("RAFT_TPU_COMPACT_INTERVAL", _parse_pos_float, 0.25,
         help="background compactor poll interval in seconds (> 0); "
              "each tick re-evaluates the tombstone/tail-overflow "
              "thresholds")
register("RAFT_TPU_DRIFT_THRESHOLD", _parse_ratio_ge1, 2.0,
         help="drift trigger: refit the coarse quantizer when the "
              "EMA of ingested rows' nearest-centroid distance exceeds "
              "this multiple of the build-time baseline (>= 1.0)")

# Durable-fleet knobs (ISSUE 18): fail-loud like the rest of the
# streaming family — a typo'd retention must never silently become
# "keep everything" (disk fills) or "keep one" (a torn newest-epoch
# write would leave nothing to fall back to), and a typo'd scrub
# interval must not silently disable at-rest corruption detection.
register("RAFT_TPU_WAL_RETAIN", _parse_pos_int, 2,
         help="epoch snapshots the streaming MutationLog retains "
              "(>= 1); older snapshots and the WAL records they fold "
              "are pruned at each epoch commit")
register("RAFT_TPU_SCRUB_INTERVAL", _parse_pos_float, 1.0,
         help="background scrubber pass interval in seconds (> 0); "
              "each pass re-verifies every epoch/WAL container CRC "
              "and the in-memory packed-list sidecar")

# Failover knobs (ISSUE 20): fail-loud — a typo'd election timeout
# must never silently become "never elect" (a dead leader would take
# ingest down forever, the exact failure mode the election exists to
# prevent), and a typo'd quorum mode must never silently weaken the
# zero-loss acked-write guarantee down to async.
register("RAFT_TPU_ELECTION_TIMEOUT", _parse_pos_float, 1.0,
         help="heartbeat-silence threshold in seconds (> 0) after "
              "which a follower triggers leader election; also the "
              "per-peer ballot-exchange timeout")
register("RAFT_TPU_WAL_QUORUM", _parse_quorum, "async",
         help="WalShipper ack mode: 'async' (ack on local journal "
              "apply), 'majority' (block until ceil((n+1)/2) "
              "followers confirm), 'all', or an explicit positive "
              "follower count")

# Overload-resilience toggles (ISSUE 16): degrade to the conservative
# setting (on) with a warning — resilience must not vanish on a typo.
register("RAFT_TPU_BROWNOUT", _parse_onoff, True, on_malformed="warn",
         help="arm the adaptive quality-brownout controller "
              "(serve/brownout.py); off = always full quality")
register("RAFT_TPU_HEDGE", _parse_onoff, True, on_malformed="warn",
         help="arm hedged re-issue in ReplicaGroup.submit "
              "(serve/replica.py); off = single dispatch")
