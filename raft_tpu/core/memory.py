"""Host-side memory observability (ref: raft/mr/).

XLA owns device allocation, so RAFT's pluggable device memory resources
collapse to observability + policy here:

- :class:`StatisticsTracker` — byte/alloc counters
  (ref: mr/statistics_adaptor.hpp:25,66)
- :class:`NotifyingTracker` — alloc/dealloc event hooks
  (ref: mr/notifying_adaptor.hpp:25,77)
- :class:`ResourceMonitor` — background sampler writing CSV rows tagged with
  the current trace range (ref: mr/resource_monitor.hpp:29-66)
- :func:`mmap_buffer` — tmpfile-backed mmap host allocation for out-of-core
  staging (ref: mr/mmap_memory_resource.hpp:31,86)
- :func:`device_memory_stats` — live/peak HBM from the JAX runtime.
"""

from __future__ import annotations

import contextlib
import csv
import mmap
import os
import tempfile
import threading
import time
from typing import Callable, List, Optional, Tuple

import jax
import numpy as np

from raft_tpu.core import trace


class StatisticsTracker:
    """Counts allocations/bytes reported through it."""

    def __init__(self):
        self._lock = threading.Lock()
        self.bytes_allocated = 0
        self.peak_bytes = 0
        self.allocation_count = 0
        self.deallocation_count = 0

    def on_alloc(self, nbytes: int) -> None:
        with self._lock:
            self.bytes_allocated += nbytes
            self.peak_bytes = max(self.peak_bytes, self.bytes_allocated)
            self.allocation_count += 1

    def on_dealloc(self, nbytes: int) -> None:
        with self._lock:
            self.bytes_allocated -= nbytes
            self.deallocation_count += 1

    def snapshot(self) -> Tuple[int, int, int, int]:
        with self._lock:
            return (self.bytes_allocated, self.peak_bytes,
                    self.allocation_count, self.deallocation_count)


class NotifyingTracker(StatisticsTracker):
    """Statistics tracker that additionally wakes observers on events."""

    def __init__(self):
        super().__init__()
        self._observers: List[Callable[[str, int], None]] = []

    def subscribe(self, fn: Callable[[str, int], None]) -> None:
        self._observers.append(fn)

    def on_alloc(self, nbytes: int) -> None:
        super().on_alloc(nbytes)
        for fn in self._observers:
            fn("alloc", nbytes)

    def on_dealloc(self, nbytes: int) -> None:
        super().on_dealloc(nbytes)
        for fn in self._observers:
            fn("dealloc", nbytes)


def device_memory_stats(device: Optional[jax.Device] = None) -> dict:
    """Live/peak HBM usage from the runtime (bytes), when supported."""
    device = device or jax.devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:
        stats = {}
    return {
        "bytes_in_use": stats.get("bytes_in_use", 0),
        "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
        "bytes_limit": stats.get("bytes_limit", 0),
    }


class ResourceMonitor:
    """Background thread sampling memory stats to CSV, tagged with the
    active trace range (ref: mr/resource_monitor.hpp:29-66)."""

    def __init__(self, path: str, tracker: Optional[StatisticsTracker] = None,
                 interval_s: float = 0.1,
                 device: Optional[jax.Device] = None):
        self.path = path
        self.tracker = tracker
        self.interval_s = interval_s
        self.device = device
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._file = None
        # The sampler tags rows with the *starting* thread's range stack.
        self._range_fn = trace.current_range

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError(
                "ResourceMonitor already started; stop() it first")
        # The file handle lives on self so stop() — not the sampler
        # thread — owns flush/close: a daemon thread torn down at
        # interpreter exit must not be the only thing between buffered
        # rows and the disk.
        self._file = open(self.path, "w", newline="")
        writer = csv.writer(self._file)
        writer.writerow(["time_s", "range", "host_bytes", "host_peak",
                         "device_bytes", "device_peak"])
        t0 = time.monotonic()

        def run():
            while not self._stop.is_set():
                host_bytes = host_peak = 0
                if self.tracker is not None:
                    host_bytes, host_peak, _, _ = self.tracker.snapshot()
                dstats = device_memory_stats(self.device)
                writer.writerow([
                    f"{time.monotonic() - t0:.4f}",
                    self._range_fn() or "",
                    host_bytes, host_peak,
                    dstats["bytes_in_use"], dstats["peak_bytes_in_use"],
                ])
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Join the sampler thread, then flush and close the CSV writer.
        Idempotent; after stop() the monitor can be start()ed again
        (a fresh file is opened, truncating the path)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._file is not None:
            self._file.flush()
            self._file.close()
            self._file = None
        self._stop.clear()


class MmapBuffer:
    """tmpfile-backed mmap host buffer for out-of-core staging
    (ref: mr/mmap_memory_resource.hpp:31,86)."""

    def __init__(self, nbytes: int, dir: Optional[str] = None):
        # mkstemp + immediate unlink (rather than TemporaryFile, whose
        # unlink timing is platform-dependent): the backing file has no
        # name from the first moment, so no path can leak even if the
        # process dies mid-use; the space is reclaimed when the last fd
        # and mapping go away.
        fd, path = tempfile.mkstemp(dir=dir, prefix="raft_tpu_mmap_")
        try:
            os.unlink(path)
        except OSError:
            os.close(fd)
            raise
        self._file = os.fdopen(fd, "r+b")
        self._file.truncate(nbytes)
        self.nbytes = nbytes
        self._closed = False
        self._mmap = mmap.mmap(self._file.fileno(), nbytes)

    def as_array(self, dtype=np.uint8, shape=None) -> np.ndarray:
        arr = np.frombuffer(self._mmap, dtype=dtype)
        return arr.reshape(shape) if shape is not None else arr

    def close(self) -> None:
        """Release the mapping and the backing descriptor. Idempotent —
        and the descriptor is closed even when live array views keep the
        mapping itself alive, so repeated create/close cycles never
        accumulate fds (the file was unlinked at creation)."""
        if self._closed:
            return
        self._closed = True
        try:
            # Arrays may still view the mapping; the OS reclaims it when
            # they are garbage collected (the tmpfile is already
            # unlinked).
            with contextlib.suppress(BufferError):
                self._mmap.close()
        finally:
            self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def mmap_buffer(nbytes: int, dir: Optional[str] = None) -> MmapBuffer:
    return MmapBuffer(nbytes, dir=dir)
