"""Owning sparse structure/matrix types (ref: core/csr_matrix.hpp:21-235,
core/coo_matrix.hpp, core/sparse_types.hpp).

The reference separates *structure* (indices) from *elements* (values) with
owning/preserving sparsity semantics and host/device variants.  Here both
host (numpy) and device (jax.numpy) arrays are accepted; static shapes are
required under jit, so ``nnz`` is a static Python int and growth re-allocates
(mirroring the reference's ``initialize_sparsity`` re-allocation contract).

These classes are registered as JAX pytrees so they can flow through jitted
functions with indices/values as leaves.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# -- dynamic-nnz bucketing (SURVEY §7 hard part: every distinct nnz is a
#    distinct static shape, so a stream of graphs with varying nnz would
#    retrace every sparse jit; ref contrast: sparse/detail/coo.cuh:38
#    setSize just realloc's). Policy: pad indices/data up to a size class;
#    ``indptr`` is NOT touched, so ``indptr[-1]`` remains the LOGICAL nnz
#    as device data. Pad entries carry data == 0 and column == 0:
#    - linear ops (spmv/spmm/norms/degree) are unaffected — zero
#      contributions land in the last row;
#    - per-nnz-output and selection ops mask on position < indptr[-1];
#    - eager conversions (csr_to_coo and everything built on it) slice
#      back to the logical nnz.
#    Quarter-octave classes (2^k × {1, 1.25, 1.5, 1.75}) bound the wasted
#    bandwidth at ≤25% while keeping the class count logarithmic.

PAD_MIN_NNZ = 256


def nnz_bucket(n: int, min_size: int = PAD_MIN_NNZ) -> int:
    """Smallest quarter-octave size class ≥ n."""
    if n <= min_size:
        return min_size
    b = min_size
    while b * 2 <= n:
        b *= 2
    for frac in (4, 5, 6, 7):
        cand = b * frac // 4
        if cand >= n:
            return cand
    return b * 2


def _default_pad() -> bool:
    from raft_tpu.core import env

    return env.read("RAFT_TPU_SPARSE_PAD")


class CSRMatrix:
    """Compressed sparse row matrix: indptr[n_rows+1], indices[nnz], data[nnz].

    ref: csr_matrix / compressed_structure_t (core/csr_matrix.hpp:21,55,106).
    """

    def __init__(self, indptr, indices, data, shape: Tuple[int, int]):
        self.indptr = indptr
        self.indices = indices
        self.data = data
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def nnz(self) -> int:
        """PHYSICAL nnz (the static jit shape). With padding this can
        exceed :meth:`logical_nnz` = ``indptr[-1]``."""
        return int(self.indices.shape[0])

    def logical_nnz(self) -> int:
        """Actual stored-entry count, ``indptr[-1]``, as a host int.

        EAGER-ONLY (raises on tracers): jit-compatible consumers build
        positional masks from the device scalar ``indptr[-1]`` instead
        (see e.g. sparse.linalg._segment_spmv). The value is cached at
        construction where known (pad_nnz/from_scipy), so the common
        eager paths don't device-sync; the cache is deliberately NOT
        pytree aux data — a per-graph static would retrace every jit,
        defeating the bucketing."""
        hint = getattr(self, "_logical_nnz_hint", None)
        if hint is not None:
            return hint
        n = int(np.asarray(self.indptr[-1]))
        self._logical_nnz_hint = n
        return n

    def pad_nnz(self, target: Optional[int] = None,
                min_size: int = PAD_MIN_NNZ) -> "CSRMatrix":
        """Pad indices/data to ``target`` (default: the nnz size class) so
        matrices with nearby nnz share one jit executable. Pad entries:
        data 0, column 0; ``indptr`` is unchanged — ``indptr[-1]`` stays
        the logical nnz."""
        phys = self.nnz
        logical = self.logical_nnz()
        if target is None:
            target = nnz_bucket(max(logical, phys), min_size)
        pad = target - phys
        if pad <= 0:
            return self
        if isinstance(self.indices, jax.Array):
            indices = jnp.concatenate(
                [self.indices, jnp.zeros(pad, self.indices.dtype)])
            data = jnp.concatenate(
                [self.data, jnp.zeros(pad, self.data.dtype)])
        else:
            indices = np.concatenate(
                [self.indices, np.zeros(pad, self.indices.dtype)])
            data = np.concatenate(
                [self.data, np.zeros(pad, self.data.dtype)])
        out = CSRMatrix(self.indptr, indices, data, self.shape)
        out._logical_nnz_hint = logical
        return out

    def depad(self) -> "CSRMatrix":
        """Slice back to the logical nnz (eager; host syncs indptr[-1])."""
        n = self.logical_nnz()
        if n == self.nnz:
            return self
        return CSRMatrix(self.indptr, self.indices[:n], self.data[:n],
                         self.shape)

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @property
    def dtype(self):
        return self.data.dtype

    def structure_view(self) -> Tuple[Any, Any]:
        return self.indptr, self.indices

    def to_device(self) -> "CSRMatrix":
        return CSRMatrix(jnp.asarray(self.indptr), jnp.asarray(self.indices),
                         jnp.asarray(self.data), self.shape)

    def to_host(self) -> "CSRMatrix":
        g = jax.device_get
        return CSRMatrix(np.asarray(g(self.indptr)),
                         np.asarray(g(self.indices)),
                         np.asarray(g(self.data)), self.shape)

    def to_scipy(self):
        import scipy.sparse as sp

        h = self.to_host().depad()   # drop bucketing pad entries
        return sp.csr_matrix((h.data, h.indices, h.indptr), shape=self.shape)

    @staticmethod
    def from_scipy(mat, pad: Optional[bool] = None) -> "CSRMatrix":
        """scipy → device CSR. ``pad`` controls nnz bucketing (default: on;
        opt out per-call with ``pad=False`` or globally with
        ``RAFT_TPU_SPARSE_PAD=0``)."""
        mat = mat.tocsr()
        out = CSRMatrix(jnp.asarray(mat.indptr), jnp.asarray(mat.indices),
                        jnp.asarray(mat.data), mat.shape)
        out._logical_nnz_hint = int(mat.nnz)
        if pad if pad is not None else _default_pad():
            out = out.pad_nnz()
        return out

    def host_edges(self):
        """Host numpy (rows, cols, data) of the LOGICAL entries (pad
        tail stripped) — the COO expansion every host-side driver
        (MNMG banding, packers) starts from; one definition so the
        padding convention has a single consumer-side reading."""
        indptr = np.asarray(self.indptr)
        nnz = int(indptr[-1])
        rows = np.repeat(np.arange(self.n_rows, dtype=np.int32),
                         np.diff(indptr)).astype(np.int32)[:nnz]
        cols = np.asarray(self.indices)[:nnz].astype(np.int32)
        data = np.asarray(self.data)[:nnz]
        return rows, cols, data

    def row_lengths(self):
        return self.indptr[1:] - self.indptr[:-1]

    def row_ids(self):
        """Expand indptr to a per-nnz row-id vector (the reference's
        csr_to_coo conversion kernel, sparse/convert/coo.cuh). Always
        PHYSICAL length: bucketing pad slots get the last row's id (the
        same fill jnp.repeat's total_repeat_length uses)."""
        lengths = self.indptr[1:] - self.indptr[:-1]
        row_range = jnp.arange(self.n_rows, dtype=self.indices.dtype)
        if isinstance(self.indptr, jax.Array):
            return jnp.repeat(row_range, lengths,
                              total_repeat_length=self.nnz)
        out = np.repeat(np.asarray(row_range), np.asarray(lengths))
        if out.shape[0] < self.nnz:
            fill = self.n_rows - 1 if self.n_rows else 0
            out = np.concatenate(
                [out, np.full(self.nnz - out.shape[0], fill, out.dtype)])
        return out


class COOMatrix:
    """Coordinate-format matrix: rows[nnz], cols[nnz], data[nnz].

    ref: coo_matrix (core/coo_matrix.hpp); the legacy `COO` container
    (sparse/detail/coo.cuh:38) is the same triple with a setSize contract.
    """

    def __init__(self, rows, cols, data, shape: Tuple[int, int]):
        self.rows = rows
        self.cols = cols
        self.data = data
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @property
    def dtype(self):
        return self.data.dtype

    def to_device(self) -> "COOMatrix":
        return COOMatrix(jnp.asarray(self.rows), jnp.asarray(self.cols),
                         jnp.asarray(self.data), self.shape)

    def to_host(self) -> "COOMatrix":
        g = jax.device_get
        return COOMatrix(np.asarray(g(self.rows)), np.asarray(g(self.cols)),
                         np.asarray(g(self.data)), self.shape)

    def to_scipy(self):
        import scipy.sparse as sp

        h = self.to_host()
        return sp.coo_matrix((h.data, (h.rows, h.cols)), shape=self.shape)

    @staticmethod
    def from_scipy(mat) -> "COOMatrix":
        mat = mat.tocoo()
        return COOMatrix(jnp.asarray(mat.row), jnp.asarray(mat.col),
                         jnp.asarray(mat.data), mat.shape)


# -- pytree registration so sparse matrices flow through jit ----------------

def _csr_flatten(m: CSRMatrix):
    return (m.indptr, m.indices, m.data), m.shape


def _csr_unflatten(shape, children):
    return CSRMatrix(*children, shape=shape)


def _coo_flatten(m: COOMatrix):
    return (m.rows, m.cols, m.data), m.shape


def _coo_unflatten(shape, children):
    return COOMatrix(*children, shape=shape)


jax.tree_util.register_pytree_node(CSRMatrix, _csr_flatten, _csr_unflatten)
jax.tree_util.register_pytree_node(COOMatrix, _coo_flatten, _coo_unflatten)
