"""Owning sparse structure/matrix types (ref: core/csr_matrix.hpp:21-235,
core/coo_matrix.hpp, core/sparse_types.hpp).

The reference separates *structure* (indices) from *elements* (values) with
owning/preserving sparsity semantics and host/device variants.  Here both
host (numpy) and device (jax.numpy) arrays are accepted; static shapes are
required under jit, so ``nnz`` is a static Python int and growth re-allocates
(mirroring the reference's ``initialize_sparsity`` re-allocation contract).

These classes are registered as JAX pytrees so they can flow through jitted
functions with indices/values as leaves.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class CSRMatrix:
    """Compressed sparse row matrix: indptr[n_rows+1], indices[nnz], data[nnz].

    ref: csr_matrix / compressed_structure_t (core/csr_matrix.hpp:21,55,106).
    """

    def __init__(self, indptr, indices, data, shape: Tuple[int, int]):
        self.indptr = indptr
        self.indices = indices
        self.data = data
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @property
    def dtype(self):
        return self.data.dtype

    def structure_view(self) -> Tuple[Any, Any]:
        return self.indptr, self.indices

    def to_device(self) -> "CSRMatrix":
        return CSRMatrix(jnp.asarray(self.indptr), jnp.asarray(self.indices),
                         jnp.asarray(self.data), self.shape)

    def to_host(self) -> "CSRMatrix":
        g = jax.device_get
        return CSRMatrix(np.asarray(g(self.indptr)),
                         np.asarray(g(self.indices)),
                         np.asarray(g(self.data)), self.shape)

    def to_scipy(self):
        import scipy.sparse as sp

        h = self.to_host()
        return sp.csr_matrix((h.data, h.indices, h.indptr), shape=self.shape)

    @staticmethod
    def from_scipy(mat) -> "CSRMatrix":
        mat = mat.tocsr()
        return CSRMatrix(jnp.asarray(mat.indptr), jnp.asarray(mat.indices),
                         jnp.asarray(mat.data), mat.shape)

    def row_lengths(self):
        return self.indptr[1:] - self.indptr[:-1]

    def row_ids(self):
        """Expand indptr to a per-nnz row-id vector (the reference's
        csr_to_coo conversion kernel, sparse/convert/coo.cuh)."""
        lengths = self.indptr[1:] - self.indptr[:-1]
        row_range = jnp.arange(self.n_rows, dtype=self.indices.dtype)
        if isinstance(self.indptr, jax.Array):
            return jnp.repeat(row_range, lengths,
                              total_repeat_length=self.nnz)
        return np.repeat(np.asarray(row_range), np.asarray(lengths))


class COOMatrix:
    """Coordinate-format matrix: rows[nnz], cols[nnz], data[nnz].

    ref: coo_matrix (core/coo_matrix.hpp); the legacy `COO` container
    (sparse/detail/coo.cuh:38) is the same triple with a setSize contract.
    """

    def __init__(self, rows, cols, data, shape: Tuple[int, int]):
        self.rows = rows
        self.cols = cols
        self.data = data
        self.shape = (int(shape[0]), int(shape[1]))

    @property
    def nnz(self) -> int:
        return int(self.rows.shape[0])

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_cols(self) -> int:
        return self.shape[1]

    @property
    def dtype(self):
        return self.data.dtype

    def to_device(self) -> "COOMatrix":
        return COOMatrix(jnp.asarray(self.rows), jnp.asarray(self.cols),
                         jnp.asarray(self.data), self.shape)

    def to_host(self) -> "COOMatrix":
        g = jax.device_get
        return COOMatrix(np.asarray(g(self.rows)), np.asarray(g(self.cols)),
                         np.asarray(g(self.data)), self.shape)

    def to_scipy(self):
        import scipy.sparse as sp

        h = self.to_host()
        return sp.coo_matrix((h.data, (h.rows, h.cols)), shape=self.shape)

    @staticmethod
    def from_scipy(mat) -> "COOMatrix":
        mat = mat.tocoo()
        return COOMatrix(jnp.asarray(mat.row), jnp.asarray(mat.col),
                         jnp.asarray(mat.data), mat.shape)


# -- pytree registration so sparse matrices flow through jit ----------------

def _csr_flatten(m: CSRMatrix):
    return (m.indptr, m.indices, m.data), m.shape


def _csr_unflatten(shape, children):
    return CSRMatrix(*children, shape=shape)


def _coo_flatten(m: COOMatrix):
    return (m.rows, m.cols, m.data), m.shape


def _coo_unflatten(shape, children):
    return COOMatrix(*children, shape=shape)


jax.tree_util.register_pytree_node(CSRMatrix, _csr_flatten, _csr_unflatten)
jax.tree_util.register_pytree_node(COOMatrix, _coo_flatten, _coo_unflatten)
