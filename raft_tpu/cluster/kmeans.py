"""k-means (Lloyd) on the fused contraction kernel, single-chip and MNMG.

Rebuilt from primitives per the BASELINE north star (the algorithm layer
moved from the reference to cuVS; its building blocks — the contractions
engine, segment reductions, comms allreduce — are the layers below):

- assignment + update: `fused_lloyd_pallas` (raft_tpu.linalg.contractions)
  — one X pass computing distances, argmin, AND one-hot centroid
  sums/counts, both contractions on the MXU; no m×n matrix and no scatter.
- MNMG: rows partitioned across the mesh's data axis (the reference's
  row-partitioned convention, docs/source/using_raft_comms.rst); per-shard
  partial sums/counts combined with `psum` — the NCCL allreduce of the
  reference's MNMG k-means, riding ICI.

The MNMG step also supports a model axis: centroids sharded over a second
mesh axis, each shard computing a local argmin over its centroid block and
the global argmin combined with a min-reduce over (dist, idx) pairs — the
TPU expression of the reference's "distribute the k dimension" scaling.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.core import logger, trace
from raft_tpu import obs
from raft_tpu.linalg.contractions import (_kernel_dot_exact_lhs,
                                          fused_l2_argmin_pallas,
                                          fused_lloyd_pallas)
from raft_tpu.matrix.epilogue import host_assign_update, label_onehot
from raft_tpu.random.rng_state import RngState
from raft_tpu.util.precision import with_matmul_precision


class KMeansInit(enum.Enum):
    """Initialization methods (lineage: cuvs::cluster::kmeans::params)."""

    KMEANS_PLUS_PLUS = "kmeans++"
    RANDOM = "random"
    ARRAY = "array"  # caller-supplied centroids


@dataclasses.dataclass
class KMeansParams:
    """Hyper-parameters (lineage: cuvs kmeans params / sklearn vocabulary).

    ``check_every``: convergence is polled on the host every this many
    Lloyd iterations. Each poll is a device→host sync — on a remote-
    dispatch TPU setup one sync costs ~70 ms while an iteration costs
    ~12 ms at the BASELINE shape, so polling every iteration would
    dominate. Iterations between polls dispatch back-to-back; at most
    check_every-1 extra iterations run past convergence (identical
    result, monotone updates)."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: KMeansInit = KMeansInit.KMEANS_PLUS_PLUS
    oversampling_factor: float = 2.0
    seed: int = 0
    check_every: int = 1


# ---------------------------------------------------------------------------
# single-chip
# ---------------------------------------------------------------------------


def _assign(x, centroids):
    """Nearest-centroid assignment via the fused Pallas kernel (jnp
    reference formulation for dtypes the kernel doesn't take)."""
    if x.dtype in (jnp.float32, jnp.bfloat16):
        return fused_l2_argmin_pallas(x, centroids)
    from raft_tpu.linalg.contractions import _argmin_jnp

    val, idx = _argmin_jnp(x, centroids)
    return val, idx.astype(jnp.int32)


def _finish_update(sums, counts, old_centroids):
    """sums/counts → new centroids with empty-cluster carry-over.

    Sums/counts accumulate in float32 regardless of input dtype — bf16
    accumulation saturates (256 + 1 == 256 in bf16), which would silently
    mis-scale centroids for clusters with >256 members."""
    # counts can be FRACTIONAL under sample weights: dividing by
    # max(counts, 1) would scale a cluster with total mass 0.3 down to
    # 0.3x its true mean — divide by the actual positive mass instead
    safe = jnp.where(counts > 0, counts, 1.0)[:, None]
    new = (sums / safe).astype(old_centroids.dtype)
    return jnp.where(counts[:, None] > 0, new, old_centroids)


def _lloyd_sums(x, centroids):
    """(sums, counts, dist², labels) for one Lloyd pass — the fused kernel
    when the dtype allows, the kernels' jnp reference otherwise (never a
    scatter: one-hot update runs at MXU rate, segment_sum's scatter does
    not — 9.6 ms vs 22.4 ms measured at 1M×128, k=1024 on v5e)."""
    if x.dtype in (jnp.float32, jnp.bfloat16):
        return fused_lloyd_pallas(x, centroids)
    from raft_tpu.linalg.contractions import _lloyd_jnp

    sums, counts, dist, labels = _lloyd_jnp(x, centroids)
    return sums, counts, dist, labels.astype(jnp.int32)


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("n_clusters",))
def lloyd_step(x, centroids, n_clusters: int):
    """One Lloyd iteration: returns (new_centroids, inertia, labels).

    This is the jittable hot step (the flagship forward step for the
    driver's compile check). One fused kernel pass over X computes the
    assignment AND the centroid sums/counts.
    """
    sums, counts, dist, labels = _lloyd_sums(x, centroids)
    new_centroids = _finish_update(sums, counts, centroids)
    return new_centroids, jnp.sum(dist), labels


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("tm", "m"))
def lloyd_step_prepared(ops, centroids, *, tm: int, m: int):
    """:func:`lloyd_step` against hoisted X operands (see
    `raft_tpu.linalg.contractions.lloyd_prepare`): at tier 'high' the
    invariant bf16 hi/lo split + row norms of X are produced once per
    fit instead of once per iteration (~1.3 GB/iter of HBM traffic at
    1M×128). Bit-identical to :func:`lloyd_step` — same kernel, same
    operand bytes."""
    from raft_tpu.linalg.contractions import fused_lloyd_prepared

    sums, counts, dist, labels = fused_lloyd_prepared(
        ops, centroids, tm=tm, m=m)
    new_centroids = _finish_update(sums, counts, centroids)
    return new_centroids, jnp.sum(dist), labels


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("n_steps", "tm", "m"))
def lloyd_iterate_prepared(ops, centroids, n_steps: int, *, tm: int, m: int):
    """``n_steps`` prepared Lloyd iterations compiled as ONE device
    program — ``lax.scan`` over :func:`lloyd_step_prepared`'s body.

    On a remote-dispatch runtime every program launch pays tunnel RTT
    and forfeits the cross-launch overlap the on-device scheduler gets
    inside one program, so the iterations between convergence polls
    (``KMeansParams.check_every``) should ride a single launch. The scan
    chains the centroid carry on device and returns the final step's
    ``(centroids, inertia, labels)`` — the same triple a sequence of
    ``n_steps`` :func:`lloyd_step_prepared` calls ends with,
    bit-identically (same kernel, same operand bytes, same order).
    Reference lineage: the host loop enqueueing fused kernels
    back-to-back (SURVEY §3 kmeans fit call stack); the scan is the
    jit-native spelling of "enqueue N".
    """
    if n_steps < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    from raft_tpu.linalg.contractions import fused_lloyd_prepared

    def body(carry, _):
        c = carry[0]
        sums, counts, dist, labels = fused_lloyd_prepared(
            ops, c, tm=tm, m=m)
        new_c = _finish_update(sums, counts, c)
        return (new_c, jnp.sum(dist), labels), None

    init = (centroids, jnp.asarray(jnp.inf, jnp.float32),
            jnp.zeros((m,), jnp.int32))
    (c, inertia, labels), _ = jax.lax.scan(body, init, None, length=n_steps)
    return c, inertia, labels


def _weighted_sums(x, w, labels, dist, n_clusters: int):
    """Weighted (sums, counts, inertia_term) from an assignment — the
    scatter-free one-hot contraction with w-scaled rows, shared by the
    single-chip and both MNMG weighted update paths."""
    wf = w.astype(jnp.float32)
    oh = label_onehot(labels, n_clusters)
    sums = _kernel_dot_exact_lhs(oh.T, x.astype(jnp.float32)
                                 * wf[:, None])
    counts = oh.T @ wf
    return sums, counts, jnp.sum(dist * wf)


def _validate_sample_weights(w, n_rows: int):
    """Shared fit-entry validation (both kmeans_fit and the MNMG fit)."""
    import numpy as np

    if w.shape != (n_rows,):
        raise ValueError(
            f"sample_weights shape {w.shape} != ({n_rows},)")
    w_host = np.asarray(w)
    if not np.all(np.isfinite(w_host)) or np.any(w_host < 0) \
            or w_host.sum() <= 0:
        raise ValueError("sample_weights must be finite, non-negative, "
                         "with positive total")


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("n_clusters",))
def weighted_lloyd_step(x, w, centroids, n_clusters: int):
    """Sample-weighted Lloyd iteration (ref/cuVS parity: kmeans fit takes
    ``sample_weight``; detail applies it to both the update sums and the
    inertia). Assignment rides the fused argmin kernel; the weighted
    update is the scatter-free one-hot contraction with w-scaled rows —
    XLA-side rather than the fused kernel (the unweighted fused path
    stays the hot default; w == ones reproduces lloyd_step exactly)."""
    dist, labels = _assign(x, centroids)
    sums, counts, winertia = _weighted_sums(x, w, labels, dist, n_clusters)
    new_centroids = _finish_update(sums, counts, centroids)
    return new_centroids, winertia, labels


# ---------------------------------------------------------------------------
# compiled inner loop (runtime/compiled_driver): sync_every > 1 runs a
# chunk of Lloyd iterations as ONE device program with a donated carry
# ---------------------------------------------------------------------------


def _lloyd_convergence_step(lloyd_fn, carry, tol: float):
    """In-graph half of the host loops' convergence poll, shared by the
    compiled single-chip and MNMG chunk bodies: one Lloyd update, then
    the host loops' relative-inertia test. ``prev`` is +inf until the
    first completed iteration (the host's ``prev is None``); the
    accumulator dtype is float64 when x64 is on, so the in-graph test
    matches the host loops' Python-float arithmetic on the test meshes.
    """
    c, prev, _ = carry
    new_c, inertia = lloyd_fn(c)
    cur = inertia.astype(prev.dtype)
    rel = jnp.abs(prev - cur) / jnp.maximum(prev, 1e-30)
    rel = jnp.where(jnp.isfinite(prev), rel, jnp.inf)
    done = jnp.isfinite(prev) & (rel <= tol)
    return (new_c, cur, rel), done


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("n_clusters", "tol"),
                   donate_argnums=(1,))
def _lloyd_chunk(x, carry, steps, *, n_clusters: int, tol: float):
    """Up to ``steps`` plain Lloyd iterations as one device program —
    the compiled twin of the :func:`lloyd_step` host loop, with the
    convergence test fused in-graph and the carry donated."""
    from raft_tpu.runtime.compiled_driver import chunk_while

    def step(carry):
        def lloyd(c):
            sums, counts, dist, _ = _lloyd_sums(x, c)
            return _finish_update(sums, counts, c), jnp.sum(dist)

        return _lloyd_convergence_step(lloyd, carry, tol)

    return chunk_while(step, carry, steps)


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("tm", "m", "tol"),
                   donate_argnums=(1,))
def _lloyd_chunk_prepared(ops, carry, steps, *, tm: int, m: int,
                          tol: float):
    """Prepared-operand variant of :func:`_lloyd_chunk` (tier-'high'
    hoisted X split — see :func:`lloyd_step_prepared`)."""
    from raft_tpu.linalg.contractions import fused_lloyd_prepared
    from raft_tpu.runtime.compiled_driver import chunk_while

    def step(carry):
        def lloyd(c):
            sums, counts, dist, _ = fused_lloyd_prepared(
                ops, c, tm=tm, m=m)
            return _finish_update(sums, counts, c), jnp.sum(dist)

        return _lloyd_convergence_step(lloyd, carry, tol)

    return chunk_while(step, carry, steps)


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("n_clusters", "tol"),
                   donate_argnums=(2,))
def _weighted_lloyd_chunk(x, w, carry, steps, *, n_clusters: int,
                          tol: float):
    """Sample-weighted variant of :func:`_lloyd_chunk` (the
    :func:`weighted_lloyd_step` body in-graph)."""
    from raft_tpu.runtime.compiled_driver import chunk_while

    def step(carry):
        def lloyd(c):
            dist, labels = _assign(x, c)
            sums, counts, winertia = _weighted_sums(
                x, w, labels, dist, n_clusters)
            return _finish_update(sums, counts, c), winertia

        return _lloyd_convergence_step(lloyd, carry, tol)

    return chunk_while(step, carry, steps)


def _lloyd_sentinel(carry, steps_done: int):
    """Guard-mode boundary check for the compiled Lloyd chunks: after at
    least one completed iteration the carried inertia must be finite —
    a NaN/Inf here means the update diverged, surfaced as the typed
    error at the chunk boundary instead of NaN centroids at the end."""
    import numpy as np

    from raft_tpu.core.guards import NonFiniteError

    val = float(np.asarray(carry[1]))
    if steps_done > 0 and not np.isfinite(val):
        raise NonFiniteError(
            f"cluster.kmeans: non-finite inertia {val!r} at compiled "
            f"chunk boundary (iteration {steps_done})",
            op="cluster.kmeans_fit")


class _LazyHostMirror:
    """Deferred host copy of a device operand.

    The MNMG fit used to materialize ``np.asarray(x)`` unconditionally —
    a full extra dataset copy in host RSS — even though only a
    shrink/resume rebuild ever reads it. The copy now happens on first
    :meth:`get`; the common single-process fit never pays it."""

    def __init__(self, arr):
        self._arr = arr
        self._host = None

    @property
    def built(self) -> bool:
        return self._host is not None

    def get(self):
        if self._host is None:
            import numpy as np

            self._host = np.asarray(self._arr)
        return self._host


def _weighted_plus_plus(rng, cand, w, n_clusters: int):
    """Classic weighted k-means++ on the (small) candidate set — host-side
    numpy; candidate count is O(rounds · oversampling · k)."""
    import numpy as np

    ncand = cand.shape[0]
    centers = np.empty((n_clusters, cand.shape[1]), cand.dtype)
    first = rng.choice(ncand, p=w / w.sum())
    centers[0] = cand[first]
    d2 = np.sum((cand - centers[0][None, :]) ** 2, axis=1)
    for i in range(1, n_clusters):
        probs = w * d2
        total = probs.sum()
        if total <= 0:
            nxt = rng.choice(ncand)
        else:
            nxt = rng.choice(ncand, p=probs / total)
        centers[i] = cand[nxt]
        d2 = np.minimum(d2, np.sum((cand - cand[nxt][None, :]) ** 2, axis=1))
    return centers


@jax.jit
def _min_d2_update(x, new_pts, d2):
    d = (jnp.sum(x * x, 1, keepdims=True)
         - 2.0 * (x @ new_pts.T)
         + jnp.sum(new_pts * new_pts, 1)[None, :])
    return jnp.minimum(d2, jnp.min(d, axis=1))


def _kmeans_plus_plus(state: RngState, x, n_clusters: int,
                      oversampling_factor: float = 2.0,
                      sample_weights=None):
    """k-means|| seeding (Bahmani et al., the scalable k-means++): a few
    oversampled D²-Bernoulli rounds over the full data (each one fused
    device pass), then weighted k-means++ on the small candidate set.

    Replaces the naive k sequential D² draws — k full-dataset passes — with
    ~5 passes regardless of k."""
    import numpy as np

    m = x.shape[0]
    key = state.next_key()
    k0, key = jax.random.split(key)
    if sample_weights is None:
        first = int(jax.random.randint(k0, (), 0, m))
        wts = None
    else:
        # weighted first draw (ref/cuVS: sample_weight reaches the
        # init's D^2 sampling — zero-weight points are never seeds)
        wts = jnp.asarray(sample_weights, jnp.float32)
        first = int(jax.random.categorical(k0, jnp.log(
            jnp.maximum(wts, 1e-30))))
    cand = [np.asarray(x[first])[None, :]]
    d2 = jnp.sum((x - x[first][None, :]) ** 2, axis=1).astype(jnp.float32)
    ell = max(1.0, oversampling_factor * n_clusters)

    for _ in range(5):
        ki, key = jax.random.split(key)
        # d2 stays the PURE min-squared-distance; weights enter only the
        # sampling mass (probability ∝ w·D² — the reference's weighted
        # D² sampling), never the distance recurrence itself
        mass = d2 if wts is None else d2 * wts
        total = float(jnp.sum(mass))
        if total <= 0:
            break
        probs = jnp.minimum(1.0, ell * mass / total)
        picked = np.nonzero(
            np.asarray(jax.random.uniform(ki, (m,)) < probs))[0]
        if picked.size == 0:
            continue
        new_pts = x[jnp.asarray(picked)]
        cand.append(np.asarray(new_pts))
        # Pad the candidate batch to a power-of-two bucket (rows duplicated;
        # duplicates don't change the min) so _min_d2_update sees O(log)
        # distinct shapes across rounds instead of recompiling every round.
        bucket = 1 << (int(picked.size) - 1).bit_length()
        if bucket != picked.size:
            pad = jnp.broadcast_to(new_pts[:1],
                                   (bucket - picked.size, x.shape[1]))
            new_pts = jnp.concatenate([new_pts, pad], axis=0)
        d2 = _min_d2_update(x, new_pts, d2)

    cand_np = np.concatenate(cand, axis=0)
    rng = np.random.default_rng(int(jax.random.randint(
        key, (), 0, np.iinfo(np.int32).max)))
    if cand_np.shape[0] <= n_clusters:
        # degenerate: too few candidates — top up with random rows,
        # weighted so zero-weight points can never become seeds
        p = None
        if wts is not None:
            p = np.asarray(wts, np.float64)
            p = p / p.sum()
        extra = rng.choice(m, n_clusters - cand_np.shape[0] + 1,
                           replace=False, p=p)
        cand_np = np.concatenate([cand_np, np.asarray(x[jnp.asarray(extra)])])
    # weight candidates by how much (weighted) mass they serve
    _, labels = _assign(x, jnp.asarray(cand_np, x.dtype))
    w = np.bincount(
        np.asarray(labels), minlength=cand_np.shape[0],
        weights=None if wts is None else np.asarray(wts, np.float64)) \
        .astype(np.float64) + 1e-3
    centers = _weighted_plus_plus(rng, cand_np.astype(np.float64), w,
                                  n_clusters)
    return jnp.asarray(centers, x.dtype)


def _init_centroids(params: KMeansParams, state: RngState, x,
                    centroids: Optional[jnp.ndarray],
                    sample_weights=None):
    # An explicitly supplied centroid array always wins (warm start),
    # regardless of params.init — matching the reference's behavior where a
    # caller-provided centroids buffer with init=Array is the only way to
    # pass one and passing one implies using it.
    if centroids is not None:
        return jnp.asarray(centroids, x.dtype)
    if params.init == KMeansInit.ARRAY:
        raise ValueError("init=ARRAY requires centroids")
    if params.init == KMeansInit.RANDOM:
        idx = jax.random.choice(state.next_key(), x.shape[0],
                                (params.n_clusters,), replace=False)
        return x[idx]
    return _kmeans_plus_plus(state, x, params.n_clusters,
                             params.oversampling_factor,
                             sample_weights=sample_weights)


@with_matmul_precision
def _finish_report(converged: bool, n_iter: int, rel_change: float,
                   params: KMeansParams, strict: bool, op: str):
    """Shared convergence-report epilogue for the Lloyd fits: build the
    uniform :class:`~raft_tpu.core.guards.ConvergenceReport`, raise under
    ``strict`` or warn (matching the solver-layer contract of ISSUE 3)."""
    from raft_tpu.core.guards import ConvergenceError, ConvergenceReport

    report = ConvergenceReport(converged=converged, n_iter=int(n_iter),
                               residual=float(rel_change),
                               tol=float(params.tol))
    obs.record_convergence(op, report)
    if not converged:
        if strict:
            raise ConvergenceError(
                f"{op}: inertia change {rel_change:.3e} still above tol "
                f"{params.tol:.3e} after max_iter={params.max_iter} "
                "Lloyd iterations (strict=True)", report=report, op=op)
        logger.warn("%s: not converged after %d iterations (relative "
                    "inertia change %.3e > tol %.3e)", op, n_iter,
                    rel_change, params.tol)
    return report


def kmeans_fit(res, params: KMeansParams, x,
               centroids: Optional[jnp.ndarray] = None,
               sample_weights=None, strict: bool = False,
               return_report: bool = False,
               sync_every: Optional[int] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Lloyd's algorithm. Returns (centroids, inertia, labels, n_iter).

    Host-driven convergence loop around the jitted `lloyd_step` — the same
    structure as the reference lineage's host loop enqueueing fused kernels.

    ``sync_every``: with n > 1, chunks of n Lloyd iterations run as ONE
    jitted ``lax.while_loop`` with a donated carry and the convergence
    test in-graph; the host (and its deadline poll) is touched only at
    chunk boundaries (see :mod:`raft_tpu.runtime.compiled_driver`).
    ``sync_every=1`` IS the host-driven path above, bit-for-bit. The
    default ``None`` asks the cost model: 1 on CPU, 8–16 on an
    accelerator.

    ``sample_weights`` [m] (ref/cuVS parity: fit's ``sample_weight``):
    points contribute proportionally to the centroid update and the
    inertia; None (the default) is the unweighted fused-kernel hot path.

    Numerical guardrails (ISSUE 3): ``strict=True`` raises
    :class:`~raft_tpu.core.guards.ConvergenceError` when ``max_iter``
    elapses without the inertia stabilizing below ``tol``;
    ``return_report=True`` appends the
    :class:`~raft_tpu.core.guards.ConvergenceReport` to the return tuple.

    >>> import numpy as np
    >>> from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
    >>> x = np.concatenate([np.zeros((10, 2)), np.ones((10, 2)) * 9])
    >>> x = (x + np.linspace(0, .1, 20)[:, None]).astype(np.float32)
    >>> c, inertia, labels, n_iter = kmeans_fit(
    ...     None, KMeansParams(n_clusters=2, seed=0), x)
    >>> sorted(np.asarray(labels)[[0, 19]].tolist())   # two blobs split
    [0, 1]
    >>> bool(np.asarray(labels)[:10].std() == 0)
    True
    """
    import numpy as np

    from raft_tpu.runtime import limits
    from raft_tpu.util.input_validation import expect_2d, expect_finite

    x = jnp.asarray(x)
    expect_2d(x, name="kmeans_fit: x")
    expect_finite(x, name="kmeans_fit: x")
    w = None if sample_weights is None else jnp.asarray(sample_weights)
    if w is not None:
        _validate_sample_weights(w, x.shape[0])
    state = RngState(seed=params.seed)
    c = _init_centroids(params, state, x, centroids, sample_weights=w)
    prev_inertia = None
    n_iter = 0
    labels = None
    check = max(1, int(params.check_every))
    inertia = jnp.asarray(jnp.inf, x.dtype)
    converged = False
    rel_change = float("inf")
    # Hoist the loop-invariant X operand work (tier-'high' split + norms)
    # out of the Lloyd loop; (None, None) when the prepared path doesn't
    # apply and the plain step is used unchanged.
    from raft_tpu.linalg.contractions import lloyd_prepare

    ops, meta = (None, None) if w is not None \
        else lloyd_prepare(x, params.n_clusters)
    from raft_tpu.runtime import compiled_driver

    sync = compiled_driver.resolve_sync_every(sync_every)
    if sync > 1:
        # Compiled inner loop: sync_every iterations per launch, carry
        # donated, convergence tested in-graph — host syncs once per
        # chunk (deadline poll + slack recording ride the boundary).
        acc = compiled_driver.host_float_dtype()
        tol = float(params.tol)
        if ops is not None:
            chunk_call = functools.partial(_lloyd_chunk_prepared, ops,
                                           tol=tol, **meta)
        elif w is not None:
            chunk_call = functools.partial(
                _weighted_lloyd_chunk, x, w,
                n_clusters=params.n_clusters, tol=tol)
        else:
            chunk_call = functools.partial(
                _lloyd_chunk, x, n_clusters=params.n_clusters, tol=tol)
        dims = dict(m=int(x.shape[0]), k=int(x.shape[1]),
                    n_clusters=params.n_clusters,
                    itemsize=x.dtype.itemsize)
        est = limits.estimate_seconds("cluster.lloyd_step", **dims)
        sf, sb = limits.estimate_flops_bytes("cluster.lloyd_step",
                                             **dims)
        carry = (c, jnp.asarray(jnp.inf, acc), jnp.asarray(jnp.inf, acc))
        carry, n_iter, done = compiled_driver.run_chunked(
            chunk_call, carry, max_steps=params.max_iter,
            sync_every=sync, op="cluster.kmeans_fit",
            est_step_seconds=est, step_flops=sf, step_bytes=sb,
            sentinel=_lloyd_sentinel)
        c = carry[0]
        rel_change = float(np.asarray(carry[2]))
        converged = bool(done)
    elif ops is not None:
        # Prepared path: run each between-polls block of iterations as
        # ONE compiled scan (one launch per block instead of per step —
        # see lloyd_iterate_prepared). Identical iteration sequence and
        # poll points as the per-step loop below.
        n_iter = 0
        while n_iter < params.max_iter:
            limits.check_deadline("cluster.kmeans_fit")
            block = min(check, params.max_iter - n_iter)
            c, inertia, labels = lloyd_iterate_prepared(
                ops, c, block, **meta)
            n_iter += block
            if prev_inertia is not None:
                rel_change = abs(prev_inertia - float(inertia)) / \
                    max(prev_inertia, 1e-30)
                if rel_change <= params.tol:
                    converged = True
                    break
            prev_inertia = float(inertia)
    else:
        for n_iter in range(1, params.max_iter + 1):
            if w is None:
                c, inertia, labels = lloyd_step(x, c, params.n_clusters)
            else:
                c, inertia, labels = weighted_lloyd_step(
                    x, w, c, params.n_clusters)
            if n_iter % check and n_iter != params.max_iter:
                continue                 # no host sync between polls
            limits.check_deadline("cluster.kmeans_fit")
            if prev_inertia is not None:
                rel_change = abs(prev_inertia - float(inertia)) / \
                    max(prev_inertia, 1e-30)
                if rel_change <= params.tol:
                    converged = True
                    break
            prev_inertia = float(inertia)
    # lloyd_step's labels/inertia are measured against its *input* centroids;
    # re-assign ONCE so the returned triple is self-consistent (one pass
    # serves both labels and the [weighted] inertia).
    dist, labels = _assign(x, c)
    inertia = jnp.sum(dist) if w is None \
        else jnp.sum(dist * w.astype(dist.dtype))
    report = _finish_report(converged, n_iter, rel_change, params, strict,
                            op="cluster.kmeans_fit")
    if return_report:
        return c, inertia, labels, n_iter, report
    return c, inertia, labels, n_iter


@with_matmul_precision
@functools.partial(jax.jit, static_argnames=("n_clusters", "chunk_rows"),
                   donate_argnums=(2,))
def _minibatch_chunk(x, valid, carry, steps, *, n_clusters: int,
                     chunk_rows: int):
    """Up to ``steps`` Sculley mini-batch updates as one device program.

    Each step consumes one ``chunk_rows`` slice of the padded batch:
    nearest-centroid assignment (the same fused kernel the full fit
    uses), then the count-weighted running-mean update
    ``c += (sums - n_assigned·c) / counts_new`` — per-cluster learning
    rate 1/lifetime-count, so a cluster first touched this batch lands
    exactly on its batch mean and long-lived clusters move gently.
    ``valid`` zero-weights the pad rows (the :func:`_weighted_sums`
    contraction — scatter-free, R9's one-hot spelling)."""
    from raft_tpu.runtime.compiled_driver import chunk_while

    n_chunks = x.shape[0] // chunk_rows

    def step(carry):
        c, counts, j = carry
        # index pair must share j's dtype: a literal 0 promotes to
        # int64 under jax_enable_x64 and dynamic_slice rejects the mix
        rows = lax.dynamic_slice(
            x, (j * chunk_rows, jnp.zeros((), j.dtype)),
            (chunk_rows, x.shape[1]))
        vw = lax.dynamic_slice(valid, (j * chunk_rows,), (chunk_rows,))
        dist, labels = _assign(rows, c)
        sums, cnt, _ = _weighted_sums(rows, vw, labels, dist, n_clusters)
        new_counts = counts + cnt
        safe = jnp.where(new_counts > 0, new_counts, 1.0)
        cf = c.astype(jnp.float32)
        new_c = (cf + (sums - cnt[:, None] * cf)
                 / safe[:, None]).astype(c.dtype)
        return (new_c, new_counts, j + 1), (j + 1) >= n_chunks

    return chunk_while(step, carry, steps)


@with_matmul_precision
def kmeans_partial_fit(res, centroids, batch, *, counts=None,
                       chunk_rows: int = 256, sync_every=None,
                       checkpoint_dir: Optional[str] = None,
                       checkpoint_every: Optional[int] = None,
                       checkpoint_keep: int = 2,
                       resume_from: Optional[str] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One mini-batch k-means pass over ``batch`` (Sculley 2010): nudge
    ``centroids`` toward the stream without a full refit. Returns
    ``(new_centroids, new_counts)`` where ``counts`` is the float32
    per-cluster lifetime mass — thread it through successive calls so
    the per-cluster learning rate keeps decaying (``None`` starts cold:
    the first batch lands each touched cluster on its batch mean).

    The batch is consumed in ``chunk_rows`` slices through the
    compiled-driver chunk runner, so the streaming refit inherits the
    driver's checkpoint/deadline/trace boundary hooks for free — the
    ISSUE-17 drift loop calls this under a serving deadline and a
    mid-refit SIGKILL costs at most one chunk of progress.

    ``checkpoint_every`` (in boundary units; requires
    ``checkpoint_dir``) saves ``(centroids, counts, chunk)`` at chunk
    boundaries through :class:`~raft_tpu.core.checkpoint
    .CheckpointManager` (prefix ``kmeans_pf``, newest
    ``checkpoint_keep`` kept), and ``resume_from`` (a checkpoint file
    or directory) restarts mid-batch from the saved chunk cursor — the
    SAME ``batch`` must be passed again, since the cursor indexes into
    it (the streaming refit replays its reservoir, which recovery
    reconstructs exactly)."""
    from raft_tpu.runtime import compiled_driver, limits

    centroids = jnp.asarray(centroids)
    batch = jnp.asarray(batch)
    if centroids.ndim != 2:
        raise ValueError(f"centroids must be [k, d], got "
                         f"{centroids.shape}")
    if batch.ndim != 2 or batch.shape[1] != centroids.shape[1]:
        raise ValueError(f"batch must be [n, {centroids.shape[1]}], "
                         f"got {batch.shape}")
    if batch.shape[0] < 1:
        raise ValueError("batch must have at least one row")
    if chunk_rows < 1:
        raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
    n_clusters = int(centroids.shape[0])
    if counts is None:
        counts = jnp.zeros((n_clusters,), jnp.float32)
    else:
        counts = jnp.asarray(counts, jnp.float32)
        if counts.shape != (n_clusters,):
            raise ValueError(f"counts must be [{n_clusters}], got "
                             f"{counts.shape}")
    n = int(batch.shape[0])
    chunk_rows = min(int(chunk_rows), n)
    n_chunks = -(-n // chunk_rows)
    pad = n_chunks * chunk_rows - n
    valid = jnp.ones((n,), batch.dtype)
    if pad:
        batch = jnp.concatenate(
            [batch, jnp.zeros((pad, batch.shape[1]), batch.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), batch.dtype)])
    chunk_call = functools.partial(
        _minibatch_chunk, batch, valid, n_clusters=n_clusters,
        chunk_rows=chunk_rows)
    dims = dict(m=chunk_rows, k=int(batch.shape[1]),
                n_clusters=n_clusters, itemsize=batch.dtype.itemsize)
    est = limits.estimate_seconds("cluster.lloyd_step", **dims)
    sf, sb = limits.estimate_flops_bytes("cluster.lloyd_step", **dims)
    sync = compiled_driver.resolve_sync_every(sync_every)

    import numpy as np

    manager = None
    if checkpoint_every is not None:
        if checkpoint_dir is None:
            raise ValueError("checkpoint_every requires checkpoint_dir")
        from raft_tpu.core import checkpoint as core_ckpt

        manager = core_ckpt.CheckpointManager(
            checkpoint_dir, prefix="kmeans_pf", keep=checkpoint_keep)
    start_chunk = 0
    if resume_from is not None:
        entries = _load_kmeans_checkpoint(resume_from,
                                          prefix="kmeans_pf")
        centroids = jnp.asarray(np.asarray(entries["centroids"]),
                                centroids.dtype)
        counts = jnp.asarray(np.asarray(entries["counts"]),
                             jnp.float32)
        start_chunk = int(entries["chunk"])
        if start_chunk > n_chunks:
            raise ValueError(
                f"resume_from chunk {start_chunk} beyond this batch's "
                f"{n_chunks} chunks — pass the SAME batch the "
                "checkpoint was cut from")

    boundary = None
    if manager is not None:
        stride = sync * max(1, int(checkpoint_every))
        last_saved = [start_chunk if resume_from is not None else -1]

        def boundary(cr, steps_done, done_flag):
            if steps_done > 0 and (
                    steps_done - max(last_saved[0], 0) >= stride
                    or ((done_flag or steps_done >= n_chunks)
                        and steps_done != last_saved[0])):
                manager.save(steps_done, {
                    "centroids": np.asarray(cr[0]),
                    "counts": np.asarray(cr[1]),
                    "chunk": int(steps_done),
                })
                last_saved[0] = steps_done

    carry = (centroids, counts, jnp.asarray(start_chunk, jnp.int32))
    carry, n_steps, _ = compiled_driver.run_chunked(
        chunk_call, carry, max_steps=n_chunks, sync_every=sync,
        op="cluster.kmeans_partial_fit", steps_done=start_chunk,
        est_step_seconds=est, step_flops=sf, step_bytes=sb,
        boundary=boundary)
    trace.record_event("kmeans.partial_fit", rows=n,
                       n_clusters=n_clusters, chunks=int(n_steps),
                       chunk_rows=chunk_rows)
    new_c, new_counts, _ = carry
    return new_c, new_counts


@with_matmul_precision
def kmeans_predict(res, x, centroids):
    """Assignment only. Returns (labels, inertia)."""
    dist, labels = _assign(jnp.asarray(x), jnp.asarray(centroids))
    return labels, jnp.sum(dist)


@with_matmul_precision
def kmeans_transform(res, x, centroids):
    """Distance-to-centroid embedding [m, k]."""
    from raft_tpu.distance import pairwise_distance, DistanceType

    return pairwise_distance(res, x, centroids,
                             metric=DistanceType.L2SqrtExpanded)


@with_matmul_precision
def kmeans_fit_predict(res, params: KMeansParams, x,
                       centroids: Optional[jnp.ndarray] = None,
                       sample_weights=None, strict: bool = False,
                       return_report: bool = False):
    return kmeans_fit(
        res, params, x, centroids, sample_weights=sample_weights,
        strict=strict, return_report=return_report)


@with_matmul_precision
def cluster_cost(res, x, centroids):
    """Sum of squared distances of every point to its nearest centroid
    (cuVS/raft API parity: cluster::kmeans::cluster_cost). Same quantity
    kmeans_predict returns as its second value; exposed standalone for
    the reference's call shape."""
    dist, _ = _assign(jnp.asarray(x), jnp.asarray(centroids))
    return jnp.sum(dist)


# ---------------------------------------------------------------------------
# MNMG (multi-chip SPMD)
# ---------------------------------------------------------------------------


@with_matmul_precision
def mnmg_lloyd_step(x_shard, centroids, n_clusters: int,
                    data_axis: str = "data",
                    model_axis: Optional[str] = None,
                    w_shard=None):
    """One Lloyd iteration *inside* shard_map.

    x_shard: this shard's rows [m_local, k]. centroids: replicated [K, k]
    (or the local block [K/s, k] when ``model_axis`` shards the cluster
    dimension). Partial sums/counts ride a psum over the data axis — the
    reference's ncclAllReduce per iteration. ``w_shard`` [m_local]
    applies the reference's ``sample_weight`` semantics (weights shard
    with the rows; the psums aggregate weighted mass identically).
    """
    if model_axis is not None:
        # Local argmin over this model shard's centroid block, then combine
        # (min dist wins; ties to lower global index) across the model axis.
        kb = centroids.shape[0]
        mi = lax.axis_index(model_axis)
        dist, local_idx = _assign(x_shard, centroids)
        gidx = local_idx + mi * kb
        # min-reduce on the (dist, idx) pair: pack into a sortable key.
        best = lax.pmin(dist, model_axis)
        winner = jnp.where(dist == best, gidx, jnp.iinfo(jnp.int32).max)
        labels = lax.pmin(winner, model_axis)
        dist = best
        # Each model shard accumulates rows assigned to ITS block — a
        # one-hot contraction on the MXU (no scatter).
        in_block = (labels >= mi * kb) & (labels < (mi + 1) * kb)
        local_labels = jnp.where(in_block, labels - mi * kb, 0)
        oh = label_onehot(local_labels, kb, mask=in_block)
        if w_shard is not None:
            wf = w_shard.astype(jnp.float32)
            sums = _kernel_dot_exact_lhs(
                oh.T, x_shard.astype(jnp.float32) * wf[:, None])
            counts = oh.T @ wf
            inertia_local = jnp.sum(dist * wf)
        else:
            sums = _kernel_dot_exact_lhs(oh.T,
                                         x_shard.astype(jnp.float32))
            counts = jnp.sum(oh, axis=0)
            inertia_local = jnp.sum(dist)
        sums = lax.psum(sums, data_axis)
        counts = lax.psum(counts, data_axis)
        new_c = _finish_update(sums, counts, centroids)
        inertia = lax.psum(inertia_local, data_axis)
        return new_c, inertia, labels

    if w_shard is not None:
        dist, labels = _assign(x_shard, centroids)
        sums, counts, inertia_local = _weighted_sums(
            x_shard, w_shard, labels, dist, n_clusters)
    else:
        sums, counts, dist, labels = _lloyd_sums(x_shard, centroids)
        inertia_local = jnp.sum(dist)
    sums = lax.psum(sums, data_axis)            # ← the per-iter allreduce
    counts = lax.psum(counts, data_axis)
    new_c = _finish_update(sums, counts, centroids)
    inertia = lax.psum(inertia_local, data_axis)
    return new_c, inertia, labels


@with_matmul_precision
def kmeans_fit_mnmg(res, params: KMeansParams, x,
                    centroids: Optional[jnp.ndarray] = None,
                    mesh=None, data_axis: str = "data",
                    model_axis: Optional[str] = None,
                    sample_weights=None,
                    checkpoint_every: Optional[int] = None,
                    checkpoint_dir: Optional[str] = None,
                    checkpoint_keep: int = 2,
                    resume_from: Optional[str] = None,
                    strict: bool = False,
                    return_report: bool = False,
                    sync_every: Optional[int] = None):
    """MNMG Lloyd over a row-partitioned dataset (ref workload: raft-dask
    MNMG k-means; BASELINE config 5).

    ``sync_every``: with n > 1, the per-iteration ``shard_map`` launch
    becomes ONE program per n iterations — a ``lax.while_loop`` INSIDE
    the shard_map body, so the per-iteration ``lax.psum`` epilogues and
    the convergence test fuse in-graph and the host is touched once per
    chunk. The checkpoint hook, comms health probe and deadline poll all
    move to the chunk boundary (same checkpoint-before-probe-before-poll
    ordering as the host loop, so expiry still leaves a resumable file).
    ``sync_every=1`` (and the CPU default) is the host-driven loop below,
    bit-for-bit; the host-mailbox :func:`kmeans_fit_elastic` stays the
    rank-death-tolerant fallback, unchanged.

    x: global [m, k] array (sharded or to-be-sharded along rows over
    ``data_axis``). Returns (centroids, inertia, labels, n_iter).

    ``model_axis`` (2-D mesh): centroid BLOCKS are sharded over it —
    each model shard scans only its n_clusters/s block, the global
    argmin combines via paired pmins, and the per-block one-hot update
    psums over ``data_axis`` only (see :func:`mnmg_lloyd_step`). This is
    the k≫VMEM regime the reference reaches with multi-GPU cluster
    splits; requires n_clusters divisible by the model-axis size.

    Elastic execution (ISSUE 2): ``checkpoint_every=n`` saves solver
    state (centroids, previous inertia, iteration, RNG) every n-th poll
    boundary into ``checkpoint_dir`` (atomic, CRC-checked — see
    :mod:`raft_tpu.core.checkpoint`); ``resume_from`` starts from a
    checkpoint file or the newest checkpoint in a directory.  When the
    handle carries a :class:`~raft_tpu.comms.comms.MeshComms`, each
    poll boundary also health-checks the clique; on a peer failure the
    survivors run ``agree_on_survivors`` → ``shrink``, the data is
    re-sharded over the survivor mesh, the last checkpoint is reloaded,
    and the fit FINISHES on fewer ranks.  Resuming a checkpoint on the
    same mesh replays bit-for-bit: iterations between the checkpoint
    and the failure are re-run, never trusted from the failed epoch.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.core import checkpoint as core_ckpt
    from raft_tpu.core import resources as core_res
    from raft_tpu.comms.errors import CommsAbortedError, PeerFailedError
    from raft_tpu.runtime import limits
    from raft_tpu.util.input_validation import expect_2d, expect_finite

    import numpy as np

    x = jnp.asarray(x)
    expect_2d(x, name="kmeans_fit_mnmg: x")
    expect_finite(x, name="kmeans_fit_mnmg: x")
    w = None if sample_weights is None else jnp.asarray(sample_weights)
    if w is not None:
        _validate_sample_weights(w, x.shape[0])
    if mesh is None:
        mesh = core_res.get_mesh(core_res.default_resources(res))
    # validate the sharding config BEFORE the (expensive) k-means|| seeding
    if model_axis is not None:
        ms = mesh.shape[model_axis]
        if params.n_clusters % ms:
            raise ValueError(
                f"n_clusters={params.n_clusters} not divisible by "
                f"model axis {model_axis!r} size {ms}")
        c_spec = P(model_axis)
    else:
        c_spec = P()

    comms = None
    handle = core_res.default_resources(res)
    if core_res.comms_initialized(handle):
        comms = core_res.get_comms(handle)
    manager = None
    if checkpoint_every is not None:
        if checkpoint_dir is None:
            raise ValueError("checkpoint_every requires checkpoint_dir")
        manager = core_ckpt.CheckpointManager(checkpoint_dir,
                                              prefix="kmeans",
                                              keep=checkpoint_keep)

    # Host mirrors survive any mesh (a shrink re-places them over the
    # survivor devices) — but only a shrink/resume rebuild ever reads
    # them, so they are LAZY: the common single-process fit never pays
    # the extra dataset copy in host RSS.
    x_mirror = _LazyHostMirror(x)
    w_mirror = None if w is None else _LazyHostMirror(w)

    from raft_tpu.runtime import compiled_driver

    sync = compiled_driver.resolve_sync_every(sync_every)

    state = RngState(seed=params.seed)
    prev = None
    start_iter = 0
    if resume_from is not None:
        entries = _load_kmeans_checkpoint(resume_from)
        c_init = jnp.asarray(entries["centroids"])
        start_iter = int(entries["n_iter"])
        prev = entries["prev_inertia"]
        if prev is not None and not np.isfinite(prev):
            prev = None
        state = entries.get("rng", state)
    else:
        c_init = _init_centroids(params, state, x, centroids,
                                 sample_weights=w)

    per_shard_k = (params.n_clusters if model_axis is None
                   else params.n_clusters // mesh.shape[model_axis])

    def build_run(cur_mesh, c_host, from_host: bool = False):
        """(Re)build the jitted step over ``cur_mesh`` and place the
        data + centroids on it; returns (run, centroids_on_device,
        run_chunk). ``from_host=True`` re-places from the lazy host
        mirrors — the shrink/resume rebuild path, the only consumer of
        the host copies. ``run_chunk`` is the compiled chunk program
        (None when ``sync_every <= 1``)."""
        xd = jax.device_put(
            jnp.asarray(x_mirror.get()) if from_host else x,
            NamedSharding(cur_mesh, P(data_axis)))
        cd = jax.device_put(jnp.asarray(c_host),
                            NamedSharding(cur_mesh, c_spec))
        wd = None
        if w is not None:
            wd = jax.device_put(
                jnp.asarray(w_mirror.get()) if from_host else w,
                NamedSharding(cur_mesh, P(data_axis)))
        # per-shard cluster count: the model-axis branch derives its
        # block from the sharded centroids' shape, but the WEIGHTED
        # data-parallel branch uses n_clusters as the one-hot width —
        # it must be the per-shard truth
        step_fn = functools.partial(
            mnmg_lloyd_step, n_clusters=per_shard_k,
            data_axis=data_axis, model_axis=model_axis)
        if wd is None:
            in_specs = (P(data_axis), c_spec)
            body = step_fn
        else:
            in_specs = (P(data_axis), c_spec, P(data_axis))
            body = lambda xs, cs, ws: step_fn(xs, cs, w_shard=ws)  # noqa: E731
        step = jax.jit(jax.shard_map(
            body, mesh=cur_mesh, in_specs=in_specs,
            out_specs=(c_spec, P(), P(data_axis))))

        def run(cc):
            args = (xd, cc) if wd is None else (xd, cc, wd)
            return step(*args)

        if sync <= 1:
            return run, cd, None

        # Compiled chunk: the while_loop sits INSIDE the shard_map body,
        # so the per-iteration psums fuse into one program and XLA
        # schedules the collectives across iterations. The carry's
        # convergence scalars are psum products — replicated, so the
        # P() specs hold.
        from raft_tpu.runtime.compiled_driver import chunk_while

        tol = float(params.tol)
        carry_specs = (c_spec, P(), P())
        if wd is None:
            def chunk_body(xs, carry, steps):
                def one(car):
                    return _lloyd_convergence_step(
                        lambda cc: step_fn(xs, cc)[:2], car, tol)

                return chunk_while(one, carry, steps)

            chunk_in = (P(data_axis), carry_specs, P())
            donate = 1
        else:
            def chunk_body(xs, ws, carry, steps):
                def one(car):
                    return _lloyd_convergence_step(
                        lambda cc: step_fn(xs, cc, w_shard=ws)[:2],
                        car, tol)

                return chunk_while(one, carry, steps)

            chunk_in = (P(data_axis), P(data_axis), carry_specs, P())
            donate = 2
        chunk = jax.jit(jax.shard_map(
            chunk_body, mesh=cur_mesh, in_specs=chunk_in,
            out_specs=(carry_specs, P(), P())),
            donate_argnums=(donate,))

        def run_chunk(carry, steps):
            args = ((xd, carry, steps) if wd is None
                    else (xd, wd, carry, steps))
            return chunk(*args)

        return run, cd, run_chunk

    run, c, run_chunk = build_run(mesh, c_init)
    n_iter = start_iter
    check = max(1, int(params.check_every))
    ckpt_stride = (None if manager is None
                   else check * max(1, int(checkpoint_every)))
    inertia = jnp.asarray(0.0)
    labels = None
    converged = False
    rel_change = float("inf")
    if sync > 1:
        # Compiled path: every robustness hook fires at chunk
        # boundaries via run_chunked — checkpoint then health probe
        # (the boundary closure, same ordering as the host loop below)
        # then the deadline poll, so expiry always leaves a resumable
        # file and a peer failure recovers from the newest boundary.
        acc = compiled_driver.host_float_dtype()
        chunk_stride = (None if manager is None
                        else sync * max(1, int(checkpoint_every)))
        dims = dict(m=-(-int(x.shape[0]) // mesh.shape[data_axis]),
                    k=int(x.shape[1]), n_clusters=params.n_clusters,
                    itemsize=x.dtype.itemsize)
        est = limits.estimate_seconds("cluster.lloyd_step", **dims)
        sf, sb = limits.estimate_flops_bytes("cluster.lloyd_step",
                                             **dims)
        carry = (c,
                 jnp.asarray(np.inf if prev is None else prev, acc),
                 jnp.asarray(np.inf, acc))
        last_saved = [start_iter if resume_from is not None else -1]

        def boundary(cr, steps_done, done_flag):
            if chunk_stride is not None and steps_done > 0 and (
                    steps_done - max(last_saved[0], 0) >= chunk_stride
                    or ((done_flag or steps_done >= params.max_iter)
                        and steps_done != last_saved[0])):
                manager.save(steps_done, {
                    "centroids": np.asarray(cr[0]),
                    "prev_inertia": float(np.asarray(cr[1])),
                    "n_iter": int(steps_done),
                    "rng": state,
                })
                last_saved[0] = steps_done
            if comms is not None:
                comms.ensure_healthy()

        while True:
            try:
                carry, n_iter, conv = compiled_driver.run_chunked(
                    run_chunk, carry, max_steps=params.max_iter,
                    sync_every=sync, op="cluster.kmeans_fit_mnmg",
                    steps_done=n_iter, est_step_seconds=est,
                    step_flops=sf, step_bytes=sb,
                    boundary=boundary, sentinel=_lloyd_sentinel)
                converged = bool(conv)
                c = carry[0]
                rel_change = float(np.asarray(carry[2]))
                break
            except (PeerFailedError, CommsAbortedError) as e:
                if comms is None or manager is None:
                    raise
                latest = manager.restore_latest()
                if latest is None:
                    raise
                logger.warn("kmeans_fit_mnmg: clique failure at "
                            "iteration %d (%r); recovering on "
                            "survivors", n_iter, e)
                survivors = comms.agree_on_survivors()
                comms = comms.shrink(survivors)
                core_res.set_comms(handle, comms)
                mesh = comms.mesh
                step_at, entries = latest
                state = entries.get("rng", state)
                run, c, run_chunk = build_run(
                    mesh, entries["centroids"], from_host=True)
                n_iter = int(entries["n_iter"])
                last_saved[0] = n_iter
                carry = (c,
                         jnp.asarray(entries["prev_inertia"], acc),
                         jnp.asarray(np.inf, acc))
                trace.record_event("kmeans.elastic_resume",
                                   iteration=n_iter,
                                   checkpoint_step=step_at,
                                   survivors=tuple(survivors))
    else:
        while n_iter < params.max_iter:
            try:
                converged = False
                for n_iter in range(n_iter + 1, params.max_iter + 1):
                    c, inertia, labels = run(c)
                    if n_iter % check and n_iter != params.max_iter:
                        continue         # no host sync between polls
                    # checkpoint BEFORE the health probe: recovery
                    # resumes from this very boundary, re-running
                    # nothing older
                    if ckpt_stride is not None and (
                            n_iter % ckpt_stride == 0
                            or n_iter == params.max_iter):
                        manager.save(n_iter, {
                            "centroids": np.asarray(c),
                            "prev_inertia": (float("inf") if prev is None
                                             else float(prev)),
                            "n_iter": int(n_iter),
                            "rng": state,
                        })
                    if comms is not None:
                        comms.ensure_healthy()
                    # deadline poll after checkpoint + health probe: an
                    # expiring budget leaves the checkpoint resumable,
                    # and DeadlineExceededError is NOT a clique failure
                    # — it propagates past the elastic handler below
                    limits.check_deadline("cluster.kmeans_fit_mnmg")
                    if prev is not None:
                        rel_change = abs(prev - float(inertia)) / \
                            max(prev, 1e-30)
                        if rel_change <= params.tol:
                            converged = True
                            break
                    prev = float(inertia)
                if converged or n_iter >= params.max_iter:
                    break
            except (PeerFailedError, CommsAbortedError) as e:
                if comms is None or manager is None:
                    raise
                latest = manager.restore_latest()
                if latest is None:
                    raise
                logger.warn("kmeans_fit_mnmg: clique failure at "
                            "iteration %d (%r); recovering on "
                            "survivors", n_iter, e)
                survivors = comms.agree_on_survivors()
                comms = comms.shrink(survivors)
                core_res.set_comms(handle, comms)
                mesh = comms.mesh
                step_at, entries = latest
                prev = entries["prev_inertia"]
                if not np.isfinite(prev):
                    prev = None
                state = entries.get("rng", state)
                run, c, _ = build_run(mesh, entries["centroids"],
                                      from_host=True)
                n_iter = int(entries["n_iter"])
                trace.record_event("kmeans.elastic_resume",
                                   iteration=n_iter,
                                   checkpoint_step=step_at,
                                   survivors=tuple(survivors))
    # re-assign against the FINAL centroids for a self-consistent return:
    # one more step gives labels + inertia vs c (its centroid update is
    # discarded) — works identically on 1-D and 2-D meshes
    _, inertia, labels = run(c)
    report = _finish_report(converged, n_iter, rel_change, params, strict,
                            op="cluster.kmeans_fit_mnmg")
    if return_report:
        return c, inertia, labels, n_iter, report
    return c, inertia, labels, n_iter


def _load_kmeans_checkpoint(resume_from: str, prefix: str = "kmeans"):
    """Resolve ``resume_from`` (a checkpoint file, or a directory whose
    newest checkpoint wins) to its entry dict."""
    import os

    from raft_tpu.core import checkpoint as core_ckpt

    if os.path.isdir(resume_from):
        latest = core_ckpt.CheckpointManager(
            resume_from, prefix=prefix).restore_latest()
        if latest is None:
            raise FileNotFoundError(
                f"no {prefix} checkpoints in {resume_from!r}")
        return latest[1]
    return core_ckpt.restore_checkpoint(resume_from)


def kmeans_fit_elastic(comms, params: KMeansParams, x,
                       sample_weights=None,
                       checkpoint_every: Optional[int] = None,
                       checkpoint_dir: Optional[str] = None,
                       checkpoint_keep: int = 2,
                       resume_from: Optional[str] = None,
                       on_iteration=None):
    """Host-driven elastic Lloyd: MNMG k-means that survives rank DEATH
    (ISSUE 2 acceptance: one SIGKILL'd rank, survivors finish).

    :func:`kmeans_fit_mnmg` reduces with device ``psum`` over a global
    mesh — a collective that can never complete once a participating
    *process* is gone.  This variant keeps the reduction on the host
    mailbox (:meth:`MeshComms.host_allreduce`), which the failure
    detector, abort propagation and ``shrink`` all understand, so a
    killed rank costs one recovery round instead of the job: the first
    rank to notice aborts the clique (waking every blocked peer within
    a heartbeat), survivors quiesce → ``agree_on_survivors`` →
    ``shrink``, re-partition the rows over the new clique size, reload
    the newest checkpoint and continue.

    Every rank passes the SAME full ``x``; rank r computes partials for
    its contiguous row block (boundaries a pure function of (n_rows,
    size, rank)).  Determinism is structural — fixed
    partition, float64 host accumulation, rank-ascending reduction
    order in ``host_allreduce`` — so a post-failure run on m survivors
    is bit-for-bit equal to a clean m-rank run resumed from the same
    checkpoint.

    ``on_iteration(it, centroids)`` is a test/chaos hook fired after
    every update (the SIGKILL suite uses it to kill a rank mid-run).
    Returns ``(centroids [k, d] float64, inertia, n_iter, comms)`` —
    the returned clique is the LIVE one (post-shrink after a recovery;
    the caller's original handle is stale once a peer has died).
    """
    import time as _time

    from raft_tpu.comms.errors import CommsAbortedError, PeerFailedError
    from raft_tpu.core import checkpoint as core_ckpt
    from raft_tpu.runtime import limits

    import numpy as np

    x = np.asarray(x, np.float64)
    n, d = x.shape
    k = int(params.n_clusters)
    if k <= 0 or k > n:
        raise ValueError(f"need 0 < n_clusters <= n_rows, got {k} vs {n}")
    w = (np.ones(n, np.float64) if sample_weights is None
         else np.asarray(sample_weights, np.float64))
    _validate_sample_weights(w, n)
    manager = None
    if checkpoint_every is not None:
        if checkpoint_dir is None:
            raise ValueError("checkpoint_every requires checkpoint_dir")
        manager = core_ckpt.CheckpointManager(checkpoint_dir,
                                              prefix="kmeans_host",
                                              keep=checkpoint_keep)

    if resume_from is not None:
        entries = _load_kmeans_checkpoint(resume_from, prefix="kmeans_host")
        c = np.asarray(entries["centroids"], np.float64)
        it = int(entries["n_iter"])
    else:
        rng = np.random.default_rng(params.seed)
        c = x[np.sort(rng.choice(n, size=k, replace=False))].copy()
        it = 0

    inertia = float("inf")
    stride = max(1, int(checkpoint_every)) if checkpoint_every else None
    while it < params.max_iter:
        try:
            while it < params.max_iter:
                # per-iteration poll (the allreduce below is ALSO
                # deadline-capped through TagStore.get, so a rank
                # blocked mid-collective still observes the budget)
                limits.check_deadline("cluster.kmeans_fit_elastic")
                it += 1
                size, rank = comms.get_size(), comms.get_rank()
                bounds = np.linspace(0, n, size + 1).astype(np.int64)
                lo, hi = int(bounds[rank]), int(bounds[rank + 1])
                xs, ws = x[lo:hi], w[lo:hi]
                labels, sums, counts, best = host_assign_update(
                    xs, ws, c)
                buf = np.concatenate(
                    [sums.ravel(), counts, [float((best * ws).sum())]])
                tot = comms.host_allreduce(buf, tag=2 * it)
                gsums = tot[:k * d].reshape(k, d)
                gcounts = tot[k * d:k * d + k]
                inertia = float(tot[-1])
                new_c = np.where(gcounts[:, None] > 0,
                                 gsums / np.maximum(gcounts, 1.0)[:, None],
                                 c)
                shift = float(np.abs(new_c - c).max())
                c = new_c
                if on_iteration is not None:
                    on_iteration(it, c)
                converged = shift <= params.tol
                done = converged or it >= params.max_iter
                if stride is not None and it % stride == 0:
                    # rank 0 of the CURRENT clique owns the checkpoint
                    # files; save precedes the health probe so recovery
                    # resumes from exactly this boundary
                    if rank == 0:
                        manager.save(it, {"centroids": c,
                                          "n_iter": int(it),
                                          "prev_inertia": inertia})
                    # the probe protects the NEXT allreduce; on the last
                    # iteration peers may already have returned and
                    # closed — their goodbye must not read as a failure
                    if not done:
                        comms.ensure_healthy()
                if converged:
                    return c, inertia, it, comms
            return c, inertia, it, comms
        except (PeerFailedError, CommsAbortedError) as e:
            if manager is None:
                raise
            if isinstance(e, PeerFailedError):
                # first detector: poison the clique so peers blocked in
                # the allreduce wake NOW instead of at their own timeout
                comms.abort(f"kmeans_fit_elastic: {e}")
            # quiesce: concurrent detectors send their own aborts within
            # ~one heartbeat of the first; outlive them before clearing
            # so no stray poison frame lands mid-consensus
            _time.sleep(2.0 * comms.heartbeat_interval)
            comms.clear_abort()
            survivors = comms.agree_on_survivors()
            comms = comms.shrink(survivors)
            latest = manager.restore_latest()
            if latest is None:
                raise
            step_at, entries = latest
            c = np.asarray(entries["centroids"], np.float64)
            it = int(entries["n_iter"])
            logger.warn("kmeans_fit_elastic: clique failure (%r); resuming "
                        "iteration %d on %d survivors", e, it,
                        len(survivors))
            trace.record_event("kmeans.elastic_host_resume",
                               checkpoint_step=step_at, iteration=it,
                               survivors=tuple(survivors))
    return c, inertia, it, comms
