"""k-means (Lloyd) on the fused contraction kernel, single-chip and MNMG.

Rebuilt from primitives per the BASELINE north star (the algorithm layer
moved from the reference to cuVS; its building blocks — the contractions
engine, segment reductions, comms allreduce — are the layers below):

- assignment: `fused_l2_argmin_pallas` (raft_tpu.linalg.contractions) — one
  MXU contraction per (row-tile × centroid-tile), no m×n matrix in HBM.
- update: `segment_sum` over assignments (raft_tpu.linalg.reduce analogue
  of reduce_rows_by_key).
- MNMG: rows partitioned across the mesh's data axis (the reference's
  row-partitioned convention, docs/source/using_raft_comms.rst); per-shard
  partial sums/counts combined with `psum` — the NCCL allreduce of the
  reference's MNMG k-means, riding ICI.

The MNMG step also supports a model axis: centroids sharded over a second
mesh axis, each shard computing a local argmin over its centroid block and
the global argmin combined with a min-reduce over (dist, idx) pairs — the
TPU expression of the reference's "distribute the k dimension" scaling.
"""

from __future__ import annotations

import dataclasses
import enum
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.linalg.contractions import fused_l2_argmin_pallas
from raft_tpu.random.rng_state import RngState


class KMeansInit(enum.Enum):
    """Initialization methods (lineage: cuvs::cluster::kmeans::params)."""

    KMEANS_PLUS_PLUS = "kmeans++"
    RANDOM = "random"
    ARRAY = "array"  # caller-supplied centroids


@dataclasses.dataclass
class KMeansParams:
    """Hyper-parameters (lineage: cuvs kmeans params / sklearn vocabulary)."""

    n_clusters: int = 8
    max_iter: int = 300
    tol: float = 1e-4
    init: KMeansInit = KMeansInit.KMEANS_PLUS_PLUS
    oversampling_factor: float = 2.0
    seed: int = 0


# ---------------------------------------------------------------------------
# single-chip
# ---------------------------------------------------------------------------


def _assign(x, centroids):
    """Nearest-centroid assignment via the fused Pallas kernel."""
    if x.dtype in (jnp.float32, jnp.bfloat16):
        return fused_l2_argmin_pallas(x, centroids)
    d = (jnp.sum(x * x, 1, keepdims=True) - 2.0 * (x @ centroids.T)
         + jnp.sum(centroids * centroids, 1)[None, :])
    return jnp.min(d, 1), jnp.argmin(d, 1).astype(jnp.int32)


def _update(x, labels, n_clusters, old_centroids):
    """Centroid update: segment mean with empty-cluster carry-over."""
    sums = jax.ops.segment_sum(x, labels, num_segments=n_clusters)
    counts = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), labels,
                                 num_segments=n_clusters)
    safe = jnp.maximum(counts, 1.0)[:, None]
    new = sums / safe
    return jnp.where(counts[:, None] > 0, new, old_centroids), counts


@functools.partial(jax.jit, static_argnames=("n_clusters",))
def lloyd_step(x, centroids, n_clusters: int):
    """One Lloyd iteration: returns (new_centroids, inertia, labels).

    This is the jittable hot step (the flagship forward step for the
    driver's compile check).
    """
    dist, labels = _assign(x, centroids)
    new_centroids, _ = _update(x, labels, n_clusters, centroids)
    return new_centroids, jnp.sum(dist), labels


def _kmeans_plus_plus(state: RngState, x, n_clusters: int):
    """k-means++ seeding (scalable variant of Arthur & Vassilvitskii):
    greedy D² sampling with one fused-argmin pass per chosen center."""
    m = x.shape[0]
    key = state.next_key()
    k0, key = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, m)
    centroids = jnp.zeros((n_clusters, x.shape[1]), x.dtype)
    centroids = centroids.at[0].set(x[first])

    d2 = jnp.sum((x - centroids[0][None, :]) ** 2, axis=1)
    for i in range(1, n_clusters):
        ki, key = jax.random.split(key)
        probs = d2 / jnp.maximum(jnp.sum(d2), 1e-12)
        nxt = jax.random.choice(ki, m, p=probs)
        centroids = centroids.at[i].set(x[nxt])
        d2 = jnp.minimum(d2, jnp.sum((x - x[nxt][None, :]) ** 2, axis=1))
    return centroids


def _init_centroids(params: KMeansParams, state: RngState, x,
                    centroids: Optional[jnp.ndarray]):
    if params.init == KMeansInit.ARRAY:
        if centroids is None:
            raise ValueError("init=ARRAY requires centroids")
        return jnp.asarray(centroids, x.dtype)
    if params.init == KMeansInit.RANDOM:
        idx = jax.random.choice(state.next_key(), x.shape[0],
                                (params.n_clusters,), replace=False)
        return x[idx]
    return _kmeans_plus_plus(state, x, params.n_clusters)


def kmeans_fit(res, params: KMeansParams, x,
               centroids: Optional[jnp.ndarray] = None
               ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, int]:
    """Lloyd's algorithm. Returns (centroids, inertia, labels, n_iter).

    Host-driven convergence loop around the jitted `lloyd_step` — the same
    structure as the reference lineage's host loop enqueueing fused kernels.
    """
    x = jnp.asarray(x)
    state = RngState(seed=params.seed)
    c = _init_centroids(params, state, x, centroids)
    prev_inertia = None
    n_iter = 0
    labels = None
    inertia = jnp.asarray(jnp.inf, x.dtype)
    for n_iter in range(1, params.max_iter + 1):
        c, inertia, labels = lloyd_step(x, c, params.n_clusters)
        if prev_inertia is not None and \
                abs(prev_inertia - float(inertia)) <= \
                params.tol * max(prev_inertia, 1e-30):
            break
        prev_inertia = float(inertia)
    return c, inertia, labels, n_iter


def kmeans_predict(res, x, centroids):
    """Assignment only. Returns (labels, inertia)."""
    dist, labels = _assign(jnp.asarray(x), jnp.asarray(centroids))
    return labels, jnp.sum(dist)


def kmeans_transform(res, x, centroids):
    """Distance-to-centroid embedding [m, k]."""
    from raft_tpu.distance import pairwise_distance, DistanceType

    return pairwise_distance(res, x, centroids,
                             metric=DistanceType.L2SqrtExpanded)


def kmeans_fit_predict(res, params: KMeansParams, x,
                       centroids: Optional[jnp.ndarray] = None):
    c, inertia, labels, n_iter = kmeans_fit(res, params, x, centroids)
    return c, inertia, labels, n_iter


# ---------------------------------------------------------------------------
# MNMG (multi-chip SPMD)
# ---------------------------------------------------------------------------


def mnmg_lloyd_step(x_shard, centroids, n_clusters: int,
                    data_axis: str = "data",
                    model_axis: Optional[str] = None):
    """One Lloyd iteration *inside* shard_map.

    x_shard: this shard's rows [m_local, k]. centroids: replicated [K, k]
    (or the local block [K/s, k] when ``model_axis`` shards the cluster
    dimension). Partial sums/counts ride a psum over the data axis — the
    reference's ncclAllReduce per iteration.
    """
    if model_axis is not None:
        # Local argmin over this model shard's centroid block, then combine
        # (min dist wins; ties to lower global index) across the model axis.
        kb = centroids.shape[0]
        mi = lax.axis_index(model_axis)
        dist, local_idx = _assign(x_shard, centroids)
        gidx = local_idx + mi * kb
        # min-reduce on the (dist, idx) pair: pack into a sortable key.
        best = lax.pmin(dist, model_axis)
        winner = jnp.where(dist == best, gidx, jnp.iinfo(jnp.int32).max)
        labels = lax.pmin(winner, model_axis)
        dist = best
        # Each model shard accumulates rows assigned to ITS block.
        in_block = (labels >= mi * kb) & (labels < (mi + 1) * kb)
        local_labels = jnp.where(in_block, labels - mi * kb, 0)
        w = in_block.astype(x_shard.dtype)
        sums = jax.ops.segment_sum(x_shard * w[:, None], local_labels,
                                   num_segments=kb)
        counts = jax.ops.segment_sum(w, local_labels, num_segments=kb)
        sums = lax.psum(sums, data_axis)
        counts = lax.psum(counts, data_axis)
        safe = jnp.maximum(counts, 1.0)[:, None]
        new_c = jnp.where(counts[:, None] > 0, sums / safe, centroids)
        inertia = lax.psum(jnp.sum(dist), data_axis)
        return new_c, inertia, labels

    dist, labels = _assign(x_shard, centroids)
    sums = jax.ops.segment_sum(x_shard, labels, num_segments=n_clusters)
    counts = jax.ops.segment_sum(
        jnp.ones((x_shard.shape[0],), x_shard.dtype), labels,
        num_segments=n_clusters)
    sums = lax.psum(sums, data_axis)            # ← the per-iter allreduce
    counts = lax.psum(counts, data_axis)
    safe = jnp.maximum(counts, 1.0)[:, None]
    new_c = jnp.where(counts[:, None] > 0, sums / safe, centroids)
    inertia = lax.psum(jnp.sum(dist), data_axis)
    return new_c, inertia, labels


def kmeans_fit_mnmg(res, params: KMeansParams, x,
                    centroids: Optional[jnp.ndarray] = None,
                    mesh=None, data_axis: str = "data"):
    """MNMG Lloyd over a row-partitioned dataset (ref workload: raft-dask
    MNMG k-means; BASELINE config 5).

    x: global [m, k] array (sharded or to-be-sharded along rows over
    ``data_axis``). Returns (centroids, inertia, labels, n_iter).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.core import resources as core_res

    x = jnp.asarray(x)
    if mesh is None:
        mesh = core_res.get_mesh(core_res.default_resources(res))
    state = RngState(seed=params.seed)
    if centroids is None:
        idx = jax.random.choice(state.next_key(), x.shape[0],
                                (params.n_clusters,), replace=False)
        c = x[idx]
    else:
        c = jnp.asarray(centroids, x.dtype)

    x = jax.device_put(x, NamedSharding(mesh, P(data_axis)))
    c = jax.device_put(c, NamedSharding(mesh, P()))

    step = jax.jit(
        jax.shard_map(
            functools.partial(mnmg_lloyd_step, n_clusters=params.n_clusters,
                              data_axis=data_axis),
            mesh=mesh,
            in_specs=(P(data_axis), P()),
            out_specs=(P(), P(), P(data_axis)),
            # Pallas calls don't carry varying-mesh-axis metadata yet.
            check_vma=False,
        ))

    prev = None
    n_iter = 0
    labels = None
    inertia = jnp.asarray(jnp.inf, x.dtype)
    for n_iter in range(1, params.max_iter + 1):
        c, inertia, labels = step(x, c)
        if prev is not None and abs(prev - float(inertia)) <= \
                params.tol * max(prev, 1e-30):
            break
        prev = float(inertia)
    return c, inertia, labels, n_iter
