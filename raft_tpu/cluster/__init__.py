"""Clustering rebuilt from primitives (the BASELINE north-star workload).

The reference's k-means moved to cuVS (SURVEY.md preamble); per the north
star it is rebuilt here from the primitive layers exactly as cuVS builds it:
fused L2+argmin contraction kernel (assignment), segment-sum (update),
comms allreduce (MNMG).
"""

from raft_tpu.cluster.kmeans import (  # noqa: F401
    KMeansParams,
    KMeansInit,
    kmeans_fit,
    kmeans_predict,
    kmeans_transform,
    kmeans_fit_predict,
    cluster_cost,
    lloyd_step,
    weighted_lloyd_step,
    mnmg_lloyd_step,
    kmeans_fit_mnmg,
    kmeans_fit_elastic,
)
