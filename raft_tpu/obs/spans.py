"""Structured spans (ISSUE 4 tentpole part 2): named host-side regions
with monotonic start/duration, parented off the existing
:mod:`raft_tpu.core.trace` range stack.

``trace.push_range`` is the NVTX analogue — it marks a region for Xprof
but records nothing the host can query afterwards. A span is the
recorded counterpart: entering one pushes the name onto the same
thread-local range stack (so nested ranges, spans, and
``trace.record_event`` events all attribute consistently), and exiting
appends a completed-span record to a bounded in-memory ring and to the
JSONL sink when one is attached (:mod:`raft_tpu.obs.export`).

Cost model matches the metrics registry: with ``RAFT_TPU_METRICS=off``
:func:`span` returns a shared null context manager — no allocation, no
range-stack push, bit-identical behavior.

Retention and sampling are bounded by construction:

* the ring keeps the newest ``RAFT_TPU_SPAN_RETAIN`` spans (default
  2048) — observability, not an audit log;
* ``RAFT_TPU_SPAN_SAMPLE`` (a rate in (0, 1], default 1.0) keeps
  deterministically every ``round(1/rate)``-th span per name — a
  counter-stride, not a coin flip, so runs are reproducible.

Both env knobs fail loud: a malformed or out-of-range value raises
``ValueError`` at import (the PR-5 policy of ``RAFT_TPU_RECV_TIMEOUT``
and ``RAFT_TPU_HBM_BUDGET`` — a typo'd retention silently falling back
to the default is a debugging session, not a convenience).

When tracing is on (:mod:`raft_tpu.obs.tracectx`), a span entered under
an active :class:`TraceContext` records ``trace_id`` / ``request_id`` /
``tenant`` as top-level record keys, so a span ring (or flight bundle,
or chrome trace) can be sliced by request.
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Deque, Dict, List, Optional

from raft_tpu.core import env as _env_mod
from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import tracectx as _tracectx

__all__ = ["span", "spans", "clear_spans", "record_span",
           "set_sample_rate", "set_retention", "ring_stats"]

_lock = threading.Lock()
_counts: Dict[str, int] = {}      # per-name emission counter (sampling)

# Loss accounting (ISSUE 13 satellite): a truncated flight bundle must
# be distinguishable from a quiet system, so the ring counts what it
# sheds — spans evicted by retention (_dropped) and spans the
# counter-stride never admitted (_sampled_out). obs.snapshot() surfaces
# both.
_dropped = 0
_sampled_out = 0


# Both knobs are fail-loud at import (matching RAFT_TPU_RECV_TIMEOUT /
# RAFT_TPU_HBM_BUDGET): a malformed retention or sample rate raises
# rather than silently keeping the default.
_spans: Deque[dict] = collections.deque(
    maxlen=_env_mod.read("RAFT_TPU_SPAN_RETAIN"))
_sample_stride = (
    0 if (_r := _env_mod.read("RAFT_TPU_SPAN_SAMPLE")) == 0.0
    else max(1, int(round(1.0 / _r))))


def set_sample_rate(rate: float) -> None:
    """Keep every ``round(1/rate)``-th span per name (rate in [0, 1];
    0 drops all spans)."""
    global _sample_stride
    rate = float(rate)
    if not (0.0 <= rate <= 1.0):
        raise ValueError("sample rate must be in [0, 1]")
    _sample_stride = 0 if rate == 0.0 else max(1, int(round(1.0 / rate)))


def set_retention(maxlen: int) -> None:
    """Resize the in-memory span ring (drops current contents)."""
    global _spans
    with _lock:
        _spans = collections.deque(maxlen=max(1, int(maxlen)))


class _NullSpan:
    """Zero-cost stand-in when metrics are off (shared singleton)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, **attrs) -> None:
        pass


_NULL = _NullSpan()


class _Span:
    __slots__ = ("name", "attrs", "parent", "t_start", "duration",
                 "_thread", "_ctx")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.parent: Optional[str] = None
        self.t_start = 0.0
        self.duration = 0.0
        self._thread = None
        self._ctx = None

    def set_attr(self, **attrs) -> None:
        """Attach attributes discovered mid-span (iteration counts,
        byte totals)."""
        self.attrs.update(attrs)

    def __enter__(self):
        from raft_tpu.core import trace
        self.parent = trace.current_range()
        self._thread = threading.get_ident()
        if _tracectx.tracing_enabled():
            self._ctx = _tracectx.current_context()
        trace._stack().append(self.name)
        self.t_start = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration = time.monotonic() - self.t_start
        from raft_tpu.core import trace
        st = trace._stack()
        if st and st[-1] == self.name:
            st.pop()
        if exc_type is not None:
            self.attrs.setdefault("error", exc_type.__name__)
        _record(self)
        return False


def _record(sp: _Span) -> None:
    global _sampled_out, _dropped
    with _lock:
        n = _counts.get(sp.name, 0) + 1
        _counts[sp.name] = n
        if _sample_stride == 0 or (n - 1) % _sample_stride != 0:
            _sampled_out += 1
            return
        rec = {"name": sp.name, "t": sp.t_start,
               "duration": sp.duration, "parent": sp.parent,
               "thread": sp._thread, "attrs": dict(sp.attrs)}
        if sp._ctx is not None:
            rec.update(sp._ctx.attrs())
        if len(_spans) == _spans.maxlen:
            _dropped += 1
        _spans.append(rec)
    # sink write happens outside the span lock (the sink has its own)
    from raft_tpu.obs import export
    export._sink_span(rec)


def record_span(name: str, *, t_start: float, duration: float,
                parent: Optional[str] = None,
                thread: Optional[int] = None,
                ctx: Optional["_tracectx.TraceContext"] = None,
                **attrs) -> Optional[dict]:
    """Record a manufactured span — one whose lifetime was measured
    outside a ``with`` block (e.g. per-request queue-wait/execute slices
    derived after a batch launch completes).

    No-op (returns None) when metrics are off. NOT subject to
    counter-stride sampling: manufactured spans are explicit, their
    caller already decided they matter. ``ctx`` defaults to the calling
    thread's active :class:`TraceContext`."""
    if not _metrics.enabled():
        return None
    if ctx is None and _tracectx.tracing_enabled():
        ctx = _tracectx.current_context()
    rec = {"name": name, "t": float(t_start),
           "duration": float(duration), "parent": parent,
           "thread": thread if thread is not None
           else threading.get_ident(),
           "attrs": dict(attrs)}
    if ctx is not None:
        rec.update(ctx.attrs())
    global _dropped
    with _lock:
        if len(_spans) == _spans.maxlen:
            _dropped += 1
        _spans.append(rec)
    from raft_tpu.obs import export
    export._sink_span(rec)
    return rec


def span(name: str, **attrs):
    """Context manager recording a completed span on exit.

    Returns a shared no-op object when metrics are off; the recorded
    span's parent is the innermost :func:`raft_tpu.core.trace.push_range`
    range (or enclosing span) at entry time."""
    if not _metrics.enabled():
        return _NULL
    return _Span(name, dict(attrs))


def spans(name: Optional[str] = None) -> List[dict]:
    """Snapshot of retained spans, newest last; optionally filtered by
    span name."""
    with _lock:
        out = list(_spans)
    if name is None:
        return out
    return [s for s in out if s["name"] == name]


def ring_stats() -> dict:
    """Retention/loss accounting for the span ring: spans currently
    retained, spans evicted by the retention bound since the last
    :func:`clear_spans`, and spans the sampling stride never admitted.
    ``dropped``/``sampled_out`` nonzero means the ring (and any flight
    bundle snapshotting it) is a truncated view, not a quiet system."""
    with _lock:
        return {"retained": len(_spans), "dropped": _dropped,
                "sampled_out": _sampled_out}


def clear_spans() -> None:
    global _dropped, _sampled_out
    with _lock:
        _spans.clear()
        _counts.clear()
        _dropped = 0
        _sampled_out = 0
