"""JSONL event-stream schema (ISSUE 4 satellite: the contract
ci/smoke.sh validates the exported stream against).

Every line the :class:`raft_tpu.obs.export.JsonlSink` writes is one
JSON object with a ``kind`` discriminator:

``kind="event"``
    ``name`` str, ``ts`` wall-clock float, ``t`` monotonic float,
    ``range`` str|null, ``range_stack`` list[str]; any further keys are
    free-form event attributes.
``kind="span"``
    ``name`` str, ``ts`` float, ``t`` monotonic float,
    ``duration`` float >= 0, ``parent`` str|null, ``thread`` int|null,
    ``attrs`` dict.

The validator is deliberately dependency-free (no jsonschema in the
image): it returns human-readable problem strings instead of raising,
so the CI gate can report every violation in one pass.
"""

from __future__ import annotations

import json
from typing import List, Tuple

__all__ = ["validate_record", "validate_jsonl"]

KINDS = ("event", "span")


def _check(problems, cond, msg):
    if not cond:
        problems.append(msg)


def validate_record(obj) -> List[str]:
    """Problems with one decoded JSONL record ([] when valid)."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"record is {type(obj).__name__}, not an object"]
    kind = obj.get("kind")
    if kind not in KINDS:
        return [f"kind={kind!r} not in {KINDS}"]
    _check(problems, isinstance(obj.get("name"), str) and obj["name"],
           "name must be a non-empty string")
    _check(problems, isinstance(obj.get("ts"), (int, float)),
           "ts (wall clock) must be a number")
    _check(problems, isinstance(obj.get("t"), (int, float)),
           "t (monotonic) must be a number")
    if kind == "event":
        rng = obj.get("range")
        _check(problems, rng is None or isinstance(rng, str),
               "range must be a string or null")
        st = obj.get("range_stack")
        _check(problems,
               isinstance(st, list) and all(isinstance(s, str)
                                            for s in st),
               "range_stack must be a list of strings")
    else:  # span
        dur = obj.get("duration")
        _check(problems,
               isinstance(dur, (int, float)) and dur >= 0,
               "duration must be a non-negative number")
        parent = obj.get("parent")
        _check(problems, parent is None or isinstance(parent, str),
               "parent must be a string or null")
        _check(problems, isinstance(obj.get("attrs"), dict),
               "attrs must be an object")
    return problems


def validate_jsonl(path: str) -> Tuple[int, List[str]]:
    """Validate a JSONL file; returns (n_valid_records, problems).
    Problems are prefixed with their 1-based line number."""
    n_ok = 0
    problems: List[str] = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                problems.append(f"line {lineno}: not JSON ({e.msg})")
                continue
            probs = validate_record(obj)
            if probs:
                problems.extend(f"line {lineno}: {p}" for p in probs)
            else:
                n_ok += 1
    return n_ok, problems
