"""JSONL event-stream schema (ISSUE 4 satellite: the contract
ci/smoke.sh validates the exported stream against).

Every line the :class:`raft_tpu.obs.export.JsonlSink` writes is one
JSON object with a ``kind`` discriminator:

``kind="event"``
    ``name`` str, ``ts`` wall-clock float, ``t`` monotonic float,
    ``range`` str|null, ``range_stack`` list[str]; any further keys are
    free-form event attributes.
``kind="span"``
    ``name`` str, ``ts`` float, ``t`` monotonic float,
    ``duration`` float >= 0, ``parent`` str|null, ``thread`` int|null,
    ``attrs`` dict.
``kind="flight"``
    flight-recorder bundle header (ISSUE 10): ``error_type`` non-empty
    str, ``error`` str, ``ts``/``t`` numbers, ``n_spans``/``n_events``
    non-negative ints; ``op`` str|null.
``kind="metrics"``
    flight-bundle trailer: ``ts``/``t`` numbers, ``metrics`` dict (the
    registry snapshot).

Events, spans, and flight headers may additionally carry the bounded
trace-context triple ``trace_id``/``request_id``/``tenant`` — when
present each must be a non-empty string.

The validator is deliberately dependency-free (no jsonschema in the
image): it returns human-readable problem strings instead of raising,
so the CI gate can report every violation in one pass.
"""

from __future__ import annotations

import json
from typing import List, Tuple

__all__ = ["validate_record", "validate_jsonl",
           "validate_flight_bundle", "validate_chrome_trace"]

KINDS = ("event", "span", "flight", "metrics")

_CTX_FIELDS = ("trace_id", "request_id", "tenant")


def _check(problems, cond, msg):
    if not cond:
        problems.append(msg)


def validate_record(obj) -> List[str]:
    """Problems with one decoded JSONL record ([] when valid)."""
    problems: List[str] = []
    if not isinstance(obj, dict):
        return [f"record is {type(obj).__name__}, not an object"]
    kind = obj.get("kind")
    if kind not in KINDS:
        return [f"kind={kind!r} not in {KINDS}"]
    _check(problems, isinstance(obj.get("ts"), (int, float)),
           "ts (wall clock) must be a number")
    _check(problems, isinstance(obj.get("t"), (int, float)),
           "t (monotonic) must be a number")
    if kind in ("event", "span"):
        _check(problems,
               isinstance(obj.get("name"), str) and obj["name"],
               "name must be a non-empty string")
    if kind in ("event", "span", "flight"):
        for f in _CTX_FIELDS:
            if f in obj:
                _check(problems,
                       isinstance(obj[f], str) and obj[f],
                       f"{f} must be a non-empty string when present")
    if kind == "event":
        rng = obj.get("range")
        _check(problems, rng is None or isinstance(rng, str),
               "range must be a string or null")
        st = obj.get("range_stack")
        _check(problems,
               isinstance(st, list) and all(isinstance(s, str)
                                            for s in st),
               "range_stack must be a list of strings")
    elif kind == "span":
        dur = obj.get("duration")
        _check(problems,
               isinstance(dur, (int, float)) and dur >= 0,
               "duration must be a non-negative number")
        parent = obj.get("parent")
        _check(problems, parent is None or isinstance(parent, str),
               "parent must be a string or null")
        _check(problems, isinstance(obj.get("attrs"), dict),
               "attrs must be an object")
    elif kind == "flight":
        et = obj.get("error_type")
        _check(problems, isinstance(et, str) and et,
               "error_type must be a non-empty string")
        _check(problems, isinstance(obj.get("error"), str),
               "error must be a string")
        op = obj.get("op")
        _check(problems, op is None or isinstance(op, str),
               "op must be a string or null")
        for f in ("n_spans", "n_events"):
            v = obj.get(f)
            _check(problems,
                   isinstance(v, int) and not isinstance(v, bool)
                   and v >= 0,
                   f"{f} must be a non-negative integer")
    else:  # metrics
        _check(problems, isinstance(obj.get("metrics"), dict),
               "metrics must be an object")
    return problems


def validate_jsonl(path: str) -> Tuple[int, List[str]]:
    """Validate a JSONL file; returns (n_valid_records, problems).
    Problems are prefixed with their 1-based line number."""
    n_ok = 0
    problems: List[str] = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                problems.append(f"line {lineno}: not JSON ({e.msg})")
                continue
            probs = validate_record(obj)
            if probs:
                problems.extend(f"line {lineno}: {p}" for p in probs)
            else:
                n_ok += 1
    return n_ok, problems


def validate_flight_bundle(path: str) -> Tuple[int, List[str]]:
    """Validate one flight-recorder JSONL bundle file: every line must
    be a valid record, line 1 must be the ``kind="flight"`` header, and
    the final line must be the ``kind="metrics"`` trailer. Returns
    (n_valid_records, problems)."""
    n_ok, problems = validate_jsonl(path)
    kinds: List[str] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                kinds.append("<garbage>")
                continue
            kinds.append(obj.get("kind") if isinstance(obj, dict)
                         else "<non-object>")
    if not kinds:
        problems.append("bundle is empty")
    else:
        if kinds[0] != "flight":
            problems.append(
                f"first record must be kind='flight', got {kinds[0]!r}")
        if kinds[-1] != "metrics":
            problems.append(
                f"last record must be kind='metrics', got {kinds[-1]!r}")
        if kinds.count("flight") != 1:
            problems.append("bundle must contain exactly one flight header")
    return n_ok, problems


_CHROME_PHASES = ("X", "B", "E", "b", "e", "i", "M")


def validate_chrome_trace(doc) -> List[str]:
    """Problems with a chrome://tracing / Perfetto JSON document as
    produced by :func:`raft_tpu.obs.export.render_chrome_trace`
    ([] when valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, not an object"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _CHROME_PHASES:
            problems.append(f"event {i}: ph={ph!r} not in {_CHROME_PHASES}")
            continue
        _check(problems,
               isinstance(ev.get("name"), str) and ev["name"],
               f"event {i}: name must be a non-empty string")
        _check(problems, isinstance(ev.get("ts"), (int, float)),
               f"event {i}: ts must be a number (microseconds)")
        _check(problems, "pid" in ev, f"event {i}: pid required")
        _check(problems, "tid" in ev, f"event {i}: tid required")
        if ph == "X":
            dur = ev.get("dur")
            _check(problems,
                   isinstance(dur, (int, float)) and dur >= 0,
                   f"event {i}: ph=X needs a non-negative dur")
        if ph in ("b", "e"):
            _check(problems, "id" in ev,
                   f"event {i}: async ph={ph} needs an id")
    return problems
