"""Failure flight recorder (ISSUE 10 tentpole part 3): on every typed
failure, snapshot what the process was doing in the seconds before —
the span ring, the always-on event ring, the metrics registry, and the
failing thread's trace context — into a bounded in-memory ring and
(when a dump directory is configured) a schema-validated JSONL bundle.

This is the black-box role: ALWAYS ON, like the event ring it snapshots
— a crash with ``RAFT_TPU_METRICS=off`` still leaves the event history
behind (spans/metrics are simply empty then). The raise sites in
``runtime/limits.py`` (deadline, budget, breaker), ``core/guards.py``
(non-finite sentinels), ``serve/`` (queue-full, in-queue expiry) and
``comms/resilience.py`` (dead peers) call :func:`record_failure` just
before raising; the call is bounded, lock-scoped, and can never itself
raise into the failure path.

Bundle file format (one JSONL stream, validated by
:func:`raft_tpu.obs.schema.validate_flight_bundle`): line 1 is the
``kind="flight"`` header (error type/message/op, the trace context that
died, ring occupancy counts), then one ``kind="span"`` line per
retained span, one ``kind="event"`` line per retained event, and a
final ``kind="metrics"`` line carrying the registry snapshot.

Bounded by construction: at most ``_RETAIN`` bundles in memory and
``_MAX_FILES`` files per process on disk — a failure storm degrades
recording, never memory or the filesystem.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Deque, List, Optional

from raft_tpu.core import env as _env_mod
from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import tracectx as _tracectx

__all__ = [
    "record_failure", "flight_bundles", "clear_flight_bundles",
    "set_flight_dir", "flight_dir",
]

_RETAIN = 16        # in-memory bundle ring
_MAX_FILES = 32     # on-disk bundles per process (storm bound)

_lock = threading.Lock()
_bundles: Deque[dict] = collections.deque(maxlen=_RETAIN)
_seq = 0
_files_written = 0
_dir: Optional[str] = _env_mod.read("RAFT_TPU_FLIGHT_DIR")


def set_flight_dir(path: Optional[str]) -> Optional[str]:
    """Set (or with None, disable) the on-disk bundle directory — the
    programmatic twin of ``RAFT_TPU_FLIGHT_DIR``. Returns the previous
    value. The in-memory ring records regardless."""
    global _dir
    with _lock:
        prev, _dir = _dir, (str(path) if path else None)
    return prev


def flight_dir() -> Optional[str]:
    return _dir


def flight_bundles(error_type: Optional[str] = None) -> List[dict]:
    """Snapshot of in-memory bundles, newest last; optionally filtered
    by the failing exception's type name."""
    with _lock:
        out = list(_bundles)
    if error_type is None:
        return out
    return [b for b in out
            if b["header"]["error_type"] == error_type]


def clear_flight_bundles() -> None:
    global _files_written
    with _lock:
        _bundles.clear()
        _files_written = 0


def record_failure(exc: BaseException, *, op: Optional[str] = None,
                   **attrs) -> Optional[dict]:
    """Snapshot the rings + registry for one typed failure.

    Called at the raise site, just before ``raise exc``: the thread's
    current trace context (or one already attached to ``exc``) names
    the trace the failure killed. Returns the bundle dict (None only if
    recording itself failed — this function NEVER raises into the
    caller's failure path)."""
    global _seq
    try:
        # note: `import raft_tpu.obs.spans as m` resolves through the
        # facade, whose re-exported spans() *function* shadows the
        # submodule attribute — import the ring accessors directly
        from raft_tpu.obs.export import events as _list_events
        from raft_tpu.obs.spans import spans as _list_spans

        ctx = _tracectx.current_context()
        with _lock:
            _seq += 1
            seq = _seq
        span_recs = _list_spans()
        event_recs = _list_events()
        header = {
            "kind": "flight",
            "seq": seq,
            "ts": time.time(),
            "t": time.monotonic(),
            "error_type": type(exc).__name__,
            "error": str(exc)[:2000],
            "op": op if op is not None else getattr(exc, "op", None),
            "n_spans": len(span_recs),
            "n_events": len(event_recs),
        }
        if ctx is not None:
            header.update(ctx.attrs())
        for k, v in attrs.items():
            header.setdefault(k, v)
        bundle = {
            "header": header,
            "spans": span_recs,
            "events": event_recs,
            "metrics": _metrics.get_registry().snapshot(),
        }
        with _lock:
            _bundles.append(bundle)
        _maybe_dump(bundle, seq)
        return bundle
    except Exception:  # noqa: BLE001 — the recorder must never compound
        return None    # the failure it is recording


def _maybe_dump(bundle: dict, seq: int) -> None:
    global _files_written
    with _lock:
        path_dir = _dir
        if path_dir is None or _files_written >= _MAX_FILES:
            return
        _files_written += 1
    from raft_tpu.obs.export import _json_safe, JsonlSink

    os.makedirs(path_dir, exist_ok=True)
    name = (f"flight-{os.getpid()}-{seq:04d}-"
            f"{bundle['header']['error_type']}.jsonl")
    path = os.path.join(path_dir, name)
    sink = JsonlSink(path)
    try:
        sink.write(bundle["header"])
        ts = bundle["header"]["ts"]
        for rec in bundle["spans"]:
            out = dict(_json_safe(rec))
            out["kind"] = "span"
            out.setdefault("ts", ts)
            sink.write(out)
        for rec in bundle["events"]:
            out = dict(_json_safe(rec))
            out["kind"] = "event"
            out.setdefault("ts", ts)
            sink.write(out)
        sink.write({"kind": "metrics", "ts": ts,
                    "t": bundle["header"]["t"],
                    "metrics": bundle["metrics"]})
    finally:
        sink.close()
    bundle["header"]["path"] = path
