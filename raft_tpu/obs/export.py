"""Export surface (ISSUE 4 tentpole part 3): ``snapshot()`` dict, JSONL
event sink, Prometheus text-exposition rendering — plus the single
process-wide event ring that :func:`raft_tpu.core.trace.record_event`
now feeds (satellite: one emit path for comms trace events, guard
escalations, checkpoint events, and obs spans).

The event ring keeps the exact record shape the old ``core/trace.py``
ring kept (``name``/``range``/``range_stack``/``t`` + attrs) so every
existing ``trace.events(...)`` consumer keeps working, and it is NOT
gated by ``RAFT_TPU_METRICS`` — the ring is part of the library's
always-on error-path observability (tests assert on it with metrics
off). Only the JSONL sink fan-out is additive.

JSONL stream: one JSON object per line, each carrying ``kind``
(``"event"`` | ``"span"``), ``ts`` (wall clock) and ``t`` (monotonic).
``RAFT_TPU_METRICS_JSONL=<path>`` attaches a file sink at import when
metrics are on, so any workload can be observed without code changes —
the contract ci/smoke.sh validates via :mod:`raft_tpu.obs.schema`.
"""

from __future__ import annotations

import atexit
import contextlib
import collections
import io
import json
import threading
import time
from typing import Deque, List, Optional

from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs import tracectx as _tracectx

__all__ = [
    "emit_event", "events", "clear_events",
    "JsonlSink", "get_sink", "set_sink",
    "snapshot", "render_prometheus", "render_chrome_trace",
]


# ---------------------------------------------------------------------------
# JSONL sink
# ---------------------------------------------------------------------------

def _json_safe(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    try:                       # np scalars and friends
        return v.item()
    except (AttributeError, ValueError):
        return repr(v)


class JsonlSink:
    """Thread-safe JSON-lines writer (one event per line, flushed so a
    crash loses at most the line being written)."""

    def __init__(self, target):
        """``target`` is a path (opened for append) or a file-like
        object with ``write``/``flush``."""
        self._lock = threading.Lock()
        self._closed = False
        if isinstance(target, (str, bytes)) or hasattr(target, "__fspath__"):
            self._fh = open(target, "a", encoding="utf-8")
            self._owns = True
        else:
            self._fh = target
            self._owns = False

    def write(self, record: dict) -> None:
        line = json.dumps(_json_safe(record), separators=(",", ":"))
        with self._lock:
            if self._closed:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._fh.flush()

    def close(self) -> None:
        """Flush and (when this sink opened the file) close it.
        Idempotent — safe to call from both user code and the atexit
        hook."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            # underlying stream may already be closed at interpreter exit
            with contextlib.suppress(ValueError):
                self._fh.flush()
            if self._owns:
                self._fh.close()


_sink_lock = threading.Lock()
_sink: Optional[JsonlSink] = None


def get_sink() -> Optional[JsonlSink]:
    return _sink


def set_sink(sink: Optional[JsonlSink]) -> Optional[JsonlSink]:
    """Install (or with None, detach) the process JSONL sink; returns
    the previous sink (caller owns closing it)."""
    global _sink
    with _sink_lock:
        old, _sink = _sink, sink
    return old


# ---------------------------------------------------------------------------
# the unified event ring (rehomed from core/trace.py; record shape is
# frozen — trace.events() consumers depend on it)
# ---------------------------------------------------------------------------

_events_lock = threading.Lock()
_events: Deque[dict] = collections.deque(maxlen=1024)
# ring-overwrite counter (ISSUE 13 satellite): events evicted by the
# bounded ring since the last clear_events() — snapshot() surfaces it
# so a truncated event history is distinguishable from a quiet one
_events_overwritten = 0


def emit_event(name: str, **attrs) -> None:
    """Record an instantaneous host-side event in the active range.

    Always appends to the bounded in-memory ring (the pre-obs
    ``trace.record_event`` contract); additionally writes a
    ``kind="event"`` JSONL line when a sink is attached."""
    from raft_tpu.core import trace
    ev = {"name": name, "range": trace.current_range(),
          "range_stack": tuple(trace.range_stack()),
          "t": time.monotonic()}
    if _tracectx.tracing_enabled():
        ctx = _tracectx.current_context()
        if ctx is not None:
            ev.update(ctx.attrs())
    ev.update(attrs)
    global _events_overwritten
    with _events_lock:
        if len(_events) == _events.maxlen:
            _events_overwritten += 1
        _events.append(ev)
    sink = _sink
    if sink is not None:
        rec = dict(ev)
        rec["kind"] = "event"
        rec["ts"] = time.time()
        sink.write(rec)


def events(name: Optional[str] = None) -> List[dict]:
    """Snapshot of recorded events, newest last; optionally filtered by
    event name."""
    with _events_lock:
        evs = list(_events)
    if name is None:
        return evs
    return [e for e in evs if e["name"] == name]


def clear_events() -> None:
    global _events_overwritten
    with _events_lock:
        _events.clear()
        _events_overwritten = 0


def _sink_span(rec: dict) -> None:
    """Fan a completed span out to the JSONL sink (spans.py calls this;
    the in-memory retention lives there)."""
    sink = _sink
    if sink is None:
        return
    out = dict(rec)
    out["kind"] = "span"
    out["ts"] = time.time()
    sink.write(out)


# ---------------------------------------------------------------------------
# snapshot + Prometheus text exposition
# ---------------------------------------------------------------------------

def snapshot(registry: Optional[_metrics.MetricsRegistry] = None) -> dict:
    """One JSON-able dict of everything: enabled flag, every metric
    family/series, span/event ring occupancy *and loss counters* (a
    truncated flight bundle must be distinguishable from a quiet
    system), and the performance-attribution section
    (:mod:`raft_tpu.obs.perf`). This is what ``bench.py`` attaches to
    its output line."""
    from raft_tpu.obs import perf as _perf
    from raft_tpu.obs.spans import ring_stats as _ring_stats
    reg = registry or _metrics.get_registry()
    st = _ring_stats()
    with _events_lock:
        ev_retained, ev_overwritten = len(_events), _events_overwritten
    return {
        "enabled": _metrics.enabled(),
        "metrics": reg.snapshot(),
        "spans_retained": st["retained"],
        "spans_dropped": st["dropped"],
        "spans_sampled_out": st["sampled_out"],
        "events_retained": ev_retained,
        "events_overwritten": ev_overwritten,
        "perf": _perf.perf_snapshot(),
    }


def _esc_label(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n").replace('"', r'\"')


def _fmt_labels(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(f'{k}="{_esc_label(str(v))}"'
                     for k, v in zip(names, values))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(
        registry: Optional[_metrics.MetricsRegistry] = None) -> str:
    """Prometheus text exposition (version 0.0.4) of the registry:
    ``# HELP`` / ``# TYPE`` headers, one line per series, histograms as
    cumulative ``_bucket{le=...}`` plus ``_sum`` / ``_count``."""
    reg = registry or _metrics.get_registry()
    out = io.StringIO()
    for name, fam in sorted(reg.families().items()):
        if fam.help:
            out.write(f"# HELP {name} {fam.help}\n")
        out.write(f"# TYPE {name} {fam.kind}\n")
        with fam._lock:
            children = list(fam._children.values())
        for child in sorted(children, key=lambda c: c.labels):
            lbl = _fmt_labels(fam.labelnames, child.labels)
            if fam.kind != "histogram":
                out.write(f"{name}{lbl} {_fmt_value(child.value)}\n")
                continue
            cum = 0
            for bound, n in zip(list(fam.buckets) + ["+Inf"],
                                child.bucket_counts):
                cum += n
                blbl = _fmt_labels(
                    list(fam.labelnames) + ["le"],
                    list(child.labels) + [bound])
                out.write(f"{name}_bucket{blbl} {cum}\n")
            out.write(f"{name}_sum{lbl} {_fmt_value(child.sum)}\n")
            out.write(f"{name}_count{lbl} {child.count}\n")
    return out.getvalue()


# ---------------------------------------------------------------------------
# chrome://tracing / Perfetto exporter
# ---------------------------------------------------------------------------

_CHROME_TRACE_FIELDS = ("trace_id", "request_id", "tenant")


def render_chrome_trace(path: Optional[str] = None, *,
                        spans: Optional[List[dict]] = None) -> dict:
    """Render the span ring as a Perfetto / ``chrome://tracing`` JSON
    document (the NVTX → Nsight-Systems timeline analogue).

    Every span record becomes a ``"ph": "X"`` complete duration event —
    host monotonic seconds scaled to the microseconds the format wants,
    keyed on the recorded thread id so nesting within a thread renders
    as the stack it was. ``*.chunk`` spans (compiled-driver device-wall
    chunks) additionally emit an async ``"b"``/``"e"`` slice pair on a
    per-op track, which Perfetto draws as a separate device lane.
    Trace-context fields and span attrs land in ``args`` so the UI's
    selection panel shows which request a slice belonged to.

    ``spans`` overrides the ring (e.g. a flight bundle's span list);
    ``path`` additionally writes the JSON document to a file. Returns
    the document either way."""
    import os as _os

    recs = spans if spans is not None else _list_all_spans()
    pid = _os.getpid()
    out: List[dict] = []
    async_id = 0
    for rec in recs:
        args = dict(rec.get("attrs") or {})
        if rec.get("parent"):
            args["parent"] = rec["parent"]
        for f in _CHROME_TRACE_FIELDS:
            if rec.get(f):
                args[f] = rec[f]
        ts_us = float(rec["t"]) * 1e6
        dur_us = max(0.0, float(rec["duration"])) * 1e6
        out.append({
            "name": rec["name"], "ph": "X", "cat": "host",
            "ts": ts_us, "dur": dur_us, "pid": pid,
            "tid": rec.get("thread") or 0,
            "args": _json_safe(args),
        })
        if rec["name"].endswith(".chunk"):
            # device-wall lane: one async slice per chunk, tracked per
            # op so concurrent solvers get separate rows
            async_id += 1
            base = {"name": rec["name"], "cat": "device",
                    "id": async_id, "pid": pid, "tid": 0,
                    "args": _json_safe(args)}
            out.append({**base, "ph": "b", "ts": ts_us})
            out.append({**base, "ph": "e", "ts": ts_us + dur_us})
    doc = {"traceEvents": out, "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, separators=(",", ":"))
    return doc


def _list_all_spans() -> List[dict]:
    from raft_tpu.obs.spans import spans as _list_spans
    return _list_spans()


# -- import-time sink attachment (env-driven, metrics-on only) --------------

def _maybe_attach_env_sink() -> None:
    from raft_tpu.core import env
    path = env.read("RAFT_TPU_METRICS_JSONL")
    if path and _metrics.enabled() and get_sink() is None:
        set_sink(JsonlSink(path))


@atexit.register
def _atexit_close_sink() -> None:
    """Flush+close the attached sink at interpreter shutdown so a
    short-lived process (a serving bench, a smoke gate) never drops its
    final buffered lines. close() is idempotent, so a sink the caller
    already closed is a no-op here."""
    sink = get_sink()
    if sink is not None:
        sink.close()


_maybe_attach_env_sink()
