"""Performance attribution (ISSUE 13 tentpole): per-executable static
costs, achieved-throughput roofline telemetry, and aligned device
profiling.

The request-level layers (metrics, spans, tracing, flight) answer *what
happened*; this module answers *how fast* — in the spirit of the
Roofline model (Williams et al., CACM 2009) and always-on fleet
profiling (Google-Wide Profiling, Ren et al., IEEE Micro 2010):

* **Static costs at warm time.** :func:`profile_executable` registers
  an :class:`ExecutableProfile` keyed exactly the way
  ``serve/executor.py`` keys its warmed executables — ``(op, bucket)``
  — holding the executable's flops and bytes. When a traceable
  ``fn``/``example`` pair is given, the costs come from XLA's own
  ``compiled.cost_analysis()`` (source ``"xla"``); otherwise (or when
  the compiler declines) they fall back to the caller's model numbers —
  the same ``limits.estimate_bytes`` / ``estimate_seconds`` cost models
  the admission layer already trusts (source ``"model"``).
* **Achieved throughput at launch time.** :func:`record_launch`
  converts a wall time the executor / compiled-driver already measures
  into achieved FLOP/s, bytes/s, and a roofline fraction against
  :func:`raft_tpu.core.hw.peaks`, classifying each launch as
  ``compute`` / ``bandwidth`` / ``overhead`` bound and emitting
  ``perf_roofline_frac{op,bucket,bound}``,
  ``perf_achieved_flops_per_s`` and ``perf_achieved_bytes_per_s``
  gauges through the one obs registry.
* **HBM watermarks.** :func:`record_hbm_watermark` polls
  ``device_memory_stats`` (compiled-driver chunk boundaries call it)
  into ``perf_hbm_bytes_in_use`` / ``perf_hbm_peak_bytes_in_use``.
* **Aligned device profiles.** :func:`profile_session` wraps
  ``jax.profiler`` tracing and records a ``perf.profile_session`` span
  over the same monotonic clock the span ring uses, so the captured
  device profile lines up with host spans in the PR-10 Perfetto export
  (``obs.render_chrome_trace``).

Cost discipline is the established one: ``RAFT_TPU_PERF=off`` (the
default) makes every helper here a single-bool no-op — bit-identical
library behavior, pinned by raftlint R5 and the serve-path CI identity
gate. The knob is independent of ``RAFT_TPU_METRICS``: profiles
accumulate whenever perf is on, gauges additionally publish when
metrics are on too.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from raft_tpu.core import env as _env_mod
from raft_tpu.core import hw as _hw
from raft_tpu.obs import metrics as _metrics

__all__ = [
    "ExecutableProfile", "perf_enabled", "set_perf_enabled",
    "profile_executable", "record_launch", "record_hbm_watermark",
    "profile_session", "perf_profiles", "clear_perf_profiles",
    "perf_snapshot",
]

# the single-bool switch (same discipline as metrics._enabled)
_enabled: bool = _env_mod.read("RAFT_TPU_PERF")

# a launch whose modeled device time explains less than this fraction
# of its wall time spent the wall on dispatch/queueing/compile, not the
# device — classified "overhead" rather than compute/bandwidth bound
OVERHEAD_FRAC = 0.1

_lock = threading.Lock()
_profiles: Dict[Tuple[str, Any], "ExecutableProfile"] = {}
_peaks: Optional[_hw.HwPeaks] = None
_hbm: Dict[str, int] = {"bytes_in_use": 0, "peak_bytes_in_use": 0,
                        "polls": 0}


def perf_enabled() -> bool:
    return _enabled


def set_perf_enabled(on: bool) -> bool:
    """Flip performance attribution at runtime (the programmatic twin
    of ``RAFT_TPU_PERF``); returns the previous state."""
    global _enabled
    prev, _enabled = _enabled, bool(on)
    return prev


@dataclass
class ExecutableProfile:
    """Static costs + running achieved-throughput attribution for one
    warmed executable. ``flops``/``bytes`` are per launch at scale 1
    (per chunk *step* for the compiled-driver entries)."""

    op: str
    bucket: Any                      # serve row bucket, or "chunk"
    flops: float = 0.0
    bytes: float = 0.0
    source: str = "model"            # "xla" | "model"
    launches: int = 0
    wall_s: float = 0.0              # cumulative measured wall
    steps: float = 0.0               # cumulative launch scale
    achieved_flops_per_s: float = 0.0
    achieved_bytes_per_s: float = 0.0
    roofline_frac: float = 0.0
    bound: str = ""                  # "compute"|"bandwidth"|"overhead"
    attrs: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {"op": self.op, "bucket": self.bucket,
                "flops": self.flops, "bytes": self.bytes,
                "source": self.source, "launches": self.launches,
                "wall_s": round(self.wall_s, 6),
                "achieved_flops_per_s": self.achieved_flops_per_s,
                "achieved_bytes_per_s": self.achieved_bytes_per_s,
                "roofline_frac": self.roofline_frac,
                "bound": self.bound, **self.attrs}


def _device_peaks() -> _hw.HwPeaks:
    global _peaks
    pk = _peaks
    if pk is None:
        pk = _peaks = _hw.peaks()
    return pk


def reset_peaks() -> None:
    """Drop the cached peak table (tests that flip the env override)."""
    global _peaks
    _peaks = None


def _xla_costs(fn, example) -> Tuple[float, float]:
    """flops / bytes-accessed from XLA's cost analysis of ``fn`` lowered
    at ``example``'s shapes. Raises on any compiler refusal — the
    caller falls back to the model costs."""
    import jax

    compiled = jax.jit(fn).lower(*example).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):     # older JAX returns [dict]
        ca = ca[0] if ca else {}
    flops = float(ca.get("flops", 0.0) or 0.0)
    bytes_ = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and bytes_ <= 0.0:
        raise ValueError("cost analysis returned no flops/bytes")
    return flops, bytes_


def profile_executable(op: str, bucket, *, fn=None, example=None,
                       model_flops: float = 0.0,
                       model_bytes: float = 0.0,
                       **attrs) -> Optional[ExecutableProfile]:
    """Register (or refresh) the static-cost profile for one
    ``(op, bucket)`` executable. No-op returning None when perf is off.

    With a ``fn``/``example`` pair the costs come from XLA's
    ``cost_analysis()`` of a fresh lowering (an extra compile — paid
    only when perf is on, at warm time, never per launch); on any
    compiler refusal — or without a pair — the ``model_*`` numbers from
    the limits cost models are used instead, so every profile always
    has *some* static cost to attribute launches against."""
    if not _enabled:
        return None
    flops, bytes_, source = float(model_flops), float(model_bytes), "model"
    if fn is not None and example is not None:
        try:
            flops, bytes_ = _xla_costs(fn, example)
            source = "xla"
        except Exception:
            pass                     # model fallback, already loaded
    key = (op, bucket)
    with _lock:
        prof = _profiles.get(key)
        if prof is None:
            prof = _profiles[key] = ExecutableProfile(op, bucket)
        prof.flops, prof.bytes, prof.source = flops, bytes_, source
        prof.attrs.update(attrs)
    return prof


def record_launch(op: str, bucket, wall_s: float, *,
                  steps: float = 1.0) -> Optional[ExecutableProfile]:
    """Attribute one measured launch to its profile: achieved FLOP/s,
    bytes/s, roofline fraction, and a compute/bandwidth/overhead bound
    classification, published as gauges. ``steps`` scales the static
    per-launch costs (the compiled driver passes the number of solver
    iterations its chunk ran). No-op when perf is off; silently ignores
    launches with no registered profile or a non-positive wall."""
    if not _enabled:
        return None
    wall_s = float(wall_s)
    if wall_s <= 0.0:
        return None
    with _lock:
        prof = _profiles.get((op, bucket))
        if prof is None:
            return None
        flops = prof.flops * steps
        bytes_ = prof.bytes * steps
        prof.launches += 1
        prof.wall_s += wall_s
        prof.steps += steps
        prof.achieved_flops_per_s = flops / wall_s
        prof.achieved_bytes_per_s = bytes_ / wall_s
        pk = _device_peaks()
        t_f = flops / pk.flops_per_s if pk.flops_per_s > 0 else 0.0
        t_b = bytes_ / pk.bytes_per_s if pk.bytes_per_s > 0 else 0.0
        t_dev = max(t_f, t_b)
        frac = t_dev / wall_s
        prof.roofline_frac = frac
        if t_dev < OVERHEAD_FRAC * wall_s:
            bound = "overhead"
        elif t_f >= t_b:
            bound = "compute"
        else:
            bound = "bandwidth"
        prof.bound = bound
    lbl = str(bucket)
    _metrics.set_gauge("perf_roofline_frac", frac,
                       help="achieved fraction of the binding roofline "
                            "ceiling for the last launch",
                       op=op, bucket=lbl, bound=bound)
    _metrics.set_gauge("perf_achieved_flops_per_s",
                       prof.achieved_flops_per_s,
                       help="achieved FLOP/s over the last launch",
                       op=op, bucket=lbl)
    _metrics.set_gauge("perf_achieved_bytes_per_s",
                       prof.achieved_bytes_per_s,
                       help="achieved HBM bytes/s over the last launch",
                       op=op, bucket=lbl)
    return prof


def record_hbm_watermark(device=None) -> Optional[dict]:
    """Poll live/peak HBM use into gauges (the compiled driver calls
    this at chunk boundaries; serving code may call it ad hoc). No-op
    when perf is off; never raises — a backend without memory stats
    reports zeros, same as ``device_memory_stats``."""
    if not _enabled:
        return None
    from raft_tpu.core.memory import device_memory_stats

    try:
        stats = device_memory_stats(device)
    except Exception:
        return None
    with _lock:
        _hbm["bytes_in_use"] = int(stats["bytes_in_use"])
        _hbm["peak_bytes_in_use"] = max(
            _hbm["peak_bytes_in_use"], int(stats["peak_bytes_in_use"]))
        _hbm["polls"] += 1
    _metrics.set_gauge("perf_hbm_bytes_in_use", stats["bytes_in_use"],
                       help="live HBM bytes in use at the last "
                            "chunk-boundary poll")
    _metrics.set_gauge("perf_hbm_peak_bytes_in_use",
                       stats["peak_bytes_in_use"],
                       help="runtime-reported peak HBM bytes in use")
    return stats


@contextlib.contextmanager
def profile_session(log_dir: Optional[str] = None):
    """Capture a device profile aligned with the span ring.

    Wraps ``jax.profiler`` tracing around the body and records a
    ``perf.profile_session`` span over the same monotonic clock every
    other span uses — so the Xprof capture under ``log_dir`` and the
    host timeline ``obs.render_chrome_trace`` exports can be lined up
    by the session's start/duration. Yields the log directory (a fresh
    temp dir when none is given), or None when perf is off (the whole
    manager is then a no-op) or the profiler refuses to start (the body
    still runs; only the device capture is lost)."""
    if not _enabled:
        yield None
        return
    if log_dir is None:
        import tempfile

        log_dir = tempfile.mkdtemp(prefix="raft_tpu_profile_")
    started = False
    try:
        import jax

        jax.profiler.start_trace(str(log_dir))
        started = True
    except Exception:
        pass
    t0 = time.monotonic()
    try:
        yield log_dir if started else None
    finally:
        dur = time.monotonic() - t0
        if started:
            with contextlib.suppress(Exception):
                jax.profiler.stop_trace()
        from raft_tpu.obs.spans import record_span as _record_span
        _record_span("perf.profile_session", t_start=t0,
                     duration=dur, log_dir=str(log_dir),
                     captured=started)


def perf_profiles() -> Dict[Tuple[str, Any], ExecutableProfile]:
    """Snapshot of the live profile registry (the objects themselves —
    read-only by convention; tests and the smoke gate introspect
    these)."""
    with _lock:
        return dict(_profiles)


def clear_perf_profiles() -> None:
    """Drop all profiles and HBM watermarks (tests and REPL hygiene)."""
    with _lock:
        _profiles.clear()
        _hbm.update(bytes_in_use=0, peak_bytes_in_use=0, polls=0)


def perf_snapshot() -> dict:
    """JSON-able view for ``obs.snapshot()``'s ``"perf"`` section:
    enabled flag, the peak table in force, every profile, and the HBM
    watermark. Cheap when off — no device inspection, empty tables."""
    if not _enabled:
        return {"enabled": False, "profiles": {}, "hbm": dict(_hbm)}
    pk = _device_peaks()
    with _lock:
        profs = {f"{op}[{bucket}]": p.as_dict()
                 for (op, bucket), p in _profiles.items()}
        hbm = dict(_hbm)
    return {"enabled": True,
            "peaks": {"name": pk.name, "flops_per_s": pk.flops_per_s,
                      "bytes_per_s": pk.bytes_per_s,
                      "source": pk.source},
            "profiles": profs, "hbm": hbm}
