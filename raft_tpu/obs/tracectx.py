"""Request-scoped trace context (ISSUE 10 tentpole part 1): the identity
a request carries from `serve.RequestQueue` enqueue through coalescing,
dispatch, the limits checks, compiled-driver chunk boundaries, and —
via the comms context header — across ranks.

A :class:`TraceContext` is three strings: ``trace_id`` (one logical
request flow, shared by every rank that touches it), ``request_id``
(this enqueued block — batch spans link the member request_ids), and
``tenant``. Contexts are immutable facts; PROPAGATION is a thread-local
(:func:`use_context` scoped, :func:`adopt` unscoped for message-receipt
threads), which spans (:mod:`raft_tpu.obs.spans`) and events
(:mod:`raft_tpu.obs.export`) read at emission time.

Cost model matches the metrics registry: ``RAFT_TPU_TRACING=off`` (the
default) makes :func:`mint` return None behind one module-level bool —
no ids, no thread-local writes, bit-identical behavior. Everything
downstream keys off ``ctx is None``, so the off path never allocates.

Minting is collision-free by construction: a per-process random prefix
(so two processes in an MNMG job cannot collide) plus a lock-protected
counter (so eight submitting threads cannot either).
"""

from __future__ import annotations

import contextlib
import json
import threading
import uuid
from dataclasses import dataclass
from typing import Optional

from raft_tpu.core import env as _env_mod

__all__ = [
    "TraceContext", "tracing_enabled", "set_tracing", "mint",
    "current_context", "use_context", "adopt",
]


# -- the on/off knob (pattern: metrics.RAFT_TPU_METRICS — env read once
# at import, bad values warn and fall back to the safe default) ------------

_tracing = _env_mod.read("RAFT_TPU_TRACING")


def tracing_enabled() -> bool:
    """True when trace contexts are minted and propagated
    (``RAFT_TPU_TRACING=on``). When False, :func:`mint` returns None
    and every propagation site is a ``ctx is None`` no-op."""
    return _tracing


def set_tracing(on: bool) -> None:
    """Flip context minting at runtime (tests; long-lived services)."""
    global _tracing
    _tracing = bool(on)


# -- the context itself ----------------------------------------------------

@dataclass(frozen=True)
class TraceContext:
    """One request's tracing identity (immutable)."""

    trace_id: str
    request_id: str
    tenant: str = "default"

    def attrs(self) -> dict:
        """The bounded label/attr set spans, events, and flight bundles
        attach — exactly these three keys, never free-form."""
        return {"trace_id": self.trace_id, "request_id": self.request_id,
                "tenant": self.tenant}

    def to_header(self) -> str:
        """Compact wire form for the comms context frame (JSON array —
        tenant names may contain any delimiter a hand-rolled format
        would pick)."""
        return json.dumps([self.trace_id, self.request_id, self.tenant],
                          separators=(",", ":"))

    @classmethod
    def from_header(cls, header: str) -> "TraceContext":
        """Parse :meth:`to_header` output; raises ``ValueError`` on
        anything malformed (a corrupt context frame is dropped by the
        transport, never half-adopted)."""
        try:
            parts = json.loads(header)
        except json.JSONDecodeError as e:
            raise ValueError(f"malformed trace header: {e.msg}") from None
        if (not isinstance(parts, list) or len(parts) != 3
                or not all(isinstance(p, str) and p for p in parts)):
            raise ValueError(
                f"trace header must be [trace_id, request_id, tenant] "
                f"strings, got {header!r}")
        return cls(trace_id=parts[0], request_id=parts[1],
                   tenant=parts[2])


# -- minting ---------------------------------------------------------------

# process-unique prefix: two ranks of an MNMG job mint disjoint id
# spaces without coordination
_PREFIX = uuid.uuid4().hex[:10]
_mint_lock = threading.Lock()
_mint_counter = 0


def mint(*, tenant: str = "default",
         trace_id: Optional[str] = None) -> Optional[TraceContext]:
    """Mint a fresh context (None when tracing is off — the single-bool
    no-op).

    ``trace_id`` joins an existing trace (a retry, a fan-out child)
    under a new request_id; default is a fresh trace. Thread-safe and
    collision-free across threads and processes."""
    if not _tracing:
        return None
    global _mint_counter
    with _mint_lock:
        _mint_counter += 1
        n = _mint_counter
    rid = f"r-{_PREFIX}-{n:08x}"
    return TraceContext(
        trace_id=trace_id if trace_id is not None
        else f"t-{_PREFIX}-{n:08x}",
        request_id=rid, tenant=str(tenant))


# -- thread-local propagation ----------------------------------------------

_tls = threading.local()


def current_context() -> Optional[TraceContext]:
    """The thread's active context (None outside any request)."""
    return getattr(_tls, "ctx", None)


def adopt(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Unscoped set: make ``ctx`` the thread's active context and return
    the previous one. This is the message-receipt form — a comms rank
    thread that just received a context header adopts it for everything
    it does next (no scope exit exists there). Request-scoped code wants
    :func:`use_context` instead."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


@contextlib.contextmanager
def use_context(ctx: Optional[TraceContext]):
    """Scoped propagation: ``ctx`` is the thread's active context inside
    the block, the previous context is restored on exit. ``None`` is a
    true no-op (the tracing-off path pays one ``is None`` check)."""
    if ctx is None:
        yield None
        return
    prev = adopt(ctx)
    try:
        yield ctx
    finally:
        _tls.ctx = prev
