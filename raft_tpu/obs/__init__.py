"""Unified observability (ISSUE 4): metrics, spans, exporters.

One layer answers "what is this system doing" across every subsystem
the previous PRs instrumented ad hoc — the comms trace ring, guard
escalation events, checkpoint logs. Three parts:

:mod:`raft_tpu.obs.metrics`
    thread-safe registry of labeled Counter/Gauge/Histogram families
    (fixed log-spaced buckets, per-family cardinality cap), plus the
    ``RAFT_TPU_METRICS=off|on`` toggle — ``off`` (the default) makes
    every emit helper a no-op behind one bool check.
:mod:`raft_tpu.obs.spans`
    recorded host-side regions parented off the ``core/trace.py`` range
    stack, with bounded retention and deterministic sampling.
:mod:`raft_tpu.obs.export`
    ``snapshot()``, the process JSONL sink (``RAFT_TPU_METRICS_JSONL``),
    Prometheus text exposition, the chrome://tracing exporter, and the
    process-wide event ring that ``trace.record_event`` feeds.
:mod:`raft_tpu.obs.tracectx`
    request-scoped :class:`TraceContext` (ISSUE 10) minted at serve
    enqueue, propagated thread-locally and across comms ranks —
    ``RAFT_TPU_TRACING=off`` (the default) keeps minting a single-bool
    no-op.
:mod:`raft_tpu.obs.flight`
    the always-on failure flight recorder: ``record_failure(exc)`` at
    a typed raise site snapshots the span/event rings + registry into
    a bounded bundle ring (and a JSONL file under
    ``RAFT_TPU_FLIGHT_DIR``).
:mod:`raft_tpu.obs.perf`
    performance attribution (ISSUE 13): per-executable static costs
    (XLA ``cost_analysis`` with a limits-model fallback) keyed like the
    serve executor's warmed (service, bucket) executables, converted at
    launch time into achieved FLOP/s / bytes/s / roofline-fraction
    gauges against the :mod:`raft_tpu.core.hw` peak table, plus HBM
    watermarks and ``profile_session`` (span-aligned ``jax.profiler``
    capture). ``RAFT_TPU_PERF=off`` (the default) keeps every helper a
    single-bool no-op.

Everything any instrumented module needs is re-exported here; emitting
through private internals (or a second bespoke registry) is a lint
failure in ci/smoke.sh.
"""

from raft_tpu.obs.metrics import (          # noqa: F401
    enabled, set_enabled, MetricsRegistry, get_registry, set_registry,
    log_buckets, DEFAULT_BUCKETS, RESIDUAL_BUCKETS,
    inc, set_gauge, observe, record_convergence,
)
from raft_tpu.obs.spans import (            # noqa: F401
    span, spans, clear_spans, record_span, set_sample_rate,
    set_retention, ring_stats,
)
from raft_tpu.obs.export import (           # noqa: F401
    emit_event, events, clear_events,
    JsonlSink, get_sink, set_sink,
    snapshot, render_prometheus, render_chrome_trace,
)
from raft_tpu.obs.tracectx import (         # noqa: F401
    TraceContext, tracing_enabled, set_tracing, mint,
    current_context, use_context, adopt,
)
from raft_tpu.obs.flight import (           # noqa: F401
    record_failure, flight_bundles, clear_flight_bundles,
    set_flight_dir, flight_dir,
)
from raft_tpu.obs.perf import (             # noqa: F401
    ExecutableProfile, perf_enabled, set_perf_enabled,
    profile_executable, record_launch, record_hbm_watermark,
    profile_session, perf_profiles, clear_perf_profiles, perf_snapshot,
)

__all__ = [
    "enabled", "set_enabled", "MetricsRegistry", "get_registry",
    "set_registry", "log_buckets", "DEFAULT_BUCKETS", "RESIDUAL_BUCKETS",
    "inc", "set_gauge", "observe", "record_convergence",
    "span", "spans", "clear_spans", "record_span", "set_sample_rate",
    "set_retention", "ring_stats",
    "emit_event", "events", "clear_events",
    "JsonlSink", "get_sink", "set_sink",
    "snapshot", "render_prometheus", "render_chrome_trace",
    "TraceContext", "tracing_enabled", "set_tracing", "mint",
    "current_context", "use_context", "adopt",
    "record_failure", "flight_bundles", "clear_flight_bundles",
    "set_flight_dir", "flight_dir",
    "ExecutableProfile", "perf_enabled", "set_perf_enabled",
    "profile_executable", "record_launch", "record_hbm_watermark",
    "profile_session", "perf_profiles", "clear_perf_profiles",
    "perf_snapshot",
]
