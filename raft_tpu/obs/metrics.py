"""Thread-safe metrics registry: labeled Counter / Gauge / Histogram
(ISSUE 4 tentpole part 1; the host-side answer to the reference's
NVTX-only instrumentation — counters and histograms a production stack
can actually scrape).

Design mirrors the Prometheus client-library data model (families of
labeled series; histograms carry per-bucket counts plus ``sum`` and
``count``) without taking the dependency, and the guard-mode philosophy
of :mod:`raft_tpu.core.guards`:

``RAFT_TPU_METRICS=off`` (default)
    emission is a no-op behind a single module-level bool check —
    instrumented ops are bit-identical to the uninstrumented library and
    the hot path allocates nothing (no label tuples, no locks taken).
``RAFT_TPU_METRICS=on``
    series are created lazily on first emission; every mutation happens
    under the owning family's lock, so concurrent emitters (the comms
    server thread, heartbeat thread, and solver driver) never lose
    increments.

Cardinality is bounded per family (``max_series``, default 64): once a
family is full, emissions with novel label values collapse into a single
``<overflow>`` series and the family counts the drop — a misbehaving
label (say, a peer address) degrades metrics, never memory.

Histogram buckets are fixed and log-spaced (:func:`log_buckets`); the
default span (1 µs … 1000 s at two buckets per decade) covers collective
latencies, compile times, and checkpoint writes. Convergence residuals
use the wider :data:`RESIDUAL_BUCKETS` (1e-14 … 1e2).
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

from raft_tpu.core import env as _env_mod

__all__ = [
    "enabled", "set_enabled", "MetricsRegistry",
    "get_registry", "set_registry",
    "log_buckets", "DEFAULT_BUCKETS", "RESIDUAL_BUCKETS",
    "inc", "set_gauge", "observe", "record_convergence",
]


# ---------------------------------------------------------------------------
# the on/off knob (pattern: guards.RAFT_TPU_GUARD_MODE — env read once at
# import, bad values warn and fall back to the safe default)
# ---------------------------------------------------------------------------

_enabled = _env_mod.read("RAFT_TPU_METRICS")


def enabled() -> bool:
    """True when metric/span emission is live (``RAFT_TPU_METRICS=on``).

    Instrumentation sites gate on this: when False the emit helpers
    return before touching any lock or allocating any label tuple."""
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip metric emission at runtime (tests; long-lived services that
    want to arm metrics after warmup)."""
    global _enabled
    _enabled = bool(on)


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

def log_buckets(lo: float, hi: float, per_decade: int = 2
                ) -> Tuple[float, ...]:
    """Fixed log-spaced histogram bucket upper bounds covering
    ``[lo, hi]`` with ``per_decade`` buckets per factor of 10. The
    implicit ``+Inf`` bucket is NOT included (histograms add it)."""
    if not (lo > 0 and hi > lo):
        raise ValueError("want 0 < lo < hi")
    if per_decade < 1:
        raise ValueError("per_decade must be >= 1")
    n = int(round(math.log10(hi / lo) * per_decade))
    out = [lo * 10.0 ** (i / per_decade) for i in range(n + 1)]
    # round to a stable short decimal so bucket labels are reproducible
    return tuple(float(f"{b:.6g}") for b in out)


#: 1 µs … 1000 s — latencies, compile seconds, checkpoint writes.
DEFAULT_BUCKETS = log_buckets(1e-6, 1e3, per_decade=2)

#: 1e-14 … 100 — convergence residuals (relative measures near eps64).
RESIDUAL_BUCKETS = log_buckets(1e-14, 1e2, per_decade=1)

_OVERFLOW = "<overflow>"


# ---------------------------------------------------------------------------
# series (children)
# ---------------------------------------------------------------------------

class _Series:
    __slots__ = ("labels",)

    def __init__(self, labels: Tuple[str, ...]):
        self.labels = labels


class Counter(_Series):
    """Monotonically increasing value. ``inc`` with a negative amount
    raises — counters only go up (rate() must be meaningful)."""

    __slots__ = ("_family", "value")

    def __init__(self, family, labels):
        super().__init__(labels)
        self._family = family
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        if amount < 0:
            raise ValueError("counters can only increase")
        with self._family._lock:
            self.value += amount


class Gauge(_Series):
    """Point-in-time value (queue depths, live peer counts)."""

    __slots__ = ("_family", "value")

    def __init__(self, family, labels):
        super().__init__(labels)
        self._family = family
        self.value = 0.0

    def set(self, value: float) -> None:
        if not _enabled:
            return
        with self._family._lock:
            self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        if not _enabled:
            return
        with self._family._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Histogram(_Series):
    """Fixed-bucket histogram: per-bucket observation counts plus sum
    and count (Prometheus semantics; cumulative ``le`` series are
    materialized at render time, not stored)."""

    __slots__ = ("_family", "bucket_counts", "sum", "count")

    def __init__(self, family, labels):
        super().__init__(labels)
        self._family = family
        # one slot per finite bound + the +Inf slot
        self.bucket_counts = [0] * (len(family.buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        if not _enabled:
            return
        value = float(value)
        if not math.isfinite(value):
            # non-finite observations land in +Inf and poison the sum;
            # count them where they are at least visible
            idx = len(self._family.buckets)
            with self._family._lock:
                self.bucket_counts[idx] += 1
                self.count += 1
            return
        idx = bisect.bisect_left(self._family.buckets, value)
        with self._family._lock:
            self.bucket_counts[idx] += 1
            self.sum += value
            self.count += 1


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


# ---------------------------------------------------------------------------
# families
# ---------------------------------------------------------------------------

class _Family:
    """All series of one metric name: one kind, one labelname schema,
    one lock, one cardinality budget."""

    def __init__(self, kind: str, name: str, help: str,
                 labelnames: Tuple[str, ...], max_series: int,
                 buckets: Optional[Tuple[float, ...]] = None):
        self.kind = kind
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self.max_series = max_series
        self.buckets = buckets or ()
        self.dropped = 0          # emissions rerouted to <overflow>
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], _Series] = {}

    def labels(self, **labels) -> _Series:
        """The series for these label values, created on first use.

        Label names must match the family schema exactly. Past the
        cardinality cap, novel label values collapse into one
        ``<overflow>`` series (and ``dropped`` counts the reroutes)."""
        if tuple(sorted(labels)) != self.labelnames:
            raise ValueError(
                f"metric {self.name!r} expects labels "
                f"{self.labelnames}, got {tuple(sorted(labels))}")
        key = tuple(str(labels[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if len(self._children) >= self.max_series:
                    self.dropped += 1
                    key = (_OVERFLOW,) * len(self.labelnames)
                    child = self._children.get(key)
                    if child is None:
                        child = _KINDS[self.kind](self, key)
                        self._children[key] = child
                else:
                    child = _KINDS[self.kind](self, key)
                    self._children[key] = child
        return child

    def series(self) -> Iterable[_Series]:
        with self._lock:
            return list(self._children.values())


class MetricsRegistry:
    """Thread-safe home of all metric families.

    ``counter`` / ``gauge`` / ``histogram`` get-or-create a family;
    re-registration with a different kind, labelname schema, or bucket
    layout raises (one name means one thing process-wide)."""

    def __init__(self, max_series_per_family: int = 64):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self.max_series_per_family = int(max_series_per_family)

    # -- family constructors ------------------------------------------------

    def _family(self, kind: str, name: str, help: str,
                labelnames: Sequence[str],
                buckets: Optional[Sequence[float]] = None) -> _Family:
        labelnames = tuple(sorted(labelnames))
        bkts = tuple(buckets) if buckets is not None else None
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                if kind == "histogram":
                    bkts = bkts or DEFAULT_BUCKETS
                    if list(bkts) != sorted(bkts):
                        raise ValueError("buckets must be sorted")
                fam = _Family(kind, name, help, labelnames,
                              self.max_series_per_family, bkts)
                self._families[name] = fam
                return fam
        if fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"not {kind}")
        if fam.labelnames != labelnames:
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{fam.labelnames}, not {labelnames}")
        if kind == "histogram" and bkts is not None \
                and tuple(fam.buckets) != bkts:
            raise ValueError(
                f"metric {name!r} already registered with different "
                "buckets")
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family("counter", name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family("gauge", name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> _Family:
        return self._family("histogram", name, help, labelnames, buckets)

    # -- introspection ------------------------------------------------------

    def families(self) -> Dict[str, _Family]:
        with self._lock:
            return dict(self._families)

    def snapshot(self) -> dict:
        """JSON-able dump of every family and series (the dict behind
        :func:`raft_tpu.obs.export.snapshot`)."""
        out: dict = {}
        for name, fam in sorted(self.families().items()):
            with fam._lock:
                series = []
                for child in fam._children.values():
                    entry: dict = {
                        "labels": dict(zip(fam.labelnames, child.labels))}
                    if fam.kind == "histogram":
                        entry["buckets"] = dict(
                            zip([str(b) for b in fam.buckets] + ["+Inf"],
                                list(child.bucket_counts)))
                        entry["sum"] = child.sum
                        entry["count"] = child.count
                    else:
                        entry["value"] = child.value
                    series.append(entry)
                out[name] = {"type": fam.kind, "help": fam.help,
                             "labelnames": list(fam.labelnames),
                             "dropped_series": fam.dropped,
                             "series": series}
        return out

    def reset(self) -> None:
        """Drop every family (tests)."""
        with self._lock:
            self._families.clear()


# ---------------------------------------------------------------------------
# process-global default registry + emit helpers (the ONLY API
# instrumented modules use; ci/smoke.sh lints for this)
# ---------------------------------------------------------------------------

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Swap the process-global registry (tests); returns the old one."""
    global _default_registry
    old, _default_registry = _default_registry, registry
    return old


def inc(name: str, amount: float = 1.0, help: str = "",
        **labels) -> None:
    """Increment counter ``name`` (created on first use). No-op when
    metrics are off."""
    if not _enabled:
        return
    _default_registry.counter(
        name, help, tuple(labels)).labels(**labels).inc(amount)


def set_gauge(name: str, value: float, help: str = "", **labels) -> None:
    """Set gauge ``name``. No-op when metrics are off."""
    if not _enabled:
        return
    _default_registry.gauge(
        name, help, tuple(labels)).labels(**labels).set(value)


def observe(name: str, value: float, help: str = "",
            buckets: Optional[Sequence[float]] = None, **labels) -> None:
    """Observe ``value`` into histogram ``name``. No-op when metrics are
    off."""
    if not _enabled:
        return
    _default_registry.histogram(
        name, help, tuple(labels), buckets).labels(**labels).observe(value)


def record_convergence(op: str, report) -> None:
    """Feed a :class:`~raft_tpu.core.guards.ConvergenceReport` into the
    solver metric families — the single hook every iterative solver
    epilogue calls (lanczos, kmeans, jacobi)."""
    if not _enabled or report is None:
        return
    inc("solver_iterations_total", max(0, int(report.n_iter)),
        help="iterations spent by iterative solvers", solver=op)
    inc("solver_runs_total", 1,
        help="solver invocations by convergence outcome", solver=op,
        converged=str(bool(report.converged)).lower())
    observe("solver_residual",
            float(report.residual),
            help="final convergence residual per solver run",
            buckets=RESIDUAL_BUCKETS, solver=op)
    if getattr(report, "breakdowns", 0):
        inc("solver_breakdowns_total", int(report.breakdowns),
            help="internally recovered solver breakdown events",
            solver=op)
    if getattr(report, "escalated", False):
        inc("solver_escalations_total", 1,
            help="solver runs that used precision escalation", solver=op)
