"""Argument validation (ref: util/input_validation.hpp, RAFT_EXPECTS)."""

from __future__ import annotations


def expect(cond: bool, msg: str) -> None:
    """RAFT_EXPECTS equivalent (ref: core/error.hpp)."""
    if not cond:
        raise ValueError(msg)


def expect_shape(arr, shape, name: str = "array") -> None:
    actual = tuple(arr.shape)
    expected = tuple(shape)
    if len(actual) != len(expected) or any(
            e is not None and a != e for a, e in zip(actual, expected)):
        raise ValueError(f"{name}: expected shape {expected}, got {actual}")


def expect_2d(arr, name: str = "array") -> None:
    if arr.ndim != 2:
        raise ValueError(f"{name}: expected 2-D array, got ndim={arr.ndim}")


def expect_same_shape(a, b, names=("a", "b")) -> None:
    if tuple(a.shape) != tuple(b.shape):
        raise ValueError(
            f"{names[0]} shape {tuple(a.shape)} != {names[1]} shape "
            f"{tuple(b.shape)}")
