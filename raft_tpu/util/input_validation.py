"""Argument validation (ref: util/input_validation.hpp, RAFT_EXPECTS).

Shape/dtype expecters are metadata-only and always on. ``expect_finite``
scans values, so it is gated on the guard mode (``core/guards.py``):
under ``off`` the entry point pays nothing and NaN propagates exactly as
before the guardrails landed.
"""

from __future__ import annotations


def expect(cond: bool, msg: str) -> None:
    """RAFT_EXPECTS equivalent (ref: core/error.hpp)."""
    if not cond:
        raise ValueError(msg)


def expect_shape(arr, shape, name: str = "array") -> None:
    actual = tuple(arr.shape)
    expected = tuple(shape)
    if len(actual) != len(expected) or any(
            e is not None and a != e for a, e in zip(actual, expected)):
        raise ValueError(f"{name}: expected shape {expected}, got {actual}")


def expect_2d(arr, name: str = "array") -> None:
    if arr.ndim != 2:
        raise ValueError(f"{name}: expected 2-D array, got ndim={arr.ndim}")


def expect_same_shape(a, b, names=("a", "b")) -> None:
    if tuple(a.shape) != tuple(b.shape):
        raise ValueError(
            f"{names[0]} shape {tuple(a.shape)} != {names[1]} shape "
            f"{tuple(b.shape)}")


def expect_square(arr, name: str = "array") -> None:
    """A 2-D array with equal dims (eigensolver/factorization inputs)."""
    shape = tuple(arr.shape)
    if len(shape) != 2 or shape[0] != shape[1]:
        raise ValueError(f"{name}: expected a square matrix, got shape "
                         f"{shape}")


def expect_dtype(arr, dtypes, name: str = "array") -> None:
    """Dtype membership check (TypeError, matching the runtime layer's
    foreign-dtype rejections). ``dtypes`` is one dtype-like or a
    sequence."""
    import numpy as np

    if not isinstance(dtypes, (tuple, list, set)):
        dtypes = (dtypes,)
    want = {np.dtype(d) for d in dtypes}
    got = np.dtype(arr.dtype)
    if got not in want:
        raise TypeError(
            f"{name}: dtype {got} not in {sorted(str(d) for d in want)}")


def expect_positive(value, name: str = "value",
                    strict: bool = True) -> None:
    """A host scalar (or 0-d array) that must be > 0 (>= 0 when
    ``strict=False``) and finite."""
    import math

    v = float(value)
    ok = v > 0.0 if strict else v >= 0.0
    if not (math.isfinite(v) and ok):
        bound = ">" if strict else ">="
        raise ValueError(f"{name}: expected a finite value {bound} 0, "
                         f"got {v!r}")


def expect_finite(arr, name: str = "array", guard_mode=None) -> None:
    """All-finite value check, gated on the guard mode.

    Under guard mode ``off`` (the default) this is a no-op — entry
    points stay bit-identical and pay nothing. Under ``check``/
    ``recover`` a non-finite input raises ``NonFiniteError`` naming the
    argument, attributing garbage-in at the boundary instead of letting
    it surface as a NaN result ten ops downstream."""
    from raft_tpu.core.guards import check_finite

    check_finite(name, arr, mode=guard_mode, stage="input")
