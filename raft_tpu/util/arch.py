"""TPU generation query + capability dispatch (ref: raft/util/arch.cuh:38-121
`SM_compute_arch` / `SM_runtime` / `SM_range` — the reference gates kernel
variants on the streaming-multiprocessor architecture; the TPU analogue
gates on the accelerator generation reported by the runtime).

The reference's dispatch is two-sided (compile-time arch vs runtime arch)
because CUDA fatbins carry per-arch code. Under XLA there is exactly one
runtime target per process, so the TPU side collapses to a runtime query
plus capability tables — used the same way (pick a kernel variant, size a
VMEM budget) but with no compile-time half to reconcile.

>>> from raft_tpu.util.arch import TpuArch, runtime_arch, ArchRange
>>> ArchRange(min_gen=4).contains(TpuArch("TPU v5 lite"))
True
"""

from __future__ import annotations

import re
from typing import Optional


class TpuArch:
    """One accelerator generation, parsed from a PJRT ``device_kind``
    string (e.g. ``"TPU v5 lite"``, ``"TPU v4"``, ``"TPU v5p"``).

    ``gen`` is the major generation (0 for non-TPU/unknown: CPU backends
    compare below every real generation, mirroring how the reference's
    SM_MIN sorts below every real arch); ``lite`` marks the e-line
    (v5e/lite cores: single-core chips, smaller HBM)."""

    def __init__(self, device_kind: str):
        self.device_kind = str(device_kind)
        low = self.device_kind.lower()
        # anchored to TPU kinds: a bare v\d+ would parse GPU kinds like
        # "Tesla V100" to a bogus high generation. Two spellings exist:
        # "TPU v5 lite"/"TPU v4" and the v7-era "TPU7x"
        m = (re.search(r"tpu\s*v(\d+)", low)
             or re.search(r"tpu(\d+)", low))
        self.gen = int(m.group(1)) if m else 0
        self.lite = self.gen > 0 and (
            "lite" in low or bool(re.search(r"v\d+e", low)))

    def __repr__(self):
        return (f"TpuArch({self.device_kind!r}: gen={self.gen}"
                f"{' lite' if self.lite else ''})")

    def __eq__(self, other):
        return (isinstance(other, TpuArch)
                and (self.gen, self.lite) == (other.gen, other.lite))

    def __hash__(self):
        return hash((self.gen, self.lite))


def runtime_arch() -> TpuArch:
    """The arch the runtime actually has (ref: SM_runtime / kernel_runtime
    acquisition) — from device 0's ``device_kind``; non-TPU backends
    parse to gen 0."""
    import jax

    try:
        return TpuArch(jax.devices()[0].device_kind)
    except Exception:
        return TpuArch("unknown")


class ArchRange:
    """Inclusive generation gate [min_gen, max_gen] (ref: SM_range(min,
    max) guarding kernel variants). ``contains`` ignores unknown (gen 0)
    archs only when ``allow_unknown`` — the CPU-interpret path runs
    every variant."""

    def __init__(self, min_gen: int = 0, max_gen: Optional[int] = None,
                 allow_unknown: bool = True):
        self.min_gen = min_gen
        self.max_gen = max_gen
        self.allow_unknown = allow_unknown

    def contains(self, arch: TpuArch) -> bool:
        if arch.gen == 0:
            return self.allow_unknown
        if arch.gen < self.min_gen:
            return False
        return self.max_gen is None or arch.gen <= self.max_gen


# Capability facts (the role of cudaDeviceProp in the reference's grid
# sizing). Every generation this framework targets (v4/v5e/v5p/v6e)
# reports 128 MiB of per-core VMEM — the figure the round-5 hardware
# capture measured against ("Used 274.08M of 128.00M vmem", v5e AOT
# compile) — so the table is a single constant until a generation
# diverges; keep the function as the dispatch point, not the number.
_VMEM_BYTES_PER_CORE = 128 * 1024 * 1024
_MXU_DIM = 128        # systolic array edge — stable across v4/v5/v6
_LANES = 128
_SUBLANES = 8


def vmem_bytes(arch: Optional[TpuArch] = None) -> int:
    """Total per-core VMEM for ``arch`` (default: the runtime arch)."""
    del arch
    return _VMEM_BYTES_PER_CORE


def mxu_dim(arch: Optional[TpuArch] = None) -> int:
    """Systolic-array edge length (matmul tile quantum)."""
    del arch
    return _MXU_DIM


def vreg_shape(arch: Optional[TpuArch] = None) -> tuple:
    """(sublanes, lanes) of one vector register."""
    del arch
    return (_SUBLANES, _LANES)
