"""Set-associative device vector cache (ref: raft/util/cache.cuh:102
`Cache`, util/cache_util.cuh; used upstream to cache kernel-matrix columns
in SVM-style workloads).

TPU design: the reference's GPU hash-cache uses per-set atomic clocks for
pseudo-LRU victim selection inside a kernel. Here the cache state lives in
device arrays (keys, timestamps, payload matrix) updated with pure
scatter/gather ops; the host drives eviction decisions (lookup/assign are
one jitted gather/scatter each — no atomics needed because assignment
batches are deduplicated up front).

Two tiers (round-5: the round-4 VERDICT flagged the missing DEVICE
primitive):

- :class:`VectorCache` — host-driven, API parity with the reference's
  SVM-style workloads where the caller already round-trips to the host
  between kernel launches. NOT usable inside jit.
- :func:`device_cache_init` / :func:`device_cache_lookup` /
  :func:`device_cache_insert` over :class:`DeviceCacheState` — the
  jit-usable counterpart: pure cache state threaded through jit /
  ``lax.scan`` (the role the reference's in-kernel lookup/assign play,
  util/cache_util.cuh), per-set pseudo-LRU via on-device timestamps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import obs


class VectorCache:
    """Cache for n-dimensional vectors addressed by integer keys.

    Equivalent surface to `Cache<math_t>` (util/cache.cuh:102):
    get_vecs / store_vecs / get_cache_idx / assign_cache_idx.
    """

    def __init__(self, n_vec: int, capacity: int, associativity: int = 32,
                 dtype=jnp.float32):
        if capacity <= 0:
            raise ValueError("cache capacity must be positive")
        self.n_vec = n_vec
        self.associativity = min(associativity, capacity)
        self.n_sets = max(1, capacity // self.associativity)
        self.capacity = self.n_sets * self.associativity
        self.keys = jnp.full((self.capacity,), -1, jnp.int32)
        self.time = jnp.zeros((self.capacity,), jnp.int32)
        self.store = jnp.zeros((self.capacity, n_vec), dtype)
        self._clock = 0

    def _set_of(self, keys):
        return keys % self.n_sets

    def get_cache_idx(self, keys):
        """For each key: its cache slot, or -1 on miss. Hits refresh the
        entry's timestamp so eviction is true LRU, like the reference
        (ref: GetCacheIdx kernel updates cache_time on hit)."""
        keys = jnp.asarray(keys, jnp.int32)
        sets = self._set_of(keys)                         # [q]
        lanes = jnp.arange(self.associativity)
        slot_ids = sets[:, None] * self.associativity + lanes[None, :]
        slot_keys = self.keys[slot_ids]                   # [q, assoc]
        hit = slot_keys == keys[:, None]
        lane = jnp.argmax(hit, axis=1)
        idx = jnp.where(jnp.any(hit, axis=1),
                        sets * self.associativity + lane, -1)
        hits = np.asarray(idx)
        hits = hits[hits >= 0]
        if obs.enabled():
            if hits.size:
                obs.inc("cache_lookups_total", int(hits.size),
                        cache="vector", outcome="hit")
            misses = int(keys.shape[0]) - int(hits.size)
            if misses:
                obs.inc("cache_lookups_total", misses,
                        cache="vector", outcome="miss")
        if hits.size:
            self._clock += 1
            self.time = self.time.at[jnp.asarray(hits)].set(self._clock)
        return idx

    def assign_cache_idx(self, keys):
        """Assign slots for (missing) keys, evicting the least-recently-used
        slot in each set (ref: AssignCacheIdx kernel). Duplicate keys and
        same-set collisions beyond the associativity get -1, like the
        reference (callers retry next round)."""
        keys_h = np.asarray(keys, np.int32)
        out = np.full(keys_h.shape, -1, np.int32)
        taken: dict[int, set] = {}
        keys_dev = np.array(self.keys)   # mutable host copies
        time_dev = np.array(self.time)
        seen = set(keys_dev[keys_dev >= 0].tolist())
        for i, k in enumerate(keys_h):
            k = int(k)
            if k in seen:
                continue
            s = k % self.n_sets
            base = s * self.associativity
            lanes = range(base, base + self.associativity)
            used = taken.setdefault(s, set())
            # pick LRU lane not already taken this round
            cand = [j for j in lanes if j not in used]
            if not cand:
                continue
            j = min(cand, key=lambda j: (keys_dev[j] >= 0, time_dev[j]))
            used.add(j)
            out[i] = j
            keys_dev[j] = k
            seen.add(k)
        self._clock += 1
        self.keys = jnp.asarray(keys_dev)
        self.time = self.time.at[jnp.asarray(
            out[out >= 0])].set(self._clock)
        return jnp.asarray(out)

    def store_vecs(self, vecs, cache_idx):
        """Write vectors into assigned slots (ref: StoreVecs).

        Only rows with a valid slot are scattered — masking invalid rows
        through a dummy index would create duplicate-index writes whose
        winner is unspecified in JAX."""
        vecs = jnp.asarray(vecs)
        idx_h = np.asarray(cache_idx, np.int32)
        valid = np.nonzero(idx_h >= 0)[0]
        if valid.size == 0:
            return
        slots = jnp.asarray(idx_h[valid])
        self.store = self.store.at[slots].set(vecs[jnp.asarray(valid)])
        self._clock += 1
        self.time = self.time.at[slots].set(self._clock)

    def get_vecs(self, cache_idx):
        """Gather cached vectors for slot indices (ref: GetVecs)."""
        idx = jnp.asarray(cache_idx, jnp.int32)
        return self.store[jnp.where(idx >= 0, idx, 0)]

    def get_or_compute(self, keys, compute_fn):
        """Convenience wrapper: return vectors for keys, computing and
        caching misses via ``compute_fn(missing_keys) -> [m, n_vec]``."""
        keys = jnp.asarray(keys, jnp.int32)
        idx = self.get_cache_idx(keys)
        miss_rows = np.nonzero(np.asarray(idx < 0))[0]
        fresh = None
        if miss_rows.size:
            missing = keys[jnp.asarray(miss_rows)]
            fresh = compute_fn(missing)
            slots = self.assign_cache_idx(missing)
            self.store_vecs(fresh, slots)
            idx = self.get_cache_idx(keys)
        out = self.get_vecs(idx)
        still = np.nonzero(np.asarray(idx < 0))[0]
        if still.size:
            # Rows can be missing on re-query for two reasons: (a) an
            # associativity conflict in this batch (the row originally
            # missed, its vector is in `fresh` — reuse it) or (b) the row
            # hit at first but its slot was evicted by this very batch's
            # assignments (recompute just those).
            pos_in_miss = {int(k): i for i, k in enumerate(miss_rows)}
            reuse = [r for r in still if int(r) in pos_in_miss]
            evicted = [r for r in still if int(r) not in pos_in_miss]
            if reuse:
                rows = jnp.asarray([pos_in_miss[int(r)] for r in reuse])
                out = out.at[jnp.asarray(np.asarray(reuse))].set(fresh[rows])
            if evicted:
                ev = jnp.asarray(np.asarray(evicted))
                out = out.at[ev].set(compute_fn(keys[ev]))
        return out


# ---------------------------------------------------------------------------
# Device-resident functional cache (round 5): the jit-USABLE counterpart of
# VectorCache. The reference's Cache is a device primitive (its lookup /
# assign run inside kernels, util/cache_util.cuh); under XLA the analogue is
# a PURE cache state threaded through jit / lax.scan — no host round-trips,
# no atomics (per-set pseudo-LRU picks victims with argmin over on-device
# timestamps, the role of cache_util.cuh's per-set clocks).
# ---------------------------------------------------------------------------

class DeviceCacheState:
    """Pytree cache state: thread through jit/scan like any other carry.

    Layout: ``keys``/``time`` (n_sets, assoc) i32 (-1 = empty slot),
    ``store`` (n_sets, assoc, n_vec), ``clock`` () i32.
    """

    def __init__(self, keys, time, store, clock):
        self.keys = keys
        self.time = time
        self.store = store
        self.clock = clock

    @property
    def n_sets(self):
        return self.keys.shape[0]

    @property
    def associativity(self):
        return self.keys.shape[1]


jax.tree_util.register_pytree_node(
    DeviceCacheState,
    lambda s: ((s.keys, s.time, s.store, s.clock), None),
    lambda _, leaves: DeviceCacheState(*leaves))


def device_cache_init(n_vec: int, capacity: int, associativity: int = 32,
                      dtype=jnp.float32) -> DeviceCacheState:
    """Fresh empty cache state (device arrays).

    Capacity rounds UP to a whole number of sets (never allocates fewer
    slots than requested). Keys must be non-negative: negative keys are
    the empty-slot sentinel domain — lookups of them always miss and
    inserts of them are dropped (see lookup/insert).
    """
    if capacity <= 0:
        raise ValueError("cache capacity must be positive")
    assoc = min(associativity, capacity)
    n_sets = max(1, -(-capacity // assoc))
    return DeviceCacheState(
        keys=jnp.full((n_sets, assoc), -1, jnp.int32),
        time=jnp.zeros((n_sets, assoc), jnp.int32),
        store=jnp.zeros((n_sets, assoc, n_vec), dtype),
        clock=jnp.zeros((), jnp.int32))


def _cache_set_of(keys, n_sets):
    """ONE spelling of the sentinel contract for lookup AND insert:
    returns (valid, set_index) with invalid (negative) keys mapped to the
    out-of-range index n_sets — scatters drop them (mode='drop') and
    gathers clamp them (the matching mask is already False)."""
    valid = keys >= 0
    return valid, jnp.where(valid, keys % n_sets, n_sets)


def device_cache_lookup(state: DeviceCacheState, keys):
    """Batched lookup: ``(vecs [B, n_vec], hit [B] bool, new_state)``.

    Pure/traceable (usable inside jit and as a scan carry). Hits refresh
    their slot's timestamp (true LRU, ref: GetCacheIdx's cache_time
    update); missed rows return zeros with ``hit=False``.
    """
    keys = jnp.asarray(keys, jnp.int32)
    valid, s = _cache_set_of(keys, state.n_sets)
    set_keys = state.keys[jnp.minimum(s, state.n_sets - 1)]  # [B, assoc]
    match = (set_keys == keys[:, None]) & valid[:, None]
    hit = jnp.any(match, axis=1)
    way = jnp.argmax(match, axis=1)
    vecs = jnp.where(hit[:, None],
                     state.store[jnp.minimum(s, state.n_sets - 1), way],
                     0)
    clock = state.clock + 1
    # touch hits (duplicate (s, way) pairs collapse to one write — any
    # winner carries the same new timestamp)
    time = state.time.at[jnp.where(hit, s, state.n_sets),
                         way].set(clock, mode="drop")
    return vecs, hit, DeviceCacheState(state.keys, time, state.store,
                                       clock)


def device_cache_insert(state: DeviceCacheState, keys, vecs
                        ) -> DeviceCacheState:
    """Insert/overwrite a batch: returns the new state.

    Victim choice per entry: the key's existing slot if present, else
    the set's LRU way (empty ways first). Batch contract (same as the
    reference's AssignCacheIdx batching): keys within one batch should
    be distinct; two same-set keys in one batch may pick the same victim
    way, in which case WHICH row wins is unspecified (XLA leaves
    duplicate-index scatter order open) — dedup batches for
    deterministic contents. Negative keys (the empty-slot sentinel
    domain) are dropped.
    """
    keys = jnp.asarray(keys, jnp.int32)
    vecs = jnp.asarray(vecs)
    valid, s = _cache_set_of(keys, state.n_sets)
    set_keys = state.keys[jnp.minimum(s, state.n_sets - 1)]  # [B, assoc]
    match = set_keys == keys[:, None]
    present = jnp.any(match, axis=1)
    hit_way = jnp.argmax(match, axis=1)
    # LRU way: empty slots sort below every timestamp
    set_time = jnp.where(set_keys < 0, jnp.int32(-2**31),
                         state.time[s])
    lru_way = jnp.argmin(set_time, axis=1)
    way = jnp.where(present, hit_way, lru_way)
    clock = state.clock + 1
    new_keys = state.keys.at[s, way].set(keys, mode="drop")
    new_time = state.time.at[s, way].set(clock, mode="drop")
    new_store = state.store.at[s, way].set(
        vecs.astype(state.store.dtype), mode="drop")
    return DeviceCacheState(new_keys, new_time, new_store, clock)
