"""Set-associative device vector cache (ref: raft/util/cache.cuh:102
`Cache`, util/cache_util.cuh; used upstream to cache kernel-matrix columns
in SVM-style workloads).

TPU design: the reference's GPU hash-cache uses per-set atomic clocks for
pseudo-LRU victim selection inside a kernel. Here the cache state lives in
device arrays (keys, timestamps, payload matrix) updated with pure
scatter/gather ops; the host drives eviction decisions (lookup/assign are
one jitted gather/scatter each — no atomics needed because assignment
batches are deduplicated up front).

Scope (round-4 clarification, VERDICT weak #7): this class exists for API
parity with the reference's host-driven SVM-style workloads, where the
caller already round-trips to the host between kernel launches and the
cache lookup rides that existing sync. It is NOT usable inside jit (the
host drives eviction), and it is deliberately unbenchmarked: its win
condition is avoiding an expensive kernel-matrix column recompute, which
depends entirely on the caller's workload, not on this container.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


class VectorCache:
    """Cache for n-dimensional vectors addressed by integer keys.

    Equivalent surface to `Cache<math_t>` (util/cache.cuh:102):
    get_vecs / store_vecs / get_cache_idx / assign_cache_idx.
    """

    def __init__(self, n_vec: int, capacity: int, associativity: int = 32,
                 dtype=jnp.float32):
        if capacity <= 0:
            raise ValueError("cache capacity must be positive")
        self.n_vec = n_vec
        self.associativity = min(associativity, capacity)
        self.n_sets = max(1, capacity // self.associativity)
        self.capacity = self.n_sets * self.associativity
        self.keys = jnp.full((self.capacity,), -1, jnp.int32)
        self.time = jnp.zeros((self.capacity,), jnp.int32)
        self.store = jnp.zeros((self.capacity, n_vec), dtype)
        self._clock = 0

    def _set_of(self, keys):
        return keys % self.n_sets

    def get_cache_idx(self, keys):
        """For each key: its cache slot, or -1 on miss. Hits refresh the
        entry's timestamp so eviction is true LRU, like the reference
        (ref: GetCacheIdx kernel updates cache_time on hit)."""
        keys = jnp.asarray(keys, jnp.int32)
        sets = self._set_of(keys)                         # [q]
        lanes = jnp.arange(self.associativity)
        slot_ids = sets[:, None] * self.associativity + lanes[None, :]
        slot_keys = self.keys[slot_ids]                   # [q, assoc]
        hit = slot_keys == keys[:, None]
        lane = jnp.argmax(hit, axis=1)
        idx = jnp.where(jnp.any(hit, axis=1),
                        sets * self.associativity + lane, -1)
        hits = np.asarray(idx)
        hits = hits[hits >= 0]
        if hits.size:
            self._clock += 1
            self.time = self.time.at[jnp.asarray(hits)].set(self._clock)
        return idx

    def assign_cache_idx(self, keys):
        """Assign slots for (missing) keys, evicting the least-recently-used
        slot in each set (ref: AssignCacheIdx kernel). Duplicate keys and
        same-set collisions beyond the associativity get -1, like the
        reference (callers retry next round)."""
        keys_h = np.asarray(keys, np.int32)
        out = np.full(keys_h.shape, -1, np.int32)
        taken: dict[int, set] = {}
        keys_dev = np.array(self.keys)   # mutable host copies
        time_dev = np.array(self.time)
        seen = set(keys_dev[keys_dev >= 0].tolist())
        for i, k in enumerate(keys_h):
            k = int(k)
            if k in seen:
                continue
            s = k % self.n_sets
            base = s * self.associativity
            lanes = range(base, base + self.associativity)
            used = taken.setdefault(s, set())
            # pick LRU lane not already taken this round
            cand = [j for j in lanes if j not in used]
            if not cand:
                continue
            j = min(cand, key=lambda j: (keys_dev[j] >= 0, time_dev[j]))
            used.add(j)
            out[i] = j
            keys_dev[j] = k
            seen.add(k)
        self._clock += 1
        self.keys = jnp.asarray(keys_dev)
        self.time = self.time.at[jnp.asarray(
            out[out >= 0])].set(self._clock)
        return jnp.asarray(out)

    def store_vecs(self, vecs, cache_idx):
        """Write vectors into assigned slots (ref: StoreVecs).

        Only rows with a valid slot are scattered — masking invalid rows
        through a dummy index would create duplicate-index writes whose
        winner is unspecified in JAX."""
        vecs = jnp.asarray(vecs)
        idx_h = np.asarray(cache_idx, np.int32)
        valid = np.nonzero(idx_h >= 0)[0]
        if valid.size == 0:
            return
        slots = jnp.asarray(idx_h[valid])
        self.store = self.store.at[slots].set(vecs[jnp.asarray(valid)])
        self._clock += 1
        self.time = self.time.at[slots].set(self._clock)

    def get_vecs(self, cache_idx):
        """Gather cached vectors for slot indices (ref: GetVecs)."""
        idx = jnp.asarray(cache_idx, jnp.int32)
        return self.store[jnp.where(idx >= 0, idx, 0)]

    def get_or_compute(self, keys, compute_fn):
        """Convenience wrapper: return vectors for keys, computing and
        caching misses via ``compute_fn(missing_keys) -> [m, n_vec]``."""
        keys = jnp.asarray(keys, jnp.int32)
        idx = self.get_cache_idx(keys)
        miss_rows = np.nonzero(np.asarray(idx < 0))[0]
        fresh = None
        if miss_rows.size:
            missing = keys[jnp.asarray(miss_rows)]
            fresh = compute_fn(missing)
            slots = self.assign_cache_idx(missing)
            self.store_vecs(fresh, slots)
            idx = self.get_cache_idx(keys)
        out = self.get_vecs(idx)
        still = np.nonzero(np.asarray(idx < 0))[0]
        if still.size:
            # Rows can be missing on re-query for two reasons: (a) an
            # associativity conflict in this batch (the row originally
            # missed, its vector is in `fresh` — reuse it) or (b) the row
            # hit at first but its slot was evicted by this very batch's
            # assignments (recompute just those).
            pos_in_miss = {int(k): i for i, k in enumerate(miss_rows)}
            reuse = [r for r in still if int(r) in pos_in_miss]
            evicted = [r for r in still if int(r) not in pos_in_miss]
            if reuse:
                rows = jnp.asarray([pos_in_miss[int(r)] for r in reuse])
                out = out.at[jnp.asarray(np.asarray(reuse))].set(fresh[rows])
            if evicted:
                ev = jnp.asarray(np.asarray(evicted))
                out = out.at[ev].set(compute_fn(keys[ev]))
        return out
