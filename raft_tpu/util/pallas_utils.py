"""Pallas launch plumbing shared by raft_tpu kernels.

Kernels compile via Mosaic on TPU and fall back to the Pallas interpreter on
CPU (so the test suite runs on a virtual CPU mesh, mirroring the reference's
strategy of validating kernels against host references).

shard_map integration: raft_tpu kernels run *inside* shard_map with
``check_vma=True`` (the per-shard SPMD path of MNMG algorithms). On the
compiled (Mosaic) path this works natively: operands are pcast to the joint
varying-mesh-axes set (:func:`join_vma`) and out_shapes declare their vma
(:func:`out_struct`) — verified bit-identical in/out of shard_map on v5e.

The HLO *interpreter* cannot replay a kernel jaxpr whose operands carry vma
(jax 0.9.0 traces the kernel with vma-free block avals, then replays it with
vma-carrying tracers; primitive replay skips the pvary insertion the eager
jnp layer performs, so any kernel mixing an iota/constant with a block input
fails). :func:`interpret_needs_ref` detects that case; each kernel supplies
a numerically-matching jnp reference for it. This affects only the CPU test
tier — hardware always runs the real kernel.
"""

from __future__ import annotations

import functools

import jax
from jax.experimental import pallas as pl

# Minimum lane-aligned block edge for f32 (sublane 8 × lane 128).
MIN_BLOCK = (8, 128)


@functools.lru_cache(maxsize=None)
def use_interpret() -> bool:
    """True when Pallas must run interpreted (no TPU backend present)."""
    from raft_tpu.core import env

    forced = env.read("RAFT_TPU_PALLAS_INTERPRET")
    if forced is not None:
        return forced
    return jax.default_backend() != "tpu"


def _vma(a):
    # jax versions without jax.typeof predate the vma type system:
    # nothing varies explicitly, pcast plumbing degrades to a no-op
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return frozenset()
    return getattr(typeof(a), "vma", frozenset()) or frozenset()


def join_vma(*arrays):
    """Return (vma, arrays) with every array pcast up to the union of the
    operands' varying-mesh-axes. Outside shard_map the vma is empty and the
    arrays come back untouched."""
    vma = frozenset()
    for a in arrays:
        vma |= _vma(a)
    if not vma:
        return vma, arrays
    out = pcast_to(vma, *arrays)
    return vma, out if isinstance(out, tuple) else (out,)


def pcast_to(vma, *arrays):
    """pcast each array UP to ``vma`` (no-op outside shard_map). Use for
    loop-carry inits that must match varying body outputs (lax.scan /
    while_loop require carry types, incl. vma, to be invariant)."""
    if not vma:
        return arrays if len(arrays) != 1 else arrays[0]
    out = []
    for a in arrays:
        missing = tuple(sorted(frozenset(vma) - _vma(a)))
        out.append(jax.lax.pcast(a, missing, to="varying") if missing else a)
    return tuple(out) if len(out) != 1 else out[0]


def out_struct(shape, dtype, vma=frozenset()):
    """ShapeDtypeStruct carrying the varying-mesh-axes type when non-empty
    (required by pallas_call under shard_map check_vma=True)."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def has_vma(*arrays) -> bool:
    """True when any operand carries varying-mesh-axes (i.e. we are under
    shard_map). Kernels without vma plumbing (join_vma + out_struct vma)
    must not be dispatched to in that case — their vma-free out_shapes
    fail check_vma on the compiled path, not just in the interpreter."""
    return any(_vma(a) for a in arrays)


def interpret_needs_ref(*arrays) -> bool:
    """True when this call would hit the interpreter's vma replay limitation
    (see module doc): interpret mode AND some operand varies over mesh axes.
    Callers run their jnp reference formulation instead."""
    if not use_interpret():
        return False
    return any(_vma(a) for a in arrays)


def pallas_call(kernel, **kwargs):
    """`pl.pallas_call` with backend-appropriate interpret default."""
    kwargs.setdefault("interpret", use_interpret())
    return pl.pallas_call(kernel, **kwargs)
