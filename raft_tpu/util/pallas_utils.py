"""Pallas launch plumbing shared by raft_tpu kernels.

Kernels compile via Mosaic on TPU and fall back to the Pallas interpreter on
CPU (so the test suite runs on a virtual CPU mesh, mirroring the reference's
strategy of validating kernels against host references).
"""

from __future__ import annotations

import functools
import os

import jax
from jax.experimental import pallas as pl

# Minimum lane-aligned block edge for f32 (sublane 8 × lane 128).
MIN_BLOCK = (8, 128)


@functools.lru_cache(maxsize=None)
def use_interpret() -> bool:
    """True when Pallas must run interpreted (no TPU backend present)."""
    forced = os.environ.get("RAFT_TPU_PALLAS_INTERPRET")
    if forced is not None:
        return forced not in ("0", "false", "")
    return jax.default_backend() != "tpu"


def pallas_call(kernel, **kwargs):
    """`pl.pallas_call` with backend-appropriate interpret default."""
    kwargs.setdefault("interpret", use_interpret())
    return pl.pallas_call(kernel, **kwargs)
