"""Matmul precision policy — the TPU analogue of the reference's cuBLAS
compute-type selection (ref: linalg/detail/cublaslt_wrappers.hpp:28-62
``get_matmul_type``; every reference kernel otherwise computes f32 FMA).

The TPU MXU multiplies in bfloat16.  Under JAX's ``Precision.DEFAULT`` a
float32 ``jnp.dot`` runs ONE bf16 pass (~8 mantissa bits) — far below the
f32 accuracy the reference delivers through cuBLAS, and enough to flip
nearest-neighbor orderings (observed on v5e: pairwise L2 rel-err ~1.5e-3,
knn index agreement 95% vs the 99%+ the reference achieves).  raft_tpu
therefore computes matmuls at f32-equivalent precision by default and makes
the speed/accuracy trade explicit:

- ``'high'`` (default) — bf16x3 (~21 mantissa bits). Measured on v5e at
  the north-star shape: max rel-err 2.7e-6 on pairwise L2 (500× tighter
  than one bf16 pass, and 100-1000× inside the tolerances the reference's
  own tests assert), at 1.46× the speed of full f32.
- ``'highest'`` — full f32 (multi-pass decomposition); the accuracy
  contract of the reference's CUBLAS_COMPUTE_32F (f32-grade error bounds;
  not bit-identical across architectures — accumulation order differs).
- ``'default'`` — one bf16 pass (~8 mantissa bits); the fast path, opt-in
  only: measured 3.1% wrong top-10 neighbor sets.

Mechanics: JAX's ``jax_default_matmul_precision`` config is the source of
truth — it participates in jit trace-cache keys, so switching the policy
can never leave a stale compiled executable behind.  Public entry points
wrap their bodies in :func:`scope`, which supplies the framework default
only when neither the user's global config nor an enclosing
``jax.default_matmul_precision(...)`` context has chosen one.

Env override: ``RAFT_TPU_MATMUL_PRECISION`` ∈ {default, high, highest}
sets the initial policy.
"""

from __future__ import annotations

import contextlib
import functools

import jax
from jax import lax

from raft_tpu.core import env

__all__ = ["set_matmul_precision", "get_matmul_precision", "scope",
           "with_matmul_precision", "resolve"]

_CANON = {
    "default": "default", "fastest": "default", "bfloat16": "default",
    "high": "high", "bfloat16_3x": "high", "tensorfloat32": "high",
    "highest": "highest", "float32": "highest", "f32": "highest",
}

_AS_LAX = {
    "default": lax.Precision.DEFAULT,
    "high": lax.Precision.HIGH,
    "highest": lax.Precision.HIGHEST,
}

_env = env.read("RAFT_TPU_MATMUL_PRECISION")
_policy = _CANON.get(_env)
if _policy is None:
    import warnings

    warnings.warn(
        f"RAFT_TPU_MATMUL_PRECISION={_env!r} is not one of "
        f"{sorted(_AS_LAX)} (or an alias); using 'high'",
        stacklevel=2)
    _policy = "high"


def set_matmul_precision(name: str) -> None:
    """Set the framework-wide matmul precision policy.

    Also sets ``jax_default_matmul_precision`` so every subsequent trace —
    including already-jitted entry points — picks the new value up through
    its cache key (the reference's analogue is per-call compute-type
    selection in cublasLt; a process-wide knob is the TPU-idiomatic spelling
    because precision is a property of the trace, not of a handle).
    """
    global _policy
    canon = _CANON.get(str(name).lower())
    if canon is None:
        # Pass JAX-only spellings (dot-algorithm presets) straight through
        # so set(get()) round-trips even when the user configured one.
        try:
            jax.config.update("jax_default_matmul_precision", str(name))
        except Exception as e:
            raise ValueError(
                f"unknown precision {name!r}; want one of "
                f"{sorted(_AS_LAX)} or a value accepted by "
                f"jax_default_matmul_precision") from e
        return
    _policy = canon
    jax.config.update("jax_default_matmul_precision", canon)


def get_matmul_precision() -> str:
    """The precision actually in effect: the user's global
    ``jax_default_matmul_precision`` if set (returned verbatim when it is a
    JAX-only spelling such as a dot-algorithm preset), else the framework
    policy ('default' | 'high' | 'highest')."""
    cfg = jax.config.jax_default_matmul_precision
    if cfg is None:
        return _policy
    return _CANON.get(str(cfg).lower(), str(cfg))


def current_mode() -> str:
    """Trace-time accuracy tier for hand-written kernels:
    'default' | 'high' | 'highest'.

    Pallas/Mosaic rejects ``lax.Precision.HIGH`` on dots, so kernels cannot
    simply inherit the config — they read this mode and pick an
    implementation (single bf16 pass, manual bf16 hi/lo split, or full-f32
    HIGHEST). JAX-only config spellings (dot-algorithm presets) map to
    'highest' — never silently downgrade accuracy."""
    cfg = jax.config.jax_default_matmul_precision
    if cfg is None:
        return _policy
    return _CANON.get(str(cfg).lower(), "highest")


def resolve(precision=None):
    """Per-call override resolution for APIs with a ``precision=`` arg
    (gemm's compute-type parity). None → defer to :func:`scope`'s config."""
    if precision is None:
        return None
    if isinstance(precision, lax.Precision):
        return precision
    canon = _CANON.get(str(precision).lower())
    if canon is None:
        raise ValueError(
            f"unknown precision {precision!r}; want one of {sorted(_AS_LAX)} "
            f"(or a jax.lax.Precision)")
    return _AS_LAX[canon]


def scope():
    """Context supplying the framework default precision, unless the user
    already chose one globally (``jax_default_matmul_precision``) — their
    setting wins."""
    if jax.config.jax_default_matmul_precision is not None:
        return contextlib.nullcontext()
    return jax.default_matmul_precision(_policy)


def with_matmul_precision(fn):
    """Decorator: run ``fn`` under :func:`scope`. Applied to public entry
    points whose accuracy contract includes matmul results (distance,
    contractions, knn, PCA/cov, Lanczos)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with scope():
            return fn(*args, **kwargs)

    return wrapper
