"""Precision-escalation ladder and host-f64 helpers for the numerical
guardrails (``core/guards.py``).

The escalation ladder extends ``util/precision.py``'s matmul tiers with a
final host-f64 rung:

    ``default`` (one bf16 pass) → ``high`` (bf16x3) → ``highest``
    (full f32) → ``f64`` (float64, emulated on host — TPU f64 is
    software-emulated, and escalation targets are small corrective
    re-runs, not hot-path work)

``recover``-mode guards walk this ladder one rung at a time: a matmul-
shaped op (pairwise, gemm, spmv) retries under the next
``jax.default_matmul_precision`` tier; direct factorizations whose
breakdown is *dtype*-limited rather than matmul-tier-limited (the
Cholesky rank-1 pivot, the Jacobi sweep) jump to the ``f64`` rung and
recompute the failing step with float64 host arithmetic.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax
import numpy as np

from raft_tpu.util import precision

__all__ = ["LADDER", "next_tier", "tier_scope", "matmul_escalation",
           "f64_host"]

#: bf16 → f32 → f64-emulated, lowest to highest.
LADDER = ("default", "high", "highest", "f64")


def next_tier(tier: Optional[str] = None) -> Optional[str]:
    """The rung above ``tier`` (default: the matmul tier currently in
    effect), or None at the top of the ladder."""
    if tier is None:
        tier = precision.current_mode()
    try:
        i = LADDER.index(str(tier).lower())
    except ValueError:
        # JAX-only spellings (dot-algorithm presets) already map to
        # 'highest' in precision.current_mode(); anything else unknown
        # is treated as already-maximal matmul accuracy.
        return "f64"
    return LADDER[i + 1] if i + 1 < len(LADDER) else None


@contextlib.contextmanager
def tier_scope(tier: str):
    """Run a region at an explicit ladder rung.

    Matmul rungs install ``jax.default_matmul_precision``; the ``f64``
    rung is a no-op context — f64 escalation is per-op host arithmetic
    (see :func:`f64_host`), not a trace-wide dtype flip."""
    tier = str(tier).lower()
    if tier == "f64":
        yield
    elif tier in ("default", "high", "highest"):
        with jax.default_matmul_precision(tier):
            yield
    else:
        raise ValueError(f"unknown ladder tier {tier!r}; want one of "
                         f"{LADDER}")


def matmul_escalation(compute, op: str = ""):
    """A retry thunk one *matmul* rung up, or None when matmul accuracy
    is already maximal ('highest'): the generic ``recover`` hook for
    GEMM-shaped guarded ops. ``compute`` must be a nullary closure over
    the original operands (re-running it under the escalated scope
    re-traces with the higher tier in the jit cache key)."""
    nt = next_tier()
    if nt is None or nt == "f64":
        return None

    def rerun():
        with tier_scope(nt):
            return compute()

    return rerun


def f64_host(*arrays):
    """Operands as float64 numpy arrays — the top ladder rung.

    Escalated steps compute with these on host (LAPACK/numpy), then cast
    back to the original dtype; TPU f64 emulation is never entered."""
    out = tuple(np.asarray(a, np.float64) for a in arrays)
    return out[0] if len(out) == 1 else out
