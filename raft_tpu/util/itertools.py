"""Host-side test-case generation helper (ref: util/itertools.hpp)."""

from __future__ import annotations

import itertools
from typing import Any, Iterable, List


def product_of_lists(*lists: Iterable[Any]) -> List[tuple]:
    """Cartesian product used to build parameterized test inputs
    (ref: raft::util::itertools::product)."""
    return list(itertools.product(*lists))
