"""Integer / layout math helpers (ref: util/pow2_utils.cuh,
util/fast_int_div.cuh, util/integer_utils.hpp)."""

from __future__ import annotations


def cdiv(a: int, b: int) -> int:
    """Ceiling division (ref: raft::ceildiv, util/cuda_utils.cuh)."""
    return -(-a // b)


def round_up_to_multiple(x: int, m: int) -> int:
    return cdiv(x, m) * m


def round_down_to_multiple(x: int, m: int) -> int:
    return (x // m) * m


def is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def next_pow2(x: int) -> int:
    if x <= 1:
        return 1
    return 1 << (x - 1).bit_length()


def prev_pow2(x: int) -> int:
    if x < 1:
        raise ValueError("prev_pow2 requires x >= 1")
    return 1 << (x.bit_length() - 1)


class Pow2:
    """Power-of-two layout math (ref: util/pow2_utils.cuh `Pow2<Value>`)."""

    def __init__(self, value: int):
        if not is_pow2(value):
            raise ValueError(f"{value} is not a power of two")
        self.value = value
        self.mask = value - 1
        self.log2 = value.bit_length() - 1

    def round_down(self, x: int) -> int:
        return x & ~self.mask

    def round_up(self, x: int) -> int:
        return (x + self.mask) & ~self.mask

    def div(self, x: int) -> int:
        return x >> self.log2

    def mod(self, x: int) -> int:
        return x & self.mask

    def is_aligned(self, x: int) -> bool:
        return (x & self.mask) == 0


class FastIntDiv:
    """Strength-reduced division by a runtime constant
    (ref: util/fast_int_div.cuh).

    On TPU the XLA compiler already strength-reduces division by traced
    constants; this host-side version exists for API parity and host loops.
    """

    def __init__(self, divisor: int):
        if divisor <= 0:
            raise ValueError("divisor must be positive")
        self.divisor = divisor

    def div(self, x: int) -> int:
        return x // self.divisor

    def mod(self, x: int) -> int:
        return x % self.divisor

    def __call__(self, x: int) -> int:
        return self.div(x)


def bound_by_power_of_two_and_ratio(total: int, cap_pow2: int,
                                    ratio: int) -> int:
    """Pick the largest power-of-two tile ≤ cap that divides work into at
    least `ratio` pieces — the tile-size heuristic shape used throughout the
    reference's kernel policies (e.g. linalg/contractions.cuh:52-80)."""
    tile = min(cap_pow2, next_pow2(max(1, total // ratio)))
    return max(1, prev_pow2(tile))


class Seive:
    """Prime sieve (ref: util/seive.hpp — the reference uses it to pick
    hash strides for its GPU cache; kept name-compatible, misspelling and
    all).

    >>> from raft_tpu.util.math import Seive
    >>> s = Seive(30)
    >>> s.is_prime(29), s.is_prime(28)
    (True, False)
    >>> s.get_num_primes()
    10
    """

    def __init__(self, n: int):
        import numpy as np

        self._n = int(n)
        sieve = np.ones(max(self._n + 1, 2), dtype=bool)
        sieve[:2] = False
        for p in range(2, int(self._n ** 0.5) + 1):
            if sieve[p]:
                sieve[p * p::p] = False
        self._sieve = sieve
        self._primes = np.nonzero(sieve)[0]

    def is_prime(self, num: int) -> bool:
        if not 0 <= num <= self._n:
            raise ValueError(f"{num} outside sieve range [0, {self._n}]")
        return bool(self._sieve[num])

    def get_num_primes(self) -> int:
        return int(self._primes.shape[0])

    def get_primes(self):
        return self._primes.copy()
