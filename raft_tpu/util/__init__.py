"""Utility layer (ref: cpp/include/raft/util/).

The reference's util/ is intra-kernel CUDA machinery (warp shuffles, bitonic
sort, vectorized IO).  On TPU those jobs belong to the Mosaic compiler, so
the utilities that survive are the host-side ones: integer/layout math,
alignment helpers, and Pallas launch plumbing.
"""

from raft_tpu.util.math import (  # noqa: F401
    cdiv,
    round_up_to_multiple,
    round_down_to_multiple,
    is_pow2,
    next_pow2,
    prev_pow2,
    Pow2,
    FastIntDiv,
    Seive,
    bound_by_power_of_two_and_ratio,
)
from raft_tpu.util.pallas_utils import (  # noqa: F401
    use_interpret,
    pallas_call,
    MIN_BLOCK,
)
from raft_tpu.util.input_validation import (  # noqa: F401
    expect,
    expect_shape,
    expect_2d,
    expect_same_shape,
    expect_square,
    expect_dtype,
    expect_positive,
    expect_finite,
)
from raft_tpu.util import numerics  # noqa: F401
from raft_tpu.util.itertools import product_of_lists  # noqa: F401
from raft_tpu.util.arch import (ArchRange, TpuArch,  # noqa: F401
                                mxu_dim, runtime_arch, vmem_bytes,
                                vreg_shape)
from raft_tpu.util.cache import (DeviceCacheState,  # noqa: F401
                                 VectorCache, device_cache_init,
                                 device_cache_insert,
                                 device_cache_lookup)
from raft_tpu.util.precision import (  # noqa: F401
    set_matmul_precision,
    get_matmul_precision,
    with_matmul_precision,
)
