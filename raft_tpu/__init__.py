"""raft_tpu: a TPU-native library of reusable ML / data-analytics primitives.

A ground-up JAX/XLA/Pallas re-design of the capability surface of RAPIDS RAFT
(reference: jinsolp/raft 26.06.00 — see /root/reference, SURVEY.md): dense and
sparse linear algebra, matrix primitives (select_k, argmin, gather), statistics
and metrics, random generation, iterative and combinatorial solvers (Lanczos,
MST, LAP), spectral analysis, and multi-chip communicator infrastructure.

Layering mirrors the reference's shape (handle + resources, primitive free
functions, comms-in-handle, thin Python parity layer) but every implementation
is TPU-first: XLA ops and Pallas kernels instead of CUDA, jit-traced functions
instead of streams, named-axis `jax.sharding.Mesh` collectives instead of NCCL.

Subpackages
-----------
core     : resources handle, array model, operators, serialization, logging
comms    : communicator over mesh collectives (ref: cpp/include/raft/comms/)
linalg   : dense linear algebra           (ref: cpp/include/raft/linalg/)
matrix   : dense matrix ops incl select_k (ref: cpp/include/raft/matrix/)
sparse   : sparse formats, ops, solvers   (ref: cpp/include/raft/sparse/)
spectral : spectral analyzers             (ref: cpp/include/raft/spectral/)
stats    : statistics and metrics         (ref: cpp/include/raft/stats/)
random   : RNG and dataset generators     (ref: cpp/include/raft/random/)
solver   : linear assignment problem      (ref: cpp/include/raft/solver/)
label    : label utilities                (ref: cpp/include/raft/label/)
distance : pairwise distances (rebuilt from the contractions primitive layer)
cluster  : k-means (rebuilt from primitives, incl. multi-chip SPMD)
util     : host/device helper utilities   (ref: cpp/include/raft/util/)
"""

__version__ = "0.2.0"

import jax as _jax

# jax moved shard_map from jax.experimental to the top-level namespace;
# the MNMG layers call `jax.shard_map` (the long-term spelling). Alias
# it on older jax so the same call sites work across versions.
if not hasattr(_jax, "shard_map"):
    import functools as _functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @_functools.wraps(_shard_map)
    def _compat_shard_map(f, **kwargs):
        # the old check_rep analysis predates pcast/vma typing and
        # rejects carries the new checker accepts; disable it (runtime
        # semantics are unchanged — it is a static well-formedness check)
        kwargs.pop("check_vma", None)   # new-jax spelling of check_rep
        kwargs["check_rep"] = False
        return _shard_map(f, **kwargs)

    _jax.shard_map = _compat_shard_map

# Same treatment for the Pallas-TPU params rename
# (TPUCompilerParams → CompilerParams): kernels use the new spelling.
from jax.experimental.pallas import tpu as _pltpu

if not hasattr(_pltpu, "CompilerParams") and hasattr(_pltpu,
                                                    "TPUCompilerParams"):
    _pltpu.CompilerParams = _pltpu.TPUCompilerParams

from raft_tpu.core.resources import (  # noqa: F401
    Resources,
    device_resources,
    DeviceResources,
)
from raft_tpu.util.precision import (  # noqa: F401
    set_matmul_precision,
    get_matmul_precision,
)

# Subpackages are imported lazily by attribute access to keep `import raft_tpu`
# cheap (jax itself is imported eagerly by core).
import importlib as _importlib

_SUBPACKAGES = (
    "core",
    "comms",
    "linalg",
    "matrix",
    "sparse",
    "spectral",
    "stats",
    "random",
    "solver",
    "label",
    "cluster",
    "distance",
    "neighbors",
    "util",
    "compat",
    "runtime",
)


def __getattr__(name):
    if name in _SUBPACKAGES:
        try:
            module = _importlib.import_module(f"raft_tpu.{name}")
        except ImportError as e:
            raise AttributeError(
                f"subpackage raft_tpu.{name} failed to import: {e}") from e
        globals()[name] = module
        return module
    raise AttributeError(f"module 'raft_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals().keys()) + list(_SUBPACKAGES))
