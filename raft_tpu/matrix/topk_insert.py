"""Sorted-insertion top-k over a MATERIALIZED input — the
``insert_select`` path of matrix/select_k.

The drain itself (the bound-gated sorted-insertion body, its strip-width
contract, and the Mosaic legality notes that protect it) lives in the
unified epilogue layer — :func:`raft_tpu.matrix.epilogue.insert_drain`
(ISSUE 14) — shared with the fused kNN kernel
(neighbors/fused_topk.py). This module keeps the materialized-input
wrapper: the Pallas grid over (rows, columns) tiles, NaN padding, and
the degenerate-row fallback.

Reference lineage: the warpsort "filtered" insertion queues
(matrix/detail/select_warpsort.cuh:129 — insert only when the candidate
beats the current k-th bound) — same structural idea, re-derived for a
machine whose selection primitive is VPU passes instead of warp
shuffles. Hardware evidence for the shape: the kNN capture went
1883 ms (gated k-round merges) -> 97.7 ms (this drain) at 1M x 128,
q=4096, k=64 (tpu_battery_out/bench_full.jsonl, round 5).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.matrix.epilogue import (LANES, MAX_K,  # noqa: F401
                                      best_width, insert_drain,
                                      resolve_tn_sw, row_min_arg)
from raft_tpu.util.math import round_up_to_multiple
from raft_tpu.util.pallas_utils import join_vma, out_struct, pallas_call

# Back-compat alias: the drain body kept this name until it moved into
# the epilogue layer (fused_topk / external tune harnesses import it).
insertion_topk_body = insert_drain


# ---------------------------------------------------------------------------
# insert_select: the drain over a MATERIALIZED [rows, len] input — the
# select_k contender for k <= 256 (ref: the warpsort-filtered slot of
# matrix/detail/select_k-inl.cuh's algo table)
# ---------------------------------------------------------------------------


def _insert_kernel(v_ref, val_ref, idx_ref, *, tn: int, k: int,
                   n_valid: int, sw: int, select_min: bool):
    j = pl.program_id(1)
    d = v_ref[:].astype(jnp.float32)
    if not select_min:
        d = -d                     # drain extracts minima
    # (NaN -> +inf sanitization lives in the drain itself)
    insertion_topk_body(d, val_ref, idx_ref, j, tn, k, n_valid, sw)


@functools.partial(jax.jit, static_argnames=("k", "select_min", "tm",
                                             "tn", "sw"))
def _insert_padded(v, k: int, select_min: bool, tm: int, tn: int,
                   sw: int):
    m, n = v.shape
    bw = best_width(k)
    vma, (v,) = join_vma(v)
    kernel = functools.partial(_insert_kernel, tn=tn, k=k, n_valid=n,
                               sw=sw, select_min=select_min)
    mp = round_up_to_multiple(m, tm)
    np_ = round_up_to_multiple(n, tn)
    if (mp, np_) != (m, n):
        # NaN padding: the drain's NaN->inf sanitization turns padded
        # rows into zero-round no-ops in BOTH select directions (zeros
        # would insert up to k bogus rounds per block in the first
        # tile); column padding is masked by n_valid inside the body
        v = jnp.pad(v, ((0, mp - m), (0, np_ - n)),
                    constant_values=jnp.nan)
    return pallas_call(
        kernel,
        grid=(mp // tm, np_ // tn),
        in_specs=[
            pl.BlockSpec((tm, tn), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((mp, bw), jnp.float32, vma),
            out_struct((mp, bw), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(v)


def supports(dtype, k: int) -> bool:
    """f32/bf16/f16 only (the drain compares in f32 — exact for these;
    wide integers would round above 2^24), k within the 2-vreg best."""
    dtype = jnp.dtype(dtype)
    return (jnp.issubdtype(dtype, jnp.floating)
            and dtype.itemsize <= 4 and 1 <= k <= MAX_K)


def insert_select(values, k: int, select_min: bool = True,
                  tm: int = 256, tn: int = 2048, sw: int = 256):
    """Top-k of each row by bound-gated sorted insertion.

    Returns (vals [m, k], idx [m, k]), best-first, idx = positions.
    Contract notes: NaNs never insert (they compare false), i.e. they
    sort strictly last; rows with fewer than k candidates below the
    drain's +inf sentinel (k-th best would be +inf for select_min /
    -inf for select_max, or NaN-saturated) are DETECTED and re-answered
    through the direct lax.top_k path inside a ``lax.cond`` — full
    index parity with the direct path on degenerate data, one
    any-reduce of cost on clean data. Candidate pool cost is O(actual
    updates); adversarial best-last rows degrade to ~k rounds per tile
    (the merge cost), never the pool width."""
    v = jnp.asarray(values)
    m, n = v.shape
    if not supports(v.dtype, k):
        raise ValueError(f"insert_select: unsupported {v.dtype}/k={k}")
    tm = max(128, tm - tm % 128)            # (tm, bw) out blocks
    tn, sw = resolve_tn_sw(tn, sw, n)
    vals, idx = _insert_padded(v, k, select_min, tm, tn, sw)
    vals, idx = vals[:m, :k], idx[:m, :k]

    from raft_tpu.matrix.select_k import _direct_select

    def _fallback(_):
        dv, di = _direct_select(v, k, select_min)
        return dv.astype(jnp.float32), di.astype(jnp.int32)

    # unfilled slots still hold the drain's +inf sentinel (vals are in
    # the drain's sign convention only AFTER the negate below, so test
    # the raw buffer): lax.cond executes the direct path only when a
    # degenerate row exists
    degenerate = jnp.any(jnp.isinf(vals) & (vals > 0))
    vals, idx = jax.lax.cond(
        degenerate, _fallback,
        lambda _: ((-vals if not select_min else vals), idx),
        operand=None)
    return vals.astype(v.dtype), idx
