"""Sorted-insertion top-k drain: the shared epilogue primitive behind the
fused kNN kernel (neighbors/fused_topk.py) and the materialized-input
``insert_select`` path of matrix/select_k.

The drain keeps the running best (val, idx) lanes SORTED ascending in one
or two vregs per row. Each round a `lax.while_loop` extracts the per-row
pool minimum and compare-shifts it into the sorted best (`pltpu.roll` +
prefix mask); the while condition — "some row's pool still holds a value
below that row's k-th bound" — is the gate, so a dead tile costs ZERO
rounds and a tile with c improving candidates costs ~c rounds at full
vector width. Worst case (rows sorted best-last) degrades to ~k rounds
per tile — the k-round merge cost, never the pool width.

Reference lineage: the warpsort "filtered" insertion queues
(matrix/detail/select_warpsort.cuh:129 — insert only when the candidate
beats the current k-th bound) — same structural idea, re-derived for a
machine whose selection primitive is VPU passes instead of warp
shuffles. Hardware evidence for the shape: the kNN capture went
1883 ms (gated k-round merges) -> 97.7 ms (this drain) at 1M x 128,
q=4096, k=64 (tpu_battery_out/bench_full.jsonl, round 5).

Mosaic legality notes (probed via ci/aot_compile.py): reduce-min +
masked-iota argmin (contractions._mask_argmin rationale), `pltpu.roll`
lane shifts across one and two vregs, `lax.while_loop` with (tm, tn)
vector carries + i32 any-reduce condition; a (tm, 1)-index vector
gather from the (tm, bw) best is NOT legal (same-shape operand rule),
which is why the k-th bound is read by a masked one-lane reduce.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.util.math import round_up_to_multiple
from raft_tpu.util.pallas_utils import join_vma, out_struct, pallas_call

LANES = 128
MAX_K = 2 * LANES   # up to two vregs of sorted best per query row
                    # (larger k takes the radix / tournament paths)


def resolve_tn_sw(tn: int, sw: int, n: int):
    """One spelling of the tile-width clamp + strip-width contract for
    every drain consumer (knn_fused, insert_select): lane-align tn,
    clamp it to the data width, and validate sw against the REQUESTED
    tn — an sw that never divided the caller's tn is an error, while
    indivisibility introduced only by the small-data clamp degrades to
    the whole-tile drain (a perf knob must not error on small inputs).
    Returns (tn, sw)."""
    tn_req = max(128, tn - tn % 128)        # caller's lane-aligned ask
    tn = min(tn_req, round_up_to_multiple(n, 128))
    if sw and (sw < 0 or sw % 128 or tn_req % sw):
        raise ValueError(f"sw must be a positive lane-aligned divisor "
                         f"of tn={tn_req}")
    if sw and tn % sw:
        sw = 0                  # clamp-induced indivisibility only
    return tn, sw


def best_width(k: int) -> int:
    """Lane-aligned width of the sorted-best buffer: one vreg for
    k <= 128, two for k <= 256 (insert cost scales with the width, so
    the buffer is as narrow as k allows)."""
    return LANES * ((k + LANES - 1) // LANES)


def row_min_arg(pool, col):
    """Per-row (min, first-min argmin) of a (tm, tn) pool — reduce-min +
    masked-iota, the Mosaic-safe argmin spelling (see
    contractions._mask_argmin for why lax.argmin is not used)."""
    pm = jnp.min(pool, axis=1, keepdims=True)
    sentinel = jnp.asarray(jnp.iinfo(jnp.int32).max, jnp.int32)
    pidx = jnp.min(jnp.where(pool == pm, col, sentinel), axis=1,
                   keepdims=True)
    return pm, pidx


def insertion_topk_body(dist, val_ref, idx_ref, j, tn: int, k: int,
                        n_valid: int, sw: int = 0):
    """Drain a (tm, tn) candidate tile into the sorted (tm, bw) best.

    Each round: per-row pool min + first-min argmin (smallest column
    wins ties), consume that lane, and for rows where the minimum beats
    their k-th bound, compare-shift it into the sorted best. Rows whose
    pool holds nothing below their bound extract dead mins into a
    guarded no-op — progress is global (every looping row consumes one
    lane per round), and the loop exits when no row can improve. Tie
    contract (smallest index wins globally): within a tile the first-min
    argmin inserts equal values in column order; across tiles, earlier
    insertions win because ``keep = best <= candidate`` leaves existing
    entries to the left of an equal newcomer.

    ``sw`` (strip width, 0 = whole tile): drain the tile in static
    lane-aligned strips so the per-round vector work is O(tm·sw) while
    the producer tile keeps its full width — the tile width and the
    drain width are INDEPENDENT knobs. Round count is unchanged (a
    candidate is a candidate in any strip); only the dead-lane
    extraction width shrinks. Strips see ascending global columns,
    preserving the tie contract.

    NaN candidates are mapped to +inf HERE, for every producer: a NaN
    pool minimum would match no lane (nothing consumed) and the while
    loop could spin forever on the DEVICE while any finite candidate
    sits below the bound — a hang, not a wrong answer. One compare+
    select per tile element buys termination; +inf is the drain's own
    never-selected sentinel (NaN sorts last)."""
    tm = dist.shape[0]
    dist = jnp.where(jnp.isnan(dist), jnp.asarray(jnp.inf, jnp.float32),
                     dist)
    bw = best_width(k)
    lane = jax.lax.broadcasted_iota(jnp.int32, (tm, bw), 1)
    inf = jnp.asarray(jnp.inf, jnp.float32)

    @pl.when(j == 0)
    def _init():
        val_ref[:] = jnp.full((tm, bw), jnp.inf, jnp.float32)
        idx_ref[:] = jnp.zeros((tm, bw), jnp.int32)

    def kth(bv):
        # masked one-lane reduce: a (tm, 1)-index gather from (tm, bw)
        # is not Mosaic-legal (same-shape operand rule)
        return jnp.min(jnp.where(lane == k - 1, bv, inf), axis=1,
                       keepdims=True)

    def cond(carry):
        pool, bv, _ = carry
        # i32 max, not bool any: jnp.any's bool proxy reduces through
        # f64 under jax_enable_x64 and fails Mosaic lowering
        # (radix_select precedent)
        return jnp.max((pool < kth(bv)).astype(jnp.int32)) > 0

    def drain(pool, col_g, bv, bi):
        def body(carry):
            pool, bv, bi = carry
            pm, pidx = row_min_arg(pool, col_g)
            pool = jnp.where(col_g == pidx, inf, pool)  # consume lane
            improving = pm < kth(bv)
            keep = bv <= pm                 # prefix mask (sorted best)
            pos = jnp.sum(keep.astype(jnp.int32), axis=1, keepdims=True)
            shv = pltpu.roll(bv, 1, axis=1)
            shi = pltpu.roll(bi, 1, axis=1)
            nv = jnp.where(lane < pos, bv,
                           jnp.where(lane == pos, pm, shv))
            ni = jnp.where(lane < pos, bi,
                           jnp.where(lane == pos, pidx, shi))
            bv = jnp.where(improving, nv, bv)
            bi = jnp.where(improving, ni, bi)
            return pool, bv, bi

        _, bv, bi = jax.lax.while_loop(cond, body, (pool, bv, bi))
        return bv, bi

    sw = sw or tn
    bv, bi = val_ref[:], idx_ref[:]
    for s in range(0, tn, sw):              # static: unrolled strips
        strip = dist[:, s:s + sw]
        col_g = (jax.lax.broadcasted_iota(jnp.int32, strip.shape, 1)
                 + j * tn + s)
        pool = jnp.where(col_g < n_valid, strip, inf)
        bv, bi = drain(pool, col_g, bv, bi)
    val_ref[:] = bv
    idx_ref[:] = bi


# ---------------------------------------------------------------------------
# insert_select: the drain over a MATERIALIZED [rows, len] input — the
# select_k contender for k <= 256 (ref: the warpsort-filtered slot of
# matrix/detail/select_k-inl.cuh's algo table)
# ---------------------------------------------------------------------------


def _insert_kernel(v_ref, val_ref, idx_ref, *, tn: int, k: int,
                   n_valid: int, sw: int, select_min: bool):
    j = pl.program_id(1)
    d = v_ref[:].astype(jnp.float32)
    if not select_min:
        d = -d                     # drain extracts minima
    # (NaN -> +inf sanitization lives in the drain itself)
    insertion_topk_body(d, val_ref, idx_ref, j, tn, k, n_valid, sw)


@functools.partial(jax.jit, static_argnames=("k", "select_min", "tm",
                                             "tn", "sw"))
def _insert_padded(v, k: int, select_min: bool, tm: int, tn: int,
                   sw: int):
    m, n = v.shape
    bw = best_width(k)
    vma, (v,) = join_vma(v)
    kernel = functools.partial(_insert_kernel, tn=tn, k=k, n_valid=n,
                               sw=sw, select_min=select_min)
    mp = round_up_to_multiple(m, tm)
    np_ = round_up_to_multiple(n, tn)
    if (mp, np_) != (m, n):
        # NaN padding: the drain's NaN->inf sanitization turns padded
        # rows into zero-round no-ops in BOTH select directions (zeros
        # would insert up to k bogus rounds per block in the first
        # tile); column padding is masked by n_valid inside the body
        v = jnp.pad(v, ((0, mp - m), (0, np_ - n)),
                    constant_values=jnp.nan)
    return pallas_call(
        kernel,
        grid=(mp // tm, np_ // tn),
        in_specs=[
            pl.BlockSpec((tm, tn), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tm, bw), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            out_struct((mp, bw), jnp.float32, vma),
            out_struct((mp, bw), jnp.int32, vma),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
    )(v)


def supports(dtype, k: int) -> bool:
    """f32/bf16/f16 only (the drain compares in f32 — exact for these;
    wide integers would round above 2^24), k within the 2-vreg best."""
    dtype = jnp.dtype(dtype)
    return (jnp.issubdtype(dtype, jnp.floating)
            and dtype.itemsize <= 4 and 1 <= k <= MAX_K)


def insert_select(values, k: int, select_min: bool = True,
                  tm: int = 256, tn: int = 2048, sw: int = 256):
    """Top-k of each row by bound-gated sorted insertion.

    Returns (vals [m, k], idx [m, k]), best-first, idx = positions.
    Contract notes: NaNs never insert (they compare false), i.e. they
    sort strictly last; rows with fewer than k candidates below the
    drain's +inf sentinel (k-th best would be +inf for select_min /
    -inf for select_max, or NaN-saturated) are DETECTED and re-answered
    through the direct lax.top_k path inside a ``lax.cond`` — full
    index parity with the direct path on degenerate data, one
    any-reduce of cost on clean data. Candidate pool cost is O(actual
    updates); adversarial best-last rows degrade to ~k rounds per tile
    (the merge cost), never the pool width."""
    v = jnp.asarray(values)
    m, n = v.shape
    if not supports(v.dtype, k):
        raise ValueError(f"insert_select: unsupported {v.dtype}/k={k}")
    tm = max(128, tm - tm % 128)            # (tm, bw) out blocks
    tn, sw = resolve_tn_sw(tn, sw, n)
    vals, idx = _insert_padded(v, k, select_min, tm, tn, sw)
    vals, idx = vals[:m, :k], idx[:m, :k]

    from raft_tpu.matrix.select_k import _direct_select

    def _fallback(_):
        dv, di = _direct_select(v, k, select_min)
        return dv.astype(jnp.float32), di.astype(jnp.int32)

    # unfilled slots still hold the drain's +inf sentinel (vals are in
    # the drain's sign convention only AFTER the negate below, so test
    # the raw buffer): lax.cond executes the direct path only when a
    # degenerate row exists
    degenerate = jnp.any(jnp.isinf(vals) & (vals > 0))
    vals, idx = jax.lax.cond(
        degenerate, _fallback,
        lambda _: ((-vals if not select_min else vals), idx),
        operand=None)
    return vals.astype(v.dtype), idx
