"""Misc dense matrix ops (ref: matrix/{copy,diagonal,init,norm,power,print,
ratio,reciprocal,reverse,sign_flip,slice,sqrt,threshold,triangular,shift,
col_wise_sort,sample_rows}.cuh)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from raft_tpu.random.rng_state import RngState


def copy(res, matrix):
    """Fresh copy (ref: matrix/copy.cuh)."""
    return jnp.array(jnp.asarray(matrix))


def get_diagonal(res, matrix):
    """Extract diagonal (ref: matrix/diagonal.cuh get_diagonal)."""
    return jnp.diagonal(jnp.asarray(matrix))


def set_diagonal(res, matrix, vec):
    """Set diagonal (ref: matrix/diagonal.cuh set_diagonal)."""
    m = jnp.asarray(matrix)
    n = min(m.shape)
    idx = jnp.arange(n)
    return m.at[idx, idx].set(jnp.asarray(vec, dtype=m.dtype)[:n])


def invert_diagonal(res, matrix):
    """ref: matrix/diagonal.cuh invert_diagonal."""
    m = jnp.asarray(matrix)
    n = min(m.shape)
    idx = jnp.arange(n)
    return m.at[idx, idx].set(1.0 / m[idx, idx])


def eye(res, n_rows: int, n_cols: Optional[int] = None, dtype=jnp.float32):
    """Identity fill (ref: matrix/init.cuh / eye)."""
    return jnp.eye(n_rows, n_cols if n_cols is not None else n_rows,
                   dtype=dtype)


def fill(res, shape, value, dtype=jnp.float32):
    """Constant fill (ref: matrix/init.cuh fill)."""
    return jnp.full(shape, value, dtype=dtype)


def linspace(res, start, stop, n: int, dtype=jnp.float32):
    return jnp.linspace(start, stop, n, dtype=dtype)


def l2_norm(res, matrix):
    """Frobenius norm (ref: matrix/norm.cuh l2_norm)."""
    m = jnp.asarray(matrix)
    return jnp.sqrt(jnp.sum(m * m))


def weighted_power(res, matrix, weight: float = 1.0, exponent: float = 2.0):
    """weight · m^exponent elementwise (ref: matrix/power.cuh)."""
    return weight * jnp.power(jnp.asarray(matrix), exponent)


def power(res, matrix, exponent: float = 2.0):
    return jnp.power(jnp.asarray(matrix), exponent)


def ratio(res, matrix):
    """m / sum(m) (ref: matrix/ratio.cuh)."""
    m = jnp.asarray(matrix)
    return m / jnp.sum(m)


def reciprocal(res, matrix, scalar: float = 1.0, setzero: bool = False,
               thres: float = 1e-15):
    """scalar / m with optional zero-guard (ref: matrix/reciprocal.cuh)."""
    m = jnp.asarray(matrix)
    if setzero:
        return jnp.where(jnp.abs(m) <= thres, jnp.zeros_like(m), scalar / m)
    return scalar / m


def col_reverse(res, matrix):
    """Reverse column order (ref: matrix/reverse.cuh col_reverse)."""
    return jnp.asarray(matrix)[:, ::-1]


def row_reverse(res, matrix):
    """Reverse row order (ref: matrix/reverse.cuh row_reverse)."""
    return jnp.asarray(matrix)[::-1, :]


def sign_flip(res, matrix):
    """Flip column signs so each column's max-|v| entry is positive
    (ref: matrix/math.cuh signFlip — column-major convention)."""
    m = jnp.asarray(matrix)
    idx = jnp.argmax(jnp.abs(m), axis=0)
    signs = jnp.sign(m[idx, jnp.arange(m.shape[1])])
    signs = jnp.where(signs == 0, 1.0, signs)
    return m * signs[None, :]


def slice(res, matrix, row_range: Tuple[int, int],
          col_range: Tuple[int, int]):
    """Submatrix copy (ref: matrix/slice.cuh)."""
    return jnp.asarray(matrix)[row_range[0]:row_range[1],
                               col_range[0]:col_range[1]]


def sqrt(res, matrix):
    return jnp.sqrt(jnp.asarray(matrix))


def zero_small_values(res, matrix, thres: float = 1e-15):
    """ref: matrix/threshold.cuh zero_small_values."""
    m = jnp.asarray(matrix)
    return jnp.where(jnp.abs(m) < thres, jnp.zeros_like(m), m)


def upper_triangular(res, matrix):
    """Extract upper triangle (ref: matrix/triangular.cuh)."""
    return jnp.triu(jnp.asarray(matrix))


def lower_triangular(res, matrix):
    return jnp.tril(jnp.asarray(matrix))


# -- shift (ref: matrix/shift.cuh, shift_types.hpp) --------------------------

SHIFT_TOWARDS_END = "towards_end"
SHIFT_TOWARDS_BEGINNING = "towards_beginning"


def col_shift(res, matrix, k: int = 1,
              direction: str = SHIFT_TOWARDS_END, fill_value=0.0,
              values=None):
    """Shift columns by k, filling vacated columns with a constant or given
    values (ref: shift.cuh col shift)."""
    m = jnp.asarray(matrix)
    n_rows, n_cols = m.shape
    if values is not None:
        fill_block = jnp.broadcast_to(jnp.asarray(values, dtype=m.dtype),
                                      (n_rows, k))
    else:
        fill_block = jnp.full((n_rows, k), fill_value, dtype=m.dtype)
    if direction == SHIFT_TOWARDS_END:
        return jnp.concatenate([fill_block, m[:, : n_cols - k]], axis=1)
    return jnp.concatenate([m[:, k:], fill_block], axis=1)


def row_shift(res, matrix, k: int = 1,
              direction: str = SHIFT_TOWARDS_END, fill_value=0.0,
              values=None):
    m = jnp.asarray(matrix)
    n_rows, n_cols = m.shape
    if values is not None:
        fill_block = jnp.broadcast_to(jnp.asarray(values, dtype=m.dtype),
                                      (k, n_cols))
    else:
        fill_block = jnp.full((k, n_cols), fill_value, dtype=m.dtype)
    if direction == SHIFT_TOWARDS_END:
        return jnp.concatenate([fill_block, m[: n_rows - k, :]], axis=0)
    return jnp.concatenate([m[k:, :], fill_block], axis=0)


# -- col_wise_sort (ref: matrix/col_wise_sort.cuh) ---------------------------

def sort_cols_per_row(res, matrix, ascending: bool = True,
                      return_indices: bool = False):
    """Sort each row's values (the reference's "column-wise sort per row",
    cub segmented sort).  Optionally return source indices."""
    m = jnp.asarray(matrix)
    order = m if ascending else -m
    if return_indices:
        idx = jnp.argsort(order, axis=1, stable=True).astype(jnp.int32)
        return jnp.take_along_axis(m, idx, axis=1), idx
    srt = jnp.sort(order, axis=1)
    return srt if ascending else -srt


# -- sample_rows (ref: matrix/sample_rows.cuh:30) ----------------------------

def sample_rows(res, state: RngState, matrix, n_samples: int):
    """Uniform random row subsample without replacement
    (gather + excess_subsample, ref: detail/sample_rows.cuh)."""
    from raft_tpu.random.rng import excess_subsample

    m = jnp.asarray(matrix)
    idx = excess_subsample(res, state, n_samples, m.shape[0])
    return m[idx]


def print_matrix(res, matrix, name: str = "", h_separator: str = " ",
                 v_separator: str = "\n") -> str:
    """Render (and print) a matrix (ref: matrix/print.cuh `print` —
    host-side debug formatting; separators match the reference's args).

    >>> import numpy as np
    >>> from raft_tpu.matrix import print_matrix
    >>> s = print_matrix(None, np.array([[1., 2.], [3., 4.]]))
    1 2
    3 4
    """
    import numpy as np

    m = np.asarray(matrix)
    body = v_separator.join(
        h_separator.join(f"{v:g}" for v in row) for row in np.atleast_2d(m))
    out = (name + v_separator if name else "") + body
    print(out)
    return out
